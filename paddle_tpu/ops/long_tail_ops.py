"""Long-tail operators: the remaining user-facing reference ops.

Ref parity (per-op citations on each function): the round-2 audit named
these as genuinely absent — deformable conv, NCE, row conv, precise/PS
RoI pooling, crop family, partial concat/sum, CVM, pad2d, yolov3 loss,
unpool, center loss and friends. TPU-native: every op is a pure jnp/lax
function (static shapes, gather/one-hot instead of atomic scatter,
integral images instead of data-dependent loops) so XLA can fuse and
tile them; none of this code mirrors the reference CUDA kernels.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..core.op_registry import register_op


# ---------------------------------------------------------------------------
# manipulation
# ---------------------------------------------------------------------------


@register_op("crop")
def crop(x, *, offsets, shape):
    """ref crop_op.cc: slice `shape` starting at `offsets`."""
    return lax.dynamic_slice(x, [int(o) for o in offsets],
                             [int(s) for s in shape])


@register_op("crop_tensor")
def crop_tensor(x, *, offsets, shape):
    """ref crop_tensor_op.cc: crop with -1 in shape meaning "to the end"."""
    offs = [int(o) for o in offsets]
    dims = [x.shape[i] - offs[i] if int(s) == -1 else int(s)
            for i, s in enumerate(shape)]
    return lax.dynamic_slice(x, offs, dims)


@register_op("broadcast_tensors", multi_out=True)
def broadcast_tensors(*xs):
    """ref broadcast_tensors_op.cc: broadcast all inputs to the common
    shape (rank-aligned from the right)."""
    shape = jnp.broadcast_shapes(*[x.shape for x in xs])
    return tuple(jnp.broadcast_to(x, shape) for x in xs)


@register_op("partial_concat")
def partial_concat(*xs, start_index=0, length=-1):
    """ref partial_concat_op.cc: concat column slices [start, start+len)
    of each 2-D input."""
    outs = []
    for x in xs:
        s = start_index if start_index >= 0 else x.shape[1] + start_index
        e = x.shape[1] if length < 0 else s + length
        outs.append(x[:, s:e])
    return jnp.concatenate(outs, axis=1)


@register_op("partial_sum")
def partial_sum(*xs, start_index=0, length=-1):
    """ref partial_sum_op.cc: elementwise sum of the same column slice of
    every input."""
    acc = None
    for x in xs:
        s = start_index if start_index >= 0 else x.shape[1] + start_index
        e = x.shape[1] if length < 0 else s + length
        part = x[:, s:e]
        acc = part if acc is None else acc + part
    return acc


@register_op("reverse")
def reverse(x, *, axis):
    """ref reverse_op.cc."""
    axes = axis if isinstance(axis, (list, tuple)) else [axis]
    return jnp.flip(x, axis=tuple(int(a) for a in axes))


@register_op("increment")
def increment(x, *, value=1.0):
    """ref increment_op: x += value on a 1-element tensor."""
    return x + jnp.asarray(value, x.dtype)


@register_op("minus")
def minus(x, y):
    """ref minus_op.cc."""
    return x - y


@register_op("mv")
def mv(x, vec):
    """ref mv_op.cc: matrix @ vector."""
    return jnp.matmul(x, vec)


@register_op("sum", multi_out=False)
def sum_op(*xs):
    """ref sum_op.cc: add_n — elementwise sum of N tensors."""
    acc = xs[0]
    for x in xs[1:]:
        acc = acc + x
    return acc


@register_op("mean")
def mean(x):
    """ref mean_op.cc: global mean to a scalar."""
    return jnp.mean(x)


@register_op("norm", has_aux=True)
def norm(x, *, axis=-1, epsilon=1e-10):
    """ref norm_op.cc: x / ||x||_2 along axis; Norm is the aux output."""
    n = jnp.sqrt(jnp.sum(x * x, axis=axis, keepdims=True) + epsilon)
    return x / n, n


@register_op("unbind", multi_out=True)
def unbind(x, *, axis=0):
    """ref unbind_op.cc."""
    return tuple(jnp.squeeze(s, axis=axis)
                 for s in jnp.split(x, x.shape[axis], axis=axis))


@register_op("tril_triu")
def tril_triu(x, *, diagonal=0, lower=True):
    """ref tril_triu_op.cc: one op, `lower` picks tril vs triu."""
    return jnp.tril(x, diagonal) if lower else jnp.triu(x, diagonal)


@register_op("set_value")
def set_value(x, value, *, axes, starts, ends, steps=None):
    """ref set_value_op.cc — functional slice-assign: returns a new
    tensor (no aliasing; XLA turns it into an in-place DUS)."""
    idx = [slice(None)] * x.ndim
    steps = steps or [1] * len(axes)
    for a, s, e, st in zip(axes, starts, ends, steps):
        idx[int(a)] = slice(int(s), int(e), int(st))
    return x.at[tuple(idx)].set(jnp.asarray(value, x.dtype))


@register_op("shuffle_batch", has_aux=True)
def shuffle_batch(x, key):
    """ref shuffle_batch_op.cc: random row permutation; the permutation
    (aux) lets callers un-shuffle."""
    perm = jax.random.permutation(key, x.shape[0])
    return jnp.take(x, perm, axis=0), perm


@register_op("pad2d")
def pad2d(x, *, paddings, mode="constant", pad_value=0.0,
          data_format="NCHW"):
    """ref pad2d_op.cc: H/W padding with constant/reflect/edge modes."""
    t, b, l, r = [int(p) for p in paddings]
    if data_format == "NCHW":
        pads = [(0, 0), (0, 0), (t, b), (l, r)]
    else:
        pads = [(0, 0), (t, b), (l, r), (0, 0)]
    jmode = {"constant": "constant", "reflect": "reflect",
             "edge": "edge"}[mode]
    if jmode == "constant":
        return jnp.pad(x, pads, constant_values=pad_value)
    return jnp.pad(x, pads, mode=jmode)


@register_op("pad_constant_like")
def pad_constant_like(x, y, *, pad_value=0.0):
    """ref pad_constant_like_op.cc: pad y up to x's shape."""
    pads = [(0, xs - ys) for xs, ys in zip(x.shape, y.shape)]
    return jnp.pad(y, pads, constant_values=pad_value)


@register_op("im2sequence")
def im2sequence(x, *, kernels, strides=(1, 1), paddings=(0, 0, 0, 0)):
    """ref im2sequence_op.cc: im2col patches flattened to a sequence
    [N*oh*ow, C*kh*kw]."""
    n, c, h, w = x.shape
    kh, kw = kernels
    sh, sw = strides
    pt, pl, pb, pr = paddings
    patches = lax.conv_general_dilated_patches(
        x, (kh, kw), (sh, sw), [(pt, pb), (pl, pr)],
        dimension_numbers=lax.conv_dimension_numbers(
            x.shape, (1, c, kh, kw), ("NCHW", "OIHW", "NCHW")))
    oh, ow = patches.shape[2], patches.shape[3]
    return patches.reshape(n, c * kh * kw, oh * ow).transpose(
        0, 2, 1).reshape(n * oh * ow, c * kh * kw)


# ---------------------------------------------------------------------------
# recommendation / ranking
# ---------------------------------------------------------------------------


@register_op("cvm")
def cvm_op(x, cvm, *, use_cvm=True):
    """ref cvm_op.cc: show/click head transform. With use_cvm the first
    two columns become log(show+1), log(click+1)-log(show+1); without,
    they are dropped."""
    show = jnp.log(cvm[:, :1] + 1.0)
    click = jnp.log(cvm[:, 1:2] + 1.0) - show
    if use_cvm:
        return jnp.concatenate([show, click, x[:, 2:]], axis=1)
    return x[:, 2:]


@register_op("batch_fc")
def batch_fc(x, w, bias=None):
    """ref batch_fc_op.cc: per-slot FC — x [S, B, in], w [S, in, out]."""
    out = jnp.einsum("sbi,sio->sbo", x, w)
    if bias is not None:
        out = out + bias[:, None, :]
    return out


@register_op("filter_by_instag", has_aux=True)
def filter_by_instag(x, ins_tag, filter_tag, *, is_lod=False,
                     out_val_if_empty=0.0):
    """ref filter_by_instag_op.cc. TPU-native: static shapes — rows whose
    tag set misses filter_tag are zeroed (not removed); aux returns the
    keep mask and a loss weight per row. Hosts slice by mask when ragged
    output is required."""
    keep = jnp.isin(ins_tag, filter_tag).any(axis=-1)
    out = jnp.where(keep[:, None], x,
                    jnp.asarray(out_val_if_empty, x.dtype))
    return out, (keep, keep.astype(x.dtype))


@register_op("fsp")
def fsp(x, y):
    """ref fsp_op.cc (distillation FSP matrix): [N,C1,H,W]x[N,C2,H,W] ->
    [N,C1,C2] / (H*W)."""
    n, c1, h, w = x.shape
    c2 = y.shape[1]
    a = x.reshape(n, c1, h * w)
    b = y.reshape(n, c2, h * w)
    return jnp.einsum("nax,nbx->nab", a, b) / float(h * w)


# ---------------------------------------------------------------------------
# losses
# ---------------------------------------------------------------------------


@register_op("label_smooth")
def label_smooth(label, prior_dist=None, *, epsilon=0.1):
    """ref label_smooth_op.cc."""
    c = label.shape[-1]
    if prior_dist is None:
        smooth = jnp.full_like(label, 1.0 / c)
    else:
        smooth = jnp.broadcast_to(prior_dist, label.shape)
    return (1.0 - epsilon) * label + epsilon * smooth


@register_op("cross_entropy2", has_aux=True)
def cross_entropy2(x, label, *, ignore_index=-100):
    """ref cross_entropy_op.cc (cross_entropy2): hard-label CE over
    probabilities x (already softmaxed); aux MatchX is x[label]."""
    lbl = label.reshape(x.shape[:-1])
    safe = jnp.where(lbl == ignore_index, 0, lbl)
    match = jnp.take_along_axis(x, safe[..., None], axis=-1)[..., 0]
    loss = jnp.where(lbl == ignore_index, 0.0,
                     -jnp.log(jnp.maximum(match, 1e-12)))
    return loss[..., None], match[..., None]


@register_op("center_loss", has_aux=True)
def center_loss(x, label, centers, *, alpha=0.1, update_center=True):
    """ref center_loss_op.cc: 0.5*||x - c_y||^2; aux returns the updated
    centers (functional counterpart of the reference's in-place update:
    c_y -= alpha * mean residual of rows assigned to y)."""
    cy = centers[label]
    diff = x - cy
    loss = 0.5 * jnp.sum(diff * diff, axis=-1, keepdims=True)
    if update_center:
        num = jax.ops.segment_sum(diff, label,
                                  num_segments=centers.shape[0])
        cnt = jax.ops.segment_sum(jnp.ones((x.shape[0],), x.dtype), label,
                                  num_segments=centers.shape[0])
        new_centers = centers + alpha * num / (cnt[:, None] + 1.0)
    else:
        new_centers = centers
    return loss, new_centers


@register_op("nce", has_aux=True)
def nce(x, label, weight, bias, key, *, num_total_classes,
        num_neg_samples=10):
    """ref nce_op.cc: noise-contrastive estimation with a uniform noise
    sampler. Returns the per-row NCE cost; aux carries (logits, labels)
    of the sampled set like the reference's SampleLogits/SampleLabels."""
    b = x.shape[0]
    label = label.reshape(b, -1)
    num_true = label.shape[1]
    neg = jax.random.randint(key, (b, num_neg_samples), 0,
                             num_total_classes)
    samples = jnp.concatenate([label, neg], axis=1)  # [B, T+S]
    w = weight[samples]                              # [B, T+S, D]
    logits = jnp.einsum("bd,bsd->bs", x, w)
    if bias is not None:
        logits = logits + bias[samples]
    # uniform noise: q = 1/C; P(true) = sigmoid(logit - log(S*q))
    log_noise = jnp.log(jnp.asarray(
        num_neg_samples / num_total_classes, x.dtype))
    adj = logits - log_noise
    lbl = jnp.concatenate([jnp.ones((b, num_true), x.dtype),
                           jnp.zeros((b, num_neg_samples), x.dtype)],
                          axis=1)
    cost = -(lbl * jax.nn.log_sigmoid(adj)
             + (1.0 - lbl) * jax.nn.log_sigmoid(-adj))
    return jnp.sum(cost, axis=1, keepdims=True), (logits, samples)


@register_op("sample_logits", has_aux=True)
def sample_logits(logits, label, key, *, num_samples, use_customized_samples=False,
                  customized_samples=None):
    """ref sample_logits_op.cc: gather true + sampled-class logits for
    sampled softmax; sampled logits subtract log-probability of being
    sampled (uniform sampler)."""
    b, c = logits.shape
    label = label.reshape(b, -1)
    if use_customized_samples and customized_samples is not None:
        neg = customized_samples
    else:
        neg = jax.random.randint(key, (b, num_samples), 0, c)
    samples = jnp.concatenate([label, neg], axis=1)
    picked = jnp.take_along_axis(logits, samples, axis=1)
    logq = jnp.log(jnp.asarray(num_samples / c, logits.dtype))
    out = picked - logq
    new_label = jnp.arange(label.shape[1], dtype=jnp.int64)
    new_label = jnp.broadcast_to(new_label[None], (b, label.shape[1]))
    return out, (samples, new_label)


# ---------------------------------------------------------------------------
# vision: deformable conv, row conv, correlation, unpool, RoI pools
# ---------------------------------------------------------------------------


def _bilinear_gather(img, yy, xx):
    """img [C,H,W]; yy/xx [...]: differentiable bilinear sample with
    zero padding outside."""
    c, h, w = img.shape
    y0 = jnp.floor(yy)
    x0 = jnp.floor(xx)
    wy = yy - y0
    wx = xx - x0
    out = 0.0
    for dy, sy in ((0, 1 - wy), (1, wy)):
        for dx, sx in ((0, 1 - wx), (1, wx)):
            yi = y0.astype(jnp.int32) + dy
            xi = x0.astype(jnp.int32) + dx
            inside = ((yi >= 0) & (yi < h) & (xi >= 0) & (xi < w))
            yc = jnp.clip(yi, 0, h - 1)
            xc = jnp.clip(xi, 0, w - 1)
            v = img[:, yc, xc]                       # [C, ...]
            out = out + v * (sy * sx * inside)[None]
    return out


@register_op("deformable_conv")
def deformable_conv(x, offset, mask, weight, *, stride=1, padding=0,
                    dilation=1, groups=1, deformable_groups=1,
                    im2col_step=None):
    """ref deformable_conv_op.cc (v2, modulated). TPU-native design:
    bilinear-sample the deformed patches into an im2col tensor
    [N, C*kh*kw, OH*OW] (gathers vectorise on the VPU), then one matmul
    with the flattened weight rides the MXU — no per-pixel CUDA kernel."""
    n, c, h, w = x.shape
    co, _, kh, kw = weight.shape
    st = (stride, stride) if isinstance(stride, int) else tuple(stride)
    pd = (padding, padding) if isinstance(padding, int) else tuple(padding)
    dl = (dilation, dilation) if isinstance(dilation, int) \
        else tuple(dilation)
    oh = (h + 2 * pd[0] - (dl[0] * (kh - 1) + 1)) // st[0] + 1
    ow = (w + 2 * pd[1] - (dl[1] * (kw - 1) + 1)) // st[1] + 1
    cg = c // deformable_groups

    base_y = (jnp.arange(oh) * st[0] - pd[0])[:, None]    # [OH,1]
    base_x = (jnp.arange(ow) * st[1] - pd[1])[None, :]    # [1,OW]
    off = offset.reshape(n, deformable_groups, kh * kw, 2, oh, ow)

    def per_image(img, off_i, msk_i):
        cols = []
        for g in range(deformable_groups):
            sub = img[g * cg:(g + 1) * cg]
            for idx in range(kh * kw):
                ky, kx = idx // kw, idx % kw
                yy = base_y + ky * dl[0] + off_i[g, idx, 0]
                xx = base_x + kx * dl[1] + off_i[g, idx, 1]
                v = _bilinear_gather(sub, yy, xx)     # [cg, OH, OW]
                if msk_i is not None:
                    v = v * msk_i[g, idx][None]
                cols.append(v)
        # [dg*kh*kw*cg, OH, OW] ordered (g, idx, cg) -> regroup to
        # channel-major (c, kh*kw) to match the weight layout
        col = jnp.stack(cols).reshape(deformable_groups, kh * kw, cg,
                                      oh, ow)
        col = col.transpose(0, 2, 1, 3, 4).reshape(c, kh * kw, oh, ow)
        return col

    if mask is not None:
        msk = mask.reshape(n, deformable_groups, kh * kw, oh, ow)
        cols = jax.vmap(per_image)(x, off, msk)
    else:  # v1: no modulation — skip the mask multiply entirely
        cols = jax.vmap(lambda i, o: per_image(i, o, None))(x, off)
    # cols is channel-major (c, kh*kw, ...): conv groups slice contiguous
    # channel blocks, so regroup and contract per group in one einsum
    cg2 = (c // groups) * kh * kw
    colsg = cols.reshape(n, groups, cg2, oh * ow)
    wmat = weight.reshape(groups, co // groups, cg2)
    out = jnp.einsum("goc,ngcs->ngos", wmat, colsg)
    return out.reshape(n, co, oh, ow)


@register_op("deformable_conv_v1")
def deformable_conv_v1(x, offset, weight, *, stride=1, padding=0,
                       dilation=1, groups=1, deformable_groups=1,
                       im2col_step=None):
    """ref deformable_conv_v1_op.cc: v1 = v2 without modulation mask."""
    return deformable_conv(x, offset, None, weight, stride=stride,
                           padding=padding, dilation=dilation,
                           groups=groups,
                           deformable_groups=deformable_groups)


@register_op("row_conv")
def row_conv(x, w):
    """ref row_conv_op.cc (lookahead conv for streaming ASR):
    out[b,t,d] = sum_{i<k} x[b,t+i,d] * w[i,d]; zero beyond T."""
    k = w.shape[0]
    t = x.shape[1]
    out = jnp.zeros_like(x)
    for i in range(k):
        shifted = jnp.pad(x[:, i:], ((0, 0), (0, i), (0, 0)))
        out = out + shifted * w[i][None, None, :]
    del t
    return out


@register_op("conv_shift")
def conv_shift(x, y):
    """ref conv_shift_op.cc: circular correlation —
    out[b,i] = sum_j y[b,j] * x[b, (i + j - n//2) mod m]."""
    m = x.shape[1]
    ny = y.shape[1]
    j = jnp.arange(ny)
    i = jnp.arange(m)
    idx = (i[:, None] + j[None, :] - ny // 2) % m      # [m, ny]
    gathered = x[:, idx]                               # [B, m, ny]
    return jnp.einsum("bmn,bn->bm", gathered, y)


@register_op("correlation")
def correlation(x1, x2, *, pad_size=4, kernel_size=1, max_displacement=4,
                stride1=1, stride2=1, corr_type_multiply=1):
    """ref correlation_op.cc (FlowNet cost volume): mean over channels of
    x1 . shift(x2) for every displacement in the search window."""
    d = max_displacement
    n, c, h, w = x1.shape
    x2p = jnp.pad(x2, ((0, 0), (0, 0), (d, d), (d, d)))
    outs = []
    for dy in range(0, 2 * d + 1, stride2):
        for dx in range(0, 2 * d + 1, stride2):
            shifted = x2p[:, :, dy:dy + h, dx:dx + w]
            outs.append(jnp.mean(x1 * shifted, axis=1))
    return jnp.stack(outs, axis=1)


@register_op("unpool")
def unpool(x, indices, *, ksize, stride=None, padding=0,
           output_size=None):
    """ref unpool_op.cc: max-unpool2d scattering x to the flat positions
    recorded by max_pool2d_with_index."""
    n, c, h, w = x.shape
    if output_size is not None:
        oh, ow = output_size[-2], output_size[-1]
    else:
        ks = (ksize, ksize) if isinstance(ksize, int) else tuple(ksize)
        st = ks if stride is None else (
            (stride, stride) if isinstance(stride, int) else tuple(stride))
        pd = (padding, padding) if isinstance(padding, int) \
            else tuple(padding)
        oh = (h - 1) * st[0] - 2 * pd[0] + ks[0]
        ow = (w - 1) * st[1] - 2 * pd[1] + ks[1]
    flat = jnp.zeros((n, c, oh * ow), x.dtype)
    idx = indices.reshape(n, c, h * w)
    vals = x.reshape(n, c, h * w)
    flat = jax.vmap(jax.vmap(
        lambda f, i, v: f.at[i].add(v)))(flat, idx, vals)
    return flat.reshape(n, c, oh, ow)


@register_op("max_pool3d_with_index", has_aux=True)
def max_pool3d_with_index(x, *, ksize, stride=None, padding=0,
                          adaptive=False):
    """ref pool_with_index_op.cc (3-D): argmax flat index into the
    input D*H*W map; adaptive branch uses per-cell
    [floor(i*D/oD), ceil((i+1)*D/oD)) windows.  Both paths share the
    N-D helpers in nn_ops."""
    from .nn_ops import (adaptive_max_pool_with_index_nd,
                         max_pool_with_index_nd)

    if adaptive:
        os = (ksize,) * 3 if isinstance(ksize, int) else tuple(ksize)
        return adaptive_max_pool_with_index_nd(x, os)
    ks = (ksize,) * 3 if isinstance(ksize, int) else tuple(ksize)
    st = ks if stride is None else (
        (stride,) * 3 if isinstance(stride, int) else tuple(stride))
    pd = (padding,) * 3 if isinstance(padding, int) else tuple(padding)
    return max_pool_with_index_nd(x, ks, st, pd)


@register_op("prroi_pool")
def prroi_pool(x, rois, rois_num, *, pooled_height, pooled_width,
               spatial_scale=1.0):
    """ref prroi_pool_op.cc. TPU divergence (documented): PrRoI's exact
    bilinear integral is approximated by a dense 4x4-sample average per
    bin — continuous in the RoI coords (the property PrRoI exists for)
    and within ~1e-2 of the closed form at feature-map resolution."""
    from .detection_ops import roi_align

    return roi_align(x, rois, rois_num, output_size=(pooled_height,
                                                     pooled_width),
                     spatial_scale=spatial_scale, sampling_ratio=4,
                     aligned=False)


@register_op("psroi_pool")
def psroi_pool(x, rois, rois_num, *, output_channels, pooled_height,
               pooled_width, spatial_scale=1.0):
    """ref psroi_pool_op.cc: position-sensitive RoI average pooling —
    bin (i,j) pools channel group (i*pw+j) of its RoI."""
    n, c, h, w = x.shape
    ph, pw = pooled_height, pooled_width
    r = rois.shape[0]
    bn = jnp.asarray(rois_num, jnp.int32)
    img_of_roi = jnp.searchsorted(jnp.cumsum(bn), jnp.arange(r),
                                  side="right").astype(jnp.int32)
    rois = jnp.asarray(rois, jnp.float32)
    x1 = jnp.round(rois[:, 0]) * spatial_scale
    y1 = jnp.round(rois[:, 1]) * spatial_scale
    x2 = jnp.round(rois[:, 2] + 1.0) * spatial_scale
    y2 = jnp.round(rois[:, 3] + 1.0) * spatial_scale
    rw = jnp.maximum(x2 - x1, 0.1)
    rh = jnp.maximum(y2 - y1, 0.1)
    bin_h = rh / ph
    bin_w = rw / pw

    ys = jnp.arange(h, dtype=jnp.float32)
    xs = jnp.arange(w, dtype=jnp.float32)

    def per_roi(ri):
        img = x[img_of_roi[ri]].reshape(output_channels, ph * pw, h, w)
        outs = []
        for i in range(ph):
            for j in range(pw):
                hs = y1[ri] + i * bin_h[ri]
                he = y1[ri] + (i + 1) * bin_h[ri]
                ws = x1[ri] + j * bin_w[ri]
                we = x1[ri] + (j + 1) * bin_w[ri]
                my = ((ys >= jnp.floor(hs)) & (ys < jnp.ceil(he)))
                mx = ((xs >= jnp.floor(ws)) & (xs < jnp.ceil(we)))
                mask = my[:, None] & mx[None, :]
                area = jnp.maximum(mask.sum(), 1)
                ch = img[:, i * pw + j]               # [oc, h, w]
                outs.append(jnp.sum(ch * mask[None], axis=(1, 2))
                            / area.astype(x.dtype))
        return jnp.stack(outs, axis=1).reshape(output_channels, ph, pw)

    return jax.vmap(per_roi)(jnp.arange(r))


# ---------------------------------------------------------------------------
# yolov3 loss
# ---------------------------------------------------------------------------


@register_op("yolov3_loss", has_aux=True)
def yolov3_loss(x, gt_box, gt_label, *, anchors, anchor_mask, class_num,
                ignore_thresh=0.7, downsample_ratio=32,
                use_label_smooth=False):
    """ref yolov3_loss_op.cc. One detection head: decode predictions,
    match ground truth to the best-IoU anchor, BCE on xy/obj/cls + L1 on
    wh, objectness ignored where the best IoU exceeds ignore_thresh."""
    n, _, gh, gw = x.shape
    na = len(anchor_mask)
    an_all = jnp.asarray(anchors, jnp.float32).reshape(-1, 2)
    an = an_all[jnp.asarray(anchor_mask)]
    pred = x.reshape(n, na, 5 + class_num, gh, gw)
    tx, ty = pred[:, :, 0], pred[:, :, 1]
    tw, th = pred[:, :, 2], pred[:, :, 3]
    tobj = pred[:, :, 4]
    tcls = pred[:, :, 5:]
    stride_len = downsample_ratio
    in_w, in_h = gw * stride_len, gh * stride_len

    gx = gt_box[:, :, 0]  # normalised cx
    gy = gt_box[:, :, 1]
    gw_ = gt_box[:, :, 2]
    gh_ = gt_box[:, :, 3]
    valid = (gw_ > 0) & (gh_ > 0)                       # [N, B]

    # anchor matching on shape IoU (centered boxes), over ALL anchors
    inter = (jnp.minimum(gw_[..., None] * in_w, an_all[None, None, :, 0])
             * jnp.minimum(gh_[..., None] * in_h, an_all[None, None, :, 1]))
    union = (gw_[..., None] * in_w * gh_[..., None] * in_h
             + an_all[None, None, :, 0] * an_all[None, None, :, 1] - inter)
    best = jnp.argmax(inter / jnp.maximum(union, 1e-9), axis=-1)  # [N,B]
    mask_arr = jnp.asarray(anchor_mask)
    # position on this head's grid
    gi = jnp.clip((gx * gw).astype(jnp.int32), 0, gw - 1)
    gj = jnp.clip((gy * gh).astype(jnp.int32), 0, gh - 1)

    obj_target = jnp.zeros((n, na, gh, gw))
    txt = jnp.zeros((n, na, gh, gw))
    tyt = jnp.zeros((n, na, gh, gw))
    twt = jnp.zeros((n, na, gh, gw))
    tht = jnp.zeros((n, na, gh, gw))
    cls_t = jnp.zeros((n, na, class_num, gh, gw))
    tscale = jnp.zeros((n, na, gh, gw))

    nb = gt_box.shape[1]
    batch_idx = jnp.arange(n)[:, None].repeat(nb, 1)
    for k in range(na):
        sel = valid & (best == mask_arr[k])
        bi = batch_idx
        w_sc = 2.0 - gw_ * gh_
        obj_target = obj_target.at[bi, k, gj, gi].max(
            sel.astype(obj_target.dtype))
        txt = txt.at[bi, k, gj, gi].add(
            jnp.where(sel, gx * gw - gi, 0.0))
        tyt = tyt.at[bi, k, gj, gi].add(
            jnp.where(sel, gy * gh - gj, 0.0))
        twt = twt.at[bi, k, gj, gi].add(jnp.where(
            sel, jnp.log(jnp.maximum(gw_ * in_w / an[k, 0], 1e-9)), 0.0))
        tht = tht.at[bi, k, gj, gi].add(jnp.where(
            sel, jnp.log(jnp.maximum(gh_ * in_h / an[k, 1], 1e-9)), 0.0))
        tscale = tscale.at[bi, k, gj, gi].add(jnp.where(sel, w_sc, 0.0))
        lbl = jnp.clip(gt_label, 0, class_num - 1)
        cls_t = cls_t.at[bi, k, lbl, gj, gi].max(
            sel.astype(cls_t.dtype))

    # objectness ignore: predicted boxes overlapping any gt above thresh
    cy = (jnp.arange(gh)[:, None] + jax.nn.sigmoid(ty)) / gh
    cx = (jnp.arange(gw)[None, :] + jax.nn.sigmoid(tx)) / gw
    pw_ = an[:, 0][None, :, None, None] * jnp.exp(tw) / in_w
    ph_ = an[:, 1][None, :, None, None] * jnp.exp(th) / in_h

    def iou_with_gt(b):
        px1, px2 = cx[b] - pw_[b] / 2, cx[b] + pw_[b] / 2
        py1, py2 = cy[b] - ph_[b] / 2, cy[b] + ph_[b] / 2
        gx1 = (gx[b] - gw_[b] / 2)[:, None, None, None]
        gx2 = (gx[b] + gw_[b] / 2)[:, None, None, None]
        gy1 = (gy[b] - gh_[b] / 2)[:, None, None, None]
        gy2 = (gy[b] + gh_[b] / 2)[:, None, None, None]
        iw = jnp.maximum(jnp.minimum(px2, gx2) - jnp.maximum(px1, gx1), 0)
        ih = jnp.maximum(jnp.minimum(py2, gy2) - jnp.maximum(py1, gy1), 0)
        inter_ = iw * ih
        uni = (pw_[b] * ph_[b] + (gw_[b] * gh_[b])[:, None, None, None]
               - inter_)
        iou = inter_ / jnp.maximum(uni, 1e-9)
        return jnp.max(jnp.where(valid[b][:, None, None, None], iou, 0.0),
                       axis=0)

    best_iou = jax.vmap(iou_with_gt)(jnp.arange(n))
    noobj_mask = (best_iou < ignore_thresh) & (obj_target == 0)

    bce = lambda p, t: jnp.maximum(p, 0) - p * t + jnp.log1p(  # noqa: E731
        jnp.exp(-jnp.abs(p)))
    smooth = 1.0 / class_num if use_label_smooth else 0.0
    cls_target = cls_t * (1 - 2 * smooth) + smooth
    pos = obj_target
    loss_xy = jnp.sum((bce(tx, txt) + bce(ty, tyt)) * tscale * pos,
                      axis=(1, 2, 3))
    loss_wh = jnp.sum((jnp.abs(tw - twt) + jnp.abs(th - tht))
                      * tscale * pos, axis=(1, 2, 3))
    loss_obj = (jnp.sum(bce(tobj, jnp.ones_like(tobj)) * pos,
                        axis=(1, 2, 3))
                + jnp.sum(bce(tobj, jnp.zeros_like(tobj))
                          * noobj_mask, axis=(1, 2, 3)))
    loss_cls = jnp.sum(bce(tcls, cls_target) * pos[:, :, None],
                       axis=(1, 2, 3, 4))
    return (loss_xy + loss_wh + loss_obj + loss_cls), (obj_target,
                                                       best_iou)


# ---------------------------------------------------------------------------
# sequence-family extensions (padded [B, T, D] + lengths convention of
# sequence_ops.py; ref LoD kernels cited per op)
# ---------------------------------------------------------------------------


@register_op("sequence_concat")
def sequence_concat(*args):
    """ref sequence_concat_op.cc: concatenate sequences instance-wise.
    Padded form: inputs alternate (x_i [B,T_i,D], lengths_i [B]); output
    is [B, sum(T_i), D] with each instance's rows packed front."""
    xs = args[0::2]
    lens = args[1::2]
    b = xs[0].shape[0]
    t_out = sum(x.shape[1] for x in xs)
    d = xs[0].shape[2]
    out = jnp.zeros((b, t_out, d), xs[0].dtype)
    total = jnp.zeros((b,), jnp.int32)
    for x, ln in zip(xs, lens):
        ln = jnp.asarray(ln, jnp.int32)
        t = x.shape[1]
        pos = jnp.arange(t)[None, :]                   # [1, T_i]
        keep = pos < ln[:, None]
        dst = total[:, None] + pos                     # [B, T_i]
        bi = jnp.broadcast_to(jnp.arange(b)[:, None], dst.shape)
        out = out.at[bi, jnp.where(keep, dst, t_out - 1)].add(
            jnp.where(keep[..., None], x, 0.0))
        total = total + ln
    return out


@register_op("sequence_reshape")
def sequence_reshape(x, lengths, *, new_dim):
    """ref sequence_reshape_op.cc: refold features so D becomes new_dim;
    per-instance length scales by D/new_dim."""
    b, t, d = x.shape
    new_t = t * d // new_dim
    return (x.reshape(b, new_t, new_dim),
            (jnp.asarray(lengths, jnp.int32) * d) // new_dim)


@register_op("sequence_scatter")
def sequence_scatter(x, index, updates, lengths):
    """ref sequence_scatter_op.cc: per-instance scatter-add of `updates`
    rows at `index` positions (padded rows masked by lengths)."""
    ln = jnp.asarray(lengths, jnp.int32)
    t = index.shape[1]
    keep = jnp.arange(t)[None, :] < ln[:, None]
    upd = jnp.where(keep[..., None] if updates.ndim == 3 else keep,
                    updates, 0)
    bi = jnp.broadcast_to(jnp.arange(x.shape[0])[:, None], index.shape)
    return x.at[bi, index].add(upd)


@register_op("sequence_slice")
def sequence_slice(x, lengths, offset, length):
    """ref sequence_slice_op.cc: per-instance subsequence [offset,
    offset+length) re-packed to the front; returns (out, new_lengths)."""
    b, t, d = x.shape
    off = jnp.asarray(offset, jnp.int32).reshape(b)
    ln = jnp.asarray(length, jnp.int32).reshape(b)
    pos = jnp.arange(t)[None, :]
    src = jnp.clip(off[:, None] + pos, 0, t - 1)
    bi = jnp.broadcast_to(jnp.arange(b)[:, None], src.shape)
    gathered = x[bi, src]
    keep = pos < ln[:, None]
    return jnp.where(keep[..., None], gathered, 0.0), ln


@register_op("lod_reset")
def lod_reset(x, target_lengths):
    """ref lod_reset_op.cc: in the padded+lengths world the data is
    unchanged; the op re-labels instance lengths."""
    return x, jnp.asarray(target_lengths, jnp.int32)


# ---------------------------------------------------------------------------
# remaining vision / embedding long tail
# ---------------------------------------------------------------------------


@register_op("inplace_abn", has_aux=True)
def inplace_abn(x, scale, bias, mean, variance, *, epsilon=1e-5,
                momentum=0.9, activation="leaky_relu", alpha=0.01,
                is_test=False):
    """ref inplace_abn_op.cc: batch norm + activation in one op (the
    in-place memory trick is XLA's buffer reuse here). Returns activated
    output; aux carries updated running stats like batch_norm."""
    from ..core.op_registry import _REGISTRY

    bn = _REGISTRY["batch_norm"].fn
    y, stats = bn(x, scale, bias, mean, variance, epsilon=epsilon,
                  momentum=momentum, is_test=is_test)
    if activation == "leaky_relu":
        y = jnp.where(y >= 0, y, alpha * y)
    elif activation == "elu":
        y = jnp.where(y >= 0, y, alpha * (jnp.exp(y) - 1.0))
    elif activation == "identity":
        pass
    else:
        raise ValueError(f"inplace_abn: unknown activation {activation}")
    return y, stats


@register_op("bilateral_slice")
def bilateral_slice(x, grid, guide, *, has_offset=False):
    """ref bilateral_slice_op.cu (HDRNet): per-pixel affine coefficients
    trilinearly sampled from a bilateral grid at (gx, gy, guide(x,y)).
    x: [N,C,H,W]; grid: [N, gc, gd, gh, gw]; guide: [N,H,W]."""
    n, c, h, w = x.shape
    _, gc, gd, gh, gw = grid.shape
    n_out = gc // (c + 1) if has_offset else gc // c

    gy = (jnp.arange(h) + 0.5) * gh / h - 0.5
    gx = (jnp.arange(w) + 0.5) * gw / w - 0.5

    def sample(g_img, guide_img):
        gz = guide_img * gd - 0.5                       # [H, W]
        yy = jnp.broadcast_to(gy[:, None], (h, w))
        xx = jnp.broadcast_to(gx[None, :], (h, w))
        out = 0.0
        z0 = jnp.floor(gz)
        y0 = jnp.floor(yy)
        x0 = jnp.floor(xx)
        for dz in (0, 1):
            for dy in (0, 1):
                for dx in (0, 1):
                    zi = jnp.clip(z0 + dz, 0, gd - 1).astype(jnp.int32)
                    yi = jnp.clip(y0 + dy, 0, gh - 1).astype(jnp.int32)
                    xi = jnp.clip(x0 + dx, 0, gw - 1).astype(jnp.int32)
                    wz = 1 - jnp.abs(gz - (z0 + dz))
                    wy = 1 - jnp.abs(yy - (y0 + dy))
                    wx = 1 - jnp.abs(xx - (x0 + dx))
                    wt = (jnp.clip(wz, 0, 1) * jnp.clip(wy, 0, 1)
                          * jnp.clip(wx, 0, 1))
                    out = out + g_img[:, zi, yi, xi] * wt[None]
        return out                                      # [gc, H, W]

    coeff = jax.vmap(sample)(grid, guide)               # [N, gc, H, W]
    per = c + 1 if has_offset else c
    coeff = coeff.reshape(n, n_out, per, h, w)
    out = jnp.einsum("nocxy,ncxy->noxy", coeff[:, :, :c], x)
    if has_offset:
        out = out + coeff[:, :, c]
    return out


@register_op("pyramid_hash")
def pyramid_hash(ids, w, *, num_emb=8, space_len=100000, pyramid_layer=2,
                 rand_len=16):
    """ref pyramid_hash_op.cc (search ranking): n-gram pieces of the id
    sequence hash into a shared embedding space; output sums the
    n-gram embeddings per position."""
    ids = jnp.asarray(ids).astype(jnp.uint32)
    b, t = ids.shape
    out = jnp.zeros((b, t, num_emb), w.dtype)
    for n in range(2, 2 + pyramid_layer):
        # rolling hash of n-gram starting at each position
        acc = jnp.zeros((b, t), jnp.uint32)
        for i in range(n):
            shifted = jnp.pad(ids[:, i:], ((0, 0), (0, i)))
            acc = acc * jnp.uint32(2654435761) + shifted
        slot = (acc % jnp.uint32(space_len)).astype(jnp.int32)
        valid = (jnp.arange(t)[None, :] < t - (n - 1))
        emb = jnp.take(w, slot, axis=0)[..., :num_emb]
        out = out + emb * valid[..., None].astype(w.dtype)
    return out


@register_op("rank_attention")
def rank_attention(x, rank_offset, rank_param, *, max_rank=3,
                   max_size=0):
    """ref rank_attention_op.cc (CTR ranking): each instance selects the
    parameter block addressed by its (own rank, other rank) pairs and
    multiplies its features through; missing pairs (offset < 0)
    contribute zeros."""
    b, d = x.shape
    _, out_dim = rank_param.shape[0] // (max_rank * max_rank * d), \
        rank_param.shape[1]
    p = rank_param.reshape(max_rank * max_rank, d, out_dim)
    ins_rank = jnp.asarray(rank_offset[:, 0], jnp.int32)      # own rank
    acc = jnp.zeros((b, out_dim), x.dtype)
    cnt = jnp.zeros((b, 1), x.dtype)
    for k in range(max_rank):
        other = jnp.asarray(rank_offset[:, 2 * k + 1], jnp.int32)
        ok = (other >= 0) & (ins_rank >= 0)
        block = jnp.clip((ins_rank - 1) * max_rank
                         + jnp.clip(other - 1, 0, max_rank - 1),
                         0, max_rank * max_rank - 1)
        sel = p[block]                                        # [B, D, O]
        acc = acc + jnp.where(ok[:, None],
                              jnp.einsum("bd,bdo->bo", x, sel), 0.0)
        cnt = cnt + ok[:, None].astype(x.dtype)
    return acc / jnp.maximum(cnt, 1.0)


@register_op("tree_conv")
def tree_conv(nodes, edges, w, *, max_depth=2):
    """ref tree_conv_op.cc (tree-based CNN): propagate node features down
    `max_depth` hops of the adjacency and mix with per-hop weights.
    nodes: [N, V, D]; edges: [N, V, V] row-normalised adjacency;
    w: [max_depth+1, D, O]."""
    out = jnp.einsum("nvd,do->nvo", nodes, w[0])
    h = nodes
    for k in range(1, max_depth + 1):
        h = jnp.einsum("nuv,nud->nvd", edges, h)
        out = out + jnp.einsum("nvd,do->nvo", h, w[k])
    return jax.nn.relu(out)


@register_op("var_conv_2d")
def var_conv_2d(x, w, *, output_channel, input_channel, kernel_h,
                kernel_w, stride_h=1, stride_w=1):
    """ref var_conv_2d_op.cc: conv over per-instance variable-size 2-D
    feature maps. Padded form: x [B, C, H, W] already padded to the batch
    max; the kernel is an ordinary conv (padding SAME, stride given) —
    the LoD bookkeeping of the reference becomes the caller's mask."""
    from .nn_ops import conv2d

    wk = w.reshape(output_channel, input_channel, kernel_h, kernel_w)
    return conv2d(x, wk, stride=(stride_h, stride_w),
                  padding=((kernel_h - 1) // 2, (kernel_w - 1) // 2))


@register_op("distributed_lookup_table")
def distributed_lookup_table(ids, w, *, table_id=0, padding_idx=-1):
    """ref distributed_lookup_table_op.cc: embedding pull from the
    parameter server. Inside a compiled program the PS round-trip lives
    in the data path (ps.DistributedEmbedding pulls rows before the
    step); the op itself is the local lookup over the pulled shard."""
    from .nn_ops import lookup_table_v2

    return lookup_table_v2(jnp.asarray(ids), w, padding_idx=padding_idx)
