"""Linear-algebra ops (ref: paddle/fluid/operators/ cholesky_op, svd_op,
matrix_power_op, norm ops, inverse_op, p_norm_op)."""

from __future__ import annotations

import jax.numpy as jnp

from ..core.op_registry import register_op


@register_op("p_norm")
def p_norm(x, *, porder=2.0, axis=None, keepdim=False, epsilon=1e-12):
    if porder == float("inf"):
        return jnp.max(jnp.abs(x), axis=axis, keepdims=keepdim)
    if porder == float("-inf"):
        return jnp.min(jnp.abs(x), axis=axis, keepdims=keepdim)
    if porder == 0:
        return jnp.sum((x != 0).astype(x.dtype), axis=axis, keepdims=keepdim)
    s = jnp.sum(jnp.power(jnp.abs(x), porder), axis=axis, keepdims=keepdim)
    return jnp.power(s, 1.0 / porder)


@register_op("frobenius_norm")
def frobenius_norm(x, *, axis=None, keepdim=False):
    ax = tuple(axis) if isinstance(axis, (list, tuple)) else axis
    return jnp.sqrt(jnp.sum(jnp.square(x), axis=ax, keepdims=keepdim))


@register_op("inverse")
def inverse(x):
    return jnp.linalg.inv(x)


@register_op("cholesky")
def cholesky(x, *, upper=False):
    l = jnp.linalg.cholesky(x)
    return jnp.swapaxes(l, -1, -2) if upper else l


@register_op("matrix_power")
def matrix_power(x, *, n):
    return jnp.linalg.matrix_power(x, n)


@register_op("matrix_rank", no_grad=True)
def matrix_rank(x, *, tol=None, hermitian=False):
    return jnp.linalg.matrix_rank(x, rtol=tol)


@register_op("svd", multi_out=True)
def svd(x, *, full_matrices=False):
    u, s, vh = jnp.linalg.svd(x, full_matrices=full_matrices)
    return u, s, jnp.swapaxes(vh, -1, -2)


@register_op("qr", multi_out=True)
def qr(x, *, mode="reduced"):
    q, r = jnp.linalg.qr(x, mode=mode)
    return q, r


@register_op("eigh", multi_out=True)
def eigh(x, *, UPLO="L"):
    w, v = jnp.linalg.eigh(x, UPLO=UPLO)
    return w, v


@register_op("eigvalsh")
def eigvalsh(x, *, UPLO="L"):
    return jnp.linalg.eigvalsh(x, UPLO=UPLO)


@register_op("det")
def det(x):
    return jnp.linalg.det(x)


@register_op("slogdet", multi_out=True)
def slogdet(x):
    sign, logabs = jnp.linalg.slogdet(x)
    return sign, logabs


@register_op("pinv")
def pinv(x, *, rcond=1e-15, hermitian=False):
    return jnp.linalg.pinv(x, rtol=rcond, hermitian=hermitian)


@register_op("solve")
def solve(a, b):
    return jnp.linalg.solve(a, b)


@register_op("triangular_solve")
def triangular_solve(a, b, *, upper=True, transpose=False, unitriangular=False):
    import jax.scipy.linalg as jsl

    return jsl.solve_triangular(a, b, lower=not upper, trans=1 if transpose
                                else 0, unit_diagonal=unitriangular)


@register_op("lstsq", multi_out=True)
def lstsq(a, b, *, rcond=None):
    sol, res, rank, sv = jnp.linalg.lstsq(a, b, rcond=rcond)
    return sol, res


@register_op("tensordot")
def tensordot(a, b, *, axes):
    return jnp.tensordot(a, b, axes=axes)


@register_op("matrix_nms", no_grad=True)
def matrix_nms(*args, **kwargs):
    raise NotImplementedError("matrix_nms pending detection-op milestone")


@register_op("histogram", no_grad=True)
def histogram(x, *, bins=100, min=0, max=0):
    if min == 0 and max == 0:
        lo, hi = jnp.min(x), jnp.max(x)
    else:
        lo, hi = min, max
    hist, _ = jnp.histogram(x, bins=bins, range=(lo, hi))
    return hist


@register_op("bincount", no_grad=True)
def bincount(x, *, weights=None, minlength=0):
    return jnp.bincount(jnp.asarray(x).reshape(-1), weights=weights,
                        minlength=minlength)
