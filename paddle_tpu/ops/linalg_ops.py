"""Linear-algebra ops (ref: paddle/fluid/operators/ cholesky_op, svd_op,
matrix_power_op, norm ops, inverse_op, p_norm_op)."""

from __future__ import annotations

import jax.numpy as jnp

from ..core.op_registry import register_op


@register_op("p_norm")
def p_norm(x, *, porder=2.0, axis=None, keepdim=False, epsilon=1e-12):
    if porder == float("inf"):
        return jnp.max(jnp.abs(x), axis=axis, keepdims=keepdim)
    if porder == float("-inf"):
        return jnp.min(jnp.abs(x), axis=axis, keepdims=keepdim)
    if porder == 0:
        return jnp.sum((x != 0).astype(x.dtype), axis=axis, keepdims=keepdim)
    s = jnp.sum(jnp.power(jnp.abs(x), porder), axis=axis, keepdims=keepdim)
    return jnp.power(s, 1.0 / porder)


@register_op("frobenius_norm")
def frobenius_norm(x, *, axis=None, keepdim=False):
    ax = tuple(axis) if isinstance(axis, (list, tuple)) else axis
    return jnp.sqrt(jnp.sum(jnp.square(x), axis=ax, keepdims=keepdim))


@register_op("inverse")
def inverse(x):
    return jnp.linalg.inv(x)


@register_op("cholesky")
def cholesky(x, *, upper=False):
    l = jnp.linalg.cholesky(x)
    return jnp.swapaxes(l, -1, -2) if upper else l


@register_op("matrix_power")
def matrix_power(x, *, n):
    return jnp.linalg.matrix_power(x, n)


@register_op("matrix_rank", no_grad=True)
def matrix_rank(x, *, tol=None, hermitian=False):
    return jnp.linalg.matrix_rank(x, rtol=tol)


@register_op("svd", multi_out=True)
def svd(x, *, full_matrices=False):
    u, s, vh = jnp.linalg.svd(x, full_matrices=full_matrices)
    return u, s, jnp.swapaxes(vh, -1, -2)


@register_op("qr", multi_out=True)
def qr(x, *, mode="reduced"):
    q, r = jnp.linalg.qr(x, mode=mode)
    return q, r


@register_op("eigh", multi_out=True)
def eigh(x, *, UPLO="L"):
    w, v = jnp.linalg.eigh(x, UPLO=UPLO)
    return w, v


@register_op("eigvalsh")
def eigvalsh(x, *, UPLO="L"):
    return jnp.linalg.eigvalsh(x, UPLO=UPLO)


@register_op("det")
def det(x):
    return jnp.linalg.det(x)


@register_op("slogdet", multi_out=True)
def slogdet(x):
    sign, logabs = jnp.linalg.slogdet(x)
    return sign, logabs


@register_op("pinv")
def pinv(x, *, rcond=1e-15, hermitian=False):
    return jnp.linalg.pinv(x, rtol=rcond, hermitian=hermitian)


@register_op("solve")
def solve(a, b):
    return jnp.linalg.solve(a, b)


@register_op("triangular_solve")
def triangular_solve(a, b, *, upper=True, transpose=False, unitriangular=False):
    import jax.scipy.linalg as jsl

    return jsl.solve_triangular(a, b, lower=not upper, trans=1 if transpose
                                else 0, unit_diagonal=unitriangular)


@register_op("lstsq", multi_out=True)
def lstsq(a, b, *, rcond=None):
    sol, res, rank, sv = jnp.linalg.lstsq(a, b, rcond=rcond)
    return sol, res


@register_op("tensordot")
def tensordot(a, b, *, axes):
    return jnp.tensordot(a, b, axes=axes)


@register_op("matrix_nms", no_grad=True)
def matrix_nms(bboxes, scores, *, score_threshold=0.05, post_threshold=0.0,
               nms_top_k=400, keep_top_k=100, use_gaussian=False,
               gaussian_sigma=2.0, background_label=0, normalized=True):
    """Matrix NMS (ref paddle/fluid/operators/detection/matrix_nms_op.cc,
    SOLOv2): soft-suppression via an IoU decay matrix instead of hard
    greedy NMS. Eager/host op (dynamic output count — not jit-traceable);
    detection post-processing runs host-side.

    bboxes: [N, M, 4], scores: [N, C, M]. Returns (out [K, 6] rows of
    [label, score, x1, y1, x2, y2], index [K, 1], rois_num [N])."""
    import numpy as np

    bboxes = np.asarray(bboxes)
    scores = np.asarray(scores)
    n, m, _ = bboxes.shape
    c = scores.shape[1]
    off = 0.0 if normalized else 1.0

    def iou_matrix(b):
        x1, y1, x2, y2 = b[:, 0], b[:, 1], b[:, 2], b[:, 3]
        area = np.maximum(x2 - x1 + off, 0) * np.maximum(y2 - y1 + off, 0)
        ix1 = np.maximum(x1[:, None], x1[None, :])
        iy1 = np.maximum(y1[:, None], y1[None, :])
        ix2 = np.minimum(x2[:, None], x2[None, :])
        iy2 = np.minimum(y2[:, None], y2[None, :])
        iw = np.maximum(ix2 - ix1 + off, 0)
        ih = np.maximum(iy2 - iy1 + off, 0)
        inter = iw * ih
        union = area[:, None] + area[None, :] - inter
        return np.where(union > 0, inter / np.maximum(union, 1e-10), 0.0)

    all_rows, all_idx, rois_num = [], [], []
    for b in range(n):
        rows = []
        idxs = []
        for cls in range(c):
            if cls == background_label:
                continue
            sc = scores[b, cls]
            keep = np.where(sc > score_threshold)[0]
            if keep.size == 0:
                continue
            order = keep[np.argsort(-sc[keep])][:nms_top_k]
            boxes = bboxes[b, order]
            s = sc[order]
            iou = iou_matrix(boxes)
            iou = np.triu(iou, k=1)  # iou[i, j] for i < j
            # for each box j: max IoU with any higher-scored box, and the
            # per-suppressor compensation (matrix NMS decay)
            iou_cmax = iou.max(axis=0)
            # decay_j = min_i f(iou_ij) / f(iou_cmax_i): the compensation
            # indexes the SUPPRESSOR i (its own overlap with higher-scored
            # boxes), per the SOLOv2 matrix-NMS formula
            if use_gaussian:
                # ref matrix_nms_op.cc:87: exp((max_iou^2 - iou^2) * sigma)
                decay = np.exp(
                    (iou_cmax[:, None] ** 2 - iou ** 2) * gaussian_sigma)
            else:
                decay = (1.0 - iou) / np.maximum(1.0 - iou_cmax[:, None],
                                                 1e-10)
            decay = np.where(np.triu(np.ones_like(iou), k=1) > 0,
                             decay, np.inf)
            decay = decay.min(axis=0)
            decay = np.where(np.isinf(decay), 1.0, decay)
            new_s = s * decay
            ok = new_s >= post_threshold
            for j in np.where(ok)[0]:
                rows.append([float(cls), float(new_s[j]), *boxes[j]])
                idxs.append(b * m + order[j])
        if rows:
            rows = np.asarray(rows, np.float32)
            idxs = np.asarray(idxs, np.int64)
            top = np.argsort(-rows[:, 1])[:keep_top_k]
            rows, idxs = rows[top], idxs[top]
            all_rows.append(rows)
            all_idx.append(idxs)
            rois_num.append(len(rows))
        else:
            rois_num.append(0)
    if all_rows:
        out = np.concatenate(all_rows)
        index = np.concatenate(all_idx)[:, None]
    else:
        out = np.zeros((0, 6), np.float32)
        index = np.zeros((0, 1), np.int64)
    return (jnp.asarray(out), jnp.asarray(index),
            jnp.asarray(np.asarray(rois_num, np.int32)))


@register_op("histogram", no_grad=True)
def histogram(x, *, bins=100, min=0, max=0):
    if min == 0 and max == 0:
        lo, hi = jnp.min(x), jnp.max(x)
    else:
        lo, hi = min, max
    hist, _ = jnp.histogram(x, bins=bins, range=(lo, hi))
    return hist


@register_op("bincount", no_grad=True)
def bincount(x, *, weights=None, minlength=0):
    return jnp.bincount(jnp.asarray(x).reshape(-1), weights=weights,
                        minlength=minlength)
