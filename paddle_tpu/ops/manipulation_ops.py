"""Shape/layout manipulation ops.

Ref parity: paddle/fluid/operators/ reshape_op.cc, transpose_op.cc,
concat_op.cc, split_op.cc, gather_op.cc, scatter_op.cc, pad_op, tile_op,
expand_v2_op, flip, roll, cast_op. All static-shape (XLA requirement);
LoD-style dynamic shapes are expressed with padding + masks instead.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.op_registry import register_op


@register_op("cast")
def cast(x, *, dtype):
    from ..core.dtype import to_jax_dtype

    return jnp.asarray(x).astype(to_jax_dtype(dtype))


@register_op("assign")
def assign(x):
    return jnp.asarray(x)


@register_op("getitem")
def getitem(x, *, idx):
    return x[idx]


@register_op("reshape")
def reshape(x, *, shape):
    shape = [int(s) for s in shape]
    return jnp.reshape(x, shape)


@register_op("transpose")
def transpose(x, *, perm):
    return jnp.transpose(x, perm)


@register_op("concat")
def concat(*xs, axis=0):
    return jnp.concatenate(xs, axis=int(axis))


@register_op("stack")
def stack(*xs, axis=0):
    return jnp.stack(xs, axis=int(axis))


@register_op("split", multi_out=True)
def split(x, *, num_or_sections, axis=0):
    axis = int(axis)
    if isinstance(num_or_sections, int):
        return tuple(jnp.split(x, num_or_sections, axis=axis))
    # sections list; -1 means "the rest"
    sections = list(num_or_sections)
    total = x.shape[axis]
    if -1 in sections:
        known = sum(s for s in sections if s != -1)
        sections[sections.index(-1)] = total - known
    offsets = []
    acc = 0
    for s in sections[:-1]:
        acc += s
        offsets.append(acc)
    return tuple(jnp.split(x, offsets, axis=axis))


@register_op("unstack", multi_out=True)
def unstack(x, *, axis=0, num=None):
    n = num if num is not None else x.shape[axis]
    parts = jnp.split(x, n, axis=axis)
    return tuple(jnp.squeeze(p, axis=axis) for p in parts)


@register_op("squeeze")
def squeeze(x, *, axis=None):
    if axis is None:
        return jnp.squeeze(x)
    if isinstance(axis, (list, tuple)):
        axes = tuple(a for a in axis if x.shape[a] == 1)
        return jnp.squeeze(x, axis=axes) if axes else x
    if x.shape[axis] != 1:
        return x
    return jnp.squeeze(x, axis=axis)


@register_op("unsqueeze")
def unsqueeze(x, *, axis):
    if isinstance(axis, (list, tuple)):
        out = x
        for a in sorted(axis):
            out = jnp.expand_dims(out, a)
        return out
    return jnp.expand_dims(x, axis)


@register_op("flatten")
def flatten(x, *, start_axis=0, stop_axis=-1):
    nd = x.ndim
    if nd == 0:
        return x.reshape(1)
    start = start_axis + nd if start_axis < 0 else start_axis
    stop = stop_axis + nd if stop_axis < 0 else stop_axis
    shape = list(x.shape[:start]) + [-1] + list(x.shape[stop + 1:])
    return x.reshape(shape)


@register_op("expand_v2")
def expand_v2(x, *, shape):
    shape = list(shape)
    # paddle: -1 keeps original dim size
    x_shape = [1] * (len(shape) - x.ndim) + list(x.shape)
    out_shape = [xs if s == -1 else int(s) for s, xs in zip(shape, x_shape)]
    return jnp.broadcast_to(x.reshape(x_shape), out_shape)


@register_op("tile")
def tile(x, *, repeat_times):
    return jnp.tile(x, tuple(int(r) for r in repeat_times))


@register_op("broadcast_to")
def broadcast_to(x, *, shape):
    return jnp.broadcast_to(x, tuple(int(s) for s in shape))


@register_op("gather")
def gather(x, index, *, axis=0):
    index = jnp.asarray(index)
    if index.ndim == 0:
        index = index[None]
    return jnp.take(x, index, axis=int(axis))


@register_op("gather_nd")
def gather_nd(x, index):
    index = jnp.asarray(index)
    idx = tuple(jnp.moveaxis(index, -1, 0))
    return x[idx]


@register_op("index_select")
def index_select(x, index, *, axis=0):
    return jnp.take(x, jnp.asarray(index).reshape(-1), axis=int(axis))


@register_op("index_sample")
def index_sample(x, index):
    return jnp.take_along_axis(x, jnp.asarray(index), axis=1)


@register_op("take_along_axis")
def take_along_axis(x, index, *, axis):
    return jnp.take_along_axis(x, jnp.asarray(index), axis=int(axis))


@register_op("put_along_axis")
def put_along_axis(x, index, value, *, axis, reduce="assign"):
    index = jnp.asarray(index)
    value = jnp.broadcast_to(jnp.asarray(value), index.shape).astype(x.dtype)
    dims = [
        index if d == axis else jnp.arange(index.shape[d]).reshape(
            [-1 if i == d else 1 for i in range(index.ndim)])
        for d in range(x.ndim)
    ]
    at = x.at[tuple(dims)]
    if reduce == "assign":
        return at.set(value)
    if reduce == "add":
        return at.add(value)
    if reduce == "multiply" or reduce == "mul":
        return at.multiply(value)
    raise ValueError(f"unsupported reduce mode {reduce!r}")


@register_op("scatter")
def scatter(x, index, updates, *, overwrite=True):
    index = jnp.asarray(index).reshape(-1)
    if overwrite:
        return x.at[index].set(updates)
    # paddle: overwrite=False means accumulate, zeroing the rows first
    zeroed = x.at[index].set(jnp.zeros_like(updates))
    return zeroed.at[index].add(updates)


@register_op("scatter_nd_add")
def scatter_nd_add(x, index, updates):
    index = jnp.asarray(index)
    idx = tuple(jnp.moveaxis(index, -1, 0))
    return x.at[idx].add(updates)


@register_op("pad")
def pad(x, *, paddings, mode="constant", value=0.0, data_format="NCHW"):
    if isinstance(paddings, (list, tuple)) and len(paddings) == 2 * x.ndim:
        pads = [(int(paddings[2 * i]), int(paddings[2 * i + 1]))
                for i in range(x.ndim)]
    else:
        pads = [tuple(p) for p in paddings]
    jmode = {"constant": "constant", "reflect": "reflect",
             "replicate": "edge", "circular": "wrap"}[mode]
    if jmode == "constant":
        return jnp.pad(x, pads, mode=jmode, constant_values=value)
    return jnp.pad(x, pads, mode=jmode)


@register_op("roll")
def roll(x, *, shifts, axis=None):
    return jnp.roll(x, shifts, axis=axis)


@register_op("flip")
def flip(x, *, axis):
    return jnp.flip(x, axis=axis)


@register_op("rot90")
def rot90(x, *, k=1, axes=(0, 1)):
    return jnp.rot90(x, k=k, axes=tuple(axes))


@register_op("tril")
def tril(x, *, diagonal=0):
    return jnp.tril(x, k=diagonal)


@register_op("triu")
def triu(x, *, diagonal=0):
    return jnp.triu(x, k=diagonal)


@register_op("where")
def where(cond, x, y):
    return jnp.where(cond, x, y)


@register_op("full_like")
def full_like(x, *, fill_value, dtype=None):
    from ..core.dtype import to_jax_dtype

    dt = to_jax_dtype(dtype) if dtype is not None else None
    return jnp.full_like(x, fill_value, dtype=dt)


@register_op("strided_slice")
def strided_slice(x, *, axes, starts, ends, strides):
    idx = [slice(None)] * x.ndim
    for ax, st, en, sd in zip(axes, starts, ends, strides):
        idx[ax] = slice(int(st), int(en), int(sd))
    return x[tuple(idx)]


@register_op("slice_op")
def slice_op(x, *, axes, starts, ends):
    idx = [slice(None)] * x.ndim
    for ax, st, en in zip(axes, starts, ends):
        idx[ax] = slice(int(st), int(en))
    return x[tuple(idx)]


@register_op("repeat_interleave")
def repeat_interleave(x, *, repeats, axis=None):
    return jnp.repeat(x, repeats, axis=axis)


@register_op("moveaxis")
def moveaxis(x, *, source, destination):
    return jnp.moveaxis(x, source, destination)


@register_op("swapaxes")
def swapaxes(x, *, axis0, axis1):
    return jnp.swapaxes(x, axis0, axis1)


@register_op("as_real")
def as_real(x):
    return jnp.stack([jnp.real(x), jnp.imag(x)], axis=-1)


@register_op("as_complex")
def as_complex(x):
    return jax.lax.complex(x[..., 0], x[..., 1])


@register_op("diag_embed")
def diag_embed(x, *, offset=0, dim1=-2, dim2=-1):
    n = x.shape[-1] + abs(offset)  # output is square (n, n)
    out = jnp.zeros(x.shape[:-1] + (n, n), x.dtype)
    idx = jnp.arange(x.shape[-1])
    rows = idx + max(-offset, 0)
    cols = idx + max(offset, 0)
    out = out.at[..., rows, cols].set(x)
    if dim1 != -2 or dim2 != -1:
        out = jnp.moveaxis(out, (-2, -1), (dim1, dim2))
    return out


@register_op("meshgrid", multi_out=True)
def meshgrid(*xs):
    return tuple(jnp.meshgrid(*xs, indexing="ij"))


@register_op("one_hot", no_grad=True)
def one_hot(x, *, num_classes):
    return jax.nn.one_hot(jnp.asarray(x).astype(jnp.int32), num_classes)


@register_op("sequence_mask", no_grad=True)
def sequence_mask(lengths, *, maxlen=None, dtype="bool"):
    """Padded-sequence validity mask (the LoD replacement: SURVEY hard
    part #4 — variable length = padding + mask; ref sequence_ops/ and
    python/paddle/fluid/layers/sequence_lod.py sequence_mask)."""
    import numpy as _np

    lengths = jnp.asarray(lengths)
    if maxlen is None:
        maxlen = int(_np.asarray(jax.lax.stop_gradient(lengths)).max())
    pos = jnp.arange(maxlen)
    mask = pos[None, :] < lengths[..., None]
    if dtype == "bool":
        return mask
    from ..core.dtype import to_jax_dtype

    return mask.astype(to_jax_dtype(dtype))
