"""Reduction ops (ref: paddle/fluid/operators/reduce_ops/)."""

from __future__ import annotations

import jax.numpy as jnp

from ..core.op_registry import register_op


def _axis_arg(axis):
    if axis is None:
        return None
    if isinstance(axis, (list, tuple)):
        return None if len(axis) == 0 else tuple(int(a) for a in axis)
    return int(axis)


def _reduce(name, fn, no_grad=False):
    def op(x, *, axis=None, keepdim=False):
        return fn(x, axis=_axis_arg(axis), keepdims=keepdim)

    op.__name__ = name
    register_op(name, no_grad=no_grad)(op)


_reduce("reduce_sum", jnp.sum)
_reduce("reduce_mean", jnp.mean)
_reduce("reduce_max", jnp.max)
_reduce("reduce_min", jnp.min)
_reduce("reduce_prod", jnp.prod)
_reduce("reduce_any", jnp.any, no_grad=True)
_reduce("reduce_all", jnp.all, no_grad=True)
_reduce("nansum", jnp.nansum)
_reduce("nanmean", jnp.nanmean)


@register_op("logsumexp")
def logsumexp(x, *, axis=None, keepdim=False):
    from jax.scipy.special import logsumexp as lse

    return lse(x, axis=_axis_arg(axis), keepdims=keepdim)


@register_op("amax")
def amax(x, *, axis=None, keepdim=False):
    return jnp.amax(x, axis=_axis_arg(axis), keepdims=keepdim)


@register_op("amin")
def amin(x, *, axis=None, keepdim=False):
    return jnp.amin(x, axis=_axis_arg(axis), keepdims=keepdim)


@register_op("var")
def var(x, *, axis=None, unbiased=True, keepdim=False):
    return jnp.var(x, axis=_axis_arg(axis), ddof=1 if unbiased else 0,
                   keepdims=keepdim)


@register_op("std")
def std(x, *, axis=None, unbiased=True, keepdim=False):
    return jnp.std(x, axis=_axis_arg(axis), ddof=1 if unbiased else 0,
                   keepdims=keepdim)


@register_op("median")
def median(x, *, axis=None, keepdim=False):
    return jnp.median(x, axis=_axis_arg(axis), keepdims=keepdim)


@register_op("quantile")
def quantile(x, *, q, axis=None, keepdim=False):
    return jnp.quantile(x, jnp.asarray(q), axis=_axis_arg(axis),
                        keepdims=keepdim)


@register_op("count_nonzero", no_grad=True)
def count_nonzero(x, *, axis=None, keepdim=False):
    return jnp.count_nonzero(x, axis=_axis_arg(axis), keepdims=keepdim)
