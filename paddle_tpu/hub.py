"""paddle.hub namespace (ref: python/paddle/hub.py)."""

from .hapi.hub import help, list, load  # noqa: F401

__all__ = ["list", "help", "load"]
