"""Optimizers (ref: python/paddle/optimizer/optimizer.py:49 base +
adam/adamw/lamb/momentum/sgd/rmsprop; update rules from
paddle/fluid/operators/optimizers/*.cc).

TPU-native design: every optimizer is defined by a *pure* per-parameter
update rule `_rule(param, grad, state, lr_and_hyper) -> (new_param,
new_state)`. The eager `step()` runs the rule through one cached `jax.jit`
per shape; the functional engine maps the same rule over the whole
parameter pytree inside the compiled train step (so the reference's fused
optimizer-op IR passes are unnecessary — XLA fuses the tree-wide update
into a handful of kernels).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core import config
from ..core.tensor import Parameter, Tensor
from . import lr as lr  # noqa: PLC0414
from .lr import LRScheduler

__all__ = [
    "Optimizer", "SGD", "Momentum", "Adam", "AdamW", "Adamax", "Adagrad",
    "Adadelta", "RMSProp", "Lamb", "LarsMomentum", "Lars", "lr",
    "ExponentialMovingAverage", "LookAhead", "ModelAverage",
]


_warned_sparse_densify = False


class Optimizer:
    def __init__(self, learning_rate=0.001, parameters=None,
                 weight_decay=None, grad_clip=None, name=None,
                 multi_precision=False):
        if parameters is not None:
            parameters = list(parameters)
            if parameters and isinstance(parameters[0], dict):
                # param groups: flatten (per-group lr handled via
                # optimize_attr)
                flat = []
                for group in parameters:
                    for p in group["params"]:
                        if "learning_rate" in group:
                            p.optimize_attr["learning_rate"] = \
                                group["learning_rate"]
                        if "weight_decay" in group:
                            p.regularizer = _as_decay(group["weight_decay"])
                        flat.append(p)
                parameters = flat
        self._parameter_list = parameters
        self._learning_rate = learning_rate
        self._weight_decay = _as_decay(weight_decay)
        self._grad_clip = grad_clip
        self._accumulators = {}  # id(param) -> state dict
        self._global_step = 0
        self._param_names = {}
        self._jit_rules = {}

    # -- lr ------------------------------------------------------------------
    def get_lr(self):
        if isinstance(self._learning_rate, LRScheduler):
            return float(self._learning_rate())
        return float(self._learning_rate)

    def set_lr(self, value):
        if isinstance(self._learning_rate, LRScheduler):
            raise RuntimeError(
                "can't set_lr when learning rate is an LRScheduler")
        self._learning_rate = float(value)

    # -- state ---------------------------------------------------------------
    def _state_for(self, p):
        st = self._accumulators.get(id(p))
        if st is None:
            st = self._init_state(p._value)
            self._accumulators[id(p)] = st
        return st

    def _init_state(self, value):
        return {}

    # pure rule; override in subclasses
    def _rule(self, param, grad, state, lr, **hyper):
        raise NotImplementedError

    # hyperparams passed to the rule each step (may include python floats
    # that are stable across steps — they become compile-time constants)
    def _hyper(self):
        return {}

    # -- the eager step ------------------------------------------------------
    @config.no_grad()
    def step(self):
        from ..core.selected_rows import SelectedRows

        self._global_step += 1
        params_grads = []
        sparse_pg = []
        for p in self._parameter_list:
            if p is None or p.stop_gradient or p._grad is None:
                continue
            if isinstance(p._grad, SelectedRows):
                decay = p.regularizer if getattr(p, "regularizer", None) is not None \
                    else self._weight_decay
                if self._grad_clip is not None or (
                        decay is not None
                        and not self._decoupled_weight_decay()):
                    # clip/coupled-decay need the whole gradient: densify
                    # so the configured semantics hold exactly (the
                    # reference merges SelectedRows before clipping too)
                    global _warned_sparse_densify
                    if not _warned_sparse_densify:
                        import warnings

                        warnings.warn(
                            "sparse gradient densified because grad_clip/"
                            "weight_decay is configured; drop them to keep "
                            "the sparse fast path")
                        _warned_sparse_densify = True
                    params_grads.append((p, Tensor(p._grad)))
                else:
                    sparse_pg.append((p, p._grad))
            else:
                params_grads.append((p, Tensor(p._grad)))
        params_grads = self._preprocess(params_grads)
        lr = self.get_lr()
        for p, g in params_grads:
            state = self._state_for(p)
            plr = lr * getattr(p, "optimize_attr", {}).get("learning_rate", 1.0)
            new_p, new_state = self._run_rule(
                p._value, g._value, state, plr, self._hyper_for(p))
            p._value = new_p
            self._accumulators[id(p)] = new_state
        for p, sr in sparse_pg:
            state = self._state_for(p)
            plr = lr * getattr(p, "optimize_attr", {}).get("learning_rate", 1.0)
            new_p, new_state = self._apply_sparse(
                p._value, sr, state, plr, self._hyper_for(p))
            p._value = new_p
            self._accumulators[id(p)] = new_state

    def _apply_sparse(self, pv, sr, state, lr, hyper):
        """Apply a SelectedRows gradient (ref
        operators/optimizers/*_op.cc SelectedRows kernels). Default:
        densify and run the dense rule; SGD/Adam override with row-wise
        updates that never materialise a vocab-sized gradient."""
        return self._rule(pv, sr.to_dense(), state, lr, **hyper)

    def _hyper_for(self, p):
        """Per-parameter hyperparameters (overridden by optimizers with
        name-based exclusions, e.g. LARS weight-decay skip lists)."""
        return self._hyper()

    def _run_rule(self, pv, gv, state, lr, hyper):
        key = (pv.shape, str(pv.dtype),
               tuple(sorted((k, v) for k, v in hyper.items())))
        fn = self._jit_rules.get(key)
        if fn is None:
            fn = jax.jit(lambda p, g, s, lr_: self._rule(
                p, g, s, lr_, **hyper))
            self._jit_rules[key] = fn
        return fn(pv, gv, state, lr)

    def _preprocess(self, params_grads):
        # weight decay as L2 regularization on grads (per-param regularizer
        # wins over the optimizer-level setting, paddle semantics)
        out = []
        for p, g in params_grads:
            decay = p.regularizer if getattr(p, "regularizer", None) is not None \
                else self._weight_decay
            if decay is not None and not self._decoupled_weight_decay():
                g = Tensor(g._value + decay.coeff * p._value)
            out.append((p, g))
        if self._grad_clip is not None:
            out = self._grad_clip(out)
        return out

    def _decoupled_weight_decay(self):
        return False

    def clear_grad(self, set_to_zero=False):
        for p in self._parameter_list or []:
            if p is not None:
                p.clear_grad()

    clear_gradients = clear_grad

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        from ..static.program import Variable as _StaticVar

        if isinstance(loss, _StaticVar):
            return self._minimize_static(loss, parameters, no_grad_set)
        self.step()
        return None, [(p, p.grad) for p in self._parameter_list or []]

    def _minimize_static(self, loss, parameters=None, no_grad_set=None):
        """Static-graph path: record @backward + @update ops into the
        default main program (ref fleet/static optimizer.minimize —
        program rewriting instead of eager stepping)."""
        from ..clip import (
            ClipGradByGlobalNorm, ClipGradByNorm, ClipGradByValue,
        )
        from ..static import program as sp

        plist = parameters if parameters is not None \
            else self._parameter_list
        pairs = sp.append_backward(loss, plist, no_grad_set)

        # map grad vars back to the eager Parameters (for per-param lr /
        # regularizer attrs) via the program's intern table
        prog = sp.default_main_program()
        var_to_eager = {}
        for t in (plist or []):
            if isinstance(t, Tensor):
                hit = prog._interned.get(id(t))
                if hit is not None:
                    var_to_eager[id(hit[1])] = t

        def _coeff_for(pvar):
            eager = var_to_eager.get(id(pvar))
            decay = (getattr(eager, "regularizer", None)
                     if eager is not None else None) or self._weight_decay
            if decay is not None and not self._decoupled_weight_decay():
                return decay.coeff
            return 0.0

        per_grad_clip = None
        global_clip = isinstance(self._grad_clip, ClipGradByGlobalNorm)
        if global_clip:
            # decay folds into the grads INSIDE the clip op, before the
            # norm — matching the eager _preprocess order (decay, then
            # clip sees decay-included grads)
            sp.append_global_norm_clip(
                pairs, self._grad_clip.clip_norm,
                decay_coeffs=[_coeff_for(p) for p, _ in pairs])
        elif isinstance(self._grad_clip, ClipGradByNorm):
            per_grad_clip = ("norm", self._grad_clip.clip_norm)
        elif isinstance(self._grad_clip, ClipGradByValue):
            per_grad_clip = ("value", self._grad_clip.min,
                             self._grad_clip.max)
        elif self._grad_clip is not None:
            raise NotImplementedError(
                f"grad_clip {type(self._grad_clip).__name__} is not "
                "supported in the static path")

        for pvar, gvar in pairs:
            eager = var_to_eager.get(id(pvar))
            lr_scale = 1.0
            if eager is not None:
                lr_scale = getattr(eager, "optimize_attr",
                                   {}).get("learning_rate", 1.0)
            coeff = 0.0 if global_clip else _coeff_for(pvar)
            sp.append_optimizer_update(self, pvar, gvar, lr_scale, coeff,
                                       clip=per_grad_clip)
        return None, pairs

    # -- persistence ---------------------------------------------------------
    def state_dict(self):
        import numpy as np

        sd = {"global_step": self._global_step}
        for i, p in enumerate(self._parameter_list or []):
            st = self._accumulators.get(id(p))
            if st is None:
                continue
            name = p.name or f"param_{i}"
            for k, v in st.items():
                sd[f"{name}.{k}"] = np.asarray(v)
        if isinstance(self._learning_rate, LRScheduler):
            sd["LR_Scheduler"] = self._learning_rate.state_dict()
        return sd

    def set_state_dict(self, state_dict):
        self._global_step = int(state_dict.get("global_step", 0))
        missing = []
        for i, p in enumerate(self._parameter_list or []):
            name = p.name or f"param_{i}"
            st = self._init_state(p._value)
            found = False
            for k in list(st):
                kk = f"{name}.{k}"
                if kk not in state_dict:
                    # legacy checkpoints keyed by position before params
                    # had auto names
                    kk = f"param_{i}.{k}"
                if kk in state_dict:
                    st[k] = jnp.asarray(state_dict[kk])
                    found = True
            if found:
                self._accumulators[id(p)] = st
            elif st:
                missing.append(name)
        if missing:
            import warnings

            warnings.warn(
                "optimizer.set_state_dict found no saved state for "
                f"parameters {missing[:5]}{'...' if len(missing) > 5 else ''}"
                " — their accumulators stay at fresh initialisation",
                stacklevel=2)
        if "LR_Scheduler" in state_dict and isinstance(
                self._learning_rate, LRScheduler):
            self._learning_rate.set_state_dict(state_dict["LR_Scheduler"])

    # -- functional access (used by the compiled engine) ---------------------
    def init_state_tree(self, params):
        return jax.tree.map(
            lambda v: self._init_state(v), params,
            is_leaf=lambda x: hasattr(x, "shape") and hasattr(x, "dtype"))

    def _leaf_meta(self, p):
        """Per-parameter update metadata for the compiled path, mirroring
        the eager `_preprocess`/`step` semantics: coupled L2/L1 decay
        (per-param regularizer wins over the optimizer-level setting) and
        per-param lr multipliers (`optimize_attr['learning_rate']`)."""
        decay = p.regularizer if getattr(p, "regularizer", None) is not None \
            else self._weight_decay
        coeff, l1 = 0.0, False
        if decay is not None and not self._decoupled_weight_decay():
            coeff, l1 = decay.coeff, isinstance(decay, L1Decay)
        return {"coeff": coeff, "l1": l1,
                "lr_mult": float(p.optimize_attr.get("learning_rate", 1.0))}

    def param_metas(self, named_params):
        """dict name -> Parameter  =>  dict name -> leaf meta (static
        floats; compiled into the train step as constants)."""
        return {k: self._leaf_meta(p) for k, p in named_params.items()}

    def param_metas_for(self, param_names, state_dict):
        """Metas for `param_names` resolved from a layer `state_dict`, or
        None when any name is missing / not a Parameter (engines then run
        without per-param decay/lr metadata). Single point of truth for
        the compiled engines (engine/pp_engine/hybrid)."""
        from ..core.tensor import Parameter

        sel = {k: state_dict.get(k) for k in param_names}
        if not sel or any(not isinstance(v, Parameter)
                          for v in sel.values()):
            return None
        return self.param_metas(sel)

    def decay_gradients_tree(self, params, grads, metas):
        """Fold coupled L2/L1 decay into grads — called by the compiled
        engines BEFORE grad clipping, matching the eager `_preprocess`
        order (decay, then clip)."""
        if metas is None:
            return grads
        flat_p, tree = jax.tree.flatten(params)
        flat_g = tree.flatten_up_to(grads)
        flat_m = tree.flatten_up_to(metas)
        out = []
        for p, g, m in zip(flat_p, flat_g, flat_m):
            if m is not None and m.get("coeff"):
                reg = jnp.sign(p) if m.get("l1") else p
                g = g + jnp.asarray(m["coeff"], g.dtype) * \
                    reg.astype(g.dtype)
            out.append(g)
        return jax.tree.unflatten(tree, out)

    def apply_gradients_tree(self, params, grads, states, lr, metas=None):
        """Pure tree-wide update used inside the compiled train step.

        `params`/`grads` share a structure whose leaves are arrays; `states`
        has the same structure with a per-param state dict at each leaf.
        `metas` (optional, same structure, leaf = `_leaf_meta` dict) carries
        lr-multiplier / per-param decoupled-decay overrides. Coupled decay
        is NOT applied here — engines fold it in pre-clip via
        `decay_gradients_tree`.
        """
        hyper = self._hyper()
        flat_p, tree = jax.tree.flatten(params)
        flat_g = tree.flatten_up_to(grads)
        flat_s = tree.flatten_up_to(states)
        if metas is not None:
            flat_m = tree.flatten_up_to(metas)
        else:
            flat_m = [None] * len(flat_p)
        new_p, new_s = [], []
        for p, g, s, m in zip(flat_p, flat_g, flat_s, flat_m):
            h = hyper
            leaf_lr = lr
            if m is not None:
                if m.get("lr_mult", 1.0) != 1.0:
                    leaf_lr = lr * m["lr_mult"]
                if "decoupled_coeff" in m:
                    h = dict(hyper)
                    h["coeff"] = m["decoupled_coeff"]
                if "hyper_overrides" in m:
                    h = {**h, **m["hyper_overrides"]}
            np_, ns_ = self._rule(p, g, s, leaf_lr, **h)
            new_p.append(np_)
            new_s.append(ns_)
        return jax.tree.unflatten(tree, new_p), jax.tree.unflatten(
            tree, new_s)


class _Decay:
    def __init__(self, coeff):
        self.coeff = float(coeff)


class L2Decay(_Decay):
    pass


class L1Decay(_Decay):
    pass


def _as_decay(wd):
    if wd is None:
        return None
    if isinstance(wd, _Decay):
        return wd
    return L2Decay(float(wd))


# ---------------------------------------------------------------------------
# update rules (ref: paddle/fluid/operators/optimizers/)
# ---------------------------------------------------------------------------


class SGD(Optimizer):
    def _rule(self, param, grad, state, lr):
        return param - lr * grad.astype(param.dtype), state

    def _apply_sparse(self, pv, sr, state, lr, hyper):
        # scatter-add handles duplicate rows; mode='drop' ignores the
        # static-size unique's fill rows
        upd = (-lr * sr.values).astype(pv.dtype)
        return pv.at[sr.rows].add(upd, mode="drop"), state


class Momentum(Optimizer):
    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 use_nesterov=False, weight_decay=None, grad_clip=None,
                 multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._momentum = momentum
        self._use_nesterov = use_nesterov

    def _init_state(self, value):
        return {"velocity": jnp.zeros_like(value)}

    def _hyper(self):
        return {"momentum": self._momentum, "nesterov": self._use_nesterov}

    def _rule(self, param, grad, state, lr, *, momentum, nesterov):
        g = grad.astype(param.dtype)
        v = momentum * state["velocity"] + g
        if nesterov:
            new_p = param - lr * (g + momentum * v)
        else:
            new_p = param - lr * v
        return new_p, {"velocity": v}


class Adam(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, lazy_mode=False, multi_precision=False,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon
        self._lazy_mode = lazy_mode

    def _init_state(self, value):
        return {
            "moment1": jnp.zeros_like(value),
            "moment2": jnp.zeros_like(value),
            "beta1_pow": jnp.ones((), jnp.float32),
            "beta2_pow": jnp.ones((), jnp.float32),
        }

    def _apply_sparse(self, pv, sr, state, lr, hyper):
        """lazy_mode row-wise Adam (ref adam_op.h SelectedRows kernel +
        lazy_mode): moments update only on the looked-up rows. Without
        lazy_mode paddle still decays ALL moments — that needs the dense
        path, so fall back."""
        if not self._lazy_mode:
            return super()._apply_sparse(pv, sr, state, lr, hyper)
        beta1 = hyper["beta1"]
        beta2 = hyper["beta2"]
        epsilon = hyper["epsilon"]
        sr = sr.coalesced()
        rows, g = sr.rows, sr.values.astype(jnp.float32)
        m_r = state["moment1"][rows].astype(jnp.float32)
        v_r = state["moment2"][rows].astype(jnp.float32)
        m_r = beta1 * m_r + (1 - beta1) * g
        v_r = beta2 * v_r + (1 - beta2) * g * g
        b1p = state["beta1_pow"] * beta1
        b2p = state["beta2_pow"] * beta2
        mhat = m_r / (1 - b1p)
        vhat = v_r / (1 - b2p)
        p_r = pv[rows].astype(jnp.float32)
        coeff = hyper.get("coeff", 0.0)  # AdamW decoupled decay, row-wise
        if coeff:
            p_r = p_r * (1.0 - lr * coeff)
        new_rows = p_r - lr * mhat / (jnp.sqrt(vhat) + epsilon)
        new_p = pv.at[rows].set(new_rows.astype(pv.dtype), mode="drop")
        new_state = {
            "moment1": state["moment1"].at[rows].set(
                m_r.astype(state["moment1"].dtype), mode="drop"),
            "moment2": state["moment2"].at[rows].set(
                v_r.astype(state["moment2"].dtype), mode="drop"),
            "beta1_pow": b1p, "beta2_pow": b2p,
        }
        return new_p, new_state

    def _hyper(self):
        return {"beta1": self._beta1, "beta2": self._beta2,
                "epsilon": self._epsilon}

    def _rule(self, param, grad, state, lr, *, beta1, beta2, epsilon):
        g = grad.astype(jnp.float32)
        p32 = param.astype(jnp.float32)
        m = beta1 * state["moment1"] + (1 - beta1) * g
        v = beta2 * state["moment2"] + (1 - beta2) * g * g
        b1p = state["beta1_pow"] * beta1
        b2p = state["beta2_pow"] * beta2
        # scalar-folded bias correction — algebraically identical to
        # mhat/(sqrt(vhat)+eps) but with ONE param-sized divide + sqrt
        # instead of three divides:
        #   m/(1-b1p) / (sqrt(v/(1-b2p)) + eps)
        #   == sqrt(1-b2p)/(1-b1p) * m / (sqrt(v) + eps*sqrt(1-b2p))
        # The update fusions are VPU-compute-bound (divides/sqrts over
        # every element; 18% of the ERNIE step before folding).
        corr2 = jnp.sqrt(1.0 - b2p)
        lr_t = lr * corr2 / (1.0 - b1p)
        new_p = p32 - lr_t * (m / (jnp.sqrt(v) + epsilon * corr2))
        return new_p.astype(param.dtype), {
            "moment1": m, "moment2": v, "beta1_pow": b1p, "beta2_pow": b2p}


class AdamW(Adam):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=0.01,
                 lr_ratio=None, apply_decay_param_fun=None, grad_clip=None,
                 lazy_mode=False, multi_precision=False, name=None):
        super().__init__(learning_rate, beta1, beta2, epsilon, parameters,
                         None, grad_clip, lazy_mode)
        self._coeff = float(weight_decay) if not isinstance(
            weight_decay, _Decay) else weight_decay.coeff
        self._apply_decay_param_fun = apply_decay_param_fun

    def _decoupled_weight_decay(self):
        return True

    def _hyper(self):
        h = super()._hyper()
        h["coeff"] = self._coeff
        return h

    def _leaf_meta(self, p):
        meta = super()._leaf_meta(p)
        if self._apply_decay_param_fun is not None and \
                not self._apply_decay_param_fun(p.name or ""):
            meta["decoupled_coeff"] = 0.0
        return meta

    def _rule(self, param, grad, state, lr, *, beta1, beta2, epsilon, coeff):
        # decoupled decay applied to the param before the adam update
        p = param * (1.0 - lr * coeff)
        return super()._rule(p, grad, state, lr, beta1=beta1, beta2=beta2,
                             epsilon=epsilon)

    def _hyper_for(self, p):
        # honour apply_decay_param_fun by zeroing coeff per-param; the
        # base step() (dense AND sparse paths) consults this per leaf
        h = self._hyper()
        if self._apply_decay_param_fun is not None and \
                not self._apply_decay_param_fun(p.name or ""):
            h = dict(h)
            h["coeff"] = 0.0
        return h


class Adamax(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def _init_state(self, value):
        return {"moment": jnp.zeros_like(value),
                "inf_norm": jnp.zeros_like(value),
                "beta1_pow": jnp.ones((), jnp.float32)}

    def _hyper(self):
        return {"beta1": self._beta1, "beta2": self._beta2,
                "epsilon": self._epsilon}

    def _rule(self, param, grad, state, lr, *, beta1, beta2, epsilon):
        g = grad.astype(param.dtype)
        m = beta1 * state["moment"] + (1 - beta1) * g
        u = jnp.maximum(beta2 * state["inf_norm"], jnp.abs(g))
        b1p = state["beta1_pow"] * beta1
        new_p = param - (lr / (1 - b1p)).astype(param.dtype) * m / \
            (u + epsilon)
        return new_p, {"moment": m, "inf_norm": u, "beta1_pow": b1p}


class Adagrad(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-6, parameters=None,
                 weight_decay=None, grad_clip=None,
                 initial_accumulator_value=0.0, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._epsilon = epsilon
        self._initial = initial_accumulator_value

    def _init_state(self, value):
        return {"moment": jnp.full_like(value, self._initial)}

    def _hyper(self):
        return {"epsilon": self._epsilon}

    def _rule(self, param, grad, state, lr, *, epsilon):
        g = grad.astype(param.dtype)
        mom = state["moment"] + g * g
        new_p = param - lr * g / (jnp.sqrt(mom) + epsilon)
        return new_p, {"moment": mom}


class Adadelta(Optimizer):
    def __init__(self, learning_rate=0.001, epsilon=1e-6, rho=0.95,
                 parameters=None, weight_decay=None, grad_clip=None,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._epsilon, self._rho = epsilon, rho

    def _init_state(self, value):
        return {"avg_squared_grad": jnp.zeros_like(value),
                "avg_squared_update": jnp.zeros_like(value)}

    def _hyper(self):
        return {"epsilon": self._epsilon, "rho": self._rho}

    def _rule(self, param, grad, state, lr, *, epsilon, rho):
        g = grad.astype(param.dtype)
        asg = rho * state["avg_squared_grad"] + (1 - rho) * g * g
        update = g * jnp.sqrt(state["avg_squared_update"] + epsilon) / \
            jnp.sqrt(asg + epsilon)
        asu = rho * state["avg_squared_update"] + (1 - rho) * update * update
        return param - lr * update, {
            "avg_squared_grad": asg, "avg_squared_update": asu}


class RMSProp(Optimizer):
    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0,
                 centered=False, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._rho, self._epsilon = rho, epsilon
        self._momentum, self._centered = momentum, centered

    def _init_state(self, value):
        return {"mean_square": jnp.zeros_like(value),
                "mean_grad": jnp.zeros_like(value),
                "momentum": jnp.zeros_like(value)}

    def _hyper(self):
        return {"rho": self._rho, "epsilon": self._epsilon,
                "momentum": self._momentum, "centered": self._centered}

    def _rule(self, param, grad, state, lr, *, rho, epsilon, momentum,
              centered):
        g = grad.astype(param.dtype)
        ms = rho * state["mean_square"] + (1 - rho) * g * g
        if centered:
            mg = rho * state["mean_grad"] + (1 - rho) * g
            denom = jnp.sqrt(ms - mg * mg + epsilon)
        else:
            mg = state["mean_grad"]
            denom = jnp.sqrt(ms + epsilon)
        mom = momentum * state["momentum"] + lr * g / denom
        return param - mom, {"mean_square": ms, "mean_grad": mg,
                             "momentum": mom}


class LarsMomentum(Optimizer):
    """Layer-wise adaptive rate scaling with momentum.

    ref: paddle/fluid/operators/optimizers/lars_momentum_op.cc and
    fleet/meta_optimizers/lars_optimizer.py —
      local_lr = lr * lars_coeff * ||p|| / (||g|| + decay * ||p|| + eps)
      v' = mu * v + local_lr * (g + decay * p);  p' = p - v'
    """

    def __init__(self, learning_rate=0.001, momentum=0.9, lars_coeff=0.001,
                 lars_weight_decay=0.0005, parameters=None, grad_clip=None,
                 epsilon=0.0, exclude_from_weight_decay=None, name=None):
        super().__init__(learning_rate, parameters, None, grad_clip)
        self._momentum = momentum
        self._lars_coeff = lars_coeff
        self._lars_decay = lars_weight_decay
        self._epsilon = epsilon
        self._exclude = tuple(exclude_from_weight_decay or ())

    def _init_state(self, value):
        return {"velocity": jnp.zeros_like(value)}

    def _hyper(self):
        return {"momentum": self._momentum, "coeff": self._lars_coeff,
                "decay": self._lars_decay, "epsilon": self._epsilon}

    def _rule(self, param, grad, state, lr, *, momentum, coeff, decay,
              epsilon):
        g = grad.astype(jnp.float32)
        p32 = param.astype(jnp.float32)
        p_norm = jnp.sqrt(jnp.sum(p32 * p32))
        g_norm = jnp.sqrt(jnp.sum(g * g))
        # reference kernel form: local_lr = lr*coeff*||p||/(||g|| +
        # decay*||p|| + eps); an all-zero denominator yields 0 (not NaN)
        denom = g_norm + decay * p_norm + epsilon
        local_lr = jnp.where(
            denom > 0, lr * coeff * p_norm / jnp.maximum(denom, 1e-30),
            0.0)
        v = momentum * state["velocity"] + local_lr * (g + decay * p32)
        new_p = p32 - v
        return new_p.astype(param.dtype), {"velocity": v}

    def _excluded(self, name):
        return bool(name) and any(sub in name for sub in self._exclude)

    def _hyper_for(self, p):
        h = self._hyper()
        if self._excluded(getattr(p, "name", None)):
            h = {**h, "decay": 0.0}
        return h

    def _leaf_meta(self, p):
        # exclusion keyed on p.name in BOTH paths (eager _hyper_for above,
        # compiled via metas) — state-dict keys are a different namespace
        meta = super()._leaf_meta(p)
        if self._excluded(getattr(p, "name", None)):
            meta = dict(meta or {})
            meta["hyper_overrides"] = {"decay": 0.0}
        return meta


Lars = LarsMomentum


class Lamb(Optimizer):
    """ref: paddle/fluid/operators/optimizers/lamb_op.cc."""

    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01,
                 beta1=0.9, beta2=0.999, epsilon=1e-6, parameters=None,
                 grad_clip=None, exclude_from_weight_decay_fn=None,
                 name=None):
        super().__init__(learning_rate, parameters, None, grad_clip)
        self._coeff = lamb_weight_decay
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon
        self._exclude_fn = exclude_from_weight_decay_fn

    def _init_state(self, value):
        return {"moment1": jnp.zeros_like(value),
                "moment2": jnp.zeros_like(value),
                "beta1_pow": jnp.ones((), jnp.float32),
                "beta2_pow": jnp.ones((), jnp.float32)}

    def _hyper(self):
        return {"beta1": self._beta1, "beta2": self._beta2,
                "epsilon": self._epsilon, "coeff": self._coeff}

    def _rule(self, param, grad, state, lr, *, beta1, beta2, epsilon, coeff):
        g = grad.astype(jnp.float32)
        p32 = param.astype(jnp.float32)
        m = beta1 * state["moment1"] + (1 - beta1) * g
        v = beta2 * state["moment2"] + (1 - beta2) * g * g
        b1p = state["beta1_pow"] * beta1
        b2p = state["beta2_pow"] * beta2
        # scalar-folded bias correction (see Adam._rule): one
        # param-sized divide + sqrt instead of three divides
        corr2 = jnp.sqrt(1.0 - b2p)
        r = (corr2 / (1.0 - b1p)) * (
            m / (jnp.sqrt(v) + epsilon * corr2)) + coeff * p32
        p_norm = jnp.sqrt(jnp.sum(p32 * p32))
        r_norm = jnp.sqrt(jnp.sum(r * r))
        trust = jnp.where((p_norm > 0) & (r_norm > 0), p_norm / r_norm, 1.0)
        new_p = p32 - lr * trust * r
        return new_p.astype(param.dtype), {
            "moment1": m, "moment2": v, "beta1_pow": b1p, "beta2_pow": b2p}


# Wrappers live in incubate (their home in the reference API tree); the
# reference also exposes ExponentialMovingAverage from fluid.optimizer,
# so re-export all three here.  Import is at module tail so the circular
# incubate->optimizer import resolves against the finished class defs.
from ..incubate.optimizer import (  # noqa: E402,F401
    ExponentialMovingAverage, LookAhead, ModelAverage,
)
