"""AMP: autocast + GradScaler.

Ref parity: python/paddle/amp/auto_cast.py + grad_scaler.py, C++ lists at
paddle/fluid/imperative/amp_auto_cast.h. TPU-native default low-precision
dtype is bfloat16 (no loss scaling needed); float16 kept for compat with
scripts that ask for it, with the dynamic loss-scaling state machine of
check_finite_and_unscale/update_loss_scaling implemented on jnp.
"""

from __future__ import annotations

import contextlib

import jax
import jax.numpy as jnp

from ..core import config
from ..core.tensor import Tensor


@contextlib.contextmanager
def auto_cast(enable=True, custom_white_list=None, custom_black_list=None,
              level="O1", dtype="bfloat16"):
    st = config._state
    prev = (st.amp_level, st.amp_dtype, st.custom_white_list,
            st.custom_black_list)
    if enable:
        st.amp_level = level
        st.amp_dtype = dtype
        st.custom_white_list = custom_white_list
        st.custom_black_list = custom_black_list
    try:
        yield
    finally:
        (st.amp_level, st.amp_dtype, st.custom_white_list,
         st.custom_black_list) = prev


amp_guard = auto_cast  # legacy fluid name


def all_finite(tree):
    """Single finiteness bit over every leaf of a pytree, fused into ONE
    reduction (in-graph analogue of check_finite_and_unscale_op's
    FoundInfinite output; no per-leaf host sync). jit-safe: returns a
    traced scalar bool. Shared by GradScaler and the engine's step-level
    anomaly guard."""
    leaves = jax.tree.leaves(tree)
    if not leaves:
        return jnp.asarray(True)
    return jnp.all(jnp.stack([jnp.all(jnp.isfinite(g)) for g in leaves]))


def decorate(models, optimizers=None, level="O1", dtype="bfloat16",
             master_weight=None, save_dtype=None):
    """O2: cast model params to the low-precision dtype (keeping fp32
    master weights inside the optimizer state, which stores f32 moments)."""
    if level == "O2":
        for m in models if isinstance(models, (list, tuple)) else [models]:
            m.to(dtype=dtype)
    if optimizers is None:
        return models
    return models, optimizers


class GradScaler:
    """Dynamic loss scaling (ref: python/paddle/amp/grad_scaler.py over
    check_finite_and_unscale_op + update_loss_scaling_op). With bfloat16
    scaling is a no-op (enable=False default path on TPU)."""

    def __init__(self, enable=True, init_loss_scaling=2.0 ** 15,
                 incr_ratio=2.0, decr_ratio=0.5, incr_every_n_steps=1000,
                 decr_every_n_nan_or_inf=1, use_dynamic_loss_scaling=True):
        self._enable = enable
        self._scale = float(init_loss_scaling)
        self._incr_ratio = incr_ratio
        self._decr_ratio = decr_ratio
        self._incr_every_n_steps = incr_every_n_steps
        self._decr_every_n = decr_every_n_nan_or_inf
        self._dynamic = use_dynamic_loss_scaling
        self._good_steps = 0
        self._bad_steps = 0
        self._found_inf = False
        # optimizers already unscaled since the last update() — step() must
        # not divide by the scale a second time (reference OptimizerState)
        self._unscaled = set()

    def scale(self, loss):
        if not self._enable:
            return loss
        return loss * self._scale

    @staticmethod
    @jax.jit
    def _unscale_check(grads, inv_scale):
        """One fused program: grads/scale + a single finite-ness bit
        (in-graph analogue of check_finite_and_unscale_op; avoids one
        host sync per parameter)."""
        # keep each grad's own dtype (fp16 stays fp16; no f32 promotion)
        new = jax.tree.map(lambda g: (g * inv_scale).astype(g.dtype), grads)
        return new, all_finite(new)

    def unscale_(self, optimizer):
        if not self._enable:
            return
        if id(optimizer) in self._unscaled:
            # Explicit double-unscale between updates is user error (the
            # reference/AmpScaler and torch both refuse); silently
            # no-opping would leave grads scaled on the NEXT iteration
            # when the user steps the optimizer directly.
            raise RuntimeError(
                "unscale_() has already been called on this optimizer "
                "since the last update()")
        from ..core.selected_rows import SelectedRows

        grads = []
        for p in optimizer._parameter_list or []:
            if p is None or p._grad is None:
                continue
            # sparse grads unscale their values array; rows are untouched
            grads.append(p._grad.values
                         if isinstance(p._grad, SelectedRows) else p._grad)
        if grads:
            inv = jnp.asarray(1.0 / self._scale, jnp.float32)
            new_grads, all_finite = self._unscale_check(grads, inv)
            i = 0
            for p in optimizer._parameter_list or []:
                if p is None or p._grad is None:
                    continue
                if isinstance(p._grad, SelectedRows):
                    p._grad = SelectedRows(p._grad.rows, new_grads[i],
                                           p._grad.height)
                else:
                    p._grad = new_grads[i]
                i += 1
            self._found_inf = not bool(all_finite)
        self._unscaled.add(id(optimizer))

    def step(self, optimizer):
        if not self._enable:
            optimizer.step()
            return
        if id(optimizer) not in self._unscaled:
            self.unscale_(optimizer)
        if not self._found_inf:
            optimizer.step()
        self.update()

    def minimize(self, optimizer, scaled_loss):
        self.step(optimizer)

    def update(self):
        self._unscaled.clear()
        if not (self._enable and self._dynamic):
            self._found_inf = False
            return
        if self._found_inf:
            self._bad_steps += 1
            self._good_steps = 0
            if self._bad_steps >= self._decr_every_n:
                self._scale = max(self._scale * self._decr_ratio, 1.0)
                self._bad_steps = 0
        else:
            self._good_steps += 1
            self._bad_steps = 0
            if self._good_steps >= self._incr_every_n_steps:
                self._scale *= self._incr_ratio
                self._good_steps = 0
        self._found_inf = False

    def is_enable(self):
        return self._enable

    def is_use_dynamic_loss_scaling(self):
        return self._dynamic

    def get_loss_scaling(self):
        return Tensor(jnp.asarray(self._scale))

    def set_init_loss_scaling(self, v):
        self._scale = float(v)

    def state_dict(self):
        return {"scale": self._scale, "incr_ratio": self._incr_ratio,
                "decr_ratio": self._decr_ratio,
                "good_steps": self._good_steps,
                "bad_steps": self._bad_steps}

    def load_state_dict(self, sd):
        self._scale = sd["scale"]
        self._good_steps = sd.get("good_steps", 0)
        self._bad_steps = sd.get("bad_steps", 0)
