"""paddle_tpu.nn (ref: python/paddle/nn/__init__.py)."""

from . import functional  # noqa: F401
from . import initializer  # noqa: F401
from .layer.layers import Layer  # noqa: F401
from .layer.container import (  # noqa: F401
    LayerDict, LayerList, ParameterList, Sequential,
)
from .layer.common import (  # noqa: F401
    AlphaDropout, Bilinear, CosineSimilarity, Dropout, Dropout2D,
    Dropout3D, Embedding, Flatten, Identity, Linear, Pad1D, Pad2D, Pad3D,
    PairwiseDistance, PixelShuffle, Unfold, Upsample,
    UpsamplingBilinear2D, UpsamplingNearest2D,
)
from .layer.conv import (  # noqa: F401
    Conv1D, Conv1DTranspose, Conv2D, Conv2DTranspose, Conv3D,
    Conv3DTranspose,
)
from .layer.norm import (  # noqa: F401
    BatchNorm, BatchNorm1D, BatchNorm2D, BatchNorm3D, GroupNorm,
    InstanceNorm1D, InstanceNorm2D, InstanceNorm3D, LayerNorm,
    LocalResponseNorm, RMSNorm, SpectralNorm, SyncBatchNorm,
)
from .layer.pooling import (  # noqa: F401
    AdaptiveAvgPool1D, AdaptiveAvgPool2D, AdaptiveAvgPool3D,
    AdaptiveMaxPool1D, AdaptiveMaxPool2D, AdaptiveMaxPool3D, AvgPool1D,
    AvgPool2D, AvgPool3D, MaxPool1D, MaxPool2D, MaxPool3D,
)
from .layer.activation import (  # noqa: F401
    CELU, ELU, GELU, Hardshrink, Hardsigmoid, Hardswish, Hardtanh, LeakyReLU,
    LogSigmoid, LogSoftmax, Maxout, Mish, PReLU, ReLU, ReLU6, SELU, Sigmoid,
    Silu, Softmax, Softplus, Softshrink, Softsign, Swish, Tanh, Tanhshrink,
    ThresholdedReLU,
)
from .layer.loss import (  # noqa: F401
    BCELoss, BCEWithLogitsLoss, CrossEntropyLoss, CTCLoss, HSigmoidLoss,
    KLDivLoss, L1Loss, MarginRankingLoss, MSELoss, NLLLoss, SmoothL1Loss,
)
from . import utils  # noqa: F401
from . import quant  # noqa: F401
from .layer import loss  # noqa: F401
from .utils import spectral_norm  # noqa: F401
from .layer.rnn import (  # noqa: F401
    GRU, GRUCell, LSTM, LSTMCell, RNN, BiRNN, RNNCellBase, SimpleRNN,
    SimpleRNNCell,
)
from .layer.decode import BeamSearchDecoder, dynamic_decode  # noqa: F401
from .layer.transformer import (  # noqa: F401
    MultiHeadAttention, Transformer, TransformerDecoder,
    TransformerDecoderLayer, TransformerEncoder, TransformerEncoderLayer,
)
from ..clip import ClipGradByGlobalNorm, ClipGradByNorm, ClipGradByValue  # noqa: F401
