"""paddle_tpu.nn (ref: python/paddle/nn/__init__.py)."""

from . import functional  # noqa: F401
from . import initializer  # noqa: F401
from .layer.layers import Layer  # noqa: F401
from .layer.container import (  # noqa: F401
    LayerDict, LayerList, ParameterList, Sequential,
)
from .layer.common import (  # noqa: F401
    Bilinear, CosineSimilarity, Dropout, Dropout2D, Embedding, Flatten,
    Identity, Linear, Pad1D, Pad2D, Pad3D, PixelShuffle, Unfold, Upsample,
    UpsamplingBilinear2D, UpsamplingNearest2D,
)
from .layer.conv import Conv1D, Conv2D, Conv2DTranspose, Conv3D  # noqa: F401
from .layer.norm import (  # noqa: F401
    BatchNorm, BatchNorm1D, BatchNorm2D, BatchNorm3D, GroupNorm,
    InstanceNorm1D, InstanceNorm2D, InstanceNorm3D, LayerNorm,
    LocalResponseNorm, RMSNorm, SpectralNorm, SyncBatchNorm,
)
from .layer.pooling import (  # noqa: F401
    AdaptiveAvgPool2D, AdaptiveMaxPool2D, AvgPool1D, AvgPool2D, MaxPool1D,
    MaxPool2D,
)
from .layer.activation import (  # noqa: F401
    CELU, ELU, GELU, Hardshrink, Hardsigmoid, Hardswish, Hardtanh, LeakyReLU,
    LogSigmoid, LogSoftmax, Mish, PReLU, ReLU, ReLU6, SELU, Sigmoid, Silu,
    Softmax, Softplus, Softshrink, Softsign, Swish, Tanh, Tanhshrink,
)
from .layer.loss import (  # noqa: F401
    BCELoss, BCEWithLogitsLoss, CrossEntropyLoss, KLDivLoss, L1Loss,
    MarginRankingLoss, MSELoss, NLLLoss, SmoothL1Loss,
)
from .layer.rnn import (  # noqa: F401
    GRU, GRUCell, LSTM, LSTMCell, RNN, BiRNN, RNNCellBase, SimpleRNN,
    SimpleRNNCell,
)
from .layer.decode import BeamSearchDecoder, dynamic_decode  # noqa: F401
from .layer.transformer import (  # noqa: F401
    MultiHeadAttention, Transformer, TransformerDecoder,
    TransformerDecoderLayer, TransformerEncoder, TransformerEncoderLayer,
)
from ..clip import ClipGradByGlobalNorm, ClipGradByNorm, ClipGradByValue  # noqa: F401
