"""nn.functional (ref: python/paddle/nn/functional/)."""

from __future__ import annotations

from ...core.dispatch import apply
from ...core.tensor import Tensor
from ...framework import random as _random

# -- activations ------------------------------------------------------------


def relu(x, name=None):
    return apply("relu", x)


def relu6(x, name=None):
    return apply("relu6", x)


def gelu(x, approximate=False, name=None):
    return apply("gelu", x, approximate=approximate)


def sigmoid(x, name=None):
    return apply("sigmoid", x)


def log_sigmoid(x, name=None):
    return apply("logsigmoid", x)


def tanh(x, name=None):
    return apply("tanh", x)


def silu(x, name=None):
    return apply("silu", x)


def swish(x, name=None):
    return apply("swish", x)


def mish(x, name=None):
    return apply("mish", x)


def leaky_relu(x, negative_slope=0.01, name=None):
    return apply("leaky_relu", x, negative_slope=negative_slope)


def elu(x, alpha=1.0, name=None):
    return apply("elu", x, alpha=alpha)


def celu(x, alpha=1.0, name=None):
    return apply("celu", x, alpha=alpha)


def selu(x, scale=1.0507009873554805, alpha=1.6732632423543772, name=None):
    return apply("selu", x, scale=scale, alpha=alpha)


def prelu(x, weight, name=None):
    return apply("prelu", x, weight)


def hardtanh(x, min=-1.0, max=1.0, name=None):
    return apply("hardtanh", x, min=min, max=max)


def hardsigmoid(x, slope=1.0 / 6.0, offset=0.5, name=None):
    return apply("hardsigmoid", x, slope=slope, offset=offset)


def hardswish(x, name=None):
    return apply("hardswish", x)


def hardshrink(x, threshold=0.5, name=None):
    return apply("hardshrink", x, threshold=threshold)


def softshrink(x, threshold=0.5, name=None):
    return apply("softshrink", x, threshold=threshold)


def softplus(x, beta=1.0, threshold=20.0, name=None):
    return apply("softplus", x, beta=beta, threshold=threshold)


def softsign(x, name=None):
    return apply("softsign", x)


def tanhshrink(x, name=None):
    return apply("tanh_shrink", x)


def softmax(x, axis=-1, dtype=None, name=None):
    out = apply("softmax", x, axis=axis)
    return out.astype(dtype) if dtype is not None else out


def log_softmax(x, axis=-1, dtype=None, name=None):
    out = apply("log_softmax", x, axis=axis)
    return out.astype(dtype) if dtype is not None else out


def gumbel_softmax(x, temperature=1.0, hard=False, axis=-1, name=None):
    import jax
    import jax.numpy as jnp

    u = jax.random.uniform(_random.next_key(), tuple(x.shape))
    g = Tensor(-jnp.log(-jnp.log(jnp.maximum(1e-20, u))))
    y = softmax((x + g) / temperature, axis=axis)
    if hard:
        # straight-through: one_hot(argmax) + y - stop_grad(y)
        idx = apply("arg_max", y, axis=axis, keepdim=False)
        oh = apply("one_hot", idx, num_classes=y.shape[axis])
        if axis not in (-1, y.ndim - 1):
            oh = oh.moveaxis(-1, axis)
        return oh + y - y.detach()
    return y


# -- linear / conv ----------------------------------------------------------


def linear(x, weight, bias=None, name=None):
    # FLAGS_lowp_matmul: eligible matmuls route through the int8/fp8
    # scaled-matmul family (ops/lowp.py); returns None when off or the
    # operands aren't routable — 'off' is bitwise-unchanged
    from ...ops import lowp as _lowp

    out = _lowp.maybe_linear(x, weight)
    if out is None:
        out = apply("matmul_v2", x, weight)
    if bias is not None:
        out = out + bias
    return out


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCHW", name=None):
    out = apply("conv2d", x, weight, stride=stride, padding=padding,
                dilation=dilation, groups=groups, data_format=data_format)
    if bias is not None:
        shape = [1, -1, 1, 1] if data_format == "NCHW" else [1, 1, 1, -1]
        out = out + bias.reshape(shape)
    return out


def conv1d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCL", name=None):
    out = apply("conv1d", x, weight, stride=stride, padding=padding,
                dilation=dilation, groups=groups, data_format=data_format)
    if bias is not None:
        out = out + bias.reshape([1, -1, 1])
    return out


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCDHW", name=None):
    out = apply("conv3d", x, weight, stride=stride, padding=padding,
                dilation=dilation, groups=groups, data_format=data_format)
    if bias is not None:
        out = out + bias.reshape([1, -1, 1, 1, 1])
    return out


def conv2d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, dilation=1, groups=1,
                     data_format="NCHW", name=None):
    out = apply("conv2d_transpose", x, weight, stride=stride, padding=padding,
                output_padding=output_padding, dilation=dilation,
                groups=groups, data_format=data_format)
    if bias is not None:
        shape = [1, -1, 1, 1] if data_format == "NCHW" else [1, 1, 1, -1]
        out = out + bias.reshape(shape)
    return out


def conv1d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, dilation=1, groups=1,
                     data_format="NCL", name=None):
    def _one(v):
        return v if isinstance(v, int) else v[0]

    if data_format == "NLC":
        x = x.transpose([0, 2, 1])
    if isinstance(padding, str):
        pad = padding
    elif (isinstance(padding, (list, tuple)) and len(padding) == 2
            and all(isinstance(p, int) for p in padding)):
        # [pad_left, pad_right] asymmetric form -> explicit pairs
        pad = [(0, 0), (padding[0], padding[1])]
    else:
        pad = (0, _one(padding))
    out = apply("conv2d_transpose", x.unsqueeze(2),
                weight.unsqueeze(2) if hasattr(weight, "unsqueeze")
                else weight[:, :, None, :],
                stride=(1, _one(stride)), padding=pad,
                output_padding=(0, _one(output_padding)),
                dilation=(1, _one(dilation)), groups=groups)
    out = out.squeeze(2)
    if bias is not None:
        out = out + bias.reshape([1, -1, 1])
    if data_format == "NLC":
        out = out.transpose([0, 2, 1])
    return out


def conv3d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, dilation=1, groups=1,
                     data_format="NCDHW", name=None):
    out = apply("conv3d_transpose", x, weight, stride=stride,
                padding=padding, output_padding=output_padding,
                dilation=dilation, groups=groups, data_format=data_format)
    if bias is not None:
        shape = [1, -1, 1, 1, 1] if data_format == "NCDHW" \
            else [1, 1, 1, 1, -1]
        out = out + bias.reshape(shape)
    return out


# -- pooling ----------------------------------------------------------------


def max_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               return_mask=False, data_format="NCHW", name=None):
    if return_mask:
        # ref pool_with_index_op.cc: mask = argmax flat index into H*W
        if ceil_mode or data_format != "NCHW" or isinstance(padding, str):
            raise NotImplementedError(
                "max_pool2d(return_mask=True) supports NCHW with "
                "numeric padding and no ceil_mode (reference "
                "pool_with_index constraint)")
        return apply("max_pool2d_with_index", x, ksize=kernel_size,
                     stride=stride, padding=padding)
    return apply("pool2d", x, ksize=kernel_size, stride=stride,
                 padding=padding, ceil_mode=ceil_mode, pooling_type="max",
                 data_format=data_format)


def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCHW",
               name=None):
    return apply("pool2d", x, ksize=kernel_size, stride=stride,
                 padding=padding, ceil_mode=ceil_mode, pooling_type="avg",
                 exclusive=exclusive, data_format=data_format)


def adaptive_avg_pool2d(x, output_size, data_format="NCHW", name=None):
    return apply("pool2d", x, ksize=output_size, adaptive=True,
                 pooling_type="avg", data_format=data_format)


def adaptive_max_pool2d(x, output_size, return_mask=False, name=None):
    if return_mask:
        return apply("max_pool2d_with_index", x, ksize=output_size,
                     adaptive=True)
    return apply("pool2d", x, ksize=output_size, adaptive=True,
                 pooling_type="max")


def max_pool1d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               return_mask=False, name=None):
    x4 = x.unsqueeze(2)
    k = kernel_size if isinstance(kernel_size, int) else kernel_size[0]
    s = stride if stride is None or isinstance(stride, int) else stride[0]
    # "SAME"/"VALID" pass through whole; numeric padding pads W only
    pad = padding if isinstance(padding, str) else \
        (0, padding if isinstance(padding, int) else padding[0])
    if return_mask:
        if ceil_mode or isinstance(padding, str):
            raise NotImplementedError(
                "max_pool1d(return_mask=True) needs numeric padding "
                "and no ceil_mode (reference pool_with_index "
                "constraint)")
        # on the (1, L) map abs_y == 0, so the flat index IS the
        # position along L
        out, idx = apply("max_pool2d_with_index", x4, ksize=(1, k),
                         stride=(1, s if s is not None else k),
                         padding=pad)
        return out.squeeze(2), idx.squeeze(2)
    out = apply("pool2d", x4, ksize=(1, k),
                stride=(1, s if s is not None else k), padding=pad,
                ceil_mode=ceil_mode, pooling_type="max")
    return out.squeeze(2)


def avg_pool1d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, name=None):
    x4 = x.unsqueeze(2)
    k = kernel_size if isinstance(kernel_size, int) else kernel_size[0]
    s = stride if stride is None or isinstance(stride, int) else stride[0]
    pad = padding if isinstance(padding, str) else \
        (0, padding if isinstance(padding, int) else padding[0])
    out = apply("pool2d", x4, ksize=(1, k),
                stride=(1, s if s is not None else k), padding=pad,
                ceil_mode=ceil_mode, pooling_type="avg", exclusive=exclusive)
    return out.squeeze(2)


def max_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               return_mask=False, data_format="NCDHW", name=None):
    if return_mask:
        if ceil_mode or data_format != "NCDHW" or isinstance(padding, str):
            raise NotImplementedError(
                "max_pool3d(return_mask=True) supports NCDHW with "
                "numeric padding and no ceil_mode (reference "
                "pool_with_index constraint)")
        return apply("max_pool3d_with_index", x, ksize=kernel_size,
                     stride=stride, padding=padding)
    return apply("pool3d", x, ksize=kernel_size, stride=stride,
                 padding=padding, ceil_mode=ceil_mode, pooling_type="max",
                 data_format=data_format)


def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCDHW",
               name=None):
    return apply("pool3d", x, ksize=kernel_size, stride=stride,
                 padding=padding, ceil_mode=ceil_mode, pooling_type="avg",
                 exclusive=exclusive, data_format=data_format)


def adaptive_avg_pool1d(x, output_size, name=None):
    out = apply("pool2d", x.unsqueeze(2), ksize=(1, output_size),
                adaptive=True, pooling_type="avg")
    return out.squeeze(2)


def adaptive_max_pool1d(x, output_size, return_mask=False, name=None):
    if return_mask:
        out, idx = apply("max_pool2d_with_index", x.unsqueeze(2),
                         ksize=(1, output_size), adaptive=True)
        return out.squeeze(2), idx.squeeze(2)
    return apply("pool2d", x.unsqueeze(2), ksize=(1, output_size),
                 adaptive=True, pooling_type="max").squeeze(2)


def adaptive_avg_pool3d(x, output_size, data_format="NCDHW", name=None):
    return apply("pool3d", x, ksize=output_size, adaptive=True,
                 pooling_type="avg", data_format=data_format)


def adaptive_max_pool3d(x, output_size, return_mask=False, name=None):
    if return_mask:
        return apply("max_pool3d_with_index", x, ksize=output_size,
                     adaptive=True)
    return apply("pool3d", x, ksize=output_size, adaptive=True,
                 pooling_type="max")


def maxout(x, groups, axis=1, name=None):
    return apply("maxout", x, groups=groups, axis=axis)


def thresholded_relu(x, threshold=1.0, name=None):
    return apply("thresholded_relu", x, threshold=threshold)


def pairwise_distance(x, y, p=2.0, epsilon=1e-6, keepdim=False, name=None):
    """ref dist_op.cc usage in PairwiseDistance: p-norm of x - y + eps
    along the last axis (eps added to the SIGNED difference)."""
    d = (x - y + epsilon).abs()
    if p == float("inf"):
        out = d.max(axis=-1, keepdim=keepdim)
    elif p == 0:
        out = (d != 0).astype(d.dtype).sum(axis=-1, keepdim=keepdim)
    else:
        out = (d ** p).sum(axis=-1, keepdim=keepdim) ** (1.0 / p)
    return out


def hsigmoid_loss(input, label, num_classes, weight, bias=None,
                  path_table=None, path_code=None, is_sparse=False,
                  name=None):
    # returns per-sample [N, 1] losses unreduced (reference semantics)
    return apply("hierarchical_sigmoid", input, weight, label, bias,
                 path_table, path_code, num_classes=num_classes)


# -- normalisation ----------------------------------------------------------


def layer_norm(x, normalized_shape, weight=None, bias=None, epsilon=1e-5,
               name=None):
    if isinstance(normalized_shape, int):
        normalized_shape = [normalized_shape]
    begin = x.ndim - len(normalized_shape)
    return apply("layer_norm", x, weight, bias, epsilon=epsilon,
                 begin_norm_axis=begin) if weight is not None else \
        apply("layer_norm", x, epsilon=epsilon, begin_norm_axis=begin)


def batch_norm(x, running_mean, running_var, weight, bias, training=False,
               momentum=0.9, epsilon=1e-5, data_format="NCHW",
               use_global_stats=None, name=None):
    if use_global_stats is None:
        use_global_stats = not training
    # use_global_stats=True always normalizes with the running stats, even
    # in training (and then skips the running-stat update) — reference
    # batch_norm_op.cc semantics (ADVICE r1 fix).
    y, new_mean, new_var = apply(
        "batch_norm", x, weight, bias, running_mean, running_var,
        momentum=momentum, epsilon=epsilon, is_test=not training,
        data_format=data_format, use_global_stats=use_global_stats)
    if training and not use_global_stats:
        running_mean.set_value(new_mean)
        running_var.set_value(new_var)
    return y


def fused_bn_act(x, running_mean, running_var, weight, bias,
                 residual=None, act="relu", training=False, momentum=0.9,
                 epsilon=1e-5, data_format="NCHW", use_global_stats=None,
                 name=None):
    """act(batch_norm(x) [+ residual]) through the minimal-residual
    custom-VJP op (ref fused_bn_activation_op.cu): backward recomputes
    the normalized activation instead of re-reading saved y/masks."""
    if use_global_stats is None:
        use_global_stats = not training
    y, new_mean, new_var = apply(
        "fused_bn_act", x, weight, bias, running_mean, running_var,
        residual, momentum=momentum, epsilon=epsilon, act=act,
        is_test=not training, data_format=data_format,
        use_global_stats=use_global_stats)
    if training and not use_global_stats:
        running_mean.set_value(new_mean)
        running_var.set_value(new_var)
    return y


def fused_conv2d_bn_act(x, weight, running_mean, running_var, bn_weight,
                        bn_bias, residual=None, act="relu", training=False,
                        momentum=0.9, epsilon=1e-5, stride=1, padding=0,
                        dilation=1, groups=1, data_format="NCHW",
                        use_global_stats=None, name=None):
    """act(batch_norm(conv2d(x, weight)) [+ residual]) through the
    fused-epilogue conv op (ref conv_bn_fuse_pass.cc): eval folds BN
    into the conv epilogue, training emits the BN moments from the conv
    accumulator.  Same running-stat update contract as fused_bn_act."""
    if use_global_stats is None:
        use_global_stats = not training
    y, new_mean, new_var = apply(
        "fused_conv2d_bn_act", x, weight, bn_weight, bn_bias,
        running_mean, running_var, residual, stride=stride,
        padding=padding, dilation=dilation, groups=groups,
        momentum=momentum, epsilon=epsilon, act=act,
        is_test=not training, data_format=data_format,
        use_global_stats=use_global_stats)
    if training and not use_global_stats:
        running_mean.set_value(new_mean)
        running_var.set_value(new_var)
    return y


def instance_norm(x, running_mean=None, running_var=None, weight=None,
                  bias=None, use_input_stats=True, momentum=0.9, eps=1e-5,
                  data_format="NCHW", name=None):
    if weight is not None:
        return apply("instance_norm", x, weight, bias, epsilon=eps)
    return apply("instance_norm", x, epsilon=eps)


def group_norm(x, num_groups, epsilon=1e-5, weight=None, bias=None,
               data_format="NCHW", name=None):
    if weight is not None:
        return apply("group_norm", x, weight, bias, epsilon=epsilon,
                     groups=num_groups, data_format=data_format)
    return apply("group_norm", x, epsilon=epsilon, groups=num_groups,
                 data_format=data_format)


def normalize(x, p=2, axis=1, epsilon=1e-12, name=None):
    import jax.numpy as jnp

    if p == 2:
        return apply("l2_normalize", x, axis=axis, epsilon=epsilon)
    norm = apply("p_norm", x, porder=float(p), axis=axis, keepdim=True)
    return x / norm.clip(min=epsilon)


def local_response_norm(x, size, alpha=1e-4, beta=0.75, k=1.0, name=None):
    return apply("local_response_norm", x, size=size, alpha=alpha,
                 beta=beta, k=k)


# -- dropout ----------------------------------------------------------------


def dropout(x, p=0.5, axis=None, training=True, mode="upscale_in_train",
            name=None):
    if not training or p == 0.0:
        if mode == "downscale_in_infer" and not training:
            return x * (1.0 - p)
        return x
    key = Tensor(_random.next_key())
    return apply("dropout", x, key, p=float(p), training=training, mode=mode)


def dropout2d(x, p=0.5, training=True, data_format="NCHW", name=None):
    if not training or p == 0.0:
        return x
    import jax

    key = _random.next_key()
    shape = (x.shape[0], x.shape[1], 1, 1) if data_format == "NCHW" else \
        (x.shape[0], 1, 1, x.shape[3])
    mask = jax.random.bernoulli(key, 1.0 - p, shape)
    return x * Tensor(mask.astype(x._value.dtype)) / (1.0 - p)


def dropout3d(x, p=0.5, training=True, data_format="NCDHW", name=None):
    """Whole-channel dropout over 5-D input (ref nn/functional/common.py
    dropout3d)."""
    if not training or p == 0.0:
        return x
    import jax

    key = _random.next_key()
    shape = (x.shape[0], x.shape[1], 1, 1, 1) if data_format == "NCDHW" \
        else (x.shape[0], 1, 1, 1, x.shape[4])
    mask = jax.random.bernoulli(key, 1.0 - p, shape)
    return x * Tensor(mask.astype(x._value.dtype)) / (1.0 - p)


def alpha_dropout(x, p=0.5, training=True, name=None):
    """SELU-preserving dropout (ref nn/functional/common.py
    alpha_dropout): dropped units take alpha', then an affine correction
    restores mean/variance."""
    if not training or p == 0.0:
        return x
    import jax

    alpha = 1.6732632423543772
    scale = 1.0507009873554805
    alpha_p = -alpha * scale
    a = ((1 - p) * (1 + p * alpha_p ** 2)) ** -0.5
    b = -a * alpha_p * p
    key = _random.next_key()
    keep = jax.random.bernoulli(key, 1.0 - p, x.shape)
    keep = Tensor(keep.astype(x._value.dtype))
    return (x * keep + alpha_p * (1 - keep)) * a + b


# -- embedding --------------------------------------------------------------


def embedding(x, weight, padding_idx=None, sparse=False, name=None):
    if sparse:
        return _sparse_embedding(x, weight, padding_idx)
    return apply("lookup_table_v2", x, weight,
                 padding_idx=-1 if padding_idx is None else padding_idx)


def _sparse_embedding(x, weight, padding_idx=None):
    """sparse=True lookup: the weight gradient is a SelectedRows
    {rows=looked-up ids, values=output cotangents} instead of a dense
    vocab-sized scatter (ref framework/selected_rows.h +
    lookup_table_v2_op.cc is_sparse path). TPU-native: static shapes
    (k = number of lookups), optimizers apply it with scatter-add /
    row-wise moment updates."""
    import numpy as _np

    import jax as _jax
    import jax.numpy as jnp

    from ...core import dispatch as _dispatch
    from ...core.autograd import Node
    from ...core.op_registry import lookup as _op_lookup
    from ...core.selected_rows import SelectedRows
    from ...core.tensor import Tensor as _T
    from ...core import config as _config

    pad = -1 if padding_idx is None else int(padding_idx)
    if _dispatch._capture_fn is not None:
        # static-graph capture replays ops from the registry; SelectedRows
        # has no static representation, so is_sparse degrades to the dense
        # captured lookup (the reference's static sparse path is PS-mode
        # only — distributed_lookup_table_op)
        return apply("lookup_table_v2", x, weight, padding_idx=pad)

    ids_t = x if isinstance(x, _T) else _T(x)
    # same AMP autocast rewrite the dispatch funnel applies to the dense
    # path, so sparse=True does not silently change dtype behaviour
    ids, w = _dispatch._amp_rewrite(
        "lookup_table_v2", [jnp.asarray(ids_t._value).astype(jnp.int32),
                            weight._value])

    # same kernel as the dense path — only the backward differs
    out = _op_lookup("lookup_table_v2").fn(ids, w, padding_idx=pad)
    _dispatch._maybe_check_nan_inf("lookup_table_v2", out)

    requires_grad = (_config.is_grad_enabled() and _config.is_tape_enabled()
                     and not weight.stop_gradient)
    result = _T(out, stop_gradient=not requires_grad)
    if not requires_grad:
        return result

    height = w.shape[0]

    def vjp_fn(dy):
        rows = ids.reshape(-1)
        values = jnp.asarray(dy).reshape(-1, w.shape[1])
        if pad >= 0:
            values = values * (rows != pad)[:, None].astype(values.dtype)
        ids_zero = _np.zeros(ids.shape, _jax.dtypes.float0)
        return (ids_zero, SelectedRows(rows, values, height))

    node = Node(vjp_fn, (ids_t, weight), [(out.shape, out.dtype)],
                "lookup_table_v2_sparse", attrs={"padding_idx": pad})
    result._tape = (node, 0)
    return result


def one_hot(x, num_classes, name=None):
    return apply("one_hot", x, num_classes=num_classes)


# -- losses -----------------------------------------------------------------


def cross_entropy(input, label, weight=None, ignore_index=-100,
                  reduction="mean", soft_label=False, axis=-1,
                  use_softmax=True, name=None):
    if weight is not None:
        weight = weight._value if hasattr(weight, "_value") else weight
    return apply("cross_entropy", input, label, soft_label=soft_label,
                 axis=axis, ignore_index=ignore_index, reduction=reduction,
                 use_softmax=use_softmax, weight=weight)


def fused_linear_cross_entropy(hidden, weight, label, ignore_index=-100,
                               reduction="mean", chunk_v=0, name=None):
    """cross_entropy(hidden @ weight.T, label) as ONE streaming op that
    never materializes the [N, V] logits (ops/fused_loss.py): vocab
    chunks of the tied decoder table are scored against an online
    logsumexp, and the backward rebuilds softmax-minus-onehot tiles
    in-register. Numerically equal to the unfused pair at fp32."""
    return apply("fused_linear_cross_entropy", hidden, weight, label,
                 ignore_index=ignore_index, reduction=reduction,
                 chunk_v=chunk_v)


def softmax_with_cross_entropy(logits, label, soft_label=False,
                               ignore_index=-100, numeric_stable_mode=True,
                               return_softmax=False, axis=-1):
    loss, sm = apply("softmax_with_cross_entropy", logits, label,
                     soft_label=soft_label, axis=axis,
                     ignore_index=ignore_index)
    if return_softmax:
        return loss, sm
    return loss


def binary_cross_entropy(input, label, weight=None, reduction="mean",
                         name=None):
    loss = apply("bce_loss", input, label)
    if weight is not None:
        loss = loss * weight
    if reduction == "none":
        return loss
    return loss.sum() if reduction == "sum" else loss.mean()


def binary_cross_entropy_with_logits(logit, label, weight=None,
                                     reduction="mean", pos_weight=None,
                                     name=None):
    loss = apply("sigmoid_cross_entropy_with_logits", logit, label)
    if pos_weight is not None:
        log_weight = (pos_weight - 1.0) * label + 1.0
        loss = loss * log_weight
    if weight is not None:
        loss = loss * weight
    if reduction == "none":
        return loss
    return loss.sum() if reduction == "sum" else loss.mean()


def mse_loss(input, label, reduction="mean", name=None):
    return apply("mse_loss", input, label, reduction=reduction)


def square_error_cost(input, label):
    """Elementwise (input - label)^2, unreduced
    (ref python/paddle/nn/functional/loss.py square_error_cost)."""
    return apply("mse_loss", input, label, reduction="none")


def l1_loss(input, label, reduction="mean", name=None):
    return apply("l1_loss", input, label, reduction=reduction)


def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0,
             reduction="mean", norm_by_times=False):
    """ref python/paddle/nn/functional/loss.py ctc_loss (warpctc_op.cc);
    log_probs may be [T, B, C] (paddle layout) — transposed internally to
    the batch-major kernel layout."""
    lp = log_probs
    if lp.ndim == 3:
        lp = lp.transpose([1, 0, 2])  # [B, T, C]
    loss = apply("warpctc", lp, labels, input_lengths, label_lengths,
                 blank=blank, norm_by_times=norm_by_times)
    if reduction == "mean":
        return (loss / label_lengths.astype(loss.dtype)).mean()
    if reduction == "sum":
        return loss.sum()
    return loss


def grid_sample(x, grid, mode="bilinear", padding_mode="zeros",
                align_corners=True, name=None):
    return apply("grid_sampler", x, grid, mode=mode,
                 padding_mode=padding_mode, align_corners=align_corners)


def affine_grid(theta, out_shape, align_corners=True, name=None):
    return apply("affine_grid", theta, out_shape=tuple(out_shape),
                 align_corners=align_corners)


def npair_loss(anchor, positive, labels, l2_reg=0.002):
    return apply("npair_loss", anchor, positive, labels, l2_reg=l2_reg)


def smooth_l1_loss(input, label, reduction="mean", delta=1.0, name=None):
    return apply("smooth_l1_loss", input, label, delta=delta,
                 reduction=reduction)


def nll_loss(input, label, weight=None, ignore_index=-100, reduction="mean",
             name=None):
    if weight is not None:
        return apply("nll_loss", input, label, weight,
                     reduction=reduction, ignore_index=ignore_index)
    return apply("nll_loss", input, label, reduction=reduction,
                 ignore_index=ignore_index)


def kl_div(input, label, reduction="mean", name=None):
    return apply("kldiv_loss", input, label, reduction=reduction)


def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean",
                        name=None):
    return apply("margin_ranking_loss", input, other, label, margin=margin,
                 reduction=reduction)


def sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25, gamma=2.0,
                       reduction="sum", name=None):
    p = sigmoid(logit)
    ce = apply("sigmoid_cross_entropy_with_logits", logit, label)
    p_t = p * label + (1 - p) * (1 - label)
    alpha_t = alpha * label + (1 - alpha) * (1 - label)
    loss = alpha_t * ((1 - p_t) ** gamma) * ce
    if normalizer is not None:
        loss = loss / normalizer
    if reduction == "none":
        return loss
    return loss.sum() if reduction == "sum" else loss.mean()


def cosine_similarity(x1, x2, axis=1, eps=1e-8):
    return apply("cosine_similarity", x1, x2, axis=axis, eps=eps)


# -- shape / misc -----------------------------------------------------------


def pad(x, pad, mode="constant", value=0.0, data_format="NCHW", name=None):
    if isinstance(pad, (list, tuple)) and len(pad) == 2 * x.ndim:
        paddings = pad
    else:
        # paddle convention: pad is [left, right, top, bottom, ...] for the
        # trailing spatial dims, in data_format order
        spatial = len(pad) // 2
        paddings = [0, 0] * (x.ndim - spatial)
        if data_format.startswith("NC"):
            for i in range(spatial):
                paddings += [pad[2 * i], pad[2 * i + 1]]
        else:
            paddings = [0, 0]
            for i in range(spatial):
                paddings += [pad[2 * i], pad[2 * i + 1]]
            paddings += [0, 0]
    return apply("pad", x, paddings=list(map(int, paddings)), mode=mode,
                 value=value, data_format=data_format)


def interpolate(x, size=None, scale_factor=None, mode="nearest",
                align_corners=False, align_mode=0, data_format="NCHW",
                name=None):
    if size is not None and not isinstance(size, (list, tuple)):
        size = [size, size]
    return apply("interpolate", x, size=size, scale_factor=scale_factor,
                 mode=mode, align_corners=align_corners,
                 data_format=data_format)


def upsample(x, size=None, scale_factor=None, mode="nearest",
             align_corners=False, align_mode=0, data_format="NCHW",
             name=None):
    return interpolate(x, size, scale_factor, mode, align_corners,
                       align_mode, data_format, name)


def pixel_shuffle(x, upscale_factor, data_format="NCHW", name=None):
    return apply("pixel_shuffle", x, upscale_factor=upscale_factor,
                 data_format=data_format)


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    return apply("unfold", x, kernel_sizes=kernel_sizes, strides=strides,
                 paddings=paddings, dilations=dilations)


def temporal_shift(x, seg_num, shift_ratio=0.25, name=None,
                   data_format="NCHW"):
    return apply("temporal_shift", x, seg_num=seg_num,
                 shift_ratio=shift_ratio)


def scaled_dot_product_attention(query, key, value, attn_mask=None,
                                 dropout_p=0.0, is_causal=False,
                                 training=True, name=None,
                                 qkv_layout="bshd"):
    """query/key/value: [batch, seq, heads, head_dim] (paddle layout).

    qkv_layout='bhsd' accepts pre-transposed [batch, heads, seq, head_dim]
    inputs and returns [batch, seq, heads*...] — callers that already hold
    head-major tensors (packed-QKV attention blocks) skip the per-tensor
    transposes, which are physical copies around the opaque pallas call.
    """
    if qkv_layout == "bhsd":
        q, k, v = query, key, value
    else:
        q = query.transpose([0, 2, 1, 3])
        k = key.transpose([0, 2, 1, 3])
        v = value.transpose([0, 2, 1, 3])
    use_dropout = dropout_p > 0.0 and training
    if attn_mask is None and _has_flash():
        # flash handles attention dropout in-kernel (mask regenerated in
        # the backward from the seed, never materialised)
        seed = None
        if use_dropout:
            import jax.numpy as _jnp

            seed = Tensor(
                _random.next_key()[0].astype(_jnp.int32))
        out = apply("flash_attention", q, k, v, seed,
                    is_causal=is_causal,
                    dropout_p=dropout_p if use_dropout else 0.0)
    else:
        key = Tensor(_random.next_key()) if use_dropout else None
        out = apply("scaled_dot_product_attention", q, k, v, attn_mask,
                    key, dropout_p=dropout_p if use_dropout else 0.0,
                    is_causal=is_causal)
    return out.transpose([0, 2, 1, 3])  # back to [b, s, h, d]


def _has_flash():
    from ...core.op_registry import has_op

    return has_op("flash_attention")


def label_smooth(label, prior_dist=None, epsilon=0.1, name=None):
    k = label.shape[-1]
    smoothed = (1.0 - epsilon) * label + epsilon / k
    return smoothed


def diag_embed(input, offset=0, dim1=-2, dim2=-1):
    return apply("diag_embed", input, offset=offset, dim1=dim1, dim2=dim2)


def glu(x, axis=-1, name=None):
    a, b = x.chunk(2, axis=axis)
    return a * sigmoid(b)
