"""Convolution layers (ref: python/paddle/nn/layer/conv.py)."""

from __future__ import annotations

import numpy as np

from .. import functional as F
from .. import initializer as I
from .layers import Layer


def _pair(v):
    return tuple(v) if isinstance(v, (list, tuple)) else (v, v)


class _ConvNd(Layer):
    def __init__(self, in_channels, out_channels, kernel_size, stride,
                 padding, dilation, groups, weight_attr, bias_attr,
                 data_format, ndim, transpose=False, output_padding=0):
        super().__init__()
        self._in_channels = in_channels
        self._out_channels = out_channels
        ks = kernel_size if isinstance(kernel_size, (list, tuple)) \
            else (kernel_size,) * ndim
        self._kernel_size = tuple(ks)
        self._stride = stride
        self._padding = padding
        self._output_padding = output_padding
        self._dilation = dilation
        self._groups = groups
        self._data_format = data_format
        if transpose:
            wshape = [in_channels, out_channels // groups, *self._kernel_size]
        else:
            wshape = [out_channels, in_channels // groups, *self._kernel_size]
        fan_in = (in_channels // groups) * int(np.prod(self._kernel_size))
        bound = 1.0 / np.sqrt(fan_in)
        self.weight = self.create_parameter(
            shape=wshape, attr=weight_attr,
            default_initializer=I.KaimingUniform(fan_in=fan_in))
        self.bias = self.create_parameter(
            shape=[out_channels], attr=bias_attr, is_bias=True,
            default_initializer=I.Uniform(-bound, bound))

    def extra_repr(self):
        return (f"{self._in_channels}, {self._out_channels}, "
                f"kernel_size={list(self._kernel_size)}, "
                f"stride={self._stride}")


class Conv1D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NCL"):
        super().__init__(in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, weight_attr, bias_attr,
                         data_format, 1)

    def forward(self, x):
        return F.conv1d(x, self.weight, self.bias, self._stride,
                        self._padding, self._dilation, self._groups,
                        self._data_format)


class Conv2D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NCHW"):
        super().__init__(in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, weight_attr, bias_attr,
                         data_format, 2)

    def forward(self, x):
        return F.conv2d(x, self.weight, self.bias, self._stride,
                        self._padding, self._dilation, self._groups,
                        self._data_format)

    def _is_plain_for_fusion(self):
        """True when this layer's forward is exactly the stock
        bias-free F.conv2d above — the conv half of a fusable
        Conv->BN->act chain (vision.models.resnet._conv_bn_act).
        Subclass forwards, hooks, bias, groups, and dilation all keep
        the composed path."""
        return (type(self).forward is Conv2D.forward
                and self.bias is None
                and self._groups == 1
                and self._data_format == "NCHW"
                and _pair(self._dilation) == (1, 1)
                and not self._forward_pre_hooks
                and not self._forward_post_hooks)


class Conv3D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NCDHW"):
        super().__init__(in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, weight_attr, bias_attr,
                         data_format, 3)

    def forward(self, x):
        return F.conv3d(x, self.weight, self.bias, self._stride,
                        self._padding, self._dilation, self._groups,
                        self._data_format)


class Conv2DTranspose(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, output_padding=0, dilation=1, groups=1,
                 weight_attr=None, bias_attr=None, data_format="NCHW"):
        super().__init__(in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, weight_attr, bias_attr,
                         data_format, 2, transpose=True,
                         output_padding=output_padding)

    def forward(self, x, output_size=None):
        return F.conv2d_transpose(x, self.weight, self.bias, self._stride,
                                  self._padding, self._output_padding,
                                  self._dilation, self._groups,
                                  self._data_format)


class Conv1DTranspose(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, output_padding=0, dilation=1, groups=1,
                 weight_attr=None, bias_attr=None, data_format="NCL"):
        super().__init__(in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, weight_attr, bias_attr,
                         data_format, 1, transpose=True,
                         output_padding=output_padding)

    def forward(self, x, output_size=None):
        return F.conv1d_transpose(x, self.weight, self.bias, self._stride,
                                  self._padding, self._output_padding,
                                  self._dilation, self._groups,
                                  self._data_format)


class Conv3DTranspose(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, output_padding=0, dilation=1, groups=1,
                 weight_attr=None, bias_attr=None, data_format="NCDHW"):
        super().__init__(in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, weight_attr, bias_attr,
                         data_format, 3, transpose=True,
                         output_padding=output_padding)

    def forward(self, x, output_size=None):
        return F.conv3d_transpose(x, self.weight, self.bias, self._stride,
                                  self._padding, self._output_padding,
                                  self._dilation, self._groups,
                                  self._data_format)
