"""nn.Layer — the module base class.

Ref parity: python/paddle/fluid/dygraph/layers.py (Layer, __call__ at :880,
state_dict assembly, hook registry). Parameters are Tensors backed by
jax.Array; the functional engine can temporarily swap their values with
tracers to build one compiled XLA program from the same forward code.
"""

from __future__ import annotations

import typing as _t
from collections import OrderedDict

import numpy as np

from ...core import config
from ...core.tensor import Parameter, Tensor
from ...param_attr import ParamAttr


class HookRemoveHelper:
    _next_id = 0

    def __init__(self, hooks):
        self._hooks = hooks
        self._hook_id = HookRemoveHelper._next_id
        HookRemoveHelper._next_id += 1

    def remove(self):
        self._hooks.pop(self._hook_id, None)


_auto_name_counters: dict = {}


def _auto_prefix(layer):
    """Stable per-instance prefix like 'linear_0' (ref fluid unique_name
    generator). Cached on the instance itself (no global id map)."""
    cached = layer.__dict__.get("_auto_prefix_name")
    if cached is None:
        cls = type(layer).__name__.lower()
        n = _auto_name_counters.get(cls, 0)
        _auto_name_counters[cls] = n + 1
        cached = f"{cls}_{n}"
        layer.__dict__["_auto_prefix_name"] = cached
    return cached


class Layer:
    def __init__(self, name_scope=None, dtype="float32"):
        self.training = True
        self._dtype = dtype
        self._full_name = name_scope or self.__class__.__name__.lower()
        self._parameters: "OrderedDict[str, Parameter]" = OrderedDict()
        self._sub_layers: "OrderedDict[str, Layer]" = OrderedDict()
        self._buffers: "OrderedDict[str, Tensor]" = OrderedDict()
        self._non_persistable_buffer_names: set[str] = set()
        self._forward_pre_hooks: "OrderedDict[int, _t.Callable]" = OrderedDict()
        self._forward_post_hooks: "OrderedDict[int, _t.Callable]" = OrderedDict()

    # -- construction helpers ----------------------------------------------
    def create_parameter(self, shape, attr=None, dtype=None, is_bias=False,
                         default_initializer=None):
        from ... import nn

        dtype = dtype or self._dtype
        attr = ParamAttr._to_attr(attr)
        if attr is False:
            return None
        init = None
        if attr is not None and attr.initializer is not None:
            init = attr.initializer
        elif default_initializer is not None:
            init = default_initializer
        elif is_bias:
            init = nn.initializer.Constant(0.0)
        else:
            init = nn.initializer.XavierNormal()
        data = init(shape, dtype)
        p = Parameter(data, name=attr.name if attr else None)
        if attr is not None:
            p.optimize_attr["learning_rate"] = attr.learning_rate
            p.regularizer = attr.regularizer
            p.trainable = attr.trainable
            p.stop_gradient = not attr.trainable
            p.need_clip = attr.need_clip
        return p

    def add_parameter(self, name, parameter):
        if parameter is not None and not isinstance(parameter, Parameter):
            raise TypeError("add_parameter expects a Parameter")
        self._parameters[name] = parameter
        return parameter

    def add_sublayer(self, name, sublayer):
        self._sub_layers[str(name)] = sublayer
        return sublayer

    def register_buffer(self, name, tensor, persistable=True):
        self._buffers[name] = tensor
        if not persistable:
            self._non_persistable_buffer_names.add(name)
        return tensor

    # -- attribute protocol -------------------------------------------------
    def __setattr__(self, name, value):
        params = self.__dict__.get("_parameters")
        layers = self.__dict__.get("_sub_layers")
        buffers = self.__dict__.get("_buffers")
        if isinstance(value, Parameter):
            if params is None:
                raise RuntimeError(
                    "call super().__init__() before assigning parameters")
            for d in (layers, buffers):
                if d is not None:
                    d.pop(name, None)
            if value.name is None:
                # auto name (ref fluid unique_name): '<class>_<n>.<attr>'
                # — name-based matching (e.g. LARS exclude lists) works
                # without explicit ParamAttr names
                value.name = f"{_auto_prefix(self)}.{name}"
            params[name] = value
        elif isinstance(value, Layer):
            if layers is None:
                raise RuntimeError(
                    "call super().__init__() before assigning sublayers")
            for d in (params, buffers):
                if d is not None:
                    d.pop(name, None)
            layers[name] = value
        elif buffers is not None and name in buffers:
            if value is None or isinstance(value, Tensor):
                buffers[name] = value
            else:
                object.__setattr__(self, name, value)
        else:
            if params is not None and name in params:
                if value is None:
                    params[name] = None
                    return
            object.__setattr__(self, name, value)

    def __getattr__(self, name):
        for store in ("_parameters", "_sub_layers", "_buffers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                return d[name]
        raise AttributeError(
            f"'{type(self).__name__}' object has no attribute '{name}'")

    def __delattr__(self, name):
        for store in ("_parameters", "_sub_layers", "_buffers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                del d[name]
                return
        object.__delattr__(self, name)

    def __dir__(self):
        extra = []
        for store in ("_parameters", "_sub_layers", "_buffers"):
            d = self.__dict__.get(store)
            if d:
                extra.extend(d.keys())
        return list(super().__dir__()) + extra

    # -- traversal ----------------------------------------------------------
    def parameters(self, include_sublayers=True):
        return [p for _, p in self.named_parameters(
            include_sublayers=include_sublayers)]

    def named_parameters(self, prefix="", include_sublayers=True):
        seen = set()
        for name, layer in self.named_sublayers(
                prefix=prefix, include_self=True):
            for pname, p in layer._parameters.items():
                if p is None or id(p) in seen:
                    continue
                seen.add(id(p))
                yield (f"{name}.{pname}" if name else pname, p)
            if not include_sublayers:
                break

    def buffers(self, include_sublayers=True):
        return [b for _, b in self.named_buffers(
            include_sublayers=include_sublayers)]

    def named_buffers(self, prefix="", include_sublayers=True):
        seen = set()
        for name, layer in self.named_sublayers(
                prefix=prefix, include_self=True):
            for bname, b in layer._buffers.items():
                if b is None or id(b) in seen:
                    continue
                seen.add(id(b))
                yield (f"{name}.{bname}" if name else bname, b)
            if not include_sublayers:
                break

    def children(self):
        return [l for _, l in self.named_children()]

    def named_children(self):
        seen = set()
        for name, layer in self._sub_layers.items():
            if layer is None or id(layer) in seen:
                continue
            seen.add(id(layer))
            yield name, layer

    def sublayers(self, include_self=False):
        return [l for _, l in self.named_sublayers(include_self=include_self)]

    def named_sublayers(self, prefix="", include_self=False):
        if include_self:
            yield prefix, self
        for name, layer in self.named_children():
            sub_prefix = f"{prefix}.{name}" if prefix else name
            yield from layer.named_sublayers(prefix=sub_prefix,
                                             include_self=True)

    def apply(self, fn):
        for layer in self.children():
            layer.apply(fn)
        fn(self)
        return self

    def full_name(self):
        return self._full_name

    # -- state dict ----------------------------------------------------------
    def state_dict(self, destination=None, include_sublayers=True,
                   structured_name_prefix="", use_hook=True):
        out = OrderedDict() if destination is None else destination
        for name, p in self.named_parameters(prefix=structured_name_prefix):
            out[name] = p
        for name, layer in self.named_sublayers(
                prefix=structured_name_prefix, include_self=True):
            for bname, b in layer._buffers.items():
                if b is None or bname in layer._non_persistable_buffer_names:
                    continue
                out[f"{name}.{bname}" if name else bname] = b
        return out

    def set_state_dict(self, state_dict, use_structured_name=True):
        own = self.state_dict()
        missing, unexpected = [], []
        for name, target in own.items():
            if name not in state_dict:
                missing.append(name)
                continue
            src = state_dict[name]
            arr = src.numpy() if isinstance(src, Tensor) else np.asarray(src)
            if tuple(arr.shape) != tuple(target.shape):
                raise ValueError(
                    f"shape mismatch for {name}: {list(arr.shape)} vs "
                    f"{target.shape}")
            target.set_value(arr)
        for name in state_dict:
            if name not in own:
                unexpected.append(name)
        return missing, unexpected

    load_dict = set_state_dict
    set_dict = set_state_dict

    # -- modes ----------------------------------------------------------------
    def train(self):
        self.training = True
        for layer in self.sublayers():
            layer.training = True
        return self

    def eval(self):
        self.training = False
        for layer in self.sublayers():
            layer.training = False
        return self

    # -- dtype/device ---------------------------------------------------------
    def to(self, device=None, dtype=None, blocking=None):
        if dtype is not None:
            from ...core.dtype import to_jax_dtype

            jdt = to_jax_dtype(dtype)
            for p in self.parameters():
                p._value = p._value.astype(jdt)
            for b in self.buffers():
                if b is not None and b._value.dtype.kind == "f":
                    b._value = b._value.astype(jdt)
            self._dtype = dtype
        return self

    def astype(self, dtype):
        return self.to(dtype=dtype)

    float = lambda self: self.to(dtype="float32")  # noqa: E731

    # -- hooks ----------------------------------------------------------------
    def register_forward_pre_hook(self, hook):
        helper = HookRemoveHelper(self._forward_pre_hooks)
        self._forward_pre_hooks[helper._hook_id] = hook
        return helper

    def register_forward_post_hook(self, hook):
        helper = HookRemoveHelper(self._forward_post_hooks)
        self._forward_post_hooks[helper._hook_id] = hook
        return helper

    # -- call -----------------------------------------------------------------
    def __call__(self, *inputs, **kwargs):
        for hook in list(self._forward_pre_hooks.values()):
            out = hook(self, inputs)
            if out is not None:
                inputs = out if isinstance(out, tuple) else (out,)
        outputs = self.forward(*inputs, **kwargs)
        for hook in list(self._forward_post_hooks.values()):
            res = hook(self, inputs, outputs)
            if res is not None:
                outputs = res
        return outputs

    def forward(self, *inputs, **kwargs):
        raise NotImplementedError

    def extra_repr(self):
        return ""

    def __repr__(self):
        extra = self.extra_repr()
        lines = []
        for name, layer in self.named_children():
            mod_str = repr(layer)
            mod_str = "\n".join(
                "  " + l for l in mod_str.splitlines())
            lines.append(f"  ({name}): {mod_str.strip()}" if "\n" not in
                         mod_str else f"  ({name}): {mod_str.lstrip()}")
        main = f"{type(self).__name__}({extra}"
        if lines:
            return main + "\n" + "\n".join(lines) + "\n)"
        return main + ")"
