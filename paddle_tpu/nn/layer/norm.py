"""Normalisation layers (ref: python/paddle/nn/layer/norm.py)."""

from __future__ import annotations

from ...core.tensor import Tensor
from .. import functional as F
from .. import initializer as I
from .layers import Layer


class _BatchNormBase(Layer):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 use_global_stats=None, name=None):
        super().__init__()
        self._num_features = num_features
        self._momentum = momentum
        self._epsilon = epsilon
        self._data_format = data_format
        self._use_global_stats = use_global_stats
        self.weight = self.create_parameter(
            shape=[num_features], attr=weight_attr,
            default_initializer=I.Constant(1.0))
        self.bias = self.create_parameter(
            shape=[num_features], attr=bias_attr, is_bias=True)
        import jax.numpy as jnp

        self.register_buffer("_mean", Tensor(jnp.zeros(num_features)))
        self.register_buffer("_variance", Tensor(jnp.ones(num_features)))

    def forward(self, x):
        return F.batch_norm(
            x, self._mean, self._variance, self.weight, self.bias,
            training=self.training, momentum=self._momentum,
            epsilon=self._epsilon, data_format=self._data_format,
            use_global_stats=self._use_global_stats)

    def _is_plain(self):
        """True when this layer's forward is exactly the stock
        F.batch_norm above, so model-level fusions (fused_bn_act /
        fused_conv2d_bn_act) may bypass Layer.__call__; SyncBatchNorm,
        subclass forwards, and hook-carrying layers keep the composed
        path so hooks and overrides still fire."""
        return (type(self).forward is _BatchNormBase.forward
                and not isinstance(self, SyncBatchNorm)
                and not self._forward_pre_hooks
                and not self._forward_post_hooks)

    def extra_repr(self):
        return f"num_features={self._num_features}"


class BatchNorm(_BatchNormBase):
    """Legacy fluid-style BatchNorm (acts like BatchNorm2D)."""


class BatchNorm1D(_BatchNormBase):
    pass


class BatchNorm2D(_BatchNormBase):
    pass


class BatchNorm3D(_BatchNormBase):
    pass


class SyncBatchNorm(_BatchNormBase):
    """ref python/paddle/nn/layer/norm.py SyncBatchNorm (custom CUDA
    kernel + NCCL allreduce of partial moments). Under pjit, stats sync
    falls out of GSPMD when the batch axis is sharded; inside shard_map
    the forward dispatches the sync_batch_norm op, which psums the
    moments over the 'dp' axis by hand. Eager single-process behaviour
    equals BatchNorm."""

    def forward(self, x):
        from ...core.dispatch import apply

        out = apply("sync_batch_norm", x, self.weight, self.bias,
                    self._mean, self._variance,
                    momentum=self._momentum, epsilon=self._epsilon,
                    is_test=not self.training,
                    data_format=self._data_format,
                    use_global_stats=bool(self._use_global_stats))
        y, new_mean, new_var = out[0], out[1], out[2]
        if self.training:
            self._mean._value = new_mean._value \
                if hasattr(new_mean, "_value") else new_mean
            self._variance._value = new_var._value \
                if hasattr(new_var, "_value") else new_var
        return y

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        if isinstance(layer, _BatchNormBase) and not isinstance(
                layer, SyncBatchNorm):
            new = SyncBatchNorm(layer._num_features, layer._momentum,
                                layer._epsilon,
                                data_format=layer._data_format)
            new.weight.set_value(layer.weight)
            new.bias.set_value(layer.bias)
            new._mean.set_value(layer._mean)
            new._variance.set_value(layer._variance)
            return new
        for name, sub in list(layer._sub_layers.items()):
            layer._sub_layers[name] = cls.convert_sync_batchnorm(sub)
        return layer


class LayerNorm(Layer):
    def __init__(self, normalized_shape, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = [normalized_shape]
        self._normalized_shape = list(normalized_shape)
        self._epsilon = epsilon
        import numpy as np

        n = int(np.prod(self._normalized_shape))
        self.weight = self.create_parameter(
            shape=[n], attr=weight_attr, default_initializer=I.Constant(1.0))
        self.bias = self.create_parameter(
            shape=[n], attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.layer_norm(x, self._normalized_shape, self.weight,
                            self.bias, self._epsilon)

    def extra_repr(self):
        return f"normalized_shape={self._normalized_shape}"


class GroupNorm(Layer):
    def __init__(self, num_groups, num_channels, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 name=None):
        super().__init__()
        self._num_groups = num_groups
        self._num_channels = num_channels
        self._epsilon = epsilon
        self._data_format = data_format
        self.weight = self.create_parameter(
            shape=[num_channels], attr=weight_attr,
            default_initializer=I.Constant(1.0))
        self.bias = self.create_parameter(
            shape=[num_channels], attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.group_norm(x, self._num_groups, self._epsilon, self.weight,
                            self.bias, self._data_format)


class InstanceNorm2D(Layer):
    def __init__(self, num_features, epsilon=1e-5, momentum=0.9,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 name=None):
        super().__init__()
        self._epsilon = epsilon
        if weight_attr is False or bias_attr is False:
            self.weight = None
            self.bias = None
        else:
            self.weight = self.create_parameter(
                shape=[num_features], attr=weight_attr,
                default_initializer=I.Constant(1.0))
            self.bias = self.create_parameter(
                shape=[num_features], attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.instance_norm(x, weight=self.weight, bias=self.bias,
                               eps=self._epsilon)


InstanceNorm1D = InstanceNorm2D
InstanceNorm3D = InstanceNorm2D


class LocalResponseNorm(Layer):
    def __init__(self, size, alpha=1e-4, beta=0.75, k=1.0,
                 data_format="NCHW", name=None):
        super().__init__()
        self.size = size
        self.alpha = alpha
        self.beta = beta
        self.k = k

    def forward(self, x):
        return F.local_response_norm(x, self.size, self.alpha, self.beta,
                                     self.k)


class SpectralNorm(Layer):
    """ref nn/layer/norm.py SpectralNorm (spectral_norm_op.cc): the
    layer form — holds the power-iteration vectors as buffers and
    normalises the given weight on every call."""

    def __init__(self, weight_shape, dim=0, power_iters=1, eps=1e-12,
                 name=None):
        super().__init__()
        dim = dim % len(weight_shape)  # normalise negative dims
        self._dim = dim
        self._power_iters = power_iters
        self._eps = eps
        h = weight_shape[dim]
        w = 1
        for i, s in enumerate(weight_shape):
            if i != dim:
                w *= s
        self.weight_u = self.create_parameter(
            shape=[h], default_initializer=I.Normal(0.0, 1.0))
        self.weight_u.stop_gradient = True
        self.weight_v = self.create_parameter(
            shape=[w], default_initializer=I.Normal(0.0, 1.0))
        self.weight_v.stop_gradient = True

    def forward(self, weight):
        from ...core.dispatch import apply

        return apply("spectral_norm", weight, self.weight_u,
                     self.weight_v, dim=self._dim,
                     power_iters=self._power_iters, eps=self._eps)


class RMSNorm(Layer):
    """TPU-native addition (used by the NLP model family)."""

    def __init__(self, hidden_size, epsilon=1e-6, weight_attr=None):
        super().__init__()
        self._epsilon = epsilon
        self.weight = self.create_parameter(
            shape=[hidden_size], attr=weight_attr,
            default_initializer=I.Constant(1.0))

    def forward(self, x):
        from ...core.dispatch import apply

        return apply("rms_norm", x, self.weight, epsilon=self._epsilon)
