"""Activation layers (ref: python/paddle/nn/layer/activation.py)."""

from __future__ import annotations

from .. import functional as F
from .. import initializer as I
from .layers import Layer


def _simple(name, fn, **defaults):
    def __init__(self, name=None, **kwargs):
        Layer.__init__(self)
        self._kwargs = {**defaults, **kwargs}

    def forward(self, x):
        return fn(x, **self._kwargs)

    return type(name, (Layer,), {"__init__": __init__, "forward": forward})


ReLU = _simple("ReLU", F.relu)
ReLU6 = _simple("ReLU6", F.relu6)
Sigmoid = _simple("Sigmoid", F.sigmoid)
LogSigmoid = _simple("LogSigmoid", F.log_sigmoid)
Tanh = _simple("Tanh", F.tanh)
Tanhshrink = _simple("Tanhshrink", F.tanhshrink)
Silu = _simple("Silu", F.silu)
Swish = _simple("Swish", F.swish)
Mish = _simple("Mish", F.mish)
Hardswish = _simple("Hardswish", F.hardswish)
Softsign = _simple("Softsign", F.softsign)
SELU = _simple("SELU", F.selu)


class GELU(Layer):
    def __init__(self, approximate=False, name=None):
        super().__init__()
        self._approximate = approximate

    def forward(self, x):
        return F.gelu(x, self._approximate)


class LeakyReLU(Layer):
    def __init__(self, negative_slope=0.01, name=None):
        super().__init__()
        self._negative_slope = negative_slope

    def forward(self, x):
        return F.leaky_relu(x, self._negative_slope)


class ELU(Layer):
    def __init__(self, alpha=1.0, name=None):
        super().__init__()
        self._alpha = alpha

    def forward(self, x):
        return F.elu(x, self._alpha)


class CELU(Layer):
    def __init__(self, alpha=1.0, name=None):
        super().__init__()
        self._alpha = alpha

    def forward(self, x):
        return F.celu(x, self._alpha)


class Hardtanh(Layer):
    def __init__(self, min=-1.0, max=1.0, name=None):
        super().__init__()
        self._min, self._max = min, max

    def forward(self, x):
        return F.hardtanh(x, self._min, self._max)


class Hardsigmoid(Layer):
    def __init__(self, name=None):
        super().__init__()

    def forward(self, x):
        return F.hardsigmoid(x)


class Hardshrink(Layer):
    def __init__(self, threshold=0.5, name=None):
        super().__init__()
        self._threshold = threshold

    def forward(self, x):
        return F.hardshrink(x, self._threshold)


class Softshrink(Layer):
    def __init__(self, threshold=0.5, name=None):
        super().__init__()
        self._threshold = threshold

    def forward(self, x):
        return F.softshrink(x, self._threshold)


class Softplus(Layer):
    def __init__(self, beta=1.0, threshold=20.0, name=None):
        super().__init__()
        self._beta, self._threshold = beta, threshold

    def forward(self, x):
        return F.softplus(x, self._beta, self._threshold)


class PReLU(Layer):
    def __init__(self, num_parameters=1, init=0.25, weight_attr=None,
                 data_format="NCHW", name=None):
        super().__init__()
        shape = [num_parameters] if num_parameters == 1 else \
            [1, num_parameters] + [1, 1]
        self.weight = self.create_parameter(
            shape=shape, attr=weight_attr,
            default_initializer=I.Constant(init))

    def forward(self, x):
        return F.prelu(x, self.weight)


class Softmax(Layer):
    def __init__(self, axis=-1, name=None):
        super().__init__()
        self._axis = axis

    def forward(self, x):
        return F.softmax(x, self._axis)


class LogSoftmax(Layer):
    def __init__(self, axis=-1, name=None):
        super().__init__()
        self._axis = axis

    def forward(self, x):
        return F.log_softmax(x, self._axis)


class Maxout(Layer):
    def __init__(self, groups, axis=1, name=None):
        super().__init__()
        self._groups = groups
        self._axis = axis

    def forward(self, x):
        return F.maxout(x, self._groups, self._axis)


class ThresholdedReLU(Layer):
    def __init__(self, threshold=1.0, name=None):
        super().__init__()
        self._threshold = threshold

    def forward(self, x):
        return F.thresholded_relu(x, self._threshold)
