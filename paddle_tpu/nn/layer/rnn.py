"""Recurrent layers: cells, RNN/BiRNN wrappers, SimpleRNN/LSTM/GRU.

Ref parity: python/paddle/nn/layer/rnn.py (RNNCellBase:95, SimpleRNNCell
:258, LSTMCell:390, GRUCell:543, RNN:694, BiRNN:776, SimpleRNN/LSTM/GRU).
Same cell equations and parameter naming; the multi-layer classes dispatch
to the fused `rnn` op (ops/rnn_ops.py) whose time loop is a lax.scan —
the TPU replacement for the reference's cudnn rnn_op.
"""

from __future__ import annotations

import math

import numpy as np

from ...core.dispatch import apply
from ...core.tensor import Tensor
from ...framework import random as _random
from ...tensor.manipulation import concat, split, stack, t
from .. import functional as F
from .. import initializer as I
from .layers import Layer

__all__ = [
    "RNNCellBase", "SimpleRNNCell", "LSTMCell", "GRUCell",
    "RNN", "BiRNN", "SimpleRNN", "LSTM", "GRU",
]


def _split(x, n):
    return split(x, num_or_sections=n, axis=-1)


class RNNCellBase(Layer):
    """Base for single-step recurrent cells (ref rnn.py:95)."""

    def get_initial_states(self, batch_ref, shape=None, dtype=None,
                           init_value=0.0):
        batch = batch_ref.shape[0]
        shapes = shape if shape is not None else self.state_shape
        if isinstance(shapes, tuple) and isinstance(shapes[0], (tuple, list)):
            return tuple(
                Tensor(np.full((batch,) + tuple(s), init_value, np.float32))
                for s in shapes)
        return Tensor(np.full((batch,) + tuple(shapes), init_value,
                              np.float32))


class SimpleRNNCell(RNNCellBase):
    r"""h' = act(x W_ih^T + b_ih + h W_hh^T + b_hh) (ref rnn.py:258)."""

    def __init__(self, input_size, hidden_size, activation="tanh",
                 weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None, name=None):
        super().__init__()
        if activation not in ("tanh", "relu"):
            raise ValueError("activation must be 'tanh' or 'relu'")
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.activation = activation
        std = 1.0 / math.sqrt(hidden_size)
        u = I.Uniform(-std, std)
        self.weight_ih = self.create_parameter(
            [hidden_size, input_size], weight_ih_attr,
            default_initializer=u)
        self.weight_hh = self.create_parameter(
            [hidden_size, hidden_size], weight_hh_attr,
            default_initializer=u)
        self.bias_ih = self.create_parameter(
            [hidden_size], bias_ih_attr, is_bias=True,
            default_initializer=u)
        self.bias_hh = self.create_parameter(
            [hidden_size], bias_hh_attr, is_bias=True,
            default_initializer=u)

    @property
    def state_shape(self):
        return (self.hidden_size,)

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        pre_h = states
        gates = F.linear(inputs, t(self.weight_ih))
        if self.bias_ih is not None:
            gates = gates + self.bias_ih
        gates = gates + F.linear(pre_h, t(self.weight_hh))
        if self.bias_hh is not None:
            gates = gates + self.bias_hh
        h = F.tanh(gates) if self.activation == "tanh" else F.relu(gates)
        return h, h


class LSTMCell(RNNCellBase):
    r"""Gates i,f,g,o; c' = f*c + i*tanh(g); h' = o*tanh(c')
    (ref rnn.py:390)."""

    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        std = 1.0 / math.sqrt(hidden_size)
        u = I.Uniform(-std, std)
        self.weight_ih = self.create_parameter(
            [4 * hidden_size, input_size], weight_ih_attr,
            default_initializer=u)
        self.weight_hh = self.create_parameter(
            [4 * hidden_size, hidden_size], weight_hh_attr,
            default_initializer=u)
        self.bias_ih = self.create_parameter(
            [4 * hidden_size], bias_ih_attr, is_bias=True,
            default_initializer=u)
        self.bias_hh = self.create_parameter(
            [4 * hidden_size], bias_hh_attr, is_bias=True,
            default_initializer=u)

    @property
    def state_shape(self):
        return ((self.hidden_size,), (self.hidden_size,))

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        pre_h, pre_c = states
        gates = F.linear(inputs, t(self.weight_ih))
        if self.bias_ih is not None:
            gates = gates + self.bias_ih
        gates = gates + F.linear(pre_h, t(self.weight_hh))
        if self.bias_hh is not None:
            gates = gates + self.bias_hh
        i, f, g, o = _split(gates, 4)
        i, f, o = F.sigmoid(i), F.sigmoid(f), F.sigmoid(o)
        c = f * pre_c + i * F.tanh(g)
        h = o * F.tanh(c)
        return h, (h, c)


class GRUCell(RNNCellBase):
    r"""Gates r,z,c; h' = z*h + (1-z)*tanh(xc + r*(hc)) (ref rnn.py:543)."""

    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        std = 1.0 / math.sqrt(hidden_size)
        u = I.Uniform(-std, std)
        self.weight_ih = self.create_parameter(
            [3 * hidden_size, input_size], weight_ih_attr,
            default_initializer=u)
        self.weight_hh = self.create_parameter(
            [3 * hidden_size, hidden_size], weight_hh_attr,
            default_initializer=u)
        self.bias_ih = self.create_parameter(
            [3 * hidden_size], bias_ih_attr, is_bias=True,
            default_initializer=u)
        self.bias_hh = self.create_parameter(
            [3 * hidden_size], bias_hh_attr, is_bias=True,
            default_initializer=u)

    @property
    def state_shape(self):
        return (self.hidden_size,)

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        pre_h = states
        x_gates = F.linear(inputs, t(self.weight_ih))
        if self.bias_ih is not None:
            x_gates = x_gates + self.bias_ih
        h_gates = F.linear(pre_h, t(self.weight_hh))
        if self.bias_hh is not None:
            h_gates = h_gates + self.bias_hh
        xr, xz, xc = _split(x_gates, 3)
        hr, hz, hc = _split(h_gates, 3)
        r = F.sigmoid(xr + hr)
        z = F.sigmoid(xz + hz)
        cand = F.tanh(xc + r * hc)
        h = z * pre_h + (1.0 - z) * cand
        return h, h


class RNN(Layer):
    """Run a cell over a sequence (ref rnn.py:694). Python time loop —
    generic over user cells; the fused classes below are the fast path."""

    def __init__(self, cell, is_reverse=False, time_major=False):
        super().__init__()
        self.cell = cell
        self.is_reverse = is_reverse
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, **kwargs):
        time_axis = 0 if self.time_major else 1
        steps = inputs.shape[time_axis]
        states = initial_states
        if states is None:
            batch_ref = inputs[0] if self.time_major else inputs
            states = self.cell.get_initial_states(batch_ref)
        outputs = []
        order = range(steps - 1, -1, -1) if self.is_reverse else range(steps)
        for t in order:
            xt = inputs[t] if self.time_major else inputs[:, t]
            out, states = self.cell(xt, states, **kwargs)
            outputs.append(out)
        if self.is_reverse:
            outputs = outputs[::-1]
        stacked = stack(outputs, axis=time_axis)
        return stacked, states


class BiRNN(Layer):
    """Forward + backward cells, outputs concatenated (ref rnn.py:776)."""

    def __init__(self, cell_fw, cell_bw, time_major=False):
        super().__init__()
        self.cell_fw = cell_fw
        self.cell_bw = cell_bw
        self.rnn_fw = RNN(cell_fw, is_reverse=False, time_major=time_major)
        self.rnn_bw = RNN(cell_bw, is_reverse=True, time_major=time_major)

    def forward(self, inputs, initial_states=None, **kwargs):
        fw_states = bw_states = None
        if initial_states is not None:
            fw_states, bw_states = initial_states
        out_fw, st_fw = self.rnn_fw(inputs, fw_states, **kwargs)
        out_bw, st_bw = self.rnn_bw(inputs, bw_states, **kwargs)
        out = concat([out_fw, out_bw], axis=-1)
        return out, (st_fw, st_bw)


class _RNNBase(Layer):
    """Stacked (bi)directional recurrence over the fused `rnn` op.

    Parameter naming follows the reference: weight_ih_l{k}[_reverse], ...
    """

    def __init__(self, mode, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 weight_ih_attr=None, weight_hh_attr=None,
                 bias_ih_attr=None, bias_hh_attr=None, name=None):
        super().__init__()
        if direction not in ("forward", "bidirect", "bidirectional"):
            raise ValueError(f"unknown direction {direction!r}")
        self.mode = mode
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.time_major = time_major
        self.dropout = float(dropout)
        self.num_directions = 2 if direction.startswith("bidirect") else 1
        from ...ops.rnn_ops import _GATE_MULT

        gm = _GATE_MULT[mode]
        std = 1.0 / math.sqrt(hidden_size)
        u = I.Uniform(-std, std)
        self._weight_names = []
        for layer in range(num_layers):
            in_size = input_size if layer == 0 \
                else hidden_size * self.num_directions
            for d in range(self.num_directions):
                suffix = "_reverse" if d == 1 else ""
                names = [f"weight_ih_l{layer}{suffix}",
                         f"weight_hh_l{layer}{suffix}",
                         f"bias_ih_l{layer}{suffix}",
                         f"bias_hh_l{layer}{suffix}"]
                shapes = [[gm * hidden_size, in_size],
                          [gm * hidden_size, hidden_size],
                          [gm * hidden_size], [gm * hidden_size]]
                attrs = [weight_ih_attr, weight_hh_attr,
                         bias_ih_attr, bias_hh_attr]
                for n, s, a in zip(names, shapes, attrs):
                    p = self.create_parameter(
                        s, a, is_bias=(len(s) == 1), default_initializer=u)
                    setattr(self, n, p)
                self._weight_names.append(names)

    @property
    def state_shape(self):
        layers = self.num_layers * self.num_directions
        return (layers, -1, self.hidden_size)

    def _flat_weights(self):
        out = []
        for names in self._weight_names:
            out.extend(getattr(self, n) for n in names)
        return out

    def forward(self, inputs, initial_states=None):
        batch = inputs.shape[0 if not self.time_major else 1]
        layers = self.num_layers * self.num_directions
        zeros = np.zeros((layers, batch, self.hidden_size), np.float32)
        if self.mode == "LSTM":
            if initial_states is None:
                init_h, init_c = Tensor(zeros), Tensor(zeros)
            else:
                init_h, init_c = initial_states
        else:
            init_h = initial_states if initial_states is not None \
                else Tensor(zeros)
            init_c = Tensor(zeros)
        dropout = self.dropout if self.training else 0.0
        # only consume the RNG stream when a mask will actually be drawn —
        # eval passes must not perturb exact-resume RNG positions
        key = _random.next_key() if dropout > 0.0 \
            else np.zeros(2, np.uint32)
        outputs, final_h, final_c = apply(
            "rnn", inputs, init_h, init_c, key, *self._flat_weights(),
            mode=self.mode, num_layers=self.num_layers,
            hidden_size=self.hidden_size,
            is_bidirec=(self.num_directions == 2),
            time_major=self.time_major, dropout=dropout)
        if self.mode == "LSTM":
            return outputs, (final_h, final_c)
        return outputs, final_h

    def extra_repr(self):
        return (f"{self.input_size}, {self.hidden_size}, "
                f"num_layers={self.num_layers}")


class SimpleRNN(_RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 activation="tanh", **kwargs):
        mode = "RNN_TANH" if activation == "tanh" else "RNN_RELU"
        super().__init__(mode, input_size, hidden_size, num_layers,
                         direction, time_major, dropout, **kwargs)


class LSTM(_RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 **kwargs):
        super().__init__("LSTM", input_size, hidden_size, num_layers,
                         direction, time_major, dropout, **kwargs)


class GRU(_RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 **kwargs):
        super().__init__("GRU", input_size, hidden_size, num_layers,
                         direction, time_major, dropout, **kwargs)
