"""Seq2seq decoding: BeamSearchDecoder + dynamic_decode.

Ref parity: python/paddle/nn/layer/rnn.py BeamSearchDecoder and
python/paddle/nn/decode.py dynamic_decode (beam_search_op /
beam_search_decode_op / gather_tree_op in the reference op set).
TPU-native: the decode loop runs a fixed `max_step_num` steps with
static [B, W] beam shapes (finished beams keep extending with end_token
at probability 1), and the final sequences are re-threaded through the
`gather_tree` op — no dynamic-length LoD output.
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from ...core.dispatch import apply
from ...core.tensor import Tensor
from .layers import Layer

__all__ = ["BeamSearchDecoder", "dynamic_decode"]

_NEG_INF = -1e9


def _raw(t):
    return t._value if isinstance(t, Tensor) else jnp.asarray(t)


def _tile_beam(x, beam_size):
    """[B, ...] -> [B*W, ...] (repeat each batch item W times)."""
    x = _raw(x)
    return jnp.repeat(x, beam_size, axis=0)


class BeamSearchDecoder:
    """ref nn/layer/rnn.py BeamSearchDecoder: wraps an RNN cell for
    beam-search decoding.

    cell(step_input [B*W, D], states) -> (output, new_states); the cell
    output is projected to vocab logits by `output_fn` (or is already
    logits); `embedding_fn` maps token ids -> step inputs.
    """

    def __init__(self, cell, start_token, end_token, beam_size,
                 embedding_fn=None, output_fn=None):
        self.cell = cell
        self.start_token = int(start_token)
        self.end_token = int(end_token)
        self.beam_size = int(beam_size)
        self.embedding_fn = embedding_fn
        self.output_fn = output_fn

    @staticmethod
    def tile_beam_merge_with_batch(x, beam_size):
        """ref BeamSearchDecoder.tile_beam_merge_with_batch: repeat
        encoder outputs per beam ([B, ...] -> [B*W, ...])."""
        return Tensor(_tile_beam(x, beam_size))

    def _cell_states_tiled(self, initial_states):
        import jax

        return jax.tree.map(
            lambda s: _tile_beam(s, self.beam_size), initial_states,
            is_leaf=lambda s: isinstance(s, Tensor))

    def decode(self, initial_states, max_step_num):
        """Run the fixed-length beam search. Returns (ids [B, T, W],
        scores [B, W]) with beams sorted by score (best first)."""
        import jax

        W = self.beam_size
        # infer batch from the first state leaf
        first = jax.tree.leaves(
            initial_states,
            is_leaf=lambda s: isinstance(s, Tensor))[0]
        B = _raw(first).shape[0]

        states = self._cell_states_tiled(initial_states)
        tokens = jnp.full((B * W,), self.start_token, jnp.int32)
        # beam 0 starts live, others muted so step 1 picks W distinct
        # continuations of the single start hypothesis
        log_probs = jnp.tile(
            jnp.asarray([0.0] + [_NEG_INF] * (W - 1), jnp.float32), (B,))
        finished = jnp.zeros((B * W,), bool)

        step_ids, step_parents = [], []
        for _ in range(max_step_num):
            inp = self.embedding_fn(Tensor(tokens)) \
                if self.embedding_fn is not None else Tensor(tokens)
            out, states = self.cell(inp, states)
            logits = self.output_fn(out) if self.output_fn is not None \
                else out
            logp = jax.nn.log_softmax(
                _raw(logits).astype(jnp.float32), axis=-1)  # [B*W, V]
            V = logp.shape[-1]
            # finished beams extend ONLY with end_token at prob 1
            fin_row = jnp.full((V,), _NEG_INF, jnp.float32
                               ).at[self.end_token].set(0.0)
            logp = jnp.where(finished[:, None], fin_row[None, :], logp)
            scores = (log_probs[:, None] + logp).reshape(B, W * V)
            top_scores, top_idx = jax.lax.top_k(scores, W)  # [B, W]
            parent = (top_idx // V).astype(jnp.int32)
            token = (top_idx % V).astype(jnp.int32)

            # reorder beam-major state by chosen parents
            flat_parent = (parent
                           + (jnp.arange(B) * W)[:, None]).reshape(-1)
            states = jax.tree.map(
                lambda s: _raw(s)[flat_parent], states,
                is_leaf=lambda s: isinstance(s, Tensor))
            log_probs = top_scores.reshape(-1)
            tokens = token.reshape(-1)
            finished = finished[flat_parent] | (tokens == self.end_token)
            step_ids.append(token)
            step_parents.append(parent)
            if bool(finished.all()):
                break

        ids = jnp.stack(step_ids)          # [T, B, W]
        parents = jnp.stack(step_parents)  # [T, B, W]
        full = _raw(apply("gather_tree", ids, parents))  # [T, B, W]
        return (Tensor(jnp.transpose(full, (1, 0, 2))),
                Tensor(log_probs.reshape(B, W)))


def dynamic_decode(decoder, inits=None, max_step_num=100, **kwargs):
    """ref python/paddle/nn/decode.py dynamic_decode: drive a decoder to
    completion. Returns (ids [B, T, W] best-first, scores [B, W])."""
    if not isinstance(decoder, BeamSearchDecoder):
        raise TypeError("dynamic_decode drives a BeamSearchDecoder")
    return decoder.decode(inits, max_step_num)
