"""Loss layers (ref: python/paddle/nn/layer/loss.py)."""

from __future__ import annotations

from .. import functional as F
from .layers import Layer


class CrossEntropyLoss(Layer):
    def __init__(self, weight=None, ignore_index=-100, reduction="mean",
                 soft_label=False, axis=-1, use_softmax=True, name=None):
        super().__init__()
        self._weight = weight
        self._ignore_index = ignore_index
        self._reduction = reduction
        self._soft_label = soft_label
        self._axis = axis
        self._use_softmax = use_softmax

    def forward(self, input, label):
        return F.cross_entropy(
            input, label, weight=self._weight,
            ignore_index=self._ignore_index, reduction=self._reduction,
            soft_label=self._soft_label, axis=self._axis,
            use_softmax=self._use_softmax)


class MSELoss(Layer):
    def __init__(self, reduction="mean"):
        super().__init__()
        self._reduction = reduction

    def forward(self, input, label):
        return F.mse_loss(input, label, self._reduction)


class L1Loss(Layer):
    def __init__(self, reduction="mean", name=None):
        super().__init__()
        self._reduction = reduction

    def forward(self, input, label):
        return F.l1_loss(input, label, self._reduction)


class NLLLoss(Layer):
    def __init__(self, weight=None, ignore_index=-100, reduction="mean",
                 name=None):
        super().__init__()
        self._weight = weight
        self._ignore_index = ignore_index
        self._reduction = reduction

    def forward(self, input, label):
        return F.nll_loss(input, label, self._weight, self._ignore_index,
                          self._reduction)


class BCELoss(Layer):
    def __init__(self, weight=None, reduction="mean", name=None):
        super().__init__()
        self._weight = weight
        self._reduction = reduction

    def forward(self, input, label):
        return F.binary_cross_entropy(input, label, self._weight,
                                      self._reduction)


class BCEWithLogitsLoss(Layer):
    def __init__(self, weight=None, reduction="mean", pos_weight=None,
                 name=None):
        super().__init__()
        self._weight = weight
        self._reduction = reduction
        self._pos_weight = pos_weight

    def forward(self, logit, label):
        return F.binary_cross_entropy_with_logits(
            logit, label, self._weight, self._reduction, self._pos_weight)


class KLDivLoss(Layer):
    def __init__(self, reduction="mean"):
        super().__init__()
        self._reduction = reduction

    def forward(self, input, label):
        return F.kl_div(input, label, self._reduction)


class SmoothL1Loss(Layer):
    def __init__(self, reduction="mean", delta=1.0, name=None):
        super().__init__()
        self._reduction = reduction
        self._delta = delta

    def forward(self, input, label):
        return F.smooth_l1_loss(input, label, self._reduction, self._delta)


class MarginRankingLoss(Layer):
    def __init__(self, margin=0.0, reduction="mean", name=None):
        super().__init__()
        self._margin = margin
        self._reduction = reduction

    def forward(self, input, other, label):
        return F.margin_ranking_loss(input, other, label, self._margin,
                                     self._reduction)


class CTCLoss(Layer):
    def __init__(self, blank=0, reduction="mean"):
        super().__init__()
        self.blank = blank
        self.reduction = reduction

    def forward(self, log_probs, labels, input_lengths, label_lengths,
                norm_by_times=False):
        return F.ctc_loss(log_probs, labels, input_lengths, label_lengths,
                          blank=self.blank, reduction=self.reduction,
                          norm_by_times=norm_by_times)


class HSigmoidLoss(Layer):
    """Hierarchical sigmoid loss layer (ref nn/layer/loss.py
    HSigmoidLoss over hierarchical_sigmoid_op.cc)."""

    def __init__(self, feature_size, num_classes, weight_attr=None,
                 bias_attr=None, is_custom=False, is_sparse=False,
                 name=None):
        super().__init__()
        if not is_custom and num_classes < 2:
            raise ValueError("num_classes must be >= 2")
        self._feature_size = feature_size
        self._num_classes = num_classes
        self._is_custom = is_custom
        rows = num_classes - 1 if not is_custom else num_classes
        self.weight = self.create_parameter(
            shape=[rows, feature_size], attr=weight_attr)
        self.bias = self.create_parameter(
            shape=[rows, 1], attr=bias_attr, is_bias=True)

    def forward(self, input, label, path_table=None, path_code=None):
        if self._is_custom and (path_table is None or path_code is None):
            raise ValueError(
                "is_custom=True needs path_table and path_code")
        return F.hsigmoid_loss(input, label, self._num_classes,
                               self.weight, self.bias, path_table,
                               path_code)
