"""paddle.nn.utils — weight/spectral norm reparameterization hooks
(ref: python/paddle/nn/utils/{weight_norm_hook,spectral_norm_hook}.py).

Both rewrite an existing layer's weight parameter into derived form and
recompute the effective weight in a forward-pre-hook with TAPED tensor
ops, so gradients flow to the derived parameters (g/v, weight_orig) and
the layer's own forward stays untouched.
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from ...core.tensor import Parameter, Tensor

__all__ = ["weight_norm", "remove_weight_norm", "spectral_norm"]


def _norm_except_t(v, dim):
    """Taped L2 norm of Tensor `v` over every axis except `dim`,
    keepdims for broadcasting."""
    axes = [i for i in range(len(v.shape)) if i != dim]
    return ((v * v).sum(axis=axes, keepdim=True)) ** 0.5


def weight_norm(layer, name="weight", dim=0):
    """Reparameterize ``layer.<name>`` as g * v / ||v|| (ref
    weight_norm_hook.py).  Adds ``<name>_g`` / ``<name>_v`` parameters
    and recomputes the weight before every forward."""
    w = getattr(layer, name)
    wv = w._value
    d = None if dim is None else dim % wv.ndim
    if d is None:
        g0 = jnp.sqrt(jnp.sum(jnp.square(wv)))
    else:
        # 1-D [k] parameter, matching the reference's norm_except_dim
        # output shape (state-dict parity with reference checkpoints)
        axes = tuple(i for i in range(wv.ndim) if i != d)
        g0 = jnp.sqrt(jnp.sum(jnp.square(wv), axis=axes))
    g = Parameter(np.asarray(g0))
    v = Parameter(np.asarray(wv))
    del layer._parameters[name]
    layer.add_parameter(name + "_g", g)
    layer.add_parameter(name + "_v", v)
    bshape = None if d is None else [
        wv.shape[d] if i == d else 1 for i in range(wv.ndim)]

    def hook(lyr, inputs):
        vv = getattr(lyr, name + "_v")
        gg = getattr(lyr, name + "_g")
        if d is None:
            nrm = ((vv * vv).sum()) ** 0.5
        else:
            nrm = _norm_except_t(vv, d)
            gg = gg.reshape(bshape)
        object.__setattr__(lyr, name, vv * (gg / nrm))
        return None

    handle = layer.register_forward_pre_hook(hook)
    layer._weight_norm_state = (name, dim, handle, hook)
    hook(layer, None)  # materialize immediately (parity: eager access)
    return layer


def remove_weight_norm(layer, name="weight"):
    """Fold g*v/||v|| back into a plain parameter (ref
    weight_norm_hook.py remove_weight_norm)."""
    state = getattr(layer, "_weight_norm_state", None)
    if state is None or state[0] != name:
        raise ValueError(f"weight_norm not applied to '{name}'")
    _, dim, handle, hook = state
    hook(layer, None)  # recompute from CURRENT g/v (post-step values)
    w = getattr(layer, name)
    handle.remove()
    del layer._parameters[name + "_g"]
    del layer._parameters[name + "_v"]
    layer.__dict__.pop(name, None)  # drop the hook-computed shadow attr
    layer.add_parameter(name, Parameter(np.asarray(w._value)))
    del layer._weight_norm_state
    return layer


def spectral_norm(layer, name="weight", n_power_iterations=1, eps=1e-12,
                  dim=None):
    """Divide ``layer.<name>`` by its largest singular value, estimated
    by power iteration on persistent u/v buffers (ref
    spectral_norm_hook.py).  The u/v iteration runs untaped (buffers);
    sigma = u^T W v is taped so gradients reach ``<name>_orig``."""
    w = getattr(layer, name)
    wv = w._value
    if dim is None:
        # reference default: dim 1 for Linear / Conv*DTranspose (weight
        # layout [in, out, ...]), else 0 (out-channel-major layouts)
        cls = type(layer).__name__
        dim = 1 if (cls == "Linear" or "Transpose" in cls) \
            and wv.ndim > 1 else 0
    d = dim % wv.ndim
    h = wv.shape[d]
    rng = np.random.default_rng(0)
    u0 = rng.standard_normal(h).astype(np.float32)
    u0 /= max(np.linalg.norm(u0), eps)
    wmat_cols = int(np.prod(wv.shape)) // h
    v0 = rng.standard_normal(wmat_cols).astype(np.float32)
    v0 /= max(np.linalg.norm(v0), eps)
    orig = Parameter(np.asarray(wv))
    del layer._parameters[name]
    layer.add_parameter(name + "_orig", orig)
    layer.register_buffer(name + "_u", Tensor(u0))
    layer.register_buffer(name + "_v", Tensor(v0))

    def _l2(x):
        return x / jnp.maximum(jnp.linalg.norm(x), eps)

    def hook(lyr, inputs):
        worig = getattr(lyr, name + "_orig")
        wraw = worig._value
        wmat = jnp.moveaxis(wraw, d, 0).reshape(h, -1)
        u = getattr(lyr, name + "_u")._value
        v = getattr(lyr, name + "_v")._value
        for _ in range(max(1, n_power_iterations)):
            v = _l2(wmat.T @ u)
            u = _l2(wmat @ v)
        getattr(lyr, name + "_u")._value = u
        getattr(lyr, name + "_v")._value = v
        # taped sigma: sum over W * (u v^T) mapped back to W's layout
        uvT = jnp.moveaxis(
            jnp.outer(u, v).reshape((h,) + tuple(
                s for i, s in enumerate(wraw.shape) if i != d)), 0, d)
        sigma = (worig * Tensor(uvT)).sum()
        object.__setattr__(lyr, name, worig / sigma)
        return None

    handle = layer.register_forward_pre_hook(hook)
    layer._spectral_norm_state = (name, handle)
    hook(layer, None)
    return layer
