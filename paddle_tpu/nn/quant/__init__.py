"""paddle.nn.quant — QAT layer surface (ref: python/paddle/nn/quant/).

The quantization machinery itself lives in paddle_tpu.quantization
(observers, fake-quant rewrite, int8 freeze); this namespace exposes it
under the reference's layer names, plus the FloatFunctionalLayer
wrappers quant-aware graphs use for non-layer math.
"""

from __future__ import annotations

from ...quantization import (  # noqa: F401
    QuantedConv2D,
    QuantedLinear,
    QuantizedConv2DInt8,
    QuantizedLinearInt8,
    _MovingAverageObserver as MovingAverageAbsMaxScale,
)

# reference class names for the trainable fake-quant wrappers
QuantizedConv2D = QuantedConv2D
QuantizedLinear = QuantedLinear

from ..layer.layers import Layer  # noqa: E402


class FloatFunctionalLayer(Layer):
    """Base for functional ops as layers (ref quant/functional_layers.py)
    so activation observers can hook non-layer math."""


def _functional(name):
    class _Op(FloatFunctionalLayer):
        def forward(self, x, y=None, *args, **kwargs):
            import paddle_tpu as paddle

            fn = getattr(paddle, name)
            if y is None:
                return fn(x, *args, **kwargs)
            return fn(x, y, *args, **kwargs)

    _Op.__name__ = name
    return _Op


add = _functional("add")
subtract = _functional("subtract")
multiply = _functional("multiply")
divide = _functional("divide")
reshape = _functional("reshape")
transpose = _functional("transpose")
concat = _functional("concat")
flatten = _functional("flatten")

__all__ = [
    "FloatFunctionalLayer", "QuantizedConv2D", "QuantizedLinear",
    "QuantizedConv2DInt8", "QuantizedLinearInt8",
    "MovingAverageAbsMaxScale", "add", "subtract", "multiply", "divide",
    "reshape", "transpose", "concat", "flatten",
]
