"""Weight initializers (ref: python/paddle/fluid/initializer.py +
python/paddle/nn/initializer/). Each initializer is a callable
`(shape, dtype) -> jax array` drawing from the framework RNG."""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ...core.dtype import to_jax_dtype
from ...framework import random as _random


def _fan_in_out(shape):
    shape = list(shape)
    if len(shape) < 1:
        return 1, 1
    if len(shape) == 1:
        return shape[0], shape[0]
    receptive = int(np.prod(shape[2:])) if len(shape) > 2 else 1
    # conv weights are (out, in, kh, kw); linear weights are (in, out)
    if len(shape) > 2:
        fan_in = shape[1] * receptive
        fan_out = shape[0] * receptive
    else:
        fan_in, fan_out = shape[0], shape[1]
    return fan_in, fan_out


class Initializer:
    def __call__(self, shape, dtype="float32"):
        raise NotImplementedError


class Constant(Initializer):
    def __init__(self, value=0.0):
        self.value = value

    def __call__(self, shape, dtype="float32"):
        return jnp.full(tuple(shape), self.value, to_jax_dtype(dtype))


class Normal(Initializer):
    def __init__(self, mean=0.0, std=1.0):
        self.mean, self.std = mean, std

    def __call__(self, shape, dtype="float32"):
        key = _random.next_key()
        return self.mean + self.std * jax.random.normal(
            key, tuple(shape), to_jax_dtype(dtype))


class TruncatedNormal(Initializer):
    def __init__(self, mean=0.0, std=1.0):
        self.mean, self.std = mean, std

    def __call__(self, shape, dtype="float32"):
        key = _random.next_key()
        return self.mean + self.std * jax.random.truncated_normal(
            key, -2.0, 2.0, tuple(shape), to_jax_dtype(dtype))


class Uniform(Initializer):
    def __init__(self, low=-1.0, high=1.0):
        self.low, self.high = low, high

    def __call__(self, shape, dtype="float32"):
        key = _random.next_key()
        return jax.random.uniform(key, tuple(shape), to_jax_dtype(dtype),
                                  self.low, self.high)


class XavierNormal(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0):
        self._fan_in, self._fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype="float32"):
        fi, fo = _fan_in_out(shape)
        fi = self._fan_in if self._fan_in is not None else fi
        fo = self._fan_out if self._fan_out is not None else fo
        std = self.gain * math.sqrt(2.0 / (fi + fo))
        key = _random.next_key()
        return std * jax.random.normal(key, tuple(shape), to_jax_dtype(dtype))


class XavierUniform(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0):
        self._fan_in, self._fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype="float32"):
        fi, fo = _fan_in_out(shape)
        fi = self._fan_in if self._fan_in is not None else fi
        fo = self._fan_out if self._fan_out is not None else fo
        limit = self.gain * math.sqrt(6.0 / (fi + fo))
        key = _random.next_key()
        return jax.random.uniform(key, tuple(shape), to_jax_dtype(dtype),
                                  -limit, limit)


class KaimingNormal(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self._fan_in = fan_in
        self.negative_slope = negative_slope
        self.nonlinearity = nonlinearity

    def __call__(self, shape, dtype="float32"):
        fi, _ = _fan_in_out(shape)
        fi = self._fan_in if self._fan_in is not None else fi
        gain = math.sqrt(2.0 / (1 + self.negative_slope ** 2)) \
            if self.nonlinearity in ("relu", "leaky_relu") else 1.0
        std = gain / math.sqrt(fi)
        key = _random.next_key()
        return std * jax.random.normal(key, tuple(shape), to_jax_dtype(dtype))


class KaimingUniform(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self._fan_in = fan_in
        self.negative_slope = negative_slope
        self.nonlinearity = nonlinearity

    def __call__(self, shape, dtype="float32"):
        fi, _ = _fan_in_out(shape)
        fi = self._fan_in if self._fan_in is not None else fi
        gain = math.sqrt(2.0 / (1 + self.negative_slope ** 2)) \
            if self.nonlinearity in ("relu", "leaky_relu") else 1.0
        limit = gain * math.sqrt(3.0 / fi)
        key = _random.next_key()
        return jax.random.uniform(key, tuple(shape), to_jax_dtype(dtype),
                                  -limit, limit)


class Assign(Initializer):
    def __init__(self, value):
        self.value = value

    def __call__(self, shape, dtype="float32"):
        arr = jnp.asarray(np.asarray(self.value), to_jax_dtype(dtype))
        if tuple(arr.shape) != tuple(shape):
            arr = arr.reshape(tuple(shape))
        return arr


class Orthogonal(Initializer):
    def __init__(self, gain=1.0):
        self.gain = gain

    def __call__(self, shape, dtype="float32"):
        key = _random.next_key()
        return self.gain * jax.nn.initializers.orthogonal()(
            key, tuple(shape), to_jax_dtype(dtype))


class Dirac(Initializer):
    def __init__(self, groups=1):
        self.groups = groups

    def __call__(self, shape, dtype="float32"):
        out = np.zeros(shape, dtype=np.float32)
        oc, ic = shape[0], shape[1]
        centers = [s // 2 for s in shape[2:]]
        for i in range(min(oc, ic * self.groups)):
            idx = (i, i % ic) + tuple(centers)
            out[idx] = 1.0
        return jnp.asarray(out, to_jax_dtype(dtype))


# paddle aliases
constant = Constant
normal = Normal
uniform = Uniform
