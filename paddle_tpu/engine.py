"""Functional engine: the compiled execution path.

TPU-native replacement for the reference's static-graph Executor +
ParallelExecutor (paddle/fluid/framework/executor.cc, parallel_executor.cc)
and the Fleet meta-optimizer program rewrites: instead of interpreting a
ProgramDesc op-by-op, the eager model code is traced *functionally* (the
same nn.Layer forward runs with parameter values swapped for tracers) and
compiled by XLA into one program per train/eval step. Parallelism is
expressed with jax.sharding (GSPMD) specs attached to parameters
(`Parameter.param_spec`) and optimizer-state sharding rules (ZeRO).

Autograd note: inside the functional trace the eager tape is bypassed
(jax.grad differentiates the traced computation directly); `detach()` /
frozen parameters cut gradients via lax.stop_gradient / constant capture,
matching dygraph semantics.
"""

from __future__ import annotations

import contextlib
from collections import OrderedDict

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from .core.tensor import Parameter, Tensor
from .framework import random as _random


# ---------------------------------------------------------------------------
# functional_call: run a Layer's forward with externally-supplied params
# ---------------------------------------------------------------------------


@contextlib.contextmanager
def _swap_state(layer, values: dict):
    """Temporarily replace parameter/buffer backing arrays with `values`.
    Yields the state-dict so callers can read (possibly traced) post-call
    buffer values before restoration."""
    sd = layer.state_dict()
    saved = {}
    for name, arr in values.items():
        t = sd.get(name)
        if t is None:
            continue
        saved[name] = t._value
        t._value = arr
    try:
        yield sd
    finally:
        for name, old in saved.items():
            sd[name]._value = old


def state_values(layer):
    """OrderedDict name -> backing array for all params + persistable
    buffers."""
    return OrderedDict((k, v._value) for k, v in layer.state_dict().items())


def param_values(layer):
    return OrderedDict(
        (k, v._value) for k, v in layer.state_dict().items()
        if isinstance(v, Parameter) and not v.stop_gradient)


def buffer_values(layer):
    params = set()
    for k, v in layer.state_dict().items():
        if isinstance(v, Parameter) and not v.stop_gradient:
            params.add(k)
    return OrderedDict(
        (k, v._value) for k, v in layer.state_dict().items()
        if k not in params)


def param_specs(layer):
    """GSPMD PartitionSpecs per trainable param name (None = replicated)."""
    return OrderedDict(
        (k, getattr(v, "param_spec", None))
        for k, v in layer.state_dict().items()
        if isinstance(v, Parameter) and not v.stop_gradient)


def _unwrap(out):
    return jax.tree.map(
        lambda t: t._value if isinstance(t, Tensor) else t, out,
        is_leaf=lambda t: isinstance(t, Tensor))


def functional_call(layer, values, *args, capture_buffers=False, **kwargs):
    """Run `layer(*args)` with parameters/buffers taken from `values`
    (dict name->array). Differentiable wrt `values` under jax traces."""
    from .core.config import no_tape

    wrapped = [Tensor(a) if not isinstance(a, Tensor) else a for a in args]
    with no_tape(), _swap_state(layer, values) as sd:
        out = layer(*wrapped, **kwargs)
        if capture_buffers:
            post = OrderedDict(
                (k, sd[k]._value) for k in values if k in sd)
            return _unwrap(out), post
    return _unwrap(out)


# ---------------------------------------------------------------------------
# train step builder
# ---------------------------------------------------------------------------


class TrainState:
    """Bundles params / opt state / buffers for the compiled path."""

    def __init__(self, params, opt_state, buffers, step=0):
        self.params = params
        self.opt_state = opt_state
        self.buffers = buffers
        self.step = step


def init_train_state(layer, optimizer):
    params = dict(param_values(layer))
    buffers = dict(buffer_values(layer))
    opt_state = {k: optimizer._init_state(v) for k, v in params.items()}
    return TrainState(params, opt_state, buffers)


def write_back(layer, state: TrainState):
    """Copy compiled-state arrays back into the eager Layer."""
    sd = layer.state_dict()
    for k, v in state.params.items():
        if k in sd:
            sd[k]._value = v
    for k, v in state.buffers.items():
        if k in sd:
            sd[k]._value = v


def build_shardings(layer, optimizer, mesh, *, dp_axis="dp",
                    sharding_axis=None, zero_stage=0):
    """Construct NamedShardings for params / opt state from param_specs.

    ZeRO (`sharding` in fleet terms): stage>=1 shards optimizer moments
    along `sharding_axis` on the first divisible dimension — the GSPMD
    equivalent of DygraphShardingOptimizer's rank-wise partition
    (ref: fleet/meta_optimizers/dygraph_optimizer/
    dygraph_sharding_optimizer.py:27).
    """
    specs = param_specs(layer)

    def param_sharding(name, arr):
        spec = specs.get(name)
        return NamedSharding(mesh, spec if spec is not None else P())

    warned = set()  # once per param name across state leaves AND grads

    def opt_leaf_sharding(name, arr):
        spec = specs.get(name)
        if spec is not None and any(s is not None for s in spec):
            return NamedSharding(mesh, spec) if len(spec) == arr.ndim \
                else NamedSharding(mesh, P())
        if zero_stage >= 1 and sharding_axis is not None and arr.ndim >= 1:
            axis_size = mesh.shape[sharding_axis]
            if arr.shape[0] % axis_size == 0 and arr.shape[0] >= axis_size:
                return NamedSharding(
                    mesh, P(sharding_axis, *([None] * (arr.ndim - 1))))
            if arr.size >= axis_size and name not in warned:
                warned.add(name)
                import warnings

                warnings.warn(
                    f"ZeRO: state/gradient for '{name}' (shape "
                    f"{arr.shape}) is not divisible by sharding degree "
                    f"{axis_size} on dim 0; replicating this parameter",
                    stacklevel=3)
        return NamedSharding(mesh, P())

    return param_sharding, opt_leaf_sharding


# reserved buffer slots for in-graph dynamic loss scaling
LOSS_SCALE_KEY = "__loss_scale__"
GOOD_STEPS_KEY = "__loss_scale_good_steps__"
BAD_STEPS_KEY = "__loss_scale_bad_steps__"

# paddle GradScaler defaults (ref python/paddle/amp/grad_scaler.py)
DEFAULT_SCALE_CONFIG = dict(
    init_loss_scaling=2.0 ** 15, incr_ratio=2.0, decr_ratio=0.5,
    incr_every_n_steps=1000, decr_every_n_nan_or_inf=2)


def make_train_step(layer, loss_fn, optimizer, *, grad_clip=None,
                    donate=True, mesh=None, batch_spec=None, zero_stage=0,
                    sharding_axis=None, loss_scale=None):
    """Build a jitted step:
    (params, buffers, opt_state, batch, lr, key) ->
        (loss, params, buffers, opt_state)

    batch: dict with 'inputs' (tuple of arrays) and optional 'labels'
    (tuple). loss_fn(outputs, *labels) -> scalar Tensor.
    """
    grad_clip = grad_clip if grad_clip is not None else \
        getattr(optimizer, "_grad_clip", None)
    # per-param decay/lr-mult metadata baked in as compile-time constants
    # (mirrors eager Optimizer._preprocess; ADVICE r1 fix)
    _sd = layer.state_dict()

    def loss_of(params, buffers, batch, key):
        with _random.rng_scope(key):
            inputs = batch["inputs"]
            if not isinstance(inputs, (list, tuple)):
                inputs = (inputs,)
            values = {**buffers, **params}
            out, post = functional_call(layer, values, *inputs,
                                        capture_buffers=True)
            labels = batch.get("labels", ())
            loss = loss_fn(jax.tree.map(Tensor, out)
                           if not isinstance(out, Tensor) else out,
                           *(Tensor(l) for l in labels))
            loss_v = loss._value if isinstance(loss, Tensor) else loss
            new_buffers = {k: post[k] for k in buffers}
            return loss_v.astype(jnp.float32), new_buffers

    # single build of the sharding rules, shared by the ZeRO-2 gradient
    # constraint and the jit in/out shardings below
    param_sh = opt_sh = None
    if mesh is not None:
        param_sh, opt_sh = build_shardings(
            layer, optimizer, mesh, zero_stage=zero_stage,
            sharding_axis=sharding_axis)

    # ZeRO-2: constrain gradients to the moment sharding so GSPMD lowers
    # the dp grad sum into reduce-scatter feeding sharded updates
    # (ref fleet/meta_optimizers/sharding_optimizer.py grad sharding)
    grad_constraint = None
    if zero_stage >= 2 and mesh is not None and sharding_axis is not None:
        def grad_constraint(grads):
            return {k: jax.lax.with_sharding_constraint(
                g, opt_sh(k, g)) for k, g in grads.items()}

    # In-graph dynamic loss scaling (fp16-compat mode; ref
    # operators/amp/check_finite_and_unscale_op.cc +
    # update_loss_scaling_op.cc, python/paddle/amp/grad_scaler.py
    # defaults). State lives in reserved buffer slots; the scale decays
    # after `decr_every_n_nan_or_inf` CONSECUTIVE non-finite steps and
    # grows after `incr_every_n_steps` consecutive finite ones.
    # `loss_scale` may be: None | float (static) | "dynamic" | dict of
    # GradScaler knobs.
    scale_cfg = dict(DEFAULT_SCALE_CONFIG)
    if isinstance(loss_scale, dict):
        scale_cfg.update(loss_scale)
        dynamic_scale = True
    else:
        dynamic_scale = loss_scale == "dynamic"
    static_scale = float(loss_scale) if (
        loss_scale is not None and not dynamic_scale
        and not isinstance(loss_scale, dict)) else None

    def step_fn(params, buffers, opt_state, batch, lr, key):
        if dynamic_scale:
            scale = buffers[LOSS_SCALE_KEY]
            good = buffers[GOOD_STEPS_KEY]
            bad = buffers[BAD_STEPS_KEY]
        elif static_scale is not None:
            scale = jnp.asarray(static_scale, jnp.float32)
        model_buffers = {k: v for k, v in buffers.items()
                         if k not in (LOSS_SCALE_KEY, GOOD_STEPS_KEY,
                                      BAD_STEPS_KEY)}

        def scaled_loss(params, model_buffers, batch, key):
            loss, nb = loss_of(params, model_buffers, batch, key)
            if loss_scale is not None:
                return loss * scale, (loss, nb)
            return loss, (loss, nb)

        (_, (loss, new_buffers)), grads = jax.value_and_grad(
            scaled_loss, has_aux=True)(params, model_buffers, batch, key)
        if loss_scale is not None:
            grads = jax.tree.map(lambda g: g / scale, grads)
            # finiteness is judged on the raw unscaled grads BEFORE
            # decay/clip — clippers like ClipGradByValue would map inf to
            # finite values and hide the overflow (ref
            # check_finite_and_unscale_op: the check precedes clipping)
            finite = jnp.asarray(True)
            for g in jax.tree.leaves(grads):
                finite = finite & jnp.isfinite(g).all()
        if grad_constraint is not None:
            grads = grad_constraint(grads)
        metas = optimizer.param_metas_for(params, _sd)
        # eager _preprocess order: coupled decay first, then clip
        grads = optimizer.decay_gradients_tree(params, grads, metas)
        if grad_clip is not None:
            grads = grad_clip._clip_fn(grads)
        new_params, new_opt = optimizer.apply_gradients_tree(
            params, grads, opt_state, lr, metas=metas)
        if loss_scale is not None:
            # both static and dynamic scaling skip non-finite steps
            # (paddle GradScaler found_inf semantics)
            pick = lambda new, old: jax.tree.map(  # noqa: E731
                lambda n, o: jnp.where(finite, n, o), new, old)
            new_params = pick(new_params, params)
            new_opt = pick(new_opt, opt_state)
            new_buffers = dict(new_buffers)
        if dynamic_scale:
            good_next = jnp.where(finite, good + 1, 0)
            bad_next = jnp.where(finite, 0, bad + 1)
            grow = finite & (good_next >= scale_cfg["incr_every_n_steps"])
            shrink = (~finite) & (
                bad_next >= scale_cfg["decr_every_n_nan_or_inf"])
            new_scale = jnp.where(
                grow, scale * scale_cfg["incr_ratio"],
                jnp.where(shrink, scale * scale_cfg["decr_ratio"], scale))
            new_buffers[LOSS_SCALE_KEY] = new_scale
            new_buffers[GOOD_STEPS_KEY] = jnp.where(grow, 0, good_next)
            new_buffers[BAD_STEPS_KEY] = jnp.where(shrink, 0, bad_next)
        return loss, new_params, new_buffers, new_opt

    in_shardings = None
    out_shardings = None
    if mesh is not None:
        params0 = param_values(layer)
        p_sh = {k: param_sh(k, v) for k, v in params0.items()}
        buf_sh = {k: NamedSharding(mesh, P())
                  for k in buffer_values(layer)}
        if loss_scale == "dynamic" or isinstance(loss_scale, dict):
            buf_sh[LOSS_SCALE_KEY] = NamedSharding(mesh, P())
            buf_sh[GOOD_STEPS_KEY] = NamedSharding(mesh, P())
            buf_sh[BAD_STEPS_KEY] = NamedSharding(mesh, P())
        opt0 = {k: optimizer._init_state(v) for k, v in params0.items()}
        o_sh = {k: jax.tree.map(lambda a, kk=k: opt_sh(kk, a), st)
                for k, st in opt0.items()}
        repl = NamedSharding(mesh, P())
        b_sh = batch_spec if batch_spec is not None else repl
        in_shardings = (p_sh, buf_sh, o_sh, b_sh, repl, repl)
        out_shardings = (repl, p_sh, buf_sh, o_sh)
    donate_argnums = (0, 1, 2) if donate else ()
    if mesh is not None:
        jitted = jax.jit(step_fn, donate_argnums=donate_argnums,
                         in_shardings=in_shardings,
                         out_shardings=out_shardings)
    else:
        jitted = jax.jit(step_fn, donate_argnums=donate_argnums)
    # the un-jitted step is re-usable inside larger traced loops (bench
    # scans N steps in one program to amortise dispatch latency)
    jitted._raw_step_fn = step_fn
    return jitted


def make_eval_step(layer, mesh=None):
    def eval_fn(values, *inputs):
        was_training = layer.training
        layer.eval()
        try:
            return functional_call(layer, values, *inputs)
        finally:
            if was_training:
                layer.train()

    return jax.jit(eval_fn)


class Engine:
    """Drives compiled training of an eager Layer: the Paddle user keeps
    the dygraph API (model, optimizer, loss), this turns each step into one
    XLA program. Used by hapi.Model.prepare, bench, and the distributed
    trainers."""

    def __init__(self, layer, optimizer, loss_fn, grad_clip=None, mesh=None,
                 batch_spec=None, zero_stage=0, sharding_axis=None,
                 loss_scale=None):
        self.layer = layer
        self.optimizer = optimizer
        self.loss_fn = loss_fn
        self.mesh = mesh
        self.batch_spec = batch_spec
        self.zero_stage = zero_stage
        self.sharding_axis = sharding_axis
        self.loss_scale = loss_scale
        self.state = init_train_state(layer, optimizer)
        if loss_scale == "dynamic" or isinstance(loss_scale, dict):
            # in-graph dynamic loss scaling state (fp16-compat mode)
            cfg = dict(DEFAULT_SCALE_CONFIG)
            if isinstance(loss_scale, dict):
                cfg.update(loss_scale)
            self.state.buffers[LOSS_SCALE_KEY] = jnp.asarray(
                float(cfg["init_loss_scaling"]), jnp.float32)
            self.state.buffers[GOOD_STEPS_KEY] = jnp.asarray(0, jnp.int32)
            self.state.buffers[BAD_STEPS_KEY] = jnp.asarray(0, jnp.int32)
        self._step_fn = None
        self._grad_clip = grad_clip

    def _build(self):
        self._step_fn = make_train_step(
            self.layer, self.loss_fn, self.optimizer,
            grad_clip=self._grad_clip, mesh=self.mesh,
            batch_spec=self.batch_spec, zero_stage=self.zero_stage,
            sharding_axis=self.sharding_axis, loss_scale=self.loss_scale)

    @staticmethod
    def _arrs(ts):
        return tuple(t._value if isinstance(t, Tensor) else jnp.asarray(t)
                     for t in ts)

    def train_batch(self, inputs, labels=()):
        if self._step_fn is None:
            self._build()
        if not isinstance(inputs, (list, tuple)):
            inputs = (inputs,)
        if not isinstance(labels, (list, tuple)):
            labels = (labels,)
        batch = {"inputs": self._arrs(inputs), "labels": self._arrs(labels)}
        key = _random.default_generator.next_key()
        lr = jnp.asarray(self.optimizer.get_lr(), jnp.float32)
        loss, self.state.params, self.state.buffers, self.state.opt_state = \
            self._step_fn(self.state.params, self.state.buffers,
                          self.state.opt_state, batch, lr, key)
        self.state.step += 1
        return Tensor(loss)

    def sync_to_layer(self):
        write_back(self.layer, self.state)
