"""Functional engine: the compiled execution path.

TPU-native replacement for the reference's static-graph Executor +
ParallelExecutor (paddle/fluid/framework/executor.cc, parallel_executor.cc)
and the Fleet meta-optimizer program rewrites: instead of interpreting a
ProgramDesc op-by-op, the eager model code is traced *functionally* (the
same nn.Layer forward runs with parameter values swapped for tracers) and
compiled by XLA into one program per train/eval step. Parallelism is
expressed with jax.sharding (GSPMD) specs attached to parameters
(`Parameter.param_spec`) and optimizer-state sharding rules (ZeRO).

Autograd note: inside the functional trace the eager tape is bypassed
(jax.grad differentiates the traced computation directly); `detach()` /
frozen parameters cut gradients via lax.stop_gradient / constant capture,
matching dygraph semantics.
"""

from __future__ import annotations

import contextlib
import time
from collections import OrderedDict

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from .core.tensor import Parameter, Tensor
from .framework import random as _random


# ---------------------------------------------------------------------------
# functional_call: run a Layer's forward with externally-supplied params
# ---------------------------------------------------------------------------


@contextlib.contextmanager
def _swap_state(layer, values: dict):
    """Temporarily replace parameter/buffer backing arrays with `values`.
    Yields the state-dict so callers can read (possibly traced) post-call
    buffer values before restoration."""
    sd = layer.state_dict()
    saved = {}
    for name, arr in values.items():
        t = sd.get(name)
        if t is None:
            continue
        saved[name] = t._value
        t._value = arr
    try:
        yield sd
    finally:
        for name, old in saved.items():
            sd[name]._value = old


def state_values(layer):
    """OrderedDict name -> backing array for all params + persistable
    buffers."""
    return OrderedDict((k, v._value) for k, v in layer.state_dict().items())


def param_values(layer):
    return OrderedDict(
        (k, v._value) for k, v in layer.state_dict().items()
        if isinstance(v, Parameter) and not v.stop_gradient)


def buffer_values(layer):
    params = set()
    for k, v in layer.state_dict().items():
        if isinstance(v, Parameter) and not v.stop_gradient:
            params.add(k)
    return OrderedDict(
        (k, v._value) for k, v in layer.state_dict().items()
        if k not in params)


def param_specs(layer):
    """GSPMD PartitionSpecs per trainable param name (None = replicated)."""
    return OrderedDict(
        (k, getattr(v, "param_spec", None))
        for k, v in layer.state_dict().items()
        if isinstance(v, Parameter) and not v.stop_gradient)


def _unwrap(out):
    return jax.tree.map(
        lambda t: t._value if isinstance(t, Tensor) else t, out,
        is_leaf=lambda t: isinstance(t, Tensor))


def functional_call(layer, values, *args, capture_buffers=False, **kwargs):
    """Run `layer(*args)` with parameters/buffers taken from `values`
    (dict name->array). Differentiable wrt `values` under jax traces."""
    from .core.config import no_tape

    wrapped = [Tensor(a) if not isinstance(a, Tensor) else a for a in args]
    with no_tape(), _swap_state(layer, values) as sd:
        out = layer(*wrapped, **kwargs)
        if capture_buffers:
            post = OrderedDict(
                (k, sd[k]._value) for k in values if k in sd)
            return _unwrap(out), post
    return _unwrap(out)


def functional_apply(layer, values, fn, mesh=None):
    """Run an arbitrary `fn(layer)` with parameters/buffers taken from
    `values` (dict name->array), tape off — the inference analogue of
    functional_call for callers that need more than one plain forward
    (e.g. the serving decode step: cached GPT forward + lm-head logits
    inside one jitted function). Returns fn's result with Tensors
    unwrapped to arrays.

    When `mesh` is given the call runs inside `ops.overlap.region(mesh)`
    so RowParallelLinear matmuls route through the ring collective-matmul
    kernels when `FLAGS_mp_overlap` is on and the mesh qualifies — the
    same silent-guard contract as training (unsupported mesh or
    non-divisible shapes fall back to plain GSPMD)."""
    from .core.config import no_tape
    from .ops import overlap

    region = (overlap.region(mesh) if mesh is not None
              else contextlib.nullcontext())
    with region, no_tape(), _swap_state(layer, values):
        out = fn(layer)
    return _unwrap(out)


# ---------------------------------------------------------------------------
# train step builder
# ---------------------------------------------------------------------------


class TrainState:
    """Bundles params / opt state / buffers for the compiled path."""

    def __init__(self, params, opt_state, buffers, step=0):
        self.params = params
        self.opt_state = opt_state
        self.buffers = buffers
        self.step = step


def init_train_state(layer, optimizer, *, opt_state_mesh_host=None):
    """Build the compiled-path state.  `opt_state_mesh_host`: a mesh —
    park each parameter's freshly-built optimizer state in pinned host
    memory immediately, so the whole-tree state (2x params for Adam)
    never coexists on device.  For billion-parameter offload configs
    that transient footprint is itself the OOM; the per-param peak here
    is one parameter's state."""
    params = dict(param_values(layer))
    buffers = dict(buffer_values(layer))
    host_sh = None
    if opt_state_mesh_host is not None:
        kind = _host_memory_kind(opt_state_mesh_host)
        if kind is not None:
            host_sh = NamedSharding(opt_state_mesh_host, P(),
                                    memory_kind=kind)
    opt_state = {}
    for k, v in params.items():
        st = optimizer._init_state(v)
        if host_sh is not None:
            st = jax.device_put(st, host_sh)
            jax.block_until_ready(st)  # free the device copy promptly
        opt_state[k] = st
    return TrainState(params, opt_state, buffers)


def write_back(layer, state: TrainState):
    """Copy compiled-state arrays back into the eager Layer."""
    sd = layer.state_dict()
    for k, v in state.params.items():
        if k in sd:
            sd[k]._value = v
    for k, v in state.buffers.items():
        if k in sd:
            sd[k]._value = v


def host_offload_shardings(mesh, dev_sh_tree):
    """(device, host) sharding trees for at-rest optimizer-state offload
    (ref sharding/offload_helper.py), or None when the backend has no
    host memory space. Shared by Engine and HybridParallelEngine."""
    kind = _host_memory_kind(mesh)
    if kind is None:
        return None
    host = jax.tree.map(
        lambda sh: NamedSharding(mesh, sh.spec, memory_kind=kind),
        dev_sh_tree, is_leaf=lambda x: isinstance(x, NamedSharding))
    return dev_sh_tree, host


def _host_memory_kind(mesh):
    """'pinned_host' when the backend exposes it (TPU + recent CPU), else
    None — offload degrades to device memory with a warning."""
    try:
        dev = next(iter(mesh.devices.flat))
        kinds = {m.kind for m in dev.addressable_memories()}
        if "pinned_host" in kinds:
            return "pinned_host"
    except Exception:  # noqa: BLE001 — older jax without memories API
        pass
    import warnings

    warnings.warn("optimizer-state offload requested but the backend has "
                  "no pinned_host memory space; keeping state on device")
    return None


def build_shardings(layer, optimizer, mesh, *, dp_axis="dp",
                    sharding_axis=None, zero_stage=0):
    """Construct NamedShardings for params / opt state from param_specs.

    ZeRO (`sharding` in fleet terms, ref fleet/meta_optimizers/sharding_
    optimizer.py + dygraph_sharding_optimizer.py:27):
      stage>=1  shard optimizer moments along `sharding_axis` on the
                first divisible dim (GSPMD partitions the update)
      stage>=3  additionally shard the PARAMETERS the same way — XLA
                all-gathers them where the forward needs full values and
                frees the gathered copies after use (the stage-3
                working-set behaviour)
    """
    specs = param_specs(layer)

    def _zero_spec(arr):
        """First-divisible-dim sharding spec, or None."""
        if sharding_axis is None or arr.ndim < 1:
            return None
        axis_size = mesh.shape[sharding_axis]
        if arr.shape[0] % axis_size == 0 and arr.shape[0] >= axis_size:
            return P(sharding_axis, *([None] * (arr.ndim - 1)))
        return None

    def param_sharding(name, arr):
        spec = specs.get(name)
        if spec is not None:
            return NamedSharding(mesh, spec)
        if zero_stage >= 3:
            zspec = _zero_spec(arr)
            if zspec is not None:
                return NamedSharding(mesh, zspec)
        return NamedSharding(mesh, P())

    warned = set()  # once per param name across state leaves AND grads

    def opt_leaf_sharding(name, arr):
        spec = specs.get(name)
        if spec is not None and any(s is not None for s in spec):
            return NamedSharding(mesh, spec) if len(spec) == arr.ndim \
                else NamedSharding(mesh, P())
        if zero_stage >= 1 and sharding_axis is not None and arr.ndim >= 1:
            zspec = _zero_spec(arr)
            if zspec is not None:
                return NamedSharding(mesh, zspec)
            axis_size = mesh.shape[sharding_axis]
            if arr.size >= axis_size and name not in warned:
                warned.add(name)
                import warnings

                warnings.warn(
                    f"ZeRO: state/gradient for '{name}' (shape "
                    f"{arr.shape}) is not divisible by sharding degree "
                    f"{axis_size} on dim 0; replicating this parameter",
                    stacklevel=3)
        return NamedSharding(mesh, P())

    return param_sharding, opt_leaf_sharding


# reserved buffer slots for in-graph dynamic loss scaling
LOSS_SCALE_KEY = "__loss_scale__"
GOOD_STEPS_KEY = "__loss_scale_good_steps__"
BAD_STEPS_KEY = "__loss_scale_bad_steps__"
# reserved buffer slot for the in-graph anomaly guard: consecutive
# non-finite-step counter (int32, lives with the other step state so it
# is donated/checkpointed like everything else)
ANOMALY_BAD_STEPS_KEY = "__anomaly_bad_steps__"
# reserved buffer slot for FLAGS_record_grad_norm: global gradient norm
# (pre-clip) computed inside the compiled step, read lazily by the
# flight recorder — no extra device pass, no per-step host sync
GRAD_NORM_KEY = "__grad_norm__"
# reserved buffer slot for FLAGS_lowp_matmul delayed scaling: the
# quantization.scaling.ScaleState pytree rides the buffer carry so the
# per-tensor amax history/scales update in-graph — donated with the rest
# of the step state, never a host sync or retrace
LOWP_SCALE_KEY = "__lowp_scale__"
_RESERVED_BUFFER_KEYS = (LOSS_SCALE_KEY, GOOD_STEPS_KEY, BAD_STEPS_KEY,
                         ANOMALY_BAD_STEPS_KEY, GRAD_NORM_KEY,
                         LOWP_SCALE_KEY)

# paddle GradScaler defaults (ref python/paddle/amp/grad_scaler.py)
DEFAULT_SCALE_CONFIG = dict(
    init_loss_scaling=2.0 ** 15, incr_ratio=2.0, decr_ratio=0.5,
    incr_every_n_steps=1000, decr_every_n_nan_or_inf=2)


def make_train_step(layer, loss_fn, optimizer, *, grad_clip=None,
                    donate=True, mesh=None, batch_spec=None, zero_stage=0,
                    sharding_axis=None, loss_scale=None, comm_dtype=None,
                    anomaly_guard=False, record_grad_norm=None, lowp=None):
    """Build a jitted step:
    (params, buffers, opt_state, batch, lr, key) ->
        (loss, params, buffers, opt_state)

    batch: dict with 'inputs' (tuple of arrays) and optional 'labels'
    (tuple). loss_fn(outputs, *labels) -> scalar Tensor.

    anomaly_guard: replaces FLAGS_check_nan_inf's per-op eager scan for
    compiled training (ref nan_inf_utils_detail.cu). One fused in-graph
    finiteness bit over loss + unscaled grads per step; a bad step skips
    the parameter/optimizer/buffer update entirely (jnp.where select, no
    host sync, no recompilation) and increments the
    ANOMALY_BAD_STEPS_KEY buffer, which the Engine reads at step
    boundaries to trigger checkpoint rollback.

    comm_dtype ('bfloat16'/'float16'): the fp16_allreduce strategy (ref
    fleet/meta_optimizers/fp16_allreduce_optimizer.py). Under GSPMD the
    gradient all-reduce is fused into the backward matmuls, so reduced-
    precision communication means computing those grads in the reduced
    dtype: the step runs under O2 autocast of `comm_dtype` while params
    and optimizer state stay fp32 (master weights).
    """
    if record_grad_norm is None:
        from .framework.flags import flag as _flag

        record_grad_norm = _flag("FLAGS_record_grad_norm")
    grad_clip = grad_clip if grad_clip is not None else \
        getattr(optimizer, "_grad_clip", None)
    # per-param decay/lr-mult metadata baked in as compile-time constants
    # (mirrors eager Optimizer._preprocess; ADVICE r1 fix)
    _sd = layer.state_dict()
    # ASP n:m masks re-applied in-graph after every update, so pruned
    # weights stay zero on the compiled path too (ref asp_optimizer.py)
    from .incubate.asp import apply_masks_tree as _asp_apply, \
        masks_for as _asp_masks_for

    asp_masks = _asp_masks_for(layer)

    from .ops import overlap as _overlap
    from .ops import lowp as _lowp

    _seq_parallel = _overlap.model_sequence_parallel(layer)
    if lowp is None:
        lowp = _lowp.mode() != "off"

    def loss_of(params, buffers, batch, key, lowp_state=None):
        if comm_dtype is not None:
            from .amp import auto_cast

            amp_ctx = auto_cast(enable=True, level="O2", dtype=comm_dtype)
        else:
            amp_ctx = contextlib.nullcontext()
        # mp collective-matmul overlap (trace-time no-op unless
        # FLAGS_mp_overlap is on and the mesh is pure dp x mp)
        # lowp delayed scaling: bind the ScaleState carry to this
        # trace's quantized matmuls (trace-order slots); the updated
        # state leaves through the aux return, never a Python cell
        with _random.rng_scope(key), amp_ctx, _overlap.region(
                mesh, sequence_parallel=_seq_parallel), \
                _lowp.scale_region(lowp_state) as lowp_rec:
            inputs = batch["inputs"]
            if not isinstance(inputs, (list, tuple)):
                inputs = (inputs,)
            values = {**buffers, **params}
            out, post = functional_call(layer, values, *inputs,
                                        capture_buffers=True)
            labels = batch.get("labels", ())
            loss = loss_fn(jax.tree.map(Tensor, out)
                           if not isinstance(out, Tensor) else out,
                           *(Tensor(l) for l in labels))
            loss_v = loss._value if isinstance(loss, Tensor) else loss
            new_buffers = {k: post[k] for k in buffers}
            if lowp_rec is not None:
                new_buffers[LOWP_SCALE_KEY] = lowp_rec.updated()
            return loss_v.astype(jnp.float32), new_buffers

    # single build of the sharding rules, shared by the ZeRO-2 gradient
    # constraint and the jit in/out shardings below
    param_sh = opt_sh = None
    if mesh is not None:
        param_sh, opt_sh = build_shardings(
            layer, optimizer, mesh, zero_stage=zero_stage,
            sharding_axis=sharding_axis)

    # ZeRO-2: constrain gradients to the moment sharding so GSPMD lowers
    # the dp grad sum into reduce-scatter feeding sharded updates
    # (ref fleet/meta_optimizers/sharding_optimizer.py grad sharding)
    grad_constraint = None
    if zero_stage >= 2 and mesh is not None and sharding_axis is not None:
        def grad_constraint(grads):
            return {k: jax.lax.with_sharding_constraint(
                g, opt_sh(k, g)) for k, g in grads.items()}

    # In-graph dynamic loss scaling (fp16-compat mode; ref
    # operators/amp/check_finite_and_unscale_op.cc +
    # update_loss_scaling_op.cc, python/paddle/amp/grad_scaler.py
    # defaults). State lives in reserved buffer slots; the scale decays
    # after `decr_every_n_nan_or_inf` CONSECUTIVE non-finite steps and
    # grows after `incr_every_n_steps` consecutive finite ones.
    # `loss_scale` may be: None | float (static) | "dynamic" | dict of
    # GradScaler knobs.
    scale_cfg = dict(DEFAULT_SCALE_CONFIG)
    if isinstance(loss_scale, dict):
        scale_cfg.update(loss_scale)
        dynamic_scale = True
    else:
        dynamic_scale = loss_scale == "dynamic"
    static_scale = float(loss_scale) if (
        loss_scale is not None and not dynamic_scale
        and not isinstance(loss_scale, dict)) else None

    def _step_impl(params, buffers, opt_state, batch, lr, key):
        # trace-time: this body runs exactly once per compilation, so
        # one recorded event == one compile of the step program
        from . import observe as _observe

        _observe.record_compile(
            "train_step", signature=_observe.signature_of(batch))
        if dynamic_scale:
            scale = buffers[LOSS_SCALE_KEY]
            good = buffers[GOOD_STEPS_KEY]
            bad = buffers[BAD_STEPS_KEY]
        elif static_scale is not None:
            scale = jnp.asarray(static_scale, jnp.float32)
        anomaly_prev = buffers.get(ANOMALY_BAD_STEPS_KEY)
        lowp_prev = buffers.get(LOWP_SCALE_KEY)
        model_buffers = {k: v for k, v in buffers.items()
                         if k not in _RESERVED_BUFFER_KEYS}

        def scaled_loss(params, model_buffers, batch, key):
            loss, nb = loss_of(params, model_buffers, batch, key,
                               lowp_state=lowp_prev)
            if loss_scale is not None:
                return loss * scale, (loss, nb)
            return loss, (loss, nb)

        (_, (loss, new_buffers)), grads = jax.value_and_grad(
            scaled_loss, has_aux=True)(params, model_buffers, batch, key)
        if loss_scale is not None:
            grads = jax.tree.map(lambda g: g / scale, grads)
            # finiteness is judged on the raw unscaled grads BEFORE
            # decay/clip — clippers like ClipGradByValue would map inf to
            # finite values and hide the overflow (ref
            # check_finite_and_unscale_op: the check precedes clipping)
            finite = jnp.asarray(True)
            for g in jax.tree.leaves(grads):
                finite = finite & jnp.isfinite(g).all()
        if anomaly_guard:
            from .amp import all_finite as _all_finite

            # like the loss-scale check, judged on RAW grads before
            # decay/clip (a value clipper would map inf -> finite and
            # hide the anomaly), plus the loss itself (a NaN loss with
            # zero grads — e.g. a poisoned masked branch — must count)
            grads_finite = finite if loss_scale is not None \
                else _all_finite(grads)
            guard_ok = grads_finite & jnp.isfinite(loss)
        if record_grad_norm:
            # global l2 norm of the RAW grads (post-unscale, pre-
            # decay/clip) — the number a clipper would have seen
            gnorm = jnp.sqrt(sum(
                jnp.sum(jnp.square(g.astype(jnp.float32)))
                for g in jax.tree.leaves(grads)))
        if grad_constraint is not None:
            grads = grad_constraint(grads)
        metas = optimizer.param_metas_for(params, _sd)
        # eager _preprocess order: coupled decay first, then clip
        grads = optimizer.decay_gradients_tree(params, grads, metas)
        if grad_clip is not None:
            grads = grad_clip._clip_fn(grads)
        new_params, new_opt = optimizer.apply_gradients_tree(
            params, grads, opt_state, lr, metas=metas)
        if asp_masks:
            new_params = _asp_apply(layer, new_params,
                                    engine_name="Engine")
        if loss_scale is not None:
            # both static and dynamic scaling skip non-finite steps
            # (paddle GradScaler found_inf semantics)
            pick = lambda new, old: jax.tree.map(  # noqa: E731
                lambda n, o: jnp.where(finite, n, o), new, old)
            new_params = pick(new_params, params)
            new_opt = pick(new_opt, opt_state)
            new_buffers = dict(new_buffers)
        if anomaly_guard:
            # skip the whole update on a bad step — params, moments AND
            # captured buffer updates (BN running stats etc.) — and count
            # consecutive bad steps in-graph; everything is a where()
            # select on the one fused bit, so the compiled step stays a
            # single program with no host round-trip
            gpick = lambda new, old: jax.tree.map(  # noqa: E731
                lambda n, o: jnp.where(guard_ok, n, o), new, old)
            new_params = gpick(new_params, params)
            new_opt = gpick(new_opt, opt_state)
            # the old-side tree must mirror new_buffers' keys — the lowp
            # ScaleState rides along, and a bad step keeps the previous
            # scales (its amaxes may be the very poison being skipped)
            old_buffers = dict(model_buffers)
            if lowp_prev is not None and LOWP_SCALE_KEY in new_buffers:
                old_buffers[LOWP_SCALE_KEY] = lowp_prev
            new_buffers = dict(gpick(new_buffers, old_buffers))
            new_buffers[ANOMALY_BAD_STEPS_KEY] = jnp.where(
                guard_ok, 0, anomaly_prev + 1).astype(jnp.int32)
        if record_grad_norm:
            # written AFTER the guard's where()-select over the model
            # buffers so the recorded norm is the step's actual raw
            # norm even when the update itself was skipped
            new_buffers = dict(new_buffers)
            new_buffers[GRAD_NORM_KEY] = gnorm.astype(jnp.float32)
        if dynamic_scale:
            good_next = jnp.where(finite, good + 1, 0)
            bad_next = jnp.where(finite, 0, bad + 1)
            grow = finite & (good_next >= scale_cfg["incr_every_n_steps"])
            shrink = (~finite) & (
                bad_next >= scale_cfg["decr_every_n_nan_or_inf"])
            new_scale = jnp.where(
                grow, scale * scale_cfg["incr_ratio"],
                jnp.where(shrink, scale * scale_cfg["decr_ratio"], scale))
            new_buffers[LOSS_SCALE_KEY] = new_scale
            new_buffers[GOOD_STEPS_KEY] = jnp.where(grow, 0, good_next)
            new_buffers[BAD_STEPS_KEY] = jnp.where(shrink, 0, bad_next)
        return loss, new_params, new_buffers, new_opt

    if mesh is None:
        step_fn = _step_impl
    else:
        # meshed step: GSPMD-partitioned program — attention routes
        # through custom_partitioning so the Mosaic kernel runs
        # per-shard (fused_ops.gspmd_tracing)
        def step_fn(params, buffers, opt_state, batch, lr, key):
            from .ops.fused_ops import gspmd_tracing

            with gspmd_tracing():
                return _step_impl(params, buffers, opt_state, batch,
                                  lr, key)

    in_shardings = None
    out_shardings = None
    if mesh is not None:
        params0 = param_values(layer)
        p_sh = {k: param_sh(k, v) for k, v in params0.items()}
        buf_sh = {k: NamedSharding(mesh, P())
                  for k in buffer_values(layer)}
        if loss_scale == "dynamic" or isinstance(loss_scale, dict):
            buf_sh[LOSS_SCALE_KEY] = NamedSharding(mesh, P())
            buf_sh[GOOD_STEPS_KEY] = NamedSharding(mesh, P())
            buf_sh[BAD_STEPS_KEY] = NamedSharding(mesh, P())
        if anomaly_guard:
            buf_sh[ANOMALY_BAD_STEPS_KEY] = NamedSharding(mesh, P())
        if record_grad_norm:
            buf_sh[GRAD_NORM_KEY] = NamedSharding(mesh, P())
        if lowp:
            # sharding prefix over the ScaleState pytree: replicated
            buf_sh[LOWP_SCALE_KEY] = NamedSharding(mesh, P())
        opt0 = {k: optimizer._init_state(v) for k, v in params0.items()}
        o_sh = {k: jax.tree.map(lambda a, kk=k: opt_sh(kk, a), st)
                for k, st in opt0.items()}
        repl = NamedSharding(mesh, P())
        b_sh = batch_spec if batch_spec is not None else repl
        in_shardings = (p_sh, buf_sh, o_sh, b_sh, repl, repl)
        out_shardings = (repl, p_sh, buf_sh, o_sh)
    donate_argnums = (0, 1, 2) if donate else ()
    if mesh is not None:
        jitted = jax.jit(step_fn, donate_argnums=donate_argnums,
                         in_shardings=in_shardings,
                         out_shardings=out_shardings)
    else:
        jitted = jax.jit(step_fn, donate_argnums=donate_argnums)
    # the un-jitted step is re-usable inside larger traced loops (bench
    # scans N steps in one program to amortise dispatch latency)
    jitted._raw_step_fn = step_fn
    # exposed so Engine can pre-place live state into these shardings
    # (offload moves opt state to host memory; jit requires the arg's
    # memory kind to already match)
    jitted._state_shardings = (
        (in_shardings[0], in_shardings[1], in_shardings[2])
        if in_shardings is not None else None)
    return jitted


def make_eval_step(layer, mesh=None):
    def eval_fn(values, *inputs):
        was_training = layer.training
        layer.eval()
        try:
            return functional_call(layer, values, *inputs)
        finally:
            if was_training:
                layer.train()

    return jax.jit(eval_fn)


class Engine:
    """Drives compiled training of an eager Layer: the Paddle user keeps
    the dygraph API (model, optimizer, loss), this turns each step into one
    XLA program. Used by hapi.Model.prepare, bench, and the distributed
    trainers."""

    def __init__(self, layer, optimizer, loss_fn, grad_clip=None, mesh=None,
                 batch_spec=None, zero_stage=0, sharding_axis=None,
                 loss_scale=None, offload=False, comm_dtype=None,
                 anomaly_guard=False):
        self.layer = layer
        self.optimizer = optimizer
        self.loss_fn = loss_fn
        self.mesh = mesh
        self.batch_spec = batch_spec
        self.zero_stage = zero_stage
        self.sharding_axis = sharding_axis
        self.loss_scale = loss_scale
        self.offload = offload
        self.comm_dtype = comm_dtype
        self.anomaly_guard = anomaly_guard
        self.state = init_train_state(
            layer, optimizer,
            opt_state_mesh_host=mesh if offload else None)
        if loss_scale == "dynamic" or isinstance(loss_scale, dict):
            # in-graph dynamic loss scaling state (fp16-compat mode)
            cfg = dict(DEFAULT_SCALE_CONFIG)
            if isinstance(loss_scale, dict):
                cfg.update(loss_scale)
            self.state.buffers[LOSS_SCALE_KEY] = jnp.asarray(
                float(cfg["init_loss_scaling"]), jnp.float32)
            self.state.buffers[GOOD_STEPS_KEY] = jnp.asarray(0, jnp.int32)
            self.state.buffers[BAD_STEPS_KEY] = jnp.asarray(0, jnp.int32)
        if anomaly_guard:
            self.state.buffers[ANOMALY_BAD_STEPS_KEY] = \
                jnp.asarray(0, jnp.int32)
        # FLAGS_record_grad_norm is latched at construction: the buffer
        # tree (and so the compiled step's signature) must not change
        # mid-run, or every later step would retrace
        from .framework.flags import flag as _flag

        self._record_grad_norm = _flag("FLAGS_record_grad_norm")
        if self._record_grad_norm:
            self.state.buffers[GRAD_NORM_KEY] = jnp.asarray(0.0,
                                                            jnp.float32)
        # FLAGS_lowp_matmul latched the same way: the ScaleState buffer
        # joins the donated carry at construction or never
        from .ops import lowp as _lowp_mod

        self._lowp = _lowp_mod.mode() != "off"
        if self._lowp:
            from .quantization.scaling import init_scale_state

            self.state.buffers[LOWP_SCALE_KEY] = init_scale_state()
        self._step_fn = None
        self._offload_sh = None
        self._grad_clip = grad_clip
        self._step_protos = None
        self._mem_analysis = None
        self._batch_sig = None
        self._ckpt_manager = None
        self._last_batch = None

    def _build(self):
        self._step_fn = make_train_step(
            self.layer, self.loss_fn, self.optimizer,
            grad_clip=self._grad_clip, mesh=self.mesh,
            batch_spec=self.batch_spec, zero_stage=self.zero_stage,
            sharding_axis=self.sharding_axis, loss_scale=self.loss_scale,
            comm_dtype=self.comm_dtype, anomaly_guard=self.anomaly_guard,
            record_grad_norm=self._record_grad_norm, lowp=self._lowp)
        self._offload_sh = None
        if self.offload and self._step_fn._state_shardings is not None:
            # optimizer-state offload (ref sharding/offload_helper.py):
            # state RESTS in pinned host memory between steps and moves
            # to device around each call. (In-graph streaming transfers
            # need TPU host-offload support; the at-rest form works on
            # every backend and still frees device memory between steps.)
            # The freshly-initialised state stays on device — parking it
            # now would just round-trip it back in the first step.
            _, _, o_sh = self._step_fn._state_shardings
            self._offload_sh = host_offload_shardings(self.mesh, o_sh)

    @staticmethod
    def _arrs(ts):
        # jax.Array passes through untouched: DataLoader device
        # prefetch must not be undone by a jnp.asarray round-trip
        return tuple(
            t._value if isinstance(t, Tensor)
            else t if isinstance(t, jax.Array)
            else jnp.asarray(t)
            for t in ts)

    def train_batch(self, inputs, labels=()):
        from . import observe as _observe

        t_step0 = time.perf_counter()
        if self._step_fn is None:
            self._build()
        with _observe.phase("host-prep"):
            if not isinstance(inputs, (list, tuple)):
                inputs = (inputs,)
            if not isinstance(labels, (list, tuple)):
                labels = (labels,)
            # stashed (host-side references) so attribute_step can
            # replay the live step shape under an xplane capture
            self._last_batch = (inputs, labels)
            batch = {"inputs": self._arrs(inputs),
                     "labels": self._arrs(labels)}
            from .framework import faults as _faults

            # fault-injection point: a scheduled 'nan' action poisons
            # the HOST batch (in-graph effect on loss/grads, no
            # recompilation) — the deterministic way to exercise the
            # anomaly guard
            batch = _faults.fault_point("train.batch", batch)
            key = _random.default_generator.next_key()
            lr = jnp.asarray(self.optimizer.get_lr(), jnp.float32)
        opt_state = self.state.opt_state
        if self._offload_sh is not None:
            dev_sh, host_sh = self._offload_sh
            with _observe.phase("h2d"):
                opt_state = jax.device_put(opt_state, dev_sh)
        # cheap per-step signature: plain tuple comprehension over the
        # two known leaf tuples instead of a jax.tree.map traversal
        # (tree.map rebuilds registry nodes + a dict every step; this is
        # pure python on ~4 leaves)
        batch_sig = (
            tuple((a.shape, a.dtype.name) for a in batch["inputs"]),
            tuple((a.shape, a.dtype.name) for a in batch["labels"]),
        )
        compiling = (self._step_protos is None
                     or batch_sig != self._batch_sig)
        if compiling:
            # a new batch shape means a new compiled program: refresh
            # the protos so memory_analysis() reports the live program
            self._batch_sig = batch_sig
            self._mem_analysis = None
            self._step_protos = jax.tree.map(
                lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype),
                (self.state.params, self.state.buffers, opt_state,
                 batch, lr, key))
        t_fn0 = time.perf_counter()
        with _observe.phase("compile" if compiling else "device-step"):
            loss, self.state.params, self.state.buffers, new_opt = \
                self._step_fn(self.state.params, self.state.buffers,
                              opt_state, batch, lr, key)
        if compiling:
            # the step body's trace-time record_compile logged the
            # event; backfill how long trace+compile+first-dispatch took
            _observe.annotate("train_step",
                              wall_s=time.perf_counter() - t_fn0)
        if self._offload_sh is not None:
            with _observe.phase("h2d"):
                new_opt = jax.device_put(new_opt, self._offload_sh[1])
        self.state.opt_state = new_opt
        self.state.step += 1
        if self.anomaly_guard:
            # the counter readback is the guard's only host sync and it
            # blocks dispatch, so amortise it: the in-graph guard skips
            # every bad update immediately regardless, the host only
            # decides ROLLBACK — which FLAGS_anomaly_check_interval may
            # delay by up to interval-1 (bad, already-skipped) steps
            from .framework import flags as _flags

            interval = _flags.flag("FLAGS_anomaly_check_interval")
            if interval <= 1 or self.state.step % interval == 0:
                with _observe.phase("anomaly-readback"):
                    self._check_anomaly()
        self._flight_record(loss, compiling,
                            time.perf_counter() - t_step0)
        from . import profiler as _profiler

        if _profiler.is_op_profiling_enabled():
            _profiler.record_device_memory("train_batch")
        return Tensor(loss)

    def _flight_record(self, loss, compiling, step_s):
        """One flight-recorder entry per step. Loss / grad-norm /
        anomaly counter stay as device arrays (no host sync here); the
        recorder materializes them only when a black box is dumped."""
        from . import observe as _observe
        from .framework import flags as _flags

        fields = {"loss": loss, "step_ms": step_s * 1e3,
                  "compiled": compiling}
        if self._record_grad_norm:
            fields["grad_norm"] = self.state.buffers[GRAD_NORM_KEY]
        if self.anomaly_guard:
            fields["anomaly_bad_steps"] = \
                self.state.buffers[ANOMALY_BAD_STEPS_KEY]
        if _flags.flag("FLAGS_flight_record_memory"):
            from . import device as _device

            try:
                fields["bytes_in_use"] = \
                    _device.memory_stats()["bytes_in_use"]
            except Exception:
                pass
        _observe.flight.record_step(self.state.step, **fields)

    def attribute_step(self, logdir=None, steps=1, top=10):
        """Where does the device time of a training step go?  Captures
        an xplane trace of `steps` replays of the LAST train_batch shape
        and classifies device time into matmul / attention / collective
        / elementwise / other buckets (observe.attribute) — the
        measurement ROADMAP item 4's overlap work starts from.

        NOTE: state is donated through the compiled step, so the traced
        steps are REAL steps — training advances by `steps`.  Returns
        the attribution report dict (buckets, fractions, total_us,
        top_ops); the raw capture stays under `logdir` for xprof."""
        if self._last_batch is None:
            raise RuntimeError("run train_batch() once first")
        import tempfile

        from . import observe as _observe, profiler as _profiler

        if logdir is None:
            logdir = tempfile.mkdtemp(prefix="paddle-attrib-")
        inputs, labels = self._last_batch
        _profiler.start_trace(logdir)
        try:
            for _ in range(steps):
                self.train_batch(inputs, labels)
            # drain async dispatch so every step's device work lands
            # inside the capture window
            jax.block_until_ready(self.state.params)
        finally:
            _profiler.stop_trace()
        return _observe.attribute(logdir, top=top)

    def overlap_report(self, logdir=None, steps=1):
        """Capture a trace of `steps` real steps (same mechanics as
        attribute_step) and pair the collective bucket against
        concurrently-resident matmul/attention time: returns
        observe.overlap_report's dict, whose headline
        `exposed_collective_frac` is the share of device time spent in
        collectives with NO compute in flight — the number the
        FLAGS_mp_overlap ring schedule exists to push down."""
        if self._last_batch is None:
            raise RuntimeError("run train_batch() once first")
        import tempfile

        from . import observe as _observe, profiler as _profiler

        if logdir is None:
            logdir = tempfile.mkdtemp(prefix="paddle-overlap-")
        inputs, labels = self._last_batch
        _profiler.start_trace(logdir)
        try:
            for _ in range(steps):
                self.train_batch(inputs, labels)
            jax.block_until_ready(self.state.params)
        finally:
            _profiler.stop_trace()
        return _observe.overlap_report(logdir)

    def memory_analysis(self) -> dict:
        """MEASURED per-step device memory of the compiled train step
        (XLA's buffer assignment — ref profiler.proto:38 MemEvent /
        monitor.h:77 GPU mem high-watermark, which infer what XLA here
        reports exactly).  Keys in bytes: arguments (resident state:
        params/opt/batch), temps (activations + workspace), outputs,
        alias (donated arg<->output reuse), generated_code, peak
        (XLA's peak liveness when reported, else arg+temp+out-alias);
        host_* mirror them for host-memory-kind buffers (offload)."""
        if self._step_fn is None or self._step_protos is None:
            raise RuntimeError("run train_batch() once first")
        if self._mem_analysis is None:
            from . import observe as _observe

            # deliberate re-lowering of the SAME program: keep it out
            # of the compile-event registry (and any no_retrace guard)
            with _observe.retrace.suppress():
                ma = self._step_fn.lower(*self._step_protos) \
                    .compile().memory_analysis()
            peak = getattr(ma, "peak_memory_in_bytes", 0) or (
                ma.argument_size_in_bytes + ma.temp_size_in_bytes
                + ma.output_size_in_bytes - ma.alias_size_in_bytes)
            self._mem_analysis = {
                "arguments": ma.argument_size_in_bytes,
                "temps": ma.temp_size_in_bytes,
                "outputs": ma.output_size_in_bytes,
                "alias": ma.alias_size_in_bytes,
                "generated_code": ma.generated_code_size_in_bytes,
                "peak": peak,
                "host_arguments": ma.host_argument_size_in_bytes,
                "host_temps": ma.host_temp_size_in_bytes,
                "host_outputs": ma.host_output_size_in_bytes,
            }
            from .framework import monitor

            monitor.stat_max("device_mem_step_peak_bytes",
                             self._mem_analysis["peak"])
            # backfill the compile registry so a retrace audit shows
            # peak memory next to each program's signature
            _observe.annotate("train_step", peak_bytes=peak)
        return dict(self._mem_analysis)

    def attach_checkpoint_manager(self, manager):
        """Give the anomaly guard a rollback target: when
        FLAGS_anomaly_max_bad_steps consecutive steps go non-finite, the
        engine restores the newest readable checkpoint from this
        CheckpointManager (train_epoch_range attaches its own manager
        automatically)."""
        self._ckpt_manager = manager

    def _check_anomaly(self):
        """Step-boundary policy for the in-graph guard: ONE scalar read
        of the consecutive-bad-step buffer (the only host sync the guard
        adds — never per-op), then rollback once the budget is spent."""
        from .framework import flags as _flags, monitor as _monitor

        bad = int(self.state.buffers[ANOMALY_BAD_STEPS_KEY])
        if bad == 0:
            return
        _monitor.stat_add("anomaly_bad_steps")
        max_bad = _flags.flag("FLAGS_anomaly_max_bad_steps")
        if not max_bad or bad < max_bad:
            return  # skipped in-graph; give the run a chance to recover
        if self._ckpt_manager is None:
            from .framework.errors import PreconditionNotMetError

            raise PreconditionNotMetError(
                f"anomaly guard: {bad} consecutive non-finite steps and "
                "no checkpoint manager attached for rollback — call "
                "engine.attach_checkpoint_manager(...) or train via "
                "checkpoint.train_epoch_range")
        import warnings

        from . import observe as _observe
        from .distributed import checkpoint as _ckpt

        # rollback destroys the live (anomalous) state — preserve the
        # black box first so the post-mortem still has the bad steps
        _observe.flight.note("anomaly_rollback", bad_steps=bad,
                             engine_step=self.state.step)
        _observe.flight.dump("anomaly-rollback")
        self._ckpt_manager.wait_until_finished()
        step, _ = self._ckpt_manager.restore_with(
            lambda p: _ckpt.load_train_state(p, self))
        # the restored snapshot predates the anomaly: clear the counter
        # so the guard re-arms from zero
        self.state.buffers = dict(self.state.buffers)
        self.state.buffers[ANOMALY_BAD_STEPS_KEY] = \
            jnp.asarray(0, jnp.int32)
        _monitor.stat_add("anomaly_rollbacks")
        warnings.warn(
            f"anomaly guard: {bad} consecutive non-finite steps; rolled "
            f"back to checkpoint ckpt-{step} (engine step "
            f"{self.state.step})")

    def sync_to_layer(self):
        write_back(self.layer, self.state)
