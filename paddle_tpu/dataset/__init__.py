"""paddle.dataset — fluid-era reader-style dataset API.

Ref parity: python/paddle/dataset/{mnist,cifar,imdb,uci_housing,
conll05,movielens,wmt14}.py, whose surface is `train()`/`test()`
functions returning zero-arg READERS (composable with paddle.reader /
paddle.batch).  Implemented as thin adapters over the map-style Dataset
classes in `vision.datasets` / `text` (which carry the zero-egress
synthetic fallbacks), so both API generations share one data source.
"""

from __future__ import annotations

import types


def _reader_of(dataset_cls, mode, **kwargs):
    def rd():
        ds = dataset_cls(mode=mode, **kwargs)
        for i in range(len(ds)):
            yield tuple(ds[i])

    return rd


def _module(name, dataset_cls, train_mode="train", test_mode="test",
            **kwargs):
    m = types.ModuleType(f"{__name__}.{name}")
    m.train = lambda **kw: _reader_of(dataset_cls, train_mode,
                                      **{**kwargs, **kw})
    m.test = lambda **kw: _reader_of(dataset_cls, test_mode,
                                     **{**kwargs, **kw})
    return m


def _build():
    from ..text import WMT14, Conll05st, Imdb, Movielens, UCIHousing
    from ..vision.datasets import MNIST, Cifar10, Cifar100

    mods = {
        "mnist": _module("mnist", MNIST),
        "cifar": None,  # filled below (cifar has train10/test10 names)
        "imdb": _module("imdb", Imdb),
        "uci_housing": _module("uci_housing", UCIHousing),
        "conll05": _module("conll05", Conll05st),
        "movielens": _module("movielens", Movielens),
        "wmt14": _module("wmt14", WMT14),
    }
    cifar = types.ModuleType(f"{__name__}.cifar")
    cifar.train10 = lambda **kw: _reader_of(Cifar10, "train", **kw)
    cifar.test10 = lambda **kw: _reader_of(Cifar10, "test", **kw)
    cifar.train100 = lambda **kw: _reader_of(Cifar100, "train", **kw)
    cifar.test100 = lambda **kw: _reader_of(Cifar100, "test", **kw)
    mods["cifar"] = cifar
    return mods


_mods = _build()
mnist = _mods["mnist"]
cifar = _mods["cifar"]
imdb = _mods["imdb"]
uci_housing = _mods["uci_housing"]
conll05 = _mods["conll05"]
movielens = _mods["movielens"]
wmt14 = _mods["wmt14"]

__all__ = ["mnist", "cifar", "imdb", "uci_housing", "conll05",
           "movielens", "wmt14"]
