"""Probability distributions (ref: python/paddle/distribution.py —
Normal/Uniform/Categorical + kl_divergence)."""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ..core.dispatch import apply
from ..core.tensor import Tensor
from ..framework import random as _random


def _val(x):
    if isinstance(x, Tensor):
        return x._value
    return jnp.asarray(x, jnp.float32)


class Distribution:
    def sample(self, shape=()):
        raise NotImplementedError

    def entropy(self):
        raise NotImplementedError

    def log_prob(self, value):
        raise NotImplementedError

    def probs(self, value):
        return Tensor(jnp.exp(self.log_prob(value)._value))

    def kl_divergence(self, other):
        raise NotImplementedError


class Normal(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _val(loc)
        self.scale = _val(scale)

    @property
    def mean(self):
        return Tensor(jnp.broadcast_to(
            self.loc, jnp.broadcast_shapes(self.loc.shape,
                                           self.scale.shape)))

    @property
    def variance(self):
        return Tensor(jnp.broadcast_to(
            self.scale ** 2, jnp.broadcast_shapes(self.loc.shape,
                                                  self.scale.shape)))

    def sample(self, shape=(), seed=0):
        shape = tuple(shape)
        bshape = jnp.broadcast_shapes(self.loc.shape, self.scale.shape)
        key = _random.next_key()
        eps = jax.random.normal(key, shape + bshape)
        return Tensor(self.loc + self.scale * eps)

    def rsample(self, shape=()):
        return self.sample(shape)

    def entropy(self):
        bshape = jnp.broadcast_shapes(self.loc.shape, self.scale.shape)
        scale = jnp.broadcast_to(self.scale, bshape)
        return Tensor(0.5 + 0.5 * math.log(2 * math.pi) + jnp.log(scale))

    def log_prob(self, value):
        v = _val(value)
        var = self.scale ** 2
        return Tensor(-((v - self.loc) ** 2) / (2 * var) -
                      jnp.log(self.scale) - 0.5 * math.log(2 * math.pi))

    def kl_divergence(self, other):
        var_ratio = (self.scale / other.scale) ** 2
        t1 = ((self.loc - other.loc) / other.scale) ** 2
        return Tensor(0.5 * (var_ratio + t1 - 1 - jnp.log(var_ratio)))


class Uniform(Distribution):
    def __init__(self, low, high, name=None):
        self.low = _val(low)
        self.high = _val(high)

    def sample(self, shape=(), seed=0):
        shape = tuple(shape)
        bshape = jnp.broadcast_shapes(self.low.shape, self.high.shape)
        key = _random.next_key()
        u = jax.random.uniform(key, shape + bshape)
        return Tensor(self.low + (self.high - self.low) * u)

    def entropy(self):
        return Tensor(jnp.log(self.high - self.low))

    def log_prob(self, value):
        v = _val(value)
        inside = (v >= self.low) & (v < self.high)
        lp = -jnp.log(self.high - self.low)
        return Tensor(jnp.where(inside, lp, -jnp.inf))


class Categorical(Distribution):
    def __init__(self, logits, name=None):
        self.logits = _val(logits)

    def sample(self, shape=()):
        key = _random.next_key()
        return Tensor(jax.random.categorical(
            key, jnp.log(jax.nn.softmax(self.logits)),
            shape=tuple(shape) + self.logits.shape[:-1]))

    def entropy(self):
        p = jax.nn.softmax(self.logits)
        logp = jax.nn.log_softmax(self.logits)
        return Tensor(-jnp.sum(p * logp, axis=-1))

    def log_prob(self, value):
        logp = jax.nn.log_softmax(self.logits)
        idx = _val(value).astype(jnp.int32)
        return Tensor(jnp.take_along_axis(
            logp, idx[..., None], axis=-1).squeeze(-1))

    def probs(self, value):
        p = jax.nn.softmax(self.logits)
        idx = _val(value).astype(jnp.int32)
        return Tensor(jnp.take_along_axis(p, idx[..., None],
                                          axis=-1).squeeze(-1))

    def kl_divergence(self, other):
        p = jax.nn.softmax(self.logits)
        logp = jax.nn.log_softmax(self.logits)
        logq = jax.nn.log_softmax(other.logits)
        return Tensor(jnp.sum(p * (logp - logq), axis=-1))


def kl_divergence(p, q):
    return p.kl_divergence(q)
