"""Shared order-statistics helpers.

One implementation of linear-interpolation percentiles (numpy's
'linear' method) used by BOTH `profiler.percentiles` (host-span
latencies) and `serving.metrics` (request/step latency series), so the
two registries can never drift apart on quantile math.
"""

from __future__ import annotations

__all__ = ["percentile", "percentiles"]


def _interp(data, p):
    """`data` already sorted ascending, non-empty; p in [0, 100]."""
    rank = (len(data) - 1) * (p / 100.0)
    lo = int(rank)
    hi = min(lo + 1, len(data) - 1)
    return data[lo] + (data[hi] - data[lo]) * (rank - lo)


def percentile(samples, p):
    """Linear-interpolation percentile over an unsorted sequence."""
    if not 0 <= p <= 100:
        raise ValueError(f"percentile must be in [0, 100], got {p}")
    data = sorted(samples)
    if not data:
        raise ValueError("no samples")
    return _interp(data, p)


def percentiles(samples, ps=(50, 95, 99)):
    """{p: value} over `samples` — one sort shared by every quantile."""
    data = sorted(samples)
    if not data:
        raise ValueError("no samples")
    out = {}
    for p in ps:
        if not 0 <= p <= 100:
            raise ValueError(f"percentile must be in [0, 100], got {p}")
        out[p] = _interp(data, p)
    return out
