"""Custom-op plugin toolchain: build user C++ into loadable ops.

Ref parity: python/paddle/utils/cpp_extension/ (JIT build of user .cc into
a .so) + paddle/fluid/framework/custom_operator.cc:511 (runtime op
registration). TPU-native differences: no pybind11 — the user exposes
`extern "C"` functions loaded via ctypes; `register_custom_op` wires a
host function into the op registry through `jax.pure_callback`, so custom
ops work in eager mode AND inside jit-traced programs (XLA calls back to
the host), with an optional custom gradient.

    lib = load(name="my_ops", sources=["my_ops.cc"])
    # extern "C" void my_relu(const float* x, float* y, int64_t n);

    def my_relu(x):
        out = np.empty_like(x)
        lib.my_relu(c_ptr(x), c_ptr(out), x.size)
        return out

    register_custom_op("my_relu", my_relu,
                       infer_shape=lambda x: (x.shape, x.dtype))
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess

import numpy as np

import jax
import jax.numpy as jnp

from ..core.op_registry import register_op

__all__ = ["load", "register_custom_op", "c_ptr", "CppExtension"]

# names registered at runtime through register_custom_op (tooling like the
# op-sweep coverage gate treats these as user plugins, not framework ops)
registered_custom_ops: set = set()


def _cache_dir():
    root = os.environ.get("PADDLE_TPU_CACHE",
                          os.path.join(os.path.expanduser("~"), ".cache",
                                       "paddle_tpu"))
    d = os.path.join(root, "extensions")
    os.makedirs(d, exist_ok=True)
    return d


def load(name, sources, extra_cflags=None, extra_ldflags=None,
         verbose=False):
    """Compile `sources` (C++ files) into a shared library and return the
    ctypes.CDLL (ref cpp_extension.load). Rebuilds only when sources or
    flags change (content-hash cache)."""
    h = hashlib.sha256(name.encode())
    for src in sources:
        with open(src, "rb") as f:
            h.update(f.read())
    flags = ["-O3", "-shared", "-fPIC", "-std=c++17"] + \
        list(extra_cflags or [])
    h.update(" ".join(flags).encode())
    h.update(" ".join(extra_ldflags or []).encode())
    so = os.path.join(_cache_dir(), f"{name}-{h.hexdigest()[:16]}.so")
    if not os.path.exists(so):
        tmp = so + f".tmp{os.getpid()}"
        cmd = ["g++"] + flags + list(sources) + ["-o", tmp] + \
            list(extra_ldflags or [])
        if verbose:
            print("[cpp_extension]", " ".join(cmd))
        r = subprocess.run(cmd, capture_output=True, text=True)
        if r.returncode != 0:
            raise RuntimeError(
                f"cpp_extension build failed:\n{r.stderr}")
        os.replace(tmp, so)
    return ctypes.CDLL(so)


# torch/paddle-style spec object for setup() workflows
class CppExtension:
    def __init__(self, sources, extra_compile_args=None):
        self.sources = list(sources)
        self.extra_compile_args = list(extra_compile_args or [])


_CTYPES = {
    np.dtype(np.float32): ctypes.POINTER(ctypes.c_float),
    np.dtype(np.float64): ctypes.POINTER(ctypes.c_double),
    np.dtype(np.int32): ctypes.POINTER(ctypes.c_int32),
    np.dtype(np.int64): ctypes.POINTER(ctypes.c_int64),
    np.dtype(np.uint8): ctypes.POINTER(ctypes.c_uint8),
}


def c_ptr(array):
    """Typed ctypes pointer for a contiguous numpy array."""
    array = np.ascontiguousarray(array)
    return array.ctypes.data_as(_CTYPES[array.dtype])


def register_custom_op(name, host_fn, *, infer_shape=None, grad_fn=None,
                       no_grad=False):
    """Register a host-side function as op `name`
    (ref custom_operator.cc:511 RegisterOperatorWithMetaInfo).

    host_fn(*np_arrays, **attrs) -> np array (or tuple). Under jit the op
    becomes a jax.pure_callback using `infer_shape(*abstract) ->
    (shape, dtype) | list` for the output spec. grad_fn(*np_arrays,
    grad) -> tuple of input grads enables backward via custom_vjp."""

    def spec_of(*arrs, **attrs):
        if infer_shape is not None:
            out = infer_shape(*arrs, **attrs)
        else:
            out = (arrs[0].shape, arrs[0].dtype)
        if isinstance(out, list):
            return [jax.ShapeDtypeStruct(tuple(s), d) for s, d in out]
        return jax.ShapeDtypeStruct(tuple(out[0]), out[1])

    def call_host(*arrs, **attrs):
        return jax.pure_callback(
            lambda *xs: host_fn(*[np.asarray(x) for x in xs], **attrs),
            spec_of(*arrs, **attrs), *arrs, vmap_method="sequential")

    registered_custom_ops.add(name)
    if grad_fn is None:
        register_op(name, no_grad=True)(call_host)
        return

    @jax.custom_vjp
    def op(*arrs):
        return call_host(*arrs)

    def fwd(*arrs):
        return call_host(*arrs), arrs

    def bwd(res, g):
        specs = tuple(jax.ShapeDtypeStruct(a.shape, a.dtype) for a in res)
        out = jax.pure_callback(
            lambda *xs: tuple(
                np.asarray(r) for r in grad_fn(
                    *[np.asarray(x) for x in xs[:-1]],
                    np.asarray(xs[-1]))),
            specs, *res, g, vmap_method="sequential")
        return tuple(out)

    op.defvjp(fwd, bwd)
    register_op(name)(lambda *arrs, **attrs: op(*arrs))
