"""Minimal protobuf wire-format reader (no generated code, no protoc).

Shared by the reference-artifact importer (framework.proto messages)
and the profiler's XProf/xplane parser — both only need field-tagged
traversal of length-delimited messages."""

from __future__ import annotations

__all__ = ["read_varint", "fields"]


def read_varint(buf, pos):
    result = 0
    shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7


def fields(buf):
    """Yield (field_number, wire_type, value) over a protobuf message.
    wire 0 -> int, wire 2 -> bytes, wire 1/5 -> raw fixed bytes."""
    pos = 0
    n = len(buf)
    while pos < n:
        key, pos = read_varint(buf, pos)
        field, wire = key >> 3, key & 0x7
        if wire == 0:
            val, pos = read_varint(buf, pos)
        elif wire == 2:
            ln, pos = read_varint(buf, pos)
            val = buf[pos:pos + ln]
            pos += ln
        elif wire == 5:
            val = buf[pos:pos + 4]
            pos += 4
        elif wire == 1:
            val = buf[pos:pos + 8]
            pos += 8
        else:
            raise ValueError(f"unsupported wire type {wire}")
        yield field, wire, val
