"""paddle.utils (ref python/paddle/utils/)."""

from . import cpp_extension  # noqa: F401
from . import stats  # noqa: F401
