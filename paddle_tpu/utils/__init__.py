"""paddle.utils (ref python/paddle/utils/)."""

from . import cpp_extension  # noqa: F401
