"""paddle.hub — hubconf-based model loading (ref: python/paddle/hapi/hub.py).

A hub repo is a directory with a ``hubconf.py`` whose public callables
are model entrypoints and whose optional ``dependencies`` list names
required importable packages.  The ``local`` source is fully supported;
``github``/``gitee`` need network egress, which this environment does
not have, so they raise a clear RuntimeError (same validation and call
surface as the reference).
"""

from __future__ import annotations

import importlib.util
import os
import sys

__all__ = ["list", "help", "load"]

MODULE_HUBCONF = "hubconf.py"
_builtin_list = list


def _resolve_repo(repo_dir, source, force_reload):
    if source not in ("github", "gitee", "local"):
        raise ValueError(
            'Unknown source: "{}". Allowed values: "github" | "gitee" | '
            '"local".'.format(source))
    if source in ("github", "gitee"):
        raise RuntimeError(
            "paddle.hub source='{}' needs network access, which is not "
            "available in this environment; clone the repo yourself and "
            "use source='local'.".format(source))
    if not os.path.isdir(repo_dir):
        raise ValueError("local hub repo not found: {}".format(repo_dir))
    return repo_dir


# module names that past hub loads injected into sys.modules (sibling
# imports of a hubconf); purged before each load so two repos with
# same-named siblings never see each other's code
_hub_loaded_names: set = set()


def _import_module(name, repo_dir):
    path = os.path.join(repo_dir, MODULE_HUBCONF)
    if not os.path.isfile(path):
        raise FileNotFoundError(
            "{} has no {}".format(repo_dir, MODULE_HUBCONF))
    for stale in _hub_loaded_names:
        sys.modules.pop(stale, None)
    _hub_loaded_names.clear()
    spec = importlib.util.spec_from_file_location(name, path)
    module = importlib.util.module_from_spec(spec)
    before = set(sys.modules)
    sys.path.insert(0, repo_dir)
    try:
        spec.loader.exec_module(module)
    finally:
        sys.path.remove(repo_dir)
        # track only the repo's OWN sibling modules for next-load
        # purging; third-party imports a hubconf triggers must stay
        # cached (re-executing them would duplicate class identities)
        repo_prefix = os.path.abspath(repo_dir) + os.sep
        for n in set(sys.modules) - before:
            f = getattr(sys.modules.get(n), "__file__", None) or ""
            if f and os.path.abspath(f).startswith(repo_prefix):
                _hub_loaded_names.add(n)
    return module


def _check_dependencies(module):
    deps = getattr(module, "dependencies", None)
    if not deps:
        return
    missing = [d for d in deps
               if importlib.util.find_spec(d) is None]
    if missing:
        raise RuntimeError(
            "Missing dependencies for hub repo: {}".format(missing))


def _entries(module):
    # Reference semantics: any public callable in hubconf.py is an
    # entrypoint, including ones re-exported from sibling modules.
    return {
        name: fn
        for name, fn in vars(module).items()
        if callable(fn) and not name.startswith("_")
    }


def _load_entry_from_hubconf(module, name):
    if not isinstance(name, str):
        raise ValueError(
            "Invalid input: model should be a str of function name")
    entry = _entries(module).get(name)
    if entry is None:
        raise RuntimeError(
            "Cannot find callable {} in {}".format(name, MODULE_HUBCONF))
    return entry


def list(repo_dir, source="github", force_reload=False):  # noqa: A001
    """List entrypoint names exposed by a hub repo's hubconf.py."""
    repo_dir = _resolve_repo(repo_dir, source, force_reload)
    module = _import_module(MODULE_HUBCONF.split(".")[0], repo_dir)
    return _builtin_list(_entries(module))


def help(repo_dir, model, source="github", force_reload=False):  # noqa: A001
    """Return the docstring of one entrypoint."""
    repo_dir = _resolve_repo(repo_dir, source, force_reload)
    module = _import_module(MODULE_HUBCONF.split(".")[0], repo_dir)
    return _load_entry_from_hubconf(module, model).__doc__


def load(repo_dir, model, source="github", force_reload=False, **kwargs):
    """Build a model from a hub repo entrypoint."""
    repo_dir = _resolve_repo(repo_dir, source, force_reload)
    module = _import_module(MODULE_HUBCONF.split(".")[0], repo_dir)
    _check_dependencies(module)
    entry = _load_entry_from_hubconf(module, model)
    return entry(**kwargs)
