"""hapi callbacks (ref: python/paddle/hapi/callbacks.py)."""

from __future__ import annotations

import os
import time


class Callback:
    def __init__(self):
        self.model = None
        self.params = {}

    def set_params(self, params):
        self.params = params or {}

    def set_model(self, model):
        self.model = model

    def on_train_begin(self, logs=None):
        pass

    def on_train_end(self, logs=None):
        pass

    def on_eval_begin(self, logs=None):
        pass

    def on_eval_end(self, logs=None):
        pass

    def on_predict_begin(self, logs=None):
        pass

    def on_predict_end(self, logs=None):
        pass

    def on_epoch_begin(self, epoch, logs=None):
        pass

    def on_epoch_end(self, epoch, logs=None):
        pass

    def on_train_batch_begin(self, step, logs=None):
        pass

    def on_train_batch_end(self, step, logs=None):
        pass

    def on_eval_batch_begin(self, step, logs=None):
        pass

    def on_eval_batch_end(self, step, logs=None):
        pass

    def on_predict_batch_begin(self, step, logs=None):
        pass

    def on_predict_batch_end(self, step, logs=None):
        pass


class CallbackList:
    def __init__(self, callbacks):
        self.callbacks = list(callbacks)

    def set_params(self, params):
        for c in self.callbacks:
            c.set_params(params)

    def set_model(self, model):
        for c in self.callbacks:
            c.set_model(model)

    def __getattr__(self, name):
        def dispatch(*args, **kwargs):
            for c in self.callbacks:
                getattr(c, name)(*args, **kwargs)

        return dispatch


class ProgBarLogger(Callback):
    def __init__(self, log_freq=1, verbose=2):
        super().__init__()
        self.log_freq = log_freq
        self.verbose = verbose

    def on_epoch_begin(self, epoch, logs=None):
        self.epoch = epoch
        self.steps = self.params.get("steps")
        self._start = time.time()
        if self.verbose:
            print(f"Epoch {epoch + 1}/{self.params.get('epochs', '?')}")

    def on_train_batch_end(self, step, logs=None):
        logs = logs or {}
        if self.verbose and step % self.log_freq == 0:
            items = " - ".join(
                f"{k}: {v:.4f}" if isinstance(v, float) else f"{k}: {v}"
                for k, v in logs.items())
            print(f"step {step}/{self.steps or '?'} - {items}")

    def on_epoch_end(self, epoch, logs=None):
        if self.verbose:
            dur = time.time() - self._start
            items = " - ".join(
                f"{k}: {v:.4f}" if isinstance(v, float) else f"{k}: {v}"
                for k, v in (logs or {}).items())
            print(f"Epoch {epoch + 1} done ({dur:.1f}s) - {items}")

    def on_eval_end(self, logs=None):
        if self.verbose:
            items = " - ".join(
                f"{k}: {v}" for k, v in (logs or {}).items())
            print(f"Eval - {items}")


class ModelCheckpoint(Callback):
    def __init__(self, save_freq=1, save_dir=None):
        super().__init__()
        self.save_freq = save_freq
        self.save_dir = save_dir

    def on_epoch_end(self, epoch, logs=None):
        if self.save_dir and epoch % self.save_freq == 0:
            path = os.path.join(self.save_dir, str(epoch))
            self.model.save(path)

    def on_train_end(self, logs=None):
        if self.save_dir:
            self.model.save(os.path.join(self.save_dir, "final"))


def _monitored_value(logs, monitor):
    """Look up a monitored metric in eval logs.

    Model.evaluate prefixes its keys with ``eval_`` — accept both the
    bare name (reference spelling, e.g. ``loss``) and the prefixed one.
    Streaming metrics report lists; use the first element.
    """
    cur = logs.get(monitor)
    if cur is None:
        cur = logs.get("eval_" + monitor)
    if isinstance(cur, (list, tuple)):
        cur = cur[0] if cur else None
    return cur


def _improvement_cmp(mode, monitor, min_delta):
    if mode == "max" or (mode == "auto" and "acc" in monitor):
        return lambda cur, best: cur > best + min_delta
    return lambda cur, best: cur < best - min_delta


class EarlyStopping(Callback):
    def __init__(self, monitor="loss", mode="auto", patience=0, verbose=1,
                 min_delta=0, baseline=None, save_best_model=True):
        super().__init__()
        self.monitor = monitor
        self.patience = patience
        self.verbose = verbose
        self.min_delta = abs(min_delta)
        self.baseline = baseline
        self.save_best_model = save_best_model
        self.wait = 0
        self.best = None
        self.stopped_epoch = 0
        self._epoch = 0
        self.better = _improvement_cmp(mode, monitor, self.min_delta)

    def on_train_begin(self, logs=None):
        self.wait = 0
        self.stopped_epoch = 0
        self._epoch = 0
        # A baseline is a bar the metric must clear, not a best value to
        # update: a run that never beats it accrues wait every eval
        # (reference hapi/callbacks.py EarlyStopping.on_train_begin).
        self.best = self.baseline

    def on_epoch_begin(self, epoch=None, logs=None):
        if epoch is not None:
            self._epoch = epoch

    def on_eval_end(self, logs=None):
        cur = _monitored_value(logs or {}, self.monitor)
        if cur is None:
            return
        if self.best is None or self.better(cur, self.best):
            self.best = cur
            self.wait = 0
            save_dir = self.params.get("save_dir")
            if self.save_best_model and save_dir and self.model is not None:
                self.model.save(os.path.join(save_dir, "best_model"))
        else:
            self.wait += 1
            if self.wait >= self.patience:
                self.model.stop_training = True
                # stopped_epoch is the 0-based epoch that triggered the
                # stop, taken from on_epoch_begin — NOT an eval counter
                # (the reference counts evals here, hapi/callbacks.py:838,
                # which miscounts under eval_freq != 1; deliberate fix)
                self.stopped_epoch = self._epoch
                if self.verbose:
                    print(f"Epoch {self.stopped_epoch + 1}: "
                          "Early stopping.")


class LRScheduler(Callback):
    def __init__(self, by_step=True, by_epoch=False):
        super().__init__()
        self.by_step = by_step
        self.by_epoch = by_epoch

    def _sched(self):
        opt = getattr(self.model, "_optimizer", None)
        lr = getattr(opt, "_learning_rate", None)
        return lr if hasattr(lr, "step") and callable(
            getattr(lr, "step", None)) and not isinstance(lr, float) else None

    def on_train_batch_end(self, step, logs=None):
        if self.by_step:
            s = self._sched()
            if s is not None:
                s.step()

    def on_epoch_end(self, epoch, logs=None):
        if self.by_epoch:
            s = self._sched()
            if s is not None:
                s.step()


class ReduceLROnPlateau(Callback):
    """Reduce the optimizer LR when a monitored metric stops improving.

    Ref parity: python/paddle/hapi/callbacks.py ReduceLROnPlateau (same
    knobs).  Only a plain-float optimizer LR can be stepped down
    (matching the reference, which warns and skips for scheduler LRs).
    """

    def __init__(self, monitor="loss", factor=0.1, patience=10, verbose=1,
                 mode="auto", min_delta=1e-4, cooldown=0, min_lr=0):
        super().__init__()
        if factor >= 1.0:
            raise ValueError("ReduceLROnPlateau does not support a "
                             "factor >= 1.0")
        self.monitor = monitor
        self.factor = factor
        self.patience = patience
        self.verbose = verbose
        self.min_delta = abs(min_delta)
        self.cooldown = cooldown
        self.min_lr = min_lr
        self.cooldown_counter = 0
        self.wait = 0
        self.best = None
        self.better = _improvement_cmp(mode, monitor, self.min_delta)

    def on_eval_end(self, logs=None):
        cur = _monitored_value(logs or {}, self.monitor)
        if cur is None:
            return
        if self.cooldown_counter > 0:
            self.cooldown_counter -= 1
            self.wait = 0
        if self.best is None or self.better(cur, self.best):
            self.best = cur
            self.wait = 0
        elif self.cooldown_counter <= 0:
            self.wait += 1
            if self.wait >= self.patience:
                opt = getattr(self.model, "_optimizer", None)
                if opt is not None:
                    if not isinstance(opt._learning_rate, float):
                        import warnings

                        warnings.warn(
                            "ReduceLROnPlateau only supports a float "
                            "learning rate; the optimizer uses an "
                            "LRScheduler, skipping the reduction.")
                    else:
                        old = opt.get_lr()
                        new = max(old * self.factor, self.min_lr)
                        if old - new > 1e-12:
                            opt.set_lr(new)
                            if self.verbose:
                                print(f"ReduceLROnPlateau: lr {old:.6g} "
                                      f"-> {new:.6g}")
                self.cooldown_counter = self.cooldown
                self.wait = 0


class VisualDL(Callback):
    """Stub: VisualDL isn't available in this environment; logs scalars to
    a jsonl file instead (same call surface)."""

    def __init__(self, log_dir="./log"):
        super().__init__()
        self.log_dir = log_dir
        self._step = 0

    def on_train_batch_end(self, step, logs=None):
        import json

        os.makedirs(self.log_dir, exist_ok=True)
        with open(os.path.join(self.log_dir, "scalars.jsonl"), "a") as f:
            f.write(json.dumps({"step": step, **(logs or {})}) + "\n")
