"""paddle.Model — the high-level train/eval/predict API.

Ref parity: python/paddle/hapi/model.py:878 (Model), 1523 (fit), with the
dual Static/DynamicGraphAdapter collapsed: there is one execution path (the
functional engine compiles the step; eager fallback for debugging).
"""

from __future__ import annotations

import os

import numpy as np

from ..core.tensor import Tensor
from ..engine import Engine
from ..io import DataLoader
from .callbacks import CallbackList, ProgBarLogger


class Model:
    def __init__(self, network, inputs=None, labels=None):
        self.network = network
        self._inputs = inputs
        self._labels = labels
        self._optimizer = None
        self._loss = None
        self._metrics = []
        self._engine = None
        self.stop_training = False
        self._compiled_mode = True  # compile steps via the engine
        self._amp_level = None
        self._amp_dtype = "bfloat16"
        self._loss_scale = None

    # -- prepare -------------------------------------------------------------
    def prepare(self, optimizer=None, loss=None, metrics=None,
                amp_configs=None, jit_compile=True):
        self._optimizer = optimizer
        self._loss = loss
        if metrics is not None:
            self._metrics = metrics if isinstance(metrics, (list, tuple)) \
                else [metrics]
        self._compiled_mode = jit_compile
        # amp_configs (ref hapi/model.py prepare + amp/grad_scaler.py):
        # 'O1'/'O2' level enables autocast around the compiled step;
        # loss-scaling knobs flow to the engine's in-graph scaler
        self._amp_level = None
        self._amp_dtype = "bfloat16"
        self._loss_scale = None
        if amp_configs is not None:
            if isinstance(amp_configs, str):
                amp_configs = {"level": amp_configs}
            cfg = dict(amp_configs)
            self._amp_level = cfg.pop("level", "O1")
            self._amp_dtype = cfg.pop("dtype", "bfloat16")
            if self._amp_level in ("O0", None):
                self._amp_level = None
            if cfg.pop("use_dynamic_loss_scaling", True):
                knobs = {k: v for k, v in cfg.items()
                         if k in ("init_loss_scaling", "incr_ratio",
                                  "decr_ratio", "incr_every_n_steps",
                                  "decr_every_n_nan_or_inf")}
                self._loss_scale = knobs if knobs else "dynamic"
            else:
                self._loss_scale = float(
                    cfg.get("init_loss_scaling", 2.0 ** 15))
            scaler_knobs = ("init_loss_scaling", "incr_ratio", "decr_ratio",
                            "incr_every_n_steps", "decr_every_n_nan_or_inf",
                            "use_dynamic_loss_scaling")
            if self._amp_dtype == "bfloat16" and not any(
                    k in amp_configs for k in scaler_knobs):
                # bf16 has fp32's exponent range: scaling is unnecessary
                # unless any scaler knob was explicitly configured
                # (paddle bf16 semantics)
                self._loss_scale = None
        return self

    # -- single-batch APIs ---------------------------------------------------
    def _ensure_engine(self):
        if self._engine is None:
            self._engine = Engine(self.network, self._optimizer, self._loss,
                                  loss_scale=self._loss_scale)
        return self._engine

    def _amp_scope(self):
        import contextlib

        if self._amp_level is None:
            return contextlib.nullcontext()
        from .. import amp

        return amp.auto_cast(enable=True, dtype=self._amp_dtype,
                             level=self._amp_level)

    def train_batch(self, inputs, labels=None, update=True):
        inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        labels = labels if labels is None or isinstance(
            labels, (list, tuple)) else [labels]
        if self._compiled_mode:
            eng = self._ensure_engine()
            with self._amp_scope():
                loss = eng.train_batch(inputs, labels or ())
            return [float(loss.item())]
        # eager path
        self.network.train()
        outputs = self.network(*[_as_tensor(x) for x in inputs])
        loss = self._loss(outputs, *[_as_tensor(l) for l in labels or []])
        loss.backward()
        if update:
            self._optimizer.step()
            self._optimizer.clear_grad()
        return [float(loss.item())]

    def eval_batch(self, inputs, labels=None):
        self.network.eval()
        if self._engine is not None:
            self._engine.sync_to_layer()
        inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        outputs = self.network(*[_as_tensor(x) for x in inputs])
        results = []
        if self._loss is not None and labels:
            loss = self._loss(outputs, *[_as_tensor(l) for l in labels])
            results.append(float(loss.item()))
        metric_results = []
        for m in self._metrics:
            pred = outputs[0] if isinstance(outputs, (list, tuple)) \
                else outputs
            corr = m.compute(pred, *[_as_tensor(l) for l in labels or []])
            m.update(corr)
            metric_results.append(m.accumulate())
        self.network.train()
        return results, metric_results

    def predict_batch(self, inputs):
        self.network.eval()
        if self._engine is not None:
            self._engine.sync_to_layer()
        inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        out = self.network(*[_as_tensor(x) for x in inputs])
        self.network.train()
        return [o.numpy() if isinstance(o, Tensor) else o
                for o in (out if isinstance(out, (list, tuple)) else [out])]

    def _emergency_save(self, save_dir, *, epoch, step):
        """Preemption checkpoint: full engine state (params, moments,
        step, RNG) to <save_dir>/preempt-ckpt plus a PREEMPTED marker so
        the restarted job knows to resume rather than start fresh. With
        no save_dir there is nowhere durable to write — training just
        stops at the batch boundary."""
        from ..distributed import checkpoint as _ckpt, preempt as _preempt
        from ..framework import monitor as _monitor

        if not save_dir:
            return
        if self._engine is not None:
            _ckpt.save_train_state(
                os.path.join(save_dir, "preempt-ckpt"), self._engine)
        else:
            self.save(os.path.join(save_dir, "preempt-ckpt", "model"))
        _preempt.write_marker(save_dir, {"epoch": epoch, "step": step})
        _monitor.stat_add("preempt_emergency_saves")

    # -- fit/evaluate/predict -----------------------------------------------
    def fit(self, train_data=None, eval_data=None, batch_size=1, epochs=1,
            eval_freq=1, log_freq=10, save_dir=None, save_freq=1,
            verbose=2, drop_last=False, shuffle=True, num_workers=0,
            callbacks=None, accumulate_grad_batches=1, num_iters=None):
        train_loader = _as_loader(train_data, batch_size, shuffle,
                                  drop_last, num_workers)
        eval_loader = _as_loader(eval_data, batch_size, False, False,
                                 num_workers) if eval_data is not None \
            else None
        cbks = CallbackList([ProgBarLogger(log_freq, verbose=verbose)] +
                            (callbacks or []))
        cbks.set_model(self)
        steps = _safe_len(train_loader)
        cbks.set_params({"epochs": epochs, "steps": steps,
                         "verbose": verbose, "save_dir": save_dir})
        cbks.on_train_begin()
        self.stop_training = False
        # preemption-safe fit: SIGTERM/SIGUSR1 stop training at the next
        # BATCH boundary with an emergency checkpoint instead of dying
        # mid-step (ref: the reference elastic stack had no graceful path)
        from ..distributed import preempt as _preempt

        _preempt.install()
        it = 0
        for epoch in range(epochs):
            if self.stop_training:
                break
            cbks.on_epoch_begin(epoch)
            for m in self._metrics:
                m.reset()
            logs = {}
            for step, batch in enumerate(train_loader):
                cbks.on_train_batch_begin(step)
                inputs, labels = _split_batch(batch)
                losses = self.train_batch(inputs, labels)
                logs = {"loss": losses[0]}
                cbks.on_train_batch_end(step, logs)
                it += 1
                if num_iters is not None and it >= num_iters:
                    self.stop_training = True
                    break
                if _preempt.poll():
                    self._emergency_save(save_dir, epoch=epoch, step=step)
                    self.stop_training = True
                    break
            if eval_loader is not None and (epoch + 1) % eval_freq == 0:
                eval_logs = self.evaluate(
                    eval_loader, batch_size=batch_size, verbose=0,
                    num_workers=num_workers)
                logs.update(eval_logs)
                cbks.on_eval_end(eval_logs)
            cbks.on_epoch_end(epoch, logs)
        cbks.on_train_end(logs if "logs" in dir() else None)

    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=2,
                 num_workers=0, callbacks=None, num_samples=None):
        loader = _as_loader(eval_data, batch_size, False, False, num_workers)
        for m in self._metrics:
            m.reset()
        losses = []
        for batch in loader:
            inputs, labels = _split_batch(batch)
            res, _ = self.eval_batch(inputs, labels)
            if res:
                losses.append(res[0])
        logs = {}
        if losses:
            logs["eval_loss"] = float(np.mean(losses))
        for m in self._metrics:
            name = m.name()
            acc = m.accumulate()
            if isinstance(name, (list, tuple)):
                for n, a in zip(name, acc):
                    logs[f"eval_{n}"] = a
            else:
                logs[f"eval_{name}"] = acc
        return logs

    def predict(self, test_data, batch_size=1, num_workers=0,
                stack_outputs=False, verbose=1, callbacks=None):
        loader = _as_loader(test_data, batch_size, False, False, num_workers)
        outputs = []
        for batch in loader:
            inputs, _ = _split_batch(batch)
            outputs.append(self.predict_batch(inputs))
        if stack_outputs:
            n_out = len(outputs[0])
            return [np.concatenate([o[i] for o in outputs])
                    for i in range(n_out)]
        return outputs

    # -- persistence ---------------------------------------------------------
    def save(self, path, training=True):
        from ..framework.io import save as _save

        if self._engine is not None:
            self._engine.sync_to_layer()
        _save(self.network.state_dict(), path + ".pdparams")
        if training and self._optimizer is not None:
            _save(self._optimizer.state_dict(), path + ".pdopt")

    def load(self, path, skip_mismatch=False, reset_optimizer=False):
        from ..framework.io import load as _load

        sd = _load(path + ".pdparams")
        self.network.set_state_dict(sd)
        import os

        if not reset_optimizer and self._optimizer is not None and \
                os.path.exists(path + ".pdopt"):
            self._optimizer.set_state_dict(_load(path + ".pdopt"))

    def parameters(self, *args, **kwargs):
        return self.network.parameters(*args, **kwargs)

    def summary(self, input_size=None, dtype=None):
        total = 0
        trainable = 0
        lines = ["-" * 60]
        for name, p in self.network.named_parameters():
            n = int(np.prod(p.shape)) if p.shape else 1
            total += n
            if not p.stop_gradient:
                trainable += n
            lines.append(f"{name:<40} {str(p.shape):<18} {n}")
        lines.append("-" * 60)
        lines.append(f"Total params: {total}")
        lines.append(f"Trainable params: {trainable}")
        print("\n".join(lines))
        return {"total_params": total, "trainable_params": trainable}

    def flops(self, input_spec=None):
        """Analytic forward FLOPs for one input (ref hapi flops/paddle.flops).

        Counted from XLA's own cost analysis of the traced forward —
        exact for whatever the model actually computes, no per-layer
        bookkeeping. `input_spec`: list of InputSpec/arrays; falls back
        to self._inputs from prepare()."""
        import jax

        from ..engine import functional_call, state_values
        from ..jit import InputSpec

        spec = input_spec if input_spec is not None else self._inputs
        if spec is None:
            raise ValueError(
                "flops() needs input_spec (or Model(..., inputs=...))")
        shapes = []
        for s in spec:
            if isinstance(s, InputSpec):
                shapes.append(s.to_shape_dtype())
            else:
                arr = np.asarray(s)
                shapes.append(jax.ShapeDtypeStruct(arr.shape, arr.dtype))
        values = dict(state_values(self.network))

        def run(values, *args):
            return functional_call(self.network, values, *args)

        lowered = jax.jit(run).lower(
            jax.tree.map(lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype),
                         values), *shapes)
        # HLO cost analysis without compiling (compilation would take
        # seconds-to-minutes on large models just to read a count)
        cost = lowered.cost_analysis()
        return int(cost.get("flops", 0)) if cost else 0


def _as_tensor(x):
    return x if isinstance(x, Tensor) else Tensor(x)


def _as_loader(data, batch_size, shuffle, drop_last, num_workers):
    if data is None or isinstance(data, DataLoader):
        return data
    return DataLoader(data, batch_size=batch_size, shuffle=shuffle,
                      drop_last=drop_last, num_workers=num_workers)


def _split_batch(batch):
    if isinstance(batch, (list, tuple)):
        if len(batch) >= 2:
            return [batch[0]], list(batch[1:])
        return [batch[0]], []
    return [batch], []


def _safe_len(loader):
    try:
        return len(loader)
    except (RuntimeError, TypeError):
        return None
