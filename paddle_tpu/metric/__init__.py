"""Streaming metrics (ref: python/paddle/metric/metrics.py)."""

from __future__ import annotations

import numpy as np

from ..core.tensor import Tensor


def _np(x):
    return np.asarray(x._value) if isinstance(x, Tensor) else np.asarray(x)


class Metric:
    def reset(self):
        raise NotImplementedError

    def update(self, *args):
        raise NotImplementedError

    def accumulate(self):
        raise NotImplementedError

    def name(self):
        raise NotImplementedError

    def compute(self, *args):
        return args


class Accuracy(Metric):
    def __init__(self, topk=(1,), name=None):
        self.topk = topk if isinstance(topk, (list, tuple)) else (topk,)
        self.maxk = max(self.topk)
        self._name = name or "acc"
        self.reset()

    def reset(self):
        self.total = [0.0] * len(self.topk)
        self.count = [0] * len(self.topk)

    def compute(self, pred, label, *args):
        pred_np = _np(pred)
        label_np = _np(label)
        if label_np.ndim == pred_np.ndim and label_np.shape[-1] == 1:
            label_np = label_np.squeeze(-1)
        order = np.argsort(-pred_np, axis=-1)[..., : self.maxk]
        correct = (order == label_np[..., None])
        return Tensor(correct.astype(np.float32))

    def update(self, correct, *args):
        correct_np = _np(correct)
        accs = []
        for i, k in enumerate(self.topk):
            num_correct = correct_np[..., :k].sum()
            num_samples = int(np.prod(correct_np.shape[:-1]))
            self.total[i] += num_correct
            self.count[i] += num_samples
            accs.append(float(num_correct) / max(num_samples, 1))
        return accs[0] if len(self.topk) == 1 else accs

    def accumulate(self):
        res = [t / max(c, 1) for t, c in zip(self.total, self.count)]
        return res[0] if len(self.topk) == 1 else res

    def name(self):
        if len(self.topk) == 1:
            return self._name
        return [f"{self._name}_top{k}" for k in self.topk]


class Precision(Metric):
    def __init__(self, name="precision"):
        self._name = name
        self.reset()

    def reset(self):
        self.tp = 0
        self.fp = 0

    def update(self, preds, labels):
        preds_np = (_np(preds) > 0.5).astype(np.int32).reshape(-1)
        labels_np = _np(labels).astype(np.int32).reshape(-1)
        self.tp += int(np.sum((preds_np == 1) & (labels_np == 1)))
        self.fp += int(np.sum((preds_np == 1) & (labels_np == 0)))

    def accumulate(self):
        denom = self.tp + self.fp
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Recall(Metric):
    def __init__(self, name="recall"):
        self._name = name
        self.reset()

    def reset(self):
        self.tp = 0
        self.fn = 0

    def update(self, preds, labels):
        preds_np = (_np(preds) > 0.5).astype(np.int32).reshape(-1)
        labels_np = _np(labels).astype(np.int32).reshape(-1)
        self.tp += int(np.sum((preds_np == 1) & (labels_np == 1)))
        self.fn += int(np.sum((preds_np == 0) & (labels_np == 1)))

    def accumulate(self):
        denom = self.tp + self.fn
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Auc(Metric):
    """Streaming AUC via threshold bucketing (ref: metrics.py Auc)."""

    def __init__(self, curve="ROC", num_thresholds=4095, name="auc"):
        self._curve = curve
        self._num_thresholds = num_thresholds
        self._name = name
        self.reset()

    def reset(self):
        n = self._num_thresholds + 1
        self._stat_pos = np.zeros(n, dtype=np.int64)
        self._stat_neg = np.zeros(n, dtype=np.int64)

    def update(self, preds, labels):
        preds_np = _np(preds)
        labels_np = _np(labels).reshape(-1)
        if preds_np.ndim == 2:
            pos_prob = preds_np[:, 1]
        else:
            pos_prob = preds_np.reshape(-1)
        bins = (pos_prob * self._num_thresholds).astype(np.int64)
        bins = np.clip(bins, 0, self._num_thresholds)
        for b, l in zip(bins, labels_np):
            if l:
                self._stat_pos[b] += 1
            else:
                self._stat_neg[b] += 1

    def accumulate(self):
        tot_pos = 0.0
        tot_neg = 0.0
        auc = 0.0
        for i in range(self._num_thresholds, -1, -1):
            p = self._stat_pos[i]
            n = self._stat_neg[i]
            auc += n * tot_pos + p * n / 2.0
            tot_pos += p
            tot_neg += n
        if tot_pos == 0 or tot_neg == 0:
            return 0.0
        return auc / tot_pos / tot_neg

    def name(self):
        return self._name


def accuracy(input, label, k=1, correct=None, total=None, name=None):
    """Functional accuracy (ref: python/paddle/metric/metrics.py:789)."""
    pred_np = _np(input)
    label_np = _np(label)
    if label_np.ndim == pred_np.ndim and label_np.shape[-1] == 1:
        label_np = label_np.squeeze(-1)
    order = np.argsort(-pred_np, axis=-1)[..., :k]
    correct_np = (order == label_np[..., None]).any(axis=-1)
    return Tensor(np.asarray(correct_np.mean(), dtype=np.float32))
