"""paddle.incubate (ref python/paddle/fluid/incubate + paddle/incubate)."""

from . import asp  # noqa: F401
