"""paddle.incubate (ref python/paddle/fluid/incubate + paddle/incubate)."""

from . import asp  # noqa: F401
from .optimizer import (  # noqa: F401
    ExponentialMovingAverage, LookAhead, ModelAverage,
)
