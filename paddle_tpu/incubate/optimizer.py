"""Optimizer wrappers: LookAhead, ModelAverage, ExponentialMovingAverage.

Parity targets: python/paddle/incubate/optimizer/lookahead.py:118 (slow/
fast two-speed update), python/paddle/incubate/optimizer/modelaverage.py
+ paddle/fluid/operators/average_accumulates_op.h:80-106 (windowed sum
rotation), python/paddle/fluid/optimizer.py:3883 (ExponentialMovingAverage
with bias correction and thres_steps decay scheduling).

TPU-native design: each wrapper is itself an `Optimizer` whose pure
per-parameter `_rule` runs the wrapped optimizer's rule and then the
wrapper's own state transition, so the whole composite lowers into the
SAME compiled train step as the inner optimizer (the Engine maps `_rule`
over the parameter tree inside jit).  `jnp.where` on traced step
counters replaces the reference's host-side branches, so the k-step
LookAhead sync and the ModelAverage window rotation compile once and
never re-trace.  Wrapper state lives in the same flat per-param state
dict as the inner state (prefixed keys), so optimizer.state_dict() /
checkpointing work unchanged.

Deviation from the reference kernel (documented): average_accumulates'
16384-step precision spill uses the *pre-accumulation* sum_1 and drops
the current param from the spilled bucket (average_accumulates_op.h:87-93
reads in_sum_* after out_sum_1 was already updated); we spill the
post-accumulation sum so no step is ever dropped.  The difference is one
sample per 16384 at the spill boundary.
"""

from __future__ import annotations

from contextlib import contextmanager

import jax.numpy as jnp

from ..core import config
from ..optimizer import Optimizer

__all__ = ["LookAhead", "ModelAverage", "ExponentialMovingAverage"]


def _split_state(state, prefix):
    inner = {k: v for k, v in state.items() if not k.startswith(prefix)}
    return inner


class _WrappedOptimizer(Optimizer):
    """Shared plumbing: delegate lr/hyper/decay semantics to the inner
    optimizer and provide apply()/restore() swapping for eval."""

    _PREFIX = "wrap_"

    def __init__(self, inner_optimizer, parameters=None):
        self.inner = inner_optimizer
        if inner_optimizer is not None:
            params = (parameters if parameters is not None
                      else inner_optimizer._parameter_list)
            super().__init__(inner_optimizer._learning_rate, params,
                             None, inner_optimizer._grad_clip)
            # already-normalised decay object; bypass _as_decay
            self._weight_decay = inner_optimizer._weight_decay
        else:
            super().__init__(0.0, parameters, None, None)
        self._backup = {}

    # -- delegation ----------------------------------------------------------
    def get_lr(self):
        return self.inner.get_lr() if self.inner is not None else 0.0

    def set_lr(self, value):
        if self.inner is None:
            raise RuntimeError("no inner optimizer")
        self.inner.set_lr(value)

    def _hyper(self):
        return self.inner._hyper() if self.inner is not None else {}

    def _hyper_for(self, p):
        return self.inner._hyper_for(p) if self.inner is not None else {}

    def _decoupled_weight_decay(self):
        return (self.inner._decoupled_weight_decay()
                if self.inner is not None else False)

    def _inner_apply(self, param, grad, state, lr, hyper):
        if self.inner is None:
            return param, {}
        inner_st = _split_state(state, self._PREFIX)
        return self.inner._rule(param, grad, inner_st, lr, **hyper)

    # -- eval-time parameter swap -------------------------------------------
    def _averaged_value(self, state, param):
        raise NotImplementedError

    def _iter_param_states(self, engine=None):
        """Yield (setter, getter, state) triples for every parameter,
        from either the eager accumulators or an Engine's compiled
        opt_state."""
        if engine is not None:
            sd = engine.layer.state_dict()
            for name, value in list(engine.state.params.items()):
                st = engine.state.opt_state.get(name)
                if st is None:
                    continue

                def setter(v, name=name):
                    engine.state.params[name] = v
                    if name in sd:
                        sd[name]._value = v
                yield name, setter, value, st
        else:
            for i, p in enumerate(self._parameter_list or []):
                if p is None:
                    continue
                st = self._accumulators.get(id(p))
                if st is None:
                    continue

                def setter(v, p=p):
                    p._value = v
                yield (p.name or f"param_{i}"), setter, p._value, st

    @config.no_grad()
    def _apply_swap(self, engine=None):
        if self._backup:
            raise RuntimeError("apply() is not reentrant; call restore()")
        for name, setter, value, st in self._iter_param_states(engine):
            self._backup[name] = value
            setter(self._averaged_value(st, value))

    @config.no_grad()
    def restore(self, executor=None, engine=None):
        """Put the original (non-averaged) parameters back.  Pass the
        same `engine=` that apply() was given — the backups are keyed by
        the parameter set that was swapped."""
        for name, setter, value, st in self._iter_param_states(engine):
            if name in self._backup:
                setter(self._backup.pop(name))
        if self._backup:
            raise RuntimeError(
                "restore() could not find parameters for saved backups "
                f"{sorted(self._backup)}; if apply() was given engine=, "
                "restore() needs the same engine= (originals are still "
                "held in ._backup)")

    @contextmanager
    def apply(self, executor=None, need_restore=True, engine=None):
        """Swap parameters to their averaged values for evaluation.

        `engine=` applies to an Engine's compiled state (and writes
        through to the layer); otherwise the eager Parameter list is
        swapped in place.  `executor` accepted for reference-API
        compatibility and ignored (no separate apply program is needed —
        the swap is a host-side tree update).
        """
        self._apply_swap(engine=engine)
        try:
            yield
        finally:
            if need_restore:
                self.restore(engine=engine)


class LookAhead(_WrappedOptimizer):
    """k-step slow/fast weights (ref incubate/optimizer/lookahead.py:118).

    Every step the inner optimizer updates the fast weights; every k-th
    step  slow += alpha * (fast - slow);  fast = slow.
    """

    _PREFIX = "la_"

    def __init__(self, inner_optimizer, alpha=0.5, k=5, name=None):
        if inner_optimizer is None:
            raise ValueError("LookAhead needs an inner optimizer")
        super().__init__(inner_optimizer)
        self.alpha = float(alpha)
        self.k = int(k)

    def _init_state(self, value):
        st = dict(self.inner._init_state(value))
        # copy=True: the engine donates params and opt_state separately,
        # so the slow weights must not alias the parameter buffer
        st["la_slow"] = jnp.array(value, copy=True)
        st["la_step"] = jnp.zeros((), jnp.int32)
        return st

    def _rule(self, param, grad, state, lr, **hyper):
        fast, new_inner = self._inner_apply(param, grad, state, lr, hyper)
        step = state["la_step"] + 1
        sync = (step % self.k) == 0
        slow = jnp.where(
            sync,
            state["la_slow"] + self.alpha * (fast - state["la_slow"]),
            state["la_slow"]).astype(param.dtype)
        fast = jnp.where(sync, slow, fast).astype(param.dtype)
        out = dict(new_inner)
        out["la_slow"] = slow
        out["la_step"] = step
        return fast, out

    def _averaged_value(self, state, param):
        # eval on the slow weights
        return state["la_slow"]


class ModelAverage(_WrappedOptimizer):
    """Windowed parameter averaging (ref incubate/optimizer/
    modelaverage.py + average_accumulates_op.h:80-106).

    Maintains sum_1/sum_2/sum_3 running-parameter sums; when the window
    num_accumulates >= max(min_average_window,
                           min(max_average_window, num_updates * rate))
    is exceeded the old sums rotate into sum_3.  `apply()` swaps params
    to (sum_1+sum_2+sum_3)/(num_accumulates+old_num_accumulates).

    Use standalone (reference API: step() after the main optimizer's
    step) or as a wrapper (`inner_optimizer=`) so the accumulation runs
    inside the compiled Engine train step.
    """

    _PREFIX = "ma_"
    _SPILL = 16384  # ref kMaxNumAccumulates precision spill

    def __init__(self, average_window_rate, parameters=None,
                 min_average_window=10000, max_average_window=10000,
                 name=None, inner_optimizer=None):
        super().__init__(inner_optimizer, parameters=parameters)
        self.average_window = float(average_window_rate)
        self.min_average_window = int(min_average_window)
        self.max_average_window = int(max_average_window)

    def _init_state(self, value):
        st = (dict(self.inner._init_state(value))
              if self.inner is not None else {})
        # three distinct buffers: donation forbids aliased leaves
        st.update({
            "ma_sum_1": jnp.zeros_like(value),
            "ma_sum_2": jnp.zeros_like(value),
            "ma_sum_3": jnp.zeros_like(value),
            "ma_num_acc": jnp.zeros((), jnp.int32),
            "ma_old_num_acc": jnp.zeros((), jnp.int32),
            "ma_num_upd": jnp.zeros((), jnp.int32),
        })
        return st

    def _accumulate(self, param, st):
        n_upd = st["ma_num_upd"] + 1
        n_acc = st["ma_num_acc"] + 1
        s1 = st["ma_sum_1"] + param
        s2, s3 = st["ma_sum_2"], st["ma_sum_3"]
        spill = (n_upd % self._SPILL) == 0
        s2 = jnp.where(spill, s2 + s1, s2)
        s1 = jnp.where(spill, jnp.zeros_like(s1), s1)
        window = jnp.minimum(
            jnp.float32(self.max_average_window),
            n_upd.astype(jnp.float32) * self.average_window)
        rot = ((n_acc >= self.min_average_window)
               & (n_acc.astype(jnp.float32) >= window))
        s3 = jnp.where(rot, s1 + s2, s3)
        s1 = jnp.where(rot, jnp.zeros_like(s1), s1)
        s2 = jnp.where(rot, jnp.zeros_like(s2), s2)
        old = jnp.where(rot, n_acc, st["ma_old_num_acc"])
        n_acc = jnp.where(rot, 0, n_acc)
        return {"ma_sum_1": s1, "ma_sum_2": s2, "ma_sum_3": s3,
                "ma_num_acc": n_acc, "ma_old_num_acc": old,
                "ma_num_upd": n_upd}

    def _rule(self, param, grad, state, lr, **hyper):
        new_p, new_inner = self._inner_apply(param, grad, state, lr, hyper)
        out = dict(new_inner)
        out.update(self._accumulate(new_p, state))
        return new_p, out

    @config.no_grad()
    def step(self):
        """Standalone accumulation pass (call after the main optimizer's
        step, reference usage).  Accumulates every parameter in the list
        whether or not it has a gradient this step."""
        if self.inner is not None:
            return super().step()
        self._global_step += 1
        for p in self._parameter_list or []:
            if p is None:
                continue
            st = self._state_for(p)
            new_p, new_st = self._run_rule(
                p._value, p._value, st, 0.0, self._hyper_for(p))
            self._accumulators[id(p)] = new_st

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        self.step()

    def _averaged_value(self, state, param):
        total = (state["ma_num_acc"]
                 + state["ma_old_num_acc"]).astype(param.dtype)
        avg = ((state["ma_sum_1"] + state["ma_sum_2"] + state["ma_sum_3"])
               / jnp.maximum(total, 1))
        return jnp.where(total > 0, avg, param).astype(param.dtype)


class ExponentialMovingAverage(_WrappedOptimizer):
    """EMA of parameters with bias correction (ref fluid/optimizer.py:3883).

        ema_t = decay * ema_{t-1} + (1 - decay) * theta_t
        apply:  theta_eval = ema_t / (1 - prod_i decay_i)

    `thres_steps=None` uses the constant decay; any other value enables
    the reference's decay schedule min(decay, (1+t)/(10+t)) driven by
    the internal update counter (the static-graph reference threads a
    global-step Variable; the counter already lives in compiled state
    here, so no Variable plumbing is needed).

    Use standalone (update() after each optimizer step, reference API)
    or as a wrapper (`inner_optimizer=`) so the EMA accumulates inside
    the compiled Engine train step.
    """

    _PREFIX = "ema_"

    def __init__(self, decay=0.999, thres_steps=None, name=None,
                 parameters=None, inner_optimizer=None):
        super().__init__(inner_optimizer, parameters=parameters)
        self.decay = float(decay)
        self._thres_steps = thres_steps

    def _init_state(self, value):
        st = (dict(self.inner._init_state(value))
              if self.inner is not None else {})
        st.update({
            "ema_avg": jnp.zeros_like(value),
            "ema_decay_prod": jnp.ones((), jnp.float32),
            "ema_t": jnp.zeros((), jnp.int32),
        })
        return st

    def _decay_t(self, t):
        if self._thres_steps is None:
            return jnp.float32(self.decay)
        tf = t.astype(jnp.float32)
        return jnp.minimum(jnp.float32(self.decay),
                           (1.0 + tf) / (10.0 + tf))

    def _ema_update(self, param, st):
        d = self._decay_t(st["ema_t"])
        avg = (d * st["ema_avg"]
               + (1.0 - d) * param.astype(st["ema_avg"].dtype))
        return {"ema_avg": avg,
                "ema_decay_prod": st["ema_decay_prod"] * d,
                "ema_t": st["ema_t"] + 1}

    def _rule(self, param, grad, state, lr, **hyper):
        new_p, new_inner = self._inner_apply(param, grad, state, lr, hyper)
        out = dict(new_inner)
        out.update(self._ema_update(new_p, state))
        return new_p, out

    @config.no_grad()
    def update(self):
        """Standalone EMA accumulation (call after each optimizer step,
        reference API)."""
        self._global_step += 1
        for p in self._parameter_list or []:
            if p is None:
                continue
            st = self._state_for(p)
            _, new_st = self._run_rule(
                p._value, p._value, st, 0.0, self._hyper_for(p))
            self._accumulators[id(p)] = new_st

    def _averaged_value(self, state, param):
        corr = 1.0 - state["ema_decay_prod"]
        avg = state["ema_avg"] / jnp.maximum(corr, 1e-12)
        return jnp.where(state["ema_t"] > 0, avg, param).astype(param.dtype)
