"""ASP — automatic 2:4 structured sparsity.

Ref parity: python/paddle/fluid/contrib/sparsity/ (utils.py mask
generation, asp.py prune_model/decorate) + fleet/meta_optimizers/
asp_optimizer.py. Same workflow: compute n:m masks for eligible weights,
prune in place, and decorate the optimizer so masks are re-applied after
every step (keeping pruned weights at zero through training).
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp

__all__ = [
    "calculate_density", "check_sparsity", "create_mask", "prune_model",
    "decorate", "set_excluded_layers", "reset_excluded_layers",
    "dequant_masked_matmul",
]

import weakref

_excluded_names: set = set()
# id(param) -> (weakref(param), jnp mask). The weakref guards against
# CPython id reuse: a dead parameter's id can be recycled by an
# unrelated Parameter, which must NOT inherit the mask.
_masks: dict = {}


def _mask_of(p):
    entry = _masks.get(id(p))
    if entry is None:
        return None
    ref, mask = entry
    if ref() is not p:
        del _masks[id(p)]  # stale id-reuse entry
        return None
    return mask


def calculate_density(mat) -> float:
    mat = np.asarray(mat)
    return float(np.count_nonzero(mat)) / mat.size


def create_mask(mat, n=2, m=4):
    """n:m mask along the last axis: keep the n largest |values| in every
    group of m (ref sparsity/utils.py get_mask_1d)."""
    arr = np.asarray(mat)
    if arr.shape[-1] % m != 0:
        raise ValueError(
            f"last dim {arr.shape[-1]} not divisible by m={m}")
    groups = np.abs(arr).reshape(-1, m)
    order = np.argsort(-groups, axis=1, kind="stable")
    mask = np.zeros_like(groups, dtype=bool)
    np.put_along_axis(mask, order[:, :n], True, axis=1)
    return mask.reshape(arr.shape)


def check_sparsity(mat, n=2, m=4) -> bool:
    """True iff every m-group along the last axis has <= n non-zeros
    (ref sparsity/utils.py check_mask_1d)."""
    arr = np.asarray(mat)
    if arr.shape[-1] % m != 0:
        return False
    nz = (arr.reshape(-1, m) != 0).sum(axis=1)
    return bool((nz <= n).all())


def set_excluded_layers(param_names):
    """Exclude parameters by name substring (ref asp.py
    set_excluded_layers)."""
    _excluded_names.update(param_names)


def reset_excluded_layers():
    _excluded_names.clear()


def _eligible(name, param):
    if param.ndim < 2:
        return False
    if param._value.shape[-1] % 4 != 0:
        return False
    return not any(sub in (name or "") for sub in _excluded_names)


def prune_model(model, n=2, m=4, mask_algo="mask_1d", with_mask=True):
    """Compute + apply n:m masks to every eligible weight of `model`
    (ref asp.py prune_model). Returns {param_name: mask}."""
    out = {}
    for name, p in model.state_dict().items():
        from ..core.tensor import Parameter

        if not isinstance(p, Parameter) or not _eligible(name, p):
            continue
        mask = create_mask(p.numpy(), n=n, m=m)
        jmask = jnp.asarray(mask, p._value.dtype)
        p._value = p._value * jmask
        if with_mask:
            pid = id(p)
            # the callback evicts the entry when the parameter dies, so
            # masks of discarded models don't accumulate
            _masks[pid] = (weakref.ref(
                p, lambda _, pid=pid: _masks.pop(pid, None)), jmask)
        out[name] = mask
    return out


def masks_for(layer):
    """{param_name: mask} for this layer's pruned params — consumed by
    the compiled engines (ref asp_optimizer.py ASPOptimizer: the same
    re-masking, but inside the jitted step instead of a program pass).
    Resolved through the layer's own Parameter identities, so models
    sharing parameter names never pick up each other's masks.

    Snapshotted when an engine builds its step (first train_batch):
    call prune_model BEFORE the first step; pruning mid-training only
    affects the eager ASPOptimizerWrapper path."""
    out = {}
    for k, p in layer.state_dict().items():
        mask = _mask_of(p)
        if mask is not None:
            out[k] = mask
    return out


def stacked_masks_for(layer, block_regex, num_layers, num_stages):
    """Masks for pipeline-STACKED block params (HybridParallelEngine):
    per-layer masks of params matching `block_regex` (one group for the
    layer index, one for the within-block name) are stacked in layer
    order to [L, ...] and folded to [S, L/S, ...], matching the
    engine's block_params layout.  Unpruned layers of a partially
    pruned stack get all-ones slices.  Returns (block_masks keyed by
    within-block name, covered full-name set)."""
    import re

    pat = re.compile(block_regex)
    per: dict = {}
    covered = set()
    for name, p in layer.state_dict().items():
        m = pat.match(name)
        if not m:
            continue
        mask = _mask_of(p)
        if mask is not None:
            per.setdefault(m.group(2), {})[int(m.group(1))] = mask
            covered.add(name)
    out = {}
    for sub, by_idx in per.items():
        shape = next(iter(by_idx.values())).shape
        ones = jnp.ones(shape, jnp.bool_)
        full = jnp.stack([by_idx.get(i, ones)
                          for i in range(num_layers)])
        out[sub] = full.reshape(
            (num_stages, num_layers // num_stages) + tuple(shape))
    return out, covered


def apply_masks_tree(layer, new_params, *, engine_name="engine",
                     masks=None):
    """Masking hook shared by ALL compiled engines: re-apply this
    layer's masks to the name-keyed `new_params` tree; warn once when a
    pruned parameter is not visible under its name in the tree (e.g.
    pipeline-stacked blocks rename it — pass `masks` with those names
    already removed after applying their stacked form), so sparsity is
    never silently dropped."""
    masks = masks_for(layer) if masks is None else masks
    if not masks:
        return new_params
    missing = [k for k in masks if k not in new_params]
    if missing:
        import warnings

        warnings.warn(
            f"ASP: {engine_name} cannot see pruned parameters "
            f"{missing} under their names (renamed/stacked); their 2:4 "
            "sparsity is NOT enforced on this path")
    return {k: (v * masks[k].astype(v.dtype)) if k in masks else v
            for k, v in new_params.items()}


def dequant_masked_matmul(x, qweight, scale, mask):
    """Sparsity x quantization (ISSUE 19 satellite): contract f32
    activations against a 2:4-masked int8 weight table through the
    `dequant_matmul` epilogue kernel, never materialising the
    dequantized weights.

    x: (..., K) activations; qweight: (N, K) int8 frozen rows (the
    quantize_state_int8 layout); scale: scalar or (N,) f32; mask:
    (N, K) bool/0-1 n:m mask over the SAME layout. Masking the int8
    code points IS masking the dequantized weights (dequant_int8 maps
    0 -> 0.0 exactly), so the composition stays bit-faithful to the
    dense dequant path with masked weights — the parity contract
    tests/test_lowp.py pins."""
    from ..ops.quant_ops import dequant_matmul

    qweight = jnp.asarray(qweight)
    mq = qweight * jnp.asarray(mask).astype(qweight.dtype)
    return dequant_matmul(x, mq, scale)


class ASPOptimizerWrapper:
    """Re-applies masks after each step so pruned weights stay zero
    (ref asp_optimizer.py ASPOptimizer)."""

    def __init__(self, inner):
        self.inner = inner

    def __getattr__(self, name):
        return getattr(self.inner, name)

    def step(self):
        self.inner.step()
        for p in self.inner._parameter_list:
            mask = _mask_of(p)
            if mask is not None:
                p._value = p._value * mask


def decorate(optimizer):
    """ref asp.py decorate(optimizer)."""
    return ASPOptimizerWrapper(optimizer)
