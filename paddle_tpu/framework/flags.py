"""Runtime flags registry: paddle.set_flags / get_flags + FLAGS_* env.

Ref parity: paddle/fluid/platform/flags.cc (gflags DEFINEs) +
pybind/global_value_getter_setter.cc (the Python surface). TPU-native
differences: flags that configured CUDA allocators/streams have no
meaning; the registry keeps the reference's user-visible debugging knobs
and adds XLA-relevant ones. Unknown flags raise (same as the reference's
enforce on unknown gflag).
"""

from __future__ import annotations

import os
import threading

_lock = threading.Lock()

# name -> (default, type, doc)
_DEFS = {
    "FLAGS_check_nan_inf": (
        False, bool,
        "scan every op output for NaN/Inf and raise (ref "
        "platform/flags.cc:44 + details/nan_inf_utils_detail.cu)"),
    "FLAGS_benchmark": (
        False, bool, "block after each op for stable timing"),
    "FLAGS_paddle_num_threads": (
        1, int, "host threads for the native datafeed"),
    "FLAGS_use_pallas": (
        True, bool, "use Pallas kernels on TPU where available"),
    "FLAGS_use_pallas_conv": (
        True, bool, "route eligible convs through the Pallas fused-conv "
        "kernels on TPU (PADDLE_TPU_CONV_FORCE=pallas|lax overrides)"),
    "FLAGS_use_fused_lm_loss": (
        True, bool,
        "route the tied-decoder matmul + cross_entropy of the ERNIE/BERT "
        "pretraining head through the fused chunked-vocab loss "
        "(ops/fused_loss.py) that never materializes [N, V] logits "
        "(PADDLE_TPU_LMLOSS_FORCE=pallas|lax picks the kernel path)"),
    "FLAGS_anomaly_check_interval": (
        16, int,
        "anomaly guard: read the in-graph bad-step counter back to the "
        "host only every N steps (1 = every step). The in-graph guard "
        "still skips every bad update immediately; the interval only "
        "delays the host-side rollback decision by up to N-1 steps in "
        "exchange for not blocking dispatch on a device sync per step"),
    "FLAGS_eager_delete_tensor_gb": (
        0.0, float, "accepted for compatibility; PJRT manages memory"),
    "FLAGS_cudnn_deterministic": (
        False, bool, "accepted for compatibility; XLA is deterministic "
        "modulo collectives"),
    "FLAGS_max_inplace_grad_add": (
        0, int, "accepted for compatibility"),
    "FLAGS_anomaly_max_bad_steps": (
        3, int,
        "compiled-path anomaly guard: after this many CONSECUTIVE "
        "non-finite steps (loss or grads), roll the engine back to the "
        "last good checkpoint (0 disables rollback; bad steps are still "
        "skipped in-graph)"),
    "FLAGS_ckpt_verify_checksums": (
        True, bool,
        "verify the per-leaf sha256 manifest when restoring a "
        "checkpoint (detects truncated/corrupted leaves)"),
    "FLAGS_simulate_preempt_at_step": (
        0, int,
        "testing: report a preemption at the Nth preemption poll "
        "(step/epoch boundary); 0 disables"),
    "FLAGS_ps_wal_sync_interval": (
        1, int,
        "parameter server: fsync the write-ahead log every N appended "
        "records (1 = every record). Larger values trade a bounded "
        "post-crash loss window (at most N-1 acknowledged-but-unsynced "
        "records) for push throughput; the default keeps the "
        "exactly-once recovery certification strict"),
    "FLAGS_ps_geo_staleness": (
        64, int,
        "parameter server geo-async mode: maximum update rows a "
        "trainer may accumulate locally before the Communicator forces "
        "a synchronous flush (0 disables the bound; the geo_step "
        "cadence still flushes). Bounds reader staleness in updates "
        "rather than steps per SURVEY.md's geo semantics"),
    "FLAGS_serving_max_batch": (
        8, int,
        "serving: slot-pool size of the continuous-batching decode "
        "engine and batch cap of the dynamic batcher (the bucket "
        "ladder tops out here)"),
    "FLAGS_serving_queue_cap": (
        64, int,
        "serving: bounded admission-queue capacity; submissions beyond "
        "it are shed immediately with QueueFullError (429-style)"),
    "FLAGS_serving_default_timeout_s": (
        30.0, float,
        "serving: default per-request deadline in seconds (0 = none); "
        "expired requests fail with DeadlineExceededError whether "
        "queued or mid-decode"),
    "FLAGS_serving_kv_block_size": (
        16, int,
        "serving: tokens per physical KV block of the paged cache; a "
        "request holds ceil((prompt+max_new)/block_size) blocks"),
    "FLAGS_serving_kv_blocks": (
        0, int,
        "serving: physical KV blocks in the pool (incl. reserved null "
        "block 0); 0 = auto-size to the dense-equivalent worst case "
        "max_slots*ceil(max_seq/block_size)+1"),
    "FLAGS_serving_prefill_chunk": (
        16, int,
        "serving: max prompt tokens a prefilling slot contributes to "
        "one unified decode step (chunked prefill; replaces the "
        "deleted FLAGS_serving_prefill_buckets trace ladder)"),
    "FLAGS_serving_prefix_cache": (
        True, bool,
        "serving: index finished sequences' KV blocks by cumulative "
        "token-prefix hash so later requests sharing a prefix (system "
        "prompts) reuse physical blocks, with copy-on-write on "
        "divergence"),
    "FLAGS_serving_spec_len": (
        0, int,
        "serving: speculative-decoding draft length k — each decode "
        "round proposes up to k tokens from the draft model and "
        "verifies them in one unified step (draft trace width k+1, "
        "verify rides the decode trace). 0 disables speculation; the "
        "engine then compiles no draft trace at all"),
    "FLAGS_serving_quantize": (
        False, bool,
        "serving: freeze 2-D float weights to int8 with per-tensor "
        "abs-max scales at engine build; the decode trace dequantizes "
        "in-trace (weights ride the jit boundary as int8 — the TPU win "
        "is HBM bytes) and the tied LM head runs the dequant-matmul "
        "epilogue from ops/quant_ops.py"),
    "FLAGS_serving_max_adapters": (
        0, int,
        "serving: capacity of the engine's stacked LoRA adapter bank "
        "([n, r, H] / [n, V, r] jit arguments of the one compiled "
        "decode step; each slot gathers its own adapter row by index). "
        "Row 0 is the base model (all-zero). 0 disables adapters and "
        "keeps every existing path byte-identical"),
    "FLAGS_serving_lora_rank": (
        8, int,
        "serving: low-rank dimension r of the batched LoRA adapter "
        "bank (used only when FLAGS_serving_max_adapters > 0)"),
    "FLAGS_tenant_default_budget": (
        0, int,
        "serving: default per-tenant token budget in tokens/second "
        "(token bucket, lazily refilled) for tenants the directory "
        "auto-creates; over-budget admissions shed with a 429 whose "
        "Retry-After derives from the bucket's refill. 0 = unlimited"),
    "FLAGS_tenant_wfq_quantum": (
        256, int,
        "serving: deficit-round-robin quantum in tokens credited to a "
        "tenant's queue per scheduler visit; a tenant's effective "
        "share is quantum * weight (TenantFairQueue)"),
    "FLAGS_serving_mesh": (
        "", str,
        "serving: mesh spec 'dpD.mpM' the SlotEngine shards weights and "
        "the paged KV pool over (partition rules from "
        "serving/sharding.py; block tables stay host-side and "
        "replica-global). Empty = single-device engine, exactly the "
        "pre-mesh behavior"),
    "FLAGS_serving_kv_spill_dir": (
        "", str,
        "serving: directory for the persistent SSD KV spill tier — "
        "cold KV blocks evicted from the radix prefix cache append "
        "their payloads here (crc32-framed, append-before-evict) and "
        "restore on session resume through the all-or-nothing "
        "admission path. Empty = spill tier disabled, exactly the "
        "pre-fabric behavior"),
    "FLAGS_serving_kv_spill_cap_mb": (
        256, int,
        "serving: soft cap in MiB on a replica's spill file; crossing "
        "it triggers a tmp+rename compaction that drops invalidated "
        "and superseded records (0 = never compact on size)"),
    "FLAGS_serving_prefix_affinity": (
        True, bool,
        "serving: route each request to the fleet replica holding the "
        "longest live prefix-cache match for its token prefix (sticky "
        "session affinity with clean failover when the affine replica "
        "is dead, draining, or breaker-open); False = pure "
        "least-loaded placement"),
    "FLAGS_serving_disagg": (
        False, bool,
        "serving: disaggregate prefill and decode — the Router sends "
        "each request's prefill to a prefill-role replica, streams the "
        "finished KV blocks to a decode-role replica over the "
        "deadline-guarded migration mailbox, and pins the decode leg "
        "to the prefill leg's weight version"),
    "FLAGS_fleet_min_replicas": (
        1, int,
        "fleet: autoscaler floor — the Autoscaler never drains the "
        "membership below this many replicas"),
    "FLAGS_fleet_max_replicas": (
        8, int,
        "fleet: autoscaler ceiling — add_replica stops here even if "
        "the SLO error budget is still burning"),
    "FLAGS_fleet_scale_cooldown_s": (
        5.0, float,
        "fleet: hysteresis cooldown between autoscaler actions; an "
        "overload must also persist this long before a scale-up, and "
        "idleness before a scale-down (prevents flapping)"),
    "FLAGS_fleet_slo_p99_ms": (
        500.0, float,
        "fleet: the e2e latency SLO in milliseconds; the autoscaler "
        "treats windowed p99 above this as error-budget burn and "
        "accrues fleet.slo_violation_ms while it lasts"),
    "FLAGS_rollout_canary_secs": (
        2.0, float,
        "rollout: how long the canary replica must hold the SLO burn "
        "gate (windowed e2e p99 under FLAGS_fleet_slo_p99_ms) before "
        "the staged waves start; also the default wave sustain period"),
    "FLAGS_rollout_wave_size": (
        1, int,
        "rollout: replicas upgraded per wave after the canary passes; "
        "within a wave replicas still drain->rebuild one at a time so "
        "serving capacity never drops by more than one replica"),
    "FLAGS_rollout_golden_prompts": (
        4, int,
        "rollout: number of pinned golden prompts synthesized (seeded, "
        "deterministic) for the canary bitwise gate when the caller "
        "does not supply an explicit prompt set"),
    "FLAGS_dist_timeout_s": (
        60.0, float,
        "distributed: per-call deadline (seconds) for eager collectives, "
        "barriers, p2p send/recv, and the gang checkpoint commit "
        "barrier. A peer that does not answer within the deadline "
        "raises typed retriable CollectiveTimeoutError/PeerGoneError "
        "instead of blocking the rank forever (0 disables — the "
        "pre-gang hang-forever behaviour)"),
    "FLAGS_gang_max_restarts": (
        3, int,
        "gang supervisor: coordinated gang restarts allowed before the "
        "job fails with the last rank's exit code (each restart tears "
        "down ALL ranks and re-forms the world from the newest "
        "globally committed checkpoint)"),
    "FLAGS_gang_hang_secs": (
        30.0, float,
        "gang supervisor: a rank whose heartbeat or step-progress "
        "watermark stalls this long (while its process is still alive) "
        "is declared hung and the whole gang is restarted (0 disables "
        "hang detection; keep this above FLAGS_dist_timeout_s so "
        "collective-blocked victims unblock via their deadline and the "
        "stall is attributed to the rank that actually died)"),
    "FLAGS_mp_overlap": (
        False, bool,
        "distributed: route mp-sharded matmuls through the ring-"
        "decomposed collective-matmul kernels (ops/overlap.py) — the "
        "column-parallel all-gather / row-parallel reduce-scatter / "
        "all-reduce become lax.ppermute steps interleaved with "
        "per-shard partial matmuls so collective time hides behind "
        "compute. PADDLE_TPU_MP_OVERLAP_FORCE=on|off overrides; "
        "unsupported meshes fall back to the GSPMD collectives"),
    "FLAGS_remat_policy": (
        "auto", str,
        "rematerialisation policy for recompute() segments and the "
        "hybrid engine's per-block remat: 'full' saves nothing inside "
        "the segment (max recompute, min memory), 'dots_saveable' "
        "saves matmul outputs (jax dots_saveable policy), 'none' "
        "disables remat (max memory, no recompute). 'auto' keeps each "
        "site's default: recompute() segments remat fully, the hybrid "
        "block scan saves its residuals"),
    "FLAGS_flight_recorder_capacity": (
        256, int,
        "observe: ring-buffer size of the always-on flight recorder "
        "(last N per-step records kept for the crash black box)"),
    "FLAGS_flight_recorder_dir": (
        "", str,
        "observe: directory the flight recorder dumps its JSON black "
        "box into on crash/preemption/SIGTERM (empty = system tempdir)"),
    "FLAGS_record_grad_norm": (
        False, bool,
        "observe: have the compiled train step also return the global "
        "gradient norm (pre-clip) via a reserved engine buffer so the "
        "flight recorder can log it without an extra device pass"),
    "FLAGS_flight_record_memory": (
        True, bool,
        "observe: include device bytes_in_use in each flight-recorder "
        "step record (one host allocator-stats call per step)"),
    "FLAGS_lowp_matmul": (
        "off", str,
        "low precision: route eligible matmuls (nn.Linear, the mp "
        "Column/RowParallelLinear, the overlap-ring per-shard partials, "
        "the fused LM-head loss chunks, the hybrid tied head) through "
        "the ops/lowp.py scaled-matmul family. 'int8' quantizes "
        "operands per-tensor to int8 with int32 accumulation; 'fp8' "
        "uses bit-faithful e4m3 emulation with f32 accumulation; 'off' "
        "keeps every path bitwise-unchanged. Backward always runs in "
        "bf16 (lowp forward, high-precision backward). "
        "PADDLE_TPU_LOWP_FORCE=pallas|lax pins the kernel path"),
    "FLAGS_lowp_amax_history": (
        16, int,
        "low precision: length H of each tensor slot's abs-max history "
        "ring in quantization.scaling.ScaleState — the delayed scale is "
        "max over the ring, so a transient outlier keeps its headroom "
        "for H steps (fp8-recipe amax_history_len)"),
    "FLAGS_lowp_amax_margin": (
        0, int,
        "low precision: power-of-two headroom M added to the delayed "
        "scale (scale = ring-max * 2**M); >0 trades resolution for "
        "fewer clipped outliers between scale updates"),
    "FLAGS_lowp_scale_interval": (
        1, int,
        "low precision: recompute the delayed scales from the amax "
        "history every N steps (1 = every step); between updates the "
        "stale scale keeps the step free of any host sync or retrace"),
    "FLAGS_lowp_slots": (
        128, int,
        "low precision: per-tensor slot capacity of the ScaleState "
        "carried through the train step; call sites beyond the "
        "capacity fall back to dynamic (current-step abs-max) scaling "
        "with a one-time warning"),
    "FLAGS_serving_w8a8": (
        False, bool,
        "serving: extend the weights-only int8 decode "
        "(FLAGS_serving_quantize) to w8a8 — the tied LM-head matmul "
        "also quantizes its activation rows to int8 against a frozen "
        "per-tensor scale calibrated during warmup, still one compiled "
        "decode trace (compile counters {decode:1, cow:1} unchanged). "
        "Requires the int8-frozen tied head; ignored otherwise"),
}

_values: dict = {}


def _coerce(name, value, typ):
    if typ is bool:
        if isinstance(value, str):
            return value.lower() in ("1", "true", "yes", "on")
        return bool(value)
    return typ(value)


def _init():
    if _values:  # lock-free fast path (dict fill is atomic under the GIL)
        return
    with _lock:
        if _values:
            return
        staged = {}
        for name, (default, typ, _doc) in _DEFS.items():
            env = os.environ.get(name)
            staged[name] = _coerce(name, env, typ) if env is not None \
                else default
        _values.update(staged)


def set_flags(flags: dict):
    """paddle.set_flags({'FLAGS_check_nan_inf': True})."""
    _init()
    for name, value in flags.items():
        if name not in _DEFS:
            raise ValueError(
                f"unknown flag {name!r}; known flags: "
                f"{sorted(_DEFS)}")
        _values[name] = _coerce(name, value, _DEFS[name][1])


def get_flags(flags):
    """paddle.get_flags('FLAGS_x') / ['FLAGS_x', ...] -> dict."""
    _init()
    if isinstance(flags, str):
        flags = [flags]
    out = {}
    for name in flags:
        if name not in _DEFS:
            raise ValueError(f"unknown flag {name!r}")
        out[name] = _values[name]
    return out


def flag(name):
    """Fast internal read."""
    _init()
    return _values[name]
