"""Framework-level utilities: RNG, device management, save/load.

Ref parity: python/paddle/framework/ (random.py, io.py) and
python/paddle/device.py.
"""

from . import random  # noqa: F401
from .random import get_rng_state, seed, set_rng_state  # noqa: F401
from . import dataset  # noqa: F401
from . import trainer  # noqa: F401
from .dataset import (  # noqa: F401
    DatasetFactory, InMemoryDataset, MultiSlotDataFeed, QueueDataset,
)
from .trainer import MultiTrainer, train_from_dataset  # noqa: F401
from . import op_version  # noqa: F401
