"""Framework-level utilities: RNG, device management, save/load.

Ref parity: python/paddle/framework/ (random.py, io.py) and
python/paddle/device.py.
"""

from . import random  # noqa: F401
from .random import get_rng_state, seed, set_rng_state  # noqa: F401
