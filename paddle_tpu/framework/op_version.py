"""Op version registry: compatibility metadata for saved programs.

Ref parity: paddle/fluid/framework/op_version_registry.h
(REGISTER_OP_VERSION + pass-compat checking): each op records a version
and a changelog (attrs added/deleted, semantics changes); artifacts
saved by `static.save_inference_model` / `jit.save` embed the producer's
version map, and loading warns when the consumer's registry diverges —
the reference's checkpoint-compat contract.
"""

from __future__ import annotations

import warnings

__all__ = ["register_op_version", "get_op_version", "version_map",
           "check_compatibility", "OpVersionDesc"]


class OpVersionDesc:
    """One version bump's changelog entry (ref OpVersionDesc)."""

    def __init__(self, note=""):
        self.changes: list[tuple[str, str, str]] = []  # (kind, name, note)
        self.note = note

    def new_attr(self, name, note="", default=None):
        self.changes.append(("new_attr", name, note))
        return self

    def delete_attr(self, name, note=""):
        self.changes.append(("delete_attr", name, note))
        return self

    def modify_attr(self, name, note=""):
        self.changes.append(("modify_attr", name, note))
        return self

    def new_input(self, name, note=""):
        self.changes.append(("new_input", name, note))
        return self

    def new_output(self, name, note=""):
        self.changes.append(("new_output", name, note))
        return self

    def bug_fix(self, note=""):
        self.changes.append(("bug_fix", "", note))
        return self


_VERSIONS: dict[str, list[OpVersionDesc]] = {}


def register_op_version(op_type, desc=None):
    """Add one version bump for `op_type`; version = number of bumps
    (base version 0). Returns the desc for fluent changelog chaining."""
    desc = desc or OpVersionDesc()
    _VERSIONS.setdefault(op_type, []).append(desc)
    return desc


def get_op_version(op_type) -> int:
    return len(_VERSIONS.get(op_type, []))


def version_map() -> dict[str, int]:
    """op_type -> current version for every registered op (ops without
    explicit bumps are version 0); embedded into saved artifacts."""
    from ..core.op_registry import registered_ops

    return {op: get_op_version(op) for op in registered_ops()}


def check_compatibility(saved_map, strict=False):
    """Compare a saved artifact's version map against this runtime
    (ref op_version_registry compat check at program load).

    Returns list of (op, saved_version, current_version) mismatches;
    warns by default, raises when strict."""
    mismatches = []
    for op, saved_v in (saved_map or {}).items():
        cur = get_op_version(op)
        if cur != saved_v:
            mismatches.append((op, saved_v, cur))
    if mismatches:
        msg = ("op version mismatch between saved program and runtime: "
               + ", ".join(f"{op} (saved v{s}, runtime v{c})"
                           for op, s, c in mismatches[:5])
               + ("..." if len(mismatches) > 5 else ""))
        if strict:
            raise RuntimeError(msg)
        warnings.warn(msg)
    return mismatches


# ---------------------------------------------------------------------------
# changelog entries for ops whose semantics evolved in this repo
# ---------------------------------------------------------------------------

register_op_version("dropout").modify_attr(
    "mask", "keep-mask generated from a u16 threshold compare "
    "(rate quantised to 1/65536) instead of an f32 bernoulli draw")
register_op_version("flash_attention").new_attr(
    "min_seq_dispatch", "kernel selection is sequence-aware: the XLA "
    "fallback runs below PADDLE_TPU_FLASH_MIN_SEQ")
