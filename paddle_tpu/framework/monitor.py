"""Named stat registry (ref paddle/fluid/platform/monitor.h:77 — the
STAT_ADD int64 counters, e.g. GPU mem high-watermarks). Host-side,
thread-safe; exported for user/runtime instrumentation."""

from __future__ import annotations

import threading

_lock = threading.Lock()
_stats: dict = {}


def stat_add(name: str, value: int = 1):
    """STAT_ADD analogue (monitor.h:130)."""
    with _lock:
        _stats[name] = _stats.get(name, 0) + int(value)


def stat_set(name: str, value: int):
    with _lock:
        _stats[name] = int(value)


def stat_get(name: str) -> int:
    with _lock:
        return _stats.get(name, 0)


def stat_max(name: str, value: int):
    """Record a high-watermark. A missing key is seeded with the
    OBSERVED value (not 0) so the first negative or sub-zero watermark
    is kept rather than silently clamped."""
    v = int(value)
    with _lock:
        cur = _stats.get(name)
        _stats[name] = v if cur is None else max(cur, v)


def stat_min(name: str, value: int):
    """Record a floor-watermark (the stat_max mirror; seeded with the
    observed value on first sight)."""
    v = int(value)
    with _lock:
        cur = _stats.get(name)
        _stats[name] = v if cur is None else min(cur, v)


def stats(prefix: str = None) -> dict:
    """All counters, or only those whose name starts with `prefix`
    (e.g. stats("ckpt_") for the fault-tolerance runtime's counters)."""
    with _lock:
        if prefix is None:
            return dict(_stats)
        return {k: v for k, v in _stats.items() if k.startswith(prefix)}


def reset():
    with _lock:
        _stats.clear()
