"""Deterministic fault-injection harness.

Ref parity: the reference certified fault paths with shell-level chaos
(test_fleet_launch_elastic.sh SIGKILLs a rank; nan_inf_utils tests feed
poisoned tensors). Here the fault points are *in the runtime itself* and
fire deterministically by occurrence index, so recovery tests can assert
bitwise-identical loss trajectories instead of "it eventually restarts".

A fault point is a named site the runtime passes through:

    checkpoint.io             each checkpoint write attempt (retry target)
    checkpoint.before_commit  after arrays+manifest land in ckpt-N.tmp,
                              before the atomic directory rename
    checkpoint.after_commit   after the rename; payload = committed dir
    train.batch               each Engine.train_batch; payload = batch
    elastic.beat              each heartbeat write (drop target)
    preempt.poll              each preemption poll (step boundary)
    serving.submit            each admission attempt (drop = shed the
                              request exactly like a full queue — the
                              deterministic-overload target)
    serving.dequeue           each queue pop by the batch assembler or
                              decode engine
    serving.batch             each dynamic-batcher flush (delay = slow
                              model; raise fails the member requests)
    serving.step              each continuous-batching decode step
                              (raise = deterministic mid-decode failure
                              of all in-flight requests; engine stays up)
    serving.alloc_block       each physical KV-block allocation (raise =
                              deterministic block-pool exhaustion during
                              admission; the request fails, already-
                              reserved blocks roll back, engine stays up)
    serving.cow_split         before each copy-on-write block copy when a
                              prefix-cache hit diverges mid-block (raise
                              = deterministic mid-CoW failure)
    serving.replica_step      each supervised (fleet) replica's loop
                              iteration before its decode step; tagged
                              with the replica name (delay = hung
                              replica → watchdog eviction + failover
                              replay; raise = transient step failure the
                              Router retries; crash = process death for
                              the fork-based slow tier)
    serving.replica_heartbeat each supervised replica's heartbeat, every
                              loop iteration including idle; tagged with
                              the replica name (delay = the replica
                              stops beating and the watchdog declares it
                              dead; raise = the replica THREAD dies —
                              detected as a crash)
    serving.route             each fleet Router dispatch attempt (drop /
                              raise = transient routing failure, retried
                              under the request's budget)
    serving.replay            each failover replay of a dead replica's
                              request (raise = replay path failure →
                              typed error to the client)
    serving.shard_step        each decode step of a mesh-sharded engine
                              before the sharded dispatch, tagged with
                              the engine name (raise = step error the
                              engine survives and the Router replays)
    serving.kv_migrate        each KV-block adoption while a prefill
                              replica's finished blocks migrate to a
                              decode replica, tagged with the adopting
                              engine name (raise = migration abort —
                              all-or-nothing, the pool stays leak-free
                              and the request falls back to colocated
                              dispatch)
    serving.spill             each evicted-KV-block spill append to the
                              SSD tier, before the record write (raise /
                              ioerror = full or failing spill disk — the
                              eviction proceeds without durability and
                              the allocator stays balanced)
    serving.kv_restore        each KV-block restore from a spilled
                              record during session resume, tagged with
                              the restoring engine name (raise = restore
                              abort — all-or-nothing, blocks roll back
                              and the session re-prefills from scratch)
    serving.affinity          each prefix-affinity routing decision in
                              the fleet Router, before the sticky
                              replica is chosen (raise = affinity lookup
                              failure — the Router falls back to
                              least-loaded placement)
    serving.w8a8              each decode step of a w8a8 engine before
                              the activation-quant dispatch (raise =
                              activation-quant failure — the step
                              degrades to the weights-only dequant path
                              inside the same compiled trace, leak-free)
    serving.admit_tenant      each tenant admission decision in the
                              weighted-fair queue, after the budget
                              debit and before the enqueue, tagged with
                              the tenant name (drop = shed with the
                              tenant-budget 429; the Retry-After header
                              tracks the bucket refill)
    serving.adapter_swap      each adapter-bank hot-swap, before any
                              mutation, tagged with the engine name
                              (raise = all-or-nothing swap abort — the
                              old adapter bank keeps serving bitwise)
    ps.push                   each PS mutation between WAL append and
                              table apply, tagged with the table name
                              (crash = kill mid-push: recovery replays
                              the WAL and the client's retry dedupes;
                              raise = acked-after-logging retry path)
    ps.pull                   each PS pull_dense/pull_sparse lookup,
                              tagged with the table name
    ps.wal_append             before each WAL record write (crash =
                              death with the record lost — the client
                              retry must absorb it)
    ps.spill                  each SSDSparseTable eviction batch or
                              compaction, tagged with the table name
                              (ioerror = full/failing spill disk)
    ps.replicate              each primary->backup forward (raise =
                              replication link hiccup; delay = slow
                              backup)
    ps.failover               each PSClient promotion of a backup after
                              the primary stopped answering, tagged
                              with the failing endpoint
    rec.score                 each RankingService batch flush before the
                              dense tower runs (raise = batch-level
                              scoring failure propagated to every
                              member ranking request)
    rec.embed_pull            each serving-side embedding-provider pull,
                              tagged with the provider label (deep /
                              wide / first_order / embedding)
    rec.online_push           each OnlineTrainer.feed click batch,
                              before forward/backward (raise = dropped
                              feedback batch; serving must be unaffected)
    dist.allreduce            each eager all-reduce, before the transport
                              (delay past FLAGS_dist_timeout_s = the
                              deterministic CollectiveTimeoutError path)
    dist.barrier              each eager barrier, including the gang
                              checkpoint commit barrier
    dist.p2p_send             each p2p mailbox send, before the socket
    dist.p2p_recv             each p2p mailbox recv, before the queue
                              wait (delay eats the per-call deadline)
    gang.heartbeat            each gang worker heartbeat+watermark write
                              (drop = supervisor sees the rank stall)
    gang.restart              each coordinated gang restart, after the
                              teardown and before the respawn (delay =
                              slow re-formation, charged to restart-lost
                              time; crash = supervisor death)

The authoritative site list is the `SITES` registry below;
`fault_point` refuses to fire for an unregistered site, and the
fault-site audit test asserts every registered site is exercised by at
least one tier-1 test.

Faults are scheduled programmatically::

    with faults.inject("checkpoint.before_commit@1:raise"):
        ...   # first save attempt dies between write and commit

or across process boundaries via the env var ``PADDLE_TPU_FAULTS``
(semicolon-separated specs, read once at first use) — that is how the
kill->restore tests schedule a crash inside a forked trainer.

Spec grammar: ``site[tag]@occurrence:action[:arg]`` where the optional
``[tag]`` pins the spec to one tagged firer of a shared site (e.g.
``serving.replica_step[fleet.r0]`` hits only replica r0; tagged specs
count occurrences per tag, untagged specs per site) and occurrence is a
1-based hit index (``3``), an inclusive range (``2-5``, open ``3-``), or
``*``; actions:

    crash        os._exit(137) — ungraceful death at the exact point
    raise        raise FaultError (in-process tests)
    ioerror      raise OSError (exercises retry_with_backoff paths)
    delay:<s>    sleep s seconds (slow I/O)
    nan          return the payload with float leaves poisoned to NaN
    corrupt      truncate the largest file under payload (a ckpt dir)
    drop         return the DROP sentinel (caller skips its action)

Every fire bumps ``monitor`` counter ``faults.<site>``.
"""

from __future__ import annotations

import os
import threading
import time

from . import monitor

__all__ = ["FaultError", "DROP", "SITES", "fault_point", "inject",
           "reset", "parse_spec", "corrupt_leaf", "ChaosSchedule"]

#: every fault site in the runtime (site -> where it fires). Keeping
#: this registry authoritative is what makes chaos certification
#: honest: `fault_point` raises on an unregistered site, so a renamed
#: site cannot silently turn a chaos test into a clean run, and the
#: audit test (tests/test_fault_sites.py) fails when a registered site
#: loses its tier-1 coverage.
SITES = {
    "checkpoint.io": "each checkpoint write attempt",
    "checkpoint.before_commit": "after tmp write, before atomic rename",
    "checkpoint.after_commit": "after the rename; payload = ckpt dir",
    "train.batch": "each Engine.train_batch",
    "elastic.beat": "each elastic heartbeat write",
    "preempt.poll": "each preemption poll (step boundary)",
    "serving.submit": "each admission attempt",
    "serving.dequeue": "each queue pop by assembler/decode engine",
    "serving.batch": "each dynamic-batcher flush",
    "serving.step": "each continuous-batching decode step",
    "serving.alloc_block": "each physical KV-block allocation",
    "serving.cow_split": "before each copy-on-write block copy",
    "serving.replica_step": "each fleet replica loop iteration",
    "serving.replica_heartbeat": "each fleet replica heartbeat",
    "serving.route": "each fleet Router dispatch attempt",
    "serving.replay": "each failover replay of a dead replica request",
    "serving.scale_up": "each ReplicaSet.add_replica before the build",
    "serving.scale_down": "each ReplicaSet.remove_replica before drain",
    "serving.drain": "each drained-victim eviction attempt",
    "serving.rollout_load": "each weight-registry checkpoint-dir load",
    "serving.canary": "before the canary replica's gate evaluation",
    "serving.rollback": "each rollout rollback attempt (tag = version)",
    "serving.draft": "before each speculative draft phase (a fault "
                     "degrades the round to plain decode)",
    "serving.verify": "before each speculative verify dispatch on the "
                      "unified decode trace",
    "serving.dequant": "each decode step of an int8-frozen engine, "
                       "before the dequant decode dispatch",
    "serving.shard_step": "each decode step of a mesh-sharded engine, "
                          "before the sharded dispatch (tag = engine "
                          "name)",
    "serving.kv_migrate": "each KV-block adoption during the "
                          "prefill->decode block migration (tag = "
                          "adopting decode engine name)",
    "serving.spill": "each evicted-KV-block spill append to the SSD "
                     "tier, before the record write (a fault loses "
                     "durability, never blocks)",
    "serving.kv_restore": "each KV-block restore from a spilled record "
                          "during session resume (tag = restoring "
                          "engine name; all-or-nothing, falls back to "
                          "re-prefill)",
    "serving.affinity": "each prefix-affinity routing decision before "
                        "the sticky replica is chosen (a fault falls "
                        "back to least-loaded placement)",
    "serving.w8a8": "each decode step of a w8a8 engine before the "
                    "activation-quant dispatch (a fault degrades that "
                    "step to the weights-only dequant path, leak-free)",
    "serving.admit_tenant": "each tenant admission decision in the "
                            "weighted-fair queue, after budget debit "
                            "and before enqueue (tag = tenant name; "
                            "drop = shed with the tenant-budget 429 "
                            "whose Retry-After tracks the refill)",
    "serving.adapter_swap": "each adapter-bank hot-swap, before any "
                            "mutation (tag = engine name; a fault is "
                            "all-or-nothing — the old adapter bank "
                            "keeps serving bitwise)",
    "dist.allreduce": "each eager all-reduce before the transport "
                      "(delay eats the FLAGS_dist_timeout_s budget)",
    "dist.barrier": "each eager barrier / gang ckpt commit barrier",
    "dist.p2p_send": "each p2p mailbox send before the socket write",
    "dist.p2p_recv": "each p2p mailbox recv before the queue wait",
    "gang.heartbeat": "each gang worker heartbeat+watermark write "
                      "(drop = the supervisor sees this rank stall)",
    "gang.restart": "each coordinated gang restart, after teardown "
                    "and before the respawn",
    "ps.push": "each PS mutation between WAL append and apply",
    "ps.pull": "each PS pull_dense/pull_sparse lookup",
    "ps.wal_append": "before each PS WAL record write",
    "ps.spill": "each SSD sparse-table spill batch / compaction",
    "ps.replicate": "each PS primary->backup forward",
    "ps.failover": "each PSClient promotion of a backup",
    "rec.score": "each RankingService batch flush before the tower",
    "rec.embed_pull": "each serving embedding pull (tag = provider)",
    "rec.online_push": "each OnlineTrainer click batch",
}


class FaultError(RuntimeError):
    """Raised by the 'raise' action (deliberately NOT an OSError so
    checkpoint retry loops do not swallow injected crashes)."""


#: sentinel returned by `fault_point` when a 'drop' fault fires
DROP = object()

_lock = threading.Lock()
_specs: list = []            # active FaultSpec list (env + injected)
_hits: dict = {}             # site -> number of times the point was hit
_env_loaded = False


class FaultSpec:
    def __init__(self, site, lo, hi, action, arg=None, tag=None):
        self.site = site
        self.lo = lo          # 1-based inclusive
        self.hi = hi          # inclusive; None = open
        self.action = action
        self.arg = arg
        self.tag = tag        # None = any firer of the site

    def matches_occ(self, hit):
        if self.lo is None:   # '*'
            return True
        return hit >= self.lo and (self.hi is None or hit <= self.hi)

    def matches(self, site, hit):
        return site == self.site and self.matches_occ(hit)

    def __repr__(self):
        occ = "*" if self.lo is None else (
            str(self.lo) if self.hi == self.lo else
            f"{self.lo}-{'' if self.hi is None else self.hi}")
        arg = f":{self.arg}" if self.arg is not None else ""
        tag = f"[{self.tag}]" if self.tag is not None else ""
        return f"{self.site}{tag}@{occ}:{self.action}{arg}"


def parse_spec(text):
    """``site[tag]@occ:action[:arg]`` -> FaultSpec."""
    site, _, rest = text.strip().partition("@")
    occ, _, act = rest.partition(":")
    if not site or not occ or not act:
        raise ValueError(f"bad fault spec {text!r} "
                         "(want site[tag]@occurrence:action[:arg])")
    tag = None
    if site.endswith("]") and "[" in site:
        site, _, tag = site[:-1].partition("[")
    action, _, arg = act.partition(":")
    if occ == "*":
        lo = hi = None
    elif "-" in occ:
        a, b = occ.split("-", 1)
        lo, hi = int(a), (int(b) if b else None)
    else:
        lo = hi = int(occ)
    return FaultSpec(site, lo, hi, action, arg or None, tag=tag)


def _load_env():
    global _env_loaded
    if _env_loaded:
        return
    with _lock:
        if _env_loaded:
            return
        raw = os.environ.get("PADDLE_TPU_FAULTS", "")
        for part in raw.split(";"):
            if part.strip():
                _specs.append(parse_spec(part))
        _env_loaded = True


def reset(site=None):
    """Zero hit counters (one site — including its per-tag counters —
    or all). inject() does this for its own sites so occurrence indices
    are test-local and deterministic."""
    with _lock:
        if site is None:
            _hits.clear()
        else:
            for key in [k for k in _hits
                        if k == site
                        or (isinstance(k, tuple) and k[0] == site)]:
                del _hits[key]


class inject:
    """Context manager activating fault specs for its dynamic extent."""

    def __init__(self, *specs, reset_counters=True):
        self._specs = [parse_spec(s) if isinstance(s, str) else s
                       for s in specs]
        self._reset = reset_counters

    def __enter__(self):
        _load_env()
        with _lock:
            _specs.extend(self._specs)
        if self._reset:
            for s in self._specs:
                reset(s.site)
        return self

    def __exit__(self, *exc):
        with _lock:
            for s in self._specs:
                try:
                    _specs.remove(s)
                except ValueError:
                    pass
        return False


def _poison_nan(payload):
    import jax
    import numpy as np

    def leaf(a):
        arr = np.asarray(a)
        if np.issubdtype(arr.dtype, np.floating):
            return np.full_like(arr, np.nan)
        return a

    return jax.tree.map(leaf, payload)


def corrupt_leaf(path):
    """Truncate the largest ARRAY-DATA file under `path` to half its
    size (the 'truncate-a-leaf' checkpoint corruption). Tensorstore
    parks array bytes in content-addressed files under `d/` directories;
    preferring those over the JSON/metadata files makes the injected
    damage exercise the checksum/restore path rather than a trivial
    metadata parse error. Falls back to the largest file overall."""
    victim, size = None, -1
    any_victim, any_size = None, -1
    for root, _dirs, files in os.walk(path):
        in_data = os.path.basename(root) == "d"
        for name in files:
            p = os.path.join(root, name)
            try:
                s = os.path.getsize(p)
            except OSError:
                continue
            if s > any_size:
                any_victim, any_size = p, s
            if in_data and s > size:
                victim, size = p, s
    if victim is None:
        victim, size = any_victim, any_size
    if victim is None:
        raise FileNotFoundError(f"no files to corrupt under {path}")
    with open(victim, "r+b") as f:
        f.truncate(max(size // 2, 1))
    return victim


def fault_point(site, payload=None, tag=None):
    """Pass through a named fault site.

    `tag` names this particular firer of a shared site (e.g. the
    replica passing through ``serving.replica_step``): tagged specs
    match only their tag's own occurrence count, untagged specs the
    site-global count — so one replica can be hung deterministically
    while its siblings run clean.

    Returns `payload` (possibly transformed by a 'nan' fault), or the
    DROP sentinel when a 'drop' fault fires. May raise, sleep, or exit
    the process depending on the scheduled action.
    """
    _load_env()
    with _lock:
        if not _specs:
            return payload  # zero-cost when nothing is scheduled
        if site not in SITES and not any(s.site == site for s in _specs):
            # A spec that names the site explicitly is its own audit
            # trail (tests exercise the scheduling machinery through
            # ad-hoc sites); an unregistered site nobody asked for is
            # a typo'd or undeclared production fault point.
            raise ValueError(
                f"fault_point fired for unregistered site {site!r} — "
                "add it to faults.SITES (and a tier-1 test) so chaos "
                "schedules stay auditable")
        _hits[site] = hit = _hits.get(site, 0) + 1
        thit = None
        if tag is not None:
            key = (site, tag)
            _hits[key] = thit = _hits.get(key, 0) + 1
        matched = []
        for s in _specs:
            if s.site != site:
                continue
            if s.tag is None:
                if s.matches_occ(hit):
                    matched.append(s)
            elif tag is not None and s.tag == tag and s.matches_occ(thit):
                matched.append(s)
    for spec in matched:
        monitor.stat_add(f"faults.{site}")
        try:  # black-box the firing (lazy import: faults must stay leaf)
            from .. import observe

            observe.flight.note("fault", site=site, hit=hit,
                                action=spec.action)
            if spec.action == "crash":
                # last chance to persist the ring: os._exit skips every
                # atexit/finally a normal unwind would run
                observe.flight.dump(f"fault-crash:{site}")
        except Exception:
            pass
        if spec.action == "crash":
            os._exit(137)
        elif spec.action == "raise":
            raise FaultError(f"injected fault at {site} (hit {hit})")
        elif spec.action == "ioerror":
            raise OSError(f"injected I/O error at {site} (hit {hit})")
        elif spec.action == "delay":
            time.sleep(float(spec.arg or 0.1))
        elif spec.action == "nan":
            payload = _poison_nan(payload)
        elif spec.action == "corrupt":
            corrupt_leaf(payload)
        elif spec.action == "drop":
            return DROP
        else:
            raise ValueError(f"unknown fault action {spec.action!r}")
    return payload


class ChaosSchedule(inject):
    """`inject` that can certify its own delivery.

    A chaos test schedules a scripted fault sweep, runs the workload,
    then calls `verify()` to assert every *finite* spec actually fired
    exactly as many times as planned — catching the classic silent
    failure where a fault point was renamed (or never reached) and the
    "chaos" test quietly certified a clean run. Open-ended specs
    (`@*`, `@3-`) are excluded from the plan; `fired()` still reports
    their sites' totals.
    """

    def __enter__(self):
        super().__enter__()
        self._base = {site: monitor.stat_get(f"faults.{site}")
                      for site in {s.site for s in self._specs}}
        return self

    def fired(self):
        """{site: fires since __enter__} over this schedule's sites."""
        return {site: monitor.stat_get(f"faults.{site}") - base
                for site, base in self._base.items()}

    def planned(self):
        """{site: expected fires} summed over finite occurrence windows."""
        plan: dict = {}
        for s in self._specs:
            if s.lo is None or s.hi is None:
                continue          # open-ended: no finite plan
            plan[s.site] = plan.get(s.site, 0) + (s.hi - s.lo + 1)
        return plan

    def verify(self):
        """Assert fired == planned per site; returns the fired dict."""
        fired = self.fired()
        for site, want in self.planned().items():
            got = fired.get(site, 0)
            if got != want:
                raise AssertionError(
                    f"chaos schedule under-delivered at {site}: "
                    f"planned {want} fires, observed {got}")
        return fired
