"""Typed error taxonomy + enforce helpers.

Ref parity: paddle/fluid/platform/errors.h + error_codes.proto (the
PADDLE_ENFORCE_* macros of platform/enforce.h). User-facing errors carry
the op/context in the message instead of a raw XLA traceback.
"""

from __future__ import annotations

__all__ = [
    "PaddleError", "InvalidArgumentError", "NotFoundError",
    "OutOfRangeError", "AlreadyExistsError", "ResourceExhaustedError",
    "PreconditionNotMetError", "PermissionDeniedError", "UnavailableError",
    "FatalError", "UnimplementedError", "ExecutionTimeoutError",
    "enforce", "enforce_eq", "enforce_gt", "enforce_shape",
    "retry_with_backoff",
]


class PaddleError(Exception):
    """Base of the taxonomy (error_codes.proto)."""


class InvalidArgumentError(PaddleError, ValueError):
    pass


class NotFoundError(PaddleError, KeyError):
    pass


class OutOfRangeError(PaddleError, IndexError):
    pass


class AlreadyExistsError(PaddleError):
    pass


class ResourceExhaustedError(PaddleError, MemoryError):
    pass


class PreconditionNotMetError(PaddleError, RuntimeError):
    pass


class PermissionDeniedError(PaddleError):
    pass


class UnavailableError(PaddleError, RuntimeError):
    pass


class FatalError(PaddleError, RuntimeError):
    pass


class UnimplementedError(PaddleError, NotImplementedError):
    pass


class ExecutionTimeoutError(PaddleError, TimeoutError):
    pass


def retry_with_backoff(fn, *, retries=3, base_delay=0.1, max_delay=2.0,
                       exceptions=(OSError,), stat=None, description=""):
    """Run `fn()` retrying on `exceptions` with exponential backoff.

    Shared by checkpoint I/O and the launch bootstrap (transient
    filesystem / port / coordinator failures). `retries` is the number
    of RE-tries after the first attempt; delays are base_delay * 2**k
    capped at max_delay. Each retry bumps monitor counter ``retries``
    (plus ``<stat>`` when given) and warns with `description`, so flaky
    infrastructure is visible instead of silent.
    """
    import time
    import warnings

    from . import monitor

    attempt = 0
    while True:
        try:
            return fn()
        except exceptions as e:
            if attempt >= retries:
                raise
            delay = min(base_delay * (2 ** attempt), max_delay)
            attempt += 1
            monitor.stat_add("retries")
            if stat:
                monitor.stat_add(stat)
            warnings.warn(
                f"{description or 'operation'} failed ({e!r}); retry "
                f"{attempt}/{retries} in {delay:.2f}s")
            time.sleep(delay)


def enforce(cond, message, error_cls=InvalidArgumentError):
    """PADDLE_ENFORCE analogue (platform/enforce.h)."""
    if not cond:
        raise error_cls(message)


def enforce_eq(a, b, message="", error_cls=InvalidArgumentError):
    if a != b:
        raise error_cls(f"expected {a!r} == {b!r}"
                        + (f": {message}" if message else ""))


def enforce_gt(a, b, message="", error_cls=InvalidArgumentError):
    if not a > b:
        raise error_cls(f"expected {a!r} > {b!r}"
                        + (f": {message}" if message else ""))


def enforce_shape(tensor, expected, message=""):
    got = tuple(tensor.shape)
    exp = tuple(expected)
    ok = len(got) == len(exp) and all(
        e in (-1, None) or g == e for g, e in zip(got, exp))
    if not ok:
        raise InvalidArgumentError(
            f"shape mismatch: got {got}, expected {exp}"
            + (f": {message}" if message else ""))
