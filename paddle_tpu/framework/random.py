"""RNG state management.

Ref parity: paddle/fluid/framework/generator.h (seeded per-device Philox
Generator). TPU-native: JAX threaded PRNG keys. A global default Generator
serves the eager API (`paddle_tpu.seed`); inside jit capture (functional
engine) a *traced* base key is installed with `rng_scope(key)` so random ops
fold into the compiled program instead of baking in constants.
"""

from __future__ import annotations

import contextlib
import threading

import jax
import jax.numpy as jnp


class Generator:
    """Counter-based key stream (split-free: fold_in on a monotone counter)."""

    def __init__(self, seed=0):
        # lazy: building a PRNGKey initialises the XLA backend, which must
        # not happen at import time (jax.distributed.initialize comes first
        # in multi-process jobs)
        self._seed = seed
        self._base_cache = None
        self._counter = 0

    @property
    def _base(self):
        if self._base_cache is None:
            self._base_cache = jax.random.PRNGKey(self._seed)
        return self._base_cache

    @_base.setter
    def _base(self, value):
        self._base_cache = value

    def manual_seed(self, seed):
        self._seed = int(seed)
        self._base_cache = None
        self._counter = 0
        return self

    seed = manual_seed

    def initial_seed(self):
        return self._seed

    def next_key(self):
        self._counter += 1
        return jax.random.fold_in(self._base, self._counter)

    def get_state(self):
        return (self._seed, self._counter)

    def set_state(self, state):
        self._seed, self._counter = state
        self._base = jax.random.PRNGKey(self._seed)


default_generator = Generator(0)

_tls = threading.local()


@contextlib.contextmanager
def rng_scope(key):
    """Install a (possibly traced) base key; random ops inside draw from it.

    Used by the functional engine: the train-step's input key becomes the
    base so dropout masks differ per step and are part of the compiled fn.
    """
    gen = Generator(0)
    gen._base = jnp.asarray(key)
    prev = getattr(_tls, "scoped", None)
    _tls.scoped = gen
    try:
        yield gen
    finally:
        _tls.scoped = prev


def next_key():
    gen = getattr(_tls, "scoped", None)
    if gen is not None:
        return gen.next_key()
    return default_generator.next_key()


def seed(s):
    """paddle.seed"""
    default_generator.manual_seed(s)
    return default_generator


def get_rng_state():
    gen = getattr(_tls, "scoped", None) or default_generator
    return [gen.get_state()]


def set_rng_state(state):
    default_generator.set_state(state[0])
