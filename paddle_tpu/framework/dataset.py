"""Fleet-style datasets: file-list ingestion with slot parsing.

Ref parity: paddle/fluid/framework/data_set.h (DatasetImpl:
set_filelist/load_into_memory/local_shuffle/global_shuffle,
InMemoryDataset vs QueueDataset) + data_feed.h MultiSlotDataFeed (the
text slot format: per line, for each declared slot, a count followed by
that many values) + python/paddle/fluid/dataset.py DatasetFactory.

TPU-native: records parse into fixed-width numpy slot batches (padded
int slots + dense float slots — static shapes for XLA), files shard
across reader threads, and global_shuffle coordinates through the PS
barrier when a PS runtime is active (single-process: local shuffle).
"""

from __future__ import annotations

import random as _random
import threading

import numpy as np

__all__ = ["DatasetFactory", "InMemoryDataset", "QueueDataset",
           "MultiSlotDataFeed"]


class MultiSlotDataFeed:
    """Parses the reference's multi-slot text lines
    (ref framework/data_feed.cc MultiSlotDataFeed::ParseOneInstance).

    Line format, for each slot in order: `<n> v1 ... vn`.
    Slot kinds: 'uint64'/'int64' (sparse id slots, padded to
    `max_len`) and 'float' (dense slots, fixed width)."""

    def __init__(self, slots, pad_value=0, max_len=None):
        # slots: list of (name, dtype) or (name, dtype, width)
        self.slots = []
        for s in slots:
            name, dtype = s[0], s[1]
            width = s[2] if len(s) > 2 else None
            self.slots.append((name, dtype, width))
        self.pad_value = pad_value
        self.max_len = max_len

    def parse_line(self, line):
        toks = line.split()
        pos = 0
        rec = {}
        for name, dtype, _ in self.slots:
            n = int(toks[pos])
            pos += 1
            vals = toks[pos:pos + n]
            pos += n
            if dtype in ("uint64", "int64", "int32"):
                rec[name] = np.asarray([int(v) for v in vals], np.int64)
            else:
                rec[name] = np.asarray([float(v) for v in vals],
                                       np.float32)
        return rec

    def batch(self, records):
        """records -> dict of [B, W] arrays (id slots padded)."""
        out = {}
        for name, dtype, width in self.slots:
            vals = [r[name] for r in records]
            if dtype in ("uint64", "int64", "int32"):
                w = width or self.max_len or max(len(v) for v in vals)
                arr = np.full((len(vals), w), self.pad_value, np.int64)
                for i, v in enumerate(vals):
                    arr[i, :min(len(v), w)] = v[:w]
                out[name] = arr
            else:
                w = width or max(len(v) for v in vals)
                arr = np.zeros((len(vals), w), np.float32)
                for i, v in enumerate(vals):
                    arr[i, :min(len(v), w)] = v[:w]
                out[name] = arr
        return out


class _DatasetBase:
    """ref data_set.h DatasetImpl."""

    def __init__(self):
        self._filelist = []
        self._batch_size = 1
        self._thread_num = 1
        self._feed = None
        self._use_vars = []
        self._pipe_command = None  # accepted for API parity; unused

    # -- config (ref python/paddle/fluid/dataset.py) -------------------------
    def set_filelist(self, filelist):
        self._filelist = list(filelist)

    def set_batch_size(self, batch_size):
        self._batch_size = int(batch_size)

    def set_thread(self, thread_num):
        self._thread_num = max(int(thread_num), 1)

    def set_use_var(self, var_list):
        self._use_vars = [getattr(v, "name", v) for v in var_list]

    def set_pipe_command(self, cmd):
        self._pipe_command = cmd

    def set_feed(self, feed: MultiSlotDataFeed):
        self._feed = feed

    def _require_feed(self):
        if self._feed is None:
            if not self._use_vars:
                raise ValueError(
                    "call set_feed(MultiSlotDataFeed(...)) or "
                    "set_use_var([...]) first")
            # default: every use_var is an int64 id slot
            self._feed = MultiSlotDataFeed(
                [(n, "int64") for n in self._use_vars])
        return self._feed

    def _read_file(self, path):
        feed = self._require_feed()
        with open(path) as f:
            for line in f:
                line = line.strip()
                if line:
                    yield feed.parse_line(line)


class InMemoryDataset(_DatasetBase):
    """ref data_set.h InMemoryDataset: load all records, shuffle, then
    iterate batches (PS-mode training feeds from here)."""

    def __init__(self):
        super().__init__()
        self._records = []
        self._loaded = False

    def load_into_memory(self):
        records = []
        if self._thread_num <= 1 or len(self._filelist) <= 1:
            for path in self._filelist:
                records.extend(self._read_file(path))
        else:
            lock = threading.Lock()
            shards = [self._filelist[i::self._thread_num]
                      for i in range(self._thread_num)]

            def load(paths):
                local = []
                for p in paths:
                    local.extend(self._read_file(p))
                with lock:
                    records.extend(local)

            threads = [threading.Thread(target=load, args=(s,))
                       for s in shards if s]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
        self._records = records
        self._loaded = True

    def local_shuffle(self, seed=None):
        _random.Random(seed).shuffle(self._records)

    def global_shuffle(self, fleet=None, thread_num=None, seed=None):
        """ref DatasetImpl::GlobalShuffle: all trainers barrier, then each
        shuffles with a shared seed so shards stay disjoint. Without a PS
        runtime this is a local shuffle."""
        from ..distributed.ps import runtime as ps_runtime

        if ps_runtime._runtime is not None:
            # barrier failures must PROPAGATE: a trainer that shuffled
            # with a different seed silently breaks shard disjointness
            ps_runtime._runtime.barrier()
            seed = 7 if seed is None else seed  # shared across trainers
        self.local_shuffle(seed)

    def release_memory(self):
        self._records = []
        self._loaded = False

    def get_memory_data_size(self, fleet=None):
        return len(self._records)

    def __iter__(self):
        """Yield slot batches (dict name -> np array)."""
        if not self._loaded:
            self.load_into_memory()
        feed = self._require_feed()
        bs = self._batch_size
        for i in range(0, len(self._records) - bs + 1, bs):
            yield feed.batch(self._records[i:i + bs])


class QueueDataset(_DatasetBase):
    """ref data_set.h QueueDataset: streaming — records flow from files
    through a bounded queue without materialising in memory."""

    def __iter__(self):
        import queue as _q

        feed = self._require_feed()
        q: _q.Queue = _q.Queue(maxsize=4096)
        DONE = object()

        def produce():
            try:
                for path in self._filelist:
                    for rec in self._read_file(path):
                        q.put(rec)
                q.put(DONE)
            except BaseException as e:  # noqa: BLE001 — surface, not hang
                q.put(e)

        t = threading.Thread(target=produce, daemon=True)
        t.start()
        buf = []
        while True:
            rec = q.get()
            if rec is DONE:
                break
            if isinstance(rec, BaseException):
                raise rec
            buf.append(rec)
            if len(buf) == self._batch_size:
                yield feed.batch(buf)
                buf = []


class DatasetFactory:
    """ref python/paddle/fluid/dataset.py DatasetFactory."""

    def create_dataset(self, datafeed_class="QueueDataset"):
        if datafeed_class == "InMemoryDataset":
            return InMemoryDataset()
        if datafeed_class == "QueueDataset":
            return QueueDataset()
        raise ValueError(f"unknown dataset class {datafeed_class!r}")
