"""Trainer / DeviceWorker: the dataset-driven training loop.

Ref parity: paddle/fluid/framework/trainer.h (TrainerBase ->
MultiTrainer/DistMultiTrainer), device_worker.h (HogwildWorker,
DownpourWorker), and Executor::RunFromDataset (executor.h:137). The
reference builds per-thread scopes and runs the program op-by-op per
worker; here a worker is a Python callable over slot batches — either
an eager train function (Hogwild threads, PS-mode with async push/pull
= DownpourWorker semantics) or a compiled static Program replayed by
the Executor (one XLA computation per batch shape).
"""

from __future__ import annotations

import threading

__all__ = ["HogwildWorker", "MultiTrainer", "train_from_dataset"]


class HogwildWorker:
    """ref device_worker.h HogwildWorker: one worker thread running the
    train function over its shard of batches, lock-free on shared
    parameters (the PS Communicator carries the gradients in PS mode —
    DownpourWorker's role)."""

    def __init__(self, worker_id, train_func, fetch_info=None):
        self.worker_id = worker_id
        self.train_func = train_func
        self.fetch_info = fetch_info
        self.metrics = []
        self.error: BaseException | None = None

    def run(self, batches):
        try:
            for batch in batches:
                out = self.train_func(batch)
                if out is not None:
                    self.metrics.append(out)
        except BaseException as e:  # noqa: BLE001 — re-raised after join
            self.error = e


class MultiTrainer:
    """ref trainer.h MultiTrainer: N workers over a sharded dataset."""

    def __init__(self, thread_num=1):
        self.thread_num = max(int(thread_num), 1)
        self.workers: list[HogwildWorker] = []

    def train(self, dataset, train_func):
        """Stream the dataset's batches to worker threads through a
        shared iterator (ref MultiTrainer::Initialize reader split +
        Run). Streaming keeps QueueDataset's constant-memory property —
        batches are never materialised up front."""
        n = self.thread_num
        self.workers = [HogwildWorker(i, train_func) for i in range(n)]
        it = iter(dataset)
        if n == 1:
            self.workers[0].run(it)
        else:
            lock = threading.Lock()

            def shard():
                while True:
                    with lock:
                        try:
                            batch = next(it)
                        except StopIteration:
                            return
                    yield batch

            threads = [threading.Thread(target=w.run, args=(shard(),))
                       for w in self.workers]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
        for w in self.workers:
            if w.error is not None:
                # dataset/step failures must surface, not truncate the
                # epoch silently (single-thread mode raises in-line)
                raise w.error
        out = []
        for w in self.workers:
            out.extend(w.metrics)
        return out


def train_from_dataset(program, dataset, fetch_list=None, thread=1,
                       executor=None, debug=False):
    """Executor::RunFromDataset for static Programs: replay the compiled
    program once per slot batch, feeding slots by var name.

    Returns the per-batch fetch values (ref fetch_info printing)."""
    from ..static.program import Executor

    exe = executor or Executor()
    results = []

    def step(batch):
        feed = {k: v for k, v in batch.items()
                if program.global_block().has_var(k)}
        vals = exe.run(program, feed=feed, fetch_list=fetch_list or [])
        if debug and vals:
            print(f"[train_from_dataset] fetch={vals}")
        return vals

    trainer = MultiTrainer(thread)
    results = trainer.train(dataset, step)
    return results
