"""SelectedRows: sparse row-index gradients for embeddings.

Ref parity: paddle/fluid/framework/selected_rows.h — the reference stores
embedding gradients as {rows, value} so the optimizer touches only the
looked-up rows. TPU-native: `rows`/`values` are device arrays with STATIC
shapes (k = number of lookups, known at trace time), duplicates are
allowed (scatter-add semantics), and densification is one XLA
scatter-add. Optimizers apply them with `at[rows].add` (SGD) or a
static-size `jnp.unique` merge + row-wise moment update (Adam lazy_mode),
so a large vocab table never materialises a dense gradient.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


class SelectedRows:
    """A sparse gradient: `values[i]` is the gradient of row `rows[i]` of
    a dense tensor with leading dimension `height`."""

    __slots__ = ("rows", "values", "height")

    def __init__(self, rows, values, height):
        self.rows = jnp.asarray(rows, jnp.int32).reshape(-1)
        values = jnp.asarray(values)
        k = self.rows.shape[0]
        if values.ndim >= 2 and values.shape[0] == k:
            self.values = values
        else:
            self.values = values.reshape(k, -1)
        self.height = int(height)

    # -- tensor-protocol shims (so autograd plumbing can pass it around) --
    @property
    def dtype(self):
        return self.values.dtype

    @property
    def shape(self):
        return (self.height,) + tuple(self.values.shape[1:])

    def astype(self, dt):
        return SelectedRows(self.rows, self.values.astype(dt), self.height)

    def to_dense(self):
        dense = jnp.zeros(self.shape, self.values.dtype)
        return dense.at[self.rows].add(self.values, mode="drop")

    def merge(self, other):
        """Accumulate another gradient (sparse or dense)."""
        if isinstance(other, SelectedRows):
            return SelectedRows(
                jnp.concatenate([self.rows, other.rows]),
                jnp.concatenate([self.values, other.values]), self.height)
        return self.to_dense() + other

    def coalesced(self):
        """Merge duplicate rows with a static-size unique (XLA-friendly:
        out-of-range fill rows are dropped by scatter mode='drop')."""
        k = self.rows.shape[0]
        uniq, inv = jnp.unique(self.rows, return_inverse=True, size=k,
                               fill_value=self.height)
        merged = jax.ops.segment_sum(self.values, inv.reshape(-1),
                                     num_segments=k)
        return SelectedRows(uniq, merged, self.height)

    def __repr__(self):
        return (f"SelectedRows(height={self.height}, "
                f"nnz_rows={self.rows.shape[0]}, "
                f"row_shape={tuple(self.values.shape[1:])})")


def is_selected_rows(x):
    return isinstance(x, SelectedRows)


def accumulate(a, b):
    """Grad accumulation where either side may be sparse."""
    if a is None:
        return b
    if isinstance(a, SelectedRows):
        return a.merge(b)
    if isinstance(b, SelectedRows):
        return b.merge(a)
    return a + b
