"""Op dispatch: the single funnel every eager op call goes through.

Ref parity: paddle/fluid/imperative/tracer.cc:150 (TraceOp) — create op,
AMP autocast rewrite, run kernel, tape the backward. Here the "kernel" is a
pure jax function (XLA compiles + fuses it), autocast is an input-dtype
rewrite, and taping captures `jax.vjp` closures (see autograd.py).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from . import config
from .autograd import Node
from .op_registry import lookup

# ---------------------------------------------------------------------------
# AMP policy (ref: paddle/fluid/imperative/amp_auto_cast.h AmpOperators and
# python/paddle/fluid/dygraph/amp/auto_cast.py white/black lists). On TPU the
# low-precision dtype is bfloat16; float16 is kept for compatibility.
# ---------------------------------------------------------------------------

AMP_WHITE_LIST = {
    "matmul_v2", "matmul", "mul", "conv2d", "conv2d_transpose", "conv1d",
    "conv3d", "depthwise_conv2d", "einsum", "fused_attention",
    "flash_attention", "bmm", "addmm", "fused_linear_cross_entropy",
}

AMP_BLACK_LIST = {
    "softmax_with_cross_entropy", "cross_entropy", "log_softmax", "exp",
    "log", "log2", "log10", "log1p", "mean", "sum", "reduce_sum",
    "reduce_mean", "softmax", "layer_norm", "batch_norm", "norm", "cumsum",
    "pow", "rsqrt", "erf", "erfinv", "sigmoid_cross_entropy_with_logits",
    "nll_loss", "kldiv_loss",
}


def _amp_rewrite(op_name, arrs):
    level, amp_dtype, white, black = config.amp_state()
    if level is None:
        return arrs
    white_list = AMP_WHITE_LIST if white is None else (AMP_WHITE_LIST | set(white))
    black_list = AMP_BLACK_LIST if black is None else (AMP_BLACK_LIST | set(black))
    low = jnp.bfloat16 if amp_dtype == "bfloat16" else jnp.float16

    def cast_to(a, dt):
        if hasattr(a, "dtype") and jnp.issubdtype(a.dtype, jnp.floating) \
                and a.dtype != dt and a.dtype != jnp.float64:
            return a.astype(dt)
        return a

    if op_name in black_list:
        return [cast_to(a, jnp.float32) for a in arrs]
    if op_name in white_list or level == "O2":
        return [cast_to(a, low) for a in arrs]
    return arrs


# ---------------------------------------------------------------------------
# dispatch
# ---------------------------------------------------------------------------

_flags_mod = None
_nan_inf_jit_warned = False


def _maybe_check_nan_inf(op_name, out):
    """FLAGS_check_nan_inf: post-op scan of every output (ref
    framework/details/nan_inf_utils_detail.cu; flag at
    platform/flags.cc:44). Eager-only — under tracing the values are
    abstract; a one-time warning points at the in-graph anomaly guard."""
    global _flags_mod
    if _flags_mod is None:
        from ..framework import flags as _f

        _flags_mod = _f
    outs = out if isinstance(out, tuple) else (out,)
    if _flags_mod.flag("FLAGS_benchmark") and not any(
            isinstance(o, jax.core.Tracer) for o in outs):
        # stable op timing: block on every output (ref FLAGS_benchmark)
        jax.block_until_ready(out)
    if not _flags_mod.flag("FLAGS_check_nan_inf"):
        return
    for i, o in enumerate(outs):
        if isinstance(o, jax.core.Tracer):
            # under jit the values are abstract: a per-op host check is
            # impossible (and would defeat compilation). Tell the user
            # ONCE where the compiled-path equivalent lives instead of
            # silently doing nothing.
            global _nan_inf_jit_warned
            if not _nan_inf_jit_warned:
                _nan_inf_jit_warned = True
                import warnings

                warnings.warn(
                    "FLAGS_check_nan_inf is inert under jit tracing (op "
                    f"'{op_name}'): per-op values are abstract. For "
                    "compiled training use the in-graph anomaly guard — "
                    "Engine(..., anomaly_guard=True) with "
                    "FLAGS_anomaly_max_bad_steps — which checks loss and "
                    "gradients with one fused in-graph bit per step.")
            continue
        if not hasattr(o, "dtype"):
            continue
        if jnp.issubdtype(o.dtype, jnp.floating):
            from ..framework import monitor as _monitor

            # spy counter: proves the compiled path never falls back to
            # per-op host finiteness syncs (tier-1 asserts it stays 0)
            _monitor.stat_add("nan_inf_host_checks")
            if not bool(jnp.isfinite(o).all()):
                from ..framework.errors import PreconditionNotMetError

                raise PreconditionNotMetError(
                    f"op '{op_name}' output #{i} contains NaN/Inf "
                    "(FLAGS_check_nan_inf is enabled)")

def _as_primal(x):
    """Tensor -> backing array; arrays/scalars pass through."""
    from .tensor import Tensor

    if isinstance(x, Tensor):
        return x._value
    return x


_profiler_mod = None

# static-graph capture hook — set by paddle_tpu.static.program when
# enable_static() is active; returns NotImplemented to fall through to
# eager execution (ref: the reference routes the same op calls to either
# the dygraph tracer or ProgramDesc building, fluid/framework.py:185
# in_dygraph_mode switch)
_capture_fn = None


def apply(op_name, *inputs, **attrs):
    """Run op `op_name` on `inputs` (Tensors / arrays / scalars).

    Returns Tensor or tuple of Tensors. For `has_aux` ops the aux outputs are
    appended as stop-gradient Tensors.
    """
    global _profiler_mod
    if _profiler_mod is None:
        from .. import profiler as _p

        _profiler_mod = _p
    if _profiler_mod._op_profiling:
        with _profiler_mod.RecordEvent(op_name, cat="op"):
            return _apply_impl(op_name, inputs, attrs)
    return _apply_impl(op_name, inputs, attrs)


def _apply_impl(op_name, inputs, attrs):
    from .tensor import Tensor

    if _capture_fn is not None:
        captured = _capture_fn(op_name, inputs, attrs)
        if captured is not NotImplemented:
            return captured

    opdef = lookup(op_name)
    tensor_inputs = tuple(x if isinstance(x, Tensor) else None for x in inputs)
    arrs = [_as_primal(x) for x in inputs]
    arrs = _amp_rewrite(op_name, arrs)

    requires_grad = (
        config.is_grad_enabled()
        and config.is_tape_enabled()
        and not opdef.no_grad
        and any(t is not None and not t.stop_gradient for t in tensor_inputs)
    )

    def f(*primals):
        return opdef.fn(*primals, **attrs)

    if not requires_grad:
        out = f(*arrs)
        aux = None
        if opdef.has_aux:
            out, aux = out
        _maybe_check_nan_inf(op_name, out)
        return _wrap_outputs(opdef, out, aux, node=None)

    if opdef.has_aux:
        out, vjp_fn, aux = jax.vjp(f, *arrs, has_aux=True)
    else:
        out, vjp_fn = jax.vjp(f, *arrs)
        aux = None

    _maybe_check_nan_inf(op_name, out)
    outs_flat = out if isinstance(out, tuple) else (out,)
    out_meta = [(o.shape, o.dtype) for o in outs_flat]
    const_primals = {i: a for i, (t, a) in
                     enumerate(zip(tensor_inputs, arrs)) if t is None}
    primal_dtypes = tuple(getattr(a, "dtype", None) for a in arrs)
    node = Node(vjp_fn, tensor_inputs, out_meta, op_name, attrs=attrs,
                const_primals=const_primals, primal_dtypes=primal_dtypes)
    return _wrap_outputs(opdef, out, aux, node=node)


def _wrap_outputs(opdef, out, aux, node):
    from .tensor import Tensor

    def wrap_diff(o, idx):
        t = Tensor(o, stop_gradient=node is None)
        if node is not None:
            t._tape = (node, idx)
        return t

    if isinstance(out, tuple):
        outs = tuple(wrap_diff(o, i) for i, o in enumerate(out))
    else:
        outs = wrap_diff(out, 0)

    if aux is None:
        return outs
    aux_t = jax.tree.map(lambda a: Tensor(a, stop_gradient=True), aux)
    if isinstance(outs, tuple):
        return outs + (aux_t if isinstance(aux_t, tuple) else (aux_t,))
    return (outs,) + (aux_t if isinstance(aux_t, tuple) else (aux_t,))
