"""Operator registry.

Ref parity: paddle/fluid/framework/op_registry.h — the reference keys kernels
by OpKernelType{place,dtype,layout,library}; on TPU every op is a pure
jax-traceable function, so the registry maps op_type -> OpDef. Dispatch,
AMP policy, and autograd live in `dispatch.py`; XLA does kernel selection,
layout, and fusion.

An OpDef's `fn` signature is `fn(*arrays, **attrs) -> array | tuple`.
If `has_aux`, `fn` returns `(differentiable_outputs, aux_outputs)` and only
the first element participates in autograd (indices, masks, ... go in aux).
"""

from __future__ import annotations

import dataclasses
import typing as _t


@dataclasses.dataclass(frozen=True)
class OpDef:
    name: str
    fn: _t.Callable
    has_aux: bool = False
    # multi_out: fn returns a tuple of differentiable outputs
    multi_out: bool = False
    # ops that must never be differentiated (comparison, logical, ...)
    no_grad: bool = False


_REGISTRY: dict[str, OpDef] = {}


def register_op(name: str, *, has_aux: bool = False, multi_out: bool = False,
                no_grad: bool = False):
    """Decorator: @register_op('matmul_v2')."""

    def deco(fn):
        if name in _REGISTRY:
            raise KeyError(f"op '{name}' already registered")
        _REGISTRY[name] = OpDef(name, fn, has_aux=has_aux,
                                multi_out=multi_out, no_grad=no_grad)
        return fn

    return deco


def lookup(name: str) -> OpDef:
    try:
        return _REGISTRY[name]
    except KeyError:
        raise NotImplementedError(
            f"op '{name}' is not registered in paddle_tpu") from None


def registered_ops() -> list[str]:
    return sorted(_REGISTRY)


def has_op(name: str) -> bool:
    return name in _REGISTRY
