"""Dtype system: paddle-style names mapped onto jax/numpy dtypes.

Ref parity: paddle/fluid/framework/framework.proto VarType.Type dtype enum;
python/paddle/fluid/data_feeder.py convert_dtype. TPU-native default compute
dtype is float32 with bfloat16 as the AMP dtype (fp16 kept for compat).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

# canonical name -> jnp dtype
_NAME_TO_DTYPE = {
    "bool": jnp.bool_,
    "uint8": jnp.uint8,
    "int8": jnp.int8,
    "int16": jnp.int16,
    "int32": jnp.int32,
    "int64": jnp.int64,
    "float16": jnp.float16,
    "bfloat16": jnp.bfloat16,
    "float32": jnp.float32,
    "float64": jnp.float64,
    "complex64": jnp.complex64,
    "complex128": jnp.complex128,
}

_ALIASES = {
    "float": "float32",
    "double": "float64",
    "half": "float16",
    "int": "int32",
    "long": "int64",
    "bfloat": "bfloat16",
}


class DType:
    """Lightweight dtype handle so `paddle_tpu.float32` etc. exist and
    compare equal to their string names and numpy dtypes."""

    __slots__ = ("name", "np_dtype")

    def __init__(self, name: str):
        self.name = name
        self.np_dtype = np.dtype(_NAME_TO_DTYPE[name])

    def __repr__(self):
        return f"paddle_tpu.{self.name}"

    def __eq__(self, other):
        try:
            return canonical_dtype_name(other) == self.name
        except (TypeError, ValueError):
            return NotImplemented

    def __hash__(self):
        return hash(self.name)


_DTYPE_SINGLETONS = {name: DType(name) for name in _NAME_TO_DTYPE}


def canonical_dtype_name(d) -> str:
    """Normalise any dtype-ish (str, DType, np.dtype, jnp type) to a name."""
    if isinstance(d, DType):
        return d.name
    if isinstance(d, str):
        d = _ALIASES.get(d, d)
        if d in _NAME_TO_DTYPE:
            return d
        # fall through to np parsing for things like '<f4'
    try:
        name = np.dtype(d).name
    except TypeError as e:  # e.g. bfloat16 class
        name = getattr(d, "__name__", None) or getattr(d, "name", None)
        if name is None:
            raise ValueError(f"unsupported dtype: {d!r}") from e
    if name == "float64" or name == "int64":
        return name
    if name not in _NAME_TO_DTYPE:
        raise ValueError(f"unsupported dtype: {d!r}")
    return name


def to_jax_dtype(d):
    name = canonical_dtype_name(d)
    # TPU-native narrowing: without jax x64 mode, 64-bit requests become
    # their 32-bit counterparts (XLA:TPU emulates int64/f64 anyway).
    # Doing it here keeps jnp from warning on every creation.
    import jax

    if not jax.config.jax_enable_x64 and name in ("int64", "float64",
                                                  "complex128"):
        name = {"int64": "int32", "float64": "float32",
                "complex128": "complex64"}[name]
    return _NAME_TO_DTYPE[name]


def dtype_handle(d) -> DType:
    return _DTYPE_SINGLETONS[canonical_dtype_name(d)]


def is_floating(d) -> bool:
    return jnp.issubdtype(to_jax_dtype(d), jnp.floating)


def is_integer(d) -> bool:
    return jnp.issubdtype(to_jax_dtype(d), jnp.integer)
