"""Global runtime state: grad mode, AMP mode, default dtype, device.

TPU-native analogue of the reference's global tracer switches
(ref: python/paddle/fluid/framework.py:185 `in_dygraph_mode`,
paddle/fluid/imperative/tracer.h:50 `has_grad`, amp mode flags).
Here there is no static/dygraph split: the framework is always
imperative; compiled execution is obtained by `paddle_tpu.jit` /
the functional engine, which trace the same op set.
"""

from __future__ import annotations

import contextlib
import threading


class _RuntimeState(threading.local):
    def __init__(self):
        super().__init__()
        self.grad_enabled = True
        # amp_level: None | 'O1' | 'O2'; amp_dtype: 'bfloat16' | 'float16'
        self.amp_level = None
        self.amp_dtype = "bfloat16"
        self.custom_white_list = None
        self.custom_black_list = None
        self.default_dtype = "float32"
        self.tracing = False  # True while inside jit capture


_state = _RuntimeState()


def is_grad_enabled() -> bool:
    return _state.grad_enabled


def set_grad_enabled(mode: bool):
    """Context manager / function mirroring paddle.set_grad_enabled."""
    return _GradMode(mode)


class _GradMode(contextlib.AbstractContextManager):
    def __init__(self, mode: bool):
        self._mode = bool(mode)
        self._prev = _state.grad_enabled
        _state.grad_enabled = self._mode

    def __exit__(self, *exc):
        _state.grad_enabled = self._prev
        return False


class no_grad(contextlib.ContextDecorator):
    """paddle.no_grad — usable as context manager and decorator."""

    def __enter__(self):
        self._prev = _state.grad_enabled
        _state.grad_enabled = False
        return self

    def __exit__(self, *exc):
        _state.grad_enabled = self._prev
        return False


def is_tape_enabled() -> bool:
    return getattr(_state, "tape_enabled", True)


class no_tape(contextlib.ContextDecorator):
    """Disable eager tape recording (dispatch skips its per-op jax.vjp).

    Used by the functional engines: they differentiate the whole step with
    jax AD, so the tape's inner vjp closures are pure overhead — and a
    nested inner-vjp-under-outer-grad would require second-order rules
    from custom kernels (Pallas flash attention has first-order only)."""

    def __enter__(self):
        self._prev = getattr(_state, "tape_enabled", True)
        _state.tape_enabled = False
        return self

    def __exit__(self, *exc):
        _state.tape_enabled = self._prev
        return False


class enable_grad(contextlib.ContextDecorator):
    def __enter__(self):
        self._prev = _state.grad_enabled
        _state.grad_enabled = True
        return self

    def __exit__(self, *exc):
        _state.grad_enabled = self._prev
        return False


def get_default_dtype() -> str:
    return _state.default_dtype


def set_default_dtype(d) -> None:
    from .dtype import canonical_dtype_name

    _state.default_dtype = canonical_dtype_name(d)


def amp_state():
    return (_state.amp_level, _state.amp_dtype,
            _state.custom_white_list, _state.custom_black_list)
