"""Eager Tensor: a jax.Array handle with paddle semantics.

Ref parity: paddle/fluid/imperative/layer.h:66 (VarBase) +
python/paddle/fluid/dygraph/varbase_patch_methods.py. Differences by design:
the backing store is an immutable `jax.Array` (XLA-managed device buffer;
PJRT handles allocation/donation), "in-place" mutation rebinds the handle,
and autograd state is a (Node, output-index) tape link instead of grad-op
descriptors. LoDTensor has no analogue — variable-length data is expressed
with padding + masks (static shapes for XLA).
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from . import config
from .autograd import backward as _backward
from .dispatch import apply
from .dtype import canonical_dtype_name, dtype_handle, to_jax_dtype


def _coerce(data, dtype=None):
    """Build a jax array from arbitrary input data."""
    if isinstance(data, Tensor):
        data = data._value
    from .selected_rows import SelectedRows

    if isinstance(data, SelectedRows):
        # wrapping a sparse grad in a Tensor densifies it; the sparse fast
        # path lives in Optimizer.step/_apply_sparse which checks the type
        # before wrapping
        data = data.to_dense()
    if isinstance(data, (jax.Array, jax.core.Tracer)):
        # already device data (or a tracer inside jit) — never via numpy
        if dtype is not None:
            return data.astype(to_jax_dtype(dtype))
        return data
    if isinstance(data, (bool, int, float, complex, list, tuple, np.ndarray,
                         np.generic)) or hasattr(data, "__array__"):
        arr = np.asarray(data)
        if dtype is None and arr.dtype == np.float64:
            # paddle default: python floats / float64 numpy -> default dtype
            dtype = config.get_default_dtype()
        if dtype is None and arr.dtype == np.int64 and not isinstance(
                data, np.ndarray):
            dtype = "int64"  # keep python int64 semantics like paddle
        data = arr
    out = jnp.asarray(data, dtype=to_jax_dtype(dtype) if dtype is not None else None)
    return out


class Tensor:
    __slots__ = ("_value", "stop_gradient", "_grad", "_tape", "name",
                 "persistable", "_hooks", "__weakref__")

    def __init__(self, value, dtype=None, stop_gradient=True, name=None):
        self._value = _coerce(value, dtype)
        self.stop_gradient = stop_gradient
        self._grad = None
        self._tape = None
        self.name = name
        self.persistable = False
        self._hooks = []

    # -- basic properties ---------------------------------------------------
    @property
    def shape(self):
        return list(self._value.shape)

    @property
    def ndim(self):
        return self._value.ndim

    dim = ndim

    @property
    def size(self):
        return int(self._value.size)

    @property
    def dtype(self):
        return dtype_handle(self._value.dtype.name)

    @property
    def place(self):
        devs = getattr(self._value, "devices", None)
        if devs is None:
            return "unknown"
        return str(next(iter(self._value.devices())))

    @property
    def T(self):
        return apply("transpose",
                     self, perm=list(range(self.ndim))[::-1])

    def is_leaf(self):
        return self._tape is None

    # -- value access -------------------------------------------------------
    def numpy(self):
        return np.asarray(self._value)

    def item(self, *args):
        return self._value.item(*args)

    def tolist(self):
        return np.asarray(self._value).tolist()

    def __float__(self):
        return float(self._value)

    def __int__(self):
        return int(self._value)

    def __bool__(self):
        return bool(self._value)

    def __index__(self):
        return int(self._value)

    def __len__(self):
        if self.ndim == 0:
            raise TypeError("len() of a 0-d tensor")
        return self._value.shape[0]

    def __repr__(self):
        grad_info = "" if self.stop_gradient else ", stop_gradient=False"
        return (f"Tensor(shape={self.shape}, dtype={self.dtype.name}"
                f"{grad_info},\n       {np.asarray(self._value)!r})")

    def __hash__(self):
        return id(self)

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    # numpy interop
    def __array__(self, dtype=None):
        arr = np.asarray(self._value)
        return arr.astype(dtype) if dtype is not None else arr

    # -- autograd -----------------------------------------------------------
    @property
    def grad(self):
        if self._grad is None:
            return None
        return Tensor(self._grad, stop_gradient=True)

    @grad.setter
    def grad(self, value):
        self._grad = None if value is None else _coerce(value)

    def backward(self, grad_tensor=None, retain_graph=False):
        _backward([self], [grad_tensor], retain_graph=retain_graph)

    def clear_grad(self):
        self._grad = None

    clear_gradient = clear_grad

    def _accumulate_grad(self, g):
        if g.dtype != self._value.dtype:
            g = g.astype(self._value.dtype)
        from .selected_rows import accumulate

        # handles dense+dense, and SelectedRows sparse grads on either side
        self._grad = accumulate(self._grad, g)

    def register_hook(self, hook):
        self._hooks.append(hook)

        class _Removable:
            def remove(inner):
                if hook in self._hooks:
                    self._hooks.remove(hook)
        return _Removable()

    def detach(self):
        # lax.stop_gradient so detach also cuts jax AD when this runs under
        # a functional trace (engine/jit); identity on concrete arrays
        t = Tensor(jax.lax.stop_gradient(self._value), stop_gradient=True)
        t.name = self.name
        return t

    def detach_(self):
        self._tape = None
        self.stop_gradient = True
        return self

    def clone(self):
        return apply("assign", self)

    # -- mutation (rebinds the immutable buffer) ----------------------------
    def _check_inplace(self):
        # mutating a taped (non-leaf) tensor would leave backward walking
        # the pre-mutation graph — paddle rejects this via the inplace
        # version counter (framework/tensor.h inplace_version_counter_)
        if self._tape is not None:
            raise RuntimeError(
                "in-place mutation of a tensor produced by a taped op is "
                "not allowed (its gradient graph would become stale); "
                "use out-of-place ops or .detach() first")

    def set_value(self, value):
        self._check_inplace()
        new = _coerce(value, None)
        if tuple(new.shape) != tuple(self._value.shape):
            raise ValueError(
                f"set_value shape mismatch: {new.shape} vs {self._value.shape}")
        self._value = new.astype(self._value.dtype)

    def copy_(self, other):
        self.set_value(other)
        return self

    def fill_(self, value):
        self._check_inplace()
        self._value = jnp.full_like(self._value, value)
        return self

    def zero_(self):
        self._check_inplace()
        self._value = jnp.zeros_like(self._value)
        return self

    # -- dtype / shape ------------------------------------------------------
    def astype(self, dtype):
        return apply("cast", self, dtype=canonical_dtype_name(dtype))

    cast = astype

    def cpu(self):
        return self

    def pin_memory(self):
        return self

    def cuda(self, *a, **k):
        return self

    def to(self, *args, **kwargs):
        for a in args:
            try:
                return self.astype(a)
            except (ValueError, TypeError):
                continue
        if "dtype" in kwargs:
            return self.astype(kwargs["dtype"])
        return self

    # -- indexing -----------------------------------------------------------
    def __getitem__(self, idx):
        idx = _unwrap_index(idx)
        return apply("getitem", self, idx=idx)

    def __setitem__(self, idx, value):
        self._check_inplace()
        idx = _unwrap_index(idx)
        if isinstance(value, Tensor):
            value = value._value
        self._value = self._value.at[idx].set(value)

    # -- operators (implementations registered in paddle_tpu.ops) -----------
    def __add__(self, o):
        return apply("elementwise_add", self, o)

    def __radd__(self, o):
        return apply("elementwise_add", o, self)

    def __sub__(self, o):
        return apply("elementwise_sub", self, o)

    def __rsub__(self, o):
        return apply("elementwise_sub", o, self)

    def __mul__(self, o):
        return apply("elementwise_mul", self, o)

    def __rmul__(self, o):
        return apply("elementwise_mul", o, self)

    def __truediv__(self, o):
        return apply("elementwise_div", self, o)

    def __rtruediv__(self, o):
        return apply("elementwise_div", o, self)

    def __floordiv__(self, o):
        return apply("elementwise_floordiv", self, o)

    def __rfloordiv__(self, o):
        return apply("elementwise_floordiv", o, self)

    def __mod__(self, o):
        return apply("elementwise_mod", self, o)

    def __rmod__(self, o):
        return apply("elementwise_mod", o, self)

    def __pow__(self, o):
        return apply("elementwise_pow", self, o)

    def __rpow__(self, o):
        return apply("elementwise_pow", o, self)

    def __matmul__(self, o):
        return apply("matmul_v2", self, o)

    def __rmatmul__(self, o):
        return apply("matmul_v2", o, self)

    def __neg__(self):
        return apply("scale", self, scale=-1.0)

    def __abs__(self):
        return apply("abs", self)

    def __invert__(self):
        return apply("logical_not", self)

    def __and__(self, o):
        return apply("bitwise_and", self, o)

    def __rand__(self, o):
        return apply("bitwise_and", o, self)

    def __or__(self, o):
        return apply("bitwise_or", self, o)

    def __ror__(self, o):
        return apply("bitwise_or", o, self)

    def __xor__(self, o):
        return apply("bitwise_xor", self, o)

    def __rxor__(self, o):
        return apply("bitwise_xor", o, self)

    # in-place arithmetic rebinds (autograd-safe only outside taped regions)
    def __iadd__(self, o):
        return self.__add__(o)

    def __isub__(self, o):
        return self.__sub__(o)

    def __imul__(self, o):
        return self.__mul__(o)

    def __itruediv__(self, o):
        return self.__truediv__(o)

    # comparisons (no-grad ops)
    def __eq__(self, o):
        return apply("equal", self, o)

    def __ne__(self, o):
        return apply("not_equal", self, o)

    def __lt__(self, o):
        return apply("less_than", self, o)

    def __le__(self, o):
        return apply("less_equal", self, o)

    def __gt__(self, o):
        return apply("greater_than", self, o)

    def __ge__(self, o):
        return apply("greater_equal", self, o)


def _unwrap_index(idx):
    def unwrap(i):
        if isinstance(i, Tensor):
            return i._value
        if isinstance(i, slice):
            return slice(unwrap(i.start), unwrap(i.stop), unwrap(i.step))
        return i

    if isinstance(idx, tuple):
        return tuple(unwrap(i) for i in idx)
    return unwrap(idx)


class Parameter(Tensor):
    """Trainable tensor (ref: python/paddle/fluid/framework.py Parameter)."""

    __slots__ = ("trainable", "optimize_attr", "regularizer", "need_clip",
                 "param_spec", "is_distributed")

    def __init__(self, value, dtype=None, name=None, trainable=True):
        super().__init__(value, dtype=dtype, stop_gradient=not trainable,
                         name=name)
        self.trainable = trainable
        self.persistable = True
        self.optimize_attr = {"learning_rate": 1.0}
        self.regularizer = None
        self.need_clip = True
        # jax.sharding.PartitionSpec for GSPMD parallelism (set by parallel
        # layers; consumed by the functional engine when building shardings)
        self.param_spec = None
        self.is_distributed = False
