"""Tape-based reverse-mode autograd for the eager (dygraph) API.

Ref parity: paddle/fluid/imperative/basic_engine.cc (BasicEngine::Execute,
PrepareDeps), gradient_accumulator.cc, partial_grad_engine.cc. TPU-native
design: instead of per-op hand-written grad kernels (GradOpMaker), each
dispatched op records the `vjp_fn` produced by `jax.vjp` over its pure-jax
implementation; the backward pass is a topological walk calling those vjp
closures. Inside `jit`/functional-engine tracing the same machinery runs on
tracers, so the whole forward+backward collapses into one XLA computation.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp


class Node:
    """One taped op: holds the vjp closure and links to input tensors."""

    __slots__ = ("vjp_fn", "inputs", "out_meta", "op_name", "__weakref__")

    def __init__(self, vjp_fn, inputs, out_meta, op_name):
        self.vjp_fn = vjp_fn
        # tuple aligned with the primal arrays passed to jax.vjp;
        # entries are Tensor or None (non-tensor primals).
        self.inputs = inputs
        # list of (shape, dtype) per differentiable output, for zero cotangents
        self.out_meta = out_meta
        self.op_name = op_name


def _zero_cotangent(meta):
    shape, dtype = meta
    if jnp.issubdtype(dtype, jnp.floating) or jnp.issubdtype(dtype, jnp.complexfloating):
        return jnp.zeros(shape, dtype)
    # integer/bool outputs take float0 cotangents in jax
    return np.zeros(shape, jax.dtypes.float0)


def _is_float0(g):
    return isinstance(g, np.ndarray) and g.dtype == jax.dtypes.float0


def _topo_order(root_nodes):
    """Post-order DFS over the node graph (iterative; graphs can be deep)."""
    order, seen = [], set()
    stack = [(n, False) for n in root_nodes]
    while stack:
        node, processed = stack.pop()
        if processed:
            order.append(node)
            continue
        if id(node) in seen:
            continue
        seen.add(id(node))
        stack.append((node, True))
        for t in node.inputs:
            if t is not None and t._tape is not None and not t.stop_gradient:
                parent = t._tape[0]
                if id(parent) not in seen:
                    stack.append((parent, False))
    return order


def _accumulate(store, node, idx, value):
    slots = store.setdefault(id(node), {})
    if idx in slots and not _is_float0(slots[idx]):
        if not _is_float0(value):
            slots[idx] = slots[idx] + value
    else:
        slots[idx] = value


def _run_backward(tensors, grad_tensors, retain_graph, sinks=None):
    """Core reverse walk.

    sinks: optional dict id(tensor) -> tensor. When given, captured grads are
    returned in a dict (keyed by id) and leaf `.grad` fields are NOT written.
    When None, grads accumulate into `.grad` of reachable leaf tensors.
    """
    from .tensor import Tensor

    captured = {}

    def leaf_sink(t, g):
        if sinks is None:
            t._accumulate_grad(g)
        elif id(t) in sinks:
            captured[id(t)] = captured[id(t)] + g if id(t) in captured else g

    cot = {}  # id(node) -> {out_idx: cotangent}
    node_of = {}
    roots = []
    for t, g in zip(tensors, grad_tensors):
        if t.stop_gradient:
            continue
        if g is None:
            if t.size != 1:
                raise RuntimeError(
                    "backward() on a non-scalar tensor requires an explicit "
                    "grad_tensor (paddle semantics)")
            seed = jnp.ones_like(t._value)
        else:
            seed = g._value if isinstance(g, Tensor) else jnp.asarray(g)
        if t._tape is None:
            leaf_sink(t, seed)
        else:
            node, idx = t._tape
            _accumulate(cot, node, idx, seed)
            node_of[id(node)] = node
            roots.append(node)

    if roots:
        # map from (node id, out idx) -> intermediate sink tensor, to capture
        # cotangents of non-leaf inputs when requested
        want = {}
        if sinks:
            for t in sinks.values():
                if t._tape is not None:
                    n, i = t._tape
                    want[(id(n), i)] = t

        for node in reversed(_topo_order(roots)):
            slots = cot.pop(id(node), None)
            if slots is None:
                continue  # not reached by any cotangent
            if want:
                for i, v in slots.items():
                    sink_t = want.get((id(node), i))
                    if sink_t is not None and not _is_float0(v):
                        captured[id(sink_t)] = (
                            captured[id(sink_t)] + v
                            if id(sink_t) in captured else v)
            cots = tuple(
                slots.get(i, _zero_cotangent(m))
                for i, m in enumerate(node.out_meta))
            if node.vjp_fn is None:
                raise RuntimeError(
                    "trying to backward through the graph a second time; set "
                    "retain_graph=True if this is intended")
            in_grads = node.vjp_fn(cots if len(node.out_meta) > 1 else cots[0])
            if not retain_graph:
                node.vjp_fn = None
            for t, g in zip(node.inputs, in_grads):
                if t is None or t.stop_gradient or _is_float0(g):
                    continue
                for hook in t._hooks:
                    out = hook(Tensor(g, stop_gradient=True))
                    if out is not None:
                        g = out._value if isinstance(out, Tensor) else out
                if t._tape is None:
                    leaf_sink(t, g)
                else:
                    pnode, pidx = t._tape
                    _accumulate(cot, pnode, pidx, g)
    return captured


def backward(tensors, grad_tensors=None, retain_graph=False):
    """Run reverse accumulation from `tensors`, writing `.grad` on leaves."""
    from .tensor import Tensor

    if isinstance(tensors, Tensor):
        tensors = [tensors]
    if grad_tensors is None:
        grad_tensors = [None] * len(tensors)
    elif isinstance(grad_tensors, Tensor):
        grad_tensors = [grad_tensors]
    _run_backward(tensors, grad_tensors, retain_graph)


def grad(outputs, inputs, grad_outputs=None, retain_graph=None,
         create_graph=False, allow_unused=False, no_grad_vars=None):
    """paddle.grad — partial backward returning grads for `inputs` only.

    Ref parity: paddle/fluid/imperative/partial_grad_engine.cc. Double grad
    (create_graph=True) is not supported yet.
    """
    from .tensor import Tensor

    if create_graph:
        raise NotImplementedError(
            "create_graph=True (double grad) is not implemented yet")
    if isinstance(outputs, Tensor):
        outputs = [outputs]
    if isinstance(inputs, Tensor):
        inputs = [inputs]
    if grad_outputs is None:
        grad_outputs = [None] * len(outputs)
    elif isinstance(grad_outputs, Tensor):
        grad_outputs = [grad_outputs]

    sinks = {id(t): t for t in inputs}
    keep = bool(retain_graph) if retain_graph is not None else create_graph
    captured = _run_backward(outputs, grad_outputs, keep, sinks=sinks)

    results = []
    for t in inputs:
        if id(t) not in captured:
            if not allow_unused:
                raise RuntimeError(
                    "one of the inputs was not used in the graph; pass "
                    "allow_unused=True to return None for it")
            results.append(None)
        else:
            results.append(Tensor(captured[id(t)], stop_gradient=True))
    return results
