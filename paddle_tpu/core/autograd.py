"""Tape-based reverse-mode autograd for the eager (dygraph) API.

Ref parity: paddle/fluid/imperative/basic_engine.cc (BasicEngine::Execute,
PrepareDeps), gradient_accumulator.cc, partial_grad_engine.cc. TPU-native
design: instead of per-op hand-written grad kernels (GradOpMaker), each
dispatched op records the `vjp_fn` produced by `jax.vjp` over its pure-jax
implementation; the backward pass is a topological walk calling those vjp
closures. Inside `jit`/functional-engine tracing the same machinery runs on
tracers, so the whole forward+backward collapses into one XLA computation.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp


class Node:
    """One taped op: holds the vjp closure and links to input tensors."""

    __slots__ = ("vjp_fn", "inputs", "out_meta", "op_name", "attrs",
                 "const_primals", "replay_fn", "primal_dtypes",
                 "__weakref__")

    def __init__(self, vjp_fn, inputs, out_meta, op_name, attrs=None,
                 const_primals=None, replay_fn=None, primal_dtypes=None):
        self.vjp_fn = vjp_fn
        # tuple aligned with the primal arrays passed to jax.vjp;
        # entries are Tensor or None (non-tensor primals).
        self.inputs = inputs
        # list of (shape, dtype) per differentiable output, for zero cotangents
        self.out_meta = out_meta
        self.op_name = op_name
        # attrs + values of non-Tensor primals: enough to re-execute the
        # op's pure function for create_graph (double-grad) replay
        self.attrs = attrs
        self.const_primals = const_primals
        # alternative replay path for non-registry nodes (PyLayer): a pure
        # function over this node's Tensor-slot arrays -> outputs tuple
        self.replay_fn = replay_fn
        # dtypes the vjp actually saw (post-AMP-rewrite); replay casts to
        # these so double grad matches first-order numerics under autocast
        self.primal_dtypes = primal_dtypes


def _zero_cotangent(meta):
    shape, dtype = meta
    if jnp.issubdtype(dtype, jnp.floating) or jnp.issubdtype(dtype, jnp.complexfloating):
        return jnp.zeros(shape, dtype)
    # integer/bool outputs take float0 cotangents in jax
    return np.zeros(shape, jax.dtypes.float0)


def _is_float0(g):
    return isinstance(g, np.ndarray) and g.dtype == jax.dtypes.float0


def _topo_order(root_nodes, cut_ids=None):
    """Post-order DFS over the node graph (iterative; graphs can be deep).

    cut_ids: tensor ids acting as graph cuts — the walk does not descend
    past them (used by create_graph replay to skip everything above the
    requested inputs)."""
    order, seen = [], set()
    stack = [(n, False) for n in root_nodes]
    while stack:
        node, processed = stack.pop()
        if processed:
            order.append(node)
            continue
        if id(node) in seen:
            continue
        seen.add(id(node))
        stack.append((node, True))
        for t in node.inputs:
            if t is None or t.stop_gradient or t._tape is None:
                continue
            if cut_ids is not None and id(t) in cut_ids:
                continue
            parent = t._tape[0]
            if id(parent) not in seen:
                stack.append((parent, False))
    return order


def _accumulate(store, node, idx, value):
    slots = store.setdefault(id(node), {})
    if idx in slots and not _is_float0(slots[idx]):
        if not _is_float0(value):
            slots[idx] = slots[idx] + value
    else:
        slots[idx] = value


def _run_backward(tensors, grad_tensors, retain_graph, sinks=None):
    """Core reverse walk.

    sinks: optional dict id(tensor) -> tensor. When given, captured grads are
    returned in a dict (keyed by id) and leaf `.grad` fields are NOT written.
    When None, grads accumulate into `.grad` of reachable leaf tensors.
    """
    from .tensor import Tensor

    captured = {}

    def leaf_sink(t, g):
        from .selected_rows import accumulate

        if sinks is None:
            t._accumulate_grad(g)
        elif id(t) in sinks:
            captured[id(t)] = accumulate(captured.get(id(t)), g)

    cot = {}  # id(node) -> {out_idx: cotangent}
    node_of = {}
    roots = []
    for t, g in zip(tensors, grad_tensors):
        if t.stop_gradient:
            continue
        if g is None:
            if t.size != 1:
                raise RuntimeError(
                    "backward() on a non-scalar tensor requires an explicit "
                    "grad_tensor (paddle semantics)")
            seed = jnp.ones_like(t._value)
        else:
            seed = g._value if isinstance(g, Tensor) else jnp.asarray(g)
        if t._tape is None:
            leaf_sink(t, seed)
        else:
            node, idx = t._tape
            _accumulate(cot, node, idx, seed)
            node_of[id(node)] = node
            roots.append(node)

    if roots:
        # map from (node id, out idx) -> intermediate sink tensor, to capture
        # cotangents of non-leaf inputs when requested
        want = {}
        if sinks:
            for t in sinks.values():
                if t._tape is not None:
                    n, i = t._tape
                    want[(id(n), i)] = t

        for node in reversed(_topo_order(roots)):
            slots = cot.pop(id(node), None)
            if slots is None:
                continue  # not reached by any cotangent
            if want:
                for i, v in slots.items():
                    sink_t = want.get((id(node), i))
                    if sink_t is not None and not _is_float0(v):
                        captured[id(sink_t)] = (
                            captured[id(sink_t)] + v
                            if id(sink_t) in captured else v)
            cots = tuple(
                slots.get(i, _zero_cotangent(m))
                for i, m in enumerate(node.out_meta))
            if node.vjp_fn is None:
                raise RuntimeError(
                    "trying to backward through the graph a second time; set "
                    "retain_graph=True if this is intended")
            in_grads = node.vjp_fn(cots if len(node.out_meta) > 1 else cots[0])
            if not retain_graph:
                node.vjp_fn = None
            for t, g in zip(node.inputs, in_grads):
                if t is None or t.stop_gradient or _is_float0(g):
                    continue
                from .selected_rows import SelectedRows

                if t._hooks:
                    # hooks see a densified view (computed once); observer
                    # hooks (returning None) keep the sparse grad — only a
                    # hook that REPLACES the grad commits the dense form
                    view = g.to_dense() if isinstance(g, SelectedRows) \
                        else g
                    replaced = False
                    for hook in t._hooks:
                        out = hook(Tensor(view, stop_gradient=True))
                        if out is not None:
                            view = out._value if isinstance(out, Tensor) \
                                else out
                            replaced = True
                    if replaced or not isinstance(g, SelectedRows):
                        g = view
                if t._tape is None:
                    leaf_sink(t, g)
                else:
                    # a sparse cotangent flowing into an upstream vjp
                    # closure must densify — jax vjp_fns take arrays only
                    if isinstance(g, SelectedRows):
                        g = g.to_dense()
                    pnode, pidx = t._tape
                    _accumulate(cot, pnode, pidx, g)
    return captured


def backward(tensors, grad_tensors=None, retain_graph=False):
    """Run reverse accumulation from `tensors`, writing `.grad` on leaves."""
    from .tensor import Tensor

    if isinstance(tensors, Tensor):
        tensors = [tensors]
    if grad_tensors is None:
        grad_tensors = [None] * len(tensors)
    elif isinstance(grad_tensors, Tensor):
        grad_tensors = [grad_tensors]
    _run_backward(tensors, grad_tensors, retain_graph)


def grad(outputs, inputs, grad_outputs=None, retain_graph=None,
         create_graph=False, allow_unused=False, no_grad_vars=None):
    """paddle.grad — partial backward returning grads for `inputs` only.

    Ref parity: paddle/fluid/imperative/partial_grad_engine.cc.
    create_graph=True (double grad) re-executes the taped subgraph as a
    pure jax function and differentiates it with jax.vjp, so the returned
    grads are themselves taped (w.r.t. `inputs` AND every other leaf the
    subgraph touches, e.g. parameters — gradient-penalty training works).
    """
    from .tensor import Tensor

    if isinstance(outputs, Tensor):
        outputs = [outputs]
    if isinstance(inputs, Tensor):
        inputs = [inputs]
    if grad_outputs is None:
        grad_outputs = [None] * len(outputs)
    elif isinstance(grad_outputs, Tensor):
        grad_outputs = [grad_outputs]

    if create_graph:
        return _grad_with_graph(outputs, inputs, grad_outputs,
                                allow_unused)

    sinks = {id(t): t for t in inputs}
    keep = bool(retain_graph) if retain_graph is not None else create_graph
    captured = _run_backward(outputs, grad_outputs, keep, sinks=sinks)

    results = []
    for t in inputs:
        if id(t) not in captured:
            if not allow_unused:
                raise RuntimeError(
                    "one of the inputs was not used in the graph; pass "
                    "allow_unused=True to return None for it")
            results.append(None)
        else:
            results.append(Tensor(captured[id(t)], stop_gradient=True))
    return results


# ---------------------------------------------------------------------------
# create_graph: replay the taped subgraph as a pure function + jax.vjp
# ---------------------------------------------------------------------------


def _replay_forward(order, var_tensors, outputs):
    """Pure function xs -> output arrays re-executing `order` (deps-first)
    with the tensors in `var_tensors` replaced by the traced xs (cut
    semantics for non-leaf vars: the subgraph above them is bypassed)."""
    from .op_registry import lookup

    def forward(*xs):
        env = {id(t): x for t, x in zip(var_tensors, xs)}
        produced = {}

        def val_of(t, node, i):
            if t is not None and id(t) in env:
                v = env[id(t)]
            elif t is not None and t._tape is not None and \
                    id(t._tape[0]) in produced:
                pn, pi = t._tape
                v = produced[id(pn)][pi]
            elif t is not None:
                v = t._value
            else:
                return node.const_primals[i]
            dts = node.primal_dtypes
            if dts is not None and dts[i] is not None \
                    and hasattr(v, "dtype") and v.dtype != dts[i] \
                    and jnp.issubdtype(v.dtype, jnp.floating) \
                    and jnp.issubdtype(dts[i], jnp.floating):
                v = v.astype(dts[i])
            return v

        for node in order:
            if node.replay_fn is not None:
                args = [val_of(t, node, i)
                        for i, t in enumerate(node.inputs)
                        if t is not None]
                out = node.replay_fn(*args)
            elif node.attrs is not None:
                opdef = lookup(node.op_name)
                args = [val_of(t, node, i)
                        for i, t in enumerate(node.inputs)]
                out = opdef.fn(*args, **node.attrs)
                if opdef.has_aux:
                    out = out[0]
            else:
                raise NotImplementedError(
                    f"create_graph through op '{node.op_name}' is not "
                    "supported (no replay record)")
            produced[id(node)] = out if isinstance(out, tuple) else (out,)

        outs = []
        for t in outputs:
            if id(t) in env:
                outs.append(env[id(t)])
            elif t._tape is not None and id(t._tape[0]) in produced:
                outs.append(produced[id(t._tape[0])][t._tape[1]])
            else:
                outs.append(t._value)
        return tuple(outs)

    return forward


def _grad_with_graph(outputs, inputs, grad_outputs, allow_unused):
    from .tensor import Tensor

    # first-order semantics carry over: a stop_gradient input gets no grad
    for t in inputs:
        if t.stop_gradient:
            if allow_unused:
                continue
            raise RuntimeError(
                "grad() requested for a stop_gradient tensor; pass "
                "allow_unused=True to receive None for it")

    roots = [t._tape[0] for t in outputs if t._tape is not None]
    # cut at the requested inputs: nodes strictly above them need no
    # replay (their outputs are bypassed by the env cut anyway)
    order = _topo_order(
        roots, cut_ids={id(t) for t in inputs if not t.stop_gradient})

    for node in order:
        for t in node.inputs:
            if t is not None and t._hooks:
                raise NotImplementedError(
                    "create_graph=True does not support tensors with "
                    "registered hooks in the subgraph (the replay would "
                    "silently skip them)")

    # variables = requested (differentiable) inputs first, then every
    # other differentiable leaf in the subgraph (so second-order backward
    # reaches parameters)
    active = [t for t in inputs if not t.stop_gradient]
    input_ids = {id(t) for t in active}
    extra_leaves = []
    seen = set()
    for node in order:
        for t in node.inputs:
            if t is None or t.stop_gradient or id(t) in input_ids \
                    or id(t) in seen:
                continue
            if t._tape is None:
                seen.add(id(t))
                extra_leaves.append(t)
    var_tensors = active + extra_leaves

    seeds = []
    for t, g in zip(outputs, grad_outputs):
        if g is None:
            if t.size != 1:
                raise RuntimeError(
                    "grad() on a non-scalar output requires grad_outputs")
            seeds.append(jnp.ones_like(t._value))
        else:
            seeds.append(g._value if isinstance(g, Tensor)
                         else jnp.asarray(g))
    seeds = tuple(seeds)

    forward = _replay_forward(order, var_tensors, outputs)

    def grads_of(*xs):
        _, vjp = jax.vjp(forward, *xs)
        gs = vjp(seeds)
        # single-output shape must match how _run_backward feeds
        # cotangents back (bare array when out_meta has one entry)
        return gs if len(gs) > 1 else gs[0]

    primals = [t._value for t in var_tensors]
    gvals, vjp2 = jax.vjp(grads_of, *primals)
    if not isinstance(gvals, tuple):
        gvals = (gvals,)
    out_meta = [(g.shape, g.dtype) for g in gvals]
    node = Node(vjp2, tuple(var_tensors), out_meta, "partial_grad",
                attrs=None)

    # usage check: an unused input has an identically-zero grad function;
    # cheap structural check — the input is used iff some node consumes it
    used = set()
    for n in order:
        for t in n.inputs:
            if t is not None:
                used.add(id(t))
    for t in outputs:
        used.add(id(t))

    active_index = {id(t): i for i, t in enumerate(active)}
    results = []
    for t in inputs:
        if t.stop_gradient or id(t) not in used:
            if not t.stop_gradient and not allow_unused:
                raise RuntimeError(
                    "one of the inputs was not used in the graph; pass "
                    "allow_unused=True to return None for it")
            results.append(None)
            continue
        i = active_index[id(t)]
        g = Tensor(gvals[i], stop_gradient=False)
        g._tape = (node, i)
        results.append(g)
    return results
