"""Core runtime: Tensor, autograd tape, op registry/dispatch, dtypes.

TPU-native reimagining of paddle/fluid/{framework,imperative} — the backing
store is XLA/PJRT arrays managed by JAX; autograd tapes jax.vjp closures;
ops are jax-traceable functions.
"""

from . import config  # noqa: F401
from .autograd import grad  # noqa: F401
from .config import enable_grad, no_grad, set_grad_enabled  # noqa: F401
from .dispatch import apply  # noqa: F401
from .dtype import DType  # noqa: F401
from .op_registry import register_op, registered_ops  # noqa: F401
from .tensor import Parameter, Tensor  # noqa: F401
