"""paddle.regularizer namespace (ref: python/paddle/regularizer.py).

L1Decay/L2Decay are defined with the optimizer update rules (they feed
straight into the compiled per-parameter step); this module gives them
the reference's public import path.
"""

from .optimizer import L1Decay, L2Decay  # noqa: F401

__all__ = ["L1Decay", "L2Decay"]
