"""paddle_tpu.io — datasets and DataLoader.

Ref parity: python/paddle/fluid/dataloader/ (Dataset/BatchSampler/
DistributedBatchSampler) + fluid/reader.py DataLoader +
fluid/dataloader/dataloader_iter.py:97,248 (single-/multi-process
iterators) + dataloader/worker.py (worker loop). `num_workers>0` forks a
real worker pool: samples are collated to numpy inside the workers
(GIL-free of the parent), returned through an mp queue in batch order, and
converted to Tensors in the parent. `use_buffer_reader` double-buffers the
next batch onto the device (jax.device_put is async) while the previous
one computes. TensorDataset batches take the C++ datafeed fast path
(paddle_tpu.native.gather_rows).
"""

from __future__ import annotations

import itertools
import math
import multiprocessing as mp
import queue
import threading
import traceback

import numpy as np

from ..core.tensor import Tensor
from ..framework import random as _random


class Dataset:
    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class IterableDataset(Dataset):
    def __iter__(self):
        raise NotImplementedError

    def __getitem__(self, idx):
        raise RuntimeError("IterableDataset does not support indexing")

    def __len__(self):
        raise RuntimeError("IterableDataset has no len()")


class TensorDataset(Dataset):
    def __init__(self, tensors):
        # store host numpy copies: samples must be fork-safe (loader
        # workers) and free of device-array references
        self.tensors = [np.asarray(t.numpy()) if isinstance(t, Tensor)
                        else np.asarray(t) for t in tensors]

    def __getitem__(self, idx):
        return tuple(t[idx] for t in self.tensors)

    def __len__(self):
        return self.tensors[0].shape[0]


class ComposeDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __len__(self):
        return min(len(d) for d in self.datasets)

    def __getitem__(self, idx):
        out = []
        for d in self.datasets:
            sample = d[idx]
            if isinstance(sample, tuple):
                out.extend(sample)
            else:
                out.append(sample)
        return tuple(out)


class ChainDataset(IterableDataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __iter__(self):
        return itertools.chain(*self.datasets)


class Subset(Dataset):
    def __init__(self, dataset, indices):
        self.dataset = dataset
        self.indices = list(indices)

    def __getitem__(self, idx):
        return self.dataset[self.indices[idx]]

    def __len__(self):
        return len(self.indices)


def random_split(dataset, lengths, generator=None):
    total = len(dataset)
    if sum(lengths) != total:
        raise ValueError("sum of input lengths must equal dataset length")
    perm = np.random.permutation(total)
    out, offset = [], 0
    for n in lengths:
        out.append(Subset(dataset, perm[offset:offset + n].tolist()))
        offset += n
    return out


class Sampler:
    def __init__(self, data_source=None):
        self.data_source = data_source

    def __iter__(self):
        raise NotImplementedError

    def __len__(self):
        return len(self.data_source)


class SequenceSampler(Sampler):
    def __iter__(self):
        return iter(range(len(self.data_source)))


class RandomSampler(Sampler):
    def __init__(self, data_source, replacement=False, num_samples=None,
                 generator=None):
        super().__init__(data_source)
        self.replacement = replacement
        self._num_samples = num_samples

    @property
    def num_samples(self):
        return self._num_samples or len(self.data_source)

    def __iter__(self):
        n = len(self.data_source)
        if self.replacement:
            return iter(np.random.randint(0, n, self.num_samples).tolist())
        return iter(np.random.permutation(n)[:self.num_samples].tolist())

    def __len__(self):
        return self.num_samples


class WeightedRandomSampler(Sampler):
    def __init__(self, weights, num_samples, replacement=True):
        self.weights = np.asarray(weights, dtype=np.float64)
        self.num_samples = num_samples
        self.replacement = replacement

    def __iter__(self):
        p = self.weights / self.weights.sum()
        idx = np.random.choice(len(self.weights), self.num_samples,
                               replace=self.replacement, p=p)
        return iter(idx.tolist())

    def __len__(self):
        return self.num_samples


class BatchSampler(Sampler):
    """ref: python/paddle/fluid/dataloader/batch_sampler.py."""

    def __init__(self, dataset=None, sampler=None, shuffle=False,
                 batch_size=1, drop_last=False):
        if sampler is None:
            sampler = RandomSampler(dataset) if shuffle else \
                SequenceSampler(dataset)
        self.sampler = sampler
        self.batch_size = batch_size
        self.drop_last = drop_last

    def __iter__(self):
        batch = []
        for idx in self.sampler:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        n = len(self.sampler)
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size


class DistributedBatchSampler(BatchSampler):
    """Shards the dataset across data-parallel ranks (per-host input
    sharding on TPU; ref python/paddle/fluid/dataloader/batch_sampler.py
    DistributedBatchSampler)."""

    def __init__(self, dataset, batch_size, num_replicas=None, rank=None,
                 shuffle=False, drop_last=False):
        self.dataset = dataset
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.drop_last = drop_last
        if num_replicas is None or rank is None:
            from ..distributed import get_rank, get_world_size

            num_replicas = num_replicas or get_world_size()
            rank = rank if rank is not None else get_rank()
        self.nranks = num_replicas
        self.local_rank = rank
        self.epoch = 0
        self.num_samples = int(math.ceil(len(dataset) / self.nranks))
        self.total_size = self.num_samples * self.nranks

    def __iter__(self):
        n = len(self.dataset)
        indices = np.arange(n)
        if self.shuffle:
            rng = np.random.RandomState(self.epoch)
            rng.shuffle(indices)
        indices = np.concatenate(
            [indices, indices[: self.total_size - n]])
        indices = indices[self.local_rank::self.nranks]
        batch = []
        for idx in indices.tolist():
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        if self.drop_last:
            return self.num_samples // self.batch_size
        return (self.num_samples + self.batch_size - 1) // self.batch_size

    def set_epoch(self, epoch):
        self.epoch = epoch


def _numpy_collate(batch):
    """Worker-side collate: numpy only (Tensors would drag a jax backend
    into every worker process)."""
    sample = batch[0]
    if isinstance(sample, Tensor):
        return np.stack([np.asarray(s._value) for s in batch])
    if isinstance(sample, np.ndarray):
        return np.stack(batch)
    if isinstance(sample, (int, float, np.number)):
        return np.asarray(batch)
    if isinstance(sample, (list, tuple)):
        transposed = list(zip(*batch))
        return [_numpy_collate(list(fields)) for fields in transposed]
    if isinstance(sample, dict):
        return {k: _numpy_collate([d[k] for d in batch]) for k in sample}
    return batch


def _to_tensor_tree(item):
    if isinstance(item, np.ndarray):
        return Tensor(item)
    if isinstance(item, (list, tuple)):
        return [_to_tensor_tree(v) for v in item]
    if isinstance(item, dict):
        return {k: _to_tensor_tree(v) for k, v in item.items()}
    return item


def default_collate_fn(batch):
    return _to_tensor_tree(_numpy_collate(batch))


class WorkerInfo:
    def __init__(self, id, num_workers, dataset, seed):
        self.id = id
        self.num_workers = num_workers
        self.dataset = dataset
        self.seed = seed


_worker_info: WorkerInfo | None = None


def get_worker_info():
    """Inside a loader worker: (id, num_workers, dataset); None in the
    main process (ref fluid/dataloader/worker.py get_worker_info)."""
    return _worker_info


class _ExcInfo:
    def __init__(self, exc):
        self.type_name = type(exc).__name__
        self.tb = traceback.format_exc()


def _reject_tensors(obj, where):
    """Recursive: device arrays must never be touched inside a forked
    worker (forking an initialised XLA runtime is unsafe)."""
    if isinstance(obj, Tensor):
        raise RuntimeError(
            f"{where} produced a paddle Tensor inside a loader worker; "
            "return numpy when num_workers > 0 — touching device arrays "
            "in a forked child of an initialised XLA runtime is unsafe")
    if isinstance(obj, (list, tuple)):
        for v in obj:
            _reject_tensors(v, where)
    elif isinstance(obj, dict):
        for v in obj.values():
            _reject_tensors(v, where)


def _worker_loop(dataset, index_queue, result_queue, collate_fn, init_fn,
                 worker_id, num_workers, base_seed):
    """ref fluid/dataloader/worker.py:_worker_loop — pull index lists,
    collate to numpy, push (batch_id, data)."""
    global _worker_info
    _worker_info = WorkerInfo(worker_id, num_workers, dataset,
                              base_seed + worker_id)
    np.random.seed(base_seed + worker_id)
    if init_fn is not None:
        init_fn(worker_id)
    while True:
        job = index_queue.get()
        if job is None:
            return
        batch_id, idxs = job
        try:
            samples = [dataset[i] for i in idxs]
            for s in samples:
                _reject_tensors(s, "dataset __getitem__")
            data = collate_fn(samples)
            _reject_tensors(data, "collate_fn")
            result_queue.put((batch_id, ("ok", data)))
        except Exception as e:  # noqa: BLE001 — forwarded to parent
            result_queue.put((batch_id, ("err", _ExcInfo(e))))


class _MultiprocessIter:
    """Fork-based worker pool with ordered batch reassembly
    (ref fluid/dataloader/dataloader_iter.py:248
    _DataLoaderIterMultiProcess)."""

    def __init__(self, loader):
        self.loader = loader
        self.num_workers = loader.num_workers
        self.timeout = loader.timeout or None  # 0/None => wait, watch pool
        ctx = mp.get_context("fork")
        self.result_queue = ctx.Queue()
        self.index_queues = []
        self.workers = []
        # fresh base seed per iterator/epoch: identical reseeding every
        # epoch would repeat augmentations byte-for-byte
        epoch = loader._epoch_count
        loader._epoch_count += 1
        base_seed = (int(_random.default_generator.initial_seed())
                     * 1000003 + epoch * 7919) & 0x7FFFFFFF
        collate = loader._worker_collate_fn
        for w in range(self.num_workers):
            iq = ctx.Queue()
            p = ctx.Process(
                target=_worker_loop,
                args=(loader.dataset, iq, self.result_queue, collate,
                      loader.worker_init_fn, w, self.num_workers,
                      base_seed),
                daemon=True)
            p.start()
            self.index_queues.append(iq)
            self.workers.append(p)
        self._next_send = 0
        self._next_recv = 0
        self._reorder: dict[int, object] = {}
        self._batches = iter(loader._index_batches())
        self._exhausted = False
        self._window = max(2, loader.prefetch_factor * self.num_workers)
        self._shutdown_done = False
        for _ in range(self._window):
            self._dispatch_one()

    def _dispatch_one(self):
        if self._exhausted:
            return
        try:
            idxs = next(self._batches)
        except StopIteration:
            self._exhausted = True
            return
        wid = self._next_send % self.num_workers
        self.index_queues[wid].put((self._next_send, idxs))
        self._next_send += 1

    def __iter__(self):
        return self

    def __next__(self):
        if self._next_recv >= self._next_send and self._exhausted:
            self._shutdown()
            raise StopIteration
        waited = 0.0
        while self._next_recv not in self._reorder:
            try:
                batch_id, payload = self.result_queue.get(timeout=5.0)
            except queue.Empty:
                waited += 5.0
                dead = [i for i, p in enumerate(self.workers)
                        if not p.is_alive()]
                if dead:
                    self._shutdown()
                    raise RuntimeError(
                        f"DataLoader workers died: ranks {dead}")
                if self.timeout and waited >= self.timeout:
                    self._shutdown()
                    raise RuntimeError(
                        f"DataLoader timed out after {self.timeout}s")
                continue  # timeout unset (block indefinitely) or not yet
            self._reorder[batch_id] = payload
        status, data = self._reorder.pop(self._next_recv)
        self._next_recv += 1
        self._dispatch_one()
        if status == "err":
            self._shutdown()
            raise RuntimeError(
                f"DataLoader worker raised {data.type_name}:\n{data.tb}")
        return _to_tensor_tree(data)

    def _shutdown(self):
        if self._shutdown_done:
            return
        self._shutdown_done = True
        for iq in self.index_queues:
            try:
                iq.put(None)
            except (OSError, ValueError):
                pass
        for p in self.workers:
            p.join(timeout=5)
            if p.is_alive():
                p.terminate()
        self.result_queue.close()

    def __del__(self):
        try:
            self._shutdown()
        except Exception:  # noqa: BLE001 — interpreter teardown
            pass


def _device_prefetch(iterator):
    """Double-buffered prefetch-to-device: the transfer of batch N+1 is
    dispatched (device_put is async) while batch N computes
    (ref reader.py use_buffer_reader / double-buffer queues)."""
    import jax

    def put(batch):
        if isinstance(batch, Tensor):
            return Tensor(jax.device_put(batch._value))
        if isinstance(batch, (list, tuple)):
            return [put(b) for b in batch]
        if isinstance(batch, dict):
            return {k: put(v) for k, v in batch.items()}
        return batch

    prev = None
    for batch in iterator:
        cur = put(batch)
        if prev is not None:
            yield prev
        prev = cur
    if prev is not None:
        yield prev


class DataLoader:
    """ref: python/paddle/fluid/reader.py:146 DataLoader."""

    def __init__(self, dataset, feed_list=None, places=None,
                 return_list=True, batch_sampler=None, batch_size=1,
                 shuffle=False, drop_last=False, collate_fn=None,
                 num_workers=0, use_buffer_reader=True, prefetch_factor=2,
                 use_shared_memory=True, timeout=0, worker_init_fn=None,
                 persistent_workers=False):
        self.dataset = dataset
        self.return_list = return_list
        self.collate_fn = collate_fn or default_collate_fn
        # worker-side collate must stay numpy; a user collate_fn runs
        # verbatim in the worker and np leaves become Tensors in the parent
        self._worker_collate_fn = collate_fn or _numpy_collate
        self.num_workers = num_workers
        self.prefetch_factor = prefetch_factor
        self.use_buffer_reader = use_buffer_reader
        self.timeout = timeout
        self.worker_init_fn = worker_init_fn
        self._epoch_count = 0
        self._iterable_mode = isinstance(dataset, IterableDataset)
        if batch_sampler is not None:
            self.batch_sampler = batch_sampler
            self.batch_size = batch_sampler.batch_size
        elif not self._iterable_mode:
            self.batch_size = batch_size
            self.batch_sampler = BatchSampler(
                dataset, shuffle=shuffle, batch_size=batch_size,
                drop_last=drop_last) if batch_size is not None else None
        else:
            self.batch_size = batch_size
            self.batch_sampler = None
        self.drop_last = drop_last

    def __len__(self):
        if self._iterable_mode:
            raise RuntimeError("IterableDataset has no len()")
        if self.batch_sampler is None:
            return len(self.dataset)
        return len(self.batch_sampler)

    def _index_batches(self):
        """Index lists consumed by the worker pool (map-style, batched)."""
        yield from self.batch_sampler

    def _native_tensor_batch(self, idxs):
        """C++ datafeed fast path: one parallel gather per component
        instead of per-sample indexing + stack."""
        from .. import native

        return [Tensor(native.gather_rows(a, idxs))
                for a in self._native_arrays]

    def _can_use_native(self):
        from .. import native

        cached = getattr(self, "_native_ok", None)
        if cached is not None:
            return cached
        ok = (isinstance(self.dataset, TensorDataset)
              and self.collate_fn is default_collate_fn
              and native.available())
        if ok:
            arrays = []
            for t in self.dataset.tensors:
                a = t.numpy() if isinstance(t, Tensor) else np.asarray(t)
                arrays.append(np.ascontiguousarray(a))
            self._native_arrays = arrays
        self._native_ok = ok
        return ok

    def _iter_batches(self):
        if self._iterable_mode:
            it = iter(self.dataset)
            while True:
                batch = list(itertools.islice(it, self.batch_size))
                if not batch:
                    return
                if len(batch) < self.batch_size and self.drop_last:
                    return
                yield self.collate_fn(batch)
        elif self.batch_sampler is None:
            for i in range(len(self.dataset)):
                yield self.dataset[i]
        elif self._can_use_native():
            for idxs in self.batch_sampler:
                yield self._native_tensor_batch(idxs)
        else:
            for idxs in self.batch_sampler:
                yield self.collate_fn([self.dataset[i] for i in idxs])

    def __iter__(self):
        workers = bool(self.num_workers and self.num_workers > 0)
        if workers and self._iterable_mode:
            # iterable datasets keep the thread prefetcher (each fork would
            # otherwise re-iterate the same stream)
            it = self._prefetch_iter()
        elif workers and self.batch_sampler is not None \
                and not self._can_use_native():
            # batch_size=None (raw-sample mode) and pre-loaded
            # TensorDatasets gain nothing from forking
            it = iter(_MultiprocessIter(self))
        else:
            it = self._iter_batches()
        if self.use_buffer_reader:
            return _device_prefetch(it)
        return it

    def _prefetch_iter(self):
        """Thread-based prefetch pipeline (keeps the accelerator fed while
        the next host batch is assembled). Producer exceptions re-raise in
        the consumer (a dataset error must not look like end-of-epoch)."""
        q: "queue.Queue" = queue.Queue(
            maxsize=max(2, self.prefetch_factor * self.num_workers))
        sentinel = object()
        error = []

        def producer():
            try:
                for b in self._iter_batches():
                    q.put(b)
            except BaseException as e:  # noqa: BLE001 — forwarded below
                error.append(e)
            finally:
                q.put(sentinel)

        t = threading.Thread(target=producer, daemon=True)
        t.start()
        while True:
            item = q.get()
            if item is sentinel:
                if error:
                    raise error[0]
                return
            yield item


