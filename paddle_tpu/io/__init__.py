"""paddle_tpu.io — datasets and DataLoader.

Ref parity: python/paddle/fluid/dataloader/ (Dataset/BatchSampler/
DistributedBatchSampler/worker machinery) + fluid/reader.py DataLoader.
Single-process iteration is the default; `num_workers>0` uses a
thread-based prefetcher (the heavy per-sample decode work on TPU hosts is
numpy-bound and the C++ datafeed (paddle_tpu/native) covers the hot path;
a full shm+fork worker pool mirrors the reference but is deferred).
"""

from __future__ import annotations

import itertools
import math
import queue
import threading

import numpy as np

from ..core.tensor import Tensor
from ..framework import random as _random


class Dataset:
    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class IterableDataset(Dataset):
    def __iter__(self):
        raise NotImplementedError

    def __getitem__(self, idx):
        raise RuntimeError("IterableDataset does not support indexing")

    def __len__(self):
        raise RuntimeError("IterableDataset has no len()")


class TensorDataset(Dataset):
    def __init__(self, tensors):
        self.tensors = tensors

    def __getitem__(self, idx):
        return tuple(t[idx] for t in self.tensors)

    def __len__(self):
        return self.tensors[0].shape[0]


class ComposeDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __len__(self):
        return min(len(d) for d in self.datasets)

    def __getitem__(self, idx):
        out = []
        for d in self.datasets:
            sample = d[idx]
            if isinstance(sample, tuple):
                out.extend(sample)
            else:
                out.append(sample)
        return tuple(out)


class ChainDataset(IterableDataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __iter__(self):
        return itertools.chain(*self.datasets)


class Subset(Dataset):
    def __init__(self, dataset, indices):
        self.dataset = dataset
        self.indices = list(indices)

    def __getitem__(self, idx):
        return self.dataset[self.indices[idx]]

    def __len__(self):
        return len(self.indices)


def random_split(dataset, lengths, generator=None):
    total = len(dataset)
    if sum(lengths) != total:
        raise ValueError("sum of input lengths must equal dataset length")
    perm = np.random.permutation(total)
    out, offset = [], 0
    for n in lengths:
        out.append(Subset(dataset, perm[offset:offset + n].tolist()))
        offset += n
    return out


class Sampler:
    def __init__(self, data_source=None):
        self.data_source = data_source

    def __iter__(self):
        raise NotImplementedError

    def __len__(self):
        return len(self.data_source)


class SequenceSampler(Sampler):
    def __iter__(self):
        return iter(range(len(self.data_source)))


class RandomSampler(Sampler):
    def __init__(self, data_source, replacement=False, num_samples=None,
                 generator=None):
        super().__init__(data_source)
        self.replacement = replacement
        self._num_samples = num_samples

    @property
    def num_samples(self):
        return self._num_samples or len(self.data_source)

    def __iter__(self):
        n = len(self.data_source)
        if self.replacement:
            return iter(np.random.randint(0, n, self.num_samples).tolist())
        return iter(np.random.permutation(n)[:self.num_samples].tolist())

    def __len__(self):
        return self.num_samples


class WeightedRandomSampler(Sampler):
    def __init__(self, weights, num_samples, replacement=True):
        self.weights = np.asarray(weights, dtype=np.float64)
        self.num_samples = num_samples
        self.replacement = replacement

    def __iter__(self):
        p = self.weights / self.weights.sum()
        idx = np.random.choice(len(self.weights), self.num_samples,
                               replace=self.replacement, p=p)
        return iter(idx.tolist())

    def __len__(self):
        return self.num_samples


class BatchSampler(Sampler):
    """ref: python/paddle/fluid/dataloader/batch_sampler.py."""

    def __init__(self, dataset=None, sampler=None, shuffle=False,
                 batch_size=1, drop_last=False):
        if sampler is None:
            sampler = RandomSampler(dataset) if shuffle else \
                SequenceSampler(dataset)
        self.sampler = sampler
        self.batch_size = batch_size
        self.drop_last = drop_last

    def __iter__(self):
        batch = []
        for idx in self.sampler:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        n = len(self.sampler)
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size


class DistributedBatchSampler(BatchSampler):
    """Shards the dataset across data-parallel ranks (per-host input
    sharding on TPU; ref python/paddle/fluid/dataloader/batch_sampler.py
    DistributedBatchSampler)."""

    def __init__(self, dataset, batch_size, num_replicas=None, rank=None,
                 shuffle=False, drop_last=False):
        self.dataset = dataset
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.drop_last = drop_last
        if num_replicas is None or rank is None:
            from ..distributed import get_rank, get_world_size

            num_replicas = num_replicas or get_world_size()
            rank = rank if rank is not None else get_rank()
        self.nranks = num_replicas
        self.local_rank = rank
        self.epoch = 0
        self.num_samples = int(math.ceil(len(dataset) / self.nranks))
        self.total_size = self.num_samples * self.nranks

    def __iter__(self):
        n = len(self.dataset)
        indices = np.arange(n)
        if self.shuffle:
            rng = np.random.RandomState(self.epoch)
            rng.shuffle(indices)
        indices = np.concatenate(
            [indices, indices[: self.total_size - n]])
        indices = indices[self.local_rank::self.nranks]
        batch = []
        for idx in indices.tolist():
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        if self.drop_last:
            return self.num_samples // self.batch_size
        return (self.num_samples + self.batch_size - 1) // self.batch_size

    def set_epoch(self, epoch):
        self.epoch = epoch


def default_collate_fn(batch):
    sample = batch[0]
    if isinstance(sample, (Tensor,)):
        return Tensor(np.stack([np.asarray(s._value) for s in batch]))
    if isinstance(sample, np.ndarray):
        return Tensor(np.stack(batch))
    if isinstance(sample, (int, float, np.number)):
        return Tensor(np.asarray(batch))
    if isinstance(sample, (list, tuple)):
        transposed = list(zip(*batch))
        return [default_collate_fn(list(fields)) for fields in transposed]
    if isinstance(sample, dict):
        return {k: default_collate_fn([d[k] for d in batch]) for k in sample}
    return batch


class DataLoader:
    """ref: python/paddle/fluid/reader.py:146 DataLoader."""

    def __init__(self, dataset, feed_list=None, places=None,
                 return_list=True, batch_sampler=None, batch_size=1,
                 shuffle=False, drop_last=False, collate_fn=None,
                 num_workers=0, use_buffer_reader=True, prefetch_factor=2,
                 use_shared_memory=True, timeout=0, worker_init_fn=None,
                 persistent_workers=False):
        self.dataset = dataset
        self.return_list = return_list
        self.collate_fn = collate_fn or default_collate_fn
        self.num_workers = num_workers
        self.prefetch_factor = prefetch_factor
        self._iterable_mode = isinstance(dataset, IterableDataset)
        if batch_sampler is not None:
            self.batch_sampler = batch_sampler
            self.batch_size = batch_sampler.batch_size
        elif not self._iterable_mode:
            self.batch_size = batch_size
            self.batch_sampler = BatchSampler(
                dataset, shuffle=shuffle, batch_size=batch_size,
                drop_last=drop_last) if batch_size is not None else None
        else:
            self.batch_size = batch_size
            self.batch_sampler = None
        self.drop_last = drop_last

    def __len__(self):
        if self._iterable_mode:
            raise RuntimeError("IterableDataset has no len()")
        if self.batch_sampler is None:
            return len(self.dataset)
        return len(self.batch_sampler)

    def _iter_batches(self):
        if self._iterable_mode:
            it = iter(self.dataset)
            while True:
                batch = list(itertools.islice(it, self.batch_size))
                if not batch:
                    return
                if len(batch) < self.batch_size and self.drop_last:
                    return
                yield self.collate_fn(batch)
        elif self.batch_sampler is None:
            for i in range(len(self.dataset)):
                yield self.dataset[i]
        else:
            for idxs in self.batch_sampler:
                yield self.collate_fn([self.dataset[i] for i in idxs])

    def __iter__(self):
        if self.num_workers and self.num_workers > 0:
            return self._prefetch_iter()
        return self._iter_batches()

    def _prefetch_iter(self):
        """Thread-based prefetch pipeline (keeps the accelerator fed while
        the next host batch is assembled). Producer exceptions re-raise in
        the consumer (a dataset error must not look like end-of-epoch)."""
        q: "queue.Queue" = queue.Queue(
            maxsize=max(2, self.prefetch_factor * self.num_workers))
        sentinel = object()
        error = []

        def producer():
            try:
                for b in self._iter_batches():
                    q.put(b)
            except BaseException as e:  # noqa: BLE001 — forwarded below
                error.append(e)
            finally:
                q.put(sentinel)

        t = threading.Thread(target=producer, daemon=True)
        t.start()
        while True:
            item = q.get()
            if item is sentinel:
                if error:
                    raise error[0]
                return
            yield item


def get_worker_info():
    return None
