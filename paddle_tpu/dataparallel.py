"""paddle.DataParallel (ref: python/paddle/fluid/dygraph/parallel.py:382 +
paddle/fluid/imperative/reducer.cc).

TPU-native semantics: in compiled (engine/pjit) execution, data parallelism
is a sharding of the batch axis over the mesh's 'dp' axis — gradient
synchronisation falls out of GSPMD as XLA all-reduces (no bucketing Reducer
needed; XLA's latency-hiding scheduler overlaps them with the backward).
This wrapper exists for API compatibility: it marks the model as
data-parallel and, when a multi-device mesh is active, lets the engine pick
batch sharding up automatically. Eager single-process behaviour is
identity.
"""

from __future__ import annotations

from .nn.layer.layers import Layer


class DataParallel(Layer):
    def __init__(self, layers, strategy=None, comm_buffer_size=25,
                 last_comm_buffer_size=1, find_unused_parameters=False,
                 group=None):
        super().__init__()
        self._layers = layers
        self.find_unused_parameters = find_unused_parameters
        self.group = group

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)

    def scale_loss(self, loss):
        # kept for API parity; grads are averaged by the compiled allreduce
        return loss

    def apply_collective_grads(self):
        # eager single-process: nothing to reduce; multi-device runs use the
        # compiled engine where XLA emits the reductions
        pass

    def state_dict(self, *args, **kwargs):
        return self._layers.state_dict(*args, **kwargs)

    def set_state_dict(self, state_dict, *args, **kwargs):
        return self._layers.set_state_dict(state_dict, *args, **kwargs)

    load_dict = set_state_dict
    set_dict = set_state_dict
