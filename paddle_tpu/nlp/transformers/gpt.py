"""GPT model family (parity target: FleetX / PaddleNLP GPT-2/3 used by the
reference's hybrid-parallel ladder config; the reference repo itself ships
the layer primitives — nn/layer/transformer.py — and the fleet TP/PP
machinery these models plug into).

TPU-native design:
- decoder blocks use `F.scaled_dot_product_attention` (pallas flash
  attention on TPU, jnp fallback elsewhere);
- TP: q/k/v + mlp projections are Column/RowParallelLinear carrying GSPMD
  specs over 'mp'; vocab embedding sharded over 'mp'; logits stay vocab-
  sharded into ParallelCrossEntropy;
- sequence parallel (megatron-style): optional sharding of the seq axis
  over 'mp' outside the matmul regions (`sequence_parallel=True`);
- PP: blocks are structurally identical -> their params stack into
  [num_layers, ...] leaves, consumed by the scan/ppermute pipeline
  (distributed/hybrid.py).
"""

from __future__ import annotations

import math

from ... import nn
from ...core.tensor import Tensor
from ...distributed.fleet.meta_parallel.mp_layers import (
    ColumnParallelLinear, ParallelCrossEntropy, RowParallelLinear,
    VocabParallelEmbedding, shard_hint,
)
from ...distributed.topology import DP_AXIS, MP_AXIS
from ...nn import functional as F


class GPTConfig:
    def __init__(self, vocab_size=50304, hidden_size=768, num_layers=12,
                 num_heads=12, ffn_hidden_size=None, max_seq_len=1024,
                 dropout=0.1, attn_dropout=0.1, layer_norm_eps=1e-5,
                 initializer_range=0.02, use_parallel=True,
                 sequence_parallel=False, tie_word_embeddings=True):
        self.vocab_size = vocab_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.num_heads = num_heads
        self.ffn_hidden_size = ffn_hidden_size or 4 * hidden_size
        self.max_seq_len = max_seq_len
        self.dropout = dropout
        self.attn_dropout = attn_dropout
        self.layer_norm_eps = layer_norm_eps
        self.initializer_range = initializer_range
        self.use_parallel = use_parallel
        self.sequence_parallel = sequence_parallel
        self.tie_word_embeddings = tie_word_embeddings


_PRESETS = {
    "gpt2-small": dict(hidden_size=768, num_layers=12, num_heads=12),
    "gpt2-medium": dict(hidden_size=1024, num_layers=24, num_heads=16),
    "gpt2-large": dict(hidden_size=1280, num_layers=36, num_heads=20),
    "gpt3-1.3b": dict(hidden_size=2048, num_layers=24, num_heads=16,
                      max_seq_len=2048),
    "gpt3-6.7b": dict(hidden_size=4096, num_layers=32, num_heads=32,
                      max_seq_len=2048),
}


def gpt_config(name, **overrides):
    cfg = dict(_PRESETS[name])
    cfg.update(overrides)
    return GPTConfig(**cfg)


class GPTAttention(nn.Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        h, nh = config.hidden_size, config.num_heads
        self.num_heads = nh
        self.head_dim = h // nh
        self.attn_dropout = config.attn_dropout
        init = nn.initializer.Normal(std=config.initializer_range)
        if config.use_parallel:
            self.qkv_proj = ColumnParallelLinear(
                h, 3 * h, weight_attr=init, gather_output=False)
            self.out_proj = RowParallelLinear(
                h, h, weight_attr=init, input_is_parallel=True)
        else:
            self.qkv_proj = nn.Linear(h, 3 * h, weight_attr=init)
            self.out_proj = nn.Linear(h, h, weight_attr=init)

    def forward(self, x):
        b, s, h = x.shape
        # single packed transpose (see ernie.py): minimises physical
        # copies around the pallas flash custom-call
        qkv = self.qkv_proj(x).reshape(
            [b, s, 3, self.num_heads, self.head_dim]).transpose(
            [2, 0, 3, 1, 4])
        q, k, v = qkv.unstack(axis=0)
        out = F.scaled_dot_product_attention(
            q, k, v, is_causal=True,
            dropout_p=self.attn_dropout if self.training else 0.0,
            qkv_layout="bhsd")
        out = out.reshape([b, s, h])
        return self.out_proj(out)


class GPTMLP(nn.Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        h, f = config.hidden_size, config.ffn_hidden_size
        init = nn.initializer.Normal(std=config.initializer_range)
        if config.use_parallel:
            self.fc1 = ColumnParallelLinear(h, f, weight_attr=init,
                                            gather_output=False)
            self.fc2 = RowParallelLinear(f, h, weight_attr=init,
                                         input_is_parallel=True)
        else:
            self.fc1 = nn.Linear(h, f, weight_attr=init)
            self.fc2 = nn.Linear(f, h, weight_attr=init)

    def forward(self, x):
        return self.fc2(F.gelu(self.fc1(x), approximate=True))


class GPTDecoderLayer(nn.Layer):
    """Pre-LN decoder block. All blocks are structurally identical so
    their params stack for the pipeline scan."""

    def __init__(self, config: GPTConfig):
        super().__init__()
        self.norm1 = nn.LayerNorm(config.hidden_size,
                                  epsilon=config.layer_norm_eps)
        self.attn = GPTAttention(config)
        self.norm2 = nn.LayerNorm(config.hidden_size,
                                  epsilon=config.layer_norm_eps)
        self.mlp = GPTMLP(config)
        self.dropout = config.dropout
        self.sequence_parallel = config.sequence_parallel

    def _sp(self, x):
        if self.sequence_parallel:
            # megatron sequence parallelism: outside matmul regions the
            # activations shard their seq axis over 'mp'
            return shard_hint(x, DP_AXIS, MP_AXIS, None)
        return shard_hint(x, DP_AXIS, None, None)

    def forward(self, x):
        x = self._sp(x)
        h = self.attn(self.norm1(x))
        h = F.dropout(h, self.dropout, training=self.training)
        x = x + h
        x = self._sp(x)
        h = self.mlp(self.norm2(x))
        h = F.dropout(h, self.dropout, training=self.training)
        return x + h


class GPTEmbeddings(nn.Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        init = nn.initializer.Normal(std=config.initializer_range)
        if config.use_parallel:
            self.word_embeddings = VocabParallelEmbedding(
                config.vocab_size, config.hidden_size, weight_attr=init)
        else:
            self.word_embeddings = nn.Embedding(
                config.vocab_size, config.hidden_size, weight_attr=init)
        self.position_embeddings = nn.Embedding(
            config.max_seq_len, config.hidden_size, weight_attr=init)
        self.dropout = config.dropout

    def forward(self, input_ids, position_ids=None):
        import jax.numpy as jnp

        if position_ids is None:
            s = input_ids.shape[-1]
            position_ids = Tensor(jnp.arange(s, dtype=jnp.int32))
        x = self.word_embeddings(input_ids) + \
            self.position_embeddings(position_ids)
        return F.dropout(x, self.dropout, training=self.training)


class GPTModel(nn.Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        self.config = config
        self.embeddings = GPTEmbeddings(config)
        self.layers = nn.LayerList(
            [GPTDecoderLayer(config) for _ in range(config.num_layers)])
        self.final_norm = nn.LayerNorm(config.hidden_size,
                                       epsilon=config.layer_norm_eps)

    def forward(self, input_ids, position_ids=None):
        x = self.embeddings(input_ids, position_ids)
        for layer in self.layers:
            x = layer(x)
        return self.final_norm(x)


class GPTForPretraining(nn.Layer):
    """LM-head model; logits = h @ E^T (tied) stay vocab-sharded over
    'mp' and feed ParallelCrossEntropy."""

    def __init__(self, config: GPTConfig):
        super().__init__()
        self.config = config
        self.gpt = GPTModel(config)
        if not config.tie_word_embeddings:
            self.lm_head = nn.Linear(config.hidden_size, config.vocab_size,
                                     bias_attr=False)

    def forward(self, input_ids, position_ids=None):
        h = self.gpt(input_ids, position_ids)
        return self.logits(h)

    def logits(self, h):
        from ...core.dispatch import apply

        if self.config.tie_word_embeddings:
            w = self.gpt.embeddings.word_embeddings.weight
            logits = apply("matmul_v2", h, w, trans_y=True)
            if self.config.use_parallel:
                logits = shard_hint(logits, DP_AXIS, None, MP_AXIS)
            return logits
        return self.lm_head(h)


class GPTPretrainingCriterion(nn.Layer):
    def __init__(self, config: GPTConfig = None, ignore_index=-100):
        super().__init__()
        use_parallel = config.use_parallel if config is not None else False
        self.loss_fn = ParallelCrossEntropy(ignore_index=ignore_index) \
            if use_parallel else None
        self.ignore_index = ignore_index

    def forward(self, logits, labels, loss_mask=None):
        if self.loss_fn is not None:
            loss = self.loss_fn(logits, labels)
            loss = loss.squeeze(-1)
        else:
            loss = F.cross_entropy(logits, labels, reduction="none",
                                   ignore_index=self.ignore_index)
        if loss_mask is not None:
            m = loss_mask.reshape(loss.shape).astype("float32")
            return (loss * m).sum() / m.sum().clip(min=1.0)
        return loss.mean()
