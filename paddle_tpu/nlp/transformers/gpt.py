"""GPT model family (parity target: FleetX / PaddleNLP GPT-2/3 used by the
reference's hybrid-parallel ladder config; the reference repo itself ships
the layer primitives — nn/layer/transformer.py — and the fleet TP/PP
machinery these models plug into).

TPU-native design:
- decoder blocks use `F.scaled_dot_product_attention` (pallas flash
  attention on TPU, jnp fallback elsewhere);
- TP: q/k/v + mlp projections are Column/RowParallelLinear carrying GSPMD
  specs over 'mp'; vocab embedding sharded over 'mp'; logits stay vocab-
  sharded into ParallelCrossEntropy;
- sequence parallel (megatron-style): optional sharding of the seq axis
  over 'mp' outside the matmul regions (`sequence_parallel=True`);
- PP: blocks are structurally identical -> their params stack into
  [num_layers, ...] leaves, consumed by the scan/ppermute pipeline
  (distributed/hybrid.py).
"""

from __future__ import annotations

import math

from ... import nn
from ...core.config import no_grad
from ...core.tensor import Tensor
from ...distributed.fleet.meta_parallel.mp_layers import (
    ColumnParallelLinear, ParallelCrossEntropy, RowParallelLinear,
    VocabParallelEmbedding, shard_hint,
)
from ...distributed.topology import DP_AXIS, MP_AXIS
from ...nn import functional as F


class GPTConfig:
    def __init__(self, vocab_size=50304, hidden_size=768, num_layers=12,
                 num_heads=12, ffn_hidden_size=None, max_seq_len=1024,
                 dropout=0.1, attn_dropout=0.1, layer_norm_eps=1e-5,
                 initializer_range=0.02, use_parallel=True,
                 sequence_parallel=False, tie_word_embeddings=True,
                 recompute=False):
        self.vocab_size = vocab_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.num_heads = num_heads
        self.ffn_hidden_size = ffn_hidden_size or 4 * hidden_size
        self.max_seq_len = max_seq_len
        self.dropout = dropout
        self.attn_dropout = attn_dropout
        self.layer_norm_eps = layer_norm_eps
        self.initializer_range = initializer_range
        self.use_parallel = use_parallel
        self.sequence_parallel = sequence_parallel
        self.tie_word_embeddings = tie_word_embeddings
        self.recompute = recompute


_PRESETS = {
    "gpt2-small": dict(hidden_size=768, num_layers=12, num_heads=12),
    "gpt2-medium": dict(hidden_size=1024, num_layers=24, num_heads=16),
    "gpt2-large": dict(hidden_size=1280, num_layers=36, num_heads=20),
    "gpt3-1.3b": dict(hidden_size=2048, num_layers=24, num_heads=16,
                      max_seq_len=2048),
    "gpt3-6.7b": dict(hidden_size=4096, num_layers=32, num_heads=32,
                      max_seq_len=2048),
}


def gpt_config(name, **overrides):
    cfg = dict(_PRESETS[name])
    cfg.update(overrides)
    return GPTConfig(**cfg)


class GPTAttention(nn.Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        h, nh = config.hidden_size, config.num_heads
        self.num_heads = nh
        self.head_dim = h // nh
        self.attn_dropout = config.attn_dropout
        init = nn.initializer.Normal(std=config.initializer_range)
        if config.use_parallel:
            self.qkv_proj = ColumnParallelLinear(
                h, 3 * h, weight_attr=init, gather_output=False)
            self.out_proj = RowParallelLinear(
                h, h, weight_attr=init, input_is_parallel=True)
        else:
            self.qkv_proj = nn.Linear(h, 3 * h, weight_attr=init)
            self.out_proj = nn.Linear(h, h, weight_attr=init)

    def forward(self, x, cache=None):
        b, s, h = x.shape
        # single packed transpose (see ernie.py): minimises physical
        # copies around the pallas flash custom-call
        qkv = self.qkv_proj(x).reshape(
            [b, s, 3, self.num_heads, self.head_dim]).transpose(
            [2, 0, 3, 1, 4])
        q, k, v = qkv.unstack(axis=0)
        if cache is not None:
            out, new_cache = self._attend_cached(q, k, v, cache)
            # [b, nh, s, hd] -> [b, s, nh*hd] (sdpa's bhsd mode returns
            # seq-major already; the cached path must match)
            out = out.transpose([0, 2, 1, 3]).reshape([b, s, h])
            return self.out_proj(out), new_cache
        out = F.scaled_dot_product_attention(
            q, k, v, is_causal=True,
            dropout_p=self.attn_dropout if self.training else 0.0,
            qkv_layout="bhsd")
        out = out.reshape([b, s, h])
        return self.out_proj(out)

    def _attend_cached(self, q, k, v, cache):
        """Incremental decode attention over a static-shape KV cache
        (ref paddlenlp generation + fused multi_transformer decode
        caches): new keys/values land at `pos` via dynamic_update_slice;
        queries attend to all cached positions <= their own. Inference
        only — jnp math, no tape.

        `pos` may be a scalar (whole batch at one position — generate())
        or a [b] vector of PER-ROW positions (the serving slot engine,
        where each batch row is an independent request mid-decode). The
        per-row causal mask doubles as stale-KV masking: a recycled
        slot's leftover keys live at positions > the new request's pos,
        so they are never attended before being overwritten.

        Paged mode (the serving block-paged pool): `pos` is a tuple
        ``(pos_vec, block_tables)`` and k/v caches are physical block
        pools ``[num_blocks, nh, block_size, hd]``. Row b's logical
        position t lives at physical row ``(tables[b, t // bs],
        t % bs)``; new KV scatters through the table and the logical
        ``[b, nh, max_seq, hd]`` view is gathered back for the scores.
        Padding rows (positions past the sequence / chunk) are routed
        to reserved block 0, so the step shape never depends on how
        many rows are real — the compile-once property survives
        arbitrary chunked-prefill/decode mixes. The same overwrite-
        before-attend invariant makes block recycling and whole-block
        copy-on-write safe without zeroing."""
        import jax
        import jax.numpy as jnp
        from jax import lax

        k_cache, v_cache, pos = cache
        qv = q._value if isinstance(q, Tensor) else q
        kv = k._value if isinstance(k, Tensor) else k
        vv = v._value if isinstance(v, Tensor) else v
        s_new = qv.shape[2]
        if isinstance(pos, tuple):
            return self._attend_paged(qv, kv, vv, k_cache, v_cache,
                                      pos[0], pos[1])
        s_max = k_cache.shape[2]
        key_idx = jnp.arange(s_max)
        pos_vec = getattr(pos, "ndim", 0) == 1
        if pos_vec:
            b = qv.shape[0]
            row = jnp.arange(b)[:, None]              # [b, 1]
            t_idx = pos[:, None] + jnp.arange(s_new)  # [b, s_new]
            # advanced-index scatter: rows land at their own positions
            k_cache = k_cache.at[row, :, t_idx, :].set(
                jnp.swapaxes(kv, 1, 2).astype(k_cache.dtype))
            v_cache = v_cache.at[row, :, t_idx, :].set(
                jnp.swapaxes(vv, 1, 2).astype(v_cache.dtype))
        else:
            k_cache = lax.dynamic_update_slice(
                k_cache, kv.astype(k_cache.dtype), (0, 0, pos, 0))
            v_cache = lax.dynamic_update_slice(
                v_cache, vv.astype(v_cache.dtype), (0, 0, pos, 0))
        scale = 1.0 / (self.head_dim ** 0.5)
        scores = jnp.einsum("bhqd,bhkd->bhqk", qv.astype(jnp.float32),
                            k_cache.astype(jnp.float32)) * scale
        if pos_vec:
            mask = key_idx[None, None, :] <= t_idx[:, :, None]
            scores = jnp.where(mask[:, None], scores, -1e30)
        else:
            q_pos = pos + jnp.arange(s_new)
            mask = key_idx[None, :] <= q_pos[:, None]  # [s_new, s_max]
            scores = jnp.where(mask[None, None], scores, -1e30)
        p = jax.nn.softmax(scores, axis=-1)
        out = jnp.einsum("bhqk,bhkd->bhqd", p,
                         v_cache.astype(jnp.float32)).astype(qv.dtype)
        return Tensor(out), (k_cache, v_cache, pos + s_new)

    def _attend_paged(self, qv, kv, vv, k_pool, v_pool, pos, tables):
        """Paged variant of the vector-pos branch: scatter the new KV
        through per-row block tables into the physical pool, gather the
        logical per-row view back, then the identical per-row causal
        mask. Out-of-range rows (padding past max_seq) write into the
        reserved null block 0; table entries past a slot's allocation
        are 0 too, and both stay unattended because the mask only admits
        keys <= each row's own position.

        Speculative decoding rides the same scatter: a verify step
        bulk-writes all k+1 staged columns (next token + proposals) in
        this one dispatch, and a rejected suffix's pool rows are just
        more garbage-above-the-frontier — masked out by ``key_idx <=
        t_idx`` now, overwritten by the next round's staging before the
        coverage frontier reaches them."""
        import jax
        import jax.numpy as jnp

        b, nh = qv.shape[0], qv.shape[1]
        s_new = qv.shape[2]
        bs = k_pool.shape[2]
        mb = tables.shape[1]
        s_max = mb * bs
        hd = k_pool.shape[3]
        row = jnp.arange(b)[:, None]                  # [b, 1]
        t_idx = pos[:, None] + jnp.arange(s_new)      # [b, s_new]
        safe_t = jnp.minimum(t_idx, s_max - 1)
        blk = jnp.where(t_idx >= s_max, 0,
                        tables[row, safe_t // bs])    # [b, s_new]
        off = safe_t % bs
        # advanced-index scatter through the tables: value rows land at
        # (physical block, in-block offset) of their logical position
        k_pool = k_pool.at[blk, :, off, :].set(
            jnp.swapaxes(kv, 1, 2).astype(k_pool.dtype))
        v_pool = v_pool.at[blk, :, off, :].set(
            jnp.swapaxes(vv, 1, 2).astype(v_pool.dtype))
        # gather each row's logical [nh, s_max, hd] view for the scores
        k_view = k_pool[tables].transpose(0, 2, 1, 3, 4).reshape(
            b, nh, s_max, hd)
        v_view = v_pool[tables].transpose(0, 2, 1, 3, 4).reshape(
            b, nh, s_max, hd)
        scale = 1.0 / (self.head_dim ** 0.5)
        scores = jnp.einsum("bhqd,bhkd->bhqk", qv.astype(jnp.float32),
                            k_view.astype(jnp.float32)) * scale
        key_idx = jnp.arange(s_max)
        mask = key_idx[None, None, :] <= t_idx[:, :, None]
        scores = jnp.where(mask[:, None], scores, -1e30)
        p = jax.nn.softmax(scores, axis=-1)
        out = jnp.einsum("bhqk,bhkd->bhqd", p,
                         v_view.astype(jnp.float32)).astype(qv.dtype)
        return Tensor(out), (k_pool, v_pool, (pos + s_new, tables))


class GPTMLP(nn.Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        h, f = config.hidden_size, config.ffn_hidden_size
        init = nn.initializer.Normal(std=config.initializer_range)
        if config.use_parallel:
            self.fc1 = ColumnParallelLinear(h, f, weight_attr=init,
                                            gather_output=False)
            self.fc2 = RowParallelLinear(f, h, weight_attr=init,
                                         input_is_parallel=True)
        else:
            self.fc1 = nn.Linear(h, f, weight_attr=init)
            self.fc2 = nn.Linear(f, h, weight_attr=init)

    def forward(self, x):
        return self.fc2(F.gelu(self.fc1(x), approximate=True))


class GPTDecoderLayer(nn.Layer):
    """Pre-LN decoder block. All blocks are structurally identical so
    their params stack for the pipeline scan."""

    def __init__(self, config: GPTConfig):
        super().__init__()
        self.norm1 = nn.LayerNorm(config.hidden_size,
                                  epsilon=config.layer_norm_eps)
        self.attn = GPTAttention(config)
        self.norm2 = nn.LayerNorm(config.hidden_size,
                                  epsilon=config.layer_norm_eps)
        self.mlp = GPTMLP(config)
        self.dropout = config.dropout
        self.sequence_parallel = config.sequence_parallel

    def _sp(self, x):
        if self.sequence_parallel:
            # megatron sequence parallelism: outside matmul regions the
            # activations shard their seq axis over 'mp'
            return shard_hint(x, DP_AXIS, MP_AXIS, None)
        return shard_hint(x, DP_AXIS, None, None)

    def forward(self, x, cache=None):
        x = self._sp(x)
        if cache is not None:
            h, new_cache = self.attn(self.norm1(x), cache)
        else:
            h = self.attn(self.norm1(x))
        h = F.dropout(h, self.dropout, training=self.training)
        x = x + h
        x = self._sp(x)
        h = self.mlp(self.norm2(x))
        h = F.dropout(h, self.dropout, training=self.training)
        x = x + h
        if cache is not None:
            return x, new_cache
        return x


class GPTEmbeddings(nn.Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        init = nn.initializer.Normal(std=config.initializer_range)
        if config.use_parallel:
            self.word_embeddings = VocabParallelEmbedding(
                config.vocab_size, config.hidden_size, weight_attr=init)
        else:
            self.word_embeddings = nn.Embedding(
                config.vocab_size, config.hidden_size, weight_attr=init)
        self.position_embeddings = nn.Embedding(
            config.max_seq_len, config.hidden_size, weight_attr=init)
        self.dropout = config.dropout

    def forward(self, input_ids, position_ids=None):
        import jax.numpy as jnp

        if position_ids is None:
            s = input_ids.shape[-1]
            position_ids = Tensor(jnp.arange(s, dtype=jnp.int32))
        x = self.word_embeddings(input_ids) + \
            self.position_embeddings(position_ids)
        return F.dropout(x, self.dropout, training=self.training)


class GPTModel(nn.Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        self.config = config
        self.embeddings = GPTEmbeddings(config)
        self.layers = nn.LayerList(
            [GPTDecoderLayer(config) for _ in range(config.num_layers)])
        self.final_norm = nn.LayerNorm(config.hidden_size,
                                       epsilon=config.layer_norm_eps)

    def forward(self, input_ids, position_ids=None, caches=None):
        x = self.embeddings(input_ids, position_ids)
        if caches is not None:
            new_caches = []
            for layer, c in zip(self.layers, caches):
                x, nc = layer(x, c)
                new_caches.append(nc)
            return self.final_norm(x), new_caches
        if self.config.recompute and self.training:
            # per-block rematerialisation: activations recomputed in the
            # backward, trading FLOPs for the memory that puts billion-
            # parameter configs on one chip (ref recompute strategy)
            from ...distributed.fleet.utils.recompute import recompute

            for layer in self.layers:
                x = recompute(layer, x)
        else:
            for layer in self.layers:
                x = layer(x)
        return self.final_norm(x)

    def init_caches(self, batch_size, max_len, dtype=None):
        """Zeroed static-shape KV caches for incremental decode."""
        import jax.numpy as jnp

        cfg = self.config
        hd = cfg.hidden_size // cfg.num_heads
        dtype = dtype or jnp.bfloat16
        shape = (batch_size, cfg.num_heads, max_len, hd)
        return [(jnp.zeros(shape, dtype), jnp.zeros(shape, dtype), 0)
                for _ in range(cfg.num_layers)]


class GPTForPretraining(nn.Layer):
    """LM-head model; logits = h @ E^T (tied) stay vocab-sharded over
    'mp' and feed ParallelCrossEntropy."""

    def __init__(self, config: GPTConfig):
        super().__init__()
        self.config = config
        self.gpt = GPTModel(config)
        if not config.tie_word_embeddings:
            self.lm_head = nn.Linear(config.hidden_size, config.vocab_size,
                                     bias_attr=False)

    def forward(self, input_ids, position_ids=None):
        h = self.gpt(input_ids, position_ids)
        return self.logits(h)

    @no_grad()
    def generate(self, input_ids, *, max_new_tokens=20, do_sample=False,
                 top_k=50, temperature=1.0, eos_token_id=None, seed=0):
        """Autoregressive decoding with a static-shape KV cache (ref
        paddlenlp GenerationMixin.generate greedy/sampling): one prefill
        pass over the prompt, then one single-token step per new token —
        O(1) attention work per step instead of re-running the prompt.
        Returns [batch, prompt + max_new_tokens] ids; positions after an
        eos repeat eos."""
        import jax
        import jax.numpy as jnp

        was_training = self.training
        self.eval()
        try:
            ids = input_ids._value if isinstance(input_ids, Tensor) \
                else jnp.asarray(input_ids)
            ids = jnp.asarray(ids, jnp.int32)
            b, s0 = ids.shape
            max_len = s0 + max_new_tokens
            if max_len > self.config.max_seq_len:
                raise ValueError(
                    f"prompt + max_new_tokens = {max_len} exceeds "
                    f"max_seq_len {self.config.max_seq_len}")
            caches = self.gpt.init_caches(b, max_len)
            key = jax.random.PRNGKey(seed)
            done = jnp.zeros((b,), bool)

            def step(tok_ids, pos_ids, caches):
                h, caches = self.gpt(Tensor(tok_ids),
                                     Tensor(pos_ids), caches)
                # only the last position feeds sampling: skip the
                # full-vocab projection of the rest of the prompt
                logits = self.logits(h[:, -1:])
                lv = logits._value if isinstance(logits, Tensor) \
                    else logits
                return lv[:, 0, :].astype(jnp.float32), caches

            logits, caches = step(ids, jnp.arange(s0, dtype=jnp.int32),
                                  caches)
            out = [ids]
            for t in range(max_new_tokens):
                if do_sample:
                    scaled = logits / max(temperature, 1e-6)
                    if top_k:
                        kth = jax.lax.top_k(scaled,
                                            min(top_k,
                                                scaled.shape[-1]))[0]
                        scaled = jnp.where(
                            scaled < kth[:, -1:], -jnp.inf, scaled)
                    key, sub = jax.random.split(key)
                    nxt = jax.random.categorical(sub, scaled, axis=-1)
                else:
                    nxt = jnp.argmax(logits, axis=-1)
                nxt = nxt.astype(jnp.int32)
                if eos_token_id is not None:
                    nxt = jnp.where(done, eos_token_id, nxt)
                    done = done | (nxt == eos_token_id)
                out.append(nxt[:, None])
                if t == max_new_tokens - 1:
                    break
                if eos_token_id is not None and bool(done.all()):
                    # pad the remainder with eos and stop early
                    rest = max_new_tokens - t - 1
                    out.append(jnp.full((b, rest), eos_token_id,
                                        jnp.int32))
                    break
                pos = jnp.asarray([s0 + t], jnp.int32)
                logits, caches = step(nxt[:, None], pos, caches)
            return Tensor(jnp.concatenate(out, axis=1))
        finally:
            if was_training:
                self.train()

    def logits(self, h):
        from ...core.dispatch import apply

        if self.config.tie_word_embeddings:
            w = self.gpt.embeddings.word_embeddings.weight
            logits = apply("matmul_v2", h, w, trans_y=True)
            if self.config.use_parallel:
                logits = shard_hint(logits, DP_AXIS, None, MP_AXIS)
            return logits
        return self.lm_head(h)


def lora_logits_delta(hrows, aid, lora_a, lora_b):
    """Batched low-rank LM-head delta for multi-adapter serving
    (ISSUE 20): each slot's hidden rows pick up ``B[aid] @ A[aid] @ h``
    with its own adapter gathered by index — row 0 is the base model's
    all-zero pair, so base slots add exactly ``0.0`` and stay bitwise.

    ``hrows`` is ``[S, H]`` (one row per slot) or ``[S, C, H]`` (the
    speculative verify columns); ``aid`` is ``[S]`` int32;
    ``lora_a`` is ``[n_adapters, r, H]`` and ``lora_b`` is
    ``[n_adapters, V, r]``. Returns f32 logits deltas shaped like the
    head's output (``[S, V]`` / ``[S, C, V]``). Pure jnp — traced
    inside the engine's ONE compiled step; the gather keeps shapes
    static so adding adapters to a slot never retraces."""
    import jax.numpy as jnp

    h = jnp.asarray(hrows).astype(jnp.float32)
    a = jnp.take(jnp.asarray(lora_a), jnp.asarray(aid), axis=0)
    b = jnp.take(jnp.asarray(lora_b), jnp.asarray(aid), axis=0)
    if h.ndim == 2:          # [S, H] x [S, r, H] -> [S, r] -> [S, V]
        low = jnp.einsum("sh,srh->sr", h, a)
        return jnp.einsum("sr,svr->sv", low, b)
    # [S, C, H] x [S, r, H] -> [S, C, r] -> [S, C, V]
    low = jnp.einsum("sch,srh->scr", h, a)
    return jnp.einsum("scr,svr->scv", low, b)


class GPTPretrainingCriterion(nn.Layer):
    def __init__(self, config: GPTConfig = None, ignore_index=-100):
        super().__init__()
        use_parallel = config.use_parallel if config is not None else False
        self.loss_fn = ParallelCrossEntropy(ignore_index=ignore_index) \
            if use_parallel else None
        self.ignore_index = ignore_index

    def forward(self, logits, labels, loss_mask=None):
        if self.loss_fn is not None:
            loss = self.loss_fn(logits, labels)
            loss = loss.squeeze(-1)
        else:
            loss = F.cross_entropy(logits, labels, reduction="none",
                                   ignore_index=self.ignore_index)
        if loss_mask is not None:
            m = loss_mask.reshape(loss.shape).astype("float32")
            return (loss * m).sum() / m.sum().clip(min=1.0)
        return loss.mean()
