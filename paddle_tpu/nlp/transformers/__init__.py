from .ernie import (  # noqa: F401
    BertConfig, BertForPretraining, BertModel, BertPretrainingCriterion,
    ErnieConfig, ErnieForPretraining, ErnieForSequenceClassification,
    ErnieModel, ErniePretrainingCriterion, bert_config, ernie_config,
)
from .gpt import (  # noqa: F401
    GPTConfig, GPTDecoderLayer, GPTForPretraining, GPTModel,
    GPTPretrainingCriterion, gpt_config,
)
