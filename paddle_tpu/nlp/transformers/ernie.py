"""ERNIE / BERT encoder family (parity target: PaddleNLP ErnieModel /
BertModel — the Baidu flagship pretraining config of BASELINE.json; the
reference repo provides the primitives in python/paddle/nn/layer/
transformer.py that PaddleNLP assembles the model from).

Encoder-only transformer with MLM + NSP pretraining heads. Same TP/GSPMD
options as the GPT family; blocks are structurally uniform for the
pipeline scan.
"""

from __future__ import annotations

from ... import nn
from ...core.tensor import Tensor
from ...distributed.fleet.meta_parallel.mp_layers import (
    ColumnParallelLinear, ParallelCrossEntropy, RowParallelLinear,
    VocabParallelEmbedding, shard_hint,
)
from ...distributed.topology import DP_AXIS, MP_AXIS
from ...nn import functional as F


class ErnieConfig:
    def __init__(self, vocab_size=18000, hidden_size=768, num_layers=12,
                 num_heads=12, ffn_hidden_size=3072, max_seq_len=512,
                 type_vocab_size=4, dropout=0.1, attn_dropout=0.1,
                 layer_norm_eps=1e-12, initializer_range=0.02,
                 use_parallel=False, sequence_parallel=False,
                 recompute=False):
        self.vocab_size = vocab_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.num_heads = num_heads
        self.ffn_hidden_size = ffn_hidden_size
        self.max_seq_len = max_seq_len
        self.type_vocab_size = type_vocab_size
        self.dropout = dropout
        self.attn_dropout = attn_dropout
        self.layer_norm_eps = layer_norm_eps
        self.initializer_range = initializer_range
        self.use_parallel = use_parallel
        self.sequence_parallel = sequence_parallel
        self.recompute = recompute


_PRESETS = {
    "ernie-1.0": dict(vocab_size=18000, hidden_size=768, num_layers=12,
                      num_heads=12, ffn_hidden_size=3072),
    "bert-base": dict(vocab_size=30522, hidden_size=768, num_layers=12,
                      num_heads=12, ffn_hidden_size=3072,
                      type_vocab_size=2),
    "bert-large": dict(vocab_size=30522, hidden_size=1024, num_layers=24,
                       num_heads=16, ffn_hidden_size=4096,
                       type_vocab_size=2),
}


def ernie_config(name, **overrides):
    cfg = dict(_PRESETS[name])
    cfg.update(overrides)
    return ErnieConfig(**cfg)


class ErnieEmbeddings(nn.Layer):
    def __init__(self, config: ErnieConfig):
        super().__init__()
        init = nn.initializer.Normal(std=config.initializer_range)
        if config.use_parallel:
            self.word_embeddings = VocabParallelEmbedding(
                config.vocab_size, config.hidden_size, weight_attr=init)
        else:
            self.word_embeddings = nn.Embedding(
                config.vocab_size, config.hidden_size, weight_attr=init)
        self.position_embeddings = nn.Embedding(
            config.max_seq_len, config.hidden_size, weight_attr=init)
        self.token_type_embeddings = nn.Embedding(
            config.type_vocab_size, config.hidden_size, weight_attr=init)
        self.layer_norm = nn.LayerNorm(config.hidden_size,
                                       epsilon=config.layer_norm_eps)
        self.dropout = config.dropout

    def forward(self, input_ids, token_type_ids=None, position_ids=None):
        import jax.numpy as jnp

        if position_ids is None:
            s = input_ids.shape[-1]
            position_ids = Tensor(jnp.arange(s, dtype=jnp.int32))
        x = self.word_embeddings(input_ids) + \
            self.position_embeddings(position_ids)
        if token_type_ids is not None:
            x = x + self.token_type_embeddings(token_type_ids)
        x = self.layer_norm(x)
        return F.dropout(x, self.dropout, training=self.training)


class ErnieSelfAttention(nn.Layer):
    def __init__(self, config: ErnieConfig):
        super().__init__()
        h, nh = config.hidden_size, config.num_heads
        self.num_heads = nh
        self.head_dim = h // nh
        self.attn_dropout = config.attn_dropout
        init = nn.initializer.Normal(std=config.initializer_range)
        if config.use_parallel:
            self.qkv_proj = ColumnParallelLinear(
                h, 3 * h, weight_attr=init, gather_output=False)
            self.out_proj = RowParallelLinear(
                h, h, weight_attr=init, input_is_parallel=True)
        else:
            self.qkv_proj = nn.Linear(h, 3 * h, weight_attr=init)
            self.out_proj = nn.Linear(h, h, weight_attr=init)

    def forward(self, x, attn_mask=None):
        b, s, h = x.shape
        # one packed [b,s,3,nh,d] -> [3,b,nh,s,d] transpose instead of
        # three per-tensor ones: the pallas flash custom-call is opaque to
        # XLA transpose fusion, so physical transposes are minimised
        qkv = self.qkv_proj(x).reshape(
            [b, s, 3, self.num_heads, self.head_dim]).transpose(
            [2, 0, 3, 1, 4])
        q, k, v = qkv.unstack(axis=0)
        out = F.scaled_dot_product_attention(
            q, k, v, attn_mask=attn_mask,
            dropout_p=self.attn_dropout if self.training else 0.0,
            qkv_layout="bhsd")
        return self.out_proj(out.reshape([b, s, h]))


class ErnieEncoderLayer(nn.Layer):
    """Post-LN encoder block (BERT convention), structurally uniform."""

    def __init__(self, config: ErnieConfig):
        super().__init__()
        init = nn.initializer.Normal(std=config.initializer_range)
        self.self_attn = ErnieSelfAttention(config)
        self.norm1 = nn.LayerNorm(config.hidden_size,
                                  epsilon=config.layer_norm_eps)
        if config.use_parallel:
            self.fc1 = ColumnParallelLinear(
                config.hidden_size, config.ffn_hidden_size,
                weight_attr=init, gather_output=False)
            self.fc2 = RowParallelLinear(
                config.ffn_hidden_size, config.hidden_size,
                weight_attr=init, input_is_parallel=True)
        else:
            self.fc1 = nn.Linear(config.hidden_size,
                                 config.ffn_hidden_size, weight_attr=init)
            self.fc2 = nn.Linear(config.ffn_hidden_size,
                                 config.hidden_size, weight_attr=init)
        self.norm2 = nn.LayerNorm(config.hidden_size,
                                  epsilon=config.layer_norm_eps)
        self.dropout = config.dropout
        self.sequence_parallel = config.sequence_parallel

    def _sp(self, x):
        if self.sequence_parallel:
            return shard_hint(x, DP_AXIS, MP_AXIS, None)
        return shard_hint(x, DP_AXIS, None, None)

    def forward(self, x, attn_mask=None):
        x = self._sp(x)
        h = self.self_attn(x, attn_mask)
        h = F.dropout(h, self.dropout, training=self.training)
        x = self.norm1(x + h)
        x = self._sp(x)
        h = self.fc2(F.gelu(self.fc1(x), approximate=True))
        h = F.dropout(h, self.dropout, training=self.training)
        return self.norm2(x + h)


class ErniePooler(nn.Layer):
    def __init__(self, config: ErnieConfig):
        super().__init__()
        self.dense = nn.Linear(config.hidden_size, config.hidden_size)

    def forward(self, hidden):
        return F.tanh(self.dense(hidden[:, 0]))


class ErnieModel(nn.Layer):
    def __init__(self, config: ErnieConfig):
        super().__init__()
        self.config = config
        self.embeddings = ErnieEmbeddings(config)
        self.encoder = nn.LayerList(
            [ErnieEncoderLayer(config) for _ in range(config.num_layers)])
        self.pooler = ErniePooler(config)

    def forward(self, input_ids, token_type_ids=None, position_ids=None,
                attention_mask=None):
        if attention_mask is not None and attention_mask.ndim == 2:
            # [b, s] padding mask -> additive [b, 1, 1, s]
            import jax.numpy as jnp

            m = attention_mask._value.astype(jnp.float32)
            attention_mask = Tensor((1.0 - m)[:, None, None, :] * -1e4)
        x = self.embeddings(input_ids, token_type_ids, position_ids)
        if self.config.recompute:
            # rematerialise each block in backward (jax.checkpoint) —
            # trades ~1/3 more FLOPs for O(layers) activation memory
            from ...distributed.fleet.utils.recompute import recompute

            for layer in self.encoder:
                x = recompute(layer, x, attention_mask)
        else:
            for layer in self.encoder:
                x = layer(x, attention_mask)
        pooled = self.pooler(x)
        return x, pooled


class ErniePretrainingHeads(nn.Layer):
    def __init__(self, config: ErnieConfig, embedding_weights=None):
        super().__init__()
        self.transform = nn.Linear(config.hidden_size, config.hidden_size)
        self.layer_norm = nn.LayerNorm(config.hidden_size,
                                       epsilon=config.layer_norm_eps)
        self._tied = embedding_weights
        if embedding_weights is None:
            self.decoder = nn.Linear(config.hidden_size, config.vocab_size)
        self.seq_relationship = nn.Linear(config.hidden_size, 2)
        self.config = config

    def _fuse_lm_loss(self) -> bool:
        """Plainness predicate for the fused LM-head loss (mirrors the
        FLAGS_use_pallas_conv routing of ResNet): the head must be the
        plain tied-matmul -> cross_entropy pattern — a tied [V, H] table
        with no vocab sharding (ParallelCrossEntropy owns the TP path)."""
        from ...framework.flags import flag

        return (self._tied is not None
                and not self.config.use_parallel
                and flag("FLAGS_use_fused_lm_loss"))

    def forward(self, sequence_output, pooled_output):
        from ...core.dispatch import apply

        h = self.layer_norm(F.gelu(self.transform(sequence_output)))
        if self._fuse_lm_loss():
            # defer the tied matmul: the criterion consumes (h, W)
            # through the fused chunked-vocab loss so [B, S, V] logits
            # are never written (ops/fused_loss.py); .materialize()
            # recovers plain logits for any other consumer
            from ...ops.fused_loss import DeferredLMHead

            logits = DeferredLMHead(h, self._tied)
        elif self._tied is not None:
            logits = apply("matmul_v2", h, self._tied, trans_y=True)
            if self.config.use_parallel:
                logits = shard_hint(logits, DP_AXIS, None, MP_AXIS)
        else:
            logits = self.decoder(h)
        nsp = self.seq_relationship(pooled_output)
        return logits, nsp


class ErnieForPretraining(nn.Layer):
    def __init__(self, config: ErnieConfig):
        super().__init__()
        self.config = config
        self.ernie = ErnieModel(config)
        self.cls = ErniePretrainingHeads(
            config,
            embedding_weights=self.ernie.embeddings.word_embeddings.weight)

    def forward(self, input_ids, token_type_ids=None, position_ids=None,
                attention_mask=None):
        seq, pooled = self.ernie(input_ids, token_type_ids, position_ids,
                                 attention_mask)
        return self.cls(seq, pooled)


class ErniePretrainingCriterion(nn.Layer):
    """MLM + NSP loss (PaddleNLP ErniePretrainingCriterion parity)."""

    def __init__(self, config: ErnieConfig = None, ignore_index=-100):
        super().__init__()
        self.ignore_index = ignore_index
        use_parallel = config.use_parallel if config is not None else False
        self.parallel_ce = ParallelCrossEntropy(ignore_index=ignore_index) \
            if use_parallel else None

    def forward(self, prediction_scores, seq_relationship_score,
                masked_lm_labels, next_sentence_labels=None):
        from ...ops.fused_loss import DeferredLMHead

        if isinstance(prediction_scores, DeferredLMHead):
            # fused path: the head handed us (hidden, tied W) instead of
            # logits — one streaming linear+CE op, identical math
            mlm = F.fused_linear_cross_entropy(
                prediction_scores.hidden, prediction_scores.weight,
                masked_lm_labels, ignore_index=self.ignore_index)
        elif self.parallel_ce is not None:
            mlm = self.parallel_ce(prediction_scores, masked_lm_labels)
            mlm = mlm.squeeze(-1)
            mask = (masked_lm_labels != self.ignore_index)
            mlm = (mlm * mask.astype("float32")).sum() / \
                mask.astype("float32").sum().clip(min=1.0)
        else:
            mlm = F.cross_entropy(prediction_scores, masked_lm_labels,
                                  ignore_index=self.ignore_index)
        if next_sentence_labels is None:
            return mlm
        nsp = F.cross_entropy(seq_relationship_score,
                              next_sentence_labels)
        return mlm + nsp


class ErnieForSequenceClassification(nn.Layer):
    def __init__(self, config: ErnieConfig, num_classes=2, dropout=None):
        super().__init__()
        self.ernie = ErnieModel(config)
        self.dropout = nn.Dropout(dropout if dropout is not None
                                  else config.dropout)
        self.classifier = nn.Linear(config.hidden_size, num_classes)

    def forward(self, input_ids, token_type_ids=None, position_ids=None,
                attention_mask=None):
        _, pooled = self.ernie(input_ids, token_type_ids, position_ids,
                               attention_mask)
        return self.classifier(self.dropout(pooled))


# Bert aliases (same architecture)
BertConfig = ErnieConfig
BertModel = ErnieModel
BertForPretraining = ErnieForPretraining
BertPretrainingCriterion = ErniePretrainingCriterion
bert_config = ernie_config
