"""paddle_tpu.nlp — transformer model family (ERNIE/BERT/GPT) for the
pretraining ladder configs (BASELINE.json)."""

from . import transformers  # noqa: F401
