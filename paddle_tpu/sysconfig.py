"""paddle.sysconfig namespace (ref: python/paddle/sysconfig.py).

Returns the header / native-library directories for the C++ extension
toolchain (utils.cpp_extension builds against these).
"""

import os

__all__ = ["get_include", "get_lib"]


def get_include():
    """Directory containing the framework's C++ headers."""
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "native")


def get_lib():
    """Directory containing the framework's native shared objects."""
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "native")
