"""Step timeline: bounded per-phase aggregates + device-time attribution.

The runtime's engines wrap each stage of a step in a named phase::

    host-prep            batch normalization, fault points, key/lr
    h2d                  host->device batch placement (+ offload moves)
    compile              a step call that traces/compiles a new program
    device-step          the compiled step dispatch (training + decode)
    anomaly-readback     the guard's host sync at step boundaries
    sample               serving host-side token sampling
    checkpoint-snapshot  device->host state copy on the step thread
    checkpoint-write     synchronous checkpoint serialization + commit
    checkpoint-write-async  the same, on the background writer thread
    checkpoint-restore   checkpoint load/verify

Each `phase(...)` context both emits a `profiler.RecordEvent` span (so
phases land in the chrome trace and XProf annotations) and folds the
duration into an O(1) per-phase aggregate here — the aggregate is what
`goodput()` and the Prometheus export read, so the timeline stays
bounded no matter how long the run is.

`attribute(logdir)` closes the loop ROADMAP item 4 asks for: parse the
xplane capture with `profiler.device_op_table` and classify device time
into matmul / attention / collective / elementwise / other buckets —
`Engine.attribute_step()` is the one-call front for it.
"""

from __future__ import annotations

import contextlib
import re
import threading
import time

__all__ = ["StepTimeline", "timeline", "phase", "BUCKETS", "classify_op",
           "attribute", "attribute_rows", "overlap_stats",
           "overlap_report"]


class StepTimeline:
    """Thread-safe phase aggregator: name -> calls/total/max seconds."""

    def __init__(self):
        self._lock = threading.Lock()
        self._agg: dict = {}  # name -> [calls, total_s, max_s]

    @contextlib.contextmanager
    def phase(self, name, cat="phase"):
        from .. import profiler

        t0 = time.perf_counter()
        try:
            with profiler.RecordEvent(f"step.{name}", cat=cat):
                yield
        finally:
            dt = time.perf_counter() - t0
            with self._lock:
                c = self._agg.setdefault(name, [0, 0.0, 0.0])
                c[0] += 1
                c[1] += dt
                c[2] = max(c[2], dt)

    def add(self, name, seconds):
        """Fold an externally-timed duration into a phase aggregate."""
        with self._lock:
            c = self._agg.setdefault(name, [0, 0.0, 0.0])
            c[0] += 1
            c[1] += seconds
            c[2] = max(c[2], seconds)

    def aggregates(self):
        with self._lock:
            return {
                name: {"calls": c[0], "total_s": c[1],
                       "avg_s": c[1] / c[0] if c[0] else 0.0,
                       "max_s": c[2]}
                for name, c in self._agg.items()
            }

    def total(self, name):
        with self._lock:
            c = self._agg.get(name)
            return c[1] if c else 0.0

    def reset(self):
        with self._lock:
            self._agg.clear()


#: process-global timeline every engine reports into
timeline = StepTimeline()
phase = timeline.phase


# ---------------------------------------------------------------------------
# device-time attribution (ROADMAP item 4)
# ---------------------------------------------------------------------------

BUCKETS = ("matmul", "attention", "collective", "elementwise", "other")

# runtime-framework events on the xplane are bookkeeping, not ops —
# e.g. "TfrtCpuExecutable::Execute", "PjitFunction(f)", threadpool
# listeners, and our own "step.*" trace annotations
_FRAMEWORK_RE = re.compile(
    r"::|\(|^(ParseArguments|Thread|Thunk|Stream|Xla|TSL|jit_|Infeed|"
    r"Outfeed|program|shard_args|DevicePut|device_put|BufferFrom|"
    r"TransferTo|CopyTo|H2D|D2H|step\.|serving\.|checkpoint\.|train\.)")

# HLO control-flow wrappers: a `call.3` / `while.2` row's duration
# encloses its children, which appear as their own rows — counting the
# wrapper double-counts the body (seen with the remat'd block scan)
_WRAPPER_RE = re.compile(r"^(call|while|conditional)(\.\d+)?$")

# ordered: the first matching bucket wins (softmax -> attention even
# though a fused name may also contain "multiply"; "convert" must not
# hit the matmul "conv" pattern). Collective names are separator-
# tolerant: fusion rows spell them with underscores (`all_gather_fusion`
# vs the plain op's `all-gather.3`)
_BUCKET_RES = (
    ("collective", re.compile(
        r"all[-_]reduce|all[-_]gather|all[-_]to[-_]all|"
        r"reduce[-_]scatter|collective|permute|psum|send|recv")),
    ("attention", re.compile(r"attention|flash|mha|softmax")),
    ("matmul", re.compile(r"dot|conv(?!ert)|gemm|einsum|matmul")),
    ("elementwise", re.compile(
        r"add|sub(?!scribe)|mul|div|max|min|exp|log|tanh|relu|sqrt|"
        r"select|compare|broadcast|reduce|convert|fusion|transpose|"
        r"copy|concat|slice|pad|iota|rng|scatter|gather|clamp|power|"
        r"neg|sign|floor|erf|bitcast|reshape|update|tuple|constant")),
)


def classify_op(name):
    """Bucket one xplane op name, or None for runtime-framework rows."""
    if name.startswith("$") or _FRAMEWORK_RE.search(name):
        return None
    low = name.lower()
    if _WRAPPER_RE.match(low):
        return None
    for bucket, rx in _BUCKET_RES:
        if rx.search(low):
            return bucket
    return "other"


def attribute_rows(rows, top=10):
    """Classify `profiler.device_op_table` rows into the buckets.

    Framework rows (executor/jit shells that enclose the real ops) are
    dropped so bucket totals do not double-count; the report carries
    the top per-op rows for drill-down."""
    buckets = {b: 0.0 for b in BUCKETS}
    ops = []
    for r in rows:
        b = classify_op(r["name"])
        if b is None:
            continue
        buckets[b] += r["total"]
        ops.append({**r, "bucket": b})
    ops.sort(key=lambda r: r["total"], reverse=True)
    total = sum(buckets.values())
    return {
        "buckets": buckets,
        "fractions": {b: (v / total if total else 0.0)
                      for b, v in buckets.items()},
        "total_us": total,
        "top_ops": ops[:top],
    }


def attribute(logdir, top=10):
    """Parse an xplane capture under `logdir` and bucket device time."""
    from .. import profiler

    _, rows = profiler.device_op_table(logdir)
    return attribute_rows(rows, top=top)


def _merge_intervals(ivs):
    """Union of (start, end) intervals, sorted and coalesced."""
    merged = []
    for s, e in sorted(ivs):
        if merged and s <= merged[-1][1]:
            merged[-1][1] = max(merged[-1][1], e)
        else:
            merged.append([s, e])
    return merged


def _covered(iv, merged):
    """Length of interval `iv` covered by the merged union."""
    s, e = iv
    cov = 0.0
    for ms, me in merged:
        if me <= s:
            continue
        if ms >= e:
            break
        cov += min(e, me) - max(s, ms)
    return cov


def overlap_stats(events):
    """Pair collective device time against CONCURRENTLY-RESIDENT compute.

    events: `profiler.device_op_events` rows (per-occurrence intervals
    on the capture's shared clock). Every collective interval is
    intersected with the union of matmul+attention intervals across all
    device lines: the covered part is collective time hidden behind
    compute somewhere on the chip set; the rest is exposed — time the
    interconnect serializes the step. `exposed_collective_frac` (exposed
    collective time over total classified device time) is the headline
    the FLAGS_mp_overlap ring schedule exists to push down."""
    comp, coll = [], []
    compute_us = collective_us = total_us = 0.0
    for e in events:
        b = classify_op(e["name"])
        if b is None:
            continue
        iv = (e["start_us"], e["start_us"] + e["dur_us"])
        total_us += e["dur_us"]
        if b == "collective":
            coll.append(iv)
            collective_us += e["dur_us"]
        elif b in ("matmul", "attention"):
            comp.append(iv)
            compute_us += e["dur_us"]
    merged = _merge_intervals(comp)
    hidden = sum(_covered(iv, merged) for iv in coll)
    exposed = max(collective_us - hidden, 0.0)
    return {
        "collective_us": collective_us,
        "compute_us": compute_us,
        "hidden_collective_us": hidden,
        "exposed_collective_us": exposed,
        "exposed_collective_frac": (exposed / total_us
                                    if total_us else 0.0),
        "collective_share": (collective_us / total_us
                             if total_us else 0.0),
        "total_us": total_us,
    }


def overlap_report(logdir):
    """Parse an xplane capture and report how much collective time hides
    behind concurrently-resident compute (see overlap_stats)."""
    from .. import profiler

    return overlap_stats(profiler.device_op_events(logdir))
