"""paddle_tpu.observe — unified runtime telemetry.

Four coupled pieces, one import:

* `timeline` / `phase(name)` — nested step-phase spans with bounded
  aggregates, plus `attribute(logdir)` device-time bucketing
  (matmul/attention/collective/elementwise/other).
* `retrace` — global compile-event registry; `no_retrace()` raises on
  any unexpected recompilation, `suppress()` mutes deliberate ones.
* `flight` / `flight_guard()` — always-on bounded black box of recent
  step records, dumped to JSON on crash/preemption/SIGTERM/rollback.
* `snapshot()` / `dump()` / `prometheus_text()` — one export across
  monitor counters, serving metrics, phase aggregates, and goodput.
"""

from .timeline import (BUCKETS, StepTimeline, attribute, attribute_rows,  # noqa: F401
                       classify_op, overlap_report, overlap_stats, phase,
                       timeline)
from .retrace import (RetraceError, annotate, compile_events, no_retrace,  # noqa: F401
                      record_compile, signature_of, suppress)
from . import retrace  # noqa: F401
from .recorder import (FlightRecorder, flight, flight_guard,  # noqa: F401
                       install_signal_handler)
from . import recorder  # noqa: F401
from .export import dump, goodput, prometheus_text, snapshot  # noqa: F401

__all__ = [
    "BUCKETS", "StepTimeline", "attribute", "attribute_rows", "classify_op",
    "overlap_report", "overlap_stats", "phase", "timeline",
    "RetraceError", "annotate", "compile_events", "no_retrace",
    "record_compile", "signature_of", "suppress", "retrace",
    "FlightRecorder", "flight", "flight_guard", "install_signal_handler",
    "recorder",
    "dump", "goodput", "prometheus_text", "snapshot",
]


def reset():
    """Reset every observe registry (tests)."""
    timeline.reset()
    retrace.reset()
    flight.reset()
