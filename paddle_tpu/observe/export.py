"""Unified telemetry export: one snapshot, two formats.

Folds the four registries that grew up independently — the
framework.monitor counters, per-server ServingMetrics, the step
timeline's phase aggregates, and the retrace audit — into a single
labeled view, exported either as a JSON snapshot (`snapshot()` /
`dump()`) or as Prometheus text exposition (`prometheus_text()`, what
the serving front serves on `GET /metrics` with an appropriate Accept
header).

Goodput accounting lives here because it is a pure fold over the
timeline: productive device time over total accounted wall time, with
checkpoint/restore/compile attributed and background (overlapped)
checkpoint writes excluded from the denominator."""

from __future__ import annotations

import json
import os
import re
import time

from . import recorder, retrace
from .timeline import timeline as _timeline

__all__ = ["goodput", "snapshot", "dump", "prometheus_text"]


# phase name -> goodput category; phases not listed count as "other"
_GOODPUT_CATS = {
    "device-step": "productive",
    "compile": "compile",
    "checkpoint-snapshot": "checkpoint",
    "checkpoint-write": "checkpoint",
    "checkpoint-restore": "restore",
    "host-prep": "host",
    "h2d": "host",
    "sample": "host",
    "anomaly-readback": "host",
    # gang supervisor: teardown + backoff + respawn after a rank died
    # or stalled — wall time lost to the coordinated restart
    "gang-restart": "restart",
}
# background writer time overlaps the step thread: report it, but keep
# it out of the goodput denominator
_OVERLAPPED = {"checkpoint-write-async"}


def goodput(aggregates=None):
    """Goodput fractions from the timeline's phase aggregates."""
    if aggregates is None:
        aggregates = _timeline.aggregates()
    cats = {"productive": 0.0, "compile": 0.0, "checkpoint": 0.0,
            "restore": 0.0, "restart": 0.0, "host": 0.0, "other": 0.0}
    overlapped = 0.0
    for name, agg in aggregates.items():
        if name in _OVERLAPPED:
            overlapped += agg["total_s"]
            continue
        cats[_GOODPUT_CATS.get(name, "other")] += agg["total_s"]
    total = sum(cats.values())
    return {
        "categories_s": cats,
        "overlapped_s": overlapped,
        "accounted_s": total,
        "goodput": cats["productive"] / total if total else 0.0,
    }


def snapshot(serving=None):
    """One JSON-able dict across every registry."""
    from ..framework import monitor

    aggs = _timeline.aggregates()
    out = {
        "time": time.time(),
        "pid": os.getpid(),
        "monitor": monitor.stats(),
        "timeline": aggs,
        "goodput": goodput(aggs),
        "compiles": retrace.compile_events(),
        "flight": {
            "last": recorder.flight.snapshot()["records"][-1:],
            "dumps": recorder.flight.dumps(),
        },
        # durable-PS view mirrors the paddle_ps_* Prometheus family
        "ps": {stat.split(".", 1)[1]: monitor.stat_get(stat)
               for stat in _PS_METRICS},
        # recommender-serving view mirrors paddle_rec_*: lifetime
        # counters from monitor + computed gauges over the live caches
        "rec": dict(
            {stat.split(".", 1)[1]: monitor.stat_get(stat)
             for stat in _REC_METRICS},
            **{name.replace("paddle_rec_", ""): value
               for name, (value, _h) in _rec_gauges().items()}),
        # elastic-fleet view mirrors paddle_fleet_*: autoscaler gauges
        # + scale-event counters + SLO error-budget burn (in seconds)
        "fleet": dict(
            {stat.split(".", 1)[1]: monitor.stat_get(stat)
             for stat in _FLEET_METRICS},
            slo_violation_seconds=(
                monitor.stat_get("fleet.slo_violation_ms") / 1e3)),
        # gang-supervised training view mirrors paddle_gang_*: restart/
        # timeout counters + wall time lost to coordinated restarts +
        # live per-rank heartbeat ages from the supervisor registry
        "gang": dict(
            {stat.split(".", 1)[1]: monitor.stat_get(stat)
             for stat in _GANG_METRICS},
            restart_lost_seconds=(
                monitor.stat_get("gang.restart_lost_ms") / 1e3),
            heartbeat_ages=_gang_heartbeat_ages()),
        # mesh-sharded serving view mirrors paddle_serving_mesh_*: the
        # KV-migration counters (every ServingMetrics.inc also lands in
        # the monitor registry; per-engine mesh shape / per-shard
        # occupancy detail lives in snapshot()["serving"]["mesh"] when
        # a ServingMetrics registry is passed)
        "mesh": {stat.split(".", 1)[1]: monitor.stat_get(stat)
                 for stat in _MESH_STATS},
        # persistent-KV-tier view mirrors paddle_serving_kvstore_*
        "kvstore": {stat.split(".", 1)[1]: monitor.stat_get(stat)
                    for stat in _KVSTORE_METRICS},
        # low-precision compute view mirrors paddle_lowp_*
        "lowp": {stat.split(".", 1)[1]: monitor.stat_get(stat)
                 for stat in _LOWP_METRICS},
    }
    if serving is not None:
        out["serving"] = serving.snapshot()
    return out


def dump(path, serving=None):
    """Write `snapshot()` to a JSON file; returns the path."""
    snap = snapshot(serving=serving)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(snap, f, indent=1, default=repr)
    os.replace(tmp, path)
    return path


# ---------------------------------------------------------------------------
# Prometheus text exposition
# ---------------------------------------------------------------------------

_NAME_OK = re.compile(r"[^a-zA-Z0-9_:]")

#: monitor stat -> (prometheus name, type, help) for the durable-PS
#: family; emitted explicitly (ahead of the generic monitor dump, which
#: would mistype the gauges as counters) and mirrored in snapshot()["ps"]
_PS_METRICS = {
    "ps.wal_bytes": (
        "paddle_ps_wal_bytes", "gauge",
        "bytes appended to the PS write-ahead logs"),
    "ps.replication_lag_updates": (
        "paddle_ps_replication_lag_updates", "gauge",
        "updates queued on the async primary->backup replica link"),
    "ps.failovers": (
        "paddle_ps_failovers_total", "counter",
        "primary->backup promotions performed by PS clients"),
    "ps.dedup_hits": (
        "paddle_ps_dedup_hits_total", "counter",
        "retried PS pushes suppressed by (client_id, seq) dedup"),
}

#: monitor stat -> (prometheus name, type, help) for the recommender-
#: serving family (TPUEmbeddingCache + OnlineTrainer); same contract as
#: _PS_METRICS, mirrored in snapshot()["rec"] alongside the live-cache
#: gauges of _rec_gauges()
_REC_METRICS = {
    "rec.cache_hits": (
        "paddle_rec_cache_hits_total", "counter",
        "embedding-cache lookups served from resident rows"),
    "rec.cache_misses": (
        "paddle_rec_cache_misses_total", "counter",
        "embedding-cache lookups that pulled rows from the PS"),
    "rec.cache_evictions": (
        "paddle_rec_cache_evictions_total", "counter",
        "LRU evictions from embedding caches"),
    "rec.cache_invalidations": (
        "paddle_rec_cache_invalidations_total", "counter",
        "resident cache rows marked stale by applied pushes"),
    "rec.cache_refreshes": (
        "paddle_rec_cache_refreshes_total", "counter",
        "stale resident rows re-pulled before being served"),
    "rec.max_served_staleness": (
        "paddle_rec_max_served_staleness", "gauge",
        "max applied-push lag observed by any served embedding read"),
    "rec.online_steps": (
        "paddle_rec_online_steps_total", "counter",
        "click batches fed by online trainers"),
}

#: monitor stat -> (prometheus name, type, help) for the elastic-fleet
#: family (ReplicaSet membership + Autoscaler); same contract as
#: _PS_METRICS, mirrored in snapshot()["fleet"]. Scale-event counters
#: get a direction label; slo_violation_ms is converted to seconds
_FLEET_METRICS = {
    "fleet.target_replicas": (
        "paddle_fleet_target_replicas", "gauge",
        "fleet size the autoscaler is steering toward"),
    "fleet.live_replicas": (
        "paddle_fleet_live_replicas", "gauge",
        "replicas currently healthy (able to take new routes)"),
    "fleet.scale_events_up": (
        "paddle_fleet_scale_events_total", "counter",
        "fleet membership changes (labelled by direction)"),
    "fleet.scale_events_down": (
        "paddle_fleet_scale_events_total", "counter",
        "fleet membership changes (labelled by direction)"),
    "fleet.weight_version": (
        "paddle_fleet_weight_version", "gauge",
        "committed model weight version serving the fleet"),
    "fleet.rollouts": (
        "paddle_fleet_rollouts_total", "counter",
        "rolling weight upgrades committed fleet-wide"),
    "fleet.rollbacks": (
        "paddle_fleet_rollbacks_total", "counter",
        "rollouts auto-rolled-back (gate failure or operator abort)"),
}
#: fleet stats consumed by _FLEET_METRICS or converted inline — kept
#: out of the generic (counter-typed) monitor dump
_FLEET_STATS = set(_FLEET_METRICS) | {"fleet.slo_violation_ms"}

#: monitor stat -> (prometheus name, type, help) for the gang-supervised
#: training family (distributed/gang.py); same contract as _PS_METRICS,
#: mirrored in snapshot()["gang"]. restart_lost_ms is converted to
#: seconds; per-rank heartbeat ages are live gauges from the supervisor
_GANG_METRICS = {
    "gang.restarts": (
        "paddle_gang_restarts_total", "counter",
        "coordinated whole-gang restarts (a rank died or stalled)"),
    "gang.collective_timeouts": (
        "paddle_gang_collective_timeouts_total", "counter",
        "eager collectives/barriers that hit their "
        "FLAGS_dist_timeout_s deadline"),
    "gang.peer_gone": (
        "paddle_gang_peer_gone_total", "counter",
        "p2p sends/recvs that raised PeerGoneError (peer dead or "
        "unreachable within the deadline)"),
    "gang.quarantined": (
        "paddle_gang_quarantined_total", "counter",
        "flaky rank slots excluded from world re-formation"),
    "gang.commits": (
        "paddle_gang_commits_total", "counter",
        "checkpoint steps that passed the gang commit barrier "
        "(globally committed on every rank)"),
    "gang.restores": (
        "paddle_gang_restores_total", "counter",
        "rank restores from a globally committed step"),
    "gang.heartbeats": (
        "paddle_gang_heartbeats_total", "counter",
        "worker heartbeat+watermark writes into the gang registry"),
}
#: gang stats consumed by _GANG_METRICS or converted inline
_GANG_STATS = set(_GANG_METRICS) | {"gang.restart_lost_ms"}

#: monitor stats mirrored in snapshot()["mesh"] (mesh-sharded serving's
#: KV-migration traffic; the serving-registry counters of the same
#: names feed the labelled paddle_serving_mesh_* family below)
_MESH_STATS = (
    "serving.kv_migrations", "serving.kv_migrate_blocks",
    "serving.kv_migrate_bytes", "serving.kv_migrate_faults",
    "serving.kv_migrate_timeouts",
)

#: monitor stat -> (prometheus name, type, help) for the persistent SSD
#: KV tier (serving/kvstore.py); same contract as _PS_METRICS, emitted
#: ahead of the generic dump and mirrored in snapshot()["kvstore"].
#: The per-replica prefix-affinity hit rate rides with the fleet
#: section (it is a labelled gauge over the Router snapshot)
_KVSTORE_METRICS = {
    "serving.kv_spilled_blocks": (
        "paddle_serving_kvstore_spilled_blocks_total", "counter",
        "evicted KV blocks durably appended to the SSD spill tier"),
    "serving.kv_restored_blocks": (
        "paddle_serving_kvstore_restored_blocks_total", "counter",
        "KV blocks re-staged from spilled records on session resume"),
    "serving.kv_invalidated_blocks": (
        "paddle_serving_kvstore_invalidated_blocks_total", "counter",
        "spilled records fenced by weight-rollout commits"),
    "serving.kv_spill_bytes": (
        "paddle_serving_kvstore_spill_bytes_total", "counter",
        "bytes appended to the SSD KV spill tier"),
    "serving.kv_restore_corrupt": (
        "paddle_serving_kvstore_restore_corrupt_records_total",
        "counter",
        "spilled records that failed crc re-verification at restore "
        "(degraded to re-prefill, never wrong tokens)"),
    "serving.kv_restore_fenced": (
        "paddle_serving_kvstore_restore_fenced_total", "counter",
        "session resumes that hit a generation-fenced record and fell "
        "back to re-prefill on the live weights"),
    "serving.kv_spill_errors": (
        "paddle_serving_kvstore_spill_errors_total", "counter",
        "spill appends that failed (durability lost for that block; "
        "the eviction itself proceeded)"),
}

#: monitor stat -> (prometheus name, type, help) for the low-precision
#: compute family (ops/lowp.py + quantization/scaling.py); same
#: contract as _PS_METRICS, mirrored in snapshot()["lowp"]. The
#: matmuls counters carry a dtype label (one prometheus name), and the
#: clip rate is stored as an integer ppm in the monitor registry
#: (monitor stats coerce to int) and rescaled to a ratio at emission
_LOWP_METRICS = {
    "lowp.matmuls_int8": (
        "paddle_lowp_matmuls_total", "counter",
        "matmul instances quantized by the lowp scaled-matmul family, "
        "by quantized dtype (trace-time: one per compiled program)"),
    "lowp.matmuls_fp8": (
        "paddle_lowp_matmuls_total", "counter",
        "matmul instances quantized by the lowp scaled-matmul family, "
        "by quantized dtype (trace-time: one per compiled program)"),
    "lowp.scale_updates": (
        "paddle_lowp_scale_updates_total", "counter",
        "delayed-scaling recompute events absorbed by the ScaleState "
        "carry"),
    "lowp.clipped_elems": (
        "paddle_lowp_clipped_elements_total", "counter",
        "elements that saturated the quantization range under the "
        "delayed scales"),
    "lowp.quantized_elems": (
        "paddle_lowp_quantized_elements_total", "counter",
        "elements quantized under the delayed-scaling region"),
    "lowp.clip_rate_ppm": (
        "paddle_lowp_clip_rate_ppm", "gauge",
        "per-tensor clip/saturation rate of the delayed-scaling "
        "region, parts per million"),
    "lowp.amax_history_depth": (
        "paddle_lowp_amax_history_depth", "gauge",
        "length of each tensor slot's abs-max history ring "
        "(FLAGS_lowp_amax_history)"),
    "lowp.slot_overflow": (
        "paddle_lowp_slot_overflow_total", "counter",
        "matmul operands beyond the ScaleState slot capacity that "
        "fell back to dynamic scaling"),
}

#: disaggregation role encodings for the mesh-family role gauge
MESH_ROLE_CODES = {"any": 0, "prefill": 1, "decode": 2}


def _gang_heartbeat_ages():
    """{rank slot: seconds since its last heartbeat} across live
    supervisors (empty outside a supervisor process)."""
    try:
        from ..distributed.gang import heartbeat_ages

        return heartbeat_ages()
    except Exception:  # telemetry must never break the exporter
        return {}


def _rec_gauges():
    """Live-cache gauges (computed, not monotonic — they track the
    caches currently alive, unlike the process-lifetime counters)."""
    from ..distributed.ps.heter import cache_stats

    s = cache_stats()
    return {
        "paddle_rec_cache_hit_rate": (
            s["hit_rate"],
            "lookup fraction served from resident rows (live caches)"),
        "paddle_rec_cache_size": (
            s["size"], "resident rows across live embedding caches"),
        "paddle_rec_cache_capacity": (
            s["capacity"], "total slots across live embedding caches"),
    }


def _pname(name):
    """Sanitize into a legal Prometheus metric name."""
    n = _NAME_OK.sub("_", name)
    if not n or not (n[0].isalpha() or n[0] in "_:"):
        n = "_" + n
    return n


def _fmt(v):
    if isinstance(v, bool):
        return "1" if v else "0"
    if isinstance(v, float):
        return repr(v)
    return str(v)


class _Lines:
    def __init__(self):
        self.out = []
        self._typed = set()

    def add(self, name, value, mtype="gauge", labels=None, help_=None):
        name = _pname(name)
        if name not in self._typed:
            if help_:
                self.out.append(f"# HELP {name} {help_}")
            self.out.append(f"# TYPE {name} {mtype}")
            self._typed.add(name)
        lab = ""
        if labels:
            parts = ",".join(
                f'{_pname(k)}="{str(v).replace(chr(92), chr(92) * 2).replace(chr(34), chr(92) + chr(34))}"'
                for k, v in labels.items())
            lab = "{" + parts + "}"
        self.out.append(f"{name}{lab} {_fmt(value)}")

    def text(self):
        return "\n".join(self.out) + "\n"


def prometheus_text(serving=None, queue_depth=None, fleet=None):
    """Prometheus/OpenMetrics text across monitor + timeline + goodput
    (+ one server's ServingMetrics when handling its /metrics; `fleet`
    takes a Router/ReplicaSet `snapshot()` and adds the per-replica
    state, restart, heartbeat, and breaker gauges)."""
    from ..framework import monitor

    L = _Lines()

    # durable-PS family first: stable names + correct types (the generic
    # monitor dump below would publish the gauges as counters), always
    # present even at zero so dashboards see the series from boot
    for stat, (pname, mtype, help_) in _PS_METRICS.items():
        L.add(pname, monitor.stat_get(stat), mtype=mtype, help_=help_)

    # recommender-serving family: lifetime counters + live-cache gauges
    for stat, (pname, mtype, help_) in _REC_METRICS.items():
        L.add(pname, monitor.stat_get(stat), mtype=mtype, help_=help_)
    for pname, (value, help_) in _rec_gauges().items():
        L.add(pname, value, help_=help_)

    # elastic-fleet family: autoscaler gauges + direction-labelled
    # scale-event counters + SLO error-budget burn
    for stat, (pname, mtype, help_) in _FLEET_METRICS.items():
        labels = None
        if stat.startswith("fleet.scale_events_"):
            labels = {"direction": stat.rsplit("_", 1)[1]}
        L.add(pname, monitor.stat_get(stat), mtype=mtype, labels=labels,
              help_=help_)
    L.add("paddle_fleet_slo_violation_seconds_total",
          monitor.stat_get("fleet.slo_violation_ms") / 1e3,
          mtype="counter",
          help_="cumulative seconds the windowed e2e p99 spent over "
                "FLAGS_fleet_slo_p99_ms")

    # gang-supervised training family: restart/timeout counters,
    # restart-lost seconds, and live per-rank heartbeat-age gauges
    for stat, (pname, mtype, help_) in _GANG_METRICS.items():
        L.add(pname, monitor.stat_get(stat), mtype=mtype, help_=help_)
    L.add("paddle_gang_restart_lost_seconds_total",
          monitor.stat_get("gang.restart_lost_ms") / 1e3,
          mtype="counter",
          help_="wall time lost to coordinated gang restarts "
                "(detection -> teardown -> backoff -> respawn)")
    for slot, age in sorted(_gang_heartbeat_ages().items()):
        L.add("paddle_gang_rank_heartbeat_age_seconds", age,
              labels={"rank": slot},
              help_="age of this rank's last gang heartbeat")

    # persistent-KV-tier family: spill/restore/fencing traffic of the
    # SSD tier, stable names + helps (mirrored in snapshot()["kvstore"])
    for stat, (pname, mtype, help_) in _KVSTORE_METRICS.items():
        L.add(pname, monitor.stat_get(stat), mtype=mtype, help_=help_)

    # low-precision compute family: dtype-labelled quantized-matmul
    # counters + delayed-scaling clip/update telemetry
    for stat, (pname, mtype, help_) in _LOWP_METRICS.items():
        labels = None
        if stat.startswith("lowp.matmuls_"):
            labels = {"dtype": stat.rsplit("_", 1)[1]}
        L.add(pname, monitor.stat_get(stat), mtype=mtype, labels=labels,
              help_=help_)

    for name, value in sorted(monitor.stats().items()):
        if not isinstance(value, (int, float)):
            continue
        if name in _PS_METRICS or name in _REC_METRICS \
                or name in _FLEET_STATS or name in _GANG_STATS \
                or name in _KVSTORE_METRICS or name in _LOWP_METRICS:
            continue
        L.add(f"paddle_{name}", value, mtype="counter",
              help_="framework.monitor stat")

    aggs = _timeline.aggregates()
    for phase, agg in sorted(aggs.items()):
        L.add("paddle_phase_seconds_total", agg["total_s"], mtype="counter",
              labels={"phase": phase}, help_="step timeline phase time")
        L.add("paddle_phase_calls_total", agg["calls"], mtype="counter",
              labels={"phase": phase})
        L.add("paddle_phase_max_seconds", agg["max_s"],
              labels={"phase": phase})

    gp = goodput(aggs)
    for cat, secs in sorted(gp["categories_s"].items()):
        L.add("paddle_goodput_seconds_total", secs, mtype="counter",
              labels={"category": cat},
              help_="wall time by goodput category")
    L.add("paddle_goodput_seconds_total", gp["overlapped_s"],
          mtype="counter", labels={"category": "overlapped"})
    L.add("paddle_goodput_ratio", gp["goodput"],
          help_="productive fraction of accounted wall time")

    L.add("paddle_compile_events_total", len(retrace.compile_events()),
          mtype="counter", help_="jit compilations recorded")

    if serving is not None:
        snap = serving.snapshot(queue_depth=queue_depth)
        for k, v in sorted(snap.get("counters", {}).items()):
            L.add(f"paddle_serving_{k}_total", v, mtype="counter",
                  help_="serving counter")
        L.add("paddle_serving_uptime_seconds", snap["uptime_s"],
              mtype="counter")
        L.add("paddle_serving_qps", snap["qps"])
        L.add("paddle_serving_tokens_per_second", snap["tokens_per_s"])
        occ = snap["batch_occupancy"]
        L.add("paddle_serving_batch_occupancy", occ["avg"],
              labels={"stat": "avg"},
              help_="decode slot utilisation (active/capacity)")
        L.add("paddle_serving_batch_occupancy", occ["max"],
              labels={"stat": "max"})
        for kind, stats in sorted(snap.get("latency_s", {}).items()):
            for q in ("p50", "p95", "p99", "max"):
                L.add("paddle_serving_latency_seconds", stats[q],
                      labels={"kind": kind, "quantile": q},
                      help_="serving latency quantiles (seconds)")
        blk = snap.get("kv_blocks")
        if blk:
            L.add("paddle_serving_kv_blocks_in_use", blk["in_use"],
                  help_="physical KV blocks referenced at the last step")
            L.add("paddle_serving_kv_blocks_total", blk["total"],
                  help_="usable physical KV blocks in the paged pool")
            L.add("paddle_serving_kv_block_occupancy", blk["occupancy"],
                  labels={"stat": "avg"},
                  help_="KV block-pool utilisation (in_use/total)")
            L.add("paddle_serving_kv_block_occupancy",
                  blk["occupancy_max"], labels={"stat": "max"})
        pfx = snap.get("prefix_cache")
        if pfx:
            L.add("paddle_serving_prefix_cache_hit_rate",
                  pfx["hit_rate"],
                  help_="prompt tokens served from cached KV blocks")
        cp = snap.get("chunked_prefill")
        if cp:
            L.add("paddle_serving_prefill_tokens_per_step",
                  cp["tokens_per_step"],
                  help_="prompt tokens folded into each decode step")
        # speculative decoding: the drafted/accepted/rejected counters
        # already flow through the generic counter loop above as
        # paddle_serving_spec_*_total — only the gauges are added here
        spec = snap.get("speculative")
        if spec:
            L.add("paddle_serving_spec_acceptance_rate",
                  spec["acceptance_rate"],
                  help_="accepted/drafted proposal tokens since start")
            for s, rate in sorted(spec["per_slot_acceptance"].items()):
                L.add("paddle_serving_spec_slot_acceptance_rate", rate,
                      labels={"slot": s},
                      help_="per-slot speculative acceptance rate")
            L.add("paddle_serving_spec_dequant_path",
                  spec["dequant_path"],
                  help_="1 while the engine serves int8-frozen weights "
                        "through the dequant epilogue path")
        # mesh-sharded serving: shape-labelled gauges + KV-migration
        # counters + the disaggregation role gauge
        mesh = snap.get("mesh")
        if mesh:
            mlab = {"mesh": mesh["spec"] or "single"}
            L.add("paddle_serving_mesh_devices", mesh["devices"],
                  labels=mlab,
                  help_="devices in this engine's serving mesh")
            L.add("paddle_serving_mesh_role",
                  MESH_ROLE_CODES.get(mesh["role"], -1),
                  labels={**mlab, "role": mesh["role"]},
                  help_="disaggregation role (0=any 1=prefill 2=decode)")
            for shard in mesh["per_shard_occupancy"]:
                L.add("paddle_serving_mesh_shard_occupancy",
                      shard["occupancy"],
                      labels={**mlab, "shard": str(shard["shard"])},
                      help_="per-shard decode slot occupancy (GSPMD "
                            "runs one program per shard)")
            for k in ("kv_migrations", "kv_migrate_blocks",
                      "kv_migrate_bytes", "kv_migrate_faults"):
                L.add(f"paddle_serving_mesh_{k}_total", mesh[k],
                      mtype="counter", labels=mlab,
                      help_="prefill->decode KV block migration traffic")
        # multi-tenant serving: one labelled family per tenant-scoped
        # signal (qps, tokens, shed, latency quantiles, budget gauge)
        for tname, tsnap in sorted(snap.get("tenants", {}).items()):
            tlab = {"tenant": tname}
            for k, v in sorted(tsnap.get("counters", {}).items()):
                L.add(f"paddle_tenant_{k}_total", v, mtype="counter",
                      labels=tlab, help_="per-tenant serving counter")
            L.add("paddle_tenant_qps", tsnap["qps"], labels=tlab,
                  help_="completions per second billed to this tenant")
            L.add("paddle_tenant_tokens_per_second",
                  tsnap["tokens_per_s"], labels=tlab,
                  help_="generated tokens per second billed to this "
                        "tenant")
            lat = tsnap.get("latency_s")
            if lat:
                for q in ("p50", "p95", "p99", "max"):
                    L.add("paddle_tenant_latency_seconds", lat[q],
                          labels={**tlab, "quantile": q},
                          help_="per-tenant end-to-end latency "
                                "quantiles (seconds)")
            for g, v in sorted(tsnap.get("gauges", {}).items()):
                L.add(f"paddle_tenant_{g}", v, labels=tlab,
                      help_="per-tenant gauge (e.g. budget_remaining "
                            "tokens)")
    if queue_depth is not None:
        L.add("paddle_serving_queue_depth", queue_depth)

    if fleet is not None:
        from ..serving.fleet import REPLICA_STATE_CODES

        breaker_codes = {"closed": 0, "open": 1, "half-open": 2}
        for rep in fleet.get("replicas", ()):
            # model_version labels every per-replica series so a
            # mid-rollout scrape shows exactly which replicas moved
            labels = {"replica": rep["name"],
                      "model_version": str(rep.get("weight_version", 0))}
            L.add("paddle_serving_replica_model_version",
                  rep.get("weight_version", 0), labels=labels,
                  help_="weight version this replica serves (or is "
                        "rebuilding toward)")
            L.add("paddle_serving_replica_state",
                  REPLICA_STATE_CODES.get(rep["state"], -1),
                  labels={**labels, "state": rep["state"]},
                  help_="replica lifecycle state (0=starting 1=healthy "
                        "2=dead 3=backoff 4=stopped 5=draining)")
            L.add("paddle_serving_replica_restarts", rep["restarts"],
                  mtype="counter", labels=labels,
                  help_="supervised restarts of this replica")
            L.add("paddle_serving_replica_deaths", rep["deaths"],
                  mtype="counter", labels=labels)
            L.add("paddle_serving_replica_heartbeats", rep["heartbeats"],
                  mtype="counter", labels=labels,
                  help_="engine loop iterations (liveness beats)")
            L.add("paddle_serving_replica_load", rep["load"],
                  labels=labels,
                  help_="router-visible in-flight attempts")
            if "uptime_s" in rep:
                L.add("paddle_serving_replica_uptime_seconds",
                      rep["uptime_s"], labels=labels,
                      help_="seconds since this replica's engine built")
            if "beat_age_s" in rep:
                L.add("paddle_serving_replica_beat_age_seconds",
                      rep["beat_age_s"], labels=labels,
                      help_="age of the replica's last liveness beat")
            role = rep.get("role", "any")
            L.add("paddle_serving_replica_role",
                  MESH_ROLE_CODES.get(role, -1),
                  labels={**labels, "role": role,
                          "mesh": rep.get("mesh", "") or "single"},
                  help_="replica disaggregation role "
                        "(0=any 1=prefill 2=decode)")
            br = rep.get("breaker", {})
            L.add("paddle_serving_replica_breaker_state",
                  breaker_codes.get(br.get("state"), -1),
                  labels={**labels, "state": br.get("state", "?")},
                  help_="circuit breaker (0=closed 1=open 2=half-open)")
        if "brownout" in fleet:
            L.add("paddle_serving_brownout_active", fleet["brownout"],
                  help_="fleet brownout (load shedding) engaged")
        if "in_flight" in fleet:
            L.add("paddle_serving_fleet_in_flight", fleet["in_flight"],
                  help_="client requests the Router is tracking")
        aff = fleet.get("affinity")
        if aff:
            L.add("paddle_serving_kvstore_affinity_lookups_total",
                  aff["lookups"], mtype="counter",
                  help_="prefix-affinity routing decisions attempted")
            L.add("paddle_serving_kvstore_affinity_hits_total",
                  aff["hits"], mtype="counter",
                  help_="dispatches steered to the replica holding the "
                        "longest live prefix match")
            L.add("paddle_serving_kvstore_affinity_hit_rate",
                  aff["hit_rate"],
                  help_="fleet-wide sticky-affinity hit fraction")
            for rname, per in sorted(aff.get("per_replica", {}).items()):
                L.add("paddle_serving_kvstore_replica_affinity_hits",
                      per["hits"], mtype="counter",
                      labels={"replica": rname},
                      help_="affinity-steered dispatches per replica")
                L.add(
                    "paddle_serving_kvstore_replica_prefix_hit_rate",
                    per["prefix_hit_rate"], labels={"replica": rname},
                    help_="this replica's own prompt-token prefix-cache "
                          "hit rate")

    return L.text()
