"""Flight recorder: an always-on black box for the training runtime.

A bounded ring of per-step records (step, loss, grad-norm, step wall
ms, HBM in-use, anomaly bit) plus out-of-band notes (fired fault
points, preemption requests, anomaly rollbacks). Recording costs one
deque append — loss/grad-norm stay as device arrays until dump time so
the hot path never forces a host sync.

On a crash the ring is flushed to a JSON "black box" file; `dump()` is
wired into the preemption handler, the anomaly-rollback path, the
fault-injection `crash` action, and SIGTERM, so a post-mortem always
has the last N steps even when the process died mid-run."""

from __future__ import annotations

import collections
import contextlib
import json
import os
import signal
import tempfile
import threading
import time

__all__ = ["FlightRecorder", "flight", "flight_guard", "install_signal_handler"]


def _scalar(v):
    """Best-effort host conversion of a (possibly device-array) value."""
    try:
        return float(v)
    except Exception:
        try:
            return repr(v)
        except Exception:
            # e.g. a donated/deleted jax array: even repr() raises
            return f"<unreadable {type(v).__name__}>"


class FlightRecorder:
    def __init__(self, capacity=None):
        if capacity is None:
            from ..framework import flags
            capacity = flags.flag("FLAGS_flight_recorder_capacity")
        self._lock = threading.Lock()
        self._ring: collections.deque = collections.deque(maxlen=capacity)
        self._notes: collections.deque = collections.deque(maxlen=256)
        self._dumped = []

    def record_step(self, step, **fields):
        """Append one step record. Array-valued fields are kept lazy;
        they are converted to python floats only at dump time."""
        with self._lock:
            self._ring.append({"step": int(step), "t": time.time(),
                               **fields})

    def note(self, kind, **fields):
        """Out-of-band event (fault fired, preemption, rollback)."""
        with self._lock:
            self._notes.append({"kind": kind, "t": time.time(), **fields})

    def last(self):
        with self._lock:
            return dict(self._ring[-1]) if self._ring else None

    def snapshot(self):
        """Materialized (host-side) copy of the ring + notes."""
        with self._lock:
            ring = [dict(r) for r in self._ring]
            notes = [dict(n) for n in self._notes]
        for r in ring:
            for k, v in r.items():
                if not isinstance(v, (int, float, str, bool, type(None))):
                    r[k] = _scalar(v)
        return {"records": ring, "notes": notes}

    def dump(self, reason, path=None):
        """Flush the black box to a JSON file; returns the path."""
        from ..framework import flags, monitor

        snap = self.snapshot()
        snap["reason"] = reason
        snap["time"] = time.time()
        snap["pid"] = os.getpid()
        if path is None:
            d = flags.flag("FLAGS_flight_recorder_dir") or tempfile.gettempdir()
            os.makedirs(d, exist_ok=True)
            path = os.path.join(
                d, f"flight-{os.getpid()}-{int(time.time() * 1000)}.json")
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(snap, f, indent=1, default=repr)
        os.replace(tmp, path)  # atomic: a reader never sees a torn file
        with self._lock:
            self._dumped.append(path)
        monitor.stat_add("flight_dumps")
        return path

    def dumps(self):
        with self._lock:
            return list(self._dumped)

    def reset(self):
        with self._lock:
            self._ring.clear()
            self._notes.clear()
            self._dumped.clear()


#: process-global recorder every runtime component reports into
flight = FlightRecorder.__new__(FlightRecorder)
flight._lock = threading.Lock()
flight._ring = collections.deque(maxlen=256)
flight._notes = collections.deque(maxlen=256)
flight._dumped = []


def configure(capacity=None):
    """Re-size the global ring from flags (keeps existing records)."""
    if capacity is None:
        from ..framework import flags
        capacity = flags.flag("FLAGS_flight_recorder_capacity")
    with flight._lock:
        if flight._ring.maxlen != capacity:
            flight._ring = collections.deque(flight._ring, maxlen=capacity)


@contextlib.contextmanager
def flight_guard(reason="exception"):
    """Dump the black box when the body raises, then re-raise.

    This is the in-process analogue of the `crash` fault action's dump:
    wrap a training loop in it and an injected `raise` fault (or any
    real exception) leaves a post-mortem file behind."""
    try:
        yield flight
    except BaseException as e:
        flight.note("exception", error=repr(e))
        flight.dump(f"{reason}:{type(e).__name__}")
        raise


_handler_installed = False


def install_signal_handler(signum=signal.SIGTERM):
    """Chain a SIGTERM handler that dumps the black box first."""
    global _handler_installed
    if _handler_installed or threading.current_thread() is not threading.main_thread():
        return False
    prev = signal.getsignal(signum)

    def _on_signal(sig, frame):
        try:
            flight.dump(f"signal:{sig}")
        finally:
            if callable(prev):
                prev(sig, frame)
            elif prev == signal.SIG_DFL:
                signal.signal(sig, signal.SIG_DFL)
                signal.raise_signal(sig)

    signal.signal(signum, _on_signal)
    _handler_installed = True
    return True
