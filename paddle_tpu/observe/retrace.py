"""Retrace audit: a global registry of every jit trace the runtime takes.

XLA compiles one program per (function, abstract signature). A shape
that drifts step-to-step silently recompiles every step and the run
crawls; on TPU pods a single stray retrace can cost minutes. The
engines call `record_compile(name, *tracers)` from inside their traced
bodies — trace-time python runs exactly once per compilation, so each
registry entry IS one compile. `annotate(name, ...)` backfills wall
time and `memory_analysis` peak once the lowering is in hand.

`no_retrace()` turns the audit into a tripwire: any compile recorded
inside the context (beyond an allow-list) raises `RetraceError` with
the offending signature, which is how the tier-1 smoke test pins the
steady-state "3 steps, 1 trace" contract. `suppress()` mutes recording
for deliberate re-lowerings (e.g. `Engine.memory_analysis`)."""

from __future__ import annotations

import contextlib
import threading
import time

__all__ = ["RetraceError", "record_compile", "annotate", "compile_events",
           "signature_of", "no_retrace", "suppress", "reset"]


class RetraceError(RuntimeError):
    """An unexpected recompilation happened inside `no_retrace()`."""


_lock = threading.Lock()
_events: list = []          # [{name, signature, time, wall_s?, peak_bytes?}]
_guards: list = []          # stack of active no_retrace allow-lists
_suppressed = 0             # >0: record_compile is a no-op


def signature_of(*args):
    """Abstract (shape, dtype) signature of tracer/array pytree leaves."""
    import jax

    sig = []
    for leaf in jax.tree_util.tree_leaves(args):
        shape = getattr(leaf, "shape", None)
        dtype = getattr(leaf, "dtype", None)
        if shape is None:
            sig.append(repr(leaf))
        else:
            sig.append(f"{getattr(dtype, 'name', dtype)}{list(shape)}")
    return "(" + ", ".join(sig) + ")"


def record_compile(name, *args, signature=None):
    """Log one compilation. Call from inside the traced function body.

    Raises RetraceError when a `no_retrace()` guard is active and
    `name` is not on its allow-list."""
    if signature is None:
        signature = signature_of(*args)
    with _lock:
        if _suppressed:
            return
        ev = {"name": name, "signature": signature, "time": time.time()}
        _events.append(ev)
        if len(_events) > 4096:
            del _events[:-4096]
        guard = _guards[-1] if _guards else None
    if guard is not None and name not in guard:
        raise RetraceError(
            f"unexpected recompilation of {name!r} with signature "
            f"{signature} inside no_retrace() — steady-state step shapes "
            f"changed (pad batches / bucket sequence lengths)")


def annotate(name, wall_s=None, peak_bytes=None):
    """Attach wall time / memory peak to the most recent `name` event."""
    with _lock:
        for ev in reversed(_events):
            if ev["name"] == name:
                if wall_s is not None:
                    ev["wall_s"] = wall_s
                if peak_bytes is not None:
                    ev["peak_bytes"] = int(peak_bytes)
                return


def compile_events(name=None):
    with _lock:
        return [dict(e) for e in _events
                if name is None or e["name"] == name]


@contextlib.contextmanager
def no_retrace(allow=()):
    """Raise RetraceError on any compile recorded inside the context."""
    allow = frozenset(allow)
    with _lock:
        _guards.append(allow)
    try:
        yield
    finally:
        with _lock:
            _guards.pop()


@contextlib.contextmanager
def suppress():
    """Mute the audit for a deliberate re-lowering (no event, no guard
    trip) — e.g. `Engine.memory_analysis` re-lowers the same step."""
    global _suppressed
    with _lock:
        _suppressed += 1
    try:
        yield
    finally:
        with _lock:
            _suppressed -= 1


def reset():
    global _suppressed
    with _lock:
        _events.clear()
        _guards.clear()
        _suppressed = 0
