"""paddle.text — NLP datasets.

Ref parity: python/paddle/text/datasets/ (Imdb, UCIHousing, Conll05,
Movielens, WMT14/16). Zero-egress environment: each dataset reads the
standard on-disk format under `~/.cache/paddle_tpu/<name>/` when present
and otherwise falls back to a deterministic synthetic corpus with the
right shapes/vocab/classes (same policy as paddle_tpu.vision.datasets).
"""

from __future__ import annotations

import os
import re
import tarfile

import numpy as np

from ..io import Dataset

__all__ = ["Imdb", "UCIHousing", "Conll05st", "Movielens", "WMT14"]

_CACHE = os.path.expanduser("~/.cache/paddle_tpu")


def _synthetic_sequences(n, vocab_size, max_len, num_classes, seed):
    """Token sequences with a learnable signal: class-c samples over-use
    tokens from the c-th vocab slice."""
    rng = np.random.RandomState(seed)
    labels = rng.randint(0, num_classes, n).astype(np.int64)
    band = vocab_size // num_classes
    seqs = []
    for lbl in labels:
        length = rng.randint(max_len // 2, max_len + 1)
        base = rng.randint(1, vocab_size, length)
        biased = rng.rand(length) < 0.35
        base[biased] = rng.randint(lbl * band, (lbl + 1) * band,
                                   biased.sum()).clip(1, vocab_size - 1)
        padded = np.zeros(max_len, np.int64)
        padded[:length] = base
        seqs.append(padded)
    return np.stack(seqs), labels


class Imdb(Dataset):
    """IMDB sentiment (ref python/paddle/text/datasets/imdb.py). Samples
    are (token_ids [max_len], label) with 0 = padding."""

    def __init__(self, data_file=None, mode="train", cutoff=150,
                 max_len=256, vocab_size=5000):
        self.mode = mode
        self.max_len = max_len
        data_file = data_file or os.path.join(_CACHE, "imdb",
                                              "aclImdb_v1.tar.gz")
        if os.path.exists(data_file):
            self.docs, self.labels, self.word_idx = self._load_tar(
                data_file, mode, cutoff, max_len)
        else:
            n = 2048 if mode == "train" else 512
            self.docs, self.labels = _synthetic_sequences(
                n, vocab_size, max_len, 2,
                seed=101 if mode == "train" else 102)
            self.word_idx = {i: i for i in range(vocab_size)}

    def _load_tar(self, path, mode, cutoff, max_len):
        tokenize = re.compile(r"[a-z]+").findall
        # vocabulary always comes from the TRAIN split (ref imdb.py
        # build_dict) so train/test share token ids
        freq: dict = {}
        train_pat = re.compile(r"aclImdb/train/(pos|neg)/.*\.txt$")
        pattern = re.compile(rf"aclImdb/{mode}/(pos|neg)/.*\.txt$")
        docs_raw, labels = [], []
        with tarfile.open(path) as tf:
            for member in tf.getmembers():
                in_vocab = train_pat.match(member.name)
                m = pattern.match(member.name)
                if not (in_vocab or m):
                    continue
                text = tf.extractfile(member).read().decode(
                    "latin-1").lower()
                toks = tokenize(text)
                if in_vocab:
                    for t in toks:
                        freq[t] = freq.get(t, 0) + 1
                if m:
                    docs_raw.append(toks)
                    labels.append(0 if m.group(1) == "pos" else 1)
        vocab = [w for w, c in sorted(freq.items(),
                                      key=lambda kv: (-kv[1], kv[0]))
                 if c > cutoff]
        word_idx = {w: i + 1 for i, w in enumerate(vocab)}
        docs = np.zeros((len(docs_raw), max_len), np.int64)
        for i, toks in enumerate(docs_raw):
            ids = [word_idx[t] for t in toks if t in word_idx][:max_len]
            docs[i, :len(ids)] = ids
        return docs, np.asarray(labels, np.int64), word_idx

    def __getitem__(self, idx):
        return self.docs[idx], self.labels[idx]

    def __len__(self):
        return len(self.labels)


class UCIHousing(Dataset):
    """Boston housing regression
    (ref python/paddle/text/datasets/uci_housing.py): 13 features ->
    price."""

    FEATURES = 13

    def __init__(self, data_file=None, mode="train"):
        data_file = data_file or os.path.join(_CACHE, "uci_housing",
                                              "housing.data")
        if os.path.exists(data_file):
            raw = np.loadtxt(data_file).astype(np.float32)
        else:
            rng = np.random.RandomState(7)
            x = rng.rand(506, self.FEATURES).astype(np.float32)
            w = rng.randn(self.FEATURES).astype(np.float32)
            y = (x @ w + 0.1 * rng.randn(506)).astype(np.float32)
            raw = np.concatenate([x, y[:, None]], axis=1)
        x, y = raw[:, :-1], raw[:, -1:]
        x = (x - x.mean(0)) / (x.std(0) + 1e-8)
        split = int(0.8 * len(x))
        if mode == "train":
            self.x, self.y = x[:split], y[:split]
        else:
            self.x, self.y = x[split:], y[split:]

    def __getitem__(self, idx):
        return self.x[idx], self.y[idx]

    def __len__(self):
        return len(self.x)


class Conll05st(Dataset):
    """SRL dataset surface (ref text/datasets/conll05.py); synthetic
    tagged sequences when the corpus is absent."""

    NUM_TAGS = 67

    def __init__(self, data_file=None, mode="train", max_len=64,
                 vocab_size=8000):
        n = 1024 if mode == "train" else 256
        seqs, _ = _synthetic_sequences(n, vocab_size, max_len, 4,
                                       seed=201)
        rng = np.random.RandomState(202)
        self.words = seqs
        self.tags = rng.randint(0, self.NUM_TAGS,
                                seqs.shape).astype(np.int64)
        self.tags[seqs == 0] = 0

    def __getitem__(self, idx):
        return self.words[idx], self.tags[idx]

    def __len__(self):
        return len(self.words)


class Movielens(Dataset):
    """Rating prediction surface (ref text/datasets/movielens.py):
    (user_id, movie_id, rating)."""

    def __init__(self, data_file=None, mode="train", num_users=944,
                 num_movies=1683):
        rng = np.random.RandomState(301 if mode == "train" else 302)
        n = 4096 if mode == "train" else 1024
        self.users = rng.randint(1, num_users, n).astype(np.int64)
        self.movies = rng.randint(1, num_movies, n).astype(np.int64)
        base = (self.users % 5 + self.movies % 5) / 2.0
        self.ratings = np.clip(
            base + rng.rand(n) * 2, 1, 5).astype(np.float32)

    def __getitem__(self, idx):
        return self.users[idx], self.movies[idx], self.ratings[idx]

    def __len__(self):
        return len(self.users)


class WMT14(Dataset):
    """Translation pair surface (ref text/datasets/wmt14.py):
    (src_ids, trg_ids, trg_next_ids) padded."""

    def __init__(self, data_file=None, mode="train", dict_size=3000,
                 max_len=32):
        n = 1024 if mode == "train" else 256
        src, _ = _synthetic_sequences(n, dict_size, max_len, 4, seed=401)
        trg, _ = _synthetic_sequences(n, dict_size, max_len, 4, seed=402)
        self.src = src
        self.trg = trg
        nxt = np.zeros_like(trg)
        nxt[:, :-1] = trg[:, 1:]
        self.trg_next = nxt

    def __getitem__(self, idx):
        return self.src[idx], self.trg[idx], self.trg_next[idx]

    def __len__(self):
        return len(self.src)
