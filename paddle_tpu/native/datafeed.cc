// Native datafeed: the GIL-free hot path of batch assembly.
//
// Ref parity: paddle/fluid/framework/data_feed.cc (MultiSlotDataFeed's
// C++ batch assembly) — the reference keeps ingestion out of Python for
// throughput; here the same role is a small C library driven through
// ctypes. The hot loops are batch gather (fancy-index + stack fused into
// one parallel copy) and image decode normalisation (u8 HWC -> f32 CHW),
// partitioned across POSIX threads.
//
// Built on demand by paddle_tpu/native/__init__.py:
//   g++ -O3 -march=native -shared -fPIC -pthread datafeed.cc -o libptfeed.so

#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

namespace {

// Run fn(i) for i in [0, n) over up to nthreads threads. bytes_per_item
// gates threading: std::thread spawn costs ~50us, so small copies run
// inline (numpy-comparable) and threads only amortise on multi-MB work.
template <typename F>
void parallel_for(int64_t n, int nthreads, int64_t bytes_per_item, F fn) {
  constexpr int64_t kMinBytesPerThread = 1 << 21;  // 2 MiB
  if (bytes_per_item > 0) {
    int64_t by_size =
        static_cast<int64_t>(n * bytes_per_item / kMinBytesPerThread);
    if (by_size < nthreads) nthreads = static_cast<int>(by_size);
  }
  if (nthreads <= 1 || n < 2) {
    for (int64_t i = 0; i < n; ++i) fn(i);
    return;
  }
  int workers = static_cast<int>(nthreads < n ? nthreads : n);
  std::vector<std::thread> pool;
  pool.reserve(workers);
  int64_t chunk = (n + workers - 1) / workers;
  for (int t = 0; t < workers; ++t) {
    int64_t lo = t * chunk;
    int64_t hi = lo + chunk < n ? lo + chunk : n;
    if (lo >= hi) break;
    pool.emplace_back([lo, hi, &fn]() {
      for (int64_t i = lo; i < hi; ++i) fn(i);
    });
  }
  for (auto& th : pool) th.join();
}

template <typename T>
void gather_rows(const T* src, int64_t row_elems, const int64_t* idx,
                 int64_t n, T* out, int nthreads) {
  parallel_for(n, nthreads,
               static_cast<int64_t>(sizeof(T)) * row_elems, [=](int64_t i) {
    std::memcpy(out + i * row_elems, src + idx[i] * row_elems,
                sizeof(T) * static_cast<size_t>(row_elems));
  });
}

}  // namespace

extern "C" {

// Gather n rows of row_elems elements each: out[i] = src[idx[i]].
void pt_gather_rows_f32(const float* src, int64_t row_elems,
                        const int64_t* idx, int64_t n, float* out,
                        int nthreads) {
  gather_rows(src, row_elems, idx, n, out, nthreads);
}

void pt_gather_rows_u8(const uint8_t* src, int64_t row_elems,
                       const int64_t* idx, int64_t n, uint8_t* out,
                       int nthreads) {
  gather_rows(src, row_elems, idx, n, out, nthreads);
}

void pt_gather_rows_i64(const int64_t* src, int64_t row_elems,
                        const int64_t* idx, int64_t n, int64_t* out,
                        int nthreads) {
  gather_rows(src, row_elems, idx, n, out, nthreads);
}

void pt_gather_rows_i32(const int32_t* src, int64_t row_elems,
                        const int64_t* idx, int64_t n, int32_t* out,
                        int nthreads) {
  gather_rows(src, row_elems, idx, n, out, nthreads);
}

// Image batch decode: gather u8 HWC rows by index, layout to f32 CHW with
// out = (x * scale + shift) — the vision-pipeline ToTensor+Normalize hot
// loop fused into one pass.
void pt_gather_u8hwc_to_f32chw(const uint8_t* src, const int64_t* idx,
                               int64_t n, int64_t h, int64_t w, int64_t c,
                               float scale, float shift, float* out,
                               int nthreads) {
  const int64_t hw = h * w;
  const int64_t img = hw * c;
  parallel_for(n, nthreads, img * 5, [=](int64_t i) {
    const uint8_t* s = src + idx[i] * img;
    float* o = out + i * img;
    for (int64_t p = 0; p < hw; ++p) {
      for (int64_t ch = 0; ch < c; ++ch) {
        o[ch * hw + p] = static_cast<float>(s[p * c + ch]) * scale + shift;
      }
    }
  });
}

}  // extern "C"
