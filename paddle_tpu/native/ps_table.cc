// Native sparse parameter table for the parameter-server runtime.
//
// Ref parity: paddle/fluid/distributed/table/common_sparse_table.cc — the
// reference stores sparse embedding shards in a C++ hash table with
// server-side optimizer application. This is the TPU build's equivalent:
// an int64 -> row open-hash (std::unordered_map index + contiguous row
// arena), lazy deterministic row init (splitmix64 per id), and fused
// pull / push(+SGD/Adagrad) kernels. Thread-safe: one mutex per table
// (the PS server is a thread pool; row-granular locking is a later
// optimisation, contention is dominated by network time).
//
// Built with g++ via paddle_tpu.native (ctypes ABI, no pybind11).

#include <cmath>
#include <cstdint>
#include <cstring>
#include <mutex>
#include <unordered_map>
#include <vector>

namespace {

struct Table {
  int64_t dim;
  float init_lo, init_hi;
  uint64_t seed;
  bool has_accum = false;  // adagrad accumulators allocated on first use
  std::unordered_map<int64_t, int64_t> index;  // id -> slot
  std::vector<float> rows;    // slot * dim
  std::vector<float> accum;   // slot * dim (adagrad G)
  std::mutex mu;

  static uint64_t splitmix(uint64_t x) {
    x += 0x9e3779b97f4a7c15ull;
    x = (x ^ (x >> 30)) * 0xbf58476d1ce4e5b9ull;
    x = (x ^ (x >> 27)) * 0x94d049bb133111ebull;
    return x ^ (x >> 31);
  }

  int64_t slot_of(int64_t id) {
    auto it = index.find(id);
    if (it != index.end()) return it->second;
    int64_t slot = static_cast<int64_t>(index.size());
    index.emplace(id, slot);
    rows.resize((slot + 1) * dim);
    if (has_accum) accum.resize((slot + 1) * dim, 0.f);
    float* r = rows.data() + slot * dim;
    if (init_lo == 0.f && init_hi == 0.f) {
      std::memset(r, 0, sizeof(float) * dim);
    } else {
      uint64_t s = splitmix(seed ^ static_cast<uint64_t>(id));
      const float span = init_hi - init_lo;
      for (int64_t j = 0; j < dim; ++j) {
        s = splitmix(s);
        r[j] = init_lo + span * ((s >> 11) * 0x1.0p-53f);
      }
    }
    return slot;
  }

  void ensure_accum() {
    if (!has_accum) {
      accum.assign(rows.size(), 0.f);
      has_accum = true;
    }
  }
};

}  // namespace

extern "C" {

void* pst_create(int64_t dim, float init_lo, float init_hi, uint64_t seed) {
  auto* t = new Table();
  t->dim = dim;
  t->init_lo = init_lo;
  t->init_hi = init_hi;
  t->seed = seed;
  return t;
}

void pst_free(void* h) { delete static_cast<Table*>(h); }

int64_t pst_size(void* h) {
  auto* t = static_cast<Table*>(h);
  std::lock_guard<std::mutex> g(t->mu);
  return static_cast<int64_t>(t->index.size());
}

void pst_pull(void* h, const int64_t* ids, int64_t n, float* out) {
  auto* t = static_cast<Table*>(h);
  std::lock_guard<std::mutex> g(t->mu);
  for (int64_t i = 0; i < n; ++i) {
    int64_t slot = t->slot_of(ids[i]);
    std::memcpy(out + i * t->dim, t->rows.data() + slot * t->dim,
                sizeof(float) * t->dim);
  }
}

void pst_push_sgd(void* h, const int64_t* ids, int64_t n, const float* grads,
                  float lr) {
  auto* t = static_cast<Table*>(h);
  std::lock_guard<std::mutex> g(t->mu);
  for (int64_t i = 0; i < n; ++i) {
    int64_t slot = t->slot_of(ids[i]);
    float* r = t->rows.data() + slot * t->dim;
    const float* gr = grads + i * t->dim;
    for (int64_t j = 0; j < t->dim; ++j) r[j] -= lr * gr[j];
  }
}

void pst_push_adagrad(void* h, const int64_t* ids, int64_t n,
                      const float* grads, float lr, float eps) {
  auto* t = static_cast<Table*>(h);
  std::lock_guard<std::mutex> g(t->mu);
  t->ensure_accum();
  for (int64_t i = 0; i < n; ++i) {
    int64_t slot = t->slot_of(ids[i]);
    float* r = t->rows.data() + slot * t->dim;
    float* a = t->accum.data() + slot * t->dim;
    const float* gr = grads + i * t->dim;
    for (int64_t j = 0; j < t->dim; ++j) {
      a[j] += gr[j] * gr[j];
      r[j] -= lr * gr[j] / (std::sqrt(a[j]) + eps);
    }
  }
}

// delta-add (GeoSGD merge): row += delta
void pst_push_delta(void* h, const int64_t* ids, int64_t n,
                    const float* deltas) {
  auto* t = static_cast<Table*>(h);
  std::lock_guard<std::mutex> g(t->mu);
  for (int64_t i = 0; i < n; ++i) {
    int64_t slot = t->slot_of(ids[i]);
    float* r = t->rows.data() + slot * t->dim;
    const float* d = deltas + i * t->dim;
    for (int64_t j = 0; j < t->dim; ++j) r[j] += d[j];
  }
}

void pst_export(void* h, int64_t* ids_out, float* rows_out) {
  auto* t = static_cast<Table*>(h);
  std::lock_guard<std::mutex> g(t->mu);
  int64_t i = 0;
  for (const auto& kv : t->index) {
    ids_out[i] = kv.first;
    std::memcpy(rows_out + i * t->dim, t->rows.data() + kv.second * t->dim,
                sizeof(float) * t->dim);
    ++i;
  }
}

void pst_import(void* h, const int64_t* ids, int64_t n, const float* rows) {
  auto* t = static_cast<Table*>(h);
  std::lock_guard<std::mutex> g(t->mu);
  for (int64_t i = 0; i < n; ++i) {
    int64_t slot = t->slot_of(ids[i]);
    std::memcpy(t->rows.data() + slot * t->dim, rows + i * t->dim,
                sizeof(float) * t->dim);
  }
}

}  // extern "C"
