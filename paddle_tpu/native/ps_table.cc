// Native sparse parameter table for the parameter-server runtime.
//
// Ref parity: paddle/fluid/distributed/table/common_sparse_table.cc — the
// reference stores sparse embedding shards in a C++ hash table with
// server-side optimizer application. This is the TPU build's equivalent:
// an int64 -> row open-hash (std::unordered_map index + contiguous row
// arena), lazy deterministic row init (splitmix64 per id), and fused
// pull / push(+SGD/Adagrad) kernels. Thread-safe: one mutex per table
// (the PS server is a thread pool; row-granular locking is a later
// optimisation, contention is dominated by network time).
//
// Built with g++ via paddle_tpu.native (ctypes ABI, no pybind11).

#include <cmath>
#include <cstdint>
#include <cstring>
#include <mutex>
#include <unordered_map>
#include <vector>

namespace {

// shared by BOTH tables: initial row values must stay bit-identical
// between the in-RAM and SSD variants (the conformance tests diff them)
inline uint64_t pst_splitmix(uint64_t x) {
  x += 0x9e3779b97f4a7c15ull;
  x = (x ^ (x >> 30)) * 0xbf58476d1ce4e5b9ull;
  x = (x ^ (x >> 27)) * 0x94d049bb133111ebull;
  return x ^ (x >> 31);
}

inline void pst_init_row(float* r, int64_t dim, float lo, float hi,
                         uint64_t seed, int64_t id) {
  if (lo == 0.f && hi == 0.f) {
    std::memset(r, 0, sizeof(float) * dim);
    return;
  }
  uint64_t s = pst_splitmix(seed ^ static_cast<uint64_t>(id));
  const float span = hi - lo;
  for (int64_t j = 0; j < dim; ++j) {
    s = pst_splitmix(s);
    r[j] = lo + span * ((s >> 11) * 0x1.0p-53f);
  }
}

struct Table {
  int64_t dim;
  float init_lo, init_hi;
  uint64_t seed;
  bool has_accum = false;  // adagrad accumulators allocated on first use
  std::unordered_map<int64_t, int64_t> index;  // id -> slot
  std::vector<float> rows;    // slot * dim
  std::vector<float> accum;   // slot * dim (adagrad G)
  std::mutex mu;

  int64_t slot_of(int64_t id) {
    auto it = index.find(id);
    if (it != index.end()) return it->second;
    int64_t slot = static_cast<int64_t>(index.size());
    index.emplace(id, slot);
    rows.resize((slot + 1) * dim);
    if (has_accum) accum.resize((slot + 1) * dim, 0.f);
    pst_init_row(rows.data() + slot * dim, dim, init_lo, init_hi, seed,
                 id);
    return slot;
  }

  void ensure_accum() {
    if (!has_accum) {
      accum.assign(rows.size(), 0.f);
      has_accum = true;
    }
  }
};

}  // namespace

extern "C" {

void* pst_create(int64_t dim, float init_lo, float init_hi, uint64_t seed) {
  auto* t = new Table();
  t->dim = dim;
  t->init_lo = init_lo;
  t->init_hi = init_hi;
  t->seed = seed;
  return t;
}

void pst_free(void* h) { delete static_cast<Table*>(h); }

int64_t pst_size(void* h) {
  auto* t = static_cast<Table*>(h);
  std::lock_guard<std::mutex> g(t->mu);
  return static_cast<int64_t>(t->index.size());
}

void pst_pull(void* h, const int64_t* ids, int64_t n, float* out) {
  auto* t = static_cast<Table*>(h);
  std::lock_guard<std::mutex> g(t->mu);
  for (int64_t i = 0; i < n; ++i) {
    int64_t slot = t->slot_of(ids[i]);
    std::memcpy(out + i * t->dim, t->rows.data() + slot * t->dim,
                sizeof(float) * t->dim);
  }
}

void pst_push_sgd(void* h, const int64_t* ids, int64_t n, const float* grads,
                  float lr) {
  auto* t = static_cast<Table*>(h);
  std::lock_guard<std::mutex> g(t->mu);
  for (int64_t i = 0; i < n; ++i) {
    int64_t slot = t->slot_of(ids[i]);
    float* r = t->rows.data() + slot * t->dim;
    const float* gr = grads + i * t->dim;
    for (int64_t j = 0; j < t->dim; ++j) r[j] -= lr * gr[j];
  }
}

void pst_push_adagrad(void* h, const int64_t* ids, int64_t n,
                      const float* grads, float lr, float eps) {
  auto* t = static_cast<Table*>(h);
  std::lock_guard<std::mutex> g(t->mu);
  t->ensure_accum();
  for (int64_t i = 0; i < n; ++i) {
    int64_t slot = t->slot_of(ids[i]);
    float* r = t->rows.data() + slot * t->dim;
    float* a = t->accum.data() + slot * t->dim;
    const float* gr = grads + i * t->dim;
    for (int64_t j = 0; j < t->dim; ++j) {
      a[j] += gr[j] * gr[j];
      r[j] -= lr * gr[j] / (std::sqrt(a[j]) + eps);
    }
  }
}

// delta-add (GeoSGD merge): row += delta
void pst_push_delta(void* h, const int64_t* ids, int64_t n,
                    const float* deltas) {
  auto* t = static_cast<Table*>(h);
  std::lock_guard<std::mutex> g(t->mu);
  for (int64_t i = 0; i < n; ++i) {
    int64_t slot = t->slot_of(ids[i]);
    float* r = t->rows.data() + slot * t->dim;
    const float* d = deltas + i * t->dim;
    for (int64_t j = 0; j < t->dim; ++j) r[j] += d[j];
  }
}

void pst_export(void* h, int64_t* ids_out, float* rows_out) {
  auto* t = static_cast<Table*>(h);
  std::lock_guard<std::mutex> g(t->mu);
  int64_t i = 0;
  for (const auto& kv : t->index) {
    ids_out[i] = kv.first;
    std::memcpy(rows_out + i * t->dim, t->rows.data() + kv.second * t->dim,
                sizeof(float) * t->dim);
    ++i;
  }
}

void pst_import(void* h, const int64_t* ids, int64_t n, const float* rows) {
  auto* t = static_cast<Table*>(h);
  std::lock_guard<std::mutex> g(t->mu);
  for (int64_t i = 0; i < n; ++i) {
    int64_t slot = t->slot_of(ids[i]);
    std::memcpy(t->rows.data() + slot * t->dim, rows + i * t->dim,
                sizeof(float) * t->dim);
  }
}

}  // extern "C"

// ---------------------------------------------------------------------------
// SSD spill table (ref paddle/fluid/distributed/table/ssd_sparse_table.h:
// in-memory shard paired with an on-disk store).  Hot rows live in a
// bounded LRU arena; eviction appends a fixed-size record
// [int64 id][f32 payload] to the spill file with an id -> offset index
// pointing at the newest record; re-touching a spilled id reads it back
// hot.  Dead records beyond the live count trigger in-place compaction.
// The fixed-record append-only file + hash index IS the LSM level this
// workload needs (point lookups by id, full scan at save) — no rocksdb
// in the image.
// ---------------------------------------------------------------------------

#include <fcntl.h>
#include <unistd.h>

#include <cstdio>
#include <string>

namespace {

struct SsdTable {
  int64_t dim;            // embedding dim
  int64_t rec_dim;        // payload floats (dim, or 2*dim with adagrad)
  int64_t mem_rows;       // LRU capacity
  float init_lo, init_hi;
  uint64_t seed;
  bool has_accum;

  // resident arena + intrusive LRU list over slots
  std::unordered_map<int64_t, int32_t> resident;  // id -> slot
  std::vector<float> arena;                        // slot * rec_dim
  std::vector<int64_t> slot_id;
  std::vector<int32_t> lru_prev, lru_next;
  std::vector<int32_t> free_slots;
  int32_t lru_head = -1, lru_tail = -1;  // head = MRU

  // spill file
  std::unordered_map<int64_t, int64_t> offsets;  // id -> file offset
  int fd = -1;
  int64_t tail_off = 0;
  int64_t dead = 0;
  std::string path;
  std::vector<char> recbuf;
  std::mutex mu;

  int64_t rec_bytes() const { return 8 + 4 * rec_dim; }

  void lru_unlink(int32_t s) {
    int32_t p = lru_prev[s], n = lru_next[s];
    if (p >= 0) lru_next[p] = n; else lru_head = n;
    if (n >= 0) lru_prev[n] = p; else lru_tail = p;
  }

  void lru_push_front(int32_t s) {
    lru_prev[s] = -1;
    lru_next[s] = lru_head;
    if (lru_head >= 0) lru_prev[lru_head] = s;
    lru_head = s;
    if (lru_tail < 0) lru_tail = s;
  }

  void touch(int32_t s) {
    if (lru_head == s) return;
    lru_unlink(s);
    lru_push_front(s);
  }

  int32_t alloc_slot(int64_t id) {
    int32_t s;
    if (!free_slots.empty()) {
      s = free_slots.back();
      free_slots.pop_back();
    } else {
      s = static_cast<int32_t>(slot_id.size());
      slot_id.push_back(0);
      lru_prev.push_back(-1);
      lru_next.push_back(-1);
      arena.resize((s + 1) * rec_dim);
    }
    slot_id[s] = id;
    lru_push_front(s);
    resident.emplace(id, s);
    return s;
  }

  void init_row(float* r, int64_t id) {
    pst_init_row(r, dim, init_lo, init_hi, seed, id);
    if (rec_dim > dim)
      std::memset(r + dim, 0, sizeof(float) * (rec_dim - dim));
  }

  // resident payload for id, faulting from disk / initialising fresh
  float* payload_of(int64_t id) {
    auto it = resident.find(id);
    if (it != resident.end()) {
      touch(it->second);
      return arena.data() + static_cast<int64_t>(it->second) * rec_dim;
    }
    int32_t s = alloc_slot(id);
    float* r = arena.data() + static_cast<int64_t>(s) * rec_dim;
    auto sp = offsets.find(id);
    if (sp != offsets.end()) {
      if (pread(fd, recbuf.data(), rec_bytes(), sp->second) ==
          (ssize_t)rec_bytes()) {
        std::memcpy(r, recbuf.data() + 8, sizeof(float) * rec_dim);
      } else {
        init_row(r, id);  // unreadable record: deterministic re-init
      }
      offsets.erase(sp);
      ++dead;
    } else {
      init_row(r, id);
    }
    return r;
  }

  void evict() {
    while (static_cast<int64_t>(resident.size()) > mem_rows &&
           lru_tail >= 0) {
      int32_t s = lru_tail;
      int64_t id = slot_id[s];
      std::memcpy(recbuf.data(), &id, 8);
      std::memcpy(recbuf.data() + 8,
                  arena.data() + static_cast<int64_t>(s) * rec_dim,
                  sizeof(float) * rec_dim);
      if (pwrite(fd, recbuf.data(), rec_bytes(), tail_off) !=
          (ssize_t)rec_bytes()) {
        // short write (ENOSPC etc.): keep the row RESIDENT rather than
        // record a corrupt offset — the table degrades to over-capacity
        // memory use instead of silently losing trained state
        break;
      }
      if (offsets.count(id)) ++dead;
      offsets[id] = tail_off;
      tail_off += rec_bytes();
      lru_unlink(s);
      resident.erase(id);
      free_slots.push_back(s);
    }
    if (dead > 64 && dead > static_cast<int64_t>(offsets.size()))
      compact();
  }

  void compact() {
    std::string tmp = path + ".compact";
    int nfd = ::open(tmp.c_str(), O_RDWR | O_CREAT | O_TRUNC, 0600);
    if (nfd < 0) return;
    int64_t off = 0;
    std::unordered_map<int64_t, int64_t> fresh;
    fresh.reserve(offsets.size());
    for (const auto& kv : offsets) {
      if (pread(fd, recbuf.data(), rec_bytes(), kv.second) !=
          (ssize_t)rec_bytes())
        continue;
      if (pwrite(nfd, recbuf.data(), rec_bytes(), off) !=
          (ssize_t)rec_bytes()) {
        // can't complete the compacted copy: keep the old file intact
        ::close(nfd);
        ::unlink(tmp.c_str());
        return;
      }
      fresh[kv.first] = off;
      off += rec_bytes();
    }
    ::close(fd);
    ::rename(tmp.c_str(), path.c_str());
    fd = nfd;
    tail_off = off;
    offsets.swap(fresh);
    dead = 0;
  }
};

}  // namespace

extern "C" {

void* pst_ssd_create(int64_t dim, float init_lo, float init_hi,
                     uint64_t seed, int64_t mem_rows,
                     const char* spill_path, int has_accum) {
  auto* t = new SsdTable();
  t->dim = dim;
  t->has_accum = has_accum != 0;
  t->rec_dim = dim * (t->has_accum ? 2 : 1);
  t->mem_rows = mem_rows > 0 ? mem_rows : 1;
  t->init_lo = init_lo;
  t->init_hi = init_hi;
  t->seed = seed;
  t->path = spill_path;
  t->fd = ::open(spill_path, O_RDWR | O_CREAT | O_TRUNC, 0600);
  if (t->fd < 0) {
    delete t;
    return nullptr;
  }
  t->recbuf.resize(t->rec_bytes());
  return t;
}

void pst_ssd_free(void* h) {
  auto* t = static_cast<SsdTable*>(h);
  if (t->fd >= 0) ::close(t->fd);
  delete t;
}

int64_t pst_ssd_size(void* h) {
  auto* t = static_cast<SsdTable*>(h);
  std::lock_guard<std::mutex> g(t->mu);
  return static_cast<int64_t>(t->resident.size() + t->offsets.size());
}

int64_t pst_ssd_resident(void* h) {
  auto* t = static_cast<SsdTable*>(h);
  std::lock_guard<std::mutex> g(t->mu);
  return static_cast<int64_t>(t->resident.size());
}

int64_t pst_ssd_spilled(void* h) {
  auto* t = static_cast<SsdTable*>(h);
  std::lock_guard<std::mutex> g(t->mu);
  return static_cast<int64_t>(t->offsets.size());
}

void pst_ssd_pull(void* h, const int64_t* ids, int64_t n, float* out) {
  auto* t = static_cast<SsdTable*>(h);
  std::lock_guard<std::mutex> g(t->mu);
  for (int64_t i = 0; i < n; ++i) {
    std::memcpy(out + i * t->dim, t->payload_of(ids[i]),
                sizeof(float) * t->dim);
  }
  t->evict();
}

void pst_ssd_push_sgd(void* h, const int64_t* ids, int64_t n,
                      const float* grads, float lr) {
  auto* t = static_cast<SsdTable*>(h);
  std::lock_guard<std::mutex> g(t->mu);
  for (int64_t i = 0; i < n; ++i) {
    float* r = t->payload_of(ids[i]);
    const float* gr = grads + i * t->dim;
    for (int64_t j = 0; j < t->dim; ++j) r[j] -= lr * gr[j];
  }
  t->evict();
}

void pst_ssd_push_adagrad(void* h, const int64_t* ids, int64_t n,
                          const float* grads, float lr, float eps) {
  auto* t = static_cast<SsdTable*>(h);
  std::lock_guard<std::mutex> g(t->mu);
  for (int64_t i = 0; i < n; ++i) {
    float* r = t->payload_of(ids[i]);
    float* a = r + t->dim;  // accumulator rides the payload
    const float* gr = grads + i * t->dim;
    for (int64_t j = 0; j < t->dim; ++j) {
      a[j] += gr[j] * gr[j];
      r[j] -= lr * gr[j] / (std::sqrt(a[j]) + eps);
    }
  }
  t->evict();
}

void pst_ssd_push_delta(void* h, const int64_t* ids, int64_t n,
                        const float* deltas) {
  auto* t = static_cast<SsdTable*>(h);
  std::lock_guard<std::mutex> g(t->mu);
  for (int64_t i = 0; i < n; ++i) {
    float* r = t->payload_of(ids[i]);
    const float* d = deltas + i * t->dim;
    for (int64_t j = 0; j < t->dim; ++j) r[j] += d[j];
  }
  t->evict();
}

// export ids (sorted not required; caller sorts) then rows: two-call
// protocol so the caller can size buffers from pst_ssd_size first.
// Returns the number of entries actually filled (unreadable spill
// records are skipped, never exported as garbage).
int64_t pst_ssd_export(void* h, int64_t* ids_out, float* rows_out) {
  auto* t = static_cast<SsdTable*>(h);
  std::lock_guard<std::mutex> g(t->mu);
  int64_t i = 0;
  for (const auto& kv : t->resident) {
    ids_out[i] = kv.first;
    std::memcpy(rows_out + i * t->dim,
                t->arena.data() +
                    static_cast<int64_t>(kv.second) * t->rec_dim,
                sizeof(float) * t->dim);
    ++i;
  }
  for (const auto& kv : t->offsets) {
    if (pread(t->fd, t->recbuf.data(), t->rec_bytes(), kv.second) !=
        (ssize_t)t->rec_bytes())
      continue;
    ids_out[i] = kv.first;
    std::memcpy(rows_out + i * t->dim, t->recbuf.data() + 8,
                sizeof(float) * t->dim);
    ++i;
  }
  return i;
}

void pst_ssd_import(void* h, const int64_t* ids, int64_t n,
                    const float* rows) {
  auto* t = static_cast<SsdTable*>(h);
  std::lock_guard<std::mutex> g(t->mu);
  for (int64_t i = 0; i < n; ++i) {
    // payload_of zero-inits the accumulator for fresh ids and keeps it
    // for existing ones — matching the python table's load semantics
    std::memcpy(t->payload_of(ids[i]), rows + i * t->dim,
                sizeof(float) * t->dim);
  }
  t->evict();
}

}  // extern "C"
