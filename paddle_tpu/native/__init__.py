"""paddle_tpu.native — C++ runtime components (ctypes-loaded).

Ref parity: the reference keeps its data ingestion in C++
(paddle/fluid/framework/data_feed.cc); this package holds the TPU build's
native pieces. The library is compiled on demand with the system g++ into
a per-version cache and loaded via ctypes (no pybind11 dependency).

Public surface:
  available()                     -> bool (toolchain + build ok)
  gather_rows(src, indices)       -> np.ndarray, == src[indices] but
                                     GIL-free and multi-threaded
  gather_images_u8_chw(src, idx, scale, shift)
                                  -> f32 NCHW batch from u8 NHWC storage
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "datafeed.cc")

_lock = threading.Lock()
_lib = None
_build_error: str | None = None


def _cache_dir():
    root = os.environ.get("PADDLE_TPU_CACHE",
                          os.path.join(os.path.expanduser("~"), ".cache",
                                       "paddle_tpu"))
    os.makedirs(root, exist_ok=True)
    return root


def _compile(src, prefix, extra_flags=()):
    """Hash-keyed g++ build shared by every native component.

    -march=native binaries are host-specific: the cache key includes the
    machine/processor/compiler so a shared cache dir never serves a
    binary with illegal instructions to a different CPU generation."""
    import platform

    h = hashlib.sha256()
    with open(src, "rb") as f:
        h.update(f.read())
    h.update(platform.machine().encode())
    h.update(platform.processor().encode())
    try:
        h.update(subprocess.run(["g++", "--version"], capture_output=True,
                                text=True).stdout.encode())
    except OSError:
        pass
    digest = h.hexdigest()[:16]
    so = os.path.join(_cache_dir(), f"{prefix}-{digest}.so")
    if not os.path.exists(so):
        tmp = so + f".tmp{os.getpid()}"
        cmd = ["g++", "-O3", "-march=native", *extra_flags, "-shared",
               "-fPIC", "-pthread", "-std=c++17", src, "-o", tmp]
        subprocess.run(cmd, check=True, capture_output=True, text=True)
        os.replace(tmp, so)
    return ctypes.CDLL(so)


def _build():
    lib = _compile(_SRC, "libptfeed", ("-funroll-loops",))
    i64p = ctypes.POINTER(ctypes.c_int64)
    for name, ptr_t in [
        ("pt_gather_rows_f32", ctypes.POINTER(ctypes.c_float)),
        ("pt_gather_rows_u8", ctypes.POINTER(ctypes.c_uint8)),
        ("pt_gather_rows_i64", i64p),
        ("pt_gather_rows_i32", ctypes.POINTER(ctypes.c_int32)),
    ]:
        fn = getattr(lib, name)
        fn.argtypes = [ptr_t, ctypes.c_int64, i64p, ctypes.c_int64, ptr_t,
                       ctypes.c_int]
        fn.restype = None
    g = lib.pt_gather_u8hwc_to_f32chw
    g.argtypes = [ctypes.POINTER(ctypes.c_uint8), i64p, ctypes.c_int64,
                  ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
                  ctypes.c_float, ctypes.c_float,
                  ctypes.POINTER(ctypes.c_float), ctypes.c_int]
    g.restype = None
    return lib


def _get_lib():
    global _lib, _build_error
    with _lock:
        if _lib is None and _build_error is None:
            try:
                _lib = _build()
            except (OSError, subprocess.CalledProcessError) as e:
                _build_error = str(e)
        return _lib


def available() -> bool:
    return _get_lib() is not None


_GATHER = {
    np.dtype(np.float32): ("pt_gather_rows_f32", ctypes.c_float),
    np.dtype(np.uint8): ("pt_gather_rows_u8", ctypes.c_uint8),
    np.dtype(np.int64): ("pt_gather_rows_i64", ctypes.c_int64),
    np.dtype(np.int32): ("pt_gather_rows_i32", ctypes.c_int32),
}


def _check_indices(idx, n):
    """Numpy fancy-index semantics before the C++ kernel: wrap negatives,
    raise IndexError out of range (instead of reading OOB memory)."""
    if idx.size == 0:
        return idx
    lo, hi = int(idx.min()), int(idx.max())
    if lo < -n or hi >= n:
        bad = lo if lo < -n else hi
        raise IndexError(
            f"index {bad} is out of bounds for axis 0 with size {n}")
    if lo < 0:
        idx = np.where(idx < 0, idx + n, idx)
    return np.ascontiguousarray(idx)


def _nthreads(default=None):
    if default is not None:
        return default
    try:
        from ..framework.flags import flag

        n = int(flag("FLAGS_paddle_num_threads"))
        if n > 1:
            return n
    except Exception:  # noqa: BLE001 — flags optional here
        pass
    return min(8, os.cpu_count() or 1)


def gather_rows(src: np.ndarray, indices, nthreads=None) -> np.ndarray:
    """out[i] = src[indices[i]] — parallel C++ copy for supported dtypes,
    numpy fancy-indexing fallback otherwise."""
    lib = _get_lib()
    src = np.ascontiguousarray(src)
    idx = np.ascontiguousarray(np.asarray(indices, dtype=np.int64))
    if lib is None or src.dtype not in _GATHER or src.ndim < 1:
        return src[idx]
    idx = _check_indices(idx, src.shape[0])
    name, ctype = _GATHER[src.dtype]
    row = int(np.prod(src.shape[1:], dtype=np.int64)) if src.ndim > 1 else 1
    out = np.empty((idx.shape[0],) + src.shape[1:], dtype=src.dtype)
    getattr(lib, name)(
        src.ctypes.data_as(ctypes.POINTER(ctype)), row,
        idx.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        idx.shape[0], out.ctypes.data_as(ctypes.POINTER(ctype)),
        _nthreads(nthreads))
    return out


def gather_images_u8_chw(src: np.ndarray, indices, scale=1.0 / 255.0,
                         shift=0.0, nthreads=None) -> np.ndarray:
    """f32 NCHW batch from u8 NHWC image storage, normalised in the same
    pass (the ToTensor+Normalize hot loop)."""
    lib = _get_lib()
    idx = np.ascontiguousarray(np.asarray(indices, dtype=np.int64))
    if lib is None or src.dtype != np.uint8 or src.ndim != 4:
        batch = src[idx].astype(np.float32) * scale + shift
        return np.transpose(batch, (0, 3, 1, 2))
    src = np.ascontiguousarray(src)
    idx = _check_indices(idx, src.shape[0])
    n = idx.shape[0]
    _, h, w, c = src.shape
    out = np.empty((n, c, h, w), dtype=np.float32)
    lib.pt_gather_u8hwc_to_f32chw(
        src.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        idx.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        n, h, w, c, ctypes.c_float(scale), ctypes.c_float(shift),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        _nthreads(nthreads))
    return out


# ---------------------------------------------------------------------------
# native sparse parameter table (ps_table.cc; ref
# paddle/fluid/distributed/table/common_sparse_table.cc)
# ---------------------------------------------------------------------------

_PS_SRC = os.path.join(_HERE, "ps_table.cc")
_ps_lib = None
_ps_build_error: str | None = None


def _build_ps():
    lib = _compile(_PS_SRC, "libpstable")
    i64p = ctypes.POINTER(ctypes.c_int64)
    f32p = ctypes.POINTER(ctypes.c_float)
    lib.pst_create.argtypes = [ctypes.c_int64, ctypes.c_float,
                               ctypes.c_float, ctypes.c_uint64]
    lib.pst_create.restype = ctypes.c_void_p
    lib.pst_free.argtypes = [ctypes.c_void_p]
    lib.pst_size.argtypes = [ctypes.c_void_p]
    lib.pst_size.restype = ctypes.c_int64
    lib.pst_pull.argtypes = [ctypes.c_void_p, i64p, ctypes.c_int64, f32p]
    lib.pst_push_sgd.argtypes = [ctypes.c_void_p, i64p, ctypes.c_int64,
                                 f32p, ctypes.c_float]
    lib.pst_push_adagrad.argtypes = [ctypes.c_void_p, i64p, ctypes.c_int64,
                                     f32p, ctypes.c_float, ctypes.c_float]
    lib.pst_push_delta.argtypes = [ctypes.c_void_p, i64p, ctypes.c_int64,
                                   f32p]
    lib.pst_export.argtypes = [ctypes.c_void_p, i64p, f32p]
    lib.pst_import.argtypes = [ctypes.c_void_p, i64p, ctypes.c_int64, f32p]
    # SSD spill variant (ref ssd_sparse_table.h)
    lib.pst_ssd_create.argtypes = [
        ctypes.c_int64, ctypes.c_float, ctypes.c_float, ctypes.c_uint64,
        ctypes.c_int64, ctypes.c_char_p, ctypes.c_int]
    lib.pst_ssd_create.restype = ctypes.c_void_p
    lib.pst_ssd_free.argtypes = [ctypes.c_void_p]
    for name in ("pst_ssd_size", "pst_ssd_resident", "pst_ssd_spilled"):
        fn = getattr(lib, name)
        fn.argtypes = [ctypes.c_void_p]
        fn.restype = ctypes.c_int64
    lib.pst_ssd_pull.argtypes = [ctypes.c_void_p, i64p, ctypes.c_int64,
                                 f32p]
    lib.pst_ssd_push_sgd.argtypes = [ctypes.c_void_p, i64p,
                                     ctypes.c_int64, f32p, ctypes.c_float]
    lib.pst_ssd_push_adagrad.argtypes = [
        ctypes.c_void_p, i64p, ctypes.c_int64, f32p, ctypes.c_float,
        ctypes.c_float]
    lib.pst_ssd_push_delta.argtypes = [ctypes.c_void_p, i64p,
                                       ctypes.c_int64, f32p]
    lib.pst_ssd_export.argtypes = [ctypes.c_void_p, i64p, f32p]
    lib.pst_ssd_export.restype = ctypes.c_int64
    lib.pst_ssd_import.argtypes = [ctypes.c_void_p, i64p, ctypes.c_int64,
                                   f32p]
    return lib


def ps_table_lib():
    """The compiled sparse-table library, or None (numpy fallback)."""
    global _ps_lib, _ps_build_error
    with _lock:
        if _ps_lib is None and _ps_build_error is None:
            try:
                _ps_lib = _build_ps()
            except (OSError, subprocess.CalledProcessError) as e:
                _ps_build_error = str(e)
        return _ps_lib
