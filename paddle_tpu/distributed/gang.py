"""Gang-scheduled training supervision: collective deadlines, typed
peer-failure errors, and coordinated whole-gang restart.

Ref parity: `paddle.distributed.launch` gang semantics + fleet/elastic.py
(ElasticManager) — TPU-era training is gang-scheduled: one worker's
preemption or hang must become a coordinated, checkpoint-consistent
restart of the WHOLE job, not a per-process retry. The reference detected
membership change and signalled RESTART but nothing closed the loop; this
module closes it in three layers:

1. **Deadlines everywhere** — `deadline_guard` / `call_with_deadline`
   wrap every eager collective (`collective.all_reduce`, `barrier`), the
   p2p mailbox, and the gang checkpoint commit barrier with a per-call
   deadline (FLAGS_dist_timeout_s). A rank whose peer died mid-collective
   raises typed *retriable* `CollectiveTimeoutError` / `PeerGoneError`
   instead of blocking forever — which is what turns a single SIGKILL
   into a clean, supervisable gang failure.
2. **Gang supervision** — `GangSupervisor` owns all local ranks: per-rank
   heartbeat files + step-progress watermarks (reusing the ElasticManager
   registry format), hang detection, coordinated SIGTERM->SIGKILL
   teardown of *all* ranks when any rank dies or stalls, restart under
   exponential backoff with a flaky-rank quarantine counter, and
   ElasticManager RESTART/HOLD verdicts wired into actual world
   re-formation within [min_np, max_np].
3. **Worker participation** — `GangWorker` is the rank side: one `beat()`
   per step boundary writes liveness + the step watermark, and a
   preemption deregisters the rank so peers and the supervisor observe
   the membership change immediately.

Recovery is checkpoint-based: `checkpoint.GangCheckpointManager` commits
a step only when every rank wrote (rank-0 GANG marker with a cross-rank
digest), and a restarted gang restores from the newest *globally*
committed step — certified bitwise by tests/test_gang_slow.py and
bench_gang.py against an uninterrupted run.
"""

from __future__ import annotations

import os
import signal
import socket
import subprocess
import sys
import threading
import time
import weakref

import numpy as np

from ..framework import monitor as _monitor
from ..framework.errors import ExecutionTimeoutError, UnavailableError

__all__ = [
    "CollectiveTimeoutError", "PeerGoneError", "deadline_guard",
    "call_with_deadline", "GangWorker", "allreduce_host", "barrier_host",
    "GangSupervisor", "heartbeat_ages",
]


class CollectiveTimeoutError(ExecutionTimeoutError):
    """An eager collective/barrier exceeded its per-call deadline
    (FLAGS_dist_timeout_s): a peer died or stalled mid-collective.
    Retriable — at a step boundary the caller may retry the op or exit
    and let the gang supervisor coordinate a restart."""

    retriable = True


class PeerGoneError(UnavailableError):
    """A p2p peer did not answer within the deadline (its process is
    gone or wedged). Retriable for the same reason as
    CollectiveTimeoutError; carries the peer rank in the message."""

    retriable = True


# ---------------------------------------------------------------------------
# deadlines
# ---------------------------------------------------------------------------


def _default_deadline():
    from ..framework import flags as _flags

    return _flags.flag("FLAGS_dist_timeout_s")


def deadline_guard(site, deadline_s=None, tag=None):
    """Enter a deadline-scoped distributed op: fire the fault site (a
    `delay` action eats the budget — the deterministic timeout path) and
    return the remaining per-call deadline in seconds, or None when
    deadlines are disabled (FLAGS_dist_timeout_s=0 and no explicit
    deadline). Raises CollectiveTimeoutError when the budget is already
    spent before the transport is even reached."""
    from ..framework import faults as _faults

    if deadline_s is None:
        deadline_s = _default_deadline()
    if not deadline_s or deadline_s <= 0:
        _faults.fault_point(site, tag=tag)
        return None
    start = time.monotonic()
    _faults.fault_point(site, tag=tag)
    remaining = deadline_s - (time.monotonic() - start)
    if remaining <= 0:
        _monitor.stat_add("gang.collective_timeouts")
        raise CollectiveTimeoutError(
            f"{site} exceeded its {deadline_s:.3f}s deadline before "
            "reaching the transport (injected slowness or a scheduler "
            "stall); the op is retriable at the next step boundary")
    return remaining


def call_with_deadline(fn, deadline_s, what):
    """Run blocking transport work with a deadline. `fn` executes on a
    daemon worker thread; if it does not finish within `deadline_s` the
    caller unblocks with CollectiveTimeoutError while the thread is
    abandoned (the gang supervisor tears the process down anyway — a
    leaked blocked thread is strictly better than a rank wedged
    forever). `deadline_s=None` calls `fn` inline (deadlines off)."""
    if deadline_s is None:
        return fn()
    box = {}

    def _run():
        try:
            box["result"] = fn()
        except BaseException as e:  # noqa: BLE001 — reraised on caller
            box["error"] = e

    t = threading.Thread(target=_run, daemon=True,
                         name=f"deadline:{what}")
    t.start()
    t.join(deadline_s)
    if t.is_alive():
        _monitor.stat_add("gang.collective_timeouts")
        raise CollectiveTimeoutError(
            f"{what} did not complete within its {deadline_s:.3f}s "
            "deadline — a peer is gone or stalled mid-collective; "
            "retriable (exit and let the gang supervisor restart)")
    if "error" in box:
        raise box["error"]
    return box.get("result")


# ---------------------------------------------------------------------------
# worker side: heartbeat + step watermark
# ---------------------------------------------------------------------------


class GangWorker:
    """Rank-side gang participation.

    One instance per training process; `beat(step=...)` at every step
    boundary writes the rank's liveness heartbeat AND its step-progress
    watermark into the supervisor's registry (the ElasticManager file
    format, so the elastic machinery reads the same files). A preemption
    (`preempt.request`) deregisters the rank immediately, so the
    supervisor and peers observe the membership change without waiting
    for the heartbeat to expire."""

    def __init__(self, gang_dir=None, rank=None, node_id=None,
                 heartbeat_interval=1.0, timeout=10.0):
        from .elastic import ElasticManager
        from .parallel import ParallelEnv

        gang_dir = gang_dir or os.environ.get("PADDLE_GANG_DIR")
        if not gang_dir:
            raise RuntimeError(
                "GangWorker needs a registry dir: pass gang_dir= or run "
                "under the gang supervisor (PADDLE_GANG_DIR)")
        if rank is None:
            rank = ParallelEnv().rank
        # the node id is keyed by SLOT (the supervisor's stable rank id
        # across world re-formations), falling back to the rank
        slot = os.environ.get("PADDLE_GANG_SLOT", str(rank))
        self.rank = int(rank)
        self.slot = int(slot)
        self.em = ElasticManager(
            gang_dir, node_id=node_id or f"rank-{slot}",
            heartbeat_interval=heartbeat_interval, timeout=timeout)
        from . import preempt as _preempt

        _preempt.on_preempt(self.deregister)

    def beat(self, step=None):
        """Heartbeat + step watermark. Passes the ``gang.heartbeat``
        fault site: ``drop`` skips the write (the supervisor sees this
        rank stall), ``delay`` models a slow registry filesystem,
        ``crash`` is death at the beat itself."""
        from ..framework import faults as _faults

        if _faults.fault_point("gang.heartbeat",
                               tag=str(self.slot)) is _faults.DROP:
            return
        self.em.beat(step=step)
        _monitor.stat_add("gang.heartbeats")

    def deregister(self):
        self.em.deregister()


# ---------------------------------------------------------------------------
# eager host-staged collectives over the p2p mailbox
# ---------------------------------------------------------------------------
#
# Separate jax processes in a CPU gang have process_count()==1 each, so
# jax's multihost collectives are identities there; these rank-0-rooted
# host collectives ride the p2p mailbox instead and are what the gang
# bench/tests block inside when a peer is killed. Reduction order is
# fixed (ascending rank), so results are bitwise reproducible.


_REDUCERS = {
    "sum": lambda a, b: a + b,
    "max": np.maximum,
    "min": np.minimum,
    "prod": lambda a, b: a * b,
}


def _env_rank_world(rank, world):
    from .parallel import ParallelEnv

    env = ParallelEnv()
    return (env.rank if rank is None else int(rank),
            env.world_size if world is None else int(world))


def allreduce_host(arr, op="sum", *, rank=None, world=None,
                   deadline_s=None, box=None):
    """Deadline-guarded eager all-reduce of a host array across the gang
    (rank 0 gathers in rank order, reduces, broadcasts back). Raises
    CollectiveTimeoutError/PeerGoneError instead of blocking when a peer
    is gone."""
    rank, world = _env_rank_world(rank, world)
    remaining = deadline_guard("dist.allreduce", deadline_s)
    a = np.asarray(arr)
    if world <= 1:
        return a
    if box is None:
        from .p2p import mailbox

        box = mailbox()
    end = None if remaining is None else time.monotonic() + remaining
    mean = op in ("mean", "avg")
    reduce_fn = _REDUCERS["sum" if mean else op]

    def _left():
        return None if end is None else max(end - time.monotonic(), 1e-3)

    if rank == 0:
        out = a
        for src in range(1, world):
            out = reduce_fn(out, box.recv(src, timeout=_left()))
        if mean:
            out = (out / np.asarray(world).astype(out.dtype)).astype(
                out.dtype)
        for dst in range(1, world):
            box.send(out, dst, deadline_s=_left())
        return out
    box.send(a, 0, deadline_s=_left())
    return np.asarray(box.recv(0, timeout=_left()))


def barrier_host(*, rank=None, world=None, deadline_s=None, box=None):
    """Deadline-guarded eager barrier over the mailbox (gather tokens at
    rank 0, then release). Every live rank either passes or raises a
    typed error within the deadline — no rank is left blocked."""
    rank, world = _env_rank_world(rank, world)
    remaining = deadline_guard("dist.barrier", deadline_s)
    if world <= 1:
        return
    if box is None:
        from .p2p import mailbox

        box = mailbox()
    end = None if remaining is None else time.monotonic() + remaining

    def _left():
        return None if end is None else max(end - time.monotonic(), 1e-3)

    token = np.zeros((), np.int32)
    if rank == 0:
        for src in range(1, world):
            box.recv(src, timeout=_left())
        for dst in range(1, world):
            box.send(token, dst, deadline_s=_left())
        return
    box.send(token, 0, deadline_s=_left())
    box.recv(0, timeout=_left())


# ---------------------------------------------------------------------------
# supervisor side
# ---------------------------------------------------------------------------


def _free_ports(n, host="127.0.0.1"):
    socks, ports = [], []
    for _ in range(n):
        s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        s.bind((host, 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


def terminate_all(procs, grace=10.0):
    """Coordinated teardown: SIGTERM every live child, wait out one
    shared grace window, SIGKILL the stragglers, and REAP every exit so
    no zombie outlives the pod (launch._terminate_all delegates here)."""
    for p in procs:
        if p.poll() is None:
            try:
                p.terminate()
            except OSError:
                pass
    deadline = time.monotonic() + grace
    for p in procs:
        if p.poll() is None:
            try:
                p.wait(timeout=max(0.05, deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                try:
                    p.kill()
                except OSError:
                    pass
    # reap unconditionally: kill() without wait() leaves a zombie
    for p in procs:
        if p.poll() is None:
            try:
                p.wait(timeout=grace)
            except subprocess.TimeoutExpired:
                pass


#: live supervisors, for the observe exporter's heartbeat-age gauges
_SUPERVISORS: "weakref.WeakSet[GangSupervisor]" = weakref.WeakSet()


def heartbeat_ages():
    """{slot: seconds since that rank's last heartbeat} across live
    supervisors (observe.export's paddle_gang_rank_heartbeat_age)."""
    out = {}
    for sup in list(_SUPERVISORS):
        for slot, rec in sup.rank_snapshot().items():
            if rec.get("beat_age_s") is not None:
                out[str(slot)] = rec["beat_age_s"]
    return out


class GangSupervisor:
    """Job-level supervisor for a gang of training ranks.

    What `serving/fleet.py` does for replicas, this does for the
    training gang — with the crucial difference that training ranks are
    NOT independent: any rank dying or stalling makes every peer's next
    collective undefined, so the only safe recovery is to tear down the
    whole gang and restart it from the newest globally committed
    checkpoint.

    - liveness: child process exit codes (the classic launch watchdog)
    - progress: per-rank heartbeat files + step watermarks written by
      `GangWorker.beat` into `gang_dir` — a rank that is alive but not
      advancing past FLAGS_gang_hang_secs is hung, not healthy
    - verdicts: an ElasticManager observer over the same registry turns
      membership changes (a new node beating in, a preempted rank
      deregistering) into coordinated RESTART re-formations within
      [min_np, max_np]
    - flaky ranks: a slot that causes `quarantine_after` teardowns is
      quarantined and the world re-forms without it (never below min_np)

    `cmd` is the training command (script + args); the supervisor
    appends the launch env contract per rank plus PADDLE_GANG_DIR /
    PADDLE_GANG_SLOT / PADDLE_GANG_ATTEMPT.
    """

    def __init__(self, cmd, nranks, *, gang_dir, min_np=1, max_np=None,
                 max_restarts=None, hang_secs=None, grace_s=10.0,
                 poll_interval=0.25, quarantine_after=2, log_dir=None,
                 backoff_base_s=0.5, backoff_max_s=8.0,
                 endpoints_fn=None, base_env=None, stderr=None):
        from ..framework import flags as _flags

        self.cmd = list(cmd)
        self.nranks = int(nranks)
        self.gang_dir = os.path.abspath(gang_dir)
        os.makedirs(self.gang_dir, exist_ok=True)
        self.min_np = int(min_np)
        self.max_np = int(max_np) if max_np else None
        if self.min_np > self.nranks:
            raise ValueError(
                f"min_np={self.min_np} exceeds nranks={self.nranks}: "
                "the gang could never form")
        self.max_restarts = (
            _flags.flag("FLAGS_gang_max_restarts")
            if max_restarts is None else int(max_restarts))
        self.hang_secs = (
            _flags.flag("FLAGS_gang_hang_secs")
            if hang_secs is None else float(hang_secs))
        self.grace_s = grace_s
        self.poll_interval = poll_interval
        self.quarantine_after = int(quarantine_after)
        self.log_dir = log_dir
        self.backoff_base_s = backoff_base_s
        self.backoff_max_s = backoff_max_s
        self.endpoints_fn = endpoints_fn
        self.base_env = dict(base_env) if base_env is not None else None
        self.stderr = stderr if stderr is not None else sys.stderr

        self.restarts = 0
        self.generation = 0
        self.quarantined: set[int] = set()
        self._fault_counts: dict[int, int] = {}
        self._procs: dict[int, subprocess.Popen] = {}   # slot -> proc
        self._logs: list = []
        self._spawn_ts = 0.0
        self._watermarks: dict[int, tuple] = {}  # slot -> (step, ts)
        self._em = None
        self._formed = False
        _SUPERVISORS.add(self)

    # -- world formation ----------------------------------------------------

    def active_slots(self):
        """Slots forming the next world: original rank ids minus the
        quarantined, truncated to max_np (stable order, so rank i of
        the new world is the i-th surviving slot)."""
        slots = [s for s in range(self.nranks) if s not in self.quarantined]
        if self.max_np:
            slots = slots[: self.max_np]
        return slots

    def world_size(self):
        return len(self.active_slots())

    def _beat_path(self, slot):
        return os.path.join(self.gang_dir, f"rank-{slot}.beat")

    def _read_beat(self, slot):
        import json

        try:
            with open(self._beat_path(slot)) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    def rank_snapshot(self):
        """{slot: {alive, beat_age_s, step}} for export/telemetry."""
        now = time.time()
        out = {}
        for slot, p in self._procs.items():
            rec = self._read_beat(slot) or {}
            ts = rec.get("ts", 0)
            out[slot] = {
                "alive": p.poll() is None,
                "beat_age_s": (now - ts) if ts >= self._spawn_ts else None,
                "step": rec.get("step"),
            }
        return out

    def snapshot(self):
        return {
            "generation": self.generation,
            "restarts": self.restarts,
            "world": self.world_size(),
            "quarantined": sorted(self.quarantined),
            "ranks": {str(s): r for s, r in self.rank_snapshot().items()},
        }

    # -- spawn / teardown ---------------------------------------------------

    def _spawn_all(self):
        slots = self.active_slots()
        world = len(slots)
        if world < self.min_np:
            raise UnavailableError(
                f"cannot form a gang: {world} usable ranks < "
                f"min_np={self.min_np} (quarantined: "
                f"{sorted(self.quarantined)})")
        if self.endpoints_fn is not None:
            endpoints = self.endpoints_fn(world)
        else:
            endpoints = ["127.0.0.1:%d" % p for p in _free_ports(world)]
        base = self.base_env if self.base_env is not None \
            else dict(os.environ)
        if "PADDLE_TPU_PS_TOKEN" not in base:
            import secrets

            base["PADDLE_TPU_PS_TOKEN"] = secrets.token_hex(16)
        if self.log_dir:
            os.makedirs(self.log_dir, exist_ok=True)
        self.generation += 1
        self._spawn_ts = time.time()
        self._watermarks = {}
        self._formed = False
        procs, logs = {}, []
        for rank, slot in enumerate(slots):
            env = dict(base)
            env.update({
                "PADDLE_TRAINER_ID": str(rank),
                "PADDLE_TRAINERS_NUM": str(world),
                "PADDLE_CURRENT_ENDPOINT": endpoints[rank],
                "PADDLE_TRAINER_ENDPOINTS": ",".join(endpoints),
                "PADDLE_MASTER": endpoints[0],
                "PADDLE_LOCAL_RANK": str(rank),
                "PADDLE_GANG_DIR": self.gang_dir,
                "PADDLE_GANG_SLOT": str(slot),
                "PADDLE_GANG_ATTEMPT": str(self.generation),
            })
            if self.log_dir:
                f = open(os.path.join(self.log_dir, f"workerlog.{slot}"),
                         "a" if self.generation > 1 else "w")
                logs.append(f)
                p = subprocess.Popen(self.cmd, env=env, stdout=f,
                                     stderr=subprocess.STDOUT)
            else:
                p = subprocess.Popen(self.cmd, env=env)
            procs[slot] = p
        self._procs, self._logs = procs, logs
        # fresh elastic observer per generation: the restart itself is a
        # membership change the verdict machinery must not re-trigger on
        from .elastic import ElasticManager

        self._em = ElasticManager(
            self.gang_dir, node_id="__supervisor__",
            min_np=self.min_np, max_np=self.max_np,
            timeout=max(self.hang_secs, 5.0) if self.hang_secs else 10.0)

    def terminate(self):
        terminate_all(list(self._procs.values()), grace=self.grace_s)
        for f in self._logs:
            try:
                f.close()
            except OSError:
                pass
        self._logs = []

    # -- fault detection ----------------------------------------------------

    def _check_exits(self):
        """(done, cause): done=True when every rank exited 0; cause set
        when any rank died non-zero."""
        all_done = True
        for slot, p in self._procs.items():
            ret = p.poll()
            if ret is None:
                all_done = False
            elif ret != 0:
                return False, ("exit", slot, ret)
        return all_done, None

    def _check_stalls(self):
        """Hang detection from the registry: a live rank whose heartbeat
        (or step watermark) last advanced more than hang_secs ago is
        hung — process liveness alone is not progress."""
        if not self.hang_secs:
            return None
        now = time.time()
        worst = None   # (age, slot)
        for slot, p in self._procs.items():
            if p.poll() is not None:
                continue
            rec = self._read_beat(slot)
            if rec is None or rec.get("ts", 0) < self._spawn_ts:
                continue   # never beat this generation: still booting
            step = rec.get("step")
            last_step, last_change = self._watermarks.get(
                slot, (None, rec["ts"]))
            if step != last_step:
                self._watermarks[slot] = (step, now)
                last_change = now
            age = now - max(rec["ts"], 0)
            stalled_beat = age > self.hang_secs
            stalled_step = (step is not None
                            and now - last_change > self.hang_secs)
            if stalled_beat or stalled_step:
                stall_age = max(age, now - last_change)
                if worst is None or stall_age > worst[0]:
                    worst = (stall_age, slot)
        if worst is not None:
            return ("stall", worst[1], worst[0])
        return None

    def _check_membership(self):
        """One ElasticManager verdict poll; RESTART = membership changed
        (a node joined/deregistered) -> coordinated re-formation.

        Two guards keep the verdict honest: (1) ranks registering one by
        one during gang FORMATION is not a membership change — verdicts
        only count once every expected rank has beaten; (2) a dead child
        is the exit-check's fault to attribute (with its exit code), not
        a membership event."""
        from .elastic import ElasticStatus

        if self._em is None:
            return None
        if any(p.poll() is not None for p in self._procs.values()):
            return None
        if not self._formed:
            live = self._em.live_nodes()
            if len(live) >= len(self._procs):
                self._formed = True
                self._em._known = sorted(live)  # the formed membership
            return None
        status = self._em.watch()
        if status == ElasticStatus.RESTART:
            return ("membership",)
        if status == ElasticStatus.EXIT:
            return ("preempted",)
        return None

    # -- restart ------------------------------------------------------------

    def _note_fault(self, slot, why):
        self._fault_counts[slot] = self._fault_counts.get(slot, 0) + 1
        if (self._fault_counts[slot] >= self.quarantine_after
                and len(self.active_slots()) - 1 >= self.min_np
                and slot not in self.quarantined):
            self.quarantined.add(slot)
            _monitor.stat_add("gang.quarantined")
            try:
                os.remove(self._beat_path(slot))
            except OSError:
                pass
            self.stderr.write(
                f"[launch] rank slot {slot} quarantined after "
                f"{self._fault_counts[slot]} faults ({why}); re-forming "
                f"the world with {len(self.active_slots())} ranks\n")

    def _restart(self, cause):
        """Coordinated teardown + re-formation. Returns None to keep
        supervising, or the job's final exit code to give up."""
        from ..framework import faults as _faults
        from .. import observe as _observe

        detect_ts = time.monotonic()
        kind = cause[0]
        code = cause[2] if kind == "exit" else 1
        if kind == "exit":
            slot = cause[1]
            self.stderr.write(
                f"[launch] rank {slot} (pid {self._procs[slot].pid}) "
                f"exited with code {code}; terminating the pod\n")
            self._note_fault(slot, f"exit code {code}")
        elif kind == "stall":
            slot, age = cause[1], cause[2]
            code = 1
            self.stderr.write(
                f"[launch] rank {slot} stalled ({age:.1f}s without "
                f"heartbeat/step progress > {self.hang_secs}s); "
                "terminating the pod\n")
            self._note_fault(slot, f"stalled {age:.1f}s")
        elif kind == "membership":
            self.stderr.write(
                "[launch] gang membership changed; re-forming the "
                "world\n")
        with _observe.phase("gang-restart", cat="gang"):
            self.terminate()
            if self.restarts >= self.max_restarts:
                self.stderr.write(
                    f"[launch] gang restart budget exhausted "
                    f"({self.restarts}/{self.max_restarts}); failing "
                    f"with code {code}\n")
                return code
            self.restarts += 1
            _monitor.stat_add("gang.restarts")
            reason = f"exit code {code}" if kind == "exit" else kind
            self.stderr.write(
                f"[launch] elastic restart {self.restarts}/"
                f"{self.max_restarts} after {reason}\n")
            _faults.fault_point("gang.restart")
            time.sleep(min(self.backoff_base_s * 2 ** (self.restarts - 1),
                           self.backoff_max_s))
            self._spawn_all()
        _monitor.stat_add("gang.restart_lost_ms",
                          int((time.monotonic() - detect_ts) * 1e3))
        return None

    # -- the supervised job -------------------------------------------------

    def run(self):
        """Supervise until the gang completes (0), the restart budget is
        spent (first failing exit code), or interrupt (130). A caller
        that already pre-spawned (launch's retrying bootstrap) is not
        double-spawned."""
        if not self._procs:
            self._spawn_all()
        try:
            while True:
                done, cause = self._check_exits()
                if done:
                    return 0
                cause = cause or self._check_stalls() \
                    or self._check_membership()
                if cause == ("preempted",):
                    self.terminate()
                    return 143
                if cause is not None:
                    code = self._restart(cause)
                    if code is not None:
                        return code
                    continue
                time.sleep(self.poll_interval)
        except KeyboardInterrupt:
            self.terminate()
            return 130
