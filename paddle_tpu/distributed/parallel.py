"""Process/parallel environment.

Ref parity: python/paddle/distributed/parallel.py:58 init_parallel_env +
the PADDLE_TRAINER_* env contract (fleet/launch_utils.py). TPU-native: one
process per *host* (not per chip); `jax.distributed.initialize` plays the
role of the NCCL-id TCP bootstrap (gen_comm_id_helper.cc), and the
"world" is the set of jax processes × local devices.
"""

from __future__ import annotations

import os

import jax

_parallel_env_initialized = False


class ParallelEnv:
    """ref: fluid/dygraph/parallel.py ParallelEnv."""

    def __init__(self):
        self._rank = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
        self._world_size = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
        self._device_id = 0
        eps = os.environ.get("PADDLE_TRAINER_ENDPOINTS", "")
        self._trainer_endpoints = eps.split(",") if eps else []
        self._current_endpoint = os.environ.get("PADDLE_CURRENT_ENDPOINT",
                                                "")

    @property
    def rank(self):
        return self._rank

    @property
    def world_size(self):
        return self._world_size

    @property
    def device_id(self):
        return self._device_id

    @property
    def current_endpoint(self):
        return self._current_endpoint

    @property
    def trainer_endpoints(self):
        return self._trainer_endpoints

    # legacy names
    local_rank = rank
    nranks = world_size


def init_parallel_env():
    """Bootstrap multi-host jax (DCN). Single-host is a no-op: all local
    TPU chips already belong to this process (unlike the reference's
    process-per-GPU model)."""
    global _parallel_env_initialized
    env = ParallelEnv()
    if env.world_size > 1 and not _parallel_env_initialized:
        coordinator = os.environ.get("PADDLE_MASTER") or (
            env.trainer_endpoints[0] if env.trainer_endpoints else None)
        configured = os.environ.get("JAX_PLATFORMS", "") or str(
            getattr(jax.config, "jax_platforms", None) or "")
        if "cpu" in configured:
            # multi-process CPU (the 'no real cluster' test backend) needs
            # an explicit cross-process collectives implementation
            try:
                jax.config.update(
                    "jax_cpu_collectives_implementation", "gloo")
            except (ValueError, RuntimeError):
                pass
        jax.distributed.initialize(
            coordinator_address=coordinator,
            num_processes=env.world_size,
            process_id=env.rank)
    _parallel_env_initialized = True
    return env


def get_rank(group=None):
    if group is not None:
        return group.rank
    try:
        return jax.process_index()
    except RuntimeError:
        return int(os.environ.get("PADDLE_TRAINER_ID", "0"))


def get_world_size(group=None):
    if group is not None:
        return group.nranks
    try:
        return jax.process_count()
    except RuntimeError:
        return int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
