"""Multi-process launcher: `python -m paddle_tpu.distributed.launch`.

Ref parity: python/paddle/distributed/fleet/launch.py:396 (launch_collective)
and fleet/launch_utils.py:453 (start_local_trainers) / :565
(watch_local_trainers). TPU-native differences: one process per HOST (a jax
process owns all its local chips), so `--nproc_per_node` defaults to 1 and
is only raised for CPU-simulated multi-host tests; the NCCL-id TCP
broadcast is replaced by `jax.distributed.initialize` against a coordinator
address every rank derives from the same env contract.

Since ISSUE 14 the single-node path is gang-supervised
(distributed/gang.GangSupervisor): per-rank heartbeat files + step
watermarks detect hangs (not just exits), any rank dying or stalling
tears down ALL ranks (SIGTERM -> SIGKILL, reaped), and the gang restarts
under exponential backoff with flaky-rank quarantine — recovery is
checkpoint-based via GangCheckpointManager's globally committed steps.
The multi-node (nnodes > 1) path keeps the classic per-node watchdog:
cross-node supervision needs a shared registry filesystem, which the
training script opts into by pointing PADDLE_GANG_DIR at one.

Env contract written for each child (read by parallel.init_parallel_env):
  PADDLE_TRAINER_ID         global rank of the process
  PADDLE_TRAINERS_NUM       world size (total processes)
  PADDLE_CURRENT_ENDPOINT   this process's endpoint host:port
  PADDLE_TRAINER_ENDPOINTS  comma list of all endpoints (rank order)
  PADDLE_MASTER             coordinator address (= endpoint of rank 0)
  PADDLE_GANG_DIR           gang heartbeat registry (supervised runs)
  PADDLE_GANG_SLOT          stable slot id across world re-formations
  PADDLE_GANG_ATTEMPT       1-based spawn generation
"""

from __future__ import annotations

import argparse
import os
import signal
import subprocess
import sys
import tempfile
import time

from .gang import GangSupervisor, _free_ports, terminate_all


def parse_args(argv=None):
    parser = argparse.ArgumentParser(
        prog="python -m paddle_tpu.distributed.launch",
        description="Launch a distributed paddle_tpu job "
                    "(one process per host; jax.distributed bootstrap).")
    parser.add_argument("--nnodes", type=int, default=1,
                        help="number of hosts in the job")
    parser.add_argument("--node_rank", type=int,
                        default=int(os.environ.get("PADDLE_NODE_RANK", 0)),
                        help="rank of this host")
    parser.add_argument("--master", type=str, default=None,
                        help="coordinator host:port (rank-0 host); "
                             "required when nnodes > 1")
    parser.add_argument("--ips", type=str, default=None,
                        help="comma list of host IPs, rank order (ref "
                             "fleet.launch --ips); defaults to the master "
                             "host for every node")
    parser.add_argument("--nproc_per_node", type=int, default=1,
                        help="processes per host (1 on TPU: a process owns "
                             "all local chips; >1 only for CPU-mesh tests)")
    parser.add_argument("--log_dir", type=str, default=None,
                        help="write per-rank workerlog.N files here")
    parser.add_argument("--elastic_retries", type=int, default=0,
                        help="restart the whole local pod up to N times "
                             "after a failure (ref fleet/elastic.py; "
                             "state recovery is checkpoint-based)")
    parser.add_argument("--gang_dir", type=str, default=None,
                        help="gang heartbeat registry directory (default: "
                             "a fresh tempdir); training scripts beat into "
                             "it via distributed.gang.GangWorker")
    parser.add_argument("--gang_hang_secs", type=float, default=None,
                        help="declare a beating-but-stalled rank hung "
                             "after this long (default: "
                             "FLAGS_gang_hang_secs; 0 disables)")
    parser.add_argument("--min_np", type=int, default=None,
                        help="smallest world the gang may re-form to when "
                             "ranks are quarantined (default: nproc)")
    parser.add_argument("--poll_interval", type=float, default=0.5)
    parser.add_argument("training_script", type=str)
    parser.add_argument("training_script_args", nargs=argparse.REMAINDER)
    return parser.parse_args(argv)


def _build_endpoints(args):
    """Endpoint per global rank. Single-node: loopback + free ports."""
    world = args.nnodes * args.nproc_per_node
    if args.nnodes == 1:
        ports = _free_ports(args.nproc_per_node)
        return ["127.0.0.1:%d" % p for p in ports], world
    if not args.master:
        raise SystemExit("--master host:port is required when nnodes > 1")
    base = int(args.master.split(":")[1])
    if args.ips:
        hosts = [h.strip() for h in args.ips.split(",")]
        if len(hosts) != args.nnodes:
            raise SystemExit(
                f"--ips lists {len(hosts)} hosts but nnodes={args.nnodes}")
        # distinct hosts: each node reuses the same port block
        eps = [f"{hosts[node]}:{base + i}"
               for node in range(args.nnodes)
               for i in range(args.nproc_per_node)]
    else:
        # no --ips: all endpoints fabricated on the master host (same-host
        # testing); ports must then be globally unique to stay addressable
        host = args.master.split(":")[0]
        eps = [f"{host}:{base + node * args.nproc_per_node + i}"
               for node in range(args.nnodes)
               for i in range(args.nproc_per_node)]
    return eps, world


def start_local_trainers(args, endpoints, world, append_logs=False):
    """ref launch_utils.py:453 — one Popen per local rank with the env
    contract; stdout/stderr tee'd to workerlog.N when --log_dir given.
    append_logs: elastic retries must not truncate the failed attempt's
    traceback. (Multi-node path; single-node spawning lives in
    GangSupervisor._spawn_all.)"""
    procs = []
    logs = []
    master = args.master or endpoints[0]
    if args.log_dir:
        os.makedirs(args.log_dir, exist_ok=True)
    # single-node: mint a per-pod PS auth token so the handshake is not
    # the public default. Multi-node: set PADDLE_TPU_PS_TOKEN identically
    # on every node before launching (it is inherited below).
    if "PADDLE_TPU_PS_TOKEN" not in os.environ and args.nnodes == 1:
        import secrets

        os.environ["PADDLE_TPU_PS_TOKEN"] = secrets.token_hex(16)
    for local in range(args.nproc_per_node):
        rank = args.node_rank * args.nproc_per_node + local
        env = dict(os.environ)
        env.update({
            "PADDLE_TRAINER_ID": str(rank),
            "PADDLE_TRAINERS_NUM": str(world),
            "PADDLE_CURRENT_ENDPOINT": endpoints[rank],
            "PADDLE_TRAINER_ENDPOINTS": ",".join(endpoints),
            "PADDLE_MASTER": master,
            "PADDLE_LOCAL_RANK": str(local),
        })
        cmd = [sys.executable, "-u", args.training_script] + \
            args.training_script_args
        if args.log_dir:
            f = open(os.path.join(args.log_dir, f"workerlog.{rank}"),
                     "a" if append_logs else "w")
            logs.append(f)
            p = subprocess.Popen(cmd, env=env, stdout=f,
                                 stderr=subprocess.STDOUT)
        else:
            p = subprocess.Popen(cmd, env=env)
        procs.append(p)
    return procs, logs


def _terminate_all(procs, grace=10.0):
    """Coordinated SIGTERM -> grace -> SIGKILL teardown, every exit
    reaped (gang.terminate_all is the one implementation)."""
    terminate_all(procs, grace=grace)


def watch_local_trainers(procs, poll_interval=0.5):
    """ref launch_utils.py:565 — poll children; any non-zero exit kills
    the whole local pod and propagates the code. (Multi-node path; the
    single-node watch loop with hang detection is GangSupervisor.run.)"""
    try:
        while True:
            alive = False
            for rank, p in enumerate(procs):
                ret = p.poll()
                if ret is None:
                    alive = True
                elif ret != 0:
                    sys.stderr.write(
                        f"[launch] rank {rank} (pid {p.pid}) exited with "
                        f"code {ret}; terminating the pod\n")
                    _terminate_all(procs)
                    return ret
            if not alive:
                return 0
            time.sleep(poll_interval)
    except KeyboardInterrupt:
        _terminate_all(procs)
        return 130


def _launch_supervised(args):
    """Single-node path: the gang supervisor owns spawn, watch, hang
    detection, coordinated teardown, and backoff restarts."""
    from ..framework.errors import retry_with_backoff

    gang_dir = args.gang_dir or tempfile.mkdtemp(prefix="paddle-gang-")
    cmd = [sys.executable, "-u", args.training_script] + \
        args.training_script_args
    sup = GangSupervisor(
        cmd, args.nproc_per_node, gang_dir=gang_dir,
        min_np=args.min_np or args.nproc_per_node,
        max_np=args.nproc_per_node,
        max_restarts=args.elastic_retries,
        hang_secs=args.gang_hang_secs,
        poll_interval=args.poll_interval, log_dir=args.log_dir)

    def _sig(signum, frame):
        sup.terminate()
        sys.exit(128 + signum)

    signal.signal(signal.SIGTERM, _sig)
    # the bootstrap races the OS for ports and forks children; both fail
    # transiently under load (EADDRINUSE between probe and bind, EAGAIN
    # on fork) — retry with backoff instead of failing the job
    retry_with_backoff(sup._spawn_all, retries=3,
                       stat="launch_bootstrap_retries",
                       description="launch trainer spawn")
    return sup.run()


def _launch_legacy(args):
    """Multi-node per-node watchdog (no shared registry assumed)."""
    from ..framework.errors import retry_with_backoff

    attempts = 0
    while True:
        endpoints, world = retry_with_backoff(
            lambda: _build_endpoints(args), retries=3,
            stat="launch_bootstrap_retries",
            description="launch endpoint allocation")
        procs, logs = retry_with_backoff(
            lambda: start_local_trainers(args, endpoints, world,
                                         append_logs=(attempts > 0)),
            retries=3, stat="launch_bootstrap_retries",
            description="launch trainer spawn")

        def _sig(signum, frame, procs=procs):
            _terminate_all(procs)
            sys.exit(128 + signum)

        signal.signal(signal.SIGTERM, _sig)
        code = watch_local_trainers(procs, args.poll_interval)
        for f in logs:
            f.close()
        if code == 0 or attempts >= args.elastic_retries or code == 130:
            return code
        attempts += 1
        sys.stderr.write(
            f"[launch] elastic restart {attempts}/"
            f"{args.elastic_retries} after exit code {code}\n")


def launch(argv=None):
    args = parse_args(argv)
    if args.nnodes == 1:
        return _launch_supervised(args)
    return _launch_legacy(args)


if __name__ == "__main__":
    sys.exit(launch())
