"""4-D process topology -> jax device Mesh.

Ref parity: python/paddle/distributed/fleet/base/topology.py:29-344
(CommunicateTopology, HybridCommunicateGroup, ParallelMode). The reference
builds one NCCL ring per axis of the data x model x pipe x sharding grid;
here the grid *is* a jax.sharding.Mesh whose axis names are consumed by
GSPMD specs and shard_map collectives — comm groups collapse into axis
names.
"""

from __future__ import annotations

import itertools

import numpy as np

import jax
from jax.sharding import Mesh

from .collective import Group, new_group
from .parallel import get_rank


class ParallelMode:
    DATA_PARALLEL = 0
    TENSOR_PARALLEL = 1
    PIPELINE_PARALLEL = 2
    SHARDING_PARALLEL = 3


# canonical mesh axis names
DP_AXIS = "dp"
SHARDING_AXIS = "sharding"
PP_AXIS = "pp"
MP_AXIS = "mp"
SEP_AXIS = "sep"  # sequence/context parallel (net-new vs reference)


class CommunicateTopology:
    """ref: topology.py:29 CommunicateTopology — a named hypercube of
    ranks with per-axis comm groups."""

    def __init__(self, hybrid_group_names=("data", "pipe", "sharding",
                                           "model"),
                 dims=(1, 1, 1, 1)):
        self._parallel_names = list(hybrid_group_names)
        self._dims = list(dims)
        self.coordinate = list(itertools.product(
            *(range(d) for d in self._dims)))
        self._world_size = int(np.prod(self._dims))
        self._rank2coord = dict(zip(range(self._world_size), self.coordinate))
        self._coord2rank = {c: r for r, c in self._rank2coord.items()}

    def get_hybrid_group_names(self):
        return self._parallel_names

    def get_dim(self, axis_name):
        return self._dims[self._parallel_names.index(axis_name)]

    get_dim_size = get_dim

    def world_size(self):
        return self._world_size

    def get_rank(self, **kwargs):
        coord = tuple(kwargs[name] for name in self._parallel_names)
        return self._coord2rank[coord]

    def get_coord(self, rank):
        return self._rank2coord[rank]

    def get_axis_list(self, axis_name, index):
        axis = self._parallel_names.index(axis_name)
        return [r for r, c in self._rank2coord.items() if c[axis] == index]

    def get_comm_list(self, axis_name):
        """All rank-lists that form comm groups along `axis_name`."""
        axis = self._parallel_names.index(axis_name)
        other_dims = [d for i, d in enumerate(self._dims) if i != axis]
        comm_list = []
        for other_coord in itertools.product(*(range(d)
                                               for d in other_dims)):
            ranks = []
            for i in range(self._dims[axis]):
                coord = list(other_coord)
                coord.insert(axis, i)
                ranks.append(self._coord2rank[tuple(coord)])
            comm_list.append(ranks)
        return comm_list


class HybridCommunicateGroup:
    """ref: topology.py:117 HybridCommunicateGroup.

    Builds the dp x pp x sharding x mp grid over the *devices visible to
    jax* (chips, not processes — the TPU-native twist) and exposes a
    jax Mesh for the engine plus Group handles for API parity.
    """

    def __init__(self, topology=None, dp_degree=1, mp_degree=1, pp_degree=1,
                 sharding_degree=1, order=None):
        ndev = jax.device_count()
        if topology is not None:
            self._topo = topology
            dp_degree = topology.get_dim("data")
            pp_degree = topology.get_dim("pipe")
            sharding_degree = topology.get_dim("sharding")
            mp_degree = topology.get_dim("model")
        else:
            self._topo = CommunicateTopology(
                ("data", "pipe", "sharding", "model"),
                (dp_degree, pp_degree, sharding_degree, mp_degree))
        self._dp_degree = dp_degree
        self._mp_degree = mp_degree
        self._pp_degree = pp_degree
        self._sharding_degree = sharding_degree

        total = dp_degree * mp_degree * pp_degree * sharding_degree
        if total > ndev:
            raise ValueError(
                f"hybrid degrees product {total} exceeds visible device "
                f"count {ndev}")
        # unused devices stay out of the mesh (mirrors world_size checks)
        devices = np.array(jax.devices()[:total]).reshape(
            dp_degree, pp_degree, sharding_degree, mp_degree)
        self._mesh = Mesh(devices, (DP_AXIS, PP_AXIS, SHARDING_AXIS,
                                    MP_AXIS))

        self.global_rank = get_rank()
        coord = self._topo.get_coord(self.global_rank % total)
        self._dp_rank = coord[0]
        self._pp_rank = coord[1]
        self._sharding_rank = coord[2]
        self._mp_rank = coord[3]

        axis_of = {"data": DP_AXIS, "pipe": PP_AXIS,
                   "sharding": SHARDING_AXIS, "model": MP_AXIS}
        self._dp_group = new_group(
            self._topo.get_comm_list("data")[0], axis_name=DP_AXIS)
        self._mp_group = new_group(
            self._topo.get_comm_list("model")[0], axis_name=MP_AXIS)
        self._pp_group = new_group(
            self._topo.get_comm_list("pipe")[0], axis_name=PP_AXIS)
        self._sharding_group = new_group(
            self._topo.get_comm_list("sharding")[0],
            axis_name=SHARDING_AXIS)
        self._axis_of = axis_of

    # -- mesh ----------------------------------------------------------------
    def get_mesh(self) -> Mesh:
        return self._mesh

    # -- parallel mode -------------------------------------------------------
    def _check_vpp(self):
        return False

    def get_parallel_mode(self):
        if self._pp_degree > 1:
            return ParallelMode.PIPELINE_PARALLEL
        if self._mp_degree > 1:
            return ParallelMode.TENSOR_PARALLEL
        if self._sharding_degree > 1:
            return ParallelMode.SHARDING_PARALLEL
        return ParallelMode.DATA_PARALLEL

    def topology(self):
        return self._topo

    def get_global_rank(self):
        return self.global_rank

    # data parallel
    def get_data_parallel_rank(self):
        return self._dp_rank

    def get_data_parallel_world_size(self):
        return self._dp_degree

    def get_data_parallel_group(self):
        return self._dp_group

    def get_data_parallel_group_src_rank(self):
        return self._dp_group.ranks[0]

    # model (tensor) parallel
    def get_model_parallel_rank(self):
        return self._mp_rank

    def get_model_parallel_world_size(self):
        return self._mp_degree

    def get_model_parallel_group(self):
        return self._mp_group

    def get_model_parallel_group_src_rank(self):
        return self._mp_group.ranks[0]

    # pipeline parallel
    def get_stage_id(self):
        return self._pp_rank

    def get_pipe_parallel_rank(self):
        return self._pp_rank

    def get_pipe_parallel_world_size(self):
        return self._pp_degree

    def get_pipe_parallel_group(self):
        return self._pp_group

    def is_first_stage(self):
        return self._pp_rank == 0

    def is_last_stage(self):
        return self._pp_rank == self._pp_degree - 1

    # sharding
    def get_sharding_parallel_rank(self):
        return self._sharding_rank

    def get_sharding_parallel_world_size(self):
        return self._sharding_degree

    def get_sharding_parallel_group(self):
        return self._sharding_group

    def get_sharding_parallel_group_src_rank(self):
        return self._sharding_group.ranks[0]

    def get_check_parallel_group(self, *a, **k):
        return self._dp_group

    def get_rank_from_stage(self, stage_id, **kwargs):
        return self._topo.get_rank(
            data=self._dp_rank, pipe=stage_id,
            sharding=self._sharding_rank, model=self._mp_rank)


_hcg = None


def set_hybrid_communicate_group(hcg):
    global _hcg
    _hcg = hcg


def get_hybrid_communicate_group():
    return _hcg
