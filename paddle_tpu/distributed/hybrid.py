"""Hybrid-parallel (dp x mp x pp x sharding) compiled training for
uniform-decoder transformers — the flagship path for the ladder's ERNIE
sharding and GPT-3 hybrid configs.

Ref parity: the composition the reference reaches with
HybridCommunicateGroup + PipelineLayer + 1F1B SectionWorker + megatron TP
layers + DygraphShardingOptimizer (python/paddle/distributed/fleet/
meta_parallel/*, paddle/fluid/framework/section_worker.cc). Here the whole
thing is ONE jitted XLA program:

- dp: global batch sharded over 'dp' (GSPMD inserts grad all-reduce)
- mp: megatron TP via Parameter.param_spec on qkv/mlp weights (GSPMD
  inserts the per-block all-reduces), vocab-sharded embedding + loss
- pp: transformer blocks stacked [L, ...] -> reshaped [S, L/S, ...],
  leading axis sharded over 'pp'; a scan+ppermute collective-permute
  pipeline (meta_parallel.pipeline_parallel.pipeline_spmd) runs the
  micro-batch schedule; jax AD produces the reverse pipeline
- sharding (ZeRO): optimizer moments sharded over the 'sharding' axis via
  out_shardings on the optimizer state tree
"""

from __future__ import annotations

import re
from collections import OrderedDict

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ..core.tensor import Tensor
from ..engine import _swap_state, _unwrap, param_specs
from ..framework import random as _random
from .topology import DP_AXIS, MP_AXIS, PP_AXIS, SHARDING_AXIS
from .fleet.meta_parallel.pipeline_parallel import pipeline_spmd


def split_uniform_params(layer, block_prefix_re):
    """Split state into (stacked block params, other params).

    block_prefix_re: regex with one group for the layer index, e.g.
    r"gpt\\.layers\\.(\\d+)\\.(.*)"  -> stacked under key group(2).
    Returns (stacked: dict name -> [L, ...] array, rest: dict, num_layers).
    """
    pat = re.compile(block_prefix_re)
    per_layer = {}
    rest = {}
    for name, t in layer.state_dict().items():
        m = pat.match(name)
        if m:
            idx, sub = int(m.group(1)), m.group(2)
            per_layer.setdefault(sub, {})[idx] = t._value
        else:
            rest[name] = t._value
    num_layers = 0
    stacked = {}
    for sub, by_idx in per_layer.items():
        num_layers = max(num_layers, max(by_idx) + 1)
        stacked[sub] = jnp.stack([by_idx[i] for i in sorted(by_idx)])
    return stacked, rest, num_layers


def _block_spec_map(template_block):
    """param name (relative to one block) -> PartitionSpec or None."""
    return param_specs(template_block)


class HybridParallelEngine:
    """Compiled hybrid training for GPT/ERNIE-style models.

    The model must expose: `embeddings_forward(values, ids, key)`,
    uniform `layers` (indexable), and `head_forward(values, h, labels,
    key)` -> scalar loss. Adapters below provide these for the nlp models.
    """

    def __init__(self, model, criterion, optimizer, hcg, *,
                 block_regex, template_block, embed_fn, head_fn,
                 accumulate_steps=1, zero_stage=0, offload=False):
        self.model = model
        self.criterion = criterion
        self.optimizer = optimizer
        self.hcg = hcg
        self.mesh = hcg.get_mesh()
        self.accumulate_steps = accumulate_steps
        self.zero_stage = zero_stage
        self.offload = offload
        self.block_regex = block_regex
        self.template_block = template_block
        self.embed_fn = embed_fn
        self.head_fn = head_fn

        stacked, rest, L = split_uniform_params(model, block_regex)
        self.num_layers = L
        S = hcg.get_pipe_parallel_world_size()
        assert L % S == 0, f"num_layers {L} % pp {S} != 0"
        self.pp = S
        self.layers_per_stage = L // S
        # [L, ...] -> [S, L/S, ...]
        self.block_params = {
            k: v.reshape((S, L // S) + v.shape[1:])
            for k, v in stacked.items()}
        # trainable vs frozen split of the rest
        specs = param_specs(model)
        self.rest_params = {
            k: v for k, v in rest.items() if k in specs}
        self.rest_buffers = {
            k: v for k, v in rest.items() if k not in specs}
        self._zero_warned = set()
        self.opt_state = {
            "blocks": {k: self.optimizer._init_state(v)
                       for k, v in self.block_params.items()},
            "rest": {k: self.optimizer._init_state(v)
                     for k, v in self.rest_params.items()},
        }
        self._step_fn = None
        self._offload_sh = None
        self._step_protos = None
        self._mem_analysis = None
        self._last_batch = None
        self._shardings = self._build_shardings(specs)

    # -- sharding specs ------------------------------------------------------
    def _block_leaf_spec(self, name, arr):
        bspecs = _block_spec_map(self.template_block)
        inner = bspecs.get(name)
        if inner is None:
            inner = P(*([None] * (arr.ndim - 2)))
        return P(PP_AXIS, None, *tuple(inner))

    def _opt_leaf_spec(self, pspec, arr, name=""):
        # moments follow the param sharding; scalars replicate
        if arr.ndim == 0:
            return P()
        if self.zero_stage >= 1 and self.mesh.shape.get(SHARDING_AXIS,
                                                        1) > 1:
            # shard the first non-pp dim over 'sharding' when divisible
            spec = list(pspec) if pspec is not None else \
                [None] * arr.ndim
            spec += [None] * (arr.ndim - len(spec))
            placed = False
            for i, s in enumerate(spec):
                if s is None and arr.shape[i] % \
                        self.mesh.shape[SHARDING_AXIS] == 0 and \
                        arr.shape[i] > 1:
                    spec[i] = SHARDING_AXIS
                    placed = True
                    break
            if not placed and all(s is None for s in spec) \
                    and arr.size >= self.mesh.shape[SHARDING_AXIS] \
                    and name not in self._zero_warned:
                # only a truly replicated state warrants the warning —
                # pp/mp-sharded leaves just have no free dim left; once
                # per param, across state leaves and grad retraces
                self._zero_warned.add(name)
                import warnings

                warnings.warn(
                    f"ZeRO: state/gradient for '{name}' (shape "
                    f"{arr.shape}) has no dim divisible by sharding "
                    f"degree {self.mesh.shape[SHARDING_AXIS]}; "
                    "replicating", stacklevel=3)
            return P(*spec)
        if pspec is not None:
            spec = list(pspec) + [None] * (arr.ndim - len(pspec))
            return P(*spec)
        return P(*([None] * arr.ndim))

    def _build_shardings(self, specs):
        mesh = self.mesh

        def ns(spec):
            return NamedSharding(mesh, spec)

        def param_spec_of(k, v, base):
            # ZeRO-3: shard the parameters themselves on a free divisible
            # dim (XLA all-gathers where full values are consumed)
            if self.zero_stage >= 3:
                return self._opt_leaf_spec(
                    tuple(base) if base is not None else None, v, name=k)
            return base if base is not None else P()

        block_sh = {
            k: ns(param_spec_of(k, v, self._block_leaf_spec(k, v)))
            for k, v in self.block_params.items()}
        rest_sh = {}
        for k, v in self.rest_params.items():
            rest_sh[k] = ns(param_spec_of(k, v, specs.get(k)))
        buf_sh = {k: ns(P()) for k in self.rest_buffers}
        opt_block_sh = {
            k: jax.tree.map(
                lambda a, kk=k: ns(self._opt_leaf_spec(
                    tuple(self._block_leaf_spec(kk,
                          self.block_params[kk])), a, name=kk)), st)
            for k, st in self.opt_state["blocks"].items()}
        opt_rest_sh = {
            k: jax.tree.map(
                lambda a, kk=k: ns(self._opt_leaf_spec(
                    specs.get(kk), a, name=kk)), st)
            for k, st in self.opt_state["rest"].items()}
        data_sh = ns(P(DP_AXIS))  # tokens [B, s]: batch dim over dp
        return dict(blocks=block_sh, rest=rest_sh, buffers=buf_sh,
                    opt=dict(blocks=opt_block_sh, rest=opt_rest_sh),
                    data=data_sh, repl=ns(P()))

    # -- the compiled step ---------------------------------------------------
    def _build(self):
        M = self.accumulate_steps
        S = self.pp
        Lps = self.layers_per_stage
        template = self.template_block
        embed_fn, head_fn = self.embed_fn, self.head_fn
        mesh = self.mesh
        opt = self.optimizer
        from ..incubate.asp import masks_for as _masks_for, \
            stacked_masks_for as _stacked_masks_for

        # stacked block params re-mask via [S, L/S, ...] stacked masks;
        # everything else (embeddings/head) by state-dict name
        _asp_block_masks, _asp_covered = _stacked_masks_for(
            self.model, self.block_regex, self.num_layers, S)
        _asp_rest_masks = {k: v for k, v in _masks_for(self.model).items()
                           if k not in _asp_covered}

        from ..core.config import no_tape
        from ..ops import overlap as _overlap
        from .fleet.utils.recompute import remat_wrapper

        # FLAGS_remat_policy: 'auto' keeps the scan's save-residuals
        # shape; full/dots_saveable rematerialize each block in backward
        remat = remat_wrapper(default="none")

        def run_block(h, kk, layer_params):
            with _random.rng_scope(kk):
                with no_tape(), _swap_state(template, layer_params):
                    out = template(Tensor(h))
            return out._value if isinstance(out, Tensor) else out

        def stage_fn(stage_params, x):
            # stage_params leaves: [Lps, ...]; scan the blocks
            def body(h, inp):
                layer_params, idx = inp
                # fold-in OUTSIDE the remat wrapper: the trace-level RNG
                # stream is consumed exactly once per block regardless
                # of policy (backward replays get the key as an arg)
                kk = jax.random.fold_in(_random.next_key(), idx)
                return remat(run_block)(h, kk, layer_params), None

            h, _ = jax.lax.scan(body, x,
                                (stage_params, jnp.arange(Lps)))
            return h

        # pp==1 needs no pipeline: the single stage runs on the merged
        # micro axis (exact — one stage, no bubbles), which also keeps
        # the step a plain GSPMD trace the overlap ring shard_map can
        # nest in under the old-jax compat shim
        pipeline = pipeline_spmd(stage_fn, mesh, num_stages=S,
                                 num_micro=M) if S > 1 else None

        # per-param decay/lr-mult constants (mirrors eager _preprocess);
        # block params take their meta from the template block's Parameter
        block_metas = opt.param_metas_for(self.block_params,
                                          template.state_dict())
        rest_metas = opt.param_metas_for(self.rest_params,
                                         self.model.state_dict())

        # mp collective-matmul overlap: active only when FLAGS_mp_overlap
        # (or the FORCE env) is on AND the mesh is pure dp x mp — the
        # region is a trace-time no-op otherwise
        seq_parallel = bool(getattr(template, "sequence_parallel", False))

        def loss_of(block_params, rest_params, buffers, batch, key):
            tokens, labels = batch
            with _random.rng_scope(key), _overlap.region(
                    mesh, sequence_parallel=seq_parallel):
                values = {**buffers, **rest_params}
                x = embed_fn(self.model, values, tokens)  # [B, s, h]
                b, s, h = x.shape
                if pipeline is not None:
                    x = x.reshape((M, b // M, s, h))
                    x = pipeline(block_params, x)
                    x = x.reshape((b, s, h))
                else:
                    x = stage_fn(jax.tree.map(lambda v: v[0],
                                              block_params), x)
                loss = head_fn(self.model, values, x, labels)
                return loss.astype(jnp.float32)

        # ZeRO-2: gradients constrained to the moment shardings — GSPMD
        # lowers the grad reductions into reduce-scatter over 'sharding'
        grad_constraint = None
        if self.zero_stage >= 2 and mesh.shape.get(SHARDING_AXIS, 1) > 1:
            specs_all = param_specs(self.model)

            def grad_constraint(gb, gr):
                gb = {k: jax.lax.with_sharding_constraint(
                    g, NamedSharding(mesh, self._opt_leaf_spec(
                        tuple(self._block_leaf_spec(k, g)), g, name=k)))
                    for k, g in gb.items()}
                gr = {k: jax.lax.with_sharding_constraint(
                    g, NamedSharding(mesh, self._opt_leaf_spec(
                        specs_all.get(k), g, name=k)))
                    for k, g in gr.items()}
                return gb, gr

        def step_fn(block_params, rest_params, buffers, opt_state, batch,
                    lr, key):
            from ..ops.fused_ops import gspmd_tracing

            with gspmd_tracing():  # meshed: attention partitions via cp
                return _step_impl(block_params, rest_params, buffers,
                                  opt_state, batch, lr, key)

        def _step_impl(block_params, rest_params, buffers, opt_state,
                       batch, lr, key):
            from .. import observe as _observe

            _observe.record_compile(
                "hybrid_step", signature=_observe.signature_of(batch))
            loss, (gb, gr) = jax.value_and_grad(
                loss_of, argnums=(0, 1))(block_params, rest_params,
                                         buffers, batch, key)
            if grad_constraint is not None:
                gb, gr = grad_constraint(gb, gr)
            gb = opt.decay_gradients_tree(block_params, gb, block_metas)
            gr = opt.decay_gradients_tree(rest_params, gr, rest_metas)
            gc = getattr(opt, "_grad_clip", None)
            if gc is not None:
                gb, gr = gc._clip_fn((gb, gr))
            nb, ob = opt.apply_gradients_tree(block_params, gb,
                                              opt_state["blocks"], lr,
                                              metas=block_metas)
            nr, orr = opt.apply_gradients_tree(rest_params, gr,
                                               opt_state["rest"], lr,
                                               metas=rest_metas)
            if _asp_block_masks or _asp_rest_masks:
                from ..incubate.asp import apply_masks_tree

                nb = apply_masks_tree(self.model, nb,
                                      engine_name="HybridParallelEngine",
                                      masks=_asp_block_masks)
                nr = apply_masks_tree(self.model, nr,
                                      engine_name="HybridParallelEngine",
                                      masks=_asp_rest_masks)
            # buffers pass through as an output so they can be donated:
            # every engine-state leaf is arg<->output aliased
            return loss, nb, nr, buffers, {"blocks": ob, "rest": orr}

        sh = self._shardings
        self._step_fn = jax.jit(
            step_fn,
            in_shardings=(sh["blocks"], sh["rest"], sh["buffers"],
                          sh["opt"], (sh["data"], sh["data"]),
                          sh["repl"], sh["repl"]),
            out_shardings=(sh["repl"], sh["blocks"], sh["rest"],
                           sh["buffers"], sh["opt"]),
            donate_argnums=(0, 1, 2, 3))
        # raw (unjitted) step for bench harnesses that re-jit it inside
        # a scan (bench_attrib._timed_scan_ms)
        self._step_fn._raw_step_fn = step_fn

    def train_batch(self, tokens, labels):
        if self._step_fn is None:
            self._build()
            if self.offload:
                # opt state rests in pinned host memory between steps
                # (ref sharding/offload_helper.py); initial state stays
                # on device — the first step would only round-trip it
                from ..engine import host_offload_shardings

                self._offload_sh = host_offload_shardings(
                    self.mesh, self._shardings["opt"])
        t = tokens._value if isinstance(tokens, Tensor) else \
            jnp.asarray(tokens)
        l = labels._value if isinstance(labels, Tensor) else \
            jnp.asarray(labels)
        self._last_batch = (t, l)
        key = _random.default_generator.next_key()
        lr = jnp.asarray(self.optimizer.get_lr(), jnp.float32)
        opt_state = self.opt_state
        if self._offload_sh is not None:
            opt_state = jax.device_put(opt_state, self._offload_sh[0])
        if self._step_protos is None:
            self._step_protos = jax.tree.map(
                lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype),
                (self.block_params, self.rest_params, self.rest_buffers,
                 opt_state, (t, l), lr, key))
            self._mem_analysis = None
        loss, self.block_params, self.rest_params, self.rest_buffers, \
            new_opt = self._step_fn(self.block_params, self.rest_params,
                                    self.rest_buffers, opt_state, (t, l),
                                    lr, key)
        if self._offload_sh is not None:
            new_opt = jax.device_put(new_opt, self._offload_sh[1])
        self.opt_state = new_opt
        return Tensor(loss)

    # -- step introspection --------------------------------------------------
    def schedule(self):
        """The ordered phase list of ONE compiled hybrid step — embed,
        the N transformer blocks, head, gradient reduction, optimizer —
        each with its per-phase sharding specs. Pure metadata built from
        the engine's sharding rules (no tracing, no device work), stable
        across rebuilds of the same configuration: the introspection
        hook the sharded serving engine starts from (ROADMAP item 1)."""
        sh = self._shardings
        block_specs = OrderedDict(
            (k, sh["blocks"][k].spec) for k in sorted(sh["blocks"]))
        embed = OrderedDict()
        head = OrderedDict()
        for k in sorted(self.rest_params):
            target = embed if "embedding" in k else head
            target[k] = sh["rest"][k].spec
        phases = [dict(name="embed", kind="embed", params=embed)]
        for i in range(self.num_layers):
            phases.append(dict(
                name=f"block{i}", kind="block",
                stage=i // self.layers_per_stage, params=block_specs))
        phases.append(dict(name="head", kind="head", params=head))
        reduce_axes = [DP_AXIS]
        if self.zero_stage >= 2 and \
                self.mesh.shape.get(SHARDING_AXIS, 1) > 1:
            reduce_axes.append(SHARDING_AXIS)
        phases.append(dict(name="grad-reduce", kind="collective",
                           axes=tuple(reduce_axes), params=OrderedDict()))
        opt_specs = OrderedDict()
        for group in ("blocks", "rest"):
            for k in sorted(sh["opt"][group]):
                opt_specs[f"{group}.{k}"] = jax.tree.map(
                    lambda s: s.spec, sh["opt"][group][k])
        phases.append(dict(name="opt", kind="opt", params=opt_specs))
        return phases

    def memory_analysis(self) -> dict:
        """MEASURED per-step device memory of the compiled hybrid step
        (same keys as Engine.memory_analysis; `alias` is the donated
        arg<->output reuse the donation audit asserts on)."""
        if self._step_fn is None or self._step_protos is None:
            raise RuntimeError("run train_batch() once first")
        if self._mem_analysis is None:
            from .. import observe as _observe

            with _observe.retrace.suppress():
                ma = self._step_fn.lower(*self._step_protos) \
                    .compile().memory_analysis()
            peak = getattr(ma, "peak_memory_in_bytes", 0) or (
                ma.argument_size_in_bytes + ma.temp_size_in_bytes
                + ma.output_size_in_bytes - ma.alias_size_in_bytes)
            self._mem_analysis = {
                "arguments": ma.argument_size_in_bytes,
                "temps": ma.temp_size_in_bytes,
                "outputs": ma.output_size_in_bytes,
                "alias": ma.alias_size_in_bytes,
                "generated_code": ma.generated_code_size_in_bytes,
                "peak": peak,
                "host_arguments": ma.host_argument_size_in_bytes,
                "host_temps": ma.host_temp_size_in_bytes,
                "host_outputs": ma.host_output_size_in_bytes,
            }
            _observe.annotate("hybrid_step", peak_bytes=peak)
        return dict(self._mem_analysis)

    def attribute_step(self, logdir=None, steps=1, top=10):
        """Capture an xplane trace of `steps` replays of the LAST
        train_batch shape and classify device time into the observe
        buckets. State is donated, so these are REAL steps."""
        if self._last_batch is None:
            raise RuntimeError("run train_batch() once first")
        import tempfile

        from .. import observe as _observe, profiler as _profiler

        if logdir is None:
            logdir = tempfile.mkdtemp(prefix="paddle-attrib-")
        tokens, labels = self._last_batch
        _profiler.start_trace(logdir)
        try:
            for _ in range(steps):
                self.train_batch(tokens, labels)
            jax.block_until_ready(self.rest_params)
        finally:
            _profiler.stop_trace()
        return _observe.attribute(logdir, top=top)

    def overlap_report(self, logdir=None, steps=1):
        """Capture a trace of `steps` real steps and pair the collective
        bucket against concurrently-resident matmul/attention time:
        returns observe.overlap_report's dict, whose headline
        `exposed_collective_frac` is the share of device time spent in
        collectives with NO compute in flight."""
        if self._last_batch is None:
            raise RuntimeError("run train_batch() once first")
        import tempfile

        from .. import observe as _observe, profiler as _profiler

        if logdir is None:
            logdir = tempfile.mkdtemp(prefix="paddle-overlap-")
        tokens, labels = self._last_batch
        _profiler.start_trace(logdir)
        try:
            for _ in range(steps):
                self.train_batch(tokens, labels)
            jax.block_until_ready(self.rest_params)
        finally:
            _profiler.stop_trace()
        return _observe.overlap_report(logdir)


# -- adapters for the nlp model family --------------------------------------


def values_sub(values, prefix):
    return {k[len(prefix):]: v for k, v in values.items()
            if k.startswith(prefix)}


def make_gpt_hybrid_engine(model, criterion, optimizer, hcg, *,
                           accumulate_steps=1, zero_stage=0,
                           offload=False):
    from ..engine import functional_call

    def embed_fn(m, values, tokens):
        return functional_call(m.gpt.embeddings,
                               values_sub(values, "gpt.embeddings."),
                               Tensor(tokens))

    def head_fn(m, values, h, labels):
        fn_values = values_sub(values, "gpt.final_norm.")
        h = functional_call(m.gpt.final_norm, fn_values, Tensor(h))
        # tied embedding logits: weight lives in the rest params
        w = values["gpt.embeddings.word_embeddings.weight"]
        from ..ops import lowp as _lowp

        if _lowp.mode() != "off":
            # dynamic scales: the hybrid per-block scan has no
            # delayed-scaling region (the ScaleState carry rides the
            # plain Engine only)
            hv = h._value if isinstance(h, Tensor) else h
            logits = _lowp.scaled_matmul(
                hv, w.T, qdtype=_lowp.mode(),
                out_dtype=jnp.result_type(hv, w))
        else:
            logits = jnp.matmul(h, w.T)
        loss = criterion(Tensor(logits), Tensor(labels))
        return loss._value if isinstance(loss, Tensor) else loss

    return HybridParallelEngine(
        model, criterion, optimizer, hcg,
        block_regex=r"gpt\.layers\.(\d+)\.(.*)",
        template_block=model.gpt.layers[0],
        embed_fn=embed_fn, head_fn=head_fn,
        accumulate_steps=accumulate_steps, zero_stage=zero_stage,
        offload=offload)


def make_ernie_hybrid_engine(model, criterion, optimizer, hcg, *,
                             accumulate_steps=1, zero_stage=0,
                             offload=False):
    """ERNIE pretraining (MLM-only in the hybrid path: NSP head needs the
    pooler over the full sequence, kept in the head_fn)."""
    from ..engine import functional_call

    def embed_fn(m, values, tokens):
        return functional_call(m.ernie.embeddings,
                               values_sub(values, "ernie.embeddings."),
                               Tensor(tokens))

    def head_fn(m, values, h, labels):
        pooled = functional_call(m.ernie.pooler,
                                 values_sub(values, "ernie.pooler."),
                                 Tensor(h))
        cls_vals = values_sub(values, "cls.")
        # the tied decoder weight dedups under the embedding's name in the
        # model-level state dict; re-route it to cls's local registry name
        cls_vals["_tied"] = values[
            "ernie.embeddings.word_embeddings.weight"]
        scores, rel = functional_call(
            m.cls, cls_vals, Tensor(h), Tensor(pooled))
        loss = criterion(Tensor(scores), Tensor(rel), Tensor(labels))
        return loss._value if isinstance(loss, Tensor) else loss

    return HybridParallelEngine(
        model, criterion, optimizer, hcg,
        block_regex=r"ernie\.encoder\.(\d+)\.(.*)",
        template_block=model.ernie.encoder[0],
        embed_fn=embed_fn, head_fn=head_fn,
        accumulate_steps=accumulate_steps, zero_stage=zero_stage,
        offload=offload)
