"""Elastic training: node registry, liveness watch, restart signalling.

Ref parity: python/paddle/distributed/fleet/elastic.py:99 (ElasticManager
registers nodes in etcd, watches peer liveness, signals RESTART/HOLD) and
distributed/elastic.py (the `python -m paddle.distributed.elastic` entry).
TPU-native mapping: the registry is a shared directory (NFS/GCS-fuse on a
pod; tmpdir in tests) of per-node heartbeat files — the etcd analogue
with no extra service; fault RECOVERY is checkpoint-based
(distributed.checkpoint.CheckpointManager), the manager only detects and
signals, exactly like the reference.
"""

from __future__ import annotations

import json
import os
import time

__all__ = ["ElasticStatus", "ElasticManager"]


class ElasticStatus:
    COMPLETED = "completed"
    ERROR = "error"
    HOLD = "hold"
    RESTART = "restart"
    EXIT = "exit"


class ElasticManager:
    """File-registry elastic manager.

    np can float between min_np and max_np (PADDLE_ELASTIC_NP semantics):
    - fewer live nodes than min_np        -> HOLD (wait for peers)
    - membership changed but >= min_np    -> RESTART (re-form the job)
    - stable membership                   -> HOLD steady state
    """

    def __init__(self, registry_dir, node_id=None, min_np=1, max_np=None,
                 heartbeat_interval=1.0, timeout=10.0):
        self.registry = os.path.abspath(registry_dir)
        os.makedirs(self.registry, exist_ok=True)
        self.node_id = node_id or f"node-{os.getpid()}"
        self.min_np = int(min_np)
        self.max_np = int(max_np) if max_np else None
        self.heartbeat_interval = heartbeat_interval
        self.timeout = timeout
        self._known = None

    def _path(self, node_id):
        return os.path.join(self.registry, f"{node_id}.beat")

    # -- registration / heartbeat (ref elastic.py:142-190) -------------------
    def register(self):
        self.beat()
        return self

    def beat(self, step=None):
        from ..framework import faults as _faults

        if _faults.fault_point("elastic.beat") is _faults.DROP:
            return  # injected heartbeat loss: peers see this node die
        rec = {"node": self.node_id, "ts": time.time()}
        if step is not None:
            # step-progress watermark: the gang supervisor's hang
            # detection reads this to tell "alive but stuck" from
            # "alive and advancing"
            rec["step"] = int(step)
        tmp = self._path(self.node_id) + ".tmp"
        with open(tmp, "w") as f:
            json.dump(rec, f)
        os.replace(tmp, self._path(self.node_id))

    def deregister(self):
        try:
            os.remove(self._path(self.node_id))
        except FileNotFoundError:
            pass

    # -- liveness ------------------------------------------------------------
    def live_nodes(self):
        now = time.time()
        live = []
        for name in os.listdir(self.registry):
            if not name.endswith(".beat"):
                continue
            p = os.path.join(self.registry, name)
            try:
                with open(p) as f:
                    rec = json.load(f)
            except (OSError, ValueError):
                continue
            age = now - rec.get("ts", 0)
            if age <= self.timeout:
                live.append(rec["node"])
            elif age > 3 * self.timeout:
                # sweep long-dead registrations so the registry dir does
                # not grow forever across job generations (a revived node
                # simply re-beats)
                try:
                    os.remove(p)
                except OSError:
                    pass
        return sorted(live)

    def records(self):
        """{node_id: beat record} for every parseable registration —
        the gang supervisor's raw view (liveness judgement is the
        caller's; torn/half-written files are simply skipped)."""
        out = {}
        for name in os.listdir(self.registry):
            if not name.endswith(".beat"):
                continue
            try:
                with open(os.path.join(self.registry, name)) as f:
                    rec = json.load(f)
            except (OSError, ValueError):
                continue
            out[rec.get("node", name[:-5])] = rec
        return out

    def watch(self):
        """One poll step -> ElasticStatus (ref watch loop elastic.py)."""
        from . import preempt as _preempt

        if _preempt.requested():
            # this node is being preempted: leave the registry so peers
            # observe a membership change and re-form without us
            self.deregister()
            return ElasticStatus.EXIT
        live = self.live_nodes()
        if len(live) < self.min_np:
            self._known = live
            return ElasticStatus.HOLD
        if self.max_np and len(live) > self.max_np:
            live = live[: self.max_np]
        if self._known is None:
            self._known = live
            return ElasticStatus.HOLD
        if live != self._known:
            self._known = live
            return ElasticStatus.RESTART
        return ElasticStatus.HOLD

    def world(self):
        """(rank, world_size) from the STABLE membership snapshotted by
        the last watch() poll — not a live re-read, which could flap
        rank/world between two polls mid-step while a peer's heartbeat
        expires (same max_np truncation the watcher applies; nodes beyond
        the cutoff get rank -1). Before the first poll, falls back to a
        live read."""
        live = self._known if self._known is not None else self.live_nodes()
        if self.max_np:
            live = live[: self.max_np]
        rank = live.index(self.node_id) if self.node_id in live else -1
        return rank, len(live)
