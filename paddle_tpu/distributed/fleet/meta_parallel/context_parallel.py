"""Context/sequence parallelism: ring attention + Ulysses all-to-all.

Net-new vs the reference (SURVEY §5: the reference has no long-context
story — verified zero hits for ring/ulysses/context-parallel). TPU-native
design over the mesh's sequence axis (topology.SEP_AXIS):

- ring_attention: q/k/v sharded on the sequence axis; K/V blocks rotate
  around the ring via `lax.ppermute` (ICI neighbour exchange) while each
  device folds one block per step into its running (o, lse) online-softmax
  accumulators — peak memory O(S/P), total traffic one K/V rotation.
  The per-step block attention is wrapped in `jax.checkpoint`, so jax AD
  yields the recomputing reverse ring (ring-attention backward) without a
  hand-written schedule.
- ulysses_attention: all-to-all swaps the sharded axis from sequence to
  heads, runs dense (flash) attention on full sequences locally, and
  swaps back — the alternative when head count >= ring size.

Both compare exactly (fwd + grads) against single-device flash attention
in tests/test_context_parallel.py.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ...topology import SEP_AXIS

_NEG_INF = -1e30


def _block_attn(q, k, v, q_off, k_off, scale, causal):
    """Block attention with GLOBAL position offsets -> (o, lse).

    q: [b, h, sq, d], k/v: [b, h, sk, d]; positions are q_off+i, k_off+j.
    Returns unnormalised-softmax output folded to (o, lse) form."""
    qf = q.astype(jnp.float32)
    kf = k.astype(jnp.float32)
    s = jnp.einsum("bhqd,bhkd->bhqk", qf, kf) * scale
    if causal:
        sq, sk = s.shape[-2], s.shape[-1]
        qi = lax.broadcasted_iota(jnp.int32, (sq, sk), 0) + q_off
        kj = lax.broadcasted_iota(jnp.int32, (sq, sk), 1) + k_off
        s = jnp.where(qi >= kj, s, _NEG_INF)
    m = jnp.max(s, axis=-1)
    # fully-masked rows: exp(NEG_INF - NEG_INF) would give ones
    valid = m > _NEG_INF / 2
    p = jnp.where(valid[..., None], jnp.exp(s - m[..., None]), 0.0)
    l = jnp.sum(p, axis=-1)
    # NORMALISED block output + its logsumexp — the (o, lse) pair _merge
    # combines with exp(lse_i - lse) weights
    o = jnp.einsum("bhqk,bhkd->bhqd", p / jnp.maximum(l, 1e-30)[..., None],
                   v.astype(jnp.float32))
    lse = jnp.where(valid, m + jnp.log(jnp.maximum(l, 1e-30)), _NEG_INF)
    return o, lse


def _merge(o1, lse1, o2, lse2):
    """Combine two partial online-softmax results."""
    lse = jnp.logaddexp(lse1, lse2)
    w1 = jnp.exp(lse1 - lse)[..., None]
    w2 = jnp.exp(lse2 - lse)[..., None]
    return o1 * w1 + o2 * w2, lse


def ring_attention(q, k, v, mesh, *, axis_name=SEP_AXIS, is_causal=False,
                   scale=None):
    """Ring attention over the mesh's sequence axis.

    q, k, v: [batch, heads, seq, head_dim] (global seq); returns the same
    shape. Sequence length must divide the ring size."""
    P_ring = mesh.shape[axis_name]
    b, h, s, d = q.shape
    if s % P_ring != 0:
        raise ValueError(f"seq {s} not divisible by ring size {P_ring}")
    sc = scale if scale is not None else 1.0 / math.sqrt(d)
    chunk = s // P_ring
    fwd_perm = [(i, (i + 1) % P_ring) for i in range(P_ring)]

    def per_device(ql, kl, vl):
        # ql/kl/vl: [b, h, chunk, d]; this device owns query block `me`
        me = lax.axis_index(axis_name)
        q_off = me * chunk

        @functools.partial(jax.checkpoint, policy=None)
        def block(ql, kb, vb, k_off):
            return _block_attn(ql, kb, vb, q_off, k_off, sc, is_causal)

        def step(carry, t):
            o, lse, kb, vb = carry
            # the K/V block currently held started at device (me - t)
            owner = (me - t) % P_ring
            bo, blse = block(ql, kb, vb, owner * chunk)
            o, lse = _merge(o, lse, bo, blse)
            kb = lax.ppermute(kb, axis_name, fwd_perm)
            vb = lax.ppermute(vb, axis_name, fwd_perm)
            return (o, lse, kb, vb), None

        o0 = jnp.zeros(ql.shape, jnp.float32)
        lse0 = jnp.full(ql.shape[:-1], _NEG_INF, jnp.float32)
        (o, lse, _, _), _ = lax.scan(
            step, (o0, lse0, kl, vl), jnp.arange(P_ring))
        return o.astype(q.dtype)

    sm = jax.shard_map(
        per_device, mesh=mesh,
        in_specs=(P(None, None, axis_name, None),) * 3,
        out_specs=P(None, None, axis_name, None),
        axis_names={axis_name},
        check_vma=False)
    return sm(q, k, v)


def ulysses_attention(q, k, v, mesh, *, axis_name=SEP_AXIS,
                      is_causal=False, scale=None):
    """Ulysses sequence parallelism: all-to-all seq<->heads, then dense
    flash attention on full sequences locally.

    Requires heads % ring_size == 0."""
    from ....ops.fused_ops import flash_attention

    P_ring = mesh.shape[axis_name]
    b, h, s, d = q.shape
    if h % P_ring != 0:
        raise ValueError(
            f"heads {h} not divisible by sequence-parallel size {P_ring}")
    sc = scale if scale is not None else 1.0 / math.sqrt(d)

    def per_device(ql, kl, vl):
        # [b, h, s/P, d] -> all-to-all -> [b, h/P, s, d]
        def to_heads(x):
            return lax.all_to_all(x, axis_name, split_axis=1,
                                  concat_axis=2, tiled=True)

        def to_seq(x):
            return lax.all_to_all(x, axis_name, split_axis=2,
                                  concat_axis=1, tiled=True)

        qh, kh, vh = to_heads(ql), to_heads(kl), to_heads(vl)
        oh = flash_attention(qh, kh, vh, is_causal=is_causal, scale=sc)
        return to_seq(oh)

    sm = jax.shard_map(
        per_device, mesh=mesh,
        in_specs=(P(None, None, axis_name, None),) * 3,
        out_specs=P(None, None, axis_name, None),
        axis_names={axis_name},
        check_vma=False)
    return sm(q, k, v)
