"""Megatron-style tensor-parallel layers.

Ref parity: python/paddle/distributed/fleet/meta_parallel/parallel_layers/
mp_layers.py:30,97,170,249 (VocabParallelEmbedding, ColumnParallelLinear,
RowParallelLinear, ParallelCrossEntropy) built on _c_identity /
_mp_allreduce / _c_lookup_table collective ops.

TPU-native design (GSPMD path): parameters keep their FULL logical shape
and carry a PartitionSpec over the 'mp' mesh axis (`Parameter.param_spec`).
Forward code is ordinary dense math plus `shard_hint` constraints; the XLA
SPMD partitioner inserts the all-reduces/all-gathers the reference issues
by hand — and overlaps them with compute. Eager single-process execution
is exact dense math (degree-1 behaviour).
"""

from __future__ import annotations

import jax
from jax.sharding import PartitionSpec as P

from ....core.dispatch import apply
from ....core.tensor import Tensor
from ....nn import functional as F
from ....nn import initializer as I
from ....nn.layer.layers import Layer
from ...topology import MP_AXIS, get_hybrid_communicate_group


def _mesh():
    hcg = get_hybrid_communicate_group()
    return hcg.get_mesh() if hcg is not None else None


def shard_hint(x, *spec):
    """with_sharding_constraint when tracing on a mesh; no-op eagerly."""
    mesh = _mesh()
    if mesh is None:
        return x
    v = x._value if isinstance(x, Tensor) else x
    if not isinstance(v, jax.core.Tracer):
        return x
    from jax.sharding import NamedSharding

    # inside shard_map (e.g. the pipeline's manual 'pp' region) the trace
    # carries an abstract mesh; constraints must be built on it
    try:
        am = jax.sharding.get_abstract_mesh()
        if am is not None and am.axis_names:
            mesh = am
    except (AttributeError, RuntimeError):
        pass
    if getattr(jax.shard_map, "__paddle_tpu_compat__", False):
        # old-jax compat shard_map runs fully manual (trivial axes are
        # promoted), so a hint naming a manual axis is rejected at
        # lowering; it would constrain a size-1 axis — a no-op — so
        # dropping it is exact
        try:
            from jax._src import core as _core

            manual = set(_core.get_axis_env().axis_sizes)
        except (AttributeError, ImportError):
            manual = set()
        if manual:
            named = set()
            for part in spec:
                if part is None:
                    continue
                named.update(part if isinstance(part, tuple) else (part,))
            if named & manual:
                return x
    constrained = jax.lax.with_sharding_constraint(
        v, NamedSharding(mesh, P(*spec)))
    if isinstance(x, Tensor):
        out = Tensor(constrained)
        out.stop_gradient = x.stop_gradient
        out._tape = x._tape
        return out
    return constrained


class VocabParallelEmbedding(Layer):
    """Embedding with the vocab axis sharded over 'mp'
    (ref: mp_layers.py:30)."""

    def __init__(self, num_embeddings, embedding_dim, weight_attr=None,
                 mp_group=None, name=None):
        super().__init__()
        self.num_embeddings = num_embeddings
        self.embedding_dim = embedding_dim
        self.weight = self.create_parameter(
            shape=[num_embeddings, embedding_dim], attr=weight_attr,
            default_initializer=I.XavierNormal())
        self.weight.param_spec = P(MP_AXIS, None)
        self.weight.is_distributed = True

    def forward(self, x):
        out = F.embedding(x, self.weight)
        return shard_hint(out, None, None, None)


class ColumnParallelLinear(Layer):
    """Linear with out_features sharded over 'mp' (ref: mp_layers.py:97)."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=None, gather_output=True, fuse_matmul_bias=False,
                 mp_group=None, name=None):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.gather_output = gather_output
        self.weight = self.create_parameter(
            shape=[in_features, out_features], attr=weight_attr,
            default_initializer=I.XavierNormal())
        self.weight.param_spec = P(None, MP_AXIS)
        self.weight.is_distributed = True
        if has_bias or has_bias is None:
            self.bias = self.create_parameter(
                shape=[out_features], attr=None, is_bias=True)
            self.bias.param_spec = P(MP_AXIS)
            self.bias.is_distributed = True
        else:
            self.bias = None

    def forward(self, x):
        if not self.gather_output:
            # latency-hiding path: the SP seq all-gather decomposes into
            # ring hops hidden behind per-chunk partial matmuls
            from ....ops import overlap as _overlap

            out = _overlap.maybe_column_parallel(x, self.weight)
            if out is not None:
                if self.bias is not None:
                    out = out + self.bias
                return shard_hint(out, *([None] * (out.ndim - 1)), MP_AXIS)
        out = F.linear(x, self.weight, self.bias)
        if self.gather_output:
            return shard_hint(out, *([None] * out.ndim))
        # keep the hidden axis sharded: activations stay model-parallel
        return shard_hint(out, *([None] * (out.ndim - 1)), MP_AXIS)


class RowParallelLinear(Layer):
    """Linear with in_features sharded over 'mp'; output needs the partial
    -sum reduction, which XLA emits from the contraction sharding
    (ref: mp_layers.py:170)."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, input_is_parallel=False,
                 fuse_matmul_bias=False, mp_group=None, name=None):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.input_is_parallel = input_is_parallel
        self.weight = self.create_parameter(
            shape=[in_features, out_features], attr=weight_attr,
            default_initializer=I.XavierNormal())
        self.weight.param_spec = P(MP_AXIS, None)
        self.weight.is_distributed = True
        if has_bias:
            self.bias = self.create_parameter(
                shape=[out_features], attr=None, is_bias=True)
        else:
            self.bias = None

    def forward(self, x):
        if self.input_is_parallel:
            x = shard_hint(x, *([None] * (x.ndim - 1)), MP_AXIS)
        # latency-hiding path: the mp all-reduce (or SP reduce-scatter)
        # decomposes into ring hops hidden behind partial matmuls; the
        # shard_map output already carries its final sharding, so no
        # forcing hint is needed
        from ....ops import overlap as _overlap

        out = _overlap.maybe_row_parallel(x, self.weight)
        if out is None:
            # F.linear (not raw matmul_v2) so the FLAGS_lowp_matmul
            # route applies to the GSPMD row-parallel path too
            out = F.linear(x, self.weight)
            out = shard_hint(out, *([None] * out.ndim))  # forces all-reduce
        if self.bias is not None:
            out = out + self.bias
        return out


class ParallelCrossEntropy(Layer):
    """Vocab-sharded softmax cross-entropy (ref: mp_layers.py:249 over
    c_softmax_with_cross_entropy). With GSPMD the logits stay sharded on
    the class axis and XLA partitions the log-sum-exp reduction."""

    def __init__(self, mp_group=None, name=None, ignore_index=-100):
        super().__init__()
        self.ignore_index = ignore_index

    def forward(self, input, label):
        input = shard_hint(input, *([None] * (input.ndim - 1)), MP_AXIS)
        loss, _ = apply("softmax_with_cross_entropy", input, label,
                        soft_label=False, axis=-1,
                        ignore_index=self.ignore_index)
        return loss


def parallel_linear_split(x, size, operation, axis=0, num_partitions=1,
                          gather_out=True, weight_attr=None, bias_attr=None):
    """paddle.distributed.split (ref: distributed/collective.py:1283)."""
    if operation == "linear":
        if axis == 0:
            layer = RowParallelLinear(size[0], size[1], weight_attr,
                                      has_bias=bias_attr is not False)
        else:
            layer = ColumnParallelLinear(size[0], size[1], weight_attr,
                                         has_bias=bias_attr is not False,
                                         gather_output=gather_out)
        return layer(x)
    if operation == "embedding":
        layer = VocabParallelEmbedding(size[0], size[1], weight_attr)
        return layer(x)
    raise ValueError(f"unsupported split operation {operation!r}")
