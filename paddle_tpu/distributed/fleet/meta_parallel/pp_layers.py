"""Pipeline layer description & segmentation.

Ref parity: python/paddle/distributed/fleet/meta_parallel/parallel_layers/
pp_layers.py:76,202 (LayerDesc, SharedLayerDesc, PipelineLayer). In the
reference each rank materialises only its stage; on TPU one process owns
all local chips, so PipelineLayer builds every stage and records the
stage partition — the pipeline engine places stage s's parameters on mesh
slice pp=s via GSPMD specs / stacked shard_map leaves.
"""

from __future__ import annotations

from ....nn.layer.container import LayerList
from ....nn.layer.layers import Layer


class LayerDesc:
    def __init__(self, layer_func, *inputs, **kwargs):
        self.layer_func = layer_func
        self.inputs = inputs
        self.kwargs = kwargs
        if not issubclass(layer_func, Layer):
            raise TypeError("LayerDesc expects an nn.Layer subclass")

    def build_layer(self):
        return self.layer_func(*self.inputs, **self.kwargs)

    def __repr__(self):
        return f"LayerDesc({self.layer_func.__name__})"


class SharedLayerDesc(LayerDesc):
    def __init__(self, key, layer_func, forward_func=None,
                 shared_weight_attr="weight", *inputs, **kwargs):
        super().__init__(layer_func, *inputs, **kwargs)
        self.layer_name = key
        self.forward_func = forward_func
        self.shared_weight_attr = shared_weight_attr


class PipelineLayer(Layer):
    """ref: pp_layers.py:202 PipelineLayer."""

    def __init__(self, layers, num_stages=None, topology=None,
                 loss_fn=None, seg_method="uniform", recompute_interval=0,
                 recompute_ctx=None, num_virtual_pipeline_stages=None):
        super().__init__()
        self._loss_fn = loss_fn
        self._topo = topology
        if num_stages is None and topology is not None:
            num_stages = topology.get_dim("pipe")
        self._num_stages = num_stages or 1
        self._seg_method = seg_method
        self._recompute_interval = recompute_interval

        descs = list(layers)
        built = []
        self._shared = {}
        for d in descs:
            if isinstance(d, SharedLayerDesc):
                if d.layer_name in self._shared:
                    base = self._shared[d.layer_name]
                    built.append(_SharedRef(base, d.forward_func))
                else:
                    layer = d.build_layer()
                    self._shared[d.layer_name] = layer
                    built.append(layer)
            elif isinstance(d, LayerDesc):
                built.append(d.build_layer())
            elif isinstance(d, Layer):
                built.append(d)
            elif callable(d):
                built.append(_FuncLayer(d))
            else:
                raise TypeError(f"bad pipeline entry {d!r}")
        self.run_function = LayerList(built)
        self._segment()

    def _segment(self):
        n = len(self.run_function)
        s = self._num_stages
        base, rem = divmod(n, s)
        bounds = [0]
        for i in range(s):
            bounds.append(bounds[-1] + base + (1 if i < rem else 0))
        self.segment_parts = bounds

    def get_stage_layers(self, stage_id):
        lo, hi = self.segment_parts[stage_id], self.segment_parts[stage_id + 1]
        return [self.run_function[i] for i in range(lo, hi)]

    def stage_of_layer(self, idx):
        for s in range(self._num_stages):
            if self.segment_parts[s] <= idx < self.segment_parts[s + 1]:
                return s
        return self._num_stages - 1

    def forward(self, x):
        for layer in self.run_function:
            x = layer(x)
        return x

    @property
    def num_stages(self):
        return self._num_stages

    def loss_fn(self, *args):
        return self._loss_fn(*args)


class _FuncLayer(Layer):
    def __init__(self, fn):
        super().__init__()
        self._fn = fn

    def forward(self, *args):
        return self._fn(*args)


class _SharedRef(Layer):
    """Second occurrence of a SharedLayerDesc: same parameters, optional
    alternate forward (e.g. tied embedding -> logits)."""

    def __init__(self, base, forward_func=None):
        super().__init__()
        self._base = [base]  # hide from sublayer registry (no double count)
        self._forward_func = forward_func

    def forward(self, *args):
        base = self._base[0]
        if self._forward_func is not None:
            return self._forward_func(base, *args)
        return base(*args)
