"""Pipeline-parallel execution.

Ref parity: python/paddle/distributed/fleet/meta_parallel/
pipeline_parallel.py:32,114,382-535 (micro-batch loop with p2p
activation/grad exchange) and the 1F1B schedule of
paddle/fluid/framework/section_worker.cc:104-180.

TPU-native design: there is no interpreter to run per-stage programs and no
eager p2p. The whole schedule is ONE compiled XLA program:

- stage parameters are stacked on a leading [pp] axis and sharded over the
  mesh's 'pp' axis (each device slice holds its stage's weights);
- the micro-batch loop is a `lax.scan` (soft pipelining: iteration t
  advances every stage by one micro-batch);
- stage-to-stage transfer is `lax.ppermute` over 'pp' — XLA lowers it to
  ICI collective-permute and overlaps it with compute;
- the backward schedule needs no code: jax AD differentiates scan+ppermute
  into the reverse pipeline (grad of ppermute is the inverse permute),
  giving a GPipe/1F1B-equivalent compiled schedule;
- gradient accumulation across micro-batches falls out of the scan's sum.

This requires stage-uniform bodies (same jaxpr per stage) — true for the
transformer ladder configs; heterogeneous embedding/head run outside the
shard_map under plain GSPMD.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ....core.tensor import Tensor
from ...topology import PP_AXIS


def pipeline_spmd(stage_fn, mesh, *, num_stages, num_micro):
    """Wrap `stage_fn(stage_params, x) -> y` into a full-pipeline function
    `(stacked_params, microbatches) -> outputs`.

    stacked_params: pytree whose leaves have leading dim [num_stages]
    microbatches:   [num_micro, micro_batch, ...]
    outputs:        [num_micro, micro_batch, ...] (from the last stage)
    """
    perm = [(i, (i + 1) % num_stages) for i in range(num_stages)]

    def per_device(params, x_mb):
        # inside shard_map over 'pp': params leaves are [1, ...] (this
        # stage's slice), x_mb is the full micro-batch stream (replicated
        # along pp)
        stage = jax.lax.axis_index(PP_AXIS)
        local = jax.tree.map(lambda p: p[0], params)
        mbs = x_mb.shape[0]
        total = num_micro + num_stages - 1

        carry_buf = jnp.zeros_like(x_mb[0])
        outputs = jnp.zeros_like(x_mb)

        def tick(carry, t):
            state, outs = carry
            # stage 0 consumes micro-batch t (clamped; masked later)
            idx = jnp.clip(t, 0, num_micro - 1)
            inp = jnp.where(stage == 0, x_mb[idx], state)
            out = stage_fn(local, inp)
            # last stage emits micro-batch t-(S-1)
            emit_t = t - (num_stages - 1)
            valid = (emit_t >= 0) & (emit_t <= num_micro - 1)
            eidx = jnp.clip(emit_t, 0, num_micro - 1)
            outs = jnp.where(
                valid & (stage == num_stages - 1),
                outs.at[eidx].set(out), outs)
            nxt = jax.lax.ppermute(out, PP_AXIS, perm)
            return (nxt, outs), None

        (_, outputs), _ = jax.lax.scan(
            tick, (carry_buf, outputs), jnp.arange(total))
        # bring the last stage's outputs to every pp slice (grads flow back
        # through the psum's transpose)
        outputs = jax.lax.psum(
            jnp.where(stage == num_stages - 1, outputs, 0.0), PP_AXIS)
        return outputs

    # manual only over 'pp': dp/mp/sharding stay GSPMD-auto inside the
    # stage body, so TP sharding constraints and batch sharding compose
    return jax.shard_map(
        per_device, mesh=mesh,
        in_specs=(P(PP_AXIS), P()),
        out_specs=P(),
        axis_names={PP_AXIS},
        check_vma=False)


class PipelineParallel:
    """Dygraph-style wrapper driving the compiled pipeline
    (ref: meta_parallel/pipeline_parallel.py:32 PipelineParallel)."""

    def __init__(self, layers, hcg, strategy):
        self._layers = layers
        self._hcg = hcg
        self._strategy = strategy
        cfg = strategy.pipeline_configs
        self.micro_batch_size = cfg["micro_batch_size"]
        self.accumulate_steps = cfg["accumulate_steps"]
        self.num_stages = hcg.get_pipe_parallel_world_size()
        self._engine = None

    def parameters(self):
        return self._layers.parameters()

    def state_dict(self, *a, **k):
        return self._layers.state_dict(*a, **k)

    def set_state_dict(self, sd, *a, **k):
        return self._layers.set_state_dict(sd, *a, **k)

    def train_batch(self, data, optimizer, lr_scheduler=None, scaler=None):
        """Run one global batch = accumulate_steps micro-batches through
        the compiled pipeline + optimizer update. For stage-uniform
        PipelineLayers this uses the scan/ppermute schedule; otherwise it
        falls back to sequential GSPMD placement (still one XLA program,
        stages laid out over 'pp')."""
        from ...pp_engine import PipelineEngine

        if self._engine is None:
            self._engine = PipelineEngine(
                self._layers, optimizer, self._hcg,
                micro_batch_size=self.micro_batch_size,
                accumulate_steps=self.accumulate_steps)
        inputs, labels = data
        loss = self._engine.train_batch(inputs, labels)
        if lr_scheduler is not None:
            lr_scheduler.step()
        return loss

    def eval_batch(self, data, compute_loss=True):
        inputs, labels = data
        out = self._layers(inputs if isinstance(inputs, Tensor)
                           else Tensor(inputs))
        if compute_loss and self._layers._loss_fn is not None:
            return self._layers._loss_fn(
                out, labels if isinstance(labels, Tensor)
                else Tensor(labels))
        return out
