"""Pipeline-parallel execution.

Ref parity: python/paddle/distributed/fleet/meta_parallel/
pipeline_parallel.py:32,114,382-535 (micro-batch loop with p2p
activation/grad exchange) and the 1F1B schedule of
paddle/fluid/framework/section_worker.cc:104-180.

TPU-native design: there is no interpreter to run per-stage programs and no
eager p2p. The whole schedule is ONE compiled XLA program:

- stage parameters are stacked on a leading [pp] axis and sharded over the
  mesh's 'pp' axis (each device slice holds its stage's weights);
- the micro-batch loop is a `lax.scan` (soft pipelining: iteration t
  advances every stage by one micro-batch);
- stage-to-stage transfer is `lax.ppermute` over 'pp' — XLA lowers it to
  ICI collective-permute and overlaps it with compute;
- the backward schedule needs no code: jax AD differentiates scan+ppermute
  into the reverse pipeline (grad of ppermute is the inverse permute),
  giving a GPipe/1F1B-equivalent compiled schedule;
- gradient accumulation across micro-batches falls out of the scan's sum.

This requires stage-uniform bodies (same jaxpr per stage) — true for the
transformer ladder configs; heterogeneous embedding/head run outside the
shard_map under plain GSPMD.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ....core.tensor import Tensor
from ...topology import PP_AXIS


def pipeline_spmd(stage_fn, mesh, *, num_stages, num_micro):
    """Wrap `stage_fn(stage_params, x) -> y` into a full-pipeline function
    `(stacked_params, microbatches) -> outputs`.

    stacked_params: pytree whose leaves have leading dim [num_stages]
    microbatches:   [num_micro, micro_batch, ...]
    outputs:        [num_micro, micro_batch, ...] (from the last stage)

    NON-UNIFORM stages (ref pp_layers.py:76 SharedLayerDesc / custom
    segmentation): pass a LIST of `num_stages` callables instead of one
    `stage_fn` — stage s runs `stage_fns[s]` via `lax.switch` on the pp
    axis index (XLA executes only the taken branch per device).  Two
    contracts: every stage maps the same activation shape to the same
    activation shape (the ring carries one layout), and per-stage
    weights that do not fit the uniform stacked-params tree are closed
    over (as traced values, so AD still reaches them) or left in GSPMD
    land outside the shard_map.  Weight TYING across stages (GPT-2
    embedding/head) needs no machinery at all in this design: tied
    weights live once in the non-pipelined params and jax AD sums their
    gradient contributions from every use site — see
    hybrid.make_gpt_hybrid_engine.

    Memory schedule (the 1F1B working-set analogue,
    ref section_worker.cc:134-180): the micro-batch stream is SHARDED over
    'pp' (device s holds micro-batches {j*S+s}, L = M/S each) instead of
    replicated, and per-tick traffic is three [micro]-sized ppermutes:

    - an input ring rotating toward stage 0: every S ticks each device
      injects its next local micro-batch; after k shifts the batch due at
      tick t arrives at stage 0 exactly at tick t;
    - the activation carry (stage s -> s+1), as before;
    - an output ring rotating away from the last stage: finished
      micro-batches travel back to their owner device, which captures
      them at tick j*S + 2*s + S (last stage captures its own directly).

    Per-device stream memory drops from 2*M to 2*M/S micro-batches and the
    old O(M x batch) psum broadcast of outputs disappears entirely.
    """
    S, M = num_stages, num_micro
    # pad the stream to a multiple of S: the ring schedule needs equal
    # local shares; padded micro-batches compute garbage that is sliced
    # off the outputs (and therefore carries no gradient)
    L = -(-M // S)
    M_pad = L * S
    fwd = [(i, (i + 1) % S) for i in range(S)]
    back = [(i, (i - 1) % S) for i in range(S)]

    if callable(stage_fn):
        def apply_stage(stage, local, inp):
            return stage_fn(local, inp)
    else:
        fns = list(stage_fn)
        if len(fns) != S:
            raise ValueError(
                f"stage_fns has {len(fns)} entries for {S} stages")

        def apply_stage(stage, local, inp):
            return jax.lax.switch(
                stage, [lambda l, x, f=f: f(l, x) for f in fns],
                local, inp)

    def per_device(params, x_local):
        # inside shard_map over 'pp': params leaves are [1, ...] (this
        # stage's slice), x_local is [L, micro, ...] (this device's strided
        # share of the stream: micro-batches j*S + stage)
        stage = jax.lax.axis_index(PP_AXIS)
        local = jax.tree.map(lambda p: p[0], params)
        total = M_pad + 2 * S - 2 if S > 1 else M_pad

        zero_mb = jnp.zeros_like(x_local[0])
        outs0 = jnp.zeros_like(x_local)

        def tick(carry, u):
            act, iring, oring, outs = carry
            # 1) input injection: at ticks u = j*S every device loads its
            # j-th local micro-batch into the input ring
            jj = u // S
            inject = (u % S == 0) & (jj < L)
            iring = jnp.where(inject, x_local[jnp.clip(jj, 0, L - 1)],
                              iring)
            # 2) owner capture from the output ring (stages < S-1): the
            # batch finished at tick t = j*S+s+S-1 arrives after s+1
            # shifts, i.e. at tick j*S + 2s + S
            num = u - 2 * stage - S
            jcap = num // S
            cap = (stage < S - 1) & (num >= 0) & (num % S == 0) \
                & (jcap < L)
            outs = jnp.where(
                cap, outs.at[jnp.clip(jcap, 0, L - 1)].set(oring), outs)
            # 3) stage compute (stage 0 eats the input ring)
            inp = jnp.where(stage == 0, iring, act)
            out = apply_stage(stage, local, inp)
            # 4) last stage: emit into the output ring; micro-batches it
            # owns itself (t % S == S-1) are stored directly
            t = u - (S - 1)
            emitting = (stage == S - 1) & (t >= 0) & (t < M_pad)
            own = emitting & (t % S == S - 1)
            outs = jnp.where(
                own, outs.at[jnp.clip(t // S, 0, L - 1)].set(out), outs)
            oring = jnp.where(emitting, out, oring)
            # 5) ring shifts
            act = jax.lax.ppermute(out, PP_AXIS, fwd)
            iring = jax.lax.ppermute(iring, PP_AXIS, back)
            oring = jax.lax.ppermute(oring, PP_AXIS, fwd)
            return (act, iring, oring, outs), None

        (_, _, _, outs), _ = jax.lax.scan(
            tick, (zero_mb, zero_mb, zero_mb, outs0), jnp.arange(total))
        return outs

    # manual only over 'pp': dp/mp/sharding stay GSPMD-auto inside the
    # stage body, so TP sharding constraints and batch sharding compose
    sm = jax.shard_map(
        per_device, mesh=mesh,
        in_specs=(P(PP_AXIS), P(PP_AXIS)),
        out_specs=P(PP_AXIS),
        axis_names={PP_AXIS},
        check_vma=False)

    def run(params, x):
        # strided re-layout so device s's contiguous block holds
        # micro-batches {j*S+s}; inverse applied to the outputs
        tail = x.shape[1:]
        if M_pad != M:
            pad = jnp.zeros((M_pad - M,) + tail, x.dtype)
            x = jnp.concatenate([x, pad], axis=0)
        xs = x.reshape((L, S) + tail).swapaxes(0, 1).reshape(
            (M_pad,) + tail)
        y = sm(params, xs)
        y = y.reshape((S, L) + tail).swapaxes(0, 1).reshape(
            (M_pad,) + tail)
        return y[:M]

    return run


def pack_stage_rows(stage_trees):
    """Ragged per-stage parameter placement (ref section_worker.cc —
    each rank materialises only its stage): pack a list of S pytrees
    with DIFFERENT structures into one [S, Pmax] f32 buffer whose row s
    is stage s's flattened leaves (zero padded to the largest stage).
    Sharded P('pp'), per-device parameter memory is max_s |params_s| —
    true placement, not replication.

    Returns (rows, unpack, pack) where unpack(s, row) rebuilds stage
    s's pytree from its [Pmax] row (static slicing, so it traces inside
    a lax.switch branch) and pack(trees) re-packs updated pytrees."""
    import numpy as np

    metas = []
    for tree in stage_trees:
        leaves, treedef = jax.tree.flatten(tree)
        info, off = [], 0
        for leaf in leaves:
            size = int(np.prod(leaf.shape)) if leaf.shape else 1
            info.append((tuple(leaf.shape), leaf.dtype, off, size))
            off += size
        metas.append((treedef, info, off))
    pmax = max([m[2] for m in metas] + [1])

    def pack(trees):
        rows = []
        for tree, (treedef, info, tot) in zip(trees, metas):
            leaves = jax.tree.leaves(tree)
            if leaves:
                row = jnp.concatenate(
                    [jnp.ravel(l).astype(jnp.float32) for l in leaves])
            else:
                row = jnp.zeros((0,), jnp.float32)
            rows.append(jnp.pad(row, (0, pmax - row.shape[0])))
        return jnp.stack(rows)

    def unpack(stage, row):
        treedef, info, _ = metas[stage]
        leaves = [row[off:off + size].reshape(shape).astype(dtype)
                  for (shape, dtype, off, size) in info]
        return jax.tree.unflatten(treedef, leaves)

    return pack(stage_trees), unpack, pack


def pipeline_spmd_hetero(stage_fns, mesh, *, num_stages, num_micro,
                         unpack, act_proto, out_proto, has_extra=False):
    """Heterogeneous-stage compiled pipeline (VERDICT r4 item 4; ref
    section_worker.cc:104-180 F-then-B/1F1B over arbitrary per-stage
    programs).

    Removes pipeline_spmd's two uniformity constraints:
    - per-stage PROGRAMS and PARAMETER STRUCTURES differ (embedding
      stage != block stage != head stage): stage s's params arrive as
      row s of a pack_stage_rows buffer sharded over 'pp', and stage
      bodies run under lax.switch;
    - boundary SHAPES differ: three ring buffers carry the injected
      input (x micro-batch shape), the inter-stage activation
      (act_proto), and the final output (out_proto) independently.

    Contracts: stage_fns[0](params, shared, x_mb) -> act;
    stage_fns[s](params, shared, act) -> act for 0 < s < S-1;
    stage_fns[-1](params, shared, act[, extra_mb]) -> out.  The
    inter-stage activation is ONE array of a single shape (the ring's
    layout) — that is the remaining contract, matching the reference's
    single boundary tensor between sections.

    Returns run(rows, shared, x, extra=None, key=None) -> [M, *out]."""
    S, M = num_stages, num_micro
    L = -(-M // S)
    M_pad = L * S
    fwd = [(i, (i + 1) % S) for i in range(S)]
    back = [(i, (i - 1) % S) for i in range(S)]
    fns = list(stage_fns)
    if len(fns) != S:
        raise ValueError(f"stage_fns has {len(fns)} entries for {S} stages")
    act_shape = tuple(act_proto.shape)
    act_dtype = act_proto.dtype
    out_shape = tuple(out_proto.shape)
    out_dtype = out_proto.dtype

    from ....framework import random as _random

    def per_device(rows, shared, x_local, extra, key):
        stage = jax.lax.axis_index(PP_AXIS)
        row = rows[0]                      # this device's stage row
        total = M_pad + 2 * S - 2 if S > 1 else M_pad

        zero_in = jnp.zeros_like(x_local[0])
        zero_act = jnp.zeros(act_shape, act_dtype)
        zero_out = jnp.zeros(out_shape, out_dtype)
        outs0 = jnp.zeros((L,) + out_shape, out_dtype)

        def branch_fn(s):
            def go(row, shared, iring, act, extra_mb, k):
                with _random.rng_scope(k):
                    local = unpack(s, row)
                    if s == 0:
                        a = fns[0](local, shared, iring)
                        return (a.astype(act_dtype), zero_out)
                    if s < S - 1:
                        a = fns[s](local, shared, act)
                        return (a.astype(act_dtype), zero_out)
                    args = (local, shared, act) + (
                        (extra_mb,) if has_extra else ())
                    o = fns[s](*args)
                    return (zero_act, jnp.asarray(o, out_dtype))
            return go

        branches = [branch_fn(s) for s in range(S)]

        def tick(carry, u):
            act, iring, oring, outs = carry
            jj = u // S
            inject = (u % S == 0) & (jj < L)
            iring = jnp.where(inject, x_local[jnp.clip(jj, 0, L - 1)],
                              iring)
            num = u - 2 * stage - S
            jcap = num // S
            cap = (stage < S - 1) & (num >= 0) & (num % S == 0) \
                & (jcap < L)
            outs = jnp.where(
                cap, outs.at[jnp.clip(jcap, 0, L - 1)].set(oring), outs)
            # stream slot finished by the last stage at this tick
            t = u - (S - 1)
            if has_extra:
                extra_mb = extra[jnp.clip(t, 0, M_pad - 1)]
            else:
                extra_mb = jnp.zeros((), jnp.float32)
            k = jax.random.fold_in(jax.random.fold_in(key, u), stage)
            new_act, out = jax.lax.switch(
                stage, branches, row, shared, iring, act, extra_mb, k)
            emitting = (stage == S - 1) & (t >= 0) & (t < M_pad)
            own = emitting & (t % S == S - 1)
            outs = jnp.where(
                own, outs.at[jnp.clip(t // S, 0, L - 1)].set(out), outs)
            oring = jnp.where(emitting, out, oring)
            act = jax.lax.ppermute(new_act, PP_AXIS, fwd)
            iring = jax.lax.ppermute(iring, PP_AXIS, back)
            oring = jax.lax.ppermute(oring, PP_AXIS, fwd)
            return (act, iring, oring, outs), None

        (_, _, _, outs), _ = jax.lax.scan(
            tick, (zero_act, zero_in, zero_out, outs0),
            jnp.arange(total))
        return outs

    sm = jax.shard_map(
        per_device, mesh=mesh,
        in_specs=(P(PP_AXIS), P(), P(PP_AXIS), P(), P()),
        out_specs=P(PP_AXIS),
        axis_names={PP_AXIS},
        check_vma=False)

    def run(rows, shared, x, extra=None, key=None):
        tail = x.shape[1:]
        if M_pad != M:
            x = jnp.concatenate(
                [x, jnp.zeros((M_pad - M,) + tail, x.dtype)], axis=0)
        xs = x.reshape((L, S) + tail).swapaxes(0, 1).reshape(
            (M_pad,) + tail)
        if extra is not None:
            # tick t consumes ORIGINAL stream slot t (the striding is a
            # per-device ownership layout, undone by the injection ring),
            # so the last stage indexes extra in original order
            if M_pad != M:
                extra = jnp.concatenate(
                    [extra, jnp.zeros((M_pad - M,) + extra.shape[1:],
                                      extra.dtype)], axis=0)
            es = extra
        else:
            es = jnp.zeros((M_pad,), jnp.float32)
        if key is None:
            key = jax.random.PRNGKey(0)
        y = sm(rows, shared, xs, es, key)
        y = y.reshape((S, L) + out_shape).swapaxes(0, 1).reshape(
            (M_pad,) + out_shape)
        return y[:M]

    return run


class PipelineParallel:
    """Dygraph-style wrapper driving the compiled pipeline
    (ref: meta_parallel/pipeline_parallel.py:32 PipelineParallel)."""

    def __init__(self, layers, hcg, strategy):
        self._layers = layers
        self._hcg = hcg
        self._strategy = strategy
        cfg = strategy.pipeline_configs
        self.micro_batch_size = cfg["micro_batch_size"]
        self.accumulate_steps = cfg["accumulate_steps"]
        self.num_stages = hcg.get_pipe_parallel_world_size()
        self._engine = None

    def parameters(self):
        return self._layers.parameters()

    def state_dict(self, *a, **k):
        return self._layers.state_dict(*a, **k)

    def set_state_dict(self, sd, *a, **k):
        return self._layers.set_state_dict(sd, *a, **k)

    def train_batch(self, data, optimizer, lr_scheduler=None, scaler=None):
        """Run one global batch = accumulate_steps micro-batches through
        the compiled pipeline + optimizer update. For stage-uniform
        PipelineLayers this uses the scan/ppermute schedule; otherwise it
        falls back to sequential GSPMD placement (still one XLA program,
        stages laid out over 'pp')."""
        from ...pp_engine import PipelineEngine

        if self._engine is None:
            self._engine = PipelineEngine(
                self._layers, optimizer, self._hcg,
                micro_batch_size=self.micro_batch_size,
                accumulate_steps=self.accumulate_steps)
        inputs, labels = data
        loss = self._engine.train_batch(inputs, labels)
        if lr_scheduler is not None:
            lr_scheduler.step()
        return loss

    def eval_batch(self, data, compute_loss=True):
        inputs, labels = data
        out = self._layers(inputs if isinstance(inputs, Tensor)
                           else Tensor(inputs))
        if compute_loss and self._layers._loss_fn is not None:
            return self._layers._loss_fn(
                out, labels if isinstance(labels, Tensor)
                else Tensor(labels))
        return out
