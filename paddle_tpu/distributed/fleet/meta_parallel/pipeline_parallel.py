"""Pipeline-parallel execution.

Ref parity: python/paddle/distributed/fleet/meta_parallel/
pipeline_parallel.py:32,114,382-535 (micro-batch loop with p2p
activation/grad exchange) and the 1F1B schedule of
paddle/fluid/framework/section_worker.cc:104-180.

TPU-native design: there is no interpreter to run per-stage programs and no
eager p2p. The whole schedule is ONE compiled XLA program:

- stage parameters are stacked on a leading [pp] axis and sharded over the
  mesh's 'pp' axis (each device slice holds its stage's weights);
- the micro-batch loop is a `lax.scan` (soft pipelining: iteration t
  advances every stage by one micro-batch);
- stage-to-stage transfer is `lax.ppermute` over 'pp' — XLA lowers it to
  ICI collective-permute and overlaps it with compute;
- the backward schedule needs no code: jax AD differentiates scan+ppermute
  into the reverse pipeline (grad of ppermute is the inverse permute),
  giving a GPipe/1F1B-equivalent compiled schedule;
- gradient accumulation across micro-batches falls out of the scan's sum.

This requires stage-uniform bodies (same jaxpr per stage) — true for the
transformer ladder configs; heterogeneous embedding/head run outside the
shard_map under plain GSPMD.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ....core.tensor import Tensor
from ...topology import PP_AXIS


def pipeline_spmd(stage_fn, mesh, *, num_stages, num_micro):
    """Wrap `stage_fn(stage_params, x) -> y` into a full-pipeline function
    `(stacked_params, microbatches) -> outputs`.

    stacked_params: pytree whose leaves have leading dim [num_stages]
    microbatches:   [num_micro, micro_batch, ...]
    outputs:        [num_micro, micro_batch, ...] (from the last stage)

    NON-UNIFORM stages (ref pp_layers.py:76 SharedLayerDesc / custom
    segmentation): pass a LIST of `num_stages` callables instead of one
    `stage_fn` — stage s runs `stage_fns[s]` via `lax.switch` on the pp
    axis index (XLA executes only the taken branch per device).  Two
    contracts: every stage maps the same activation shape to the same
    activation shape (the ring carries one layout), and per-stage
    weights that do not fit the uniform stacked-params tree are closed
    over (as traced values, so AD still reaches them) or left in GSPMD
    land outside the shard_map.  Weight TYING across stages (GPT-2
    embedding/head) needs no machinery at all in this design: tied
    weights live once in the non-pipelined params and jax AD sums their
    gradient contributions from every use site — see
    hybrid.make_gpt_hybrid_engine.

    Memory schedule (the 1F1B working-set analogue,
    ref section_worker.cc:134-180): the micro-batch stream is SHARDED over
    'pp' (device s holds micro-batches {j*S+s}, L = M/S each) instead of
    replicated, and per-tick traffic is three [micro]-sized ppermutes:

    - an input ring rotating toward stage 0: every S ticks each device
      injects its next local micro-batch; after k shifts the batch due at
      tick t arrives at stage 0 exactly at tick t;
    - the activation carry (stage s -> s+1), as before;
    - an output ring rotating away from the last stage: finished
      micro-batches travel back to their owner device, which captures
      them at tick j*S + 2*s + S (last stage captures its own directly).

    Per-device stream memory drops from 2*M to 2*M/S micro-batches and the
    old O(M x batch) psum broadcast of outputs disappears entirely.
    """
    S, M = num_stages, num_micro
    # pad the stream to a multiple of S: the ring schedule needs equal
    # local shares; padded micro-batches compute garbage that is sliced
    # off the outputs (and therefore carries no gradient)
    L = -(-M // S)
    M_pad = L * S
    fwd = [(i, (i + 1) % S) for i in range(S)]
    back = [(i, (i - 1) % S) for i in range(S)]

    if callable(stage_fn):
        def apply_stage(stage, local, inp):
            return stage_fn(local, inp)
    else:
        fns = list(stage_fn)
        if len(fns) != S:
            raise ValueError(
                f"stage_fns has {len(fns)} entries for {S} stages")

        def apply_stage(stage, local, inp):
            return jax.lax.switch(
                stage, [lambda l, x, f=f: f(l, x) for f in fns],
                local, inp)

    def per_device(params, x_local):
        # inside shard_map over 'pp': params leaves are [1, ...] (this
        # stage's slice), x_local is [L, micro, ...] (this device's strided
        # share of the stream: micro-batches j*S + stage)
        stage = jax.lax.axis_index(PP_AXIS)
        local = jax.tree.map(lambda p: p[0], params)
        total = M_pad + 2 * S - 2 if S > 1 else M_pad

        zero_mb = jnp.zeros_like(x_local[0])
        outs0 = jnp.zeros_like(x_local)

        def tick(carry, u):
            act, iring, oring, outs = carry
            # 1) input injection: at ticks u = j*S every device loads its
            # j-th local micro-batch into the input ring
            jj = u // S
            inject = (u % S == 0) & (jj < L)
            iring = jnp.where(inject, x_local[jnp.clip(jj, 0, L - 1)],
                              iring)
            # 2) owner capture from the output ring (stages < S-1): the
            # batch finished at tick t = j*S+s+S-1 arrives after s+1
            # shifts, i.e. at tick j*S + 2s + S
            num = u - 2 * stage - S
            jcap = num // S
            cap = (stage < S - 1) & (num >= 0) & (num % S == 0) \
                & (jcap < L)
            outs = jnp.where(
                cap, outs.at[jnp.clip(jcap, 0, L - 1)].set(oring), outs)
            # 3) stage compute (stage 0 eats the input ring)
            inp = jnp.where(stage == 0, iring, act)
            out = apply_stage(stage, local, inp)
            # 4) last stage: emit into the output ring; micro-batches it
            # owns itself (t % S == S-1) are stored directly
            t = u - (S - 1)
            emitting = (stage == S - 1) & (t >= 0) & (t < M_pad)
            own = emitting & (t % S == S - 1)
            outs = jnp.where(
                own, outs.at[jnp.clip(t // S, 0, L - 1)].set(out), outs)
            oring = jnp.where(emitting, out, oring)
            # 5) ring shifts
            act = jax.lax.ppermute(out, PP_AXIS, fwd)
            iring = jax.lax.ppermute(iring, PP_AXIS, back)
            oring = jax.lax.ppermute(oring, PP_AXIS, fwd)
            return (act, iring, oring, outs), None

        (_, _, _, outs), _ = jax.lax.scan(
            tick, (zero_mb, zero_mb, zero_mb, outs0), jnp.arange(total))
        return outs

    # manual only over 'pp': dp/mp/sharding stay GSPMD-auto inside the
    # stage body, so TP sharding constraints and batch sharding compose
    sm = jax.shard_map(
        per_device, mesh=mesh,
        in_specs=(P(PP_AXIS), P(PP_AXIS)),
        out_specs=P(PP_AXIS),
        axis_names={PP_AXIS},
        check_vma=False)

    def run(params, x):
        # strided re-layout so device s's contiguous block holds
        # micro-batches {j*S+s}; inverse applied to the outputs
        tail = x.shape[1:]
        if M_pad != M:
            pad = jnp.zeros((M_pad - M,) + tail, x.dtype)
            x = jnp.concatenate([x, pad], axis=0)
        xs = x.reshape((L, S) + tail).swapaxes(0, 1).reshape(
            (M_pad,) + tail)
        y = sm(params, xs)
        y = y.reshape((S, L) + tail).swapaxes(0, 1).reshape(
            (M_pad,) + tail)
        return y[:M]

    return run


class PipelineParallel:
    """Dygraph-style wrapper driving the compiled pipeline
    (ref: meta_parallel/pipeline_parallel.py:32 PipelineParallel)."""

    def __init__(self, layers, hcg, strategy):
        self._layers = layers
        self._hcg = hcg
        self._strategy = strategy
        cfg = strategy.pipeline_configs
        self.micro_batch_size = cfg["micro_batch_size"]
        self.accumulate_steps = cfg["accumulate_steps"]
        self.num_stages = hcg.get_pipe_parallel_world_size()
        self._engine = None

    def parameters(self):
        return self._layers.parameters()

    def state_dict(self, *a, **k):
        return self._layers.state_dict(*a, **k)

    def set_state_dict(self, sd, *a, **k):
        return self._layers.set_state_dict(sd, *a, **k)

    def train_batch(self, data, optimizer, lr_scheduler=None, scaler=None):
        """Run one global batch = accumulate_steps micro-batches through
        the compiled pipeline + optimizer update. For stage-uniform
        PipelineLayers this uses the scan/ppermute schedule; otherwise it
        falls back to sequential GSPMD placement (still one XLA program,
        stages laid out over 'pp')."""
        from ...pp_engine import PipelineEngine

        if self._engine is None:
            self._engine = PipelineEngine(
                self._layers, optimizer, self._hcg,
                micro_batch_size=self.micro_batch_size,
                accumulate_steps=self.accumulate_steps)
        inputs, labels = data
        loss = self._engine.train_batch(inputs, labels)
        if lr_scheduler is not None:
            lr_scheduler.step()
        return loss

    def eval_batch(self, data, compute_loss=True):
        inputs, labels = data
        out = self._layers(inputs if isinstance(inputs, Tensor)
                           else Tensor(inputs))
        if compute_loss and self._layers._loss_fn is not None:
            return self._layers._loss_fn(
                out, labels if isinstance(labels, Tensor)
                else Tensor(labels))
        return out
