"""Meta-parallel layers & engines (ref: python/paddle/distributed/fleet/
meta_parallel/)."""

from .mp_layers import (  # noqa: F401
    ColumnParallelLinear, ParallelCrossEntropy, RowParallelLinear,
    VocabParallelEmbedding, parallel_linear_split, shard_hint,
)
from .pp_layers import LayerDesc, PipelineLayer, SharedLayerDesc  # noqa: F401
from .pipeline_parallel import PipelineParallel, pipeline_spmd  # noqa: F401
