"""Activation recomputation (ref: fleet/utils/recompute.py:63
RecomputeFunction — a PyLayer that re-runs forward under saved RNG state
during backward).

TPU-native: `jax.checkpoint` (rematerialisation) IS this feature, applied
at trace time — XLA recomputes the segment in the backward pass, and the
threaded-PRNG design makes dropout reproducibility automatic (the same key
is folded in on replay; no RNG state tracker needed). Eagerly (no jit)
recompute is a no-op: the tape already stores residuals.
"""

from __future__ import annotations

import jax

from ....core.tensor import Tensor
from ....framework.flags import flag


def remat_wrapper(default="full"):
    """Resolve FLAGS_remat_policy to a jax.checkpoint-style wrapper.

    Returns a callable `wrap(fn) -> fn'`:
      - 'full'          -> jax.checkpoint(fn): save nothing, recompute all
      - 'dots_saveable' -> jax.checkpoint(fn, policy=dots_saveable): the
                          matmul outputs are saved, the cheap elementwise
                          tail is recomputed
      - 'none'          -> fn unchanged: all residuals saved, no recompute
      - 'auto'          -> the site's own `default` (recompute() segments
                          default to 'full'; the hybrid block scan passes
                          'none' so auto keeps its save-residuals shape)
    """
    policy = flag("FLAGS_remat_policy")
    if policy == "auto":
        policy = default
    if policy == "full":
        return jax.checkpoint
    if policy == "dots_saveable":
        return lambda fn: jax.checkpoint(
            fn, policy=jax.checkpoint_policies.dots_saveable)
    if policy == "none":
        return lambda fn: fn
    raise ValueError(
        f"FLAGS_remat_policy={policy!r}; expected "
        "auto | full | dots_saveable | none")


def recompute(function, *args, **kwargs):
    preserve_rng_state = kwargs.pop("preserve_rng_state", True)  # noqa: F841
    use_reentrant = kwargs.pop("use_reentrant", True)  # noqa: F841

    sample = None
    for a in args:
        if isinstance(a, Tensor):
            sample = a
            break
    tracing = sample is not None and isinstance(sample._value,
                                                jax.core.Tracer)
    if not tracing:
        return function(*args, **kwargs)

    # only Tensor args flow through the checkpoint boundary; None/static
    # args stay closed over (jax.checkpoint args must be arrays)
    tensor_idx = [i for i, a in enumerate(args) if isinstance(a, Tensor)]

    def fn_arrays(*arrs):
        # the checkpointed segment is a sub-trace: the lowp delayed-
        # scaling region must not record its tracers (its matmuls use
        # dynamic scales instead)
        from ....ops import lowp as _lowp

        full = list(args)
        for j, i in enumerate(tensor_idx):
            full[i] = Tensor(arrs[j])
        with _lowp.suppress_region():
            out = function(*full, **kwargs)
        return jax.tree.map(
            lambda t: t._value if isinstance(t, Tensor) else t, out,
            is_leaf=lambda t: isinstance(t, Tensor))

    wrap = remat_wrapper(default="full")
    out = wrap(fn_arrays)(
        *[args[i]._value for i in tensor_idx])
    return jax.tree.map(Tensor, out)


class RecomputeSequential:
    """Helper: wrap sublayer calls of a Sequential in recompute segments."""

    def __init__(self, layers, segments=1):
        self.layers = layers
        self.segments = segments

    def __call__(self, x):
        n = len(self.layers)
        seg = max(n // self.segments, 1)
        i = 0
        while i < n:
            chunk = self.layers[i:i + seg]

            def run_chunk(inp, chunk=chunk):
                for l in chunk:
                    inp = l(inp)
                return inp

            x = recompute(run_chunk, x)
            i += seg
        return x
