"""Fleet utils (ref: python/paddle/distributed/fleet/utils/)."""

from .recompute import recompute  # noqa: F401
from . import fs  # noqa: F401
from .fs import HDFSClient, LocalFS  # noqa: F401
