"""Fleet utils (ref: python/paddle/distributed/fleet/utils/)."""

from .recompute import recompute  # noqa: F401
