"""Filesystem abstraction for checkpoint/data paths.

Ref parity: python/paddle/distributed/fleet/utils/fs.py — FS base with
LocalFS and HDFSClient. Checkpoints on TPU pods typically target GCS or
NFS; the FS interface stays so training loops are storage-agnostic.
HDFSClient shells out to `hadoop fs` exactly like the reference (and
raises a clear error when the toolchain is absent).
"""

from __future__ import annotations

import os
import shutil
import subprocess

__all__ = ["FS", "LocalFS", "HDFSClient", "FSFileExistsError",
           "FSFileNotExistsError"]


class FSFileExistsError(Exception):
    pass


class FSFileNotExistsError(Exception):
    pass


class FS:
    def ls_dir(self, fs_path):
        raise NotImplementedError

    def is_file(self, fs_path):
        raise NotImplementedError

    def is_dir(self, fs_path):
        raise NotImplementedError

    def is_exist(self, fs_path):
        raise NotImplementedError

    def upload(self, local_path, fs_path):
        raise NotImplementedError

    def download(self, fs_path, local_path):
        raise NotImplementedError

    def mkdirs(self, fs_path):
        raise NotImplementedError

    def delete(self, fs_path):
        raise NotImplementedError

    def rename(self, fs_src_path, fs_dst_path):
        raise NotImplementedError

    def need_upload_download(self):
        raise NotImplementedError

    def touch(self, fs_path, exist_ok=True):
        raise NotImplementedError

    def mv(self, fs_src_path, fs_dst_path, overwrite=False):
        raise NotImplementedError


class LocalFS(FS):
    """ref fs.py LocalFS."""

    def ls_dir(self, fs_path):
        if not self.is_exist(fs_path):
            return [], []
        dirs, files = [], []
        for name in os.listdir(fs_path):
            if os.path.isdir(os.path.join(fs_path, name)):
                dirs.append(name)
            else:
                files.append(name)
        return dirs, files

    def is_file(self, fs_path):
        return os.path.isfile(fs_path)

    def is_dir(self, fs_path):
        return os.path.isdir(fs_path)

    def is_exist(self, fs_path):
        return os.path.exists(fs_path)

    def mkdirs(self, fs_path):
        os.makedirs(fs_path, exist_ok=True)

    def delete(self, fs_path):
        if self.is_file(fs_path):
            os.remove(fs_path)
        elif self.is_dir(fs_path):
            shutil.rmtree(fs_path)

    def rename(self, src, dst):
        os.rename(src, dst)

    def need_upload_download(self):
        return False

    def touch(self, fs_path, exist_ok=True):
        if self.is_exist(fs_path):
            if exist_ok:
                return
            raise FSFileExistsError(fs_path)
        os.makedirs(os.path.dirname(fs_path) or ".", exist_ok=True)
        with open(fs_path, "a"):
            pass

    def mv(self, src, dst, overwrite=False, test_exists=True):
        if test_exists and not self.is_exist(src):
            raise FSFileNotExistsError(src)
        if self.is_exist(dst):
            if not overwrite:
                raise FSFileExistsError(dst)
            self.delete(dst)
        shutil.move(src, dst)

    def upload(self, local_path, fs_path):
        if local_path != fs_path:
            shutil.copy(local_path, fs_path)

    def download(self, fs_path, local_path):
        if fs_path != local_path:
            shutil.copy(fs_path, local_path)

    def list_dirs(self, fs_path):
        return self.ls_dir(fs_path)[0]


class ExecuteError(Exception):
    """A hadoop command failed (ref fs.py ExecuteError)."""


class HDFSClient(FS):
    """ref fs.py HDFSClient: shell over `hadoop fs` (same command surface
    as the reference; requires the hadoop CLI). Mutating commands check
    exit codes, retrying `retry_times` times with `sleep_inter` ms
    backoff before raising ExecuteError — the reference's contract."""

    def __init__(self, hadoop_home=None, configs=None, time_out=5 * 60,
                 sleep_inter=1000, retry_times=3):
        self._hadoop = os.path.join(hadoop_home, "bin", "hadoop") \
            if hadoop_home else "hadoop"
        self._configs = configs or {}
        self._time_out = time_out
        self._sleep_inter = sleep_inter / 1000.0
        self._retry_times = max(int(retry_times), 1)

    def _run(self, *args):
        cmd = [self._hadoop, "fs"]
        for k, v in self._configs.items():
            cmd += ["-D", f"{k}={v}"]
        cmd += list(args)
        try:
            return subprocess.run(cmd, capture_output=True, text=True,
                                  timeout=self._time_out)
        except FileNotFoundError as e:
            raise RuntimeError(
                "hadoop CLI not found — HDFSClient needs a hadoop "
                "installation (pass hadoop_home=)") from e

    def _run_checked(self, *args):
        """Mutating ops: a silently-discarded failure loses data (e.g. a
        checkpoint upload that never landed), so retry then raise."""
        import time as _time

        last = None
        for attempt in range(self._retry_times):
            try:
                r = self._run(*args)
            except subprocess.TimeoutExpired as e:
                last = f"timeout after {self._time_out}s: {e}"
            else:
                if r.returncode == 0:
                    return r
                last = r.stderr.strip() or f"exit code {r.returncode}"
            if attempt + 1 < self._retry_times:
                _time.sleep(self._sleep_inter)
        raise ExecuteError(
            f"hadoop fs {' '.join(args)} failed after "
            f"{self._retry_times} attempts: {last}")

    def is_exist(self, fs_path):
        return self._run("-test", "-e", fs_path).returncode == 0

    def is_file(self, fs_path):
        return self._run("-test", "-f", fs_path).returncode == 0

    def is_dir(self, fs_path):
        return self._run("-test", "-d", fs_path).returncode == 0

    def ls_dir(self, fs_path):
        r = self._run("-ls", fs_path)
        dirs, files = [], []
        for line in r.stdout.splitlines():
            parts = line.split()
            if len(parts) < 8:
                continue
            name = os.path.basename(parts[-1])
            (dirs if parts[0].startswith("d") else files).append(name)
        return dirs, files

    def mkdirs(self, fs_path):
        self._run_checked("-mkdir", "-p", fs_path)

    def delete(self, fs_path):
        self._run_checked("-rm", "-r", "-f", fs_path)

    def upload(self, local_path, fs_path):
        self._run_checked("-put", "-f", local_path, fs_path)

    def download(self, fs_path, local_path):
        self._run_checked("-get", fs_path, local_path)

    def mv(self, src, dst, overwrite=False):
        # full LocalFS.mv parity: src must exist BEFORE any destructive
        # delete of dst, and without overwrite an existing dst is an
        # error (hadoop -mv would otherwise silently nest src inside a
        # dst directory)
        if not self.is_exist(src):
            raise FSFileNotExistsError(src)
        if self.is_exist(dst):
            if not overwrite:
                raise FSFileExistsError(dst)
            self.delete(dst)
        self._run_checked("-mv", src, dst)

    def touch(self, fs_path, exist_ok=True):
        if self.is_exist(fs_path):
            if exist_ok:
                return  # -touchz fails on non-empty existing files
            raise FSFileExistsError(fs_path)
        self._run_checked("-touchz", fs_path)

    def need_upload_download(self):
        return True
