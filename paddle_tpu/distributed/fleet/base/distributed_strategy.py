"""DistributedStrategy.

Ref parity: paddle/fluid/framework/distributed_strategy.proto (toggles at
:159-195, configs at :26-156) + fleet/base/distributed_strategy.py (1753
LoC wrapper). Kept as a plain serialisable config object: every toggle a
bool, every *_configs a dict — scripts written against the reference
assign the same fields and launch unchanged; the TPU engine consumes them
to build mesh shardings instead of rewriting programs.
"""

from __future__ import annotations

import copy
import json


_DEFAULTS = {
    # toggles (distributed_strategy.proto:159-195)
    "amp": False,
    "recompute": False,
    "sharding": False,
    "pipeline": False,
    "tensor_parallel": False,
    "dgc": False,
    "localsgd": False,
    "adaptive_localsgd": False,
    "gradient_merge": False,
    "lars": False,
    "lamb": False,
    "fp16_allreduce": False,
    "a_sync": False,
    "asp": False,
    "heter_ccl_mode": False,
    "elastic": False,
    "auto": False,
    "semi_auto": False,
    "without_graph_optimization": True,  # XLA owns graph optimisation
    "fuse_all_reduce_ops": True,
    "fuse_grad_size_in_MB": 32,
    "nccl_comm_num": 1,
    "sync_nccl_allreduce": True,
    "use_hierarchical_allreduce": False,
    "cudnn_exhaustive_search": False,
    "find_unused_parameters": False,
}

_CONFIG_DEFAULTS = {
    "amp_configs": {
        "init_loss_scaling": 32768.0,
        "incr_every_n_steps": 1000,
        "decr_every_n_nan_or_inf": 2,
        "incr_ratio": 2.0,
        "decr_ratio": 0.5,
        "use_dynamic_loss_scaling": True,
        "custom_white_list": [],
        "custom_black_list": [],
        "use_pure_fp16": False,
        "use_fp16_guard": True,
        # TPU-native: bfloat16 by default (no loss scaling needed)
        "dtype": "bfloat16",
    },
    "recompute_configs": {
        "checkpoints": [],
        "enable_offload": False,
        "checkpoint_shape": [],
    },
    "sharding_configs": {
        # ref proto ShardingConfig (:32-45)
        "sharding_segment_strategy": "segment_broadcast_MB",
        "segment_broadcast_MB": 32.0,
        "sharding_degree": 8,
        "mp_degree": 1,
        "dp_degree": 1,
        "pp_degree": 1,
        "stage": 2,
        "offload": False,
        "gradient_merge_acc_step": 1,
        "optimize_offload": False,
    },
    "pipeline_configs": {
        # ref proto PipelineConfig (:148-152)
        "micro_batch_size": 1,
        "accumulate_steps": 1,
        "schedule_mode": "1F1B",
        "p2p_cache_shape": True,
    },
    "tensor_parallel_configs": {
        "tensor_parallel_degree": 1,
        "tensor_init_seed": -1,
    },
    "hybrid_configs": {
        "dp_degree": -1,
        "mp_degree": 1,
        "pp_degree": 1,
        "sharding_degree": 1,
        # net-new for TPU long-context (ring attention / sequence parallel)
        "sep_degree": 1,
    },
    "gradient_merge_configs": {"k_steps": 1, "avg": True},
    "localsgd_configs": {"k_steps": 1, "begin_step": 1},
    "adaptive_localsgd_configs": {"init_k_steps": 1, "begin_step": 1},
    "dgc_configs": {"rampup_begin_step": 0, "rampup_step": 1,
                    "sparsity": [0.999]},
    "lars_configs": {"lars_coeff": 0.001, "lars_weight_decay": 0.0005,
                     "epsilon": 0.0, "exclude_from_weight_decay": []},
    "lamb_configs": {"lamb_weight_decay": 0.01,
                     "exclude_from_weight_decay": []},
    "a_sync_configs": {"k_steps": -1, "max_merge_var_num": 1,
                       "send_queue_size": 16,
                       "independent_recv_thread": False,
                       "min_send_grad_num_before_recv": 1,
                       "thread_pool_size": 1, "send_wait_times": 1,
                       "runtime_split_send_recv": False, "launch_barrier": True,
                       "heter_worker_device_guard": "cpu", "lr_decay_steps": 10,
                       "use_ps_gpu": 0, "use_gpu_graph": 0},
    "elastic_configs": {},
}


class DistributedStrategy:
    def __init__(self):
        self._flags = copy.deepcopy(_DEFAULTS)
        self._configs = copy.deepcopy(_CONFIG_DEFAULTS)

    def __getattr__(self, name):
        if name.startswith("_"):
            raise AttributeError(name)
        if name in self._flags:
            return self._flags[name]
        if name in self._configs:
            return self._configs[name]
        raise AttributeError(f"DistributedStrategy has no field {name!r}")

    def __setattr__(self, name, value):
        if name.startswith("_"):
            object.__setattr__(self, name, value)
            return
        if name in _DEFAULTS:
            self._flags[name] = value
        elif name in _CONFIG_DEFAULTS:
            cfg = copy.deepcopy(_CONFIG_DEFAULTS[name])
            cfg.update(value)
            self._configs[name] = cfg
        else:
            object.__setattr__(self, name, value)

    def to_dict(self):
        return {"flags": copy.deepcopy(self._flags),
                "configs": copy.deepcopy(self._configs)}

    def save_to_prototxt(self, output):
        with open(output, "w") as f:
            json.dump(self.to_dict(), f, indent=2)

    def load_from_prototxt(self, pb_file):
        with open(pb_file) as f:
            d = json.load(f)
        self._flags.update(d.get("flags", {}))
        for k, v in d.get("configs", {}).items():
            self._configs[k].update(v)

    def __repr__(self):
        on = [k for k, v in self._flags.items() if v is True]
        return f"DistributedStrategy(enabled={on})"
