"""Fleet facade (ref: python/paddle/distributed/fleet/base/fleet_base.py:
139 init, 783 distributed_optimizer, 1288 minimize).

TPU-native: `init` builds the HybridCommunicateGroup (and thus the jax
Mesh) from strategy.hybrid_configs; `distributed_model` wraps by
ParallelMode; `distributed_optimizer` returns a HybridParallelOptimizer
that carries the strategy into the compiled engine. There are no program
rewrites — the meta-optimizer composition collapses into sharding specs +
engine options (GSPMD/ZeRO/pipeline/recompute flags).
"""

from __future__ import annotations

import jax

from ....dataparallel import DataParallel
from ...parallel import ParallelEnv, get_rank, get_world_size, \
    init_parallel_env
from ...topology import (
    HybridCommunicateGroup, ParallelMode, set_hybrid_communicate_group,
)
from .distributed_strategy import DistributedStrategy


class _RoleMaker:
    def __init__(self, is_collective=True):
        self._is_collective = is_collective

    def _is_non_distributed(self):
        return get_world_size() <= 1 and jax.device_count() <= 1


class Fleet:
    def __init__(self):
        self._role_maker = None
        self._hcg = None
        self._user_defined_strategy = None
        self._is_initialized = False
        self._ps_runtime = None

    # -- init ----------------------------------------------------------------
    def init(self, role_maker=None, is_collective=True, strategy=None):
        if strategy is None:
            strategy = DistributedStrategy()
        self._user_defined_strategy = strategy
        self._role_maker = role_maker or _RoleMaker(is_collective)

        from ...ps.runtime import PSRoleMaker, init_runtime

        if isinstance(self._role_maker, PSRoleMaker):
            # parameter-server mode (ref fleet_base.py PS branch +
            # the_one_ps.py runtime): no collective mesh is built
            a_sync = getattr(strategy, "a_sync", False)
            cfg = getattr(strategy, "a_sync_configs", {}) or {}
            k_steps = int(cfg.get("k_steps", -1))
            # paddle semantics: a_sync + k_steps>0 = GeoSGD; a_sync = async
            mode = "geo" if (a_sync and k_steps > 0) else \
                ("async" if a_sync else "sync")
            self._ps_runtime = init_runtime(
                self._role_maker, mode=mode, geo_step=max(k_steps, 1))
            self._is_initialized = True
            return self
        init_parallel_env()

        hc = strategy.hybrid_configs
        ndev = jax.device_count()
        mp = max(int(hc.get("mp_degree", 1)), 1)
        pp = max(int(hc.get("pp_degree", 1)), 1)
        sh = max(int(hc.get("sharding_degree", 1)), 1)
        dp = int(hc.get("dp_degree", -1))
        if dp <= 0:
            dp = max(ndev // (mp * pp * sh), 1)
        self._hcg = HybridCommunicateGroup(
            dp_degree=dp, mp_degree=mp, pp_degree=pp, sharding_degree=sh)
        set_hybrid_communicate_group(self._hcg)
        self._is_initialized = True
        return self

    # -- parameter-server mode (ref fleet_base.py:
    # is_server/init_server/run_server/init_worker/stop_worker) -------------
    def is_server(self):
        from ...ps.runtime import PSRoleMaker

        return isinstance(self._role_maker, PSRoleMaker) and \
            self._role_maker.is_server()

    def is_worker(self):
        from ...ps.runtime import PSRoleMaker

        if isinstance(self._role_maker, PSRoleMaker):
            return self._role_maker.is_worker()
        return True

    def init_server(self, *args, **kwargs):
        return self._ps_runtime.init_server()

    def run_server(self):
        return self._ps_runtime.run_server()

    def init_worker(self):
        return self._ps_runtime.init_worker()

    def stop_worker(self):
        return self._ps_runtime.stop_worker()

    @property
    def ps_runtime(self):
        return self._ps_runtime

    # -- info ----------------------------------------------------------------
    def get_hybrid_communicate_group(self):
        return self._hcg

    def worker_index(self):
        return get_rank()

    def worker_num(self):
        return get_world_size()

    def is_first_worker(self):
        return get_rank() == 0

    def worker_endpoints(self, to_string=False):
        eps = ParallelEnv().trainer_endpoints
        return ",".join(eps) if to_string else eps

    def barrier_worker(self):
        from ...collective import barrier

        barrier()

    @property
    def world_size(self):
        return get_world_size()

    # -- model / optimizer wrapping -----------------------------------------
    def distributed_model(self, model):
        from ..meta_parallel.pipeline_parallel import PipelineParallel
        from ..meta_parallel.pp_layers import PipelineLayer

        if self._hcg is None:
            self.init()
        mode = self._hcg.get_parallel_mode()
        if mode == ParallelMode.PIPELINE_PARALLEL and isinstance(
                model, PipelineLayer):
            return PipelineParallel(model, self._hcg,
                                    self._user_defined_strategy)
        if mode == ParallelMode.DATA_PARALLEL and \
                self._hcg.get_data_parallel_world_size() > 1:
            return DataParallel(model)
        # tensor/sharding parallel: parameters already carry GSPMD specs
        return model

    def distributed_optimizer(self, optimizer, strategy=None):
        from ..meta_optimizers.dygraph_optimizer import \
            HybridParallelOptimizer

        if strategy is not None:
            self._user_defined_strategy = strategy
        return HybridParallelOptimizer(
            optimizer, self._hcg, self._user_defined_strategy)

    def distributed_scaler(self, scaler):
        return scaler

    # -- static-graph style minimize (compat shim) ---------------------------
    def minimize(self, optimizer, loss, startup_program=None,
                 parameter_list=None, no_grad_set=None):
        optimizer.step()
        return None, []

    # -- checkpoint ----------------------------------------------------------
    def save_persistables(self, executor=None, dirname=None,
                          main_program=None, mode=0):
        """ref: fleet_base.py save_persistables -> the_one_ps runtime.
        `executor` is the Engine or Layer holding the state (the TPU path
        has no Executor/Program split); `dirname` the checkpoint dir."""
        from ...checkpoint import save_persistables as _save

        if executor is None or dirname is None:
            raise ValueError("save_persistables(engine_or_layer, dirname)")
        _save(executor, dirname)

    @property
    def hcg(self):
        return self._hcg
