from .distributed_strategy import DistributedStrategy  # noqa: F401
from .fleet_base import Fleet  # noqa: F401
