"""Distributed metric reduction.

Ref parity: python/paddle/distributed/fleet/metrics/metric.py — global
sum/max/min/avg/auc/acc across trainers. Reductions ride whichever
runtime is active: multi-process jax (process_allgather then local
reduce), or PS mode (each trainer pushes its local stat into a
fresh per-call dense table and pulls the merged value). Single-process,
both collapse to the local value.

PS-mode calls must happen in the same order on every trainer (each call
allocates a sequenced scratch table) — the same contract as the
reference's barrier-ordered metric ops.
"""

from __future__ import annotations

import itertools

import numpy as np

__all__ = ["sum", "max", "min", "avg", "acc", "auc"]

_ps_metric_seq = itertools.count()


def _reduce(value, op="sum"):
    value = np.asarray(value, np.float64)

    # PS mode: merge through a per-call scratch dense table (a fresh name
    # each call — a reused table would keep accumulating across calls;
    # rank 0 deletes it after the post-pull barrier so the server does
    # not leak one table per metric call)
    from ...ps.runtime import _runtime

    if _runtime is not None and _runtime._client is not None:
        client = _runtime.client
        name = f"@metric/{op}/{next(_ps_metric_seq)}"
        # the table starts at the reduction identity, not zeros — zeros
        # would poison min (and max for negative stats)
        ident = {"sum": 0.0, "max": -np.inf, "min": np.inf}[op]
        client.create_dense_table(
            name, list(value.reshape(-1).shape), optimizer=op, lr=1.0,
            initial=np.full(value.reshape(-1).shape, ident, np.float32))
        client.push_dense_grad(name, value.reshape(-1))
        _runtime.barrier()
        out = client.pull_dense(name).reshape(value.shape)
        _runtime.barrier()  # everyone pulled before the delete
        if _runtime.role.trainer_id == 0:
            client.delete_table(name)
        return out

    # multi-process jax: gather per-process stats, reduce locally
    import jax

    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        gathered = np.asarray(multihost_utils.process_allgather(
            value.astype(np.float32)), np.float64)
        if op == "sum":
            return gathered.sum(axis=0)
        if op == "max":
            return gathered.max(axis=0)
        if op == "min":
            return gathered.min(axis=0)
    return value


def sum(input):  # noqa: A001 — reference API name
    """ref metric.py sum: global sum of a local stat array/scalar."""
    return _reduce(np.asarray(input), "sum")


def max(input):  # noqa: A001
    return _reduce(np.asarray(input), "max")


def min(input):  # noqa: A001
    return _reduce(np.asarray(input), "min")


def avg(total, count):
    """Global average from local (total, count)."""
    t = sum(np.asarray(total, np.float64))
    c = sum(np.asarray(count, np.float64))
    return t / np.maximum(c, 1e-12)


def acc(correct, total):
    """ref metric.py acc: global accuracy from local counts."""
    return avg(correct, total)


def auc(stat_pos, stat_neg):
    """ref metric.py auc: merge per-trainer positive/negative histogram
    stats (the paddle.metric.Auc `_stat_pos/_stat_neg` buckets) and
    compute the global AUC with the same trapezoid rule."""
    pos = _reduce(np.asarray(stat_pos, np.float64), "sum")
    neg = _reduce(np.asarray(stat_neg, np.float64), "sum")
    # walk thresholds from high to low (bucket order reversed)
    tot_pos = tot_neg = 0.0
    area = 0.0
    for i in range(len(pos) - 1, -1, -1):
        new_pos = tot_pos + pos[i]
        new_neg = tot_neg + neg[i]
        area += (new_neg - tot_neg) * (tot_pos + new_pos) / 2.0
        tot_pos, tot_neg = new_pos, new_neg
    if tot_pos == 0.0 or tot_neg == 0.0:
        return 0.0
    return float(area / (tot_pos * tot_neg))
