"""paddle_tpu.distributed.fleet — the distributed-training facade.

Ref parity: python/paddle/distributed/fleet/__init__.py. Module-level
functions delegate to a singleton Fleet instance, exactly like the
reference.
"""

from .base.distributed_strategy import DistributedStrategy  # noqa: F401
from .base.fleet_base import Fleet
from . import meta_parallel  # noqa: F401
from . import metrics  # noqa: F401
from . import utils  # noqa: F401
from .utils.recompute import recompute  # noqa: F401

fleet = Fleet()

init = fleet.init
distributed_model = fleet.distributed_model
distributed_optimizer = fleet.distributed_optimizer
distributed_scaler = fleet.distributed_scaler
get_hybrid_communicate_group = fleet.get_hybrid_communicate_group
worker_index = fleet.worker_index
worker_num = fleet.worker_num
is_first_worker = fleet.is_first_worker
worker_endpoints = fleet.worker_endpoints
barrier_worker = fleet.barrier_worker
minimize = fleet.minimize
# parameter-server mode (ref fleet/__init__.py PS surface)
is_server = fleet.is_server
is_worker = fleet.is_worker
init_server = fleet.init_server
run_server = fleet.run_server
init_worker = fleet.init_worker
stop_worker = fleet.stop_worker


class UserDefinedRoleMaker:
    def __init__(self, *a, **k):
        pass


class PaddleCloudRoleMaker:
    def __init__(self, is_collective=False, **kwargs):
        self._is_collective = is_collective
