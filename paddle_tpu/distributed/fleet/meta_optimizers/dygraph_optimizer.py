"""Hybrid-parallel optimizer wrappers.

Ref parity: fleet/meta_optimizers/dygraph_optimizer/
hybrid_parallel_optimizer.py:89 (HybridParallelOptimizer + mp-aware
global-norm clip :32) and dygraph_sharding_optimizer.py:27 (ZeRO-1 param
partition). TPU-native: the wrapper carries strategy/mesh info into the
compiled engine; sharding of optimizer states is a GSPMD spec on the state
pytree (see engine.build_shardings), so eager behaviour stays identical.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ....clip import ClipGradByGlobalNorm


class HybridParallelClipGrad:
    """ref: hybrid_parallel_optimizer.py:32. In compiled SPMD execution the
    norm is computed over the full (replicated-view) parameters, so no
    explicit cross-shard reduction is needed; this class exists for eager
    parity and engine handoff."""

    def __init__(self, clip, hcg):
        self._clip = clip
        self._hcg = hcg

    def __call__(self, params_grads):
        return self._clip(params_grads)

    def _clip_fn(self, grads):
        return self._clip._clip_fn(grads)


class HybridParallelOptimizer:
    def __init__(self, optimizer, hcg=None, strategy=None):
        self._inner_opt = optimizer
        self._hcg = hcg
        self._strategy = strategy
        if isinstance(getattr(optimizer, "_grad_clip", None),
                      ClipGradByGlobalNorm) and hcg is not None:
            optimizer._grad_clip = HybridParallelClipGrad(
                optimizer._grad_clip, hcg)

    def __getattr__(self, item):
        return getattr(self._inner_opt, item)

    @property
    def inner_opt(self):
        return self._inner_opt

    def step(self):
        self._inner_opt.step()

    def clear_grad(self, *a, **k):
        self._inner_opt.clear_grad(*a, **k)

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        return self._inner_opt.minimize(loss, startup_program, parameters,
                                        no_grad_set)

    def state_dict(self):
        return self._inner_opt.state_dict()

    def set_state_dict(self, sd):
        return self._inner_opt.set_state_dict(sd)


class DygraphShardingOptimizer:
    """ZeRO-1 (ref: dygraph_sharding_optimizer.py:27). Under the engine the
    optimizer state pytree gets P('sharding', ...) specs — XLA stores each
    shard on its mesh slice and all-gathers updated params; eagerly this
    wrapper behaves like the inner optimizer."""

    def __init__(self, hcg, user_defined_strategy, params, inner_opt_class,
                 **inner_opt_kwargs):
        self._hcg = hcg
        self._strategy = user_defined_strategy
        self._inner_opt = inner_opt_class(parameters=params,
                                          **inner_opt_kwargs)
        self.zero_stage = 1

    def __getattr__(self, item):
        return getattr(self._inner_opt, item)

    def step(self):
        self._inner_opt.step()

    def clear_grad(self, *a, **k):
        self._inner_opt.clear_grad(*a, **k)
