"""LocalSGD: local steps + periodic cross-process parameter averaging.

Ref parity: fleet/meta_optimizers/localsgd_optimizer.py (LocalSGDOptimizer
and AdaptiveLocalSGDOptimizer). TPU-native: the reference rewrites the
program to replace per-step allreduce with periodic model averaging; here
the wrapper simply skips gradient synchronisation (each process trains on
its own shard) and every k steps averages parameters across jax processes
(DCN collective via multihost utils). Single-process runs degrade to the
plain inner optimizer.
"""

from __future__ import annotations

import numpy as np

import jax


class LocalSGDOptimizer:
    """Wrap an optimizer; average parameters across processes every
    `k_steps` local steps."""

    def __init__(self, inner_optimizer, k_steps=1, begin_step=1):
        self.inner = inner_optimizer
        self.k_steps = int(k_steps)
        self.begin_step = int(begin_step)
        self._local_steps = 0

    # delegate the optimizer surface
    def __getattr__(self, name):
        return getattr(self.inner, name)

    def step(self):
        self.inner.step()
        self._local_steps += 1
        if self._local_steps >= self.begin_step and \
                self._local_steps % self.k_steps == 0:
            self.average_parameters()

    def average_parameters(self):
        """Mean of every trainable parameter across jax processes
        (ref localsgd_optimizer.py _generate_avg_loss: c_allreduce/scale).
        ONE collective over the whole parameter tree + one jitted tree
        mean — not a per-parameter host loop."""
        if jax.process_count() <= 1:
            return
        from jax.experimental import multihost_utils

        params = [p for p in self.inner._parameter_list
                  if p is not None and not p.stop_gradient]
        tree = {i: p._value for i, p in enumerate(params)}
        gathered = multihost_utils.process_allgather(tree)
        # host-side f64-accumulated mean (the gather is the collective;
        # jit would cap accumulation at f32 under default x64-off)
        for i, p in enumerate(params):
            dt = np.asarray(p._value).dtype
            p._value = jax.numpy.asarray(
                np.mean(np.asarray(gathered[i]), axis=0,
                        dtype=np.float64).astype(dt))


class AdaptiveLocalSGDOptimizer(LocalSGDOptimizer):
    """Adaptive variant (ref localsgd_optimizer.py AdaptiveLocalSGD):
    the averaging period grows as the loss plateaus, bounded by
    [1, max_k_steps]."""

    def __init__(self, inner_optimizer, init_k_steps=1, max_k_steps=16,
                 begin_step=1):
        super().__init__(inner_optimizer, k_steps=init_k_steps,
                         begin_step=begin_step)
        self.max_k_steps = int(max_k_steps)
        self._best_loss = None

    def record_loss(self, loss):
        loss = float(loss)
        if self._best_loss is None or loss < self._best_loss * 0.999:
            self._best_loss = min(loss, self._best_loss or loss)
            self.k_steps = max(1, self.k_steps // 2)
        else:
            self.k_steps = min(self.max_k_steps, self.k_steps * 2)
