from .dygraph_optimizer import (  # noqa: F401
    DygraphShardingOptimizer, HybridParallelClipGrad, HybridParallelOptimizer,
)
