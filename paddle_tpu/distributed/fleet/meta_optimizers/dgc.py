"""DGC — deep gradient compression (top-k sparsification + momentum
correction + local accumulation).

Ref parity: fleet/meta_optimizers/dgc_optimizer.py +
paddle/fluid/operators/optimizers/dgc_momentum_op.* and dgc_op.*. Same
update semantics: momentum correction accumulates velocity locally, only
the top-k% magnitude entries are applied (and, in multi-process mode,
would be exchanged — sparse comm compression), the rest stay in the local
error accumulator until they grow large enough.
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp


class DGCMomentumOptimizer:
    """Momentum with gradient compression.

    rampup_begin_step: steps of plain dense momentum before compression
    starts (ref dgc_optimizer.py). sparsity: fraction of entries DROPPED
    (reference default schedule ends at 0.999 -> keep 0.1%)."""

    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 rampup_begin_step=0, rampup_step=1, sparsity=(0.999,),
                 grad_clip=None, name=None):
        from ....optimizer import Momentum

        self.inner = Momentum(learning_rate=learning_rate,
                              momentum=momentum, parameters=parameters,
                              grad_clip=grad_clip)
        self._momentum = momentum
        self.rampup_begin_step = int(rampup_begin_step)
        self.rampup_step = max(1, int(rampup_step))
        self.sparsity = list(sparsity)
        self._step_count = 0
        self._u: dict = {}  # id(p) -> velocity accumulator
        self._v: dict = {}  # id(p) -> error (unsent) accumulator

    def __getattr__(self, name):
        return getattr(self.inner, name)

    def _current_sparsity(self):
        if self._step_count < self.rampup_begin_step:
            return 0.0
        k = min(len(self.sparsity) - 1,
                (self._step_count - self.rampup_begin_step)
                * len(self.sparsity) // self.rampup_step)
        return float(self.sparsity[k])

    def step(self):
        sparsity = self._current_sparsity()
        self._step_count += 1
        if sparsity <= 0.0:
            self.inner.step()
            return
        lr = self.inner.get_lr()
        # grad clip applies before compression, same as inner.step()
        params_grads = []
        for p in self.inner._parameter_list:
            if p is None or p.stop_gradient or p._grad is None:
                continue
            from ....core.tensor import Tensor

            params_grads.append((p, Tensor(p._grad)))
        gc = getattr(self.inner, "_grad_clip", None)
        if gc is not None:
            params_grads = gc(params_grads)
        for p, g_t in params_grads:
            g = np.asarray(g_t._value, np.float32)
            u = self._u.get(id(p))
            v = self._v.get(id(p))
            if u is None:
                u = np.zeros_like(g)
                v = np.zeros_like(g)
            # momentum correction (dgc paper eq. 4-5)
            u = self._momentum * u + g
            v = v + u
            flat = np.abs(v).ravel()
            keep = max(1, int(round(flat.size * (1.0 - sparsity))))
            thresh = np.partition(flat, -keep)[-keep]
            mask = np.abs(v) >= thresh
            sparse_update = np.where(mask, v, 0.0)
            # applied entries leave the accumulators
            v = np.where(mask, 0.0, v)
            u = np.where(mask, 0.0, u)
            self._u[id(p)], self._v[id(p)] = u, v
            p._value = p._value - jnp.asarray(
                lr * sparse_update, p._value.dtype)
        # keep schedulers/global step consistent
        self.inner._global_step += 1

    def clear_grad(self):
        self.inner.clear_grad()
