"""DGC — deep gradient compression (top-k sparsification + momentum
correction + local error feedback), fully in-graph.

Ref parity: fleet/meta_optimizers/dgc_optimizer.py +
paddle/fluid/operators/optimizers/dgc_momentum_op.* and dgc_op.* +
cmake/external/dgc.cmake (the sparse allreduce library). Two pieces:

- `DGCMomentumOptimizer`: a real Optimizer whose `_rule` runs the DGC
  update inside the compiled train step (works through `Engine` /
  `apply_gradients_tree` — no host round-trips). Dense momentum during
  rampup, then momentum-corrected top-k with error feedback; the
  threshold is an in-graph quantile so the sparsity schedule can be a
  traced function of the step.
- `dgc_sparse_allreduce`: the communication half — inside shard_map over
  the dp axis each rank selects its local top-k (values, indices) and
  exchanges ONLY those 2k words via all_gather, scatter-adding into the
  dense update (the reference's dgc library does the same k-sized
  exchange over NCCL). Residuals stay local per rank.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from ....optimizer import Optimizer


class DGCMomentumOptimizer(Optimizer):
    """Momentum with in-graph gradient compression.

    rampup_begin_step: steps of plain dense momentum before compression
    starts (ref dgc_optimizer.py). sparsity: schedule of fractions
    DROPPED (reference default ends at 0.999 -> keep 0.1%); the active
    entry advances over `rampup_step` steps."""

    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 rampup_begin_step=0, rampup_step=1, sparsity=(0.999,),
                 weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay,
                         grad_clip)
        self._momentum = float(momentum)
        self.rampup_begin_step = int(rampup_begin_step)
        self.rampup_step = max(1, int(rampup_step))
        self.sparsity = tuple(float(s) for s in sparsity)

    def _init_state(self, value):
        return {"u": jnp.zeros_like(value), "v": jnp.zeros_like(value),
                "t": jnp.zeros((), jnp.int32)}

    def _hyper(self):
        return {"momentum": self._momentum,
                "rampup_begin": self.rampup_begin_step,
                "rampup_step": self.rampup_step,
                "sparsity": self.sparsity}

    def _rule(self, param, grad, state, lr, *, momentum, rampup_begin,
              rampup_step, sparsity):
        # NOTE: the schedule advances on this parameter's own update
        # counter; a parameter that skips steps (no grad) ramps later
        # than its siblings (the reference uses the global step).
        g = grad.astype(param.dtype)
        t = state["t"]
        u = momentum * state["u"] + g

        def dense_phase(_):
            # ordinary momentum (v untouched); no quantile sort paid
            return param - lr * u, u, state["v"]

        def dgc_phase(_):
            # paper alg.1 w/ momentum correction: transmitted
            # coordinates leave BOTH accumulators
            v = state["v"] + u
            idx = jnp.clip((t - rampup_begin) * len(sparsity)
                           // max(rampup_step, 1), 0, len(sparsity) - 1)
            sp = jnp.asarray(sparsity, jnp.float32)[idx]
            absv = jnp.abs(v).astype(jnp.float32)
            thresh = jnp.quantile(absv.ravel(), sp)
            mask = (absv >= thresh).astype(param.dtype)
            return (param - lr * v * mask, u * (1.0 - mask),
                    v * (1.0 - mask))

        new_p, new_u, new_v = jax.lax.cond(
            t < rampup_begin, dense_phase, dgc_phase, None)
        return new_p, {"u": new_u, "v": new_v, "t": t + 1}

    # residual accessor kept for inspection/tests: id(param) -> residual
    @property
    def _v(self):
        return {pid: np.asarray(st["v"])
                for pid, st in self._accumulators.items()
                if isinstance(st, dict) and "v" in st}


def dgc_sparse_allreduce(g, u, v, *, k, momentum=0.9, axis_name="dp",
                         mean=True):
    """One DGC exchange step INSIDE shard_map over `axis_name`.

    Per rank: momentum-correct the local gradient into (u, v), pick the
    local top-k of |v|, exchange exactly (k indices + k values) per rank
    via all_gather — the sparse communication the reference's dgc
    library performs — and scatter-add every rank's selection into the
    dense global update. Returns (update, new_u, new_v); the residual
    accumulators keep each rank's untransmitted mass.
    """
    u = momentum * u + g
    v = v + u
    flat = v.ravel()
    _, idx = lax.top_k(jnp.abs(flat), k)
    vals = flat[idx]
    # the 2k-word exchange (vs flat.size words for a dense allreduce)
    all_idx = lax.all_gather(idx, axis_name)      # [nranks, k]
    all_vals = lax.all_gather(vals, axis_name)    # [nranks, k]
    update = jnp.zeros_like(flat).at[all_idx.ravel()].add(
        all_vals.ravel()).reshape(v.shape)
    if mean:
        update = update / lax.axis_size(axis_name)
    keep = jnp.ones_like(flat).at[idx].set(0.0).reshape(v.shape)
    return update, u * keep, v * keep
