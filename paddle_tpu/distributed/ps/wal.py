"""Write-ahead log + snapshot store for the durable PS tier.

Ref intent: the reference PS persists sparse shards through rocksdb's
WAL + memtable flush; here durability is first-class in the service
layer instead. Every mutating command a `PSServer` accepts is appended
to a per-table append-only log *before* it is applied, so a `kill -9`
at any instant loses at most the in-flight (unacknowledged) push — which
the client retries, and the server dedupes by ``(client_id, seq)``.
Recovery = newest readable snapshot + replay of each table's log, and is
bitwise-exact because table optimizers are deterministic functions of
(state, ordered grads).

On-disk layout under the server's ``wal_dir``::

    meta.wal            create/delete table control records (never rotated)
    t-<name>-<crc>.wal  one push log per table
    snapshot-<gen>.bin  checksummed codec blob {tables, applied, gen}

Record framing is ``<I crc32> <I len> payload`` with the payload in the
typed wire codec (codec.py) — a torn tail (the partial record a crash
can leave) fails its checksum and cleanly ends replay; anything *after*
a bad record is unreachable, which is exactly the WAL contract (records
are acknowledged only once written, and writes are sequential).

Generation protocol (how snapshot + logs stay consistent without a
truncate race): every log file begins with a header record carrying its
``generation``. `checkpoint()` runs under the server's mutation lock
(quiesced), writes ``snapshot-<g+1>`` via tmp+fsync+rename, then rotates
every table log to a fresh file with header generation ``g+1``. At
recovery, a table log whose generation is *older* than the snapshot's
holds only records already folded into the snapshot (the quiesce
guarantees nothing landed between the state capture and the rotation) —
it is skipped wholesale and re-rotated; a log at the snapshot's
generation is replayed in full.

Batched durability: appends are buffered and fsync'd every
``FLAGS_ps_wal_sync_interval`` records (1 = every record). A larger
interval trades a bounded post-crash window — at most interval-1
acknowledged-but-unsynced records, which the client-side retry would
*not* replay — for append throughput; the default keeps the
exactly-once certification strict.
"""

from __future__ import annotations

import os
import re
import struct
import threading
import zlib

from ...framework import faults, monitor
from ...framework.flags import flag
from . import codec

__all__ = ["WriteAheadLog", "DurableStore", "WalCorruptError"]

_HDR = struct.Struct("<II")           # crc32(payload), len(payload)
_HEADER_KIND = "__wal__"              # first record of every log file


class WalCorruptError(RuntimeError):
    """A log or snapshot failed its checksum somewhere other than the
    tolerated torn tail."""


def _frame(payload: bytes) -> bytes:
    return _HDR.pack(zlib.crc32(payload), len(payload)) + payload


def _iter_frames(raw: bytes):
    """Yield decoded records; stop silently at a torn/corrupt tail."""
    pos = 0
    while pos + _HDR.size <= len(raw):
        crc, n = _HDR.unpack_from(raw, pos)
        body = raw[pos + _HDR.size:pos + _HDR.size + n]
        if len(body) < n or zlib.crc32(body) != crc:
            return                      # torn tail — end of durable data
        try:
            yield codec.loads(body)
        except ConnectionError:
            return                      # undecodable == torn
        pos += _HDR.size + n


class WriteAheadLog:
    """One append-only record log with a generation header.

    Thread-safety: append/sync/close take an internal lock; the server
    additionally serializes all mutations, so the lock is belt and
    braces for direct users (bench, tests).
    """

    def __init__(self, path, generation=0):
        self.path = path
        self._lock = threading.Lock()
        self._unsynced = 0
        fresh = not os.path.exists(path) or os.path.getsize(path) == 0
        self._f = open(path, "ab")
        self.generation = generation
        if fresh:
            self._append_raw((_HEADER_KIND, int(generation)))
            self.sync()
        else:
            got = read_header(path)
            self.generation = generation if got is None else got

    # -- append side ---------------------------------------------------------
    def _append_raw(self, record):
        buf = _frame(codec.dumps(record))
        self._f.write(buf)
        monitor.stat_add("ps.wal_bytes", len(buf))
        monitor.stat_add("ps.wal_records")
        self._unsynced += 1

    def append(self, record, sync_interval=None):
        """Append one record; fsync once `sync_interval` records are
        pending (None = FLAGS_ps_wal_sync_interval). Passes the
        ``ps.wal_append`` fault site *before* the write lands — a
        ``crash`` there models death with the record lost, which the
        client-side retry must absorb."""
        if sync_interval is None:
            sync_interval = flag("FLAGS_ps_wal_sync_interval")
        with self._lock:
            faults.fault_point("ps.wal_append", record)
            self._append_raw(record)
            if self._unsynced >= max(1, int(sync_interval)):
                self._sync_locked()

    def _sync_locked(self):
        self._f.flush()
        os.fsync(self._f.fileno())
        self._unsynced = 0

    def sync(self):
        with self._lock:
            self._sync_locked()

    @property
    def nbytes(self):
        with self._lock:
            return self._f.tell()

    def close(self):
        with self._lock:
            if self._f is not None and not self._f.closed:
                self._sync_locked()
                self._f.close()

    # -- replay side ---------------------------------------------------------
    @staticmethod
    def replay(path):
        """-> (generation, [records]) — records after the header, torn
        tail tolerated. A file without a valid header replays empty."""
        with open(path, "rb") as f:
            raw = f.read()
        it = _iter_frames(raw)
        head = next(it, None)
        if (not isinstance(head, tuple) or len(head) != 2
                or head[0] != _HEADER_KIND):
            return 0, []
        return int(head[1]), list(it)

    @classmethod
    def rotate(cls, path, generation):
        """Atomically replace `path` with a fresh log at `generation`
        (tmp + fsync + rename, so a crash leaves either the old or the
        new complete file, never a torn one)."""
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(_frame(codec.dumps((_HEADER_KIND, int(generation)))))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        return cls(path, generation=generation)


def read_header(path):
    """Generation of an existing log file, or None if unreadable."""
    try:
        with open(path, "rb") as f:
            raw = f.read(4096)
    except OSError:
        return None
    head = next(_iter_frames(raw), None)
    if (isinstance(head, tuple) and len(head) == 2
            and head[0] == _HEADER_KIND):
        return int(head[1])
    return None


def _table_file(name):
    safe = re.sub(r"[^A-Za-z0-9_.-]", "_", name)[:80]
    return f"t-{safe}-{zlib.crc32(name.encode()):08x}.wal"


class DurableStore:
    """Everything a `PSServer` needs to survive `kill -9`:

    * `log_meta` — create/delete control records (meta.wal)
    * `log_push` — per-table mutation records ``(client_id, seq, cmd,
      args)`` appended before apply
    * `checkpoint` — quiesced snapshot + log rotation (generation bump)
    * `recover` — meta replay -> snapshot load -> per-table log replay,
      driven through caller-supplied hooks so the store never imports
      the table classes
    """

    def __init__(self, directory):
        self.dir = directory
        os.makedirs(directory, exist_ok=True)
        self.generation = self._latest_snapshot_gen()
        self._meta = WriteAheadLog(os.path.join(directory, "meta.wal"),
                                   generation=0)
        self._logs: dict[str, WriteAheadLog] = {}
        self.replayed_records = 0

    # -- logging -------------------------------------------------------------
    def _log(self, table):
        wal = self._logs.get(table)
        if wal is None:
            wal = self._logs[table] = WriteAheadLog(
                os.path.join(self.dir, _table_file(table)),
                generation=self.generation)
            if wal.generation < self.generation:
                # stale pre-snapshot log (crash between snapshot rename
                # and rotation): its records are already folded in
                wal.close()
                wal = self._logs[table] = WriteAheadLog.rotate(
                    os.path.join(self.dir, _table_file(table)),
                    self.generation)
        return wal

    def log_meta(self, cmd, args):
        self._meta.append((cmd, args), sync_interval=1)

    def log_push(self, table, client_id, seq, cmd, args):
        self._log(table).append((client_id, seq, cmd, args))

    def drop_table(self, table):
        wal = self._logs.pop(table, None)
        if wal is not None:
            wal.close()
        try:
            os.unlink(os.path.join(self.dir, _table_file(table)))
        except OSError:
            pass

    def sync(self):
        for wal in self._logs.values():
            wal.sync()

    @property
    def nbytes(self):
        return sum(w.nbytes for w in self._logs.values()) + \
            self._meta.nbytes

    # -- snapshot ------------------------------------------------------------
    def _snap_path(self, gen):
        return os.path.join(self.dir, f"snapshot-{gen}.bin")

    def _latest_snapshot_gen(self):
        best = 0
        for name in os.listdir(self.dir):
            m = re.fullmatch(r"snapshot-(\d+)\.bin", name)
            if m:
                best = max(best, int(m.group(1)))
        return best

    def checkpoint(self, table_states, applied):
        """Write snapshot generation+1 and rotate every table log.

        MUST be called with the server's mutation lock held (the
        quiesce is what makes 'log generation == snapshot generation
        <=> records are post-snapshot' true)."""
        gen = self.generation + 1
        payload = codec.dumps({
            "gen": gen,
            "tables": table_states,
            "applied": [(t, c, s) for (t, c), s in applied.items()],
        })
        tmp = self._snap_path(gen) + ".tmp"
        with open(tmp, "wb") as f:
            f.write(_frame(payload))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._snap_path(gen))
        self.generation = gen
        for table, wal in list(self._logs.items()):
            wal.close()
            self._logs[table] = WriteAheadLog.rotate(wal.path, gen)
        # GC superseded snapshots (newest one is all recovery reads)
        for name in os.listdir(self.dir):
            m = re.fullmatch(r"snapshot-(\d+)\.bin", name)
            if m and int(m.group(1)) < gen:
                try:
                    os.unlink(os.path.join(self.dir, name))
                except OSError:
                    pass
        return gen

    def _load_snapshot(self):
        gen = self._latest_snapshot_gen()
        if gen == 0:
            return 0, None
        with open(self._snap_path(gen), "rb") as f:
            raw = f.read()
        rec = next(_iter_frames(raw), None)
        if rec is None:
            raise WalCorruptError(
                f"snapshot-{gen} failed its checksum; refusing to "
                "recover from corrupt state")
        return gen, rec

    # -- recovery ------------------------------------------------------------
    def recover(self, create, load, apply):
        """Rebuild server state through three hooks:

        create(cmd, args)                — meta record (create_*/delete)
        load(table_name, state_dict)     — snapshot state
        apply(table, cid, seq, cmd, args)— one logged mutation, in order

        -> (applied watermarks {(table, cid): seq}, replayed records).
        """
        for cmd, args in WriteAheadLog.replay(self._meta.path)[1]:
            create(cmd, args)
        gen, snap = self._load_snapshot()
        applied: dict = {}
        if snap is not None:
            self.generation = gen
            for name, sd in snap["tables"].items():
                load(name, sd)
            for t, c, s in snap["applied"]:
                applied[(t, c)] = s
        replayed = 0
        for fname in sorted(os.listdir(self.dir)):
            if not fname.startswith("t-") or not fname.endswith(".wal"):
                continue
            path = os.path.join(self.dir, fname)
            g, records = WriteAheadLog.replay(path)
            if g < self.generation:
                continue          # pre-snapshot: already folded in
            for cid, seq, cmd, args in records:
                table = args[0]
                has_seq = bool(cid) and seq is not None and seq >= 0
                key = (table, cid)
                if has_seq and seq <= applied.get(key, -1):
                    # a retry of an already-logged push (raise fired
                    # between WAL append and ack) left a duplicate
                    # record — replay must dedupe exactly like the
                    # live server did
                    monitor.stat_add("ps.dedup_hits")
                    continue
                apply(table, cid, seq, cmd, args)
                if has_seq:
                    applied[key] = seq
                replayed += 1
        self.replayed_records = replayed
        monitor.stat_add("ps.wal_replayed_records", replayed)
        return applied, replayed

    def close(self):
        self._meta.close()
        for wal in self._logs.values():
            wal.close()
        self._logs = {}
