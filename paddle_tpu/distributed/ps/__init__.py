"""Parameter-server mode (ref paddle/fluid/distributed/ §2.5 + fleet PS
runtime the_one_ps.py).

TPU-native redesign: dense compute stays on the accelerator; huge sparse
tables live on CPU parameter servers (native C++ hash tables,
paddle_tpu/native/ps_table.cc) behind a TCP RPC service. Trainers pull
only the touched rows, push SelectedRows-style gradients through a
sync/async/geo Communicator, and the server applies the optimizer —
the reference's brpc PS split, minus brpc.

Quick start:
    # server process:  TRAINING_ROLE=PSERVER PADDLE_PORT=9000
    fleet.init(ps.PSRoleMaker());  fleet.init_server();  fleet.run_server()
    # trainer process: TRAINING_ROLE=TRAINER
    fleet.init(ps.PSRoleMaker());  fleet.init_worker()
    emb = ps.DistributedEmbedding("emb0", 64, lr=0.1)
"""

from .runtime import (  # noqa: F401
    DistributedEmbedding, PSOptimizer, PSRoleMaker, PSRuntime, get_runtime,
    init_runtime,
)
from .heter import TPUEmbeddingCache  # noqa: F401
from .replica import FencedError, ReplicaLink  # noqa: F401
from .service import (  # noqa: F401
    Communicator, PSClient, PSServer, PSUnavailableError,
)
from .tables import DenseTable, SparseTable, SSDSparseTable  # noqa: F401
from .wal import DurableStore, WalCorruptError, WriteAheadLog  # noqa: F401

__all__ = [
    "PSRoleMaker", "PSRuntime", "PSServer", "PSClient", "Communicator",
    "PSUnavailableError", "DenseTable", "SparseTable", "SSDSparseTable",
    "DistributedEmbedding", "PSOptimizer", "TPUEmbeddingCache",
    "WriteAheadLog", "DurableStore", "WalCorruptError",
    "ReplicaLink", "FencedError",
    "get_runtime", "init_runtime",
]
