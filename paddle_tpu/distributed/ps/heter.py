"""HeterPS analogue: accelerator-resident embedding cache over PS tables.

Ref parity: paddle/fluid/framework/fleet/ps_gpu_wrapper.h:50 +
fleet/heter_ps/ — the reference builds a per-pass "GPU table" of the
feasigns a pass will touch, trains whole passes against accelerator
memory (the optimizer runs on the accelerator), and syncs back to the
host/SSD table at pass end. TPU-native redesign: the cache is one
[capacity, dim] device array (gathers/updates ride the VPU; no per-row
device hashmap — the id->slot map is host-side numpy), misses arrive in
a single batched pull_sparse, the SGD update applies on device from the
lookup's gradient, and `flush()` pushes per-row DELTAS merged by an
optimizer='sum' server table, so multiple trainers compose exactly like
the reference's pass-end sync.

Serving additions (rec.serving): a **staleness-bounded read protocol**.
Every cache keeps a per-table applied-push watermark (`push_version`,
bumped by `invalidate()` — wired to the online trainer's communicator
flushes) and remembers the watermark each resident row was pulled at.
`prepare()` refreshes any row that was explicitly invalidated or whose
pulled version lags the watermark by more than the staleness bound
(`FLAGS_ps_geo_staleness` by default), so no served read observes an
embedding older than the bound in applied pushes. Refresh reuses the
eviction path, which pushes a dirty row's local delta FIRST — refreshing
never loses a local update.
"""

from __future__ import annotations

import weakref

import numpy as np

import jax.numpy as jnp

from ...framework import monitor
from ...framework.flags import flag
from .runtime import get_runtime

# live caches, for aggregate gauges in observe.export (weak: a dropped
# cache must not be kept alive — or counted — by the metrics path)
_CACHES: "weakref.WeakSet" = weakref.WeakSet()


def cache_stats() -> dict:
    """Aggregate gauges over every live TPUEmbeddingCache in the
    process (observe.export reads this for the paddle_rec_* family)."""
    hits = misses = size = capacity = 0
    evictions = invalidations = refreshes = 0
    max_staleness = 0
    for c in list(_CACHES):
        hits += c.hits
        misses += c.misses
        size += c.size
        capacity += c.capacity
        evictions += c.evictions
        invalidations += c.invalidations
        refreshes += c.refreshes
        max_staleness = max(max_staleness, c.max_served_staleness)
    total = hits + misses
    return {
        "hits": hits, "misses": misses,
        "hit_rate": hits / total if total else 0.0,
        "size": size, "capacity": capacity,
        "evictions": evictions, "invalidations": invalidations,
        "refreshes": refreshes,
        "max_served_staleness": max_staleness,
    }


class TPUEmbeddingCache:
    """Device-cached sparse embedding with write-back to the PS.

    lookup ids -> device gather; gradients update the cache ON DEVICE
    (local SGD, ref heter_ps optimizer.cuh); `flush()` (= the
    reference's end_pass) ships accumulated row deltas to the servers.
    `serve()` is the read-only inference path: staleness-checked
    residency, device gather, no gradient hook.
    """

    def __init__(self, name, dim, capacity, *, lr=0.01, init_range=0.05,
                 runtime=None, staleness_bound=None, storage="mem",
                 mem_rows=None):
        self.name = name
        self.dim = int(dim)
        self.capacity = int(capacity)
        self.lr = float(lr)
        self.runtime = runtime or get_runtime()
        # deltas merge server-side: multiple trainers' pass-end syncs sum
        if storage == "ssd":
            self.runtime.client.create_ssd_sparse_table(
                name, dim, optimizer="sum", init_range=init_range,
                mem_rows=self.capacity if mem_rows is None else mem_rows)
        else:
            self.runtime.client.create_sparse_table(
                name, dim, optimizer="sum", init_range=init_range)
        self.cache = jnp.zeros((self.capacity, self.dim), jnp.float32)
        self._base = np.zeros((self.capacity, self.dim), np.float32)
        self._ids = np.full(self.capacity, -1, np.int64)   # slot -> id
        self._slot_of: dict[int, int] = {}                 # id -> slot
        self._dirty = np.zeros(self.capacity, bool)
        self._last_used = np.zeros(self.capacity, np.int64)
        self._clock = 0
        self.hits = 0
        self.misses = 0
        # staleness-bounded read protocol (None = FLAGS_ps_geo_staleness)
        self.staleness_bound = staleness_bound
        self.push_version = 0   # applied-push watermark for this table
        self._row_version = np.zeros(self.capacity, np.int64)
        self._invalid = np.zeros(self.capacity, bool)
        self.evictions = 0
        self.invalidations = 0
        self.refreshes = 0
        self.max_served_staleness = 0
        self.staleness_hist: dict[int, int] = {}
        _CACHES.add(self)

    def _bound(self) -> int:
        b = self.staleness_bound
        return int(flag("FLAGS_ps_geo_staleness") if b is None else b)

    def _observe_staleness(self, lags) -> None:
        lags = np.asarray(lags, np.int64)
        for v in lags.tolist():
            self.staleness_hist[v] = self.staleness_hist.get(v, 0) + 1
        if lags.size:
            m = int(lags.max())
            if m > self.max_served_staleness:
                self.max_served_staleness = m
            monitor.stat_max("rec.max_served_staleness", m)

    # -- cache management ----------------------------------------------------
    def prepare(self, ids) -> None:
        """Ensure every id is resident (the reference's BuildPull /
        pass-begin): one batched pull for all misses; LRU slots not used
        by THIS batch are evicted, dirty ones flushed first. Resident
        rows that were invalidated by an applied push, or whose pulled
        version lags the watermark beyond the staleness bound, are
        refreshed here (evict -> re-pull) before they can be served."""
        uniq = np.unique(np.asarray(ids, np.int64).reshape(-1))
        self._clock += 1
        # staleness-bounded read protocol: refresh BEFORE the hit/miss
        # split so a refreshed row simply re-pulls as a miss below
        res = np.fromiter((self._slot_of.get(int(i), -1) for i in uniq),
                          np.int64, uniq.size)
        have = res[res >= 0]
        if have.size:
            lag = self.push_version - self._row_version[have]
            stale = self._invalid[have] | (lag > self._bound())
            n_stale = int(stale.sum())
            if n_stale:
                self.refreshes += n_stale
                monitor.stat_add("rec.cache_refreshes", n_stale)
                self._evict(have[stale])
            # hits that survive the check are served at this lag;
            # refreshed/missed rows re-pull at the current watermark
            self._observe_staleness(lag[~stale])
        resident = np.fromiter(
            (i in self._slot_of for i in uniq), bool, len(uniq))
        hit_slots = np.fromiter(
            (self._slot_of[i] for i in uniq[resident]), np.int64,
            int(resident.sum()))
        self._last_used[hit_slots] = self._clock
        miss_ids = uniq[~resident]
        self.hits += int(resident.sum())
        self.misses += miss_ids.size
        monitor.stat_add("rec.cache_hits", int(resident.sum()))
        monitor.stat_add("rec.cache_misses", int(miss_ids.size))
        if miss_ids.size == 0:
            return
        if uniq.size > self.capacity:
            # hits are pinned for this batch, so residency needs room
            # for EVERY unique id in it, not just the misses
            raise ValueError(
                f"batch touches {uniq.size} unique rows > cache "
                f"capacity {self.capacity}")
        # deltas still buffered in the communicator (geo accumulator /
        # async queue) must land before the pull, or a re-touched
        # evicted id reads a stale row missing its own update
        self.runtime.communicator.flush()
        # free slots first, then LRU among slots this batch doesn't use
        free = np.nonzero(self._ids < 0)[0]
        need = miss_ids.size - free.size
        victims = np.empty(0, np.int64)
        if need > 0:
            used_now = np.zeros(self.capacity, bool)
            used_now[hit_slots] = True
            cand = np.nonzero(~used_now & (self._ids >= 0))[0]
            order = np.argsort(self._last_used[cand], kind="stable")
            victims = cand[order[:need]]
            self.evictions += int(victims.size)
            monitor.stat_add("rec.cache_evictions", int(victims.size))
            self._evict(victims)
        slots = np.concatenate([free[:miss_ids.size], victims])[
            :miss_ids.size]
        rows = self.runtime.client.pull_sparse(self.name, miss_ids)
        self.cache = self.cache.at[jnp.asarray(slots)].set(
            jnp.asarray(rows))
        self._base[slots] = rows
        self._ids[slots] = miss_ids
        self._dirty[slots] = False
        self._last_used[slots] = self._clock
        # pulled after the flush above, so fresh at the CURRENT watermark
        self._row_version[slots] = self.push_version
        self._invalid[slots] = False
        self._observe_staleness(np.zeros(miss_ids.size, np.int64))
        for i, s in zip(miss_ids.tolist(), slots.tolist()):
            self._slot_of[i] = s

    def _evict(self, slots) -> None:
        dirty = slots[self._dirty[slots]]
        if dirty.size:
            self._push_deltas(dirty)
        for s in slots.tolist():
            self._slot_of.pop(int(self._ids[s]), None)
        self._ids[slots] = -1
        self._dirty[slots] = False
        self._invalid[slots] = False

    def _push_deltas(self, slots) -> None:
        vals = np.asarray(self.cache[jnp.asarray(slots)])
        deltas = vals - self._base[slots]
        self.runtime.communicator.push_sparse(
            self.name, self._ids[slots], deltas)
        self._base[slots] = vals

    def flush(self) -> None:
        """Pass-end sync (ref ps_gpu_wrapper EndPass): push every dirty
        row's delta; the cache stays resident for the next pass."""
        dirty = np.nonzero(self._dirty)[0]
        if dirty.size:
            self._push_deltas(dirty)
            self._dirty[dirty] = False
        self.runtime.communicator.flush()

    # -- invalidation-on-push ------------------------------------------------
    def invalidate(self, ids) -> int:
        """Applied-push hook (wire to `Communicator.on_flush`): advance
        the table's watermark and mark resident rows among `ids` stale.
        The next prepare() re-pulls marked rows; a dirty row's local
        delta is pushed before the re-pull, so nothing local is lost.
        Returns how many resident rows were marked."""
        ids = np.asarray(ids, np.int64).reshape(-1)
        self.push_version += 1
        n = 0
        for i in ids.tolist():
            s = self._slot_of.get(int(i))
            if s is not None:
                self._invalid[s] = True
                n += 1
        if n:
            self.invalidations += n
            monitor.stat_add("rec.cache_invalidations", n)
        return n

    # -- serving-path lookup -------------------------------------------------
    def serve(self, ids):
        """Read-only inference lookup: staleness-checked residency +
        device gather. No gradient hook, no dirty marking — safe to call
        concurrently with a trainer pushing to the same table (the
        invalidate/refresh protocol supplies freshness)."""
        ids_arr = np.asarray(ids, np.int64)
        self.prepare(ids_arr)
        slots = np.fromiter(
            (self._slot_of[i] for i in ids_arr.reshape(-1).tolist()),
            np.int64, ids_arr.size).reshape(ids_arr.shape)
        return self.cache[jnp.asarray(slots)]

    # -- training-path lookup ------------------------------------------------
    def __call__(self, ids):
        from ...core.dispatch import apply
        from ...core.tensor import Tensor

        ids_arr = np.asarray(
            ids._value if isinstance(ids, Tensor) else ids, np.int64)
        self.prepare(ids_arr)
        slots = np.fromiter((self._slot_of[i] for i in
                             ids_arr.reshape(-1).tolist()),
                            np.int64, ids_arr.size).reshape(ids_arr.shape)
        table = Tensor(self.cache, stop_gradient=False)
        touched = np.unique(slots)

        def sgd_hook(grad):
            # the optimizer runs ON the accelerator (ref heter_ps
            # optimizer.cuh): one device op, no host round-trip
            self.cache = self.cache - self.lr * grad._value
            self._dirty[touched] = True
            return None

        table.register_hook(sgd_hook)
        return apply("lookup_table_v2",
                     jnp.asarray(slots, jnp.int32), table,
                     padding_idx=-1)

    @property
    def size(self) -> int:
        return len(self._slot_of)

    @property
    def hit_rate(self) -> float:
        total = self.hits + self.misses
        return self.hits / total if total else 0.0
