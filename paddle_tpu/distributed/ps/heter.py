"""HeterPS analogue: accelerator-resident embedding cache over PS tables.

Ref parity: paddle/fluid/framework/fleet/ps_gpu_wrapper.h:50 +
fleet/heter_ps/ — the reference builds a per-pass "GPU table" of the
feasigns a pass will touch, trains whole passes against accelerator
memory (the optimizer runs on the accelerator), and syncs back to the
host/SSD table at pass end. TPU-native redesign: the cache is one
[capacity, dim] device array (gathers/updates ride the VPU; no per-row
device hashmap — the id->slot map is host-side numpy), misses arrive in
a single batched pull_sparse, the SGD update applies on device from the
lookup's gradient, and `flush()` pushes per-row DELTAS merged by an
optimizer='sum' server table, so multiple trainers compose exactly like
the reference's pass-end sync.
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from .runtime import get_runtime


class TPUEmbeddingCache:
    """Device-cached sparse embedding with write-back to the PS.

    lookup ids -> device gather; gradients update the cache ON DEVICE
    (local SGD, ref heter_ps optimizer.cuh); `flush()` (= the
    reference's end_pass) ships accumulated row deltas to the servers.
    """

    def __init__(self, name, dim, capacity, *, lr=0.01, init_range=0.05,
                 runtime=None):
        self.name = name
        self.dim = int(dim)
        self.capacity = int(capacity)
        self.lr = float(lr)
        self.runtime = runtime or get_runtime()
        # deltas merge server-side: multiple trainers' pass-end syncs sum
        self.runtime.client.create_sparse_table(
            name, dim, optimizer="sum", init_range=init_range)
        self.cache = jnp.zeros((self.capacity, self.dim), jnp.float32)
        self._base = np.zeros((self.capacity, self.dim), np.float32)
        self._ids = np.full(self.capacity, -1, np.int64)   # slot -> id
        self._slot_of: dict[int, int] = {}                 # id -> slot
        self._dirty = np.zeros(self.capacity, bool)
        self._last_used = np.zeros(self.capacity, np.int64)
        self._clock = 0
        self.hits = 0
        self.misses = 0

    # -- cache management ----------------------------------------------------
    def prepare(self, ids) -> None:
        """Ensure every id is resident (the reference's BuildPull /
        pass-begin): one batched pull for all misses; LRU slots not used
        by THIS batch are evicted, dirty ones flushed first."""
        uniq = np.unique(np.asarray(ids, np.int64).reshape(-1))
        self._clock += 1
        resident = np.fromiter(
            (i in self._slot_of for i in uniq), bool, len(uniq))
        hit_slots = np.fromiter(
            (self._slot_of[i] for i in uniq[resident]), np.int64,
            int(resident.sum()))
        self._last_used[hit_slots] = self._clock
        miss_ids = uniq[~resident]
        self.hits += int(resident.sum())
        self.misses += miss_ids.size
        if miss_ids.size == 0:
            return
        if uniq.size > self.capacity:
            # hits are pinned for this batch, so residency needs room
            # for EVERY unique id in it, not just the misses
            raise ValueError(
                f"batch touches {uniq.size} unique rows > cache "
                f"capacity {self.capacity}")
        # deltas still buffered in the communicator (geo accumulator /
        # async queue) must land before the pull, or a re-touched
        # evicted id reads a stale row missing its own update
        self.runtime.communicator.flush()
        # free slots first, then LRU among slots this batch doesn't use
        free = np.nonzero(self._ids < 0)[0]
        need = miss_ids.size - free.size
        victims = np.empty(0, np.int64)
        if need > 0:
            used_now = np.zeros(self.capacity, bool)
            used_now[hit_slots] = True
            cand = np.nonzero(~used_now & (self._ids >= 0))[0]
            order = np.argsort(self._last_used[cand], kind="stable")
            victims = cand[order[:need]]
            self._evict(victims)
        slots = np.concatenate([free[:miss_ids.size], victims])[
            :miss_ids.size]
        rows = self.runtime.client.pull_sparse(self.name, miss_ids)
        self.cache = self.cache.at[jnp.asarray(slots)].set(
            jnp.asarray(rows))
        self._base[slots] = rows
        self._ids[slots] = miss_ids
        self._dirty[slots] = False
        self._last_used[slots] = self._clock
        for i, s in zip(miss_ids.tolist(), slots.tolist()):
            self._slot_of[i] = s

    def _evict(self, slots) -> None:
        dirty = slots[self._dirty[slots]]
        if dirty.size:
            self._push_deltas(dirty)
        for s in slots.tolist():
            self._slot_of.pop(int(self._ids[s]), None)
        self._ids[slots] = -1
        self._dirty[slots] = False

    def _push_deltas(self, slots) -> None:
        vals = np.asarray(self.cache[jnp.asarray(slots)])
        deltas = vals - self._base[slots]
        self.runtime.communicator.push_sparse(
            self.name, self._ids[slots], deltas)
        self._base[slots] = vals

    def flush(self) -> None:
        """Pass-end sync (ref ps_gpu_wrapper EndPass): push every dirty
        row's delta; the cache stays resident for the next pass."""
        dirty = np.nonzero(self._dirty)[0]
        if dirty.size:
            self._push_deltas(dirty)
            self._dirty[dirty] = False
        self.runtime.communicator.flush()

    # -- training-path lookup ------------------------------------------------
    def __call__(self, ids):
        from ...core.dispatch import apply
        from ...core.tensor import Tensor

        ids_arr = np.asarray(
            ids._value if isinstance(ids, Tensor) else ids, np.int64)
        self.prepare(ids_arr)
        slots = np.fromiter((self._slot_of[i] for i in
                             ids_arr.reshape(-1).tolist()),
                            np.int64, ids_arr.size).reshape(ids_arr.shape)
        table = Tensor(self.cache, stop_gradient=False)
        touched = np.unique(slots)

        def sgd_hook(grad):
            # the optimizer runs ON the accelerator (ref heter_ps
            # optimizer.cuh): one device op, no host round-trip
            self.cache = self.cache - self.lr * grad._value
            self._dirty[touched] = True
            return None

        table.register_hook(sgd_hook)
        return apply("lookup_table_v2",
                     jnp.asarray(slots, jnp.int32), table,
                     padding_idx=-1)

    @property
    def hit_rate(self) -> float:
        total = self.hits + self.misses
        return self.hits / total if total else 0.0
