"""Parameter-server runtime: role making, fleet wiring, trainer-side
layers/optimizer.

Ref parity: python/paddle/distributed/fleet/runtime/the_one_ps.py
(TheOnePSRuntime: init_server/run_server/init_worker/stop_worker),
PaddleCloudRoleMaker's PS env contract, and
operators/pscore/distributed_lookup_table_op.cc (the trainer-side sparse
pull) — rebuilt over the TCP service of §service.py.

Env contract (same variable names as the reference):
  TRAINING_ROLE                PSERVER | TRAINER
  PADDLE_PSERVERS_IP_PORT_LIST comma-separated host:port list
  PADDLE_PORT + POD_IP         this server's bind endpoint (server role)
  PADDLE_TRAINERS_NUM          number of trainers
  PADDLE_TRAINER_ID            this trainer's rank

Durability/replication extensions (this runtime's additions):
  PADDLE_PS_WAL_DIR            per-server write-ahead-log directory; set
                               it and the server recovers bitwise after
                               kill -9 (service.py / wal.py)
  PADDLE_PS_BACKUP_ENDPOINT    this server's standby twin — applied
                               mutations forward there under a fencing
                               epoch (replica.py)
  PADDLE_PS_BACKUP_LIST        comma-separated backup endpoint per entry
                               of PADDLE_PSERVERS_IP_PORT_LIST ('' for
                               none); workers fail over to these
  PADDLE_PS_EPOCH              starting fencing epoch of a (re)started
                               server (a relaunched old primary at a
                               stale epoch is rejected by its promoted
                               backup)
"""

from __future__ import annotations

import os

import numpy as np

from .service import Communicator, PSClient, PSServer

__all__ = ["PSRoleMaker", "PSRuntime", "DistributedEmbedding",
           "PSOptimizer", "get_runtime", "init_runtime"]


class PSRoleMaker:
    """ref PaddleCloudRoleMaker (PS mode)."""

    def __init__(self, server_endpoints=None, role=None, trainer_id=None,
                 n_trainers=None):
        env = os.environ
        eps = server_endpoints or env.get(
            "PADDLE_PSERVERS_IP_PORT_LIST", "127.0.0.1:0")
        self.server_endpoints = (eps.split(",")
                                 if isinstance(eps, str) else list(eps))
        self.role = (role or env.get("TRAINING_ROLE", "TRAINER")).upper()
        self.trainer_id = int(trainer_id if trainer_id is not None
                              else env.get("PADDLE_TRAINER_ID", "0"))
        self.n_trainers = int(n_trainers if n_trainers is not None
                              else env.get("PADDLE_TRAINERS_NUM", "1"))

    def is_server(self):
        return self.role == "PSERVER"

    def is_worker(self):
        return not self.is_server()

    def my_server_endpoint(self):
        port = os.environ.get("PADDLE_PORT")
        ip = os.environ.get("POD_IP", "127.0.0.1")
        if port is not None:
            return f"{ip}:{port}"
        return self.server_endpoints[0]


class PSRuntime:
    """ref the_one_ps.py TheOnePSRuntime."""

    def __init__(self, role_maker: PSRoleMaker, mode="async", geo_step=4):
        self.role = role_maker
        self.mode = mode
        self.geo_step = geo_step
        self._server = None
        self._client = None
        self._communicator = None

    # -- server side ---------------------------------------------------------
    def init_server(self):
        env = os.environ
        self._server = PSServer(
            self.role.my_server_endpoint(),
            wal_dir=env.get("PADDLE_PS_WAL_DIR") or None,
            backup=env.get("PADDLE_PS_BACKUP_ENDPOINT") or None,
            epoch=int(env.get("PADDLE_PS_EPOCH", "0")))
        return self._server

    def run_server(self):
        if self._server is None:
            self.init_server()
        self._server.run()

    # -- worker side ---------------------------------------------------------
    def init_worker(self):
        raw = os.environ.get("PADDLE_PS_BACKUP_LIST", "")
        backups = None
        if raw.strip():
            backups = [b.strip() or None for b in raw.split(",")]
            if len(backups) != len(self.role.server_endpoints):
                raise ValueError(
                    "PADDLE_PS_BACKUP_LIST must pair 1:1 with "
                    "PADDLE_PSERVERS_IP_PORT_LIST")
        self._client = PSClient(self.role.server_endpoints,
                                backups=backups)
        self._communicator = Communicator(
            self._client, mode=self.mode, geo_step=self.geo_step).start()
        return self._client

    @property
    def client(self):
        if self._client is None:
            self.init_worker()
        return self._client

    @property
    def communicator(self):
        if self._communicator is None:
            self.init_worker()
        return self._communicator

    def barrier(self):
        self.client.barrier(self.role.n_trainers)

    def stop_worker(self):
        if self._communicator is not None:
            self._communicator.stop()
        if self._client is not None:
            self._client.close()

    def stop_server(self):
        if self._client is not None:
            self._client.stop_servers()
        if self._server is not None:
            self._server.stop()


_runtime: PSRuntime | None = None


def init_runtime(role_maker=None, mode="async", geo_step=4) -> PSRuntime:
    global _runtime
    _runtime = PSRuntime(role_maker or PSRoleMaker(), mode=mode,
                         geo_step=geo_step)
    return _runtime


def get_runtime() -> PSRuntime:
    if _runtime is None:
        raise RuntimeError("PS runtime not initialised; call "
                           "fleet.init(role) with a PS role maker or "
                           "ps.init_runtime() first")
    return _runtime


class DistributedEmbedding:
    """Trainer-side sparse lookup against a PS table (ref
    distributed_lookup_table_op.cc + pscore/send_op.cc).

    forward: pull the unique rows for `ids`, run a local lookup (taped —
    gradients flow), and register a hook that pushes the row gradients
    through the Communicator (async/sync/geo). The table never
    materialises on the trainer: only the touched rows move.
    """

    def __init__(self, name, dim, optimizer="sgd", lr=0.01,
                 init_range=0.05, runtime=None):
        self.name = name
        self.dim = int(dim)
        self.lr = float(lr)
        self.runtime = runtime or get_runtime()
        comm = self.runtime.communicator
        if comm.mode == "geo":
            # geo tables merge parameter deltas; the server optimizer is a
            # plain sum and the SGD scale lives client-side
            self.runtime.client.create_sparse_table(
                name, dim, optimizer="sum", init_range=init_range)
            comm.set_geo_scale(name, -self.lr)
        else:
            self.runtime.client.create_sparse_table(
                name, dim, optimizer=optimizer, lr=lr,
                init_range=init_range)

    def __call__(self, ids):
        import jax.numpy as jnp

        from ...core.tensor import Tensor

        ids_arr = np.asarray(ids._value if isinstance(ids, Tensor) else ids,
                             np.int64)
        flat = ids_arr.reshape(-1)
        uniq, inverse = np.unique(flat, return_inverse=True)
        rows = self.runtime.client.pull_sparse(self.name, uniq)

        table = Tensor(jnp.asarray(rows), stop_gradient=False)
        comm = self.runtime.communicator
        name = self.name
        uniq_ids = uniq

        def push_hook(grad):
            comm.push_sparse(name, uniq_ids, np.asarray(grad._value))
            return None

        table.register_hook(push_hook)
        from ...core.dispatch import apply

        out = apply("lookup_table_v2",
                    jnp.asarray(inverse.reshape(ids_arr.shape), jnp.int32),
                    table, padding_idx=-1)
        return out


class PSOptimizer:
    """Dense-parameter PS path (ref ParameterServerOptimizer +
    communicator dense send): parameters live in DenseTables, the server
    applies the update at push time, trainers pull fresh values.

    Wraps a local model's parameters: `register(params)` uploads initial
    values; `step()` pushes grads + pulls updates (sync) or pushes async
    and pulls every `stale_steps`.
    """

    def __init__(self, parameters, lr=0.01, optimizer="sgd", runtime=None,
                 stale_steps=1):
        self.runtime = runtime or get_runtime()
        self.params = list(parameters)
        self.lr = float(lr)
        self.stale_steps = int(stale_steps)
        self._step_count = 0
        self._names = []
        client = self.runtime.client
        for i, p in enumerate(self.params):
            name = f"dense/{p.name or f'param_{i}'}/{i}"
            self._names.append(name)
            client.create_dense_table(
                name, list(p._value.shape), optimizer=optimizer, lr=lr,
                initial=np.asarray(p._value, np.float32))

    def step(self):
        import jax.numpy as jnp

        comm = self.runtime.communicator
        client = self.runtime.client
        self._step_count += 1
        for p, name in zip(self.params, self._names):
            if p._grad is None:
                continue
            comm.push_dense(name, np.asarray(p._grad, np.float32))
        if comm.mode == "sync" or \
                self._step_count % self.stale_steps == 0:
            comm.flush()
            self.runtime.barrier()
            for p, name in zip(self.params, self._names):
                p._value = jnp.asarray(client.pull_dense(name))

    def clear_grad(self):
        for p in self.params:
            p.clear_grad()
