"""Parameter-server tables: dense + sparse shards with server-side
optimizers.

Ref parity: paddle/fluid/distributed/table/ — CommonDenseTable,
CommonSparseTable (hash sparse embedding, lazy row init), SparseGeoTable
(GeoSGD delta merge). The sparse hot path is the native C++ table
(paddle_tpu/native/ps_table.cc) when the toolchain is available, with a
numpy fallback. The server applies the optimizer (sgd / adagrad / sum
for geo deltas) at push time — trainers never hold optimizer state for
PS-managed parameters, exactly the reference's split.
"""

from __future__ import annotations

import ctypes
import struct
import threading
import zlib

import numpy as np

from ...framework import faults

_I64P = ctypes.POINTER(ctypes.c_int64)
_F32P = ctypes.POINTER(ctypes.c_float)


class DenseTable:
    """Whole-array parameter shard (ref common_dense_table.cc)."""

    def __init__(self, name, shape, dtype="float32", optimizer="sgd",
                 lr=0.01, epsilon=1e-6, initial=None):
        self.name = name
        self.value = (np.zeros(shape, dtype) if initial is None
                      else np.array(initial, dtype))
        self.optimizer = optimizer
        self.lr = float(lr)
        self.epsilon = float(epsilon)
        self._accum = None
        self._lock = threading.Lock()

    def pull(self):
        with self._lock:
            return self.value.copy()

    def push_grad(self, grad):
        grad = np.asarray(grad, self.value.dtype)
        with self._lock:
            if self.optimizer == "sgd":
                self.value -= self.lr * grad
            elif self.optimizer == "adagrad":
                if self._accum is None:
                    self._accum = np.zeros_like(self.value)
                self._accum += grad * grad
                self.value -= self.lr * grad / (
                    np.sqrt(self._accum) + self.epsilon)
            elif self.optimizer == "sum":  # geo delta / metric merge
                self.value += grad
            elif self.optimizer == "max":  # metric merge
                self.value = np.maximum(self.value, grad)
            elif self.optimizer == "min":
                self.value = np.minimum(self.value, grad)
            else:
                raise ValueError(f"unknown optimizer {self.optimizer!r}")

    def set(self, value):
        with self._lock:
            self.value = np.asarray(value, self.value.dtype).copy()

    def state_dict(self):
        # optimizer state rides along: a snapshot that dropped the
        # adagrad accumulator would make post-recovery pushes diverge
        # from the uninterrupted trajectory (WAL bitwise contract)
        with self._lock:
            sd = {"value": self.value.copy()}
            if self._accum is not None:
                sd["accum"] = self._accum.copy()
            return sd

    def load_state_dict(self, sd):
        with self._lock:
            self.value = np.asarray(sd["value"]).copy()
            acc = sd.get("accum")
            self._accum = None if acc is None else \
                np.asarray(acc, self.value.dtype).copy()


class SparseTable:
    """id -> row hash table with lazy init and in-push optimizer
    (ref common_sparse_table.cc). Uses the native C++ table when built."""

    def __init__(self, name, dim, optimizer="sgd", lr=0.01, epsilon=1e-6,
                 init_range=0.05, seed=0, use_native=True):
        self.name = name
        self.dim = int(dim)
        self.optimizer = optimizer
        self.lr = float(lr)
        self.epsilon = float(epsilon)
        self.init_range = float(init_range)
        self.seed = int(seed)
        self._lock = threading.Lock()
        self._lib = None
        self._handle = None
        if use_native:
            from ...native import ps_table_lib

            self._lib = ps_table_lib()
        if self._lib is not None:
            self._handle = self._lib.pst_create(
                self.dim, ctypes.c_float(-self.init_range),
                ctypes.c_float(self.init_range),
                ctypes.c_uint64(self.seed))
        else:
            self._rows: dict[int, np.ndarray] = {}
            self._accum: dict[int, np.ndarray] = {}

    def __del__(self):
        lib, h = getattr(self, "_lib", None), getattr(self, "_handle", None)
        if lib is not None and h is not None:
            lib.pst_free(h)

    # -- numpy fallback helpers ---------------------------------------------
    def _py_row(self, i):
        r = self._rows.get(i)
        if r is None:
            rng = np.random.RandomState((self.seed * 0x9E3779B9 + i)
                                        & 0x7FFFFFFF)
            r = rng.uniform(-self.init_range, self.init_range,
                            self.dim).astype(np.float32)
            self._rows[i] = r
        return r

    def _native_push(self, prefix, handle, ids, grads):
        """Optimizer dispatch shared by the in-RAM and SSD native
        tables (prefix 'pst' / 'pst_ssd')."""
        if self.optimizer == "sgd":
            getattr(self._lib, f"{prefix}_push_sgd")(
                handle, ids.ctypes.data_as(_I64P), ids.shape[0],
                grads.ctypes.data_as(_F32P), ctypes.c_float(self.lr))
        elif self.optimizer == "adagrad":
            getattr(self._lib, f"{prefix}_push_adagrad")(
                handle, ids.ctypes.data_as(_I64P), ids.shape[0],
                grads.ctypes.data_as(_F32P), ctypes.c_float(self.lr),
                ctypes.c_float(self.epsilon))
        elif self.optimizer == "sum":
            getattr(self._lib, f"{prefix}_push_delta")(
                handle, ids.ctypes.data_as(_I64P), ids.shape[0],
                grads.ctypes.data_as(_F32P))
        else:
            raise ValueError(f"unknown optimizer {self.optimizer!r}")

    # -- API -----------------------------------------------------------------
    def pull(self, ids):
        ids = np.ascontiguousarray(np.asarray(ids, np.int64).reshape(-1))
        out = np.empty((ids.shape[0], self.dim), np.float32)
        with self._lock:
            if self._handle is not None:
                self._lib.pst_pull(self._handle,
                                   ids.ctypes.data_as(_I64P), ids.shape[0],
                                   out.ctypes.data_as(_F32P))
            else:
                for k, i in enumerate(ids):
                    out[k] = self._py_row(int(i))
        return out

    def push_grad(self, ids, grads):
        ids = np.ascontiguousarray(np.asarray(ids, np.int64).reshape(-1))
        grads = np.ascontiguousarray(
            np.asarray(grads, np.float32).reshape(ids.shape[0], self.dim))
        with self._lock:
            if self._handle is not None:
                self._native_push("pst", self._handle, ids, grads)
                return
            for k, i in enumerate(ids):
                i = int(i)
                r = self._py_row(i)
                g = grads[k]
                if self.optimizer == "sgd":
                    r -= self.lr * g
                elif self.optimizer == "adagrad":
                    a = self._accum.setdefault(
                        i, np.zeros(self.dim, np.float32))
                    a += g * g
                    r -= self.lr * g / (np.sqrt(a) + self.epsilon)
                elif self.optimizer == "sum":
                    r += g
                else:
                    raise ValueError(
                        f"unknown optimizer {self.optimizer!r}")

    def __len__(self):
        with self._lock:
            if self._handle is not None:
                return int(self._lib.pst_size(self._handle))
            return len(self._rows)

    def state_dict(self):
        # NOTE native limitation: the C++ table exports rows only (no
        # pst_export_accum entry point), so a snapshot of a *native*
        # adagrad table loses the accumulator — replay-from-genesis
        # recovery stays bitwise-exact, snapshot-based recovery of a
        # native adagrad table is value-only. The python fallback
        # exports "accums" alongside "rows" and is fully exact.
        with self._lock:
            if self._handle is not None:
                n = int(self._lib.pst_size(self._handle))
                ids = np.empty(n, np.int64)
                rows = np.empty((n, self.dim), np.float32)
                if n:
                    self._lib.pst_export(self._handle,
                                         ids.ctypes.data_as(_I64P),
                                         rows.ctypes.data_as(_F32P))
                    # hash-map iteration order is arbitrary: export
                    # sorted so snapshots are deterministic/diffable
                    order = np.argsort(ids, kind="stable")
                    ids, rows = ids[order], rows[order]
                return {"ids": ids, "rows": rows}
            ids = np.array(sorted(self._rows), np.int64)
            rows = (np.stack([self._rows[int(i)] for i in ids])
                    if len(ids) else np.empty((0, self.dim), np.float32))
            sd = {"ids": ids, "rows": rows}
            if self.optimizer == "adagrad":
                zero = np.zeros(self.dim, np.float32)
                sd["accums"] = (
                    np.stack([self._accum.get(int(i), zero) for i in ids])
                    if len(ids) else np.empty((0, self.dim), np.float32))
            return sd

    def load_state_dict(self, sd):
        ids = np.ascontiguousarray(np.asarray(sd["ids"], np.int64))
        rows = np.ascontiguousarray(np.asarray(sd["rows"], np.float32))
        with self._lock:
            if self._handle is not None:
                self._lib.pst_import(self._handle,
                                     ids.ctypes.data_as(_I64P),
                                     ids.shape[0],
                                     rows.ctypes.data_as(_F32P))
            else:
                for i, r in zip(ids, rows):
                    self._rows[int(i)] = r.copy()
                accs = sd.get("accums")
                if accs is not None:
                    for i, a in zip(ids, np.asarray(accs, np.float32)):
                        self._accum[int(i)] = a.copy()


class SSDSparseTable(SparseTable):
    """Beyond-RAM sparse embedding: hot rows in memory, cold rows
    spilled to disk (ref ssd_sparse_table.h, which pairs an in-memory
    shard with rocksdb).

    Design: the in-memory dict is an LRU of at most `mem_rows` rows;
    eviction appends the row (and its adagrad accumulator, when used) as
    a fixed-size record to an append-only spill file, with an in-memory
    id -> offset index pointing at the newest record.  Re-touching a
    spilled id reads it back and re-inserts it hot.  When dead records
    exceed half the file, it is compacted in place.  No rocksdb in the
    image — fixed-record append + index IS the LSM level this workload
    needs (point lookups by id, whole-table scan at save time).
    """

    def __init__(self, name, dim, optimizer="sgd", lr=0.01, epsilon=1e-6,
                 init_range=0.05, seed=0, mem_rows=100_000,
                 spill_dir=None, use_native=True):
        # base class stays on python rows; the native SSD table (when
        # available and requested) owns the whole LRU+spill hot path in
        # C++ — the python machinery below remains the reference
        # implementation the conformance tests diff against
        super().__init__(name, dim, optimizer=optimizer, lr=lr,
                         epsilon=epsilon, init_range=init_range,
                         seed=seed, use_native=False)
        import os
        import tempfile
        from collections import OrderedDict

        self.mem_rows = int(mem_rows)
        self._owns_spill_dir = spill_dir is None
        self._spill_dir = spill_dir or tempfile.mkdtemp(
            prefix=f"pst_ssd_{name}_")
        os.makedirs(self._spill_dir, exist_ok=True)
        self._has_accum = optimizer == "adagrad"
        self._rec_dim = self.dim * (2 if self._has_accum else 1)
        # i64 id + f32 payload + trailing crc32 — a torn or bit-rotted
        # spill record fails its checksum at read instead of handing a
        # corrupt embedding row back to training
        self._rec_bytes = 8 + 4 * self._rec_dim + 4
        self._ssd_handle = None
        self._spill_f = None
        self._closed = False
        if use_native:
            from ...native import ps_table_lib

            lib = ps_table_lib()
            if lib is not None and hasattr(lib, "pst_ssd_create"):
                native_path = os.path.join(self._spill_dir,
                                           "rows_native.bin")
                h = lib.pst_ssd_create(
                    self.dim, ctypes.c_float(-self.init_range),
                    ctypes.c_float(self.init_range),
                    ctypes.c_uint64(self.seed),
                    ctypes.c_int64(self.mem_rows),
                    native_path.encode(),
                    1 if self._has_accum else 0)
                if h:
                    self._lib = lib
                    self._ssd_handle = h
        if self._ssd_handle is None:
            # python spill apparatus built only when actually used —
            # native tables would otherwise hold a dead fd + file each
            self._rows = OrderedDict()  # LRU: oldest first
            self._spill_path = os.path.join(self._spill_dir, "rows.bin")
            try:
                # a crash mid-_compact can strand the tmp file; the
                # replace never happened so rows.bin is intact — just
                # clear the leftover
                os.unlink(self._spill_path + ".compact")
            except OSError:
                pass
            self._spill_f = open(self._spill_path, "w+b")
            self._index: dict[int, int] = {}  # id -> file offset
            self._dead_records = 0

    # -- spill machinery -----------------------------------------------------
    def _record(self, i):
        row = self._rows[i]
        if self._has_accum:
            acc = self._accum.get(i)
            if acc is None:
                acc = np.zeros(self.dim, np.float32)
            payload = np.concatenate([row, acc])
        else:
            payload = row
        body = np.int64(i).tobytes() + \
            payload.astype(np.float32).tobytes()
        return body + struct.pack("<I", zlib.crc32(body))

    def _check_rec(self, rec, i):
        """Verify one spill record's frame + checksum; -> f32 payload."""
        if len(rec) != self._rec_bytes:
            raise RuntimeError(
                f"SSD table {self.name!r}: torn spill record for id "
                f"{i} ({len(rec)}/{self._rec_bytes} bytes)")
        (crc,) = struct.unpack("<I", rec[-4:])
        if zlib.crc32(rec[:-4]) != crc:
            raise RuntimeError(
                f"SSD table {self.name!r}: spill record for id {i} "
                "failed its checksum (torn write or bit rot)")
        return np.frombuffer(rec[8:-4], np.float32)

    def _evict_lru(self):
        if len(self._rows) > self.mem_rows:
            # the mid-spill fault site: a crash here loses only cache
            # state (the WAL is the durability story); an ioerror here
            # models a full/failing spill disk
            faults.fault_point("ps.spill", tag=self.name)
        while len(self._rows) > self.mem_rows:
            i, _ = next(iter(self._rows.items()))
            if i in self._index:
                self._dead_records += 1
            self._spill_f.seek(0, 2)
            self._index[i] = self._spill_f.tell()
            self._spill_f.write(self._record(i))
            del self._rows[i]
            self._accum.pop(i, None)
        if self._dead_records > max(64, len(self._index)):
            self._compact()

    def _read_spilled(self, i):
        off = self._index.get(i)
        if off is None:
            return False
        self._spill_f.seek(off)
        payload = self._check_rec(
            self._spill_f.read(self._rec_bytes), i)
        self._rows[i] = payload[:self.dim].copy()
        if self._has_accum:
            self._accum[i] = payload[self.dim:].copy()
        del self._index[i]
        self._dead_records += 1
        return True

    def _compact(self):
        import os

        faults.fault_point("ps.spill", tag=self.name)
        new_path = self._spill_path + ".compact"
        try:
            with open(new_path, "w+b") as nf:
                new_index = {}
                for i, off in self._index.items():
                    self._spill_f.seek(off)
                    rec = self._spill_f.read(self._rec_bytes)
                    self._check_rec(rec, i)  # never propagate torn data
                    new_index[i] = nf.tell()
                    nf.write(rec)
                nf.flush()
                os.fsync(nf.fileno())
        except BaseException:
            # crash-safe: the live file is untouched until the replace
            try:
                os.unlink(new_path)
            except OSError:
                pass
            raise
        self._spill_f.close()
        os.replace(new_path, self._spill_path)
        self._spill_f = open(self._spill_path, "r+b")
        self._index = new_index
        self._dead_records = 0

    def _py_row(self, i):
        r = self._rows.get(i)
        if r is not None:
            self._rows.move_to_end(i)  # LRU touch
            return r
        if self._read_spilled(i):
            return self._rows[i]
        return super()._py_row(i)

    @property
    def _native_mode(self):
        # dispatch on table KIND, not live handle: a closed native
        # table must raise (via _native_handle) rather than silently
        # fall through to the empty python fallback and hand back
        # freshly-initialised rows
        return self._spill_f is None

    def pull(self, ids):
        self._check_open()
        if self._native_mode:
            ids = np.ascontiguousarray(
                np.asarray(ids, np.int64).reshape(-1))
            out = np.empty((ids.shape[0], self.dim), np.float32)
            with self._lock:
                self._lib.pst_ssd_pull(self._native_handle(),
                                       ids.ctypes.data_as(_I64P),
                                       ids.shape[0],
                                       out.ctypes.data_as(_F32P))
            return out
        out = super().pull(ids)
        with self._lock:
            self._evict_lru()
        return out

    def push_grad(self, ids, grads):
        self._check_open()
        if self._native_mode:
            ids = np.ascontiguousarray(
                np.asarray(ids, np.int64).reshape(-1))
            grads = np.ascontiguousarray(
                np.asarray(grads, np.float32).reshape(ids.shape[0],
                                                      self.dim))
            with self._lock:
                self._native_push("pst_ssd", self._native_handle(),
                                  ids, grads)
            return
        super().push_grad(ids, grads)
        with self._lock:
            self._evict_lru()

    def resident_rows(self):
        """In-memory (hot) row count — observability for the LRU bound."""
        self._check_open()
        with self._lock:
            if self._native_mode:
                return int(self._lib.pst_ssd_resident(self._native_handle()))
            return len(self._rows)

    def spilled_rows(self):
        self._check_open()
        with self._lock:
            if self._native_mode:
                return int(self._lib.pst_ssd_spilled(self._native_handle()))
            return len(self._index)

    def __len__(self):
        self._check_open()
        with self._lock:
            if self._native_mode:
                return int(self._lib.pst_ssd_size(self._native_handle()))
            return len(self._rows) + len(self._index)

    def state_dict(self):
        # one lock for the WHOLE export (base-class contract: a save
        # must be an atomic snapshot, never interleaved with pushes);
        # spilled rows are peeked read-only so the export causes no LRU
        # churn
        self._check_open()
        with self._lock:
            if self._native_mode:
                h = self._native_handle()
                n = int(self._lib.pst_ssd_size(h))
                ids = np.empty(n, np.int64)
                rows = np.empty((n, self.dim), np.float32)
                if n:
                    # export returns the FILLED count: unreadable spill
                    # records are skipped, never exported as garbage
                    filled = int(self._lib.pst_ssd_export(
                        h, ids.ctypes.data_as(_I64P),
                        rows.ctypes.data_as(_F32P)))
                    ids, rows = ids[:filled], rows[:filled]
                    order = np.argsort(ids, kind="stable")
                    ids, rows = ids[order], rows[order]
                return {"ids": ids, "rows": rows}
            ids = sorted(set(self._rows) | set(self._index))
            rows = np.empty((len(ids), self.dim), np.float32)
            accs = (np.zeros((len(ids), self.dim), np.float32)
                    if self._has_accum else None)
            for k, i in enumerate(ids):
                i = int(i)
                r = self._rows.get(i)
                if r is None:
                    self._spill_f.seek(self._index[i])
                    payload = self._check_rec(
                        self._spill_f.read(self._rec_bytes), i)
                    r = payload[:self.dim]
                    if accs is not None:
                        accs[k] = payload[self.dim:]
                elif accs is not None and i in self._accum:
                    accs[k] = self._accum[i]
                rows[k] = r
            sd = {"ids": np.asarray(ids, np.int64), "rows": rows}
            if accs is not None:
                sd["accums"] = accs
            return sd

    def load_state_dict(self, sd):
        self._check_open()
        if self._native_mode:
            ids = np.ascontiguousarray(np.asarray(sd["ids"], np.int64))
            rows = np.ascontiguousarray(
                np.asarray(sd["rows"], np.float32))
            with self._lock:
                self._lib.pst_ssd_import(self._native_handle(),
                                         ids.ctypes.data_as(_I64P),
                                         ids.shape[0],
                                         rows.ctypes.data_as(_F32P))
            return
        super().load_state_dict(sd)
        with self._lock:
            self._evict_lru()

    def close(self):
        """Release the spill file/handle and delete a self-created spill
        dir (delete_table / server shutdown path).  Idempotent — a
        second close (or `__del__` after an explicit close) is a no-op.
        Takes the table lock so an in-flight pull/push finishes before
        the native object is freed (the PS server is a thread pool)."""
        import os
        import shutil

        if getattr(self, "_closed", True):
            return          # already closed, or __init__ never finished
        with self._lock:
            if self._closed:
                return
            self._closed = True
            if self._ssd_handle is not None:
                self._lib.pst_ssd_free(self._ssd_handle)
                self._ssd_handle = None
            if self._spill_f is not None:
                try:
                    self._spill_f.close()
                except Exception:  # noqa: BLE001 — already closed
                    pass
                self._spill_f = None
        if getattr(self, "_owns_spill_dir", False) and \
                os.path.isdir(self._spill_dir):
            shutil.rmtree(self._spill_dir, ignore_errors=True)

    def _check_open(self):
        if self._closed:
            raise RuntimeError(f"SSD table {self.name!r} is closed")

    def _native_handle(self):
        """Handle re-read UNDER the lock: a concurrent close() nulls it,
        and calling into freed native memory would be a use-after-free —
        raise instead."""
        h = self._ssd_handle
        if h is None:
            raise RuntimeError(f"SSD table {self.name!r} is closed")
        return h

    def __del__(self):
        try:
            self.close()
        except Exception:  # noqa: BLE001 — interpreter teardown
            pass


class GraphTable:
    """Server-side graph store for GNN sampling workers (ref
    common_graph_table.h: add edges, weighted neighbour sampling, node
    features).  Adjacency is per-node id/weight arrays with cumulative
    weights precomputed at first sample, so each sample_neighbors RPC is
    a vectorised searchsorted draw."""

    def __init__(self, name, seed=0):
        self.name = name
        self._adj: dict[int, list] = {}     # id -> [ids list, w list]
        self._cum: dict[int, np.ndarray] = {}
        self._feat: dict[int, np.ndarray] = {}
        self._rng = np.random.RandomState(seed)
        self._lock = threading.Lock()

    def add_edges(self, src, dst, weight=None):
        src = np.asarray(src, np.int64).reshape(-1)
        dst = np.asarray(dst, np.int64).reshape(-1)
        w = (np.ones(len(src), np.float32) if weight is None
             else np.asarray(weight, np.float32).reshape(-1))
        with self._lock:
            for s, d, ww in zip(src, dst, w):
                ent = self._adj.setdefault(int(s), [[], []])
                ent[0].append(int(d))
                ent[1].append(float(ww))
                self._cum.pop(int(s), None)
        return None

    def sample_neighbors(self, ids, n):
        """For each id: n neighbours drawn with probability proportional
        to edge weight (with replacement, reference sampling semantics);
        isolated nodes return -1 padding."""
        ids = np.asarray(ids, np.int64).reshape(-1)
        out = np.full((len(ids), n), -1, np.int64)
        with self._lock:
            for k, i in enumerate(ids):
                i = int(i)
                ent = self._adj.get(i)
                if not ent or not ent[0]:
                    continue
                cum = self._cum.get(i)
                if cum is None:
                    cum = np.cumsum(np.asarray(ent[1], np.float64))
                    self._cum[i] = cum
                draws = self._rng.rand(n) * cum[-1]
                out[k] = np.asarray(ent[0], np.int64)[
                    np.searchsorted(cum, draws)]
        return out

    def degree(self, ids):
        ids = np.asarray(ids, np.int64).reshape(-1)
        with self._lock:
            return np.asarray(
                [len(self._adj.get(int(i), [[], []])[0]) for i in ids],
                np.int64)

    def set_node_feat(self, ids, feats):
        ids = np.asarray(ids, np.int64).reshape(-1)
        feats = np.asarray(feats, np.float32)
        with self._lock:
            for i, f in zip(ids, feats):
                self._feat[int(i)] = f.copy()
        return None

    def get_node_feat(self, ids, dim):
        ids = np.asarray(ids, np.int64).reshape(-1)
        out = np.zeros((len(ids), dim), np.float32)
        with self._lock:
            for k, i in enumerate(ids):
                f = self._feat.get(int(i))
                if f is not None:
                    out[k] = f
        return out

    def state_dict(self):
        with self._lock:
            return {
                "adj": {i: (np.asarray(e[0], np.int64),
                            np.asarray(e[1], np.float32))
                        for i, e in self._adj.items()},
                "feat": dict(self._feat),
            }

    def load_state_dict(self, sd):
        with self._lock:
            self._adj = {int(i): [list(map(int, e[0])),
                                  list(map(float, e[1]))]
                         for i, e in sd["adj"].items()}
            self._cum = {}
            self._feat = {int(i): np.asarray(f, np.float32)
                          for i, f in sd["feat"].items()}
