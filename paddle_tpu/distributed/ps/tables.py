"""Parameter-server tables: dense + sparse shards with server-side
optimizers.

Ref parity: paddle/fluid/distributed/table/ — CommonDenseTable,
CommonSparseTable (hash sparse embedding, lazy row init), SparseGeoTable
(GeoSGD delta merge). The sparse hot path is the native C++ table
(paddle_tpu/native/ps_table.cc) when the toolchain is available, with a
numpy fallback. The server applies the optimizer (sgd / adagrad / sum
for geo deltas) at push time — trainers never hold optimizer state for
PS-managed parameters, exactly the reference's split.
"""

from __future__ import annotations

import ctypes
import threading

import numpy as np

_I64P = ctypes.POINTER(ctypes.c_int64)
_F32P = ctypes.POINTER(ctypes.c_float)


class DenseTable:
    """Whole-array parameter shard (ref common_dense_table.cc)."""

    def __init__(self, name, shape, dtype="float32", optimizer="sgd",
                 lr=0.01, epsilon=1e-6, initial=None):
        self.name = name
        self.value = (np.zeros(shape, dtype) if initial is None
                      else np.array(initial, dtype))
        self.optimizer = optimizer
        self.lr = float(lr)
        self.epsilon = float(epsilon)
        self._accum = None
        self._lock = threading.Lock()

    def pull(self):
        with self._lock:
            return self.value.copy()

    def push_grad(self, grad):
        grad = np.asarray(grad, self.value.dtype)
        with self._lock:
            if self.optimizer == "sgd":
                self.value -= self.lr * grad
            elif self.optimizer == "adagrad":
                if self._accum is None:
                    self._accum = np.zeros_like(self.value)
                self._accum += grad * grad
                self.value -= self.lr * grad / (
                    np.sqrt(self._accum) + self.epsilon)
            elif self.optimizer == "sum":  # geo delta / metric merge
                self.value += grad
            elif self.optimizer == "max":  # metric merge
                self.value = np.maximum(self.value, grad)
            elif self.optimizer == "min":
                self.value = np.minimum(self.value, grad)
            else:
                raise ValueError(f"unknown optimizer {self.optimizer!r}")

    def set(self, value):
        with self._lock:
            self.value = np.asarray(value, self.value.dtype).copy()

    def state_dict(self):
        with self._lock:
            return {"value": self.value.copy()}

    def load_state_dict(self, sd):
        with self._lock:
            self.value = np.asarray(sd["value"]).copy()


class SparseTable:
    """id -> row hash table with lazy init and in-push optimizer
    (ref common_sparse_table.cc). Uses the native C++ table when built."""

    def __init__(self, name, dim, optimizer="sgd", lr=0.01, epsilon=1e-6,
                 init_range=0.05, seed=0, use_native=True):
        self.name = name
        self.dim = int(dim)
        self.optimizer = optimizer
        self.lr = float(lr)
        self.epsilon = float(epsilon)
        self.init_range = float(init_range)
        self.seed = int(seed)
        self._lock = threading.Lock()
        self._lib = None
        self._handle = None
        if use_native:
            from ...native import ps_table_lib

            self._lib = ps_table_lib()
        if self._lib is not None:
            self._handle = self._lib.pst_create(
                self.dim, ctypes.c_float(-self.init_range),
                ctypes.c_float(self.init_range),
                ctypes.c_uint64(self.seed))
        else:
            self._rows: dict[int, np.ndarray] = {}
            self._accum: dict[int, np.ndarray] = {}

    def __del__(self):
        lib, h = getattr(self, "_lib", None), getattr(self, "_handle", None)
        if lib is not None and h is not None:
            lib.pst_free(h)

    # -- numpy fallback helpers ---------------------------------------------
    def _py_row(self, i):
        r = self._rows.get(i)
        if r is None:
            rng = np.random.RandomState((self.seed * 0x9E3779B9 + i)
                                        & 0x7FFFFFFF)
            r = rng.uniform(-self.init_range, self.init_range,
                            self.dim).astype(np.float32)
            self._rows[i] = r
        return r

    # -- API -----------------------------------------------------------------
    def pull(self, ids):
        ids = np.ascontiguousarray(np.asarray(ids, np.int64).reshape(-1))
        out = np.empty((ids.shape[0], self.dim), np.float32)
        with self._lock:
            if self._handle is not None:
                self._lib.pst_pull(self._handle,
                                   ids.ctypes.data_as(_I64P), ids.shape[0],
                                   out.ctypes.data_as(_F32P))
            else:
                for k, i in enumerate(ids):
                    out[k] = self._py_row(int(i))
        return out

    def push_grad(self, ids, grads):
        ids = np.ascontiguousarray(np.asarray(ids, np.int64).reshape(-1))
        grads = np.ascontiguousarray(
            np.asarray(grads, np.float32).reshape(ids.shape[0], self.dim))
        with self._lock:
            if self._handle is not None:
                if self.optimizer == "sgd":
                    self._lib.pst_push_sgd(
                        self._handle, ids.ctypes.data_as(_I64P),
                        ids.shape[0], grads.ctypes.data_as(_F32P),
                        ctypes.c_float(self.lr))
                elif self.optimizer == "adagrad":
                    self._lib.pst_push_adagrad(
                        self._handle, ids.ctypes.data_as(_I64P),
                        ids.shape[0], grads.ctypes.data_as(_F32P),
                        ctypes.c_float(self.lr),
                        ctypes.c_float(self.epsilon))
                elif self.optimizer == "sum":
                    self._lib.pst_push_delta(
                        self._handle, ids.ctypes.data_as(_I64P),
                        ids.shape[0], grads.ctypes.data_as(_F32P))
                else:
                    raise ValueError(
                        f"unknown optimizer {self.optimizer!r}")
                return
            for k, i in enumerate(ids):
                i = int(i)
                r = self._py_row(i)
                g = grads[k]
                if self.optimizer == "sgd":
                    r -= self.lr * g
                elif self.optimizer == "adagrad":
                    a = self._accum.setdefault(
                        i, np.zeros(self.dim, np.float32))
                    a += g * g
                    r -= self.lr * g / (np.sqrt(a) + self.epsilon)
                elif self.optimizer == "sum":
                    r += g
                else:
                    raise ValueError(
                        f"unknown optimizer {self.optimizer!r}")

    def __len__(self):
        with self._lock:
            if self._handle is not None:
                return int(self._lib.pst_size(self._handle))
            return len(self._rows)

    def state_dict(self):
        with self._lock:
            if self._handle is not None:
                n = int(self._lib.pst_size(self._handle))
                ids = np.empty(n, np.int64)
                rows = np.empty((n, self.dim), np.float32)
                if n:
                    self._lib.pst_export(self._handle,
                                         ids.ctypes.data_as(_I64P),
                                         rows.ctypes.data_as(_F32P))
                return {"ids": ids, "rows": rows}
            ids = np.array(sorted(self._rows), np.int64)
            rows = (np.stack([self._rows[int(i)] for i in ids])
                    if len(ids) else np.empty((0, self.dim), np.float32))
            return {"ids": ids, "rows": rows}

    def load_state_dict(self, sd):
        ids = np.ascontiguousarray(np.asarray(sd["ids"], np.int64))
        rows = np.ascontiguousarray(np.asarray(sd["rows"], np.float32))
        with self._lock:
            if self._handle is not None:
                self._lib.pst_import(self._handle,
                                     ids.ctypes.data_as(_I64P),
                                     ids.shape[0],
                                     rows.ctypes.data_as(_F32P))
            else:
                for i, r in zip(ids, rows):
                    self._rows[int(i)] = r.copy()
