"""Primary/backup replication for the PS tier, with zombie fencing.

Each key shard (one `PSServer` in the client's endpoint list) may have a
standby twin. The primary forwards every *applied* mutation to its
backup as a ``replicate`` command carrying a **fencing epoch**; the
backup applies it through the same dedup + WAL path as a client push, so
after a failover it already holds (almost all of) the primary's state
and the client's retry of the one in-flight push lands exactly once.

Failover is client-driven (there is no coordinator to lose): when the
client exhausts its reconnect budget against a primary it sends
``promote(epoch+1)`` to the backup and swaps the pair. The epoch is the
fence — a restarted *old* primary still forwarding at the stale epoch is
rejected with `FencedError` by the promoted backup, learns it has been
superseded, and refuses further client mutations instead of splitting
the brain.

Forwarding modes:

* ``sync`` (default) — forward inline before the push is acknowledged.
  Replication lag is zero; an acknowledged push can never be lost to a
  primary death (this is what the exactly-once certification runs).
* async — forwards queue and a drain thread ships them; the
  ``ps.replication_lag_updates`` gauge tracks the queue depth. A
  primary death can lose the queued tail, which the backup's dedup +
  client retry bounds to the *unacknowledged* pushes only if callers
  also run the WAL — documented trade, off by default.

Fault site ``ps.replicate`` fires on every forward (``raise`` = link
hiccup: the primary drops the link, counts it, and keeps serving —
availability over replication; ``delay`` = slow backup).
"""

from __future__ import annotations

import hashlib
import hmac
import socket
import threading

from ...framework import faults, monitor

__all__ = ["FencedError", "ReplicaLink"]


class FencedError(RuntimeError):
    """A mutation arrived under a stale fencing epoch (zombie primary),
    or at a server that has learned it was superseded. Deliberately NOT
    retriable: retrying cannot make an old epoch new again."""


class ReplicaLink:
    """Primary-side connection that mirrors applied mutations to the
    backup endpoint. One link per server; the server calls `forward()`
    under its mutation lock, so records arrive at the backup in apply
    order."""

    def __init__(self, endpoint, sync=True, on_fenced=None):
        self.endpoint = endpoint
        self.sync = sync
        self.on_fenced = on_fenced    # primary's "I am a zombie" hook
        self.lost = False             # backup unreachable — link dropped
        self.fenced = False
        self._sock = None
        self._lock = threading.Lock()
        self._queue: list = []
        self._cv = threading.Condition(self._lock)
        self._thread = None
        if not sync:
            self._thread = threading.Thread(target=self._drain,
                                            daemon=True)
            self._thread.start()

    # -- transport (the client handshake, inlined to avoid a cycle) ----------
    def _connect(self):
        from .service import _MAGIC, _auth_key, _recv_exact

        host, port = self.endpoint.rsplit(":", 1)
        s = socket.create_connection((host, int(port)), timeout=10.0)
        s.settimeout(30.0)
        s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        try:
            head = _recv_exact(s, 20)
            if head[:4] != _MAGIC:
                raise ConnectionError("bad PS handshake magic")
            s.sendall(hmac.new(_auth_key(), head[4:],
                               hashlib.sha256).digest())
            if _recv_exact(s, 2) != b"OK":
                raise ConnectionError("replica link authentication failed")
        except BaseException:
            s.close()
            raise
        return s

    def _ship(self, msg):
        """One RPC to the backup; raises on transport error/rejection."""
        from .service import _recv_msg, _send_msg

        faults.fault_point("ps.replicate", msg)
        if self._sock is None:
            self._sock = self._connect()
        try:
            _send_msg(self._sock, msg)
            status, result = _recv_msg(self._sock)
        except (ConnectionError, OSError):
            try:
                self._sock.close()
            finally:
                self._sock = None
            raise
        if status == "ok":
            return
        if status == "errR":
            # transient backup-side error (e.g. an injected fault at its
            # own ps.push site): a link hiccup, not a verdict — let the
            # forward loop retry or drop the link, socket stays good
            raise ConnectionError(
                f"transient backup error from {self.endpoint}: {result}")
        if "FencedError" in str(result):
            self.fenced = True
            monitor.stat_add("ps.replication_fenced")
            if self.on_fenced is not None:
                self.on_fenced()
            raise FencedError(str(result))
        raise RuntimeError(f"replicate rejected by {self.endpoint}: "
                           f"{result}")

    # -- public --------------------------------------------------------------
    def forward(self, epoch, table, client_id, seq, cmd, args):
        """Mirror one applied mutation. Sync mode ships inline (one
        reconnect attempt on a broken cached socket); async mode
        enqueues. A dead backup marks the link lost and stops costing
        anything; a fencing rejection marks the *primary* fenced."""
        record = (int(epoch), table, client_id, seq, cmd, args)
        return self._forward_msg(("replicate", record))

    def forward_command(self, cmd, args):
        """Mirror a control command (table create/delete) verbatim, so
        the backup holds the table a later replicated push mutates.
        Creates are idempotent at the receiver, so no epoch is needed."""
        return self._forward_msg((cmd, args))

    def _forward_msg(self, msg):
        if self.lost or self.fenced:
            return False
        if self.sync:
            for attempt in (0, 1):
                try:
                    self._ship(msg)
                    monitor.stat_add("ps.replicated_updates")
                    return True
                except FencedError:
                    raise
                except (faults.FaultError, ConnectionError, OSError):
                    if attempt:       # second strike: give the link up
                        self.lost = True
                        monitor.stat_add("ps.replication_lost")
                        return False
            return False
        with self._cv:
            self._queue.append(msg)
            monitor.stat_set("ps.replication_lag_updates",
                             len(self._queue))
            self._cv.notify()
        return True

    def _drain(self):
        while True:
            with self._cv:
                while not self._queue and not self.lost:
                    self._cv.wait(timeout=0.5)
                if self.lost and not self._queue:
                    return
                msg = self._queue.pop(0)
                monitor.stat_set("ps.replication_lag_updates",
                                 len(self._queue))
                self._cv.notify_all()   # wake a blocked flush()
            try:
                self._ship(msg)
                monitor.stat_add("ps.replicated_updates")
            except FencedError:
                return
            except (ConnectionError, OSError, RuntimeError):
                self.lost = True
                monitor.stat_add("ps.replication_lost")
                return

    def flush(self, timeout=10.0):
        """Async mode: block until the queue drains (tests/benches)."""
        if self.sync:
            return True
        with self._cv:
            return self._cv.wait_for(
                lambda: not self._queue or self.lost, timeout=timeout)

    def close(self):
        with self._lock:
            self.lost = True
            self._cv.notify_all()
            if self._sock is not None:
                try:
                    self._sock.close()
                except OSError:
                    pass
                self._sock = None
