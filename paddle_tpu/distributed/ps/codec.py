"""Typed binary codec shared by the PS wire protocol and the WAL.

This is the schema role sendrecv.proto plays in the reference — a typed
tag codec that can round-trip the PS value universe (None/bool/int/
float/str/bytes/ndarray/list/tuple/dict) without ever touching pickle,
so neither a hostile peer nor a corrupted log record can execute code.
Extracted from service.py so wal.py can persist records in the exact
format the wire speaks (service re-exports `_dumps`/`_loads` for
compatibility).

tags: N none, T true, F false, i int64, I big-int(str), f float64,
      s str, b bytes, l list, t tuple, d dict, a ndarray
"""

from __future__ import annotations

import struct

import numpy as np

_MAX_DEPTH = 32               # nesting bound for the decoder
_I64_MIN, _I64_MAX = -(1 << 63), (1 << 63) - 1

__all__ = ["dumps", "loads"]


def _enc(obj, out: bytearray):
    if obj is None:
        out += b"N"
    elif isinstance(obj, (bool, np.bool_)):
        out += b"T" if obj else b"F"
    elif isinstance(obj, (int, np.integer)):
        v = int(obj)
        if _I64_MIN <= v <= _I64_MAX:
            out += b"i" + struct.pack("<q", v)
        else:
            s = str(v).encode()
            out += b"I" + struct.pack("<I", len(s)) + s
    elif isinstance(obj, (float, np.floating)):
        out += b"f" + struct.pack("<d", float(obj))
    elif isinstance(obj, str):
        raw = obj.encode()
        out += b"s" + struct.pack("<I", len(raw)) + raw
    elif isinstance(obj, bytes):
        out += b"b" + struct.pack("<Q", len(obj)) + obj
    elif isinstance(obj, np.ndarray):
        if obj.dtype.hasobject:
            raise TypeError("PS wire codec cannot serialize object arrays")
        dt = obj.dtype.str.encode()     # e.g. b'<f4' — endian-explicit
        raw = np.ascontiguousarray(obj).tobytes()
        out += (b"a" + struct.pack("<B", len(dt)) + dt
                + struct.pack("<B", obj.ndim)
                + struct.pack(f"<{obj.ndim}q", *obj.shape)
                + struct.pack("<Q", len(raw)) + raw)
    elif isinstance(obj, (list, tuple)):
        out += (b"l" if isinstance(obj, list) else b"t")
        out += struct.pack("<I", len(obj))
        for x in obj:
            _enc(x, out)
    elif isinstance(obj, dict):
        out += b"d" + struct.pack("<I", len(obj))
        for k, v in obj.items():
            _enc(k, out)
            _enc(v, out)
    else:
        raise TypeError(
            f"PS wire codec cannot serialize {type(obj).__name__}")


class _Dec:
    def __init__(self, buf: bytes):
        self.buf = buf
        self.pos = 0

    def _take(self, n):
        if self.pos + n > len(self.buf):
            raise ConnectionError("truncated PS frame")
        v = self.buf[self.pos:self.pos + n]
        self.pos += n
        return v

    def value(self, depth=0):
        if depth > _MAX_DEPTH:
            raise ConnectionError("PS frame nests too deep")
        tag = self._take(1)
        if tag == b"N":
            return None
        if tag == b"T":
            return True
        if tag == b"F":
            return False
        if tag == b"i":
            return struct.unpack("<q", self._take(8))[0]
        if tag == b"I":
            (n,) = struct.unpack("<I", self._take(4))
            return int(self._take(n).decode())
        if tag == b"f":
            return struct.unpack("<d", self._take(8))[0]
        if tag == b"s":
            (n,) = struct.unpack("<I", self._take(4))
            return self._take(n).decode()
        if tag == b"b":
            (n,) = struct.unpack("<Q", self._take(8))
            return self._take(n)
        if tag == b"a":
            (dtn,) = struct.unpack("<B", self._take(1))
            dt = np.dtype(self._take(dtn).decode())
            if dt.hasobject:
                raise ConnectionError("object arrays not allowed on wire")
            (ndim,) = struct.unpack("<B", self._take(1))
            shape = struct.unpack(f"<{ndim}q", self._take(8 * ndim))
            (nbytes,) = struct.unpack("<Q", self._take(8))
            arr = np.frombuffer(self._take(nbytes), dtype=dt)
            return arr.reshape(shape).copy()
        if tag in (b"l", b"t"):
            (n,) = struct.unpack("<I", self._take(4))
            items = [self.value(depth + 1) for _ in range(n)]
            return items if tag == b"l" else tuple(items)
        if tag == b"d":
            (n,) = struct.unpack("<I", self._take(4))
            return {self.value(depth + 1): self.value(depth + 1)
                    for _ in range(n)}
        raise ConnectionError(f"bad PS wire tag {tag!r}")


def dumps(obj) -> bytes:
    out = bytearray()
    _enc(obj, out)
    return bytes(out)


def loads(buf: bytes):
    try:
        dec = _Dec(buf)
        val = dec.value()
        if dec.pos != len(buf):
            raise ConnectionError("trailing bytes in PS frame")
        return val
    except ConnectionError:
        raise
    except (ValueError, TypeError, UnicodeDecodeError, struct.error) as e:
        # bad utf-8, dtype strings, buffer-size mismatches, unhashable
        # dict keys — normalise so the server's drop path handles them
        raise ConnectionError(f"malformed PS frame: {e!r}") from e
