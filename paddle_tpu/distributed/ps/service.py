"""Parameter-server RPC service: PSServer / PSClient / Communicator.

Ref parity: paddle/fluid/distributed/service/ — BrpcPsServer/BrpcPsClient
(brpc RPC with sendrecv.proto) and Communicator (trainer-side async
send queues, sync/async/geo modes, communicator.h:197). TPU-native
redesign: the transport is a length-prefixed binary protocol over TCP
with a typed tag codec (codec.py — the wire schema role sendrecv.proto
plays in the reference) — never pickle, so a hostile peer cannot execute
code — plus an HMAC shared-secret handshake per connection. Servers are
a thread pool holding the tables of §tables.py, and sparse rows are
partitioned across servers by `id % n_servers` (the reference shards by
id range per table — modulo keeps shard balance without a shard map).
Trainers talk through PSClient; Communicator batches pushes in a
background thread (async), pushes inline (sync), or accumulates local
deltas pushed every k steps (geo, ref SparseGeoTable) under the
`FLAGS_ps_geo_staleness` bound.

Durability & failure transparency (the robustness layer serving/fleet.py
gave replicas, grown here for the PS tier):

* every mutating command carries ``(client_id, seq)``; servers dedupe by
  the per-(table, client) watermark, so a push retried across a
  reconnect — or across a primary->backup failover — applies exactly
  once (``ps.dedup_hits`` counts the suppressions);
* with ``wal_dir`` set, mutations append to a per-table write-ahead log
  (wal.py) *before* they apply, and a restarted server replays
  snapshot + WAL back to bitwise-identical table state;
* with ``backup`` set, applied mutations forward to a standby under a
  fencing epoch (replica.py); `PSClient` promotes the backup when the
  primary stops answering, and a zombie primary that comes back is
  rejected by epoch;
* `PSClient` calls retry transparently: dead cached sockets (broken
  pipe / ECONNRESET after a server restart) are dropped and redialed
  under exponential backoff, each attempt's socket timeout clipped to
  the call's remaining deadline.

Fault sites (framework/faults.py): ``ps.push`` between WAL append and
apply, ``ps.pull`` per lookup, ``ps.wal_append`` before the log write,
``ps.replicate`` per forward, ``ps.failover`` per client promotion,
``ps.spill`` per SSD spill batch.
"""

from __future__ import annotations

import hashlib
import hmac
import os
import socket
import uuid
import zlib
import socketserver
import struct
import threading
import time

import numpy as np

from ...framework import faults, monitor
from ...framework.flags import flag
from .codec import dumps as _dumps, loads as _loads  # noqa: F401 — re-export
from .replica import FencedError, ReplicaLink
from .tables import DenseTable, SparseTable

_MAGIC = b"PTPS"
_MAX_FRAME = 1 << 34          # 16 GiB — sanity bound on frame length


_warned_default_token = False


def _auth_key() -> bytes:
    """Shared secret for the connection handshake.

    Set PADDLE_TPU_PS_TOKEN identically on all ranks; the launcher
    generates a random one per pod and forwards it to every rank.
    The typed codec alone already removes code execution; the token
    additionally keeps strangers from mutating tables — but only when
    it is NOT the well-known fallback, hence the warning."""
    tok = os.environ.get("PADDLE_TPU_PS_TOKEN")
    if tok is None:
        global _warned_default_token
        if not _warned_default_token:
            _warned_default_token = True
            import warnings

            warnings.warn(
                "PADDLE_TPU_PS_TOKEN is unset — the PS handshake is using "
                "the public default key, which authenticates nothing. Set "
                "the same random token on all ranks (the launcher does "
                "this automatically) to keep untrusted peers out.")
        tok = "paddle-tpu-ps"
    return tok.encode()


def _send_msg(sock, obj):
    payload = _dumps(obj)
    sock.sendall(_MAGIC + struct.pack("<Q", len(payload)) + payload)


def _recv_exact(sock, n):
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed")
        buf.extend(chunk)
    return bytes(buf)


def _recv_msg(sock):
    head = _recv_exact(sock, 12)
    if head[:4] != _MAGIC:
        raise ConnectionError("bad frame magic")
    (size,) = struct.unpack("<Q", head[4:])
    if size > _MAX_FRAME:
        raise ConnectionError("PS frame exceeds size bound")
    return _loads(_recv_exact(sock, size))


class PSUnavailableError(ConnectionError):
    """A PS call exhausted its retry deadline (server down and no
    promotable backup). ConnectionError subclass so bootstrap loops
    that poll for a server coming up keep working."""


class _RetriableServerError(RuntimeError):
    """Server answered with a transient ('errR') failure — safe to
    retry because every mutating command is idempotent under its
    (client_id, seq)."""


class _Handler(socketserver.BaseRequestHandler):
    def handle(self):
        server: PSServer = self.server.ps  # type: ignore[attr-defined]
        sock = self.request
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        with server._conns_lock:
            server._conns.add(sock)
        try:
            self._serve(server, sock)
        finally:
            with server._conns_lock:
                server._conns.discard(sock)

    def _serve(self, server, sock):
        try:
            # challenge-response handshake before any command is accepted;
            # a short pre-auth timeout keeps a silent stranger from
            # pinning this server thread forever
            sock.settimeout(10.0)
            nonce = os.urandom(16)
            sock.sendall(_MAGIC + nonce)
            reply = _recv_exact(sock, 32)
            want = hmac.new(_auth_key(), nonce, hashlib.sha256).digest()
            if not hmac.compare_digest(reply, want):
                sock.sendall(b"NO")  # explicit reject, then drop
                return
            sock.sendall(b"OK")
            sock.settimeout(None)
            while True:
                cmd, args = _recv_msg(sock)
                if cmd == "stop":
                    _send_msg(sock, ("ok", None))
                    server._shutdown_flag.set()
                    break
                try:
                    result = server._dispatch(cmd, args)
                    _send_msg(sock, ("ok", result))
                except faults.FaultError as e:
                    # injected transient infrastructure error: the
                    # client may retry (idempotent under (cid, seq))
                    _send_msg(sock, ("errR", repr(e)))
                except FencedError as e:
                    _send_msg(sock, ("err", repr(e)))
                except (ConnectionError, OSError) as e:
                    _send_msg(sock, ("errR", repr(e)))
                except Exception as e:  # noqa: BLE001 — report to client
                    _send_msg(sock, ("err", repr(e)))
        except (ConnectionError, OSError):
            pass


class _TCP(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


#: commands that mutate table state and therefore carry (cid, seq),
#: WAL-append before apply, and forward to the backup replica
_MUTATIONS = ("push_dense_grad", "push_sparse_grad", "set_dense")


class PSServer:
    """One parameter-server rank (ref BrpcPsServer, server.h:64).

    `wal_dir` makes the rank crash-durable (write-ahead log + snapshot,
    recovery happens in __init__ before the first request is served);
    `backup` mirrors applied mutations to a standby endpoint under the
    fencing `epoch`.
    """

    def __init__(self, endpoint, wal_dir=None, backup=None, epoch=0,
                 replica_sync=True):
        host, port = endpoint.rsplit(":", 1)
        self.endpoint = endpoint
        self._tables: dict[str, object] = {}
        self._tables_lock = threading.Lock()
        # one lock serializes dedup-check + WAL append + apply + forward
        # so a retry racing its original attempt can never double-apply
        self._mutate_lock = threading.RLock()
        self._applied: dict[tuple, int] = {}   # (table, cid) -> last seq
        self._epoch = int(epoch)
        self._fenced = False
        self._store = None
        self.recovered_records = 0
        self._replica = None
        if backup:
            self._replica = ReplicaLink(backup, sync=replica_sync,
                                        on_fenced=self._on_fenced)
        if wal_dir:
            from .wal import DurableStore

            self._store = DurableStore(wal_dir)
            self._recover()
        self._barrier_count = 0
        self._barrier_gen = 0
        self._barrier_cv = threading.Condition()
        self._conns: set = set()       # live client sockets
        self._conns_lock = threading.Lock()
        self._shutdown_flag = threading.Event()
        self._tcp = _TCP((host, int(port)), _Handler)
        self._tcp.ps = self  # type: ignore[attr-defined]
        self.endpoint = f"{host}:{self._tcp.server_address[1]}"
        self._thread = None

    @property
    def port(self):
        return self._tcp.server_address[1]

    @property
    def epoch(self):
        return self._epoch

    def _on_fenced(self):
        """The backup rejected our replication stream: a newer epoch
        exists, so this server is a zombie — stop taking mutations."""
        self._fenced = True
        monitor.stat_add("ps.zombies_fenced")

    # -- recovery ------------------------------------------------------------
    def _recover(self):
        def create(cmd, args):
            if cmd == "delete_table":
                self._tables.pop(args, None)
            else:
                self._create(cmd, args, durable=False)

        def load(name, sd):
            t = self._tables.get(name)
            if t is not None:
                t.load_state_dict(sd)

        def apply(table, cid, seq, cmd, args):
            if table in self._tables:
                self._apply_mutation(cmd, args)

        self._applied, self.recovered_records = self._store.recover(
            create, load, apply)

    # -- lifecycle -----------------------------------------------------------
    def start(self):
        """Serve in a background thread (tests / in-process server)."""
        self._thread = threading.Thread(target=self._tcp.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self

    def run(self):
        """Blocking serve until a client sends stop (ref run_server)."""
        t = threading.Thread(target=self._tcp.serve_forever, daemon=True)
        t.start()
        self._shutdown_flag.wait()
        self._tcp.shutdown()
        self._close_durable()

    def stop(self):
        self._shutdown_flag.set()
        self._tcp.shutdown()
        self._tcp.server_close()
        self._close_durable()

    def _close_durable(self):
        if self._store is not None:
            self._store.close()
        if self._replica is not None:
            self._replica.close()

    def kill_transport(self):
        """Ungraceful death for in-process chaos tests/benches: the TCP
        front vanishes mid-conversation — listener closed AND every live
        client connection severed — tables and WAL buffers abandoned
        exactly as `kill -9` would leave them (no checkpoint, no close,
        no final fsync beyond what already landed)."""
        self._tcp.shutdown()
        self._tcp.server_close()
        with self._conns_lock:
            conns, self._conns = list(self._conns), set()
        for s in conns:
            try:
                s.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                s.close()
            except OSError:
                pass

    # -- table creation (meta-logged) ----------------------------------------
    def _create(self, cmd, args, durable=True):
        name = args[0]
        created = False
        with self._tables_lock:  # racing trainers must not replace a
            if name not in self._tables:  # table that has taken pushes
                if cmd == "create_dense":
                    _n, shape, opt, lr, initial = args
                    self._tables[name] = DenseTable(
                        name, shape, optimizer=opt, lr=lr, initial=initial)
                elif cmd == "create_sparse":
                    _n, dim, opt, lr, init_range, seed = args
                    self._tables[name] = SparseTable(
                        name, dim, optimizer=opt, lr=lr,
                        init_range=init_range, seed=seed)
                elif cmd == "create_ssd_sparse":
                    from .tables import SSDSparseTable

                    _n, dim, opt, lr, init_range, seed, mem_rows = args
                    self._tables[name] = SSDSparseTable(
                        name, dim, optimizer=opt, lr=lr,
                        init_range=init_range, seed=seed,
                        mem_rows=mem_rows)
                elif cmd == "create_graph":
                    from .tables import GraphTable

                    _n, seed = args
                    self._tables[name] = GraphTable(name, seed=seed)
                else:
                    raise ValueError(f"unknown create command {cmd!r}")
                created = True
        if created and durable:
            if self._store is not None and cmd != "create_graph":
                # graph tables are not WAL'd
                self._store.log_meta(cmd, args)
            if self._replica is not None:
                # the backup must hold the table a replicated push will
                # mutate; creates are idempotent there
                self._replica.forward_command(cmd, args)
        return None

    # -- mutation path: dedup + WAL + apply + replicate ----------------------
    def _apply_mutation(self, cmd, args):
        if cmd == "push_dense_grad":
            self._tables[args[0]].push_grad(args[1])
        elif cmd == "push_sparse_grad":
            self._tables[args[0]].push_grad(args[1], args[2])
        elif cmd == "set_dense":
            self._tables[args[0]].set(args[1])
        else:
            raise ValueError(f"unknown mutation {cmd!r}")

    def _mutate(self, cmd, args, cid, seq, epoch=None, replicate=True):
        table = args[0]
        with self._mutate_lock:
            if epoch is not None:
                if epoch < self._epoch:
                    raise FencedError(
                        f"replicate at epoch {epoch} rejected by "
                        f"{self.endpoint} (fencing epoch {self._epoch})")
            elif self._fenced:
                raise FencedError(
                    f"server {self.endpoint} was superseded at epoch "
                    f"{self._epoch}; refusing client mutations")
            has_seq = bool(cid) and seq is not None and seq >= 0
            key = (table, cid)
            if has_seq and seq <= self._applied.get(key, -1):
                monitor.stat_add("ps.dedup_hits")
                return "dup"
            if self._store is not None:
                self._store.log_push(table, cid, seq, cmd, args)
            # THE mid-push fault site: after the record is durable,
            # before the table mutates (crash here = recovery replays
            # the WAL; the retried push dedupes)
            faults.fault_point("ps.push", tag=table)
            self._apply_mutation(cmd, args)
            if has_seq:
                self._applied[key] = seq
            if replicate and self._replica is not None:
                self._replica.forward(self._epoch, table, cid, seq,
                                      cmd, args)
        return None

    # -- request dispatch ----------------------------------------------------
    def _dispatch(self, cmd, args):
        if cmd.startswith("create_"):
            return self._create(cmd, args)
        if cmd in _MUTATIONS:
            *core, cid, seq = args
            return self._mutate(cmd, tuple(core), cid, seq)
        if cmd == "replicate":
            epoch, _table, cid, seq, mcmd, margs = args
            return self._mutate(mcmd, tuple(margs), cid, seq,
                                epoch=epoch, replicate=False)
        if cmd == "promote":
            new_epoch = int(args)
            with self._mutate_lock:
                if new_epoch <= self._epoch and self._fenced:
                    raise FencedError(
                        f"promote to epoch {new_epoch} rejected: "
                        f"{self.endpoint} already fenced at "
                        f"{self._epoch}")
                self._epoch = max(self._epoch, new_epoch)
                self._fenced = False
                monitor.stat_add("ps.promotions")
                return self._epoch
        if cmd == "epoch":
            return (self._epoch, self._fenced)
        if cmd == "ps_checkpoint":
            if self._store is None:
                return None
            with self._mutate_lock:
                states = {n: t.state_dict()
                          for n, t in self._tables.items()
                          if hasattr(t, "state_dict")
                          and not type(t).__name__ == "GraphTable"}
                return self._store.checkpoint(states, dict(self._applied))
        if cmd == "ps_wal_stats":
            if self._store is None:
                return None
            return {"generation": self._store.generation,
                    "nbytes": self._store.nbytes,
                    "replayed": self.recovered_records}
        if cmd == "graph_add_edges":
            name, src, dst, weight = args
            return self._tables[name].add_edges(src, dst, weight)
        if cmd == "graph_sample":
            name, ids, n = args
            return self._tables[name].sample_neighbors(ids, n)
        if cmd == "graph_degree":
            name, ids = args
            return self._tables[name].degree(ids)
        if cmd == "graph_set_feat":
            name, ids, feats = args
            return self._tables[name].set_node_feat(ids, feats)
        if cmd == "graph_get_feat":
            name, ids, dim = args
            return self._tables[name].get_node_feat(ids, dim)
        if cmd == "pull_dense":
            faults.fault_point("ps.pull", tag=args)
            return self._tables[args].pull()
        if cmd == "pull_sparse":
            name, ids = args
            faults.fault_point("ps.pull", tag=name)
            return self._tables[name].pull(ids)
        if cmd == "barrier":
            n_trainers = args
            with self._barrier_cv:
                gen = self._barrier_gen
                self._barrier_count += 1
                if self._barrier_count >= n_trainers:
                    self._barrier_count = 0
                    self._barrier_gen += 1
                    self._barrier_cv.notify_all()
                else:
                    ok = self._barrier_cv.wait_for(
                        lambda: self._barrier_gen != gen, timeout=60.0)
                    if not ok:
                        # withdraw ONLY this trainer's count — zeroing it
                        # would corrupt trainers still validly waiting
                        if self._barrier_gen == gen:
                            self._barrier_count = max(
                                0, self._barrier_count - 1)
                        raise RuntimeError(
                            "PS barrier timed out: not all trainers "
                            "arrived within 60s")
            return None
        if cmd == "save":
            return {n: t.state_dict() for n, t in self._tables.items()}
        if cmd == "load":
            with self._mutate_lock:
                for n, sd in args.items():
                    if n in self._tables:
                        self._tables[n].load_state_dict(sd)
                if self._store is not None:
                    # fold the loaded state into a snapshot so recovery
                    # does not replay pre-load WAL records over it
                    states = {n: t.state_dict()
                              for n, t in self._tables.items()
                              if not type(t).__name__ == "GraphTable"}
                    self._store.checkpoint(states, dict(self._applied))
            return None
        if cmd == "delete_table":
            with self._tables_lock:
                t = self._tables.pop(args, None)
            if self._store is not None:
                self._store.log_meta("delete_table", args)
                self._store.drop_table(args)
            if self._replica is not None:
                self._replica.forward_command("delete_table", args)
            if t is not None and hasattr(t, "close"):
                t.close()  # SSD tables reclaim their spill directory
            return None
        if cmd == "table_size":
            t = self._tables[args]
            return len(t) if isinstance(t, SparseTable) else 1
        raise ValueError(f"unknown PS command {cmd!r}")


class PSClient:
    """Trainer-side connection pool (ref BrpcPsClient, ps_client.h:55).

    Sparse rows are partitioned id % n_servers; dense tables live on
    server hash(name) % n_servers. Every call retries transparently
    (reconnect + exponential backoff, socket timeout clipped to the
    call's remaining `op_deadline_s`); mutations are made idempotent by
    a per-(shard, table) monotone `seq` the servers dedupe on, so a
    retry — including one that lands on a freshly promoted backup —
    applies exactly once. `backups[i]` names the standby for shard i:
    after `failover_after` consecutive connection failures the client
    promotes it with a bumped fencing epoch and swaps the pair.
    """

    def __init__(self, endpoints, backups=None, client_id=None,
                 op_deadline_s=30.0, retry_backoff_s=0.05,
                 max_backoff_s=2.0, failover_after=2):
        self.endpoints = list(endpoints)
        n = len(self.endpoints)
        self.backups = list(backups) if backups else [None] * n
        if len(self.backups) != n:
            raise ValueError("backups must pair 1:1 with endpoints")
        self.client_id = client_id or uuid.uuid4().hex
        self.op_deadline_s = float(op_deadline_s)
        self.retry_backoff_s = float(retry_backoff_s)
        self.max_backoff_s = float(max_backoff_s)
        self.failover_after = int(failover_after)
        self._socks = [None] * n
        self._locks = [threading.Lock() for _ in range(n)]
        self._seq_lock = threading.Lock()
        self._seqs: dict[tuple, int] = {}     # (shard, table) -> last seq
        self._epochs = [0] * n
        self._sparse_dims: dict[str, int] = {}

    def _next_seq(self, i, name):
        with self._seq_lock:
            key = (i, name)
            nxt = self._seqs.get(key, -1) + 1
            self._seqs[key] = nxt
            return nxt

    def _sock(self, i):
        if self._socks[i] is None:
            host, port = self.endpoints[i].rsplit(":", 1)
            s = socket.create_connection((host, int(port)), timeout=10.0)
            # per-call timeout must exceed the server's 60s barrier wait,
            # or a blocked barrier desyncs the RPC framing (the late
            # reply would be read as the NEXT call's response)
            s.settimeout(120.0)
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            try:
                head = _recv_exact(s, 20)
                if head[:4] != _MAGIC:
                    raise ConnectionError("bad PS handshake magic")
                s.sendall(hmac.new(_auth_key(), head[4:],
                                   hashlib.sha256).digest())
                ack = _recv_exact(s, 2)
                if ack != b"OK":
                    raise ConnectionError(
                        "PS authentication failed — PADDLE_TPU_PS_TOKEN "
                        f"does not match the server at {self.endpoints[i]}")
            except BaseException:
                s.close()
                raise
            self._socks[i] = s
        return self._socks[i]

    def _drop_sock(self, i):
        """A server restart leaves a dead cached socket behind (broken
        pipe / ECONNRESET on next use): close and forget it so the next
        attempt redials instead of failing forever."""
        with self._locks[i]:
            s = self._socks[i]
            if s is not None:
                try:
                    s.close()
                except OSError:
                    pass
                self._socks[i] = None

    def _attempt(self, i, cmd, args, deadline, min_timeout):
        with self._locks[i]:
            sock = self._sock(i)
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise PSUnavailableError(
                    f"PS call {cmd!r} deadline expired before the "
                    f"attempt to {self.endpoints[i]}")
            # deadline propagation: no attempt may outlive the call
            sock.settimeout(max(min_timeout, min(120.0, remaining)))
            _send_msg(sock, (cmd, args))
            status, result = _recv_msg(sock)
        if status == "ok":
            return result
        if status == "errR":
            raise _RetriableServerError(
                f"transient PS error from {self.endpoints[i]}: {result}")
        raise RuntimeError(f"PS error from "
                           f"{self.endpoints[i]}: {result}")

    def _call(self, server_idx, cmd, args, retriable=True,
              deadline_s=None, min_timeout=0.05):
        deadline = time.monotonic() + (deadline_s or self.op_deadline_s)
        backoff = self.retry_backoff_s
        conn_failures = 0
        last_err = None
        while True:
            try:
                return self._attempt(server_idx, cmd, args, deadline,
                                     min_timeout)
            except _RetriableServerError as e:
                # server-side transient: framing is intact, keep socket
                last_err = e
            except (ConnectionError, OSError) as e:
                last_err = e
                conn_failures += 1
                self._drop_sock(server_idx)
            if not retriable:
                raise last_err
            if conn_failures >= self.failover_after \
                    and self.backups[server_idx]:
                if self._failover(server_idx):
                    conn_failures = 0
                    continue          # fresh primary: retry right away
            now = time.monotonic()
            if now + backoff > deadline:
                raise PSUnavailableError(
                    f"PS call {cmd!r} to {self.endpoints[server_idx]} "
                    f"failed for {self.op_deadline_s:.0f}s "
                    f"({last_err!r})") from last_err
            time.sleep(backoff)
            backoff = min(backoff * 2, self.max_backoff_s)

    # -- failover ------------------------------------------------------------
    def _raw_call(self, endpoint, cmd, args, timeout=10.0):
        """One-shot handshake + call against an arbitrary endpoint."""
        host, port = endpoint.rsplit(":", 1)
        s = socket.create_connection((host, int(port)), timeout=timeout)
        try:
            s.settimeout(timeout)
            head = _recv_exact(s, 20)
            if head[:4] != _MAGIC:
                raise ConnectionError("bad PS handshake magic")
            s.sendall(hmac.new(_auth_key(), head[4:],
                               hashlib.sha256).digest())
            if _recv_exact(s, 2) != b"OK":
                raise ConnectionError("PS authentication failed")
            _send_msg(s, (cmd, args))
            status, result = _recv_msg(s)
        finally:
            s.close()
        if status != "ok":
            raise RuntimeError(f"PS error from {endpoint}: {result}")
        return result

    def _failover(self, i):
        """Promote shard i's backup with a bumped fencing epoch and swap
        the pair. Returns True when the backup accepted (or was already
        at) the new epoch."""
        backup = self.backups[i]
        faults.fault_point("ps.failover", tag=self.endpoints[i])
        new_epoch = self._epochs[i] + 1
        try:
            granted = int(self._raw_call(backup, "promote", new_epoch))
        except (ConnectionError, OSError):
            return False              # backup unreachable too — backoff
        except RuntimeError:
            # another client may have promoted already: accept the
            # backup as primary iff its epoch has moved past ours
            try:
                granted, fenced = self._raw_call(backup, "epoch", None)
                if fenced or granted < new_epoch:
                    return False
            except (ConnectionError, OSError, RuntimeError):
                return False
        with self._locks[i]:
            old = self.endpoints[i]
            self.endpoints[i] = backup
            self.backups[i] = old
            s = self._socks[i]
            if s is not None:
                try:
                    s.close()
                except OSError:
                    pass
                self._socks[i] = None
        self._epochs[i] = int(granted)
        monitor.stat_add("ps.failovers")
        return True

    def _dense_server(self, name):
        # stable across processes (builtin hash is randomized per run)
        return zlib.crc32(name.encode()) % len(self.endpoints)

    # -- table management ----------------------------------------------------
    def create_dense_table(self, name, shape, optimizer="sgd", lr=0.01,
                           initial=None):
        self._call(self._dense_server(name), "create_dense",
                   (name, shape, optimizer, lr, initial))

    def create_sparse_table(self, name, dim, optimizer="sgd", lr=0.01,
                            init_range=0.05, seed=0):
        self._sparse_dims[name] = int(dim)
        for i in range(len(self.endpoints)):
            self._call(i, "create_sparse",
                       (name, dim, optimizer, lr, init_range, seed + i))

    def create_ssd_sparse_table(self, name, dim, optimizer="sgd",
                                lr=0.01, init_range=0.05, seed=0,
                                mem_rows=100_000):
        """Disk-spilling sparse table (ref ssd_sparse_table.h): same
        pull/push API as create_sparse_table, rows beyond `mem_rows`
        spill to the server's disk."""
        self._sparse_dims[name] = int(dim)
        for i in range(len(self.endpoints)):
            self._call(i, "create_ssd_sparse",
                       (name, dim, optimizer, lr, init_range, seed + i,
                        mem_rows))

    # -- graph (partitioned by src id) ---------------------------------------
    def create_graph_table(self, name, seed=0):
        for i in range(len(self.endpoints)):
            self._call(i, "create_graph", (name, seed + i))

    def _by_server(self, ids):
        ids = np.asarray(ids, np.int64).reshape(-1)
        n = len(self.endpoints)
        return ids, [np.nonzero(ids % n == s)[0] for s in range(n)]

    def graph_add_edges(self, name, src, dst, weight=None):
        src = np.asarray(src, np.int64).reshape(-1)
        dst = np.asarray(dst, np.int64).reshape(-1)
        w = None if weight is None else \
            np.asarray(weight, np.float32).reshape(-1)
        _, parts = self._by_server(src)
        for s, idx in enumerate(parts):
            if idx.size:
                self._call(s, "graph_add_edges",
                           (name, src[idx], dst[idx],
                            None if w is None else w[idx]))

    def graph_sample_neighbors(self, name, ids, n):
        ids, parts = self._by_server(ids)
        out = np.full((ids.size, n), -1, np.int64)
        for s, idx in enumerate(parts):
            if idx.size:
                out[idx] = self._call(s, "graph_sample",
                                      (name, ids[idx], n))
        return out

    def graph_degree(self, name, ids):
        ids, parts = self._by_server(ids)
        out = np.zeros(ids.size, np.int64)
        for s, idx in enumerate(parts):
            if idx.size:
                out[idx] = self._call(s, "graph_degree", (name, ids[idx]))
        return out

    def graph_set_node_feat(self, name, ids, feats):
        ids, parts = self._by_server(ids)
        feats = np.asarray(feats, np.float32)
        for s, idx in enumerate(parts):
            if idx.size:
                self._call(s, "graph_set_feat",
                           (name, ids[idx], feats[idx]))

    def graph_get_node_feat(self, name, ids, dim):
        ids, parts = self._by_server(ids)
        out = np.zeros((ids.size, dim), np.float32)
        for s, idx in enumerate(parts):
            if idx.size:
                out[idx] = self._call(s, "graph_get_feat",
                                      (name, ids[idx], dim))
        return out

    # -- dense ---------------------------------------------------------------
    def pull_dense(self, name):
        return self._call(self._dense_server(name), "pull_dense", name)

    def push_dense_grad(self, name, grad):
        i = self._dense_server(name)
        self._call(i, "push_dense_grad",
                   (name, np.asarray(grad, np.float32),
                    self.client_id, self._next_seq(i, name)))

    def set_dense(self, name, value):
        i = self._dense_server(name)
        self._call(i, "set_dense",
                   (name, np.asarray(value, np.float32),
                    self.client_id, self._next_seq(i, name)))

    # -- sparse (partitioned) ------------------------------------------------
    def pull_sparse(self, name, ids):
        ids = np.asarray(ids, np.int64).reshape(-1)
        n = len(self.endpoints)
        if ids.size == 0:
            return np.empty((0, self._sparse_dims.get(name, 0)),
                            np.float32)
        parts = [np.nonzero(ids % n == i)[0] for i in range(n)]
        dim = self._sparse_dims.get(name)
        results = [None] * n
        for i, pos in enumerate(parts):
            if pos.size:
                results[i] = self._call(i, "pull_sparse", (name, ids[pos]))
                dim = results[i].shape[1]
        out = np.empty((ids.shape[0], dim), np.float32)
        for pos, res in zip(parts, results):
            if res is not None:
                out[pos] = res
        return out

    def push_sparse_grad(self, name, ids, grads):
        ids = np.asarray(ids, np.int64).reshape(-1)
        grads = np.asarray(grads, np.float32)
        n = len(self.endpoints)
        for i in range(n):
            pos = np.nonzero(ids % n == i)[0]
            if pos.size:
                self._call(i, "push_sparse_grad",
                           (name, ids[pos], grads[pos],
                            self.client_id, self._next_seq(i, name)))

    def delete_table(self, name):
        for i in range(len(self.endpoints)):
            self._call(i, "delete_table", name)
        self._sparse_dims.pop(name, None)

    # -- durability / replication control ------------------------------------
    def checkpoint(self):
        """Snapshot + WAL rotation on every durable server; -> [gen]."""
        return [self._call(i, "ps_checkpoint", None)
                for i in range(len(self.endpoints))]

    def wal_stats(self):
        return [self._call(i, "ps_wal_stats", None)
                for i in range(len(self.endpoints))]

    def server_epoch(self, i=0):
        """-> (fencing epoch, fenced?) of shard i's current primary."""
        return tuple(self._call(i, "epoch", None))

    # -- control -------------------------------------------------------------
    def barrier(self, n_trainers):
        # barriers are NOT idempotent (a blind retry would double-count
        # this trainer) and legitimately block up to the server's 60s
        # window — no transparent retry, generous deadline
        self._call(0, "barrier", n_trainers, retriable=False,
                   min_timeout=130.0)

    def save(self):
        return [self._call(i, "save", None)
                for i in range(len(self.endpoints))]

    def load(self, states):
        for i, sd in enumerate(states):
            self._call(i, "load", sd)

    def stop_servers(self):
        for i in range(len(self.endpoints)):
            try:
                self._call(i, "stop", None, retriable=False)
            except (RuntimeError, ConnectionError, OSError):
                pass

    def close(self):
        for s in self._socks:
            if s is not None:
                try:
                    s.close()
                except OSError:
                    pass
        self._socks = [None] * len(self.endpoints)


class Communicator:
    """Trainer-side grad pipe (ref distributed/service/communicator.h:197).

    modes:
      sync  — push_* forwards immediately; callers barrier per step
      async — pushes enqueue; a background thread drains (Hogwild-style)
      geo   — sparse pushes accumulate locally as deltas; every
              `geo_step` flushes merged deltas (optimizer='sum' tables).
              `FLAGS_ps_geo_staleness` bounds the accumulation: once
              that many update rows are pending the flush happens NOW,
              so a reader's staleness is capped in updates, not steps
              (SURVEY.md geo semantics).
    """

    def __init__(self, client: PSClient, mode="async", geo_step=4,
                 on_flush=None):
        self.client = client
        self.mode = mode
        self.geo_step = int(geo_step)
        # applied-push hook: on_flush(table_name, ids) fires AFTER a
        # sparse push has landed on the servers — rec.serving chains
        # TPUEmbeddingCache.invalidate here so serving caches observe
        # the online trainer's updates (invalidation-on-push)
        self.on_flush = on_flush
        # per-table geo delta scale at flush (e.g. -lr turns summed grads
        # into the SGD parameter delta merged by an optimizer='sum' table)
        self.geo_scales: dict[str, float] = {}
        self._queue: list = []
        self._cv = threading.Condition()
        self._running = False
        self._thread = None
        self._inflight = 0
        self._error: Exception | None = None
        self._geo_acc: dict[str, dict[int, np.ndarray]] = {}
        self._geo_count = 0
        self._geo_pending = 0     # update rows accumulated since flush

    def set_geo_scale(self, table_name, scale):
        self.geo_scales[table_name] = float(scale)

    def _notify_flush(self, name, ids):
        if self.on_flush is not None:
            self.on_flush(name, np.asarray(ids, np.int64).reshape(-1))

    # -- lifecycle -----------------------------------------------------------
    def start(self):
        if self.mode == "async" and not self._running:
            self._running = True
            self._thread = threading.Thread(target=self._drain, daemon=True)
            self._thread.start()
        return self

    def stop(self):
        if self._running:
            with self._cv:
                self._running = False
                self._cv.notify_all()
            self._thread.join(timeout=10.0)
        self.flush()

    def _drain(self):
        while True:
            with self._cv:
                while self._running and not self._queue:
                    self._cv.wait(timeout=0.5)
                if not self._running and not self._queue:
                    return
                batch, self._queue = self._queue, []
                self._inflight = len(batch)
            try:
                for kind, name, a, b in batch:
                    if kind == "sparse":
                        self.client.push_sparse_grad(name, a, b)
                        self._notify_flush(name, a)
                    else:
                        self.client.push_dense_grad(name, a)
                    with self._cv:
                        self._inflight -= 1
                        self._cv.notify_all()
            except Exception as e:  # noqa: BLE001 — surface via flush()
                with self._cv:
                    self._error = e
                    self._inflight = 0
                    self._cv.notify_all()

    # -- pushes --------------------------------------------------------------
    def push_sparse(self, name, ids, grads):
        if self.mode == "geo":
            acc = self._geo_acc.setdefault(name, {})
            ids = np.asarray(ids, np.int64).reshape(-1)
            grads = np.asarray(grads, np.float32)
            for i, g in zip(ids, grads):
                i = int(i)
                if i in acc:
                    acc[i] = acc[i] + g
                else:
                    acc[i] = g.copy()
            self._geo_pending += int(ids.size)
            bound = flag("FLAGS_ps_geo_staleness")
            if bound and self._geo_pending >= bound:
                # staleness bound hit: force the sync flush early
                monitor.stat_add("ps.geo_forced_flushes")
                self.flush()
            return
        if self.mode == "sync":
            self.client.push_sparse_grad(name, ids, grads)
            self._notify_flush(name, ids)
            return
        with self._cv:
            self._queue.append(("sparse", name, np.asarray(ids, np.int64),
                                np.asarray(grads, np.float32)))
            self._cv.notify()

    def push_dense(self, name, grad):
        if self.mode != "async":
            # sync pushes inline; geo applies only to sparse tables (ref
            # SparseGeoTable) so dense grads also go straight through —
            # queueing them would never drain (no drain thread in geo)
            self.client.push_dense_grad(name, grad)
            return
        with self._cv:
            self._queue.append(("dense", name,
                                np.asarray(grad, np.float32), None))
            self._cv.notify()

    def step_end(self):
        """Geo cadence hook: call once per train step."""
        if self.mode != "geo":
            return
        self._geo_count += 1
        if self._geo_count % self.geo_step == 0:
            self.flush()

    def flush(self):
        if self.mode == "geo":
            for name, acc in self._geo_acc.items():
                if not acc:
                    continue
                ids = np.fromiter(acc.keys(), np.int64, len(acc))
                grads = np.stack([acc[int(i)] for i in ids])
                scale = self.geo_scales.get(name, 1.0)
                self.client.push_sparse_grad(name, ids, scale * grads)
                self._notify_flush(name, ids)
            self._geo_acc = {}
            self._geo_pending = 0
            return
        if self.mode == "async":
            # wait until queued AND in-flight pushes have landed
            with self._cv:
                ok = self._cv.wait_for(
                    lambda: (self._error is not None
                             or (not self._queue and self._inflight == 0)),
                    timeout=60.0)
                err, self._error = self._error, None
            if err is not None:
                raise RuntimeError(
                    "async communicator push failed") from err
            if not ok:
                raise RuntimeError(
                    "async communicator flush timed out (60s) with "
                    "gradients still in flight")
