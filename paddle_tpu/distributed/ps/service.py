"""Parameter-server RPC service: PSServer / PSClient / Communicator.

Ref parity: paddle/fluid/distributed/service/ — BrpcPsServer/BrpcPsClient
(brpc RPC with sendrecv.proto) and Communicator (trainer-side async
send queues, sync/async/geo modes, communicator.h:197). TPU-native
redesign: the transport is a length-prefixed binary protocol over TCP
with a typed tag codec (the wire schema role sendrecv.proto plays in
the reference) — never pickle, so a hostile peer cannot execute code —
plus an HMAC shared-secret handshake per connection. Servers are a
thread pool holding the tables of §tables.py, and sparse rows are
partitioned across servers by `id % n_servers` (the reference shards by
id range per table — modulo keeps shard balance without a shard map).
Trainers talk through PSClient; Communicator batches pushes in a
background thread (async), pushes inline (sync), or accumulates local
deltas pushed every k steps (geo, ref SparseGeoTable).
"""

from __future__ import annotations

import hashlib
import hmac
import os
import socket
import zlib
import socketserver
import struct
import threading
import time

import numpy as np

from .tables import DenseTable, SparseTable

_MAGIC = b"PTPS"
_MAX_FRAME = 1 << 34          # 16 GiB — sanity bound on frame length
_MAX_DEPTH = 32               # nesting bound for the decoder

# -- typed wire codec (replaces sendrecv.proto; no pickle anywhere) ----------
# tags: N none, T true, F false, i int64, I big-int(str), f float64,
#       s str, b bytes, l list, t tuple, d dict, a ndarray
_I64_MIN, _I64_MAX = -(1 << 63), (1 << 63) - 1


def _enc(obj, out: bytearray):
    if obj is None:
        out += b"N"
    elif isinstance(obj, (bool, np.bool_)):
        out += b"T" if obj else b"F"
    elif isinstance(obj, (int, np.integer)):
        v = int(obj)
        if _I64_MIN <= v <= _I64_MAX:
            out += b"i" + struct.pack("<q", v)
        else:
            s = str(v).encode()
            out += b"I" + struct.pack("<I", len(s)) + s
    elif isinstance(obj, (float, np.floating)):
        out += b"f" + struct.pack("<d", float(obj))
    elif isinstance(obj, str):
        raw = obj.encode()
        out += b"s" + struct.pack("<I", len(raw)) + raw
    elif isinstance(obj, bytes):
        out += b"b" + struct.pack("<Q", len(obj)) + obj
    elif isinstance(obj, np.ndarray):
        if obj.dtype.hasobject:
            raise TypeError("PS wire codec cannot serialize object arrays")
        dt = obj.dtype.str.encode()     # e.g. b'<f4' — endian-explicit
        raw = np.ascontiguousarray(obj).tobytes()
        out += (b"a" + struct.pack("<B", len(dt)) + dt
                + struct.pack("<B", obj.ndim)
                + struct.pack(f"<{obj.ndim}q", *obj.shape)
                + struct.pack("<Q", len(raw)) + raw)
    elif isinstance(obj, (list, tuple)):
        out += (b"l" if isinstance(obj, list) else b"t")
        out += struct.pack("<I", len(obj))
        for x in obj:
            _enc(x, out)
    elif isinstance(obj, dict):
        out += b"d" + struct.pack("<I", len(obj))
        for k, v in obj.items():
            _enc(k, out)
            _enc(v, out)
    else:
        raise TypeError(
            f"PS wire codec cannot serialize {type(obj).__name__}")


class _Dec:
    def __init__(self, buf: bytes):
        self.buf = buf
        self.pos = 0

    def _take(self, n):
        if self.pos + n > len(self.buf):
            raise ConnectionError("truncated PS frame")
        v = self.buf[self.pos:self.pos + n]
        self.pos += n
        return v

    def value(self, depth=0):
        if depth > _MAX_DEPTH:
            raise ConnectionError("PS frame nests too deep")
        tag = self._take(1)
        if tag == b"N":
            return None
        if tag == b"T":
            return True
        if tag == b"F":
            return False
        if tag == b"i":
            return struct.unpack("<q", self._take(8))[0]
        if tag == b"I":
            (n,) = struct.unpack("<I", self._take(4))
            return int(self._take(n).decode())
        if tag == b"f":
            return struct.unpack("<d", self._take(8))[0]
        if tag == b"s":
            (n,) = struct.unpack("<I", self._take(4))
            return self._take(n).decode()
        if tag == b"b":
            (n,) = struct.unpack("<Q", self._take(8))
            return self._take(n)
        if tag == b"a":
            (dtn,) = struct.unpack("<B", self._take(1))
            dt = np.dtype(self._take(dtn).decode())
            if dt.hasobject:
                raise ConnectionError("object arrays not allowed on wire")
            (ndim,) = struct.unpack("<B", self._take(1))
            shape = struct.unpack(f"<{ndim}q", self._take(8 * ndim))
            (nbytes,) = struct.unpack("<Q", self._take(8))
            arr = np.frombuffer(self._take(nbytes), dtype=dt)
            return arr.reshape(shape).copy()
        if tag in (b"l", b"t"):
            (n,) = struct.unpack("<I", self._take(4))
            items = [self.value(depth + 1) for _ in range(n)]
            return items if tag == b"l" else tuple(items)
        if tag == b"d":
            (n,) = struct.unpack("<I", self._take(4))
            return {self.value(depth + 1): self.value(depth + 1)
                    for _ in range(n)}
        raise ConnectionError(f"bad PS wire tag {tag!r}")


def _dumps(obj) -> bytes:
    out = bytearray()
    _enc(obj, out)
    return bytes(out)


def _loads(buf: bytes):
    try:
        dec = _Dec(buf)
        val = dec.value()
        if dec.pos != len(buf):
            raise ConnectionError("trailing bytes in PS frame")
        return val
    except ConnectionError:
        raise
    except (ValueError, TypeError, UnicodeDecodeError, struct.error) as e:
        # bad utf-8, dtype strings, buffer-size mismatches, unhashable
        # dict keys — normalise so the server's drop path handles them
        raise ConnectionError(f"malformed PS frame: {e!r}") from e


_warned_default_token = False


def _auth_key() -> bytes:
    """Shared secret for the connection handshake.

    Set PADDLE_TPU_PS_TOKEN identically on all ranks; the launcher
    generates a random one per pod and forwards it to every rank.
    The typed codec alone already removes code execution; the token
    additionally keeps strangers from mutating tables — but only when
    it is NOT the well-known fallback, hence the warning."""
    tok = os.environ.get("PADDLE_TPU_PS_TOKEN")
    if tok is None:
        global _warned_default_token
        if not _warned_default_token:
            _warned_default_token = True
            import warnings

            warnings.warn(
                "PADDLE_TPU_PS_TOKEN is unset — the PS handshake is using "
                "the public default key, which authenticates nothing. Set "
                "the same random token on all ranks (the launcher does "
                "this automatically) to keep untrusted peers out.")
        tok = "paddle-tpu-ps"
    return tok.encode()


def _send_msg(sock, obj):
    payload = _dumps(obj)
    sock.sendall(_MAGIC + struct.pack("<Q", len(payload)) + payload)


def _recv_exact(sock, n):
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed")
        buf.extend(chunk)
    return bytes(buf)


def _recv_msg(sock):
    head = _recv_exact(sock, 12)
    if head[:4] != _MAGIC:
        raise ConnectionError("bad frame magic")
    (size,) = struct.unpack("<Q", head[4:])
    if size > _MAX_FRAME:
        raise ConnectionError("PS frame exceeds size bound")
    return _loads(_recv_exact(sock, size))


class _Handler(socketserver.BaseRequestHandler):
    def handle(self):
        server: PSServer = self.server.ps  # type: ignore[attr-defined]
        sock = self.request
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        try:
            # challenge-response handshake before any command is accepted;
            # a short pre-auth timeout keeps a silent stranger from
            # pinning this server thread forever
            sock.settimeout(10.0)
            nonce = os.urandom(16)
            sock.sendall(_MAGIC + nonce)
            reply = _recv_exact(sock, 32)
            want = hmac.new(_auth_key(), nonce, hashlib.sha256).digest()
            if not hmac.compare_digest(reply, want):
                sock.sendall(b"NO")  # explicit reject, then drop
                return
            sock.sendall(b"OK")
            sock.settimeout(None)
            while True:
                cmd, args = _recv_msg(sock)
                if cmd == "stop":
                    _send_msg(sock, ("ok", None))
                    server._shutdown_flag.set()
                    break
                try:
                    result = server._dispatch(cmd, args)
                    _send_msg(sock, ("ok", result))
                except Exception as e:  # noqa: BLE001 — report to client
                    _send_msg(sock, ("err", repr(e)))
        except (ConnectionError, OSError):
            pass


class _TCP(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


class PSServer:
    """One parameter-server rank (ref BrpcPsServer, server.h:64)."""

    def __init__(self, endpoint):
        host, port = endpoint.rsplit(":", 1)
        self.endpoint = endpoint
        self._tables: dict[str, object] = {}
        self._tables_lock = threading.Lock()
        self._barrier_count = 0
        self._barrier_gen = 0
        self._barrier_cv = threading.Condition()
        self._shutdown_flag = threading.Event()
        self._tcp = _TCP((host, int(port)), _Handler)
        self._tcp.ps = self  # type: ignore[attr-defined]
        self._thread = None

    @property
    def port(self):
        return self._tcp.server_address[1]

    # -- lifecycle -----------------------------------------------------------
    def start(self):
        """Serve in a background thread (tests / in-process server)."""
        self._thread = threading.Thread(target=self._tcp.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self

    def run(self):
        """Blocking serve until a client sends stop (ref run_server)."""
        t = threading.Thread(target=self._tcp.serve_forever, daemon=True)
        t.start()
        self._shutdown_flag.wait()
        self._tcp.shutdown()

    def stop(self):
        self._shutdown_flag.set()
        self._tcp.shutdown()
        self._tcp.server_close()

    # -- request dispatch ----------------------------------------------------
    def _dispatch(self, cmd, args):
        if cmd == "create_dense":
            name, shape, opt, lr, initial = args
            with self._tables_lock:  # racing trainers must not replace a
                if name not in self._tables:  # table that has taken pushes
                    self._tables[name] = DenseTable(
                        name, shape, optimizer=opt, lr=lr, initial=initial)
            return None
        if cmd == "create_sparse":
            name, dim, opt, lr, init_range, seed = args
            with self._tables_lock:
                if name not in self._tables:
                    self._tables[name] = SparseTable(
                        name, dim, optimizer=opt, lr=lr,
                        init_range=init_range, seed=seed)
            return None
        if cmd == "create_ssd_sparse":
            name, dim, opt, lr, init_range, seed, mem_rows = args
            from .tables import SSDSparseTable

            with self._tables_lock:
                if name not in self._tables:
                    self._tables[name] = SSDSparseTable(
                        name, dim, optimizer=opt, lr=lr,
                        init_range=init_range, seed=seed,
                        mem_rows=mem_rows)
            return None
        if cmd == "create_graph":
            name, seed = args
            from .tables import GraphTable

            with self._tables_lock:
                if name not in self._tables:
                    self._tables[name] = GraphTable(name, seed=seed)
            return None
        if cmd == "graph_add_edges":
            name, src, dst, weight = args
            return self._tables[name].add_edges(src, dst, weight)
        if cmd == "graph_sample":
            name, ids, n = args
            return self._tables[name].sample_neighbors(ids, n)
        if cmd == "graph_degree":
            name, ids = args
            return self._tables[name].degree(ids)
        if cmd == "graph_set_feat":
            name, ids, feats = args
            return self._tables[name].set_node_feat(ids, feats)
        if cmd == "graph_get_feat":
            name, ids, dim = args
            return self._tables[name].get_node_feat(ids, dim)
        if cmd == "pull_dense":
            return self._tables[args].pull()
        if cmd == "push_dense_grad":
            name, grad = args
            self._tables[name].push_grad(grad)
            return None
        if cmd == "set_dense":
            name, value = args
            self._tables[name].set(value)
            return None
        if cmd == "pull_sparse":
            name, ids = args
            return self._tables[name].pull(ids)
        if cmd == "push_sparse_grad":
            name, ids, grads = args
            self._tables[name].push_grad(ids, grads)
            return None
        if cmd == "barrier":
            n_trainers = args
            with self._barrier_cv:
                gen = self._barrier_gen
                self._barrier_count += 1
                if self._barrier_count >= n_trainers:
                    self._barrier_count = 0
                    self._barrier_gen += 1
                    self._barrier_cv.notify_all()
                else:
                    ok = self._barrier_cv.wait_for(
                        lambda: self._barrier_gen != gen, timeout=60.0)
                    if not ok:
                        # withdraw ONLY this trainer's count — zeroing it
                        # would corrupt trainers still validly waiting
                        if self._barrier_gen == gen:
                            self._barrier_count = max(
                                0, self._barrier_count - 1)
                        raise RuntimeError(
                            "PS barrier timed out: not all trainers "
                            "arrived within 60s")
            return None
        if cmd == "save":
            return {n: t.state_dict() for n, t in self._tables.items()}
        if cmd == "load":
            for n, sd in args.items():
                if n in self._tables:
                    self._tables[n].load_state_dict(sd)
            return None
        if cmd == "delete_table":
            with self._tables_lock:
                t = self._tables.pop(args, None)
            if t is not None and hasattr(t, "close"):
                t.close()  # SSD tables reclaim their spill directory
            return None
        if cmd == "table_size":
            t = self._tables[args]
            return len(t) if isinstance(t, SparseTable) else 1
        raise ValueError(f"unknown PS command {cmd!r}")


class PSClient:
    """Trainer-side connection pool (ref BrpcPsClient, ps_client.h:55).

    Sparse rows are partitioned id % n_servers; dense tables live on
    server hash(name) % n_servers.
    """

    def __init__(self, endpoints):
        self.endpoints = list(endpoints)
        self._socks = [None] * len(self.endpoints)
        self._locks = [threading.Lock() for _ in self.endpoints]
        self._sparse_dims: dict[str, int] = {}

    def _sock(self, i):
        if self._socks[i] is None:
            host, port = self.endpoints[i].rsplit(":", 1)
            s = socket.create_connection((host, int(port)), timeout=30.0)
            # per-call timeout must exceed the server's 60s barrier wait,
            # or a blocked barrier desyncs the RPC framing (the late
            # reply would be read as the NEXT call's response)
            s.settimeout(120.0)
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            try:
                head = _recv_exact(s, 20)
                if head[:4] != _MAGIC:
                    raise ConnectionError("bad PS handshake magic")
                s.sendall(hmac.new(_auth_key(), head[4:],
                                   hashlib.sha256).digest())
                ack = _recv_exact(s, 2)
                if ack != b"OK":
                    raise ConnectionError(
                        "PS authentication failed — PADDLE_TPU_PS_TOKEN "
                        f"does not match the server at {self.endpoints[i]}")
            except BaseException:
                s.close()
                raise
            self._socks[i] = s
        return self._socks[i]

    def _call(self, server_idx, cmd, args):
        with self._locks[server_idx]:
            sock = self._sock(server_idx)
            _send_msg(sock, (cmd, args))
            status, result = _recv_msg(sock)
        if status != "ok":
            raise RuntimeError(f"PS error from "
                               f"{self.endpoints[server_idx]}: {result}")
        return result

    def _dense_server(self, name):
        # stable across processes (builtin hash is randomized per run)
        return zlib.crc32(name.encode()) % len(self.endpoints)

    # -- table management ----------------------------------------------------
    def create_dense_table(self, name, shape, optimizer="sgd", lr=0.01,
                           initial=None):
        self._call(self._dense_server(name), "create_dense",
                   (name, shape, optimizer, lr, initial))

    def create_sparse_table(self, name, dim, optimizer="sgd", lr=0.01,
                            init_range=0.05, seed=0):
        self._sparse_dims[name] = int(dim)
        for i in range(len(self.endpoints)):
            self._call(i, "create_sparse",
                       (name, dim, optimizer, lr, init_range, seed + i))

    def create_ssd_sparse_table(self, name, dim, optimizer="sgd",
                                lr=0.01, init_range=0.05, seed=0,
                                mem_rows=100_000):
        """Disk-spilling sparse table (ref ssd_sparse_table.h): same
        pull/push API as create_sparse_table, rows beyond `mem_rows`
        spill to the server's disk."""
        self._sparse_dims[name] = int(dim)
        for i in range(len(self.endpoints)):
            self._call(i, "create_ssd_sparse",
                       (name, dim, optimizer, lr, init_range, seed + i,
                        mem_rows))

    # -- graph (partitioned by src id) ---------------------------------------
    def create_graph_table(self, name, seed=0):
        for i in range(len(self.endpoints)):
            self._call(i, "create_graph", (name, seed + i))

    def _by_server(self, ids):
        ids = np.asarray(ids, np.int64).reshape(-1)
        n = len(self.endpoints)
        return ids, [np.nonzero(ids % n == s)[0] for s in range(n)]

    def graph_add_edges(self, name, src, dst, weight=None):
        src = np.asarray(src, np.int64).reshape(-1)
        dst = np.asarray(dst, np.int64).reshape(-1)
        w = None if weight is None else \
            np.asarray(weight, np.float32).reshape(-1)
        _, parts = self._by_server(src)
        for s, idx in enumerate(parts):
            if idx.size:
                self._call(s, "graph_add_edges",
                           (name, src[idx], dst[idx],
                            None if w is None else w[idx]))

    def graph_sample_neighbors(self, name, ids, n):
        ids, parts = self._by_server(ids)
        out = np.full((ids.size, n), -1, np.int64)
        for s, idx in enumerate(parts):
            if idx.size:
                out[idx] = self._call(s, "graph_sample",
                                      (name, ids[idx], n))
        return out

    def graph_degree(self, name, ids):
        ids, parts = self._by_server(ids)
        out = np.zeros(ids.size, np.int64)
        for s, idx in enumerate(parts):
            if idx.size:
                out[idx] = self._call(s, "graph_degree", (name, ids[idx]))
        return out

    def graph_set_node_feat(self, name, ids, feats):
        ids, parts = self._by_server(ids)
        feats = np.asarray(feats, np.float32)
        for s, idx in enumerate(parts):
            if idx.size:
                self._call(s, "graph_set_feat",
                           (name, ids[idx], feats[idx]))

    def graph_get_node_feat(self, name, ids, dim):
        ids, parts = self._by_server(ids)
        out = np.zeros((ids.size, dim), np.float32)
        for s, idx in enumerate(parts):
            if idx.size:
                out[idx] = self._call(s, "graph_get_feat",
                                      (name, ids[idx], dim))
        return out

    # -- dense ---------------------------------------------------------------
    def pull_dense(self, name):
        return self._call(self._dense_server(name), "pull_dense", name)

    def push_dense_grad(self, name, grad):
        self._call(self._dense_server(name), "push_dense_grad",
                   (name, np.asarray(grad, np.float32)))

    def set_dense(self, name, value):
        self._call(self._dense_server(name), "set_dense",
                   (name, np.asarray(value, np.float32)))

    # -- sparse (partitioned) ------------------------------------------------
    def pull_sparse(self, name, ids):
        ids = np.asarray(ids, np.int64).reshape(-1)
        n = len(self.endpoints)
        if ids.size == 0:
            return np.empty((0, self._sparse_dims.get(name, 0)),
                            np.float32)
        parts = [np.nonzero(ids % n == i)[0] for i in range(n)]
        dim = self._sparse_dims.get(name)
        results = [None] * n
        for i, pos in enumerate(parts):
            if pos.size:
                results[i] = self._call(i, "pull_sparse", (name, ids[pos]))
                dim = results[i].shape[1]
        out = np.empty((ids.shape[0], dim), np.float32)
        for pos, res in zip(parts, results):
            if res is not None:
                out[pos] = res
        return out

    def push_sparse_grad(self, name, ids, grads):
        ids = np.asarray(ids, np.int64).reshape(-1)
        grads = np.asarray(grads, np.float32)
        n = len(self.endpoints)
        for i in range(n):
            pos = np.nonzero(ids % n == i)[0]
            if pos.size:
                self._call(i, "push_sparse_grad",
                           (name, ids[pos], grads[pos]))

    def delete_table(self, name):
        for i in range(len(self.endpoints)):
            self._call(i, "delete_table", name)
        self._sparse_dims.pop(name, None)

    # -- control -------------------------------------------------------------
    def barrier(self, n_trainers):
        self._call(0, "barrier", n_trainers)

    def save(self):
        return [self._call(i, "save", None)
                for i in range(len(self.endpoints))]

    def load(self, states):
        for i, sd in enumerate(states):
            self._call(i, "load", sd)

    def stop_servers(self):
        for i in range(len(self.endpoints)):
            try:
                self._call(i, "stop", None)
            except (RuntimeError, ConnectionError, OSError):
                pass

    def close(self):
        for s in self._socks:
            if s is not None:
                try:
                    s.close()
                except OSError:
                    pass
        self._socks = [None] * len(self.endpoints)


class Communicator:
    """Trainer-side grad pipe (ref distributed/service/communicator.h:197).

    modes:
      sync  — push_* forwards immediately; callers barrier per step
      async — pushes enqueue; a background thread drains (Hogwild-style)
      geo   — sparse pushes accumulate locally as deltas; every
              `geo_step` flushes merged deltas (optimizer='sum' tables)
    """

    def __init__(self, client: PSClient, mode="async", geo_step=4):
        self.client = client
        self.mode = mode
        self.geo_step = int(geo_step)
        # per-table geo delta scale at flush (e.g. -lr turns summed grads
        # into the SGD parameter delta merged by an optimizer='sum' table)
        self.geo_scales: dict[str, float] = {}
        self._queue: list = []
        self._cv = threading.Condition()
        self._running = False
        self._thread = None
        self._inflight = 0
        self._error: Exception | None = None
        self._geo_acc: dict[str, dict[int, np.ndarray]] = {}
        self._geo_count = 0

    def set_geo_scale(self, table_name, scale):
        self.geo_scales[table_name] = float(scale)

    # -- lifecycle -----------------------------------------------------------
    def start(self):
        if self.mode == "async" and not self._running:
            self._running = True
            self._thread = threading.Thread(target=self._drain, daemon=True)
            self._thread.start()
        return self

    def stop(self):
        if self._running:
            with self._cv:
                self._running = False
                self._cv.notify_all()
            self._thread.join(timeout=10.0)
        self.flush()

    def _drain(self):
        while True:
            with self._cv:
                while self._running and not self._queue:
                    self._cv.wait(timeout=0.5)
                if not self._running and not self._queue:
                    return
                batch, self._queue = self._queue, []
                self._inflight = len(batch)
            try:
                for kind, name, a, b in batch:
                    if kind == "sparse":
                        self.client.push_sparse_grad(name, a, b)
                    else:
                        self.client.push_dense_grad(name, a)
                    with self._cv:
                        self._inflight -= 1
                        self._cv.notify_all()
            except Exception as e:  # noqa: BLE001 — surface via flush()
                with self._cv:
                    self._error = e
                    self._inflight = 0
                    self._cv.notify_all()

    # -- pushes --------------------------------------------------------------
    def push_sparse(self, name, ids, grads):
        if self.mode == "geo":
            acc = self._geo_acc.setdefault(name, {})
            ids = np.asarray(ids, np.int64).reshape(-1)
            grads = np.asarray(grads, np.float32)
            for i, g in zip(ids, grads):
                i = int(i)
                if i in acc:
                    acc[i] = acc[i] + g
                else:
                    acc[i] = g.copy()
            return
        if self.mode == "sync":
            self.client.push_sparse_grad(name, ids, grads)
            return
        with self._cv:
            self._queue.append(("sparse", name, np.asarray(ids, np.int64),
                                np.asarray(grads, np.float32)))
            self._cv.notify()

    def push_dense(self, name, grad):
        if self.mode != "async":
            # sync pushes inline; geo applies only to sparse tables (ref
            # SparseGeoTable) so dense grads also go straight through —
            # queueing them would never drain (no drain thread in geo)
            self.client.push_dense_grad(name, grad)
            return
        with self._cv:
            self._queue.append(("dense", name,
                                np.asarray(grad, np.float32), None))
            self._cv.notify()

    def step_end(self):
        """Geo cadence hook: call once per train step."""
        if self.mode != "geo":
            return
        self._geo_count += 1
        if self._geo_count % self.geo_step == 0:
            self.flush()

    def flush(self):
        if self.mode == "geo":
            for name, acc in self._geo_acc.items():
                if not acc:
                    continue
                ids = np.fromiter(acc.keys(), np.int64, len(acc))
                grads = np.stack([acc[int(i)] for i in ids])
                scale = self.geo_scales.get(name, 1.0)
                self.client.push_sparse_grad(name, ids, scale * grads)
            self._geo_acc = {}
            return
        if self.mode == "async":
            # wait until queued AND in-flight pushes have landed
            with self._cv:
                ok = self._cv.wait_for(
                    lambda: (self._error is not None
                             or (not self._queue and self._inflight == 0)),
                    timeout=60.0)
                err, self._error = self._error, None
            if err is not None:
                raise RuntimeError(
                    "async communicator push failed") from err
            if not ok:
                raise RuntimeError(
                    "async communicator flush timed out (60s) with "
                    "gradients still in flight")
